"""Measured formulation selection (tmr_tpu/utils/autotune.py)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmr_tpu.config import preset
from tmr_tpu.utils import autotune as at

KNOBS = ("TMR_XCORR_IMPL", "TMR_XCORR_IMPL_SMALL", "TMR_WIN_ATTN",
         "TMR_XCORR_PRECISION", "TMR_GLOBAL_ATTN",
         "TMR_GLOBAL_SCORES_DTYPE", "TMR_DECODER_IMPL", "TMR_QUANT")


@pytest.fixture
def clean_knobs(monkeypatch, tmp_path):
    """No knobs set on entry; anything autotune exports is popped on exit.
    The persistent winner cache is redirected to a per-test file so tests
    never read/pollute ~/.cache/tmr_tpu/autotune.json (a prior test's
    winners would otherwise short-circuit later measurements).

    The decoder-tail picks are stubbed by default (xla wins, so the quant
    stage short-circuits to "off" without a sweep): a REAL
    pick_decoder_impl at the production 128^2 x 1024 geometry is minutes
    of CPU matmul, and the pre-existing autotune tests exercise the
    attention/xcorr stages. Tail-election tests re-patch with their own
    stubs (or call the picks directly at tiny geometry)."""
    for k in KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("TMR_AUTOTUNE_CACHE", str(tmp_path / "autotune.json"))
    monkeypatch.setenv("TMR_AUTOTUNE_SEED", str(tmp_path / "no_seed.json"))
    monkeypatch.delenv("TMR_AUTOTUNE_FORCE", raising=False)
    monkeypatch.setattr(
        at, "pick_decoder_impl",
        lambda *a, **k: {"xla": 0.01, "fused": 0.02},
    )
    monkeypatch.setattr(
        at, "pick_quant", lambda *a, **k: {"off": 0.01, "int8": 0.02},
    )
    yield
    for k in KNOBS:
        os.environ.pop(k, None)


def _cfg():
    return preset("TMR_FSCD147", backbone="sam_vit_b", image_size=256,
                  batch_size=1)


def test_autotune_noop_off_tpu(clean_knobs):
    if jax.default_backend() == "tpu":
        pytest.skip("selection legitimately runs on TPU")
    assert at.autotune(_cfg(), 256, 1) == {}
    assert not any(k in os.environ for k in KNOBS)


def test_autotune_picks_min_and_exports_env(clean_knobs, monkeypatch):
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl",
        lambda *a, **k: {"conv": 0.03, "vmap": 0.05, "fft": 0.01},
    )
    monkeypatch.setattr(
        at, "pick_win_attn_impl",
        lambda *a, **k: {"dense": 0.02, "folded": 0.01, "flash": 0.03},
    )
    monkeypatch.setattr(
        at, "pick_global_attn_impl",
        lambda *a, **k: {"blockwise": 0.03, "flash": 0.02},
    )
    report = at.autotune(_cfg(), 1024, 4)
    # the xcorr winner exports through the SMALL-scoped knob only: the
    # 127/191 buckets must keep their FFT auto path
    assert report["TMR_XCORR_IMPL_SMALL"]["picked"] == "fft"
    assert report["TMR_WIN_ATTN"]["picked"] == "folded"
    assert report["TMR_GLOBAL_ATTN"]["picked"] == "flash"
    assert os.environ["TMR_XCORR_IMPL_SMALL"] == "fft"
    assert "TMR_XCORR_IMPL" not in os.environ
    assert os.environ["TMR_WIN_ATTN"] == "folded"
    assert os.environ["TMR_GLOBAL_ATTN"] == "flash"


def test_fallback_annotated_entries_never_win(clean_knobs, monkeypatch):
    """A gate-refused variant's timing is recorded annotated ("<impl>
    (fallback)") and must be excluded from winner selection even when it is
    the fastest row — it measured a DIFFERENT formulation than its label,
    and exporting it would set an invalid env value (ADVICE r4)."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl",
        lambda *a, **k: {"conv": 0.01, "pallas" + at.FALLBACK_SUFFIX: 1e-5},
    )
    monkeypatch.setattr(
        at, "pick_win_attn_impl",
        lambda *a, **k: {"dense": 0.02, "pallas (fallback)": 0.001},
    )
    monkeypatch.setattr(
        at, "pick_global_attn_impl",
        lambda *a, **k: {"blockwise": 0.03, "flash (fallback)": 0.001},
    )
    report = at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert report["TMR_XCORR_IMPL_SMALL"]["picked"] == "conv"
    assert os.environ["TMR_XCORR_IMPL_SMALL"] == "conv"
    assert report["TMR_WIN_ATTN"]["picked"] == "dense"
    assert report["TMR_GLOBAL_ATTN"]["picked"] == "blockwise"
    assert os.environ["TMR_WIN_ATTN"] == "dense"
    assert os.environ["TMR_GLOBAL_ATTN"] == "blockwise"
    # the annotated evidence is preserved in the report
    assert "pallas (fallback)" in report["TMR_WIN_ATTN"]["times"]
    assert "pallas" + at.FALLBACK_SUFFIX in (
        report["TMR_XCORR_IMPL_SMALL"]["times"]
    )


def test_autotune_sweep_false_exports_cached_and_reports_pending(
    clean_knobs, monkeypatch, tmp_path
):
    """sweep=False (bench.py's preliminary pass) must export cached
    winners, run NO measurements, and report the knobs a full call would
    sweep under "_pending"."""
    import json

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    boom = lambda tag: lambda *a, **k: (_ for _ in ()).throw(
        AssertionError(f"{tag} swept under sweep=False")
    )
    monkeypatch.setattr(at, "pick_xcorr_impl", boom("x"))
    monkeypatch.setattr(at, "pick_win_attn_impl", boom("w"))
    monkeypatch.setattr(at, "pick_global_attn_impl", boom("g"))
    monkeypatch.setattr(at, "pick_xcorr_precision", boom("p"))
    monkeypatch.setattr(at, "measure_rtt_floor", boom("rtt"))

    class _Dev:
        device_kind = "cpu"

    monkeypatch.setattr(jax, "devices", lambda *a: [_Dev()])
    seed = tmp_path / "seed.json"
    seed.write_text(json.dumps({
        "cpu|1024|128|4|512|vit_b": {
            "TMR_GLOBAL_ATTN": "blockwise",
            "_variants_TMR_GLOBAL_ATTN": at._variants_sig(
                "TMR_GLOBAL_ATTN"
            ),
        }
    }))
    monkeypatch.setenv("TMR_AUTOTUNE_SEED", str(seed))
    report = at.autotune(_cfg(), 1024, 4, sweep=False)
    assert report["TMR_GLOBAL_ATTN"] == {"picked": "blockwise",
                                         "cached": True}
    assert os.environ["TMR_GLOBAL_ATTN"] == "blockwise"
    # the un-cached knobs are reported, not measured; the scores knob
    # resolved to its measurement-free no-op (seeded global formulation is
    # not folded) so it is recorded, not pending
    assert report["TMR_GLOBAL_SCORES_DTYPE"] == {"picked": "f32",
                                                 "times": {}}
    assert set(report["_pending"]) == {
        "TMR_WIN_ATTN", "TMR_XCORR_IMPL_SMALL", "TMR_XCORR_PRECISION",
        "TMR_DECODER_IMPL", "TMR_QUANT",
    }


def test_autotune_respects_explicit_knobs(clean_knobs, monkeypatch):
    monkeypatch.setenv("TMR_XCORR_IMPL", "conv")
    monkeypatch.setenv("TMR_WIN_ATTN", "dense")
    monkeypatch.setenv("TMR_XCORR_PRECISION", "highest")
    monkeypatch.setenv("TMR_GLOBAL_ATTN", "blockwise")
    monkeypatch.setenv("TMR_DECODER_IMPL", "xla")
    monkeypatch.setenv("TMR_QUANT", "off")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    called = []
    monkeypatch.setattr(
        at, "pick_xcorr_impl", lambda *a, **k: called.append("x") or {}
    )
    monkeypatch.setattr(
        at, "pick_win_attn_impl", lambda *a, **k: called.append("w") or {}
    )
    monkeypatch.setattr(
        at, "pick_xcorr_precision", lambda *a, **k: called.append("p") or {}
    )
    monkeypatch.setattr(
        at, "pick_global_attn_impl", lambda *a, **k: called.append("g") or {}
    )
    monkeypatch.setattr(
        at, "pick_decoder_impl", lambda *a, **k: called.append("d") or {}
    )
    monkeypatch.setattr(
        at, "pick_quant", lambda *a, **k: called.append("q") or {}
    )
    # the one unpinned knob (scores dtype) completes its cache entry as
    # the f32 no-op — no measurement runs (the pinned global formulation
    # is not folded, so there is nothing to sweep)
    assert at.autotune(_cfg(), 1024, 4) == {
        "TMR_GLOBAL_SCORES_DTYPE": {"picked": "f32", "times": {}}
    }
    assert called == []
    assert os.environ["TMR_XCORR_IMPL"] == "conv"


def test_small_scope_keeps_fft_for_big_buckets(clean_knobs, monkeypatch):
    """TMR_XCORR_IMPL_SMALL must not reroute a >threshold capacity: the
    127/191 buckets stay on the FFT path regardless of the tuned winner."""
    from tmr_tpu.ops import xcorr

    monkeypatch.setenv("TMR_XCORR_IMPL_SMALL", "vmap")
    B, C, H, W, cap = 1, 2, 16, 16, 67
    assert cap > xcorr.FFT_CAPACITY_THRESHOLD
    feat = jnp.asarray(
        np.random.default_rng(0).standard_normal((B, C, H, W)), jnp.float32
    )
    tmpl = jnp.zeros((B, C, cap, cap), jnp.float32)
    tmpl = tmpl.at[:, :, cap // 2, cap // 2].set(1.0)
    thw = jnp.array([[1, 1]], jnp.int32)
    got = xcorr.cross_correlation(feat, tmpl, thw)
    # identity template: out == feat up to FFT rounding. The conv paths at
    # Precision.HIGHEST reproduce it exactly (diff == 0); nonzero rounding
    # proves the FFT path ran despite the small-scope knob.
    np.testing.assert_allclose(np.asarray(got), np.asarray(feat), atol=1e-4)
    assert abs(np.asarray(got) - np.asarray(feat)).max() > 0


@pytest.mark.slow
def test_microbenchmarks_run_and_time_all_variants(clean_knobs):
    """The pick_* functions themselves must run every variant end to end
    (tiny shapes; CPU is fine for exercising the machinery). Off-TPU the
    pallas xcorr gate refuses, so that row reports ANNOTATED — labeled
    with what was measured (the conv fallback), like the block sweeps."""
    tx = at.pick_xcorr_impl(1, 8, 16, 5, rtt=0.0)
    assert {k.replace(at.FALLBACK_SUFFIX, "") for k in tx} == set(
        at.XCORR_VARIANTS
    )
    assert "pallas" + at.FALLBACK_SUFFIX in tx and "pallas" not in tx
    assert all(v > 0 for v in tx.values())
    # windowed block: flash falls back unavailable off-TPU but must not
    # crash the sweep; dense/folded always time
    tw = at.pick_win_attn_impl(1, 14, 16, 2, rtt=0.0)
    assert {"dense", "folded"} <= set(tw)
    assert all(v > 0 for v in tw.values())
    assert "TMR_XCORR_IMPL" not in os.environ  # knobs restored
    assert "TMR_WIN_ATTN" not in os.environ


def test_autotune_precision_stage_flips_only_on_decisive_win(
    clean_knobs, monkeypatch
):
    """The TMR_XCORR_PRECISION sweep runs on the winning small-bucket impl
    and only leaves the reference-parity 'highest' when a variant wins by
    >10% (changed numerics need a decisive speedup); an fft winner skips
    the sweep entirely (the FFT path is f32 regardless)."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl",
        lambda *a, **k: {"conv": 0.01, "vmap": 0.05, "fft": 0.03},
    )
    monkeypatch.setattr(at, "pick_win_attn_impl", lambda *a, **k: {})
    monkeypatch.setattr(at, "pick_global_attn_impl", lambda *a, **k: {})
    swept = []
    monkeypatch.setattr(
        at, "pick_xcorr_precision",
        lambda *a, **k: swept.append(1) or {
            "highest": 0.010, "default": 0.0095, "bf16": 0.0092
        },
    )
    r = at.autotune(_cfg(), 1024, 4)
    # best (bf16, 8% faster) is under the 10% bar -> parity precision stays
    assert swept and r["TMR_XCORR_PRECISION"]["picked"] == "highest"
    assert os.environ["TMR_XCORR_PRECISION"] == "highest"

    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setenv("TMR_AUTOTUNE_FORCE", "1")
    monkeypatch.setattr(
        at, "pick_xcorr_precision",
        lambda *a, **k: {"highest": 0.010, "default": 0.004, "bf16": 0.006},
    )
    r = at.autotune(_cfg(), 1024, 4)
    assert r["TMR_XCORR_PRECISION"]["picked"] == "default"
    assert os.environ["TMR_XCORR_PRECISION"] == "default"

    # fft winner: no sweep, cache records the f32 no-op
    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setattr(
        at, "pick_xcorr_impl",
        lambda *a, **k: {"conv": 0.03, "vmap": 0.05, "fft": 0.01},
    )
    boom = lambda *a, **k: (_ for _ in ()).throw(AssertionError("swept"))
    monkeypatch.setattr(at, "pick_xcorr_precision", boom)
    r = at.autotune(_cfg(), 1024, 4)
    assert r["TMR_XCORR_PRECISION"]["picked"] == "highest"


def test_autotune_tune_precision_false_skips_sweep(clean_knobs, monkeypatch):
    """Training runs (main.py passes tune_precision=False) must not export
    relaxed matcher numerics: the precision sweep never runs and the knob
    is never set."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl",
        lambda *a, **k: {"conv": 0.01, "vmap": 0.05, "fft": 0.03},
    )
    monkeypatch.setattr(at, "pick_win_attn_impl", lambda *a, **k: {})
    monkeypatch.setattr(at, "pick_global_attn_impl", lambda *a, **k: {})
    boom = lambda *a, **k: (_ for _ in ()).throw(AssertionError("swept"))
    monkeypatch.setattr(at, "pick_xcorr_precision", boom)
    r = at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert "TMR_XCORR_PRECISION" not in r
    assert "TMR_XCORR_PRECISION" not in os.environ


def test_autotune_cached_precision_is_impl_specific(clean_knobs, monkeypatch):
    """A cached relaxed-precision winner was measured under one impl; a
    later run with a DIFFERENT pinned impl must re-measure instead of
    inheriting numerics whose decisive-win evidence does not transfer."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl",
        lambda *a, **k: {"conv": 0.01, "vmap": 0.05, "fft": 0.03},
    )
    monkeypatch.setattr(at, "pick_win_attn_impl", lambda *a, **k: {})
    monkeypatch.setattr(at, "pick_global_attn_impl", lambda *a, **k: {})
    monkeypatch.setattr(
        at, "pick_xcorr_precision",
        lambda *a, **k: {"highest": 0.010, "default": 0.004, "bf16": 0.006},
    )
    r = at.autotune(_cfg(), 1024, 4)
    assert r["TMR_XCORR_PRECISION"]["picked"] == "default"  # won on conv

    # same shapes, but the user pins a different impl: the cached 'default'
    # winner (measured on conv) must NOT be exported for vmap
    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setenv("TMR_XCORR_IMPL_SMALL", "vmap")
    swept = []
    monkeypatch.setattr(
        at, "pick_xcorr_precision",
        lambda *a, **k: swept.append(1) or {
            "highest": 0.010, "default": 0.0099, "bf16": 0.0098
        },
    )
    r = at.autotune(_cfg(), 1024, 4)
    assert swept, "must re-measure under the newly pinned impl"
    assert r["TMR_XCORR_PRECISION"]["picked"] == "highest"  # <10% on vmap
    assert os.environ["TMR_XCORR_PRECISION"] == "highest"

    # with the SAME impl as measured, the cached winner exports directly
    # (attention pinned: its sweep returned {} above so it was never cached)
    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setenv("TMR_WIN_ATTN", "dense")
    monkeypatch.setenv("TMR_GLOBAL_ATTN", "blockwise")
    boom = lambda *a, **k: (_ for _ in ()).throw(AssertionError("swept"))
    monkeypatch.setattr(at, "pick_xcorr_precision", boom)
    monkeypatch.setattr(
        at, "pick_xcorr_impl", boom
    )
    r = at.autotune(_cfg(), 1024, 4)
    assert r["TMR_XCORR_IMPL_SMALL"] == {"picked": "conv", "cached": True}


def test_autotune_cache_persists_winners_across_processes(
    clean_knobs, monkeypatch
):
    """Measured once -> cached; the next autotune at the same key exports
    the winners WITHOUT re-measuring (the 'measured winners become the
    defaults' mechanism); TMR_AUTOTUNE_FORCE re-measures."""
    calls = []
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl",
        lambda *a, **k: calls.append("x") or {"conv": 0.03, "fft": 0.01},
    )
    monkeypatch.setattr(
        at, "pick_win_attn_impl",
        lambda *a, **k: calls.append("w") or {"dense": 0.02, "folded": 0.01},
    )
    monkeypatch.setattr(
        at, "pick_global_attn_impl",
        lambda *a, **k: calls.append("g") or {"blockwise": 0.02,
                                              "flash": 0.01},
    )
    r1 = at.autotune(_cfg(), 1024, 4)
    assert calls == ["x", "w", "g"]
    assert r1["TMR_WIN_ATTN"]["picked"] == "folded"

    # fresh process simulation: knobs cleared, cache file remains
    for k in KNOBS:
        os.environ.pop(k, None)
    r2 = at.autotune(_cfg(), 1024, 4)
    assert calls == ["x", "w", "g"], "cached hit must not re-measure"
    assert r2["TMR_XCORR_IMPL_SMALL"] == {"picked": "fft", "cached": True}
    assert r2["TMR_WIN_ATTN"] == {"picked": "folded", "cached": True}
    assert os.environ["TMR_XCORR_IMPL_SMALL"] == "fft"
    assert os.environ["TMR_WIN_ATTN"] == "folded"

    # a different shape key measures fresh
    for k in KNOBS:
        os.environ.pop(k, None)
    at.autotune(_cfg(), 1536, 1)
    assert calls == ["x", "w", "g", "x", "w", "g"]

    # force bypasses the cache
    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setenv("TMR_AUTOTUNE_FORCE", "1")
    at.autotune(_cfg(), 1024, 4)
    assert calls == ["x", "w", "g", "x", "w", "g", "x", "w", "g"]


def test_train_autotune_uses_separate_key_and_grad_sweep(
    clean_knobs, monkeypatch
):
    """train=True must (a) time the block sweeps with a gradient pass —
    the Pallas kernels' recompute backward inverts the fwd-only ranking —
    and (b) cache under a distinct key so eval winners never leak into
    training and vice versa."""
    seen_train = []
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def fake_xcorr(*a, train=False, **k):
        seen_train.append(("x", train))
        return {"conv": 0.03, "fft": 0.01}

    monkeypatch.setattr(at, "pick_xcorr_impl", fake_xcorr)

    def fake_sweep(*a, train=False, **k):
        seen_train.append(("a", train))
        return ({"dense": 0.02, "folded": 0.01} if train
                else {"dense": 0.01, "folded": 0.02})

    monkeypatch.setattr(at, "pick_win_attn_impl", fake_sweep)
    monkeypatch.setattr(at, "pick_global_attn_impl", fake_sweep)

    r_eval = at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert r_eval["TMR_WIN_ATTN"]["picked"] == "dense"
    assert seen_train == [("x", False), ("a", False), ("a", False)]

    for k in KNOBS:
        os.environ.pop(k, None)
    r_train = at.autotune(_cfg(), 1024, 4, tune_precision=False, train=True)
    # the eval cache entry must NOT satisfy the train run, and every sweep
    # (xcorr included) must time with gradients
    assert seen_train[3:] == [("x", True), ("a", True), ("a", True)]
    assert r_train["TMR_WIN_ATTN"]["picked"] == "folded"

    # both keys now cached independently
    for k in KNOBS:
        os.environ.pop(k, None)
    r2 = at.autotune(_cfg(), 1024, 4, tune_precision=False, train=True)
    assert r2["TMR_WIN_ATTN"] == {"picked": "folded", "cached": True}
    for k in KNOBS:
        os.environ.pop(k, None)
    r3 = at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert r3["TMR_WIN_ATTN"] == {"picked": "dense", "cached": True}


@pytest.mark.slow
def test_block_sweep_train_mode_times_grad(clean_knobs, monkeypatch):
    """The real harness under train=True must build a differentiable step
    (value_and_grad through the block) and produce a time for every
    variant that can differentiate — on CPU every variant falls back to a
    differentiable path, so all four windowed variants report. Off-TPU the
    flash/pallas gates refuse, so those entries come back ANNOTATED
    ("<impl> (fallback)"): the harness must label what it measured, never
    record a fallback timing under the requested name (ADVICE r4)."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    times = at.pick_win_attn_impl(1, 8, 16, 2, rtt=0.0, train=True)
    base = {k.replace(at.FALLBACK_SUFFIX, "") for k in times}
    assert base == set(at.WIN_ATTN_VARIANTS)
    # CPU: the kernel gates refuse -> their rows must carry the annotation
    for impl in ("flash", "pallas"):
        assert impl + at.FALLBACK_SUFFIX in times and impl not in times
    assert all(t > 0 for t in times.values())


def test_cached_winner_stale_when_variant_set_grows(clean_knobs, monkeypatch):
    """A cached winner is versioned by the variant set it beat
    (_variants_<knob>): growing the set (a new kernel) or a stamp-less
    legacy entry must trigger a re-sweep so new variants get their shot,
    while correctly stamped siblings stay cached."""
    calls = []
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl",
        lambda *a, **k: calls.append("x") or {"conv": 0.03, "fft": 0.01},
    )
    monkeypatch.setattr(
        at, "pick_win_attn_impl",
        lambda *a, **k: calls.append("w") or {"dense": 0.02, "folded": 0.01},
    )
    monkeypatch.setattr(
        at, "pick_global_attn_impl",
        lambda *a, **k: calls.append("g") or {"blockwise": 0.02,
                                              "flash": 0.01},
    )
    r1 = at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert calls == ["x", "w", "g"]

    # cached entries were stamped: a rerun re-measures nothing
    for k in KNOBS:
        os.environ.pop(k, None)
    at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert calls == ["x", "w", "g"]

    # the global-attn variant set grows (new kernel lands): ONLY that knob
    # re-sweeps; the stamped siblings stay cached
    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setattr(
        at, "GLOBAL_ATTN_VARIANTS",
        at.GLOBAL_ATTN_VARIANTS + ("newkernel",),
    )
    r3 = at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert calls == ["x", "w", "g", "g"]
    assert r3["TMR_XCORR_IMPL_SMALL"].get("cached") is True
    assert r3["TMR_WIN_ATTN"].get("cached") is True
    assert "cached" not in r3["TMR_GLOBAL_ATTN"]

    # legacy stamp-less entries (pre-versioning caches/seeds) also re-sweep
    import json
    path = os.environ["TMR_AUTOTUNE_CACHE"]
    j = json.load(open(path))
    for entry in j.values():
        for kk in list(entry):
            if kk.startswith("_variants_"):
                del entry[kk]
    json.dump(j, open(path, "w"))
    for k in KNOBS:
        os.environ.pop(k, None)
    at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert calls == ["x", "w", "g", "g", "x", "w", "g"]


def test_autotune_cached_hit_respects_explicit_knobs(
    clean_knobs, monkeypatch
):
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl", lambda *a, **k: {"conv": 0.03, "fft": 0.01}
    )
    monkeypatch.setattr(
        at, "pick_win_attn_impl", lambda *a, **k: {"dense": 0.02,
                                                  "folded": 0.01}
    )
    monkeypatch.setattr(
        at, "pick_global_attn_impl",
        lambda *a, **k: {"blockwise": 0.02, "flash": 0.01},
    )
    at.autotune(_cfg(), 1024, 4)
    for k in KNOBS:
        os.environ.pop(k, None)
    # user pins the attention knob: the cached hit must not override it
    monkeypatch.setenv("TMR_WIN_ATTN", "dense")
    r = at.autotune(_cfg(), 1024, 4)
    assert "TMR_WIN_ATTN" not in r
    assert os.environ["TMR_WIN_ATTN"] == "dense"
    assert r["TMR_XCORR_IMPL_SMALL"]["cached"] is True


def test_measured_tpu_defaults(monkeypatch):
    """VERDICT r3 #2 'measured winners become the defaults': with no knobs
    set, TPU processes default to the BENCH_LIVE.json-measured winners
    (TMR_WIN_ATTN=flash, TMR_XCORR_IMPL_SMALL=vmap); other backends keep
    the portable defaults; explicit env always wins."""
    from tmr_tpu.models import vit as vit_mod
    from tmr_tpu.ops import xcorr as xcorr_mod

    monkeypatch.delenv("TMR_WIN_ATTN", raising=False)
    monkeypatch.delenv("TMR_XCORR_IMPL", raising=False)
    monkeypatch.delenv("TMR_XCORR_IMPL_SMALL", raising=False)

    if jax.default_backend() != "tpu":  # portable default off-TPU
        assert vit_mod._WIN_ATTN_IMPL() == "dense"

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert vit_mod._WIN_ATTN_IMPL() == "flash"
    monkeypatch.setenv("TMR_WIN_ATTN", "folded")
    assert vit_mod._WIN_ATTN_IMPL() == "folded"

    # xcorr: small-bucket default resolves to vmap on TPU. Observable via
    # the dispatch: identity-template correlation through a capacity-5
    # bucket must be exact under every conv-family impl, and the TPU
    # default must NOT be fft (fft would show rounding) — plus directly.
    feat = jnp.asarray(
        np.random.default_rng(0).standard_normal((1, 2, 8, 8)), jnp.float32
    )
    tmpl = jnp.zeros((1, 2, 5, 5), jnp.float32)
    tmpl = tmpl.at[:, :, 2, 2].set(1.0)
    thw = jnp.array([[1, 1]], jnp.int32)
    got = xcorr_mod.cross_correlation(feat, tmpl, thw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(feat))


def test_cache_accepts_measured_batch_winner(clean_knobs):
    """bench_extra's batch sweep persists TMR_BENCH_BATCH as a digit string
    (bench.py defaults its headline batch to it); non-numeric or
    non-positive values must be dropped by the cache validator."""
    at._cache_store("v5e|bench_batch|1024", {
        "TMR_BENCH_BATCH": {"picked": "8"},
    })
    assert at._cache_load()["v5e|bench_batch|1024"]["TMR_BENCH_BATCH"] == "8"

    import json
    path = os.environ["TMR_AUTOTUNE_CACHE"]
    with open(path) as f:
        obj = json.load(f)
    obj["v5e|bench_batch|1024"]["TMR_BENCH_BATCH"] = "abc"
    obj["other"] = {"TMR_BENCH_BATCH": "0"}
    with open(path, "w") as f:
        json.dump(obj, f)
    loaded = at._cache_load()
    assert "TMR_BENCH_BATCH" not in loaded.get("v5e|bench_batch|1024", {})
    assert "other" not in loaded


@pytest.mark.slow
def test_global_attn_knob_validates_and_matches(monkeypatch):
    """TMR_GLOBAL_ATTN forces the global-attention formulation at trace
    time: invalid values raise, and 'blockwise' matches the auto dispatch
    off-TPU (where the flash gate falls back to blockwise anyway)."""
    from tmr_tpu.models.vit import Block

    tokens = jnp.asarray(
        np.random.default_rng(0).standard_normal((1, 32, 32, 32)),
        jnp.bfloat16,
    )
    blk = Block(num_heads=2, window_size=0, rel_pos_size=(32, 32),
                dtype=jnp.bfloat16)
    monkeypatch.delenv("TMR_GLOBAL_ATTN", raising=False)
    params = jax.jit(blk.init)(jax.random.key(0), tokens)["params"]
    auto = blk.apply({"params": params}, tokens)

    monkeypatch.setenv("TMR_GLOBAL_ATTN", "blockwise")
    forced = blk.apply({"params": params}, tokens)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(forced))

    monkeypatch.setenv("TMR_GLOBAL_ATTN", "spiral")
    with pytest.raises(ValueError, match="TMR_GLOBAL_ATTN"):
        blk.apply({"params": params}, tokens)


def test_autotune_seed_file_partial_sweep(clean_knobs, monkeypatch, tmp_path):
    """A committed seed file (AUTOTUNE_SEED.json) pre-covers knobs for a
    fresh machine: covered knobs export without measuring, ONLY the
    unseeded ones sweep, and a local user-cache entry for the same key
    fully supersedes the seed."""
    import json

    seed = tmp_path / "seed.json"
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    key = "|".join(str(p) for p in (
        jax.devices()[0].device_kind, 1024, 128, 4, 512, "vit_b"))
    seed.write_text(json.dumps({key: {
        "TMR_XCORR_IMPL_SMALL": "vmap", "TMR_WIN_ATTN": "flash",
        # seeds carry the variant sets their winners beat (an unstamped
        # entry is treated as stale — covered by
        # test_cached_winner_stale_when_variant_set_grows)
        "_variants_TMR_XCORR_IMPL_SMALL": at._variants_sig(
            "TMR_XCORR_IMPL_SMALL"),
        "_variants_TMR_WIN_ATTN": at._variants_sig("TMR_WIN_ATTN"),
    }}))
    monkeypatch.setenv("TMR_AUTOTUNE_SEED", str(seed))

    calls = []
    boom = lambda tag: lambda *a, **k: calls.append(tag) or {}
    monkeypatch.setattr(at, "pick_xcorr_impl", boom("x"))
    monkeypatch.setattr(at, "pick_win_attn_impl", boom("w"))
    monkeypatch.setattr(
        at, "pick_global_attn_impl",
        lambda *a, **k: calls.append("g") or {"blockwise": 0.02,
                                              "flash": 0.01},
    )
    monkeypatch.setattr(
        at, "pick_xcorr_precision",
        lambda *a, **k: calls.append("p") or {
            "highest": 0.01, "default": 0.002, "bf16": 0.003},
    )
    r = at.autotune(_cfg(), 1024, 4)
    # seeded knobs exported without their sweeps; unseeded ones measured
    assert "x" not in calls and "w" not in calls
    assert "g" in calls and "p" in calls
    assert r["TMR_XCORR_IMPL_SMALL"] == {"picked": "vmap", "cached": True}
    assert r["TMR_WIN_ATTN"] == {"picked": "flash", "cached": True}
    assert os.environ["TMR_WIN_ATTN"] == "flash"
    assert r["TMR_GLOBAL_ATTN"]["picked"] == "flash"
    # precision measured on the seeded vmap winner, decisive win -> default
    assert r["TMR_XCORR_PRECISION"]["picked"] == "default"

    # a local user-cache write supersedes the seed for that knob (the
    # measured run above already materialized the seeded winners into the
    # user file through its report, so the key is fully local now)
    for k in KNOBS:
        os.environ.pop(k, None)
    at._cache_store(key, {"TMR_XCORR_IMPL_SMALL": {"picked": "conv"}})
    cached = at._cache_load()[key]
    assert cached["TMR_XCORR_IMPL_SMALL"] == "conv"
    assert cached["TMR_WIN_ATTN"] == "flash"

    # and with the user cache absent, the seed alone still serves
    os.environ["TMR_AUTOTUNE_CACHE"] = str(tmp_path / "fresh_cache.json")
    assert at._cache_load()[key]["TMR_XCORR_IMPL_SMALL"] == "vmap"


def test_cached_precision_dropped_when_impl_sweep_pending(
    clean_knobs, monkeypatch
):
    """Run A (impl pinned) caches a relaxed precision measured on conv.
    Run B (nothing pinned) will sweep impls fresh — the cached bf16 must
    NOT be exported ahead of that sweep: it is re-measured on whatever the
    fresh sweep picks, so relaxed numerics never outlive their pairing."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(at, "pick_win_attn_impl", lambda *a, **k: {})
    monkeypatch.setattr(at, "pick_global_attn_impl", lambda *a, **k: {})
    monkeypatch.setattr(
        at, "pick_xcorr_precision",
        lambda *a, **k: {"highest": 0.010, "default": 0.004, "bf16": 0.003},
    )
    # run A: impl pinned to conv -> precision measured+cached under conv
    monkeypatch.setenv("TMR_XCORR_IMPL_SMALL", "conv")
    r = at.autotune(_cfg(), 1024, 4)
    assert r["TMR_XCORR_PRECISION"]["picked"] == "bf16"

    # run B: unpinned; fresh impl sweep picks pallas. Cached bf16 must be
    # dropped and re-measured (mock shows <10% this time -> highest)
    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setattr(
        at, "pick_xcorr_impl",
        lambda *a, **k: {"conv": 0.03, "vmap": 0.05, "pallas": 0.01},
    )
    reswept = []
    monkeypatch.setattr(
        at, "pick_xcorr_precision",
        lambda *a, **k: reswept.append(1) or {
            "highest": 0.010, "default": 0.0099, "bf16": 0.0098},
    )
    r = at.autotune(_cfg(), 1024, 4)
    assert r["TMR_XCORR_IMPL_SMALL"]["picked"] == "pallas"
    assert reswept, "cached precision must not be exported past a fresh sweep"
    assert r["TMR_XCORR_PRECISION"]["picked"] == "highest"
    assert os.environ["TMR_XCORR_PRECISION"] == "highest"


def test_scores_dtype_sweep_decisive_win_policy(clean_knobs, monkeypatch):
    """The TMR_GLOBAL_SCORES_DTYPE stage mirrors the xcorr precision
    policy: swept only when a folded formulation won, bf16 exported only
    on a decisive (>10%) win over the exact f32 baseline, f32 kept when
    the margin is thin or the baseline is missing, and the evidence paired
    to the formulation it was measured under."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl", lambda *a, **k: {"conv": 0.01})
    monkeypatch.setattr(
        at, "pick_xcorr_precision", lambda *a, **k: {"highest": 0.01})
    monkeypatch.setattr(
        at, "pick_win_attn_impl", lambda *a, **k: {"folded": 0.01})
    monkeypatch.setattr(
        at, "pick_global_attn_impl",
        lambda *a, **k: {"blockwise": 0.03, "blockfolded": 0.01},
    )

    # decisive win: bf16 exported, evidence paired to blockfolded
    monkeypatch.setattr(
        at, "pick_global_scores_dtype",
        lambda *a, **k: {"f32": 0.010, "bf16": 0.005},
    )
    report = at.autotune(_cfg(), 1024, 4)
    assert report["TMR_GLOBAL_SCORES_DTYPE"]["picked"] == "bf16"
    assert os.environ["TMR_GLOBAL_SCORES_DTYPE"] == "bf16"

    # thin margin: f32 kept
    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setattr(
        at, "pick_global_scores_dtype",
        lambda *a, **k: {"f32": 0.010, "bf16": 0.0095},
    )
    monkeypatch.setenv(
        "TMR_AUTOTUNE_CACHE",
        os.environ["TMR_AUTOTUNE_CACHE"] + ".2",
    )
    report = at.autotune(_cfg(), 1024, 4)
    assert report["TMR_GLOBAL_SCORES_DTYPE"]["picked"] == "f32"
    assert os.environ["TMR_GLOBAL_SCORES_DTYPE"] == "f32"

    # fallback-annotated bf16 row (TMR_GLOBAL_ATTN gate refused mid-sweep)
    # must not be electable -> f32
    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setattr(
        at, "pick_global_scores_dtype",
        lambda *a, **k: {"f32": 0.010,
                         "bf16" + at.FALLBACK_SUFFIX: 0.001},
    )
    monkeypatch.setenv(
        "TMR_AUTOTUNE_CACHE",
        os.environ["TMR_AUTOTUNE_CACHE"] + ".3",
    )
    report = at.autotune(_cfg(), 1024, 4)
    assert report["TMR_GLOBAL_SCORES_DTYPE"]["picked"] == "f32"

    # non-folded winner: stage records the no-op without sweeping
    for k in KNOBS:
        os.environ.pop(k, None)
    monkeypatch.setattr(
        at, "pick_global_attn_impl", lambda *a, **k: {"blockwise": 0.01})
    monkeypatch.setattr(
        at, "pick_global_scores_dtype",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError("swept!")),
    )
    monkeypatch.setenv(
        "TMR_AUTOTUNE_CACHE",
        os.environ["TMR_AUTOTUNE_CACHE"] + ".4",
    )
    report = at.autotune(_cfg(), 1024, 4)
    assert report["TMR_GLOBAL_SCORES_DTYPE"]["picked"] == "f32"
    assert report["TMR_GLOBAL_SCORES_DTYPE"]["times"] == {}


def test_stale_winners_returns_only_stale_stamped_entries(
    clean_knobs, monkeypatch, tmp_path
):
    """stale_winners() feeds bench.py's pre-sweep bank: it must return
    exactly the cached winners whose variant stamp is stale (still-valid
    values the sweep will re-decide), skip fresh-stamped entries (those
    export normally), and respect explicit env pins."""
    import json

    class _Dev:
        device_kind = "cpu"

    monkeypatch.setattr(jax, "devices", lambda *a: [_Dev()])
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps({
        "cpu|1024|128|4|512|vit_b": {
            "TMR_GLOBAL_ATTN": "blockfolded",
            "_variants_TMR_GLOBAL_ATTN": "old,set|old-rev",  # stale
            "TMR_WIN_ATTN": "folded",
            "_variants_TMR_WIN_ATTN": at._variants_sig("TMR_WIN_ATTN"),
            "TMR_XCORR_PRECISION": "bf16",
            "_variants_TMR_XCORR_PRECISION": "also,old",  # stale
        }
    }))
    monkeypatch.setenv("TMR_AUTOTUNE_CACHE", str(cache))
    out = at.stale_winners(_cfg(), 1024, 4)
    assert out == {"TMR_GLOBAL_ATTN": "blockfolded",
                   "TMR_XCORR_PRECISION": "bf16"}

    # an env pin wins over the stale entry
    monkeypatch.setenv("TMR_GLOBAL_ATTN", "blockwise")
    out = at.stale_winners(_cfg(), 1024, 4)
    assert out == {"TMR_XCORR_PRECISION": "bf16"}


def test_new_fused_variants_registered_and_rev_bumped():
    """The fused kernel and the XLA flash path must be electable sweep
    variants, and the _SWEEP_REV bump must make every pre-existing
    TMR_GLOBAL_ATTN winner stamp stale so it re-records at the next
    hardware window (the acceptance contract for registering a variant)."""
    assert "fused" in at.GLOBAL_ATTN_VARIANTS
    assert "xlaflash" in at.GLOBAL_ATTN_VARIANTS
    sig = at._variants_sig("TMR_GLOBAL_ATTN")
    assert "fused" in sig and "xlaflash" in sig
    assert sig.endswith("|" + at._SWEEP_REV)
    # the committed seed's stamps predate this revision by construction:
    # whatever they say, they must not equal the live signature
    for entry in at.seed_load().values():
        stamp = entry.get("_variants_TMR_GLOBAL_ATTN")
        if stamp is not None:
            assert stamp != sig, (
                "committed seed already stamped with the new revision — "
                "bump _SWEEP_REV when the variant set or harness changes"
            )
    # validation accepts the new variants as cached winners, and the
    # scores-dtype pairing stamp survives an 'auto' resolution (reload
    # churn fix: autotune.py _validate_cache_obj)
    kept = at._validate_cache_obj({
        "k": {"TMR_GLOBAL_ATTN": "fused", "_scores_global_impl": "auto"},
        "k2": {"TMR_GLOBAL_ATTN": "xlaflash"},
    })
    assert kept["k"]["TMR_GLOBAL_ATTN"] == "fused"
    assert kept["k"]["_scores_global_impl"] == "auto"
    assert kept["k2"]["TMR_GLOBAL_ATTN"] == "xlaflash"


def test_stale_winners_uses_vit_kind_helper():
    """stale_winners must derive the geometry family through _vit_kind —
    the single source shared with autotune()'s cache key — not an inlined
    mapping that can drift (the two keys must be identical or the banked
    wedge-fallback measurement reads the wrong cache row)."""
    import inspect

    src = inspect.getsource(at.stale_winners)
    assert "_vit_kind(" in src
    assert '"sam_vit_b"' not in src  # the old inlined dict is gone


@pytest.mark.slow
def test_block_sweep_fallback_rows_carry_structured_refusals(
    clean_knobs, monkeypatch
):
    """The real global-attention sweep harness off-TPU: gate-refused
    kernel variants come back fallback-annotated AND their rows carry the
    structured refusal causes (gate name, cause category, config) in
    LAST_SWEEP_REFUSALS — the sweep-side half of the gate_probe.json
    diagnostics (verdict r5 #1)."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    times = at._sweep_block_env(
        "TMR_GLOBAL_ATTN", ("blockwise", "pallas", "fused"), 0,
        1, 32, 16, 2, 0.0, lambda s: None,
    )
    assert "blockwise" in times
    for impl, gate in (("pallas", "pallas_global_ok"),
                       ("fused", "pallas_fused_ok")):
        row = impl + at.FALLBACK_SUFFIX
        assert row in times and impl not in times
        causes = at.LAST_SWEEP_REFUSALS["TMR_GLOBAL_ATTN"][row]
        assert causes, f"{row} carries no structured causes"
        assert any(c["gate"] == gate for c in causes)
        for c in causes:
            assert c["schema"] == "gate_probe/v1"
            assert c["cause"]
            assert "config" in c and "device_kind" in c


def test_autotune_report_attaches_sweep_refusals(clean_knobs, monkeypatch):
    """autotune() must copy the harness's structured refusal causes into
    the report entry of any knob whose sweep produced fallback rows — the
    path bench.py's autotune_refusals JSON field reads."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    cause = {"schema": "gate_probe/v1", "gate": "pallas_global_ok",
             "cause": "backend", "message": "", "exception": None,
             "config": {}, "backend": "cpu", "device_kind": "cpu"}

    def fake_global_sweep(*a, **k):
        at.LAST_SWEEP_REFUSALS["TMR_GLOBAL_ATTN"] = {
            "pallas" + at.FALLBACK_SUFFIX: [cause],
        }
        return {"blockwise": 0.03,
                "pallas" + at.FALLBACK_SUFFIX: 0.001}

    monkeypatch.setattr(at, "pick_xcorr_impl", lambda *a, **k: {"conv": 0.01})
    monkeypatch.setattr(at, "pick_win_attn_impl",
                        lambda *a, **k: {"dense": 0.01})
    monkeypatch.setattr(at, "pick_global_attn_impl", fake_global_sweep)
    report = at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert report["TMR_GLOBAL_ATTN"]["picked"] == "blockwise"
    ref = report["TMR_GLOBAL_ATTN"]["refusals"]
    assert ref == {"pallas" + at.FALLBACK_SUFFIX: [cause]}


# ----------------------------------------------- decoder-tail elections
def _stub_non_tail_picks(monkeypatch):
    """The tail-election tests exercise the TMR_DECODER_IMPL/TMR_QUANT
    stages only: every other sweep is stubbed (the real attention/xcorr
    microbenchmarks at the 1024 geometry are minutes of CPU work)."""
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        at, "pick_xcorr_impl", lambda *a, **k: {"conv": 0.01}
    )
    monkeypatch.setattr(
        at, "pick_xcorr_precision", lambda *a, **k: {"highest": 0.01}
    )
    monkeypatch.setattr(
        at, "pick_win_attn_impl", lambda *a, **k: {"dense": 0.01}
    )
    monkeypatch.setattr(
        at, "pick_global_attn_impl", lambda *a, **k: {"blockwise": 0.01}
    )
    monkeypatch.setattr(
        at, "pick_global_scores_dtype", lambda *a, **k: {"f32": 0.01}
    )


def test_decoder_tail_knobs_registered_and_rev_bumped():
    """TMR_DECODER_IMPL / TMR_QUANT / TMR_QUANT_STORAGE must be
    versioned sweep knobs with their variant sets registered, under the
    bumped "int8-storage" revision so every pre-storage winner
    re-records at the next hardware window (the stored arm joined the
    quant sweep)."""
    assert at.DECODER_IMPL_VARIANTS == ("xla", "fused")
    assert at.QUANT_VARIANTS == ("off", "int8")
    assert at.STORAGE_VARIANTS == ("off", "int8")
    assert "TMR_DECODER_IMPL" in at._VERSIONED_KNOBS
    assert "TMR_QUANT" in at._VERSIONED_KNOBS
    assert "TMR_QUANT_STORAGE" in at._VERSIONED_KNOBS
    assert at._SWEEP_REV == "int8-storage"
    # the quant knobs are revision-stamped too since the storage arm
    # joined (pre-storage winners must go stale)
    assert at._variants_sig("TMR_DECODER_IMPL").endswith(at._SWEEP_REV)
    assert at._variants_sig("TMR_QUANT").endswith(at._SWEEP_REV)
    assert at._variants_sig("TMR_QUANT_STORAGE").endswith(at._SWEEP_REV)


def test_autotune_elects_decoder_impl_then_quant(clean_knobs, monkeypatch):
    """The tail stages run AFTER the attention/xcorr stages: the impl
    sweep elects plain-min (both formulations are oracle-pinned identical
    numerics), then the quant sweep applies the decisive-win policy
    against the exact baseline and stamps which impl its evidence was
    measured under."""
    _stub_non_tail_picks(monkeypatch)
    monkeypatch.setattr(
        at, "pick_decoder_impl",
        lambda *a, **k: {"xla": 0.02, "fused": 0.01},
    )
    monkeypatch.setattr(
        at, "pick_quant", lambda *a, **k: {"off": 0.02, "int8": 0.01},
    )
    report = at.autotune(_cfg(), 1024, 4, tune_precision=True)
    assert report["TMR_DECODER_IMPL"]["picked"] == "fused"
    assert os.environ["TMR_DECODER_IMPL"] == "fused"
    assert report["TMR_QUANT"]["picked"] == "int8"  # 2x: decisive
    assert os.environ["TMR_QUANT"] == "int8"
    cache = at._cache_load()
    entry = cache[at._cache_key(_cfg(), 1024, 4, "vit_b", False)]
    assert entry["_quant_decoder_impl"] == "fused"


def test_quant_indecisive_win_keeps_exact(clean_knobs, monkeypatch):
    _stub_non_tail_picks(monkeypatch)
    monkeypatch.setattr(
        at, "pick_decoder_impl",
        lambda *a, **k: {"xla": 0.02, "fused": 0.01},
    )
    monkeypatch.setattr(
        at, "pick_quant", lambda *a, **k: {"off": 0.0100, "int8": 0.0095},
    )
    report = at.autotune(_cfg(), 1024, 4, tune_precision=True)
    assert report["TMR_QUANT"]["picked"] == "off"  # <10%: not decisive
    assert os.environ["TMR_QUANT"] == "off"


def test_quant_sweep_skipped_when_xla_wins(clean_knobs, monkeypatch):
    """int8 rides the fused formulation only: when xla wins the impl
    sweep, the quant stage records "off" WITHOUT sweeping (the no-op
    completes the cache entry so later runs skip)."""
    _stub_non_tail_picks(monkeypatch)
    monkeypatch.setattr(
        at, "pick_decoder_impl",
        lambda *a, **k: {"xla": 0.01, "fused": 0.02},
    )
    calls = []
    monkeypatch.setattr(
        at, "pick_quant", lambda *a, **k: calls.append(1) or {"off": 0.01},
    )
    report = at.autotune(_cfg(), 1024, 4, tune_precision=True)
    assert report["TMR_DECODER_IMPL"]["picked"] == "xla"
    assert report["TMR_QUANT"] == {"picked": "off", "times": {}}
    assert os.environ["TMR_QUANT"] == "off"
    assert not calls


def test_quant_not_swept_for_training(clean_knobs, monkeypatch):
    """tune_precision=False (the training entry): quantized weights must
    never be elected into a training program."""
    _stub_non_tail_picks(monkeypatch)
    report = at.autotune(_cfg(), 1024, 4, tune_precision=False)
    assert "TMR_QUANT" not in report
    assert "TMR_QUANT" not in os.environ


def test_cached_quant_dropped_when_impl_evidence_changes(
    clean_knobs, monkeypatch
):
    """A cached int8 winner's decisive-win evidence is decoder-impl-
    specific: when the active impl no longer matches the stamped
    _quant_decoder_impl (or the impl is about to re-sweep), the cached
    quant entry must be dropped and re-decided, not inherited."""
    import json

    _stub_non_tail_picks(monkeypatch)
    monkeypatch.setattr(
        at, "pick_decoder_impl",
        lambda *a, **k: {"xla": 0.01, "fused": 0.02},
    )
    calls = []
    monkeypatch.setattr(
        at, "pick_quant", lambda *a, **k: calls.append(1) or {"off": 0.01},
    )
    cache_path = os.environ["TMR_AUTOTUNE_CACHE"]
    key = at._cache_key(_cfg(), 1024, 4, "vit_b", False)
    sig_impl = at._variants_sig("TMR_DECODER_IMPL")
    sig_quant = at._variants_sig("TMR_QUANT")
    with open(cache_path, "w") as f:
        json.dump({key: {
            "TMR_QUANT": "int8",
            "_quant_decoder_impl": "fused",
            "_variants_TMR_DECODER_IMPL": sig_impl,
            "_variants_TMR_QUANT": sig_quant,
        }}, f)
    report = at.autotune(_cfg(), 1024, 4, tune_precision=True)
    # the impl sweep ran (nothing cached for it), xla won -> the stale
    # int8 entry was dropped, and the no-op "off" recorded in its place
    assert os.environ["TMR_QUANT"] == "off"
    assert report["TMR_QUANT"]["picked"] == "off"


def test_tail_sweeps_skipped_for_no_boxreg_models(clean_knobs, monkeypatch):
    """Single-stack (box-regression-ablated) models stay on the module
    path: no TMR_DECODER_IMPL/TMR_QUANT sweep, nothing exported."""
    _stub_non_tail_picks(monkeypatch)
    cfg = _cfg()
    cfg.ablation_no_box_regression = True
    report = at.autotune(cfg, 1024, 4, tune_precision=True)
    assert "TMR_DECODER_IMPL" not in report
    assert "TMR_DECODER_IMPL" not in os.environ
    assert "TMR_QUANT" not in os.environ


@pytest.mark.slow
def test_pick_decoder_impl_real_microbenchmark(monkeypatch, tmp_path):
    """The real _sweep_tail_env harness at a tiny geometry: both
    formulations time cleanly (no fallback annotation — the fused gate
    passes at this shape), through the SAME stage program bench.py and
    profile_breakdown measure."""
    for k in KNOBS:
        monkeypatch.delenv(k, raising=False)
    times = at.pick_decoder_impl(1, 8, 16, 1, 3, rtt=0.0)
    assert set(times) == {"xla", "fused"}
    assert all(v > 0 for v in times.values())
    for k in KNOBS:
        os.environ.pop(k, None)


def test_pick_quant_sums_decoder_and_xcorr_stages(monkeypatch):
    """With emb_dim given, pick_quant's evidence is the SUM of the two
    surfaces the export flips (decoder tail + matcher correlation); a
    fallback annotation in EITHER stage poisons the combined row, the
    tail stage's refusal causes survive the xcorr sweep's clear, and the
    stored arm ("int8+store", swept via TMR_QUANT_STORAGE) reuses the
    int8 correlation timing (storage never touches the matcher)."""
    def tail_sweep(env_var, *a, **k):
        if env_var == "TMR_QUANT_STORAGE":
            at.LAST_SWEEP_REFUSALS.setdefault(env_var, {}).clear()
            return {"int8": 0.007}
        at.LAST_SWEEP_REFUSALS.setdefault("TMR_QUANT", {}).update(
            {"int8" + at.FALLBACK_SUFFIX: [{"gate": "quant_ok"}]}
        )
        return {"off": 0.010, "int8" + at.FALLBACK_SUFFIX: 0.008}

    monkeypatch.setattr(at, "_sweep_tail_env", tail_sweep)
    monkeypatch.setattr(
        at, "_sweep_xcorr_env",
        lambda env_var, *a, **k: (
            at.LAST_SWEEP_REFUSALS.setdefault(env_var, {}).clear()
            or {"off": 0.004, "int8": 0.003}
        ),
    )
    times = at.pick_quant(1, 8, 16, 1, 3, emb_dim=16, rtt=0.0)
    assert times == {"off": 0.014,
                     "int8" + at.FALLBACK_SUFFIX: pytest.approx(0.011),
                     "int8+store": pytest.approx(0.010)}
    assert at._electable(times) == {"off": 0.014,
                                    "int8+store": pytest.approx(0.010)}
    # the decoder stage's structured causes were merged back
    assert at.LAST_SWEEP_REFUSALS["TMR_QUANT"][
        "int8" + at.FALLBACK_SUFFIX
    ] == [{"gate": "quant_ok"}]


@pytest.mark.slow
def test_pick_quant_annotates_refused_rows(monkeypatch):
    """A quant sweep run while the fused gate refuses (kill-switch) must
    record the int8 row annotated as a fallback with its structured
    causes — quantized timings never masquerade as exact-path evidence."""
    for k in KNOBS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    monkeypatch.setenv("TMR_NO_FUSED_HEADS", "1")
    from tmr_tpu.ops import fused_heads as fh

    fh._OK_CACHE.clear()
    try:
        times = at.pick_quant(1, 8, 16, 1, 3, rtt=0.0)
        # every row fell back (impl gate refused under both TMR_QUANT
        # values), so each is annotated and none is electable
        assert times
        assert all(k.endswith(at.FALLBACK_SUFFIX) for k in times)
        assert at._electable(times) == {}
        refusals = at.LAST_SWEEP_REFUSALS.get("TMR_QUANT", {})
        assert any(refusals.values())
    finally:
        fh._OK_CACHE.clear()
        for k in KNOBS:
            os.environ.pop(k, None)
