"""Export artifact (export_encoder.py / utils/export.py — the ONNX-export
equivalent, reference export_onnx.py) and the feature-extractor CLI
(extract_feature.py, reference extract_feature.py:12-123)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmr_tpu.models.vit import SamViT
from tmr_tpu.utils.export import (
    export_encoder,
    exported_input_spec,
    load_exported,
    save_exported,
)


pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

TINY = dict(embed_dim=32, depth=2, num_heads=2, global_attn_indexes=(1,),
            window_size=2, out_chans=16, pretrain_img_size=64)
SIZE = 64


@pytest.fixture(scope="module")
def tiny_encoder():
    model = SamViT(**TINY)
    img = jnp.zeros((1, SIZE, SIZE, 3), jnp.float32)
    params = model.init(jax.random.key(0), img)["params"]
    return model, params


def test_export_roundtrip_matches_apply(tiny_encoder, tmp_path):
    model, params = tiny_encoder
    data = export_encoder(model, params, image_size=SIZE,
                          platforms=("cpu",))
    path = str(tmp_path / "enc.stablehlo")
    save_exported(data, path)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, SIZE, SIZE, 3)), jnp.float32)
    want = model.apply({"params": params}, x)
    got = load_exported(path)(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_export_symbolic_batch(tiny_encoder, tmp_path):
    """One artifact serves several batch sizes (the reference's dynamic
    batch axis, export_onnx.py:85-88)."""
    model, params = tiny_encoder
    path = str(tmp_path / "enc.stablehlo")
    save_exported(
        export_encoder(model, params, image_size=SIZE, platforms=("cpu",)),
        path,
    )
    shape, dtype = exported_input_spec(path)
    assert str(shape[0]) == "b" and shape[1:] == (SIZE, SIZE, 3)
    fn = load_exported(path)
    for b in (1, 3):
        out = fn(jnp.zeros((b, SIZE, SIZE, 3), jnp.float32))
        assert out.shape[0] == b


def test_mapreduce_from_artifact(tiny_encoder, tmp_path):
    from tmr_tpu.parallel.mapreduce import (
        feature_stats,
        make_encode_stats_fn_from_artifact,
    )

    model, params = tiny_encoder
    path = str(tmp_path / "enc.stablehlo")
    save_exported(
        export_encoder(model, params, image_size=SIZE, platforms=("cpu",)),
        path,
    )
    fn = make_encode_stats_fn_from_artifact(path)
    x = jnp.ones((2, SIZE, SIZE, 3), jnp.float32) * 0.5
    feats, stats = fn(x)
    assert stats.shape == (2, 4)
    want = model.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(feats), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(stats), np.asarray(feature_stats(want)), rtol=1e-5,
        atol=1e-6,
    )


# --------------------------------------------------------- extract_feature
def test_sam_preprocess_geometry():
    from tmr_tpu.data.transforms import sam_longest_side_preprocess

    img = np.full((50, 100, 3), 255, np.uint8)  # wide -> pad bottom
    out = sam_longest_side_preprocess(img, target=64)
    assert out.shape == (64, 64, 3)
    # bottom rows are padding (zeros), top-left is normalized white
    assert np.all(out[40:] == 0.0)
    assert out[0, 0, 0] > 2.0  # (255 - 123.675) / 58.395 ≈ 2.25


def test_extract_feature_cli(tiny_encoder, tmp_path, capsys):
    import extract_feature

    model, params = tiny_encoder
    img_path = str(tmp_path / "img.png")
    from PIL import Image

    rng = np.random.default_rng(1)
    Image.fromarray(rng.integers(0, 255, (48, 80, 3), dtype=np.uint8).astype(
        np.uint8)).save(img_path)

    stats = extract_feature.run_extraction_and_analyze(
        img_path, output_dir=str(tmp_path / "feat"), model=model,
        params=params, image_size=SIZE,
    )
    out = capsys.readouterr().out
    assert "FEATURE ANALYSIS" in out and "VERDICT" in out
    saved = np.load(stats["save_path"])
    assert saved.shape == (1, SIZE // 16, SIZE // 16, TINY["out_chans"])
    np.testing.assert_allclose(stats["mean"], saved.mean(), rtol=1e-5)
    np.testing.assert_allclose(stats["sparsity"], (saved <= 0).mean(),
                               rtol=1e-5)


def test_extract_feature_dummy_fallback(tiny_encoder, tmp_path, monkeypatch):
    """Missing image -> synthesized dummy (extract_feature.py:116-121)."""
    import extract_feature

    model, params = tiny_encoder
    monkeypatch.chdir(tmp_path)
    stats = extract_feature.run_extraction_and_analyze(
        "does/not/exist.jpg", output_dir="feat", model=model, params=params,
        image_size=SIZE,
    )
    assert os.path.exists(stats["save_path"])


def test_verdict_thresholds():
    from extract_feature import verdict

    assert verdict(0.0120).startswith("HARD")
    assert verdict(0.0140).startswith("EASY")
    assert verdict(0.0133) == "MEDIUM"


def test_export_detector_roundtrip_matches_eager(tmp_path):
    """Whole-detector artifact (beyond the reference's encoder-only
    export): the serialized (image, exemplars) -> (boxes, scores, valid)
    program — the Predictor's OWN fused pipeline, config flags included —
    must reproduce the live Predictor bit-for-bit after a disk round
    trip."""
    import jax

    from tmr_tpu.config import Config
    from tmr_tpu.inference import Predictor
    from tmr_tpu.models.matching_net import MatchingNet
    from tmr_tpu.utils.export import (
        export_detector,
        load_exported_detector,
        save_exported,
    )

    cfg = Config(
        backbone="sam_vit_b", emb_dim=16, fusion=True,
        feature_upsample=False, image_size=32,
        NMS_cls_threshold=0.3, NMS_iou_threshold=0.5, max_detections=16,
        template_buckets=(5,), compute_dtype="float32",
        positive_threshold=0.5, negative_threshold=0.5,
    )
    model = MatchingNet(
        backbone=SamViT(**TINY), emb_dim=16, fusion=True,
        template_capacity=5,
    )
    predictor = Predictor(cfg, model=model)
    rng = np.random.default_rng(5)
    image = jnp.asarray(rng.standard_normal((1, 32, 32, 3)), jnp.float32)
    ex = jnp.asarray([[[0.3, 0.3, 0.55, 0.6]]], jnp.float32)
    predictor.params = jax.jit(model.init)(
        jax.random.key(0), image, ex
    )["params"]

    data = export_detector(
        predictor, capacity=5, image_size=32, platforms=("cpu",)
    )
    path = str(tmp_path / "detector.stablehlo")
    save_exported(data, path)
    call = load_exported_detector(path)
    boxes, scores, valid = call(image, ex)

    # oracle: the live Predictor's own program
    dets = predictor._get_fn(5)(
        predictor.params, predictor.refiner_params, image, ex
    )
    assert np.asarray(valid).dtype == np.bool_
    assert np.asarray(valid).shape == np.asarray(dets["valid"]).shape
    np.testing.assert_array_equal(np.asarray(valid), np.asarray(dets["valid"]))
    np.testing.assert_allclose(
        np.asarray(boxes), np.asarray(dets["boxes"]), rtol=1e-6, atol=1e-7
    )
    np.testing.assert_allclose(
        np.asarray(scores), np.asarray(dets["scores"]), rtol=1e-6, atol=1e-7
    )


def test_export_detector_multi_exemplar_matches_live(tmp_path):
    """n_exemplars > 1 exports the fused multi-exemplar program (union NMS,
    k_real masking): round-tripped artifact == live
    predict_multi_exemplar on the same 2-of-3-slot input."""
    import jax

    from tmr_tpu.config import Config
    from tmr_tpu.inference import Predictor
    from tmr_tpu.models.matching_net import MatchingNet
    from tmr_tpu.utils.export import (
        export_detector,
        load_exported_detector,
        save_exported,
    )

    cfg = Config(
        backbone="sam_vit_b", emb_dim=16, fusion=True,
        feature_upsample=False, image_size=32,
        NMS_cls_threshold=0.3, NMS_iou_threshold=0.5, max_detections=16,
        template_buckets=(5,), compute_dtype="float32",
        positive_threshold=0.5, negative_threshold=0.5, num_exemplars=3,
    )
    model = MatchingNet(
        backbone=SamViT(**TINY), emb_dim=16, fusion=True,
        template_capacity=5,
    )
    predictor = Predictor(cfg, model=model)
    rng = np.random.default_rng(6)
    image = jnp.asarray(rng.standard_normal((1, 32, 32, 3)), jnp.float32)
    ex2 = np.asarray(
        [[0.3, 0.3, 0.55, 0.6], [0.1, 0.15, 0.4, 0.35]], np.float32
    )
    predictor.params = jax.jit(model.init)(
        jax.random.key(0), image, jnp.asarray(ex2[None, :1])
    )["params"]

    # n_exemplars must equal the K bucket live inference picks for the
    # serving k (K_BUCKETS) — same program, slot-exact comparison
    data = export_detector(
        predictor, capacity=5, image_size=32, platforms=("cpu",),
        n_exemplars=2,
    )
    path = str(tmp_path / "det_multi.stablehlo")
    save_exported(data, path)
    call = load_exported_detector(path)
    boxes, scores, valid = call(
        image, jnp.asarray(ex2), jnp.asarray(2, jnp.int32)
    )

    live = predictor.predict_multi_exemplar(image, ex2)
    np.testing.assert_array_equal(np.asarray(valid), np.asarray(live["valid"]))
    np.testing.assert_allclose(
        np.asarray(boxes), np.asarray(live["boxes"]), rtol=1e-6, atol=1e-7
    )
    np.testing.assert_allclose(
        np.asarray(scores), np.asarray(live["scores"]), rtol=1e-6, atol=1e-7
    )
