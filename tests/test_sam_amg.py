"""Tests for tmr_tpu.sam_amg (the reference utils/segment_anything/utils/
amg.py surface) and the crop-pyramid automatic mask generator."""

import numpy as np
import pytest

from tmr_tpu import sam_amg


# ---------------------------------------------------------------- point grids

pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

def test_build_point_grid_matches_reference_layout():
    g = sam_amg.build_point_grid(2)
    # offset 1/4: [[.25,.25],[.75,.25],[.25,.75],[.75,.75]] (x varies fastest)
    np.testing.assert_allclose(
        g, [[0.25, 0.25], [0.75, 0.25], [0.25, 0.75], [0.75, 0.75]]
    )


def test_build_all_layer_point_grids_downscales():
    grids = sam_amg.build_all_layer_point_grids(8, 2, 2)
    assert [len(g) for g in grids] == [64, 16, 4]


# ----------------------------------------------------------------- crop boxes
def test_generate_crop_boxes_layer_counts_and_cover():
    boxes, layers = sam_amg.generate_crop_boxes((600, 900), 2, 512 / 1500)
    assert layers.count(0) == 1 and layers.count(1) == 4 and layers.count(2) == 16
    assert boxes[0] == [0, 0, 900, 600]
    for (x0, y0, x1, y1) in boxes:
        assert 0 <= x0 < x1 <= 900 and 0 <= y0 < y1 <= 600
    # layer-1 crops overlap: total covered width > image width
    l1 = [b for b, l in zip(boxes, layers) if l == 1]
    assert sum(b[2] - b[0] for b in l1[:2]) > 900 / 2 * 2


def test_uncrop_roundtrip():
    crop = [10, 20, 50, 60]
    boxes = np.array([[1.0, 2.0, 3.0, 4.0]])
    np.testing.assert_allclose(
        sam_amg.uncrop_boxes_xyxy(boxes, crop), [[11, 22, 13, 24]]
    )
    np.testing.assert_allclose(
        sam_amg.uncrop_points(np.array([[5.0, 6.0]]), crop), [[15, 26]]
    )
    m = np.ones((40, 40), bool)
    full = sam_amg.uncrop_mask(m, crop, 100, 200)
    assert full.shape == (100, 200)
    assert full[20:60, 10:50].all() and full.sum() == 40 * 40


def test_is_box_near_crop_edge():
    crop = [0, 0, 50, 50]
    orig = [0, 0, 100, 100]
    boxes = np.array(
        [[5.0, 5.0, 49.0, 30.0],   # touches crop right edge (not image edge)
         [5.0, 5.0, 30.0, 30.0],   # interior
         [0.0, 0.0, 30.0, 30.0]],  # touches image edge -> NOT filtered
    )
    near = sam_amg.is_box_near_crop_edge(boxes, crop, orig, atol=5.0)
    assert near.tolist() == [True, False, False]


# ------------------------------------------------------------------------ RLE
def test_rle_roundtrip_and_area():
    rng = np.random.default_rng(0)
    for _ in range(5):
        m = rng.random((13, 17)) > 0.5
        rle = sam_amg.mask_to_rle(m)
        assert rle["size"] == [13, 17]
        assert sum(rle["counts"]) == 13 * 17
        np.testing.assert_array_equal(sam_amg.rle_to_mask(rle), m)
        assert sam_amg.area_from_rle(rle) == int(m.sum())
    # empty + full masks
    z = np.zeros((4, 6), bool)
    assert sam_amg.mask_to_rle(z)["counts"] == [24]
    f = np.ones((4, 6), bool)
    assert sam_amg.mask_to_rle(f)["counts"] == [0, 24]


def test_rle_is_column_major():
    # single pixel at (row 1, col 0) of a 3x2 mask: fortran index = 1
    m = np.zeros((3, 2), bool)
    m[1, 0] = True
    assert sam_amg.mask_to_rle(m)["counts"] == [1, 1, 4]


# ------------------------------------------------------------- small regions
def test_remove_small_regions_holes_and_islands():
    m = np.zeros((20, 20), bool)
    m[2:18, 2:18] = True
    m[8:10, 8:10] = False      # small hole
    m2 = m.copy()
    m2[0, 19] = True           # 1px island
    filled, changed = sam_amg.remove_small_regions(m2, 8, "holes")
    assert changed and filled[8:10, 8:10].all()
    cleaned, changed = sam_amg.remove_small_regions(m2, 8, "islands")
    assert changed and not cleaned[0, 19] and cleaned[2:18, 2:18].sum() > 0
    # below-threshold everything: keep the largest island
    tiny = np.zeros((10, 10), bool)
    tiny[0:2, 0:2] = True
    tiny[5, 5] = True
    kept, changed = sam_amg.remove_small_regions(tiny, 100, "islands")
    assert changed and kept[0:2, 0:2].all() and not kept[5, 5]
    # no change case
    _, changed = sam_amg.remove_small_regions(m, 1, "islands")
    assert not changed


def test_stability_score():
    logits = np.array([[[2.0, 0.5], [-0.5, -2.0]]])
    # offset 1: >1 -> 1 px; >-1 -> 3 px
    np.testing.assert_allclose(
        sam_amg.calculate_stability_score(logits, 0.0, 1.0), [1 / 3]
    )


# ----------------------------------------------------------- batched records
def test_records_cat_and_filter():
    a = {"x": np.arange(3), "l": ["a", "b", "c"]}
    b = {"x": np.arange(3, 5), "l": ["d", "e"]}
    c = sam_amg.cat_records(a, b)
    np.testing.assert_array_equal(c["x"], np.arange(5))
    assert c["l"] == ["a", "b", "c", "d", "e"]
    f = sam_amg.filter_records(c, np.array([True, False, True, False, True]))
    np.testing.assert_array_equal(f["x"], [0, 2, 4])
    assert f["l"] == ["a", "c", "e"]


def test_batch_iterator():
    chunks = list(sam_amg.batch_iterator(2, list(range(5))))
    assert [c[0] for c in chunks] == [[0, 1], [2, 3], [4]]


# --------------------------------------------------- crop-pyramid generator
def test_amg_crop_pyramid_end_to_end():
    """crop_n_layers=1 runs 5 crops (1 + 4), output carries crop_box, and
    results stay within image bounds; min_mask_region_area smoke."""
    from tmr_tpu.models.vit import SamViT
    from tmr_tpu.sam import Sam, SamAutomaticMaskGenerator

    sam = Sam(model_type="vit_b")
    sam.image_encoder = SamViT(
        embed_dim=32, depth=2, num_heads=2, global_attn_indexes=(1,),
        patch_size=8, window_size=3, out_chans=8, pretrain_img_size=32,
    )
    sam.image_size = 32
    from tmr_tpu.models.sam_decoder import MaskDecoder, PromptEncoder

    sam.prompt_encoder = PromptEncoder(embed_dim=8)
    sam.mask_decoder = MaskDecoder(
        transformer_dim=8, transformer_num_heads=2, transformer_mlp_dim=16
    )
    sam.init_random(seed=0)

    amg = SamAutomaticMaskGenerator(
        sam, points_per_side=2, points_per_batch=4,
        pred_iou_thresh=-1e9, stability_score_thresh=-1.0,
        box_nms_thresh=0.95, crop_n_layers=1, crop_nms_thresh=0.95,
        min_mask_region_area=1,
    )
    rng = np.random.default_rng(5)
    img = rng.integers(0, 255, (40, 56, 3), dtype=np.uint8).astype(np.uint8)
    out = amg.generate(img)
    assert isinstance(out, list)
    for d in out:
        assert d["segmentation"].shape == (40, 56)
        x, y, w, h = d["bbox"]
        assert 0 <= x < 56 and 0 <= y < 40 and w > 0 and h > 0
        assert len(d["crop_box"]) == 4
    # uncompressed_rle output mode
    amg.output_mode = "uncompressed_rle"
    out2 = amg.generate(img)
    for d in out2:
        assert set(d["segmentation"]) == {"size", "counts"}


def test_amg_arg_validation():
    from tmr_tpu.sam import Sam, SamAutomaticMaskGenerator

    sam = Sam(model_type="vit_b")
    with pytest.raises(ValueError):
        SamAutomaticMaskGenerator(sam, points_per_side=None)
    with pytest.raises(ValueError):
        SamAutomaticMaskGenerator(
            sam, points_per_side=4, output_mode="bogus"
        )
    with pytest.raises(ImportError):
        SamAutomaticMaskGenerator(
            sam, points_per_side=4, output_mode="coco_rle"
        )


# ------------------------------------------------------ deploy decoder
def _tiny_decoder_sam():
    from tmr_tpu.models.sam_decoder import MaskDecoder, PromptEncoder
    from tmr_tpu.models.vit import SamViT
    from tmr_tpu.sam import Sam

    sam = Sam(model_type="vit_b")
    sam.image_encoder = SamViT(
        embed_dim=32, depth=2, num_heads=2, global_attn_indexes=(1,),
        patch_size=8, window_size=3, out_chans=8, pretrain_img_size=32,
    )
    sam.image_size = 32
    sam.prompt_encoder = PromptEncoder(embed_dim=8)
    sam.mask_decoder = MaskDecoder(
        transformer_dim=8, transformer_num_heads=2, transformer_mlp_dim=16
    )
    sam.init_random(seed=0)
    return sam


def test_deploy_decoder_shapes_and_modes():
    """SamDeployDecoder mirrors SamOnnxModel.forward (onnx.py:110-144):
    output shapes, single-mask selection, stability scoring, extra metrics,
    and the has_mask_input switch."""
    import jax.numpy as jnp

    from tmr_tpu.sam import SamDeployDecoder

    sam = _tiny_decoder_sam()
    emb_hw = (4, 4)
    rng = np.random.default_rng(0)
    emb = jnp.asarray(rng.standard_normal((1, 4, 4, 8)), jnp.float32)
    pts = jnp.asarray([[[8.0, 8.0], [0.0, 0.0]],
                       [[20.0, 12.0], [0.0, 0.0]]], jnp.float32)
    labs = jnp.asarray([[1, -1], [1, -1]], jnp.int32)
    mask_in = jnp.zeros((2, 16, 16, 1), jnp.float32)
    no_mask = jnp.zeros((2,), jnp.float32)

    multi = SamDeployDecoder(sam, return_single_mask=False)
    out, scores, low = multi(sam.params, emb, pts, labs, mask_in, no_mask,
                             (24, 30))
    assert out.shape == (2, 4, 24, 30)  # all 4 mask tokens
    assert scores.shape == (2, 4) and low.shape == (2, 4, 16, 16)

    single = SamDeployDecoder(sam, return_single_mask=True)
    out1, scores1, _ = single(sam.params, emb, pts, labs, mask_in, no_mask,
                              (24, 30))
    assert out1.shape == (2, 1, 24, 30) and scores1.shape == (2, 1)
    # 2 point slots (single click + pad): token 0 penalized by -500, so the
    # best MULTIMASK token (1..3) by predicted IoU is selected (onnx.py
    # score-reweight semantics)
    expect = np.argmax(
        np.asarray(scores) + (2 - 2.5) * np.array([1000.0, 0, 0, 0]), axis=1
    )
    assert (expect > 0).all()
    for b in range(2):
        np.testing.assert_allclose(
            np.asarray(out1[b, 0]), np.asarray(out[b, expect[b]]),
            rtol=1e-5, atol=1e-5,
        )

    # has_mask_input switches the dense embedding -> different logits
    out_m, _, _ = multi(
        sam.params, emb, pts, labs,
        jnp.asarray(rng.standard_normal((2, 16, 16, 1)), jnp.float32),
        jnp.ones((2,), jnp.float32), (24, 30),
    )
    assert not np.allclose(np.asarray(out_m), np.asarray(out))

    extra = SamDeployDecoder(sam, return_single_mask=False,
                             use_stability_score=True,
                             return_extra_metrics=True)
    o, s, stab, areas, low = extra(sam.params, emb, pts, labs, mask_in,
                                   no_mask, (24, 30))
    assert s.shape == (2, 4) and stab.shape == (2, 4)
    assert np.all((np.asarray(s) >= 0) & (np.asarray(s) <= 1))
    assert areas.shape == (2, 4)


def test_deploy_decoder_export_roundtrip(tmp_path):
    """Serialized StableHLO artifact (the ONNX-file equivalent) loads and
    reproduces the live program, including the symbolic prompt axis."""
    import jax.numpy as jnp

    from tmr_tpu.sam import SamDeployDecoder
    from tmr_tpu.utils.export import (
        export_sam_decoder,
        load_exported_decoder,
        save_exported,
    )

    sam = _tiny_decoder_sam()
    deploy = SamDeployDecoder(sam, return_single_mask=True)
    data = export_sam_decoder(
        deploy, sam.params, (4, 4), num_points=2, orig_im_size=(24, 30),
        platforms=("cpu",),
    )
    path = str(tmp_path / "decoder.stablehlo")
    save_exported(data, path)
    call = load_exported_decoder(path)

    rng = np.random.default_rng(1)
    emb = jnp.asarray(rng.standard_normal((1, 4, 4, 8)), jnp.float32)
    for n in (1, 3):  # symbolic prompt axis serves several batch sizes
        pts = jnp.asarray(rng.uniform(0, 32, (n, 2, 2)), jnp.float32)
        labs = jnp.concatenate(
            [jnp.ones((n, 1), jnp.int32), -jnp.ones((n, 1), jnp.int32)], 1
        )
        mask_in = jnp.zeros((n, 16, 16, 1), jnp.float32)
        has = jnp.zeros((n,), jnp.float32)
        got = call(emb, pts, labs, mask_in, has)
        want = deploy(sam.params, emb, pts, labs, mask_in, has, (24, 30))
        for g, w in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=1e-4, atol=1e-4
            )
