"""Sequence/context parallelism (tmr_tpu/parallel/ring.py): ring attention,
Ulysses all-to-all, and the ViT decomposed-rel-pos ring variant, validated
against dense attention on the 8-device CPU mesh."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from tmr_tpu.parallel.compat import shard_map

from tmr_tpu.parallel.ring import (
    dense_attention,
    make_ring_attention_fn,
    ring_attention,
    ring_decomposed_attention,
    ulysses_attention,
)

B, H, S, D = 2, 4, 64, 16
SEQ_SPEC = P(None, None, "seq", None)


def seq_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


def rand_qkv(seed, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, H, S, D)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("n", [4, 8])
def test_ring_matches_dense(n):
    q, k, v = rand_qkv(0)
    mesh = seq_mesh(n)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq"),
        mesh=mesh, in_specs=(SEQ_SPEC,) * 3, out_specs=SEQ_SPEC,
        check_vma=False,
    )
    got = jax.jit(fn)(q, k, v)
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_bias_matches_dense():
    n = 4
    q, k, v = rand_qkv(1)
    rng = np.random.default_rng(2)
    bias = jnp.asarray(rng.standard_normal((1, H, S, S)), jnp.float32)
    blk = S // n

    mesh = seq_mesh(n)

    def local(q, k, v):
        def bias_fn(qi, ki):
            return jax.lax.dynamic_slice(
                bias, (0, 0, qi * blk, ki * blk), (1, H, blk, blk)
            )

        return ring_attention(q, k, v, "seq", bias_fn=bias_fn)

    got = jax.jit(shard_map(local, mesh=mesh, in_specs=(SEQ_SPEC,) * 3,
                            out_specs=SEQ_SPEC, check_vma=False))(q, k, v)
    want = dense_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("n", [2, 4])
def test_ulysses_matches_dense(n):
    q, k, v = rand_qkv(3)
    mesh = seq_mesh(n)
    fn = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq"),
        mesh=mesh, in_specs=(SEQ_SPEC,) * 3, out_specs=SEQ_SPEC,
        check_vma=False,
    )
    got = jax.jit(fn)(q, k, v)
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_bf16_inputs():
    q, k, v = rand_qkv(4, jnp.bfloat16)
    mesh = seq_mesh(4)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq"),
        mesh=mesh, in_specs=(SEQ_SPEC,) * 3, out_specs=SEQ_SPEC,
        check_vma=False,
    )
    got = jax.jit(fn)(q, k, v)
    assert got.dtype == jnp.bfloat16
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


@pytest.mark.slow
def test_ring_gradients_match_dense():
    q, k, v = rand_qkv(5)
    mesh = seq_mesh(4)
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq"),
        mesh=mesh, in_specs=(SEQ_SPEC,) * 3, out_specs=SEQ_SPEC,
        check_vma=False,
    )

    def loss_ring(q, k, v):
        return (ring(q, k, v) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=5e-4, atol=5e-4)


def test_ring_decomposed_matches_vit_dense():
    """Row-sharded ring attention with decomposed rel-pos == the dense
    decomposed attention of models/vit.py Attention (sam_ViT.py:325-361)."""
    n = 4
    GH, GW = 8, 8  # token grid; S = 64
    hd = D
    rng = np.random.default_rng(6)
    q, k, v = rand_qkv(7)
    rh = jnp.asarray(rng.standard_normal((GH, GH, hd)), jnp.float32)
    rw = jnp.asarray(rng.standard_normal((GW, GW, hd)), jnp.float32)

    # dense oracle, exactly the vit.py:127-132 formulation
    scale = hd ** -0.5
    r_q = np.asarray(q).reshape(B, H, GH, GW, hd)
    rel_h = np.einsum("bnhwc,hkc->bnhwk", r_q, np.asarray(rh))
    rel_w = np.einsum("bnhwc,wkc->bnhwk", r_q, np.asarray(rw))
    bias = rel_h[..., :, None] + rel_w[..., None, :]
    bias = jnp.asarray(bias.reshape(B, H, S, S))
    want = dense_attention(q, k, v, bias=bias, scale=scale)

    mesh = seq_mesh(n)
    fn = shard_map(
        lambda q, k, v: ring_decomposed_attention(q, k, v, rh, rw, GW, "seq"),
        mesh=mesh, in_specs=(SEQ_SPEC,) * 3, out_specs=SEQ_SPEC,
        check_vma=False,
    )
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_vit_seq_parallel_matches_dense():
    """SamViT with a 'seq' mesh (ring-attention global blocks) must produce
    the same features as the single-device dense path."""
    from tmr_tpu.models.vit import SamViT

    tiny = dict(embed_dim=32, depth=2, num_heads=2, global_attn_indexes=(1,),
                window_size=2, out_chans=8, pretrain_img_size=64)
    x = jnp.asarray(
        np.random.default_rng(9).standard_normal((2, 64, 64, 3)), jnp.float32
    )
    dense_model = SamViT(**tiny)
    params = dense_model.init(jax.random.key(0), x)["params"]
    want = dense_model.apply({"params": params}, x)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "seq"))
    ring_model = SamViT(**tiny, seq_mesh=mesh)
    got = jax.jit(
        lambda p, v: ring_model.apply({"params": p}, v)
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_vit_seq_parallel_grad_matches_dense():
    """Backward pass through the ring island matches the dense grad (the
    training path under context parallelism)."""
    from tmr_tpu.models.vit import SamViT

    tiny = dict(embed_dim=16, depth=1, num_heads=2, global_attn_indexes=(0,),
                window_size=0, out_chans=8, pretrain_img_size=32)
    x = jnp.asarray(
        np.random.default_rng(10).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    dense_model = SamViT(**tiny)
    params = dense_model.init(jax.random.key(1), x)["params"]

    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    ring_model = SamViT(**tiny, seq_mesh=mesh)

    def loss(model, p):
        return (model.apply({"params": p}, x) ** 2).mean()

    g_dense = jax.jit(jax.grad(partial(loss, dense_model)))(params)
    g_ring = jax.jit(jax.grad(partial(loss, ring_model)))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4
        ),
        g_dense, g_ring,
    )


def test_vit_seq_parallel_batch1_on_dp_mesh():
    """Eval batch (1) not divisible by the data axis must fall back to a
    replicated batch instead of crashing (regression)."""
    from tmr_tpu.models.vit import SamViT

    tiny = dict(embed_dim=32, depth=1, num_heads=2, global_attn_indexes=(0,),
                window_size=0, out_chans=8, pretrain_img_size=64)
    x = jnp.asarray(
        np.random.default_rng(11).standard_normal((1, 64, 64, 3)), jnp.float32
    )
    dense = SamViT(**tiny)
    params = dense.init(jax.random.key(2), x)["params"]
    want = dense.apply({"params": params}, x)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "seq"))
    ring = SamViT(**tiny, seq_mesh=mesh)
    got = jax.jit(lambda p, v: ring.apply({"params": p}, v))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_vit_seq_parallel_composes_with_tp_mesh():
    """Heads shard over 'model' inside the ring island (TP+SP compose)."""
    from tmr_tpu.models.vit import SamViT

    tiny = dict(embed_dim=32, depth=1, num_heads=2, global_attn_indexes=(0,),
                window_size=0, out_chans=8, pretrain_img_size=64)
    x = jnp.asarray(
        np.random.default_rng(12).standard_normal((2, 64, 64, 3)), jnp.float32
    )
    dense = SamViT(**tiny)
    params = dense.init(jax.random.key(3), x)["params"]
    want = dense.apply({"params": params}, x)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "model", "seq"))
    ring = SamViT(**tiny, seq_mesh=mesh)
    got = jax.jit(lambda p, v: ring.apply({"params": p}, v))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_make_mesh_axis_name_validation():
    from tmr_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError):
        make_mesh((2, 2), axis_names=("data",))
    m = make_mesh((2, 2, 2))
    assert m.axis_names == ("data", "model", "seq")
    m2 = make_mesh((4,), axis_names=("replica",))
    assert m2.axis_names == ("replica",)


def test_make_ring_attention_fn_convenience():
    q, k, v = rand_qkv(8)
    mesh = seq_mesh(8)
    fn = make_ring_attention_fn(mesh)
    got = jax.jit(fn)(q, k, v)
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_blockwise_attention_matches_dense_at_global_grid():
    """The blockwise path is the production kernel for every global-attention
    block at real image sizes (h*w >= 1024 in models/vit.py); pin it to the
    dense oracle at a grid that actually takes that branch (32x32 = 1024
    tokens), with and without the decomposed rel-pos bias."""
    import numpy as np

    from tmr_tpu.models.vit import blockwise_decomposed_attention
    from tmr_tpu.parallel.ring import dense_attention

    rng = np.random.default_rng(5)
    B, H, gh, gw, D = 1, 2, 32, 32, 8
    S = gh * gw
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    rh = jnp.asarray(rng.standard_normal((gh, gh, D)), jnp.float32) * 0.2
    rw = jnp.asarray(rng.standard_normal((gw, gw, D)), jnp.float32) * 0.2
    scale = D**-0.5

    r_q = q.reshape(B, H, gh, gw, D)
    rel_h = jnp.einsum("bnhwc,hkc->bnhwk", r_q, rh)
    rel_w = jnp.einsum("bnhwc,wkc->bnhwk", r_q, rw)
    bias = (rel_h[..., :, None] + rel_w[..., None, :]).reshape(B, H, S, S)

    got = jax.jit(
        lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw)
    want = dense_attention(q, k, v, bias=bias, scale=scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    got_nb = jax.jit(
        lambda *a: blockwise_decomposed_attention(*a, None, None, (gh, gw), scale)
    )(q, k, v)
    want_nb = dense_attention(q, k, v, scale=scale)
    np.testing.assert_allclose(np.asarray(got_nb), np.asarray(want_nb),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_blockfolded_attention_matches_blockwise():
    """TMR_GLOBAL_ATTN=blockfolded (fold-into-QK + band scan, models/vit.py)
    must equal the exact blockwise path in f32 — the fold is algebraically
    exact there — at a grid that takes the global branch, bias on and off,
    non-square grid included."""
    import numpy as np

    from tmr_tpu.models.vit import (
        blockfolded_decomposed_attention,
        blockwise_decomposed_attention,
    )

    rng = np.random.default_rng(11)
    for gh, gw in ((32, 32), (16, 8)):
        B, H, D = 2, 3, 8
        S = gh * gw
        q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
        rh = jnp.asarray(rng.standard_normal((gh, gh, D)), jnp.float32) * 0.2
        rw = jnp.asarray(rng.standard_normal((gw, gw, D)), jnp.float32) * 0.2
        scale = D**-0.5

        got = jax.jit(
            lambda *a: blockfolded_decomposed_attention(*a, (gh, gw), scale)
        )(q, k, v, rh, rw)
        want = jax.jit(
            lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
        )(q, k, v, rh, rw)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

        got_nb = jax.jit(
            lambda *a: blockfolded_decomposed_attention(
                *a, None, None, (gh, gw), scale)
        )(q, k, v)
        want_nb = jax.jit(
            lambda *a: blockwise_decomposed_attention(
                *a, None, None, (gh, gw), scale)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(got_nb), np.asarray(want_nb),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_densefolded_attention_matches_blockwise():
    """TMR_GLOBAL_ATTN=densefolded (folded QK, no band scan) must equal the
    exact blockwise path in f32, bias on and off, non-square grid included
    — same contract as blockfolded, different XLA schedule."""
    from tmr_tpu.models.vit import (
        blockwise_decomposed_attention,
        densefolded_decomposed_attention,
    )

    rng = np.random.default_rng(13)
    for gh, gw in ((32, 32), (16, 8)):
        B, H, D = 2, 3, 8
        S = gh * gw
        q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
        rh = jnp.asarray(rng.standard_normal((gh, gh, D)), jnp.float32) * 0.2
        rw = jnp.asarray(rng.standard_normal((gw, gw, D)), jnp.float32) * 0.2
        scale = D**-0.5

        got = jax.jit(
            lambda *a: densefolded_decomposed_attention(*a, (gh, gw), scale)
        )(q, k, v, rh, rw)
        want = jax.jit(
            lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
        )(q, k, v, rh, rw)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

        got_nb = jax.jit(
            lambda *a: densefolded_decomposed_attention(
                *a, None, None, (gh, gw), scale)
        )(q, k, v)
        want_nb = jax.jit(
            lambda *a: blockwise_decomposed_attention(
                *a, None, None, (gh, gw), scale)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(got_nb), np.asarray(want_nb),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_global_bands_unroll_invariance(monkeypatch):
    """TMR_GLOBAL_BANDS_UNROLL is a schedule knob: unroll 2/4 (and a value
    past the band count, which clamps) must match the default scan — the
    bands compute the same ops either way. Tolerance instead of bit-equal:
    rolled vs unrolled scan bodies are different XLA programs and the
    compiler may legally reassociate the per-band reductions."""
    from tmr_tpu.models.vit import blockwise_decomposed_attention

    rng = np.random.default_rng(14)
    gh = gw = 32
    B, H, D = 2, 3, 8
    S = gh * gw
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    rh = jnp.asarray(rng.standard_normal((gh, gh, D)), jnp.float32) * 0.2
    rw = jnp.asarray(rng.standard_normal((gw, gw, D)), jnp.float32) * 0.2
    scale = D**-0.5

    monkeypatch.delenv("TMR_GLOBAL_BANDS_UNROLL", raising=False)
    want = jax.jit(
        lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw)
    for unroll in ("2", "4", "1000"):
        monkeypatch.setenv("TMR_GLOBAL_BANDS_UNROLL", unroll)
        got = jax.jit(
            lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
        )(q, k, v, rh, rw)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-7)

    monkeypatch.setenv("TMR_GLOBAL_BANDS_UNROLL", "auto")
    with pytest.raises(ValueError, match="TMR_GLOBAL_BANDS_UNROLL"):
        jax.jit(
            lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
        )(q, k, v, rh, rw)


@pytest.mark.slow
def test_scores_dtype_bf16_matches_oracle(monkeypatch):
    """TMR_GLOBAL_SCORES_DTYPE=bf16 (folded paths materialize the score
    tiles in bf16 — half the HBM traffic) must stay within bf16-rounding
    tolerance of the exact blockwise oracle, for both folded formulations;
    f32 inputs must be untouched by the knob (bit-equal to f32 scores)."""
    from tmr_tpu.models.vit import (
        blockfolded_decomposed_attention,
        blockwise_decomposed_attention,
        densefolded_decomposed_attention,
    )

    rng = np.random.default_rng(16)
    gh = gw = 16
    B, H, D = 2, 3, 8
    S = gh * gw
    mk = lambda *s: jnp.asarray(rng.standard_normal(s), jnp.float32)
    q, k, v = mk(B, H, S, D), mk(B, H, S, D), mk(B, H, S, D)
    rh, rw = mk(gh, gh, D) * 0.2, mk(gw, gw, D) * 0.2
    scale = D**-0.5

    monkeypatch.delenv("TMR_GLOBAL_SCORES_DTYPE", raising=False)
    oracle = np.asarray(jax.jit(
        lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw), np.float32)

    monkeypatch.setenv("TMR_GLOBAL_SCORES_DTYPE", "bf16")
    for name, fn in (("blockfolded", blockfolded_decomposed_attention),
                     ("densefolded", densefolded_decomposed_attention)):
        got16 = np.asarray(jax.jit(
            lambda *a, _f=fn: _f(*a, (gh, gw), scale)
        )(*(t.astype(jnp.bfloat16) for t in (q, k, v)), rh, rw), np.float32)
        rel = np.abs(got16 - oracle).max() / (np.abs(oracle).max() + 1e-6)
        assert rel < 0.05, (name, rel)
        # liveness: the knob must change the traced PROGRAM (bf16 score
        # tiles where the f32 run had f32). Output inequality is the
        # wrong pin at this tiny geometry — the post-softmax bf16
        # rounding can absorb the score-tile rounding entirely (it does
        # for densefolded on CPU) — so assert at the jaxpr level, the
        # PR-1 no-S^2 technique.
        trace = lambda _f=fn: str(jax.make_jaxpr(
            lambda *a: _f(*a, (gh, gw), scale)
        )(*(t.astype(jnp.bfloat16) for t in (q, k, v)), rh, rw))
        jaxpr_on = trace()
        monkeypatch.delenv("TMR_GLOBAL_SCORES_DTYPE", raising=False)
        jaxpr_off = trace()
        monkeypatch.setenv("TMR_GLOBAL_SCORES_DTYPE", "bf16")
        assert jaxpr_on != jaxpr_off, f"{name}: knob is a silent no-op"

        # f32 inputs: the knob must be inert (exact path untouched)
        got_f32 = np.asarray(jax.jit(
            lambda *a, _f=fn: _f(*a, (gh, gw), scale)
        )(q, k, v, rh, rw), np.float32)
        np.testing.assert_allclose(got_f32, oracle, rtol=1e-5, atol=1e-5)

    # the PARITY ORACLE must ignore the env knob entirely: a bare
    # blockwise call with rh=None (no-rel-pos models, and the pallas
    # custom_vjp's backward oracle) under TMR_GLOBAL_SCORES_DTYPE=bf16
    # must be bit-equal to the knob-unset run — the knob is plumbed as an
    # explicit parameter only the gated folded formulations pass
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    bw16 = np.asarray(jax.jit(
        lambda *a: blockwise_decomposed_attention(
            *a, None, None, (gh, gw), scale)
    )(qb, kb, vb), np.float32)
    monkeypatch.delenv("TMR_GLOBAL_SCORES_DTYPE")
    bw_ref = np.asarray(jax.jit(
        lambda *a: blockwise_decomposed_attention(
            *a, None, None, (gh, gw), scale)
    )(qb, kb, vb), np.float32)
    np.testing.assert_array_equal(bw16, bw_ref)

    monkeypatch.setenv("TMR_GLOBAL_SCORES_DTYPE", "fp8")
    with pytest.raises(ValueError, match="TMR_GLOBAL_SCORES_DTYPE"):
        jax.jit(
            lambda *a: blockfolded_decomposed_attention(
                *a, (gh, gw), scale)
        )(*(t.astype(jnp.bfloat16) for t in (q, k, v)), rh, rw)


@pytest.mark.slow
def test_scores_dtype_gate_keys_on_knob(monkeypatch):
    """The blockfolded/densefolded numerics gates must cache their verdict
    PER scores dtype — a verdict under f32 scores must never vouch for
    bf16 score tiles (different checked numerics)."""
    from tmr_tpu.ops import flash_attn

    flash_attn.blockfolded_ok.cache_clear()
    monkeypatch.delenv("TMR_GLOBAL_SCORES_DTYPE", raising=False)
    v_f32 = flash_attn.blockfolded_ok(16, 16, 8, "f32")
    monkeypatch.setenv("TMR_GLOBAL_SCORES_DTYPE", "bf16")
    v_bf16 = flash_attn.blockfolded_ok(16, 16, 8, "bf16")
    assert isinstance(v_f32, bool) and isinstance(v_bf16, bool)
    info = flash_attn.blockfolded_ok.cache_info()
    assert info.currsize >= 2  # two distinct cache entries, not one reused


@pytest.mark.slow
def test_global_attn_env_dispatch_densefolded(monkeypatch):
    """Attention must dispatch to densefolded (blockwise-equal output)
    when TMR_GLOBAL_ATTN=densefolded — the env plumbing, not just the
    free function."""
    from tmr_tpu.models.vit import Attention

    rng = np.random.default_rng(15)
    x = jnp.asarray(rng.standard_normal((1, 32, 32, 16)), jnp.float32)
    attn = Attention(num_heads=2, rel_pos_size=(32, 32))
    params = attn.init(jax.random.key(0), x)

    monkeypatch.setenv("TMR_GLOBAL_ATTN", "blockwise")
    want = jax.jit(attn.apply)(params, x)
    monkeypatch.setenv("TMR_GLOBAL_ATTN", "densefolded")
    got = jax.jit(attn.apply)(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_global_attn_env_dispatch_blockfolded(monkeypatch):
    """The Attention module must actually dispatch to the blockfolded path
    (and produce blockwise-equal output) when TMR_GLOBAL_ATTN=blockfolded —
    guarding the env plumbing, not just the free function."""
    import numpy as np

    from tmr_tpu.models.vit import Attention

    rng = np.random.default_rng(12)
    x = jnp.asarray(rng.standard_normal((1, 32, 32, 16)), jnp.float32)
    attn = Attention(num_heads=2, rel_pos_size=(32, 32))
    params = attn.init(jax.random.key(0), x)

    monkeypatch.setenv("TMR_GLOBAL_ATTN", "blockwise")
    want = jax.jit(attn.apply)(params, x)
    monkeypatch.setenv("TMR_GLOBAL_ATTN", "blockfolded")
    got = jax.jit(attn.apply)(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    monkeypatch.setenv("TMR_GLOBAL_ATTN", "bogus")
    with pytest.raises(ValueError, match="TMR_GLOBAL_ATTN"):
        jax.jit(attn.apply)(params, x)

    # an explicit pallas request whose gate refuses (always true off-TPU)
    # must WARN about the blockwise fallback — a silent fallback corrupts
    # A/B measurements by recording blockwise timings under another label
    import warnings as _warnings

    monkeypatch.setenv("TMR_GLOBAL_ATTN", "pallas")
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        got_p = jax.jit(attn.apply)(params, x)
    assert any("blockwise fallback" in str(r.message) for r in rec)
    np.testing.assert_allclose(np.asarray(got_p), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_pallas_decomposed_attention_matches_blockwise():
    """The custom VMEM-resident global-attention kernel
    (ops/pallas_attn.py, TMR_GLOBAL_ATTN=pallas) vs the exact blockwise
    oracle — forward values and custom_vjp gradients, bias on and off, on
    the Pallas interpreter (the TPU self-check gate runs the same
    comparison compiled)."""
    import numpy as np

    from tmr_tpu.models.vit import blockwise_decomposed_attention
    from tmr_tpu.ops.pallas_attn import pallas_decomposed_attention

    rng = np.random.default_rng(13)
    B, H, gh, gw, D = 1, 2, 16, 8, 8  # S=128: one 128-token block
    S = gh * gw
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    rh = jnp.asarray(rng.standard_normal((gh, gh, D)), jnp.float32) * 0.2
    rw = jnp.asarray(rng.standard_normal((gw, gw, D)), jnp.float32) * 0.2
    scale = D**-0.5

    got = jax.jit(
        lambda *a: pallas_decomposed_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw)
    want = jax.jit(
        lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    got_nb = jax.jit(
        lambda *a: pallas_decomposed_attention(
            *a, None, None, (gh, gw), scale)
    )(q, k, v)
    want_nb = jax.jit(
        lambda *a: blockwise_decomposed_attention(
            *a, None, None, (gh, gw), scale)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got_nb), np.asarray(want_nb),
                               rtol=2e-5, atol=2e-5)

    # gradients: the custom_vjp backward recomputes through blockwise, so
    # this pins the plumbing (argument order, None-bias arity)
    def loss(fn):
        return lambda a, b, c: jnp.sum(
            fn(a, b, c, rh, rw, (gh, gw), scale) ** 2)

    g_got = jax.jit(jax.grad(loss(pallas_decomposed_attention),
                             argnums=(0, 1, 2)))(q, k, v)
    g_want = jax.jit(jax.grad(loss(blockwise_decomposed_attention),
                              argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("gh,gw,D", [(16, 32, 8), (16, 32, 80)])
@pytest.mark.slow
def test_pallas_attention_multiblock_seq(gh, gw, D):
    """S=512 at block 256 forces a real multi-k-block online-softmax pass
    (running max/denominator rescaling across iterations); D=80 is vit_h's
    head dim — not lane-aligned, exercising the kernel's padded tiles."""
    import numpy as np

    from tmr_tpu.models.vit import blockwise_decomposed_attention
    from tmr_tpu.ops import pallas_attn

    rng = np.random.default_rng(14)
    B, H = 1, 1
    S = gh * gw
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    rh = jnp.asarray(rng.standard_normal((gh, gh, D)), jnp.float32) * 0.2
    rw = jnp.asarray(rng.standard_normal((gw, gw, D)), jnp.float32) * 0.2
    scale = D**-0.5

    orig = pallas_attn._pick_block
    pallas_attn._pick_block = lambda s, preferred=256: orig(s, 256)
    try:
        got = jax.jit(
            lambda *a: pallas_attn.pallas_decomposed_attention(
                *a, (gh, gw), scale)
        )(q, k, v, rh, rw)
    finally:
        pallas_attn._pick_block = orig
    want = jax.jit(
        lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pallas_global_gate_keys_on_effective_tiles(monkeypatch):
    """The gate verdict must be cached per EFFECTIVE (bq, bk) tile config:
    TMR_PALLAS_ATTN_BQ/BK change the kernel the forward impl traces, so a
    verdict reached under one tile config must never vouch for another
    (ADVICE r4 medium). effective_global_tiles is the caller-side
    resolution — env preference clamped to a power-of-two divisor of S,
    identical to _pallas_attn_fwd_impl's."""
    from tmr_tpu.ops import pallas_attn

    monkeypatch.delenv("TMR_PALLAS_ATTN_BQ", raising=False)
    monkeypatch.delenv("TMR_PALLAS_ATTN_BK", raising=False)
    assert pallas_attn.effective_global_tiles(4096) == (512, 512)
    monkeypatch.setenv("TMR_PALLAS_ATTN_BQ", "256")
    monkeypatch.setenv("TMR_PALLAS_ATTN_BK", "1024")
    assert pallas_attn.effective_global_tiles(4096) == (256, 1024)
    # distinct tile configs -> distinct lru_cache entries (fresh keys so
    # other tests' gate calls can't collide)
    info0 = pallas_attn.pallas_global_ok.cache_info()
    pallas_attn.pallas_global_ok(3, 3, 8, 512, 512)
    pallas_attn.pallas_global_ok(3, 3, 8, 256, 1024)
    pallas_attn.pallas_global_ok(3, 3, 8, 512, 512)  # hit, not a re-check
    info1 = pallas_attn.pallas_global_ok.cache_info()
    assert info1.misses - info0.misses == 2
    assert info1.hits - info0.hits == 1


@pytest.mark.parametrize("group,D", [(None, 8), ("3", 8), (None, 80)])
@pytest.mark.slow
def test_pallas_windowed_attention_matches_blockwise(group, D, monkeypatch):
    """TMR_WIN_ATTN=pallas (ops/pallas_attn.pallas_windowed_attention) vs
    the exact blockwise oracle at the REAL 14x14 window grid (196 tokens
    padded to a 256 tile with in-kernel masking), values and grads —
    grouped (TMR_PALLAS_WIN_GROUP=3 -> G=3 at bh=6) and ungrouped, plus
    vit_h's non-lane-aligned head_dim 80."""
    import numpy as np

    from tmr_tpu.models.vit import blockwise_decomposed_attention
    from tmr_tpu.ops.pallas_attn import pallas_windowed_attention

    if group is not None:
        monkeypatch.setenv("TMR_PALLAS_WIN_GROUP", group)
    rng = np.random.default_rng(15)
    B, H, gh, gw = 3, 2, 14, 14  # B = batch*windows
    S = gh * gw
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    rh = jnp.asarray(rng.standard_normal((gh, gh, D)), jnp.float32) * 0.2
    rw = jnp.asarray(rng.standard_normal((gw, gw, D)), jnp.float32) * 0.2
    scale = D**-0.5

    got = jax.jit(
        lambda *a: pallas_windowed_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw)
    want = jax.jit(
        lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss(fn):
        return lambda a, b, c: jnp.sum(
            fn(a, b, c, rh, rw, (gh, gw), scale) ** 2)

    g_got = jax.jit(jax.grad(loss(pallas_windowed_attention),
                             argnums=(0, 1, 2)))(q, k, v)
    g_want = jax.jit(jax.grad(loss(blockwise_decomposed_attention),
                              argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_win_attn_env_dispatch_pallas(monkeypatch):
    """A windowed Attention module under TMR_WIN_ATTN=pallas must equal the
    dense default (off-TPU the gate refuses -> dense fallback, which is the
    point: the dispatch chain must stay numerically safe either way)."""
    import numpy as np

    from tmr_tpu.models.vit import Attention

    rng = np.random.default_rng(16)
    x = jnp.asarray(rng.standard_normal((2, 14, 14, 16)), jnp.float32)
    attn = Attention(num_heads=2, rel_pos_size=(14, 14))
    params = attn.init(jax.random.key(0), x)

    monkeypatch.setenv("TMR_WIN_ATTN", "dense")
    want = jax.jit(attn.apply)(params, x)
    monkeypatch.setenv("TMR_WIN_ATTN", "pallas")
    got = jax.jit(attn.apply)(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_fold_rel_pos_into_qk_exact():
    """The augmented-QK trick (ops/flash_attn.py) must reproduce the biased
    scores EXACTLY in f32: q'.k'^T == scale*q.k^T + decomposed bias."""
    import numpy as np

    from tmr_tpu.ops.flash_attn import fold_rel_pos_into_qk
    from tmr_tpu.parallel.ring import dense_attention

    rng = np.random.default_rng(3)
    B, H, gh, gw, D = 2, 2, 6, 10, 16
    S = gh * gw
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    rh = jnp.asarray(rng.standard_normal((gh, gh, D)), jnp.float32) * 0.3
    rw = jnp.asarray(rng.standard_normal((gw, gw, D)), jnp.float32) * 0.3
    scale = D**-0.5

    r_q = q.reshape(B, H, gh, gw, D)
    rel_h = jnp.einsum("bnhwc,hkc->bnhwk", r_q, rh)
    rel_w = jnp.einsum("bnhwc,wkc->bnhwk", r_q, rw)
    bias = (rel_h[..., :, None] + rel_w[..., None, :]).reshape(B, H, S, S)
    want_scores = (
        jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale + bias
    )

    q_aug, k_aug = fold_rel_pos_into_qk(q, k, rh, rw, (gh, gw), scale,
                                        pad_to=128)
    assert q_aug.shape[-1] == 128 and k_aug.shape[-1] == 128
    got_scores = jnp.einsum("bhqd,bhkd->bhqk", q_aug, k_aug)
    np.testing.assert_allclose(
        np.asarray(got_scores), np.asarray(want_scores), rtol=1e-5, atol=1e-5
    )

    # end to end: softmax(q'.k') @ v == biased dense attention
    want = dense_attention(q, k, v, bias=bias, scale=scale)
    got = dense_attention(q_aug, k_aug, v, scale=1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    # no-bias variant: just scaled/padded passthrough
    q2, k2 = fold_rel_pos_into_qk(q, k, None, None, (gh, gw), scale)
    np.testing.assert_allclose(np.asarray(q2), np.asarray(q) * scale,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(k2), np.asarray(k), rtol=1e-6)


def test_flash_attention_ok_is_false_off_tpu():
    if jax.default_backend() == "tpu":  # pragma: no cover - CPU CI suite
        pytest.skip("flash path legitimately enabled on TPU")
    from tmr_tpu.ops.flash_attn import flash_attention_ok

    assert flash_attention_ok() is False  # CPU test backend -> XLA path


def test_flash_block_size_selection():
    from tmr_tpu.ops.flash_attn import _block_for, flash_supported

    assert _block_for(4096, 512) == 512
    assert _block_for(9216, 512) == 512  # 1536 bucket: 9216 = 512*18
    assert _block_for(2500, 512) is None  # 50x50 grid: no pow2 factor >=128
    assert _block_for(1024, 512) == 512
    assert _block_for(1280, 512) == 256
    assert flash_supported(4096) and not flash_supported(2500)


def test_flash_attention_ok_callable_under_trace():
    """flash_attention_ok is invoked while TRACING the model; it must not
    leak tracers or poison its cache when first called inside jit."""
    if jax.default_backend() == "tpu":  # pragma: no cover - CPU CI suite
        pytest.skip("flash path legitimately enabled on TPU")
    from tmr_tpu.ops.flash_attn import flash_attention_ok

    flash_attention_ok.cache_clear()
    seen = []

    @jax.jit
    def traced(x):
        seen.append(flash_attention_ok())  # trace-time call
        return x + 1

    traced(jnp.zeros((2,)))
    assert seen == [False]  # CPU backend -> disabled, but no exception/tracer
    flash_attention_ok.cache_clear()


@pytest.mark.slow
def test_windowed_attention_folded_matches_dense(monkeypatch):
    """TMR_WIN_ATTN=folded routes the windowed blocks' bias through the QK
    contraction (ops/flash_attn.fold_rel_pos_into_qk); in f32 the algebra is
    exact, so the Attention module must agree with its default dense path."""
    from tmr_tpu.models.vit import Attention

    rng = np.random.default_rng(11)
    b, win, dim, heads = 3, 14, 32, 4
    x = jnp.asarray(rng.standard_normal((b, win, win, dim)), jnp.float32)
    attn = Attention(num_heads=heads, rel_pos_size=(win, win))
    params = attn.init(jax.random.key(0), x)
    # zero-init rel-pos tables make the bias trivial; randomize them
    params = jax.tree.map(
        lambda p: jnp.asarray(
            np.random.default_rng(3).standard_normal(p.shape) * 0.1, p.dtype
        ),
        params,
    )

    monkeypatch.delenv("TMR_WIN_ATTN", raising=False)
    want = attn.apply(params, x)
    monkeypatch.setenv("TMR_WIN_ATTN", "folded")
    got = attn.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


@pytest.mark.slow
def test_flash_windowed_padding_and_segments(monkeypatch):
    """flash_windowed_attention pads 196-token windows to 256 and masks the
    pad via a second segment. The Pallas kernel itself needs a TPU, but its
    module ships mha_reference with identical (q, k, v, ab, segment_ids)
    semantics — swapping it in validates the fold/pad/segment construction
    end to end on CPU."""
    from jax.experimental.pallas.ops.tpu import flash_attention as fa_mod

    from tmr_tpu.ops import flash_attn
    from tmr_tpu.models.vit import blockwise_decomposed_attention

    def stub(q, k, v, ab=None, segment_ids=None, causal=False, sm_scale=1.0,
             block_sizes=None, debug=False):
        return fa_mod.mha_reference(
            q, k, v, ab, segment_ids, causal=causal, sm_scale=sm_scale
        )

    monkeypatch.setattr(fa_mod, "flash_attention", stub)

    rng = np.random.default_rng(7)
    b, hds, gh, gw, d = 3, 2, 14, 14, 16
    s = gh * gw
    mk = lambda: jnp.asarray(rng.standard_normal((b, hds, s, d)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    rh = jnp.asarray(rng.standard_normal((gh, gh, d)) * 0.2, jnp.float32)
    rw = jnp.asarray(rng.standard_normal((gw, gw, d)) * 0.2, jnp.float32)
    scale = d**-0.5

    got = flash_attn.flash_windowed_attention(q, k, v, rh, rw, (gh, gw), scale)
    want = blockwise_decomposed_attention(q, k, v, rh, rw, (gh, gw), scale)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
    assert got.shape == (b, hds, s, d)


@pytest.mark.slow
def test_windowed_attention_folded_grads_match_dense(monkeypatch):
    """Training differentiates through whatever attention formulation is
    active; the folded QK path must carry the same gradients as dense."""
    from tmr_tpu.models.vit import Attention

    rng = np.random.default_rng(13)
    b, win, dim, heads = 2, 7, 16, 2
    x = jnp.asarray(rng.standard_normal((b, win, win, dim)), jnp.float32)
    attn = Attention(num_heads=heads, rel_pos_size=(win, win))
    params = attn.init(jax.random.key(0), x)
    params = jax.tree.map(
        lambda p: jnp.asarray(
            np.random.default_rng(5).standard_normal(p.shape) * 0.1, p.dtype
        ),
        params,
    )

    def loss(p, x):
        return jnp.sum(attn.apply(p, x) ** 2)

    monkeypatch.delenv("TMR_WIN_ATTN", raising=False)
    want_g = jax.grad(loss)(params, x)
    monkeypatch.setenv("TMR_WIN_ATTN", "folded")
    got_g = jax.grad(loss)(params, x)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        got_g, want_g,
    )


@pytest.mark.slow
def test_flash_self_check_harness_including_grads(monkeypatch):
    """_self_check gates the flash paths on TPU (forward AND backward since
    the train step differentiates through them). Off-TPU it must refuse;
    with the backend gate and kernel stubbed it must pass end to end,
    proving the harness itself (jit compare + grad compare) is sound."""
    from jax.experimental.pallas.ops.tpu import flash_attention as fa_mod

    from tmr_tpu.ops import flash_attn

    if jax.default_backend() == "tpu":
        pytest.skip("gate legitimately runs the real kernel on TPU")
    monkeypatch.delenv("TMR_NO_FLASH_ATTN", raising=False)

    # real backend (cpu): the gate refuses outright
    assert flash_attn._self_check(
        flash_attn.flash_windowed_attention, 1, 1, 7, 7, 8
    ) is False

    def stub(q, k, v, ab=None, segment_ids=None, causal=False, sm_scale=1.0,
             block_sizes=None, debug=False):
        return fa_mod.mha_reference(
            q, k, v, ab, segment_ids, causal=causal, sm_scale=sm_scale
        )

    monkeypatch.setattr(fa_mod, "flash_attention", stub)
    monkeypatch.setattr(flash_attn.jax, "default_backend", lambda: "tpu")
    assert flash_attn._self_check(
        flash_attn.flash_windowed_attention, 1, 1, 7, 7, 8
    ) is True
    # a broken kernel must be caught, not crash the trace
    monkeypatch.setattr(
        fa_mod, "flash_attention",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("mosaic")),
    )
    assert flash_attn._self_check(
        flash_attn.flash_windowed_attention, 1, 1, 7, 7, 8
    ) is False


def test_flash_self_check_rejects_nan(monkeypatch):
    """A Mosaic miscompile classically surfaces as NaN output; the gate must
    reject it (comparisons are phrased so NaN fails, never passes)."""
    from jax.experimental.pallas.ops.tpu import flash_attention as fa_mod

    from tmr_tpu.ops import flash_attn

    if jax.default_backend() == "tpu":
        pytest.skip("gate legitimately runs the real kernel on TPU")
    monkeypatch.delenv("TMR_NO_FLASH_ATTN", raising=False)
    monkeypatch.setattr(flash_attn.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        fa_mod, "flash_attention",
        lambda q, *a, **k: jnp.full_like(q, jnp.nan),
    )
    assert flash_attn._self_check(
        flash_attn.flash_windowed_attention, 1, 1, 7, 7, 8
    ) is False


def test_flash_supported_production_lengths():
    """Block constraints hold at both production buckets (4096 = 64x64,
    9216 = 96x96 has the 2^10 factor) and fail at the window length."""
    from tmr_tpu.ops.flash_attn import flash_supported

    assert flash_supported(4096)
    assert flash_supported(9216)
    assert not flash_supported(196)  # windows go through the padded path


@pytest.mark.slow
def test_ring_at_1536_bucket_scale():
    """The 1536 small-object bucket is the reference's longest sequence
    (96x96 = 9216 tokens, sam.py:72-76 pos-embed re-interpolation); ring
    attention must hold exactly there — per-device KV slabs of 9216/8
    tokens, online-softmax accumulation over 8 ppermute hops. Small head
    count keeps the dense oracle affordable on CPU."""
    b, h, s, d = 1, 2, 96 * 96, 16
    rng = np.random.default_rng(42)
    mk = lambda: jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    want = dense_attention(q, k, v)

    mesh = seq_mesh(8)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq"),
        mesh=mesh, in_specs=(SEQ_SPEC,) * 3,
        out_specs=SEQ_SPEC, check_vma=False,
    )
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )


@pytest.mark.slow
def test_win_scores_dtype_bf16_matches_dense(monkeypatch):
    """TMR_WIN_SCORES_DTYPE=bf16 (experiment knob: per-window folded score
    tensors materialize in bf16) must stay within bf16 tolerance of the
    dense windowed oracle on the bf16 deployment dtype, change the
    rounding vs f32 scores (liveness), and be inert for f32 models."""
    from tmr_tpu.models.vit import Attention

    rng = np.random.default_rng(17)
    # drive the Attention module directly at the window grid (14x14
    # tokens — the windowed folded branch)
    xw = jnp.asarray(rng.standard_normal((4, 14, 14, 32)), jnp.bfloat16)
    attn16 = Attention(num_heads=2, rel_pos_size=(14, 14),
                       dtype=jnp.bfloat16)
    params = attn16.init(jax.random.key(0), xw)

    monkeypatch.setenv("TMR_WIN_ATTN", "dense")
    monkeypatch.delenv("TMR_WIN_SCORES_DTYPE", raising=False)
    ref = np.asarray(jax.jit(attn16.apply)(params, xw), np.float32)

    monkeypatch.setenv("TMR_WIN_ATTN", "folded")
    f32s = np.asarray(jax.jit(attn16.apply)(params, xw), np.float32)
    monkeypatch.setenv("TMR_WIN_SCORES_DTYPE", "bf16")
    b16s = np.asarray(jax.jit(attn16.apply)(params, xw), np.float32)

    scale = np.abs(ref).max() + 1e-6
    assert np.abs(f32s - ref).max() / scale < 0.05
    assert np.abs(b16s - ref).max() / scale < 0.05
    # liveness at the trace level: the lowered programs must differ (the
    # bf16-rounded scores can coincide with f32 scores after the final
    # bf16 output cast at this tiny scale, so output inequality is not a
    # reliable signal here — unlike the global-path test)
    monkeypatch.delenv("TMR_WIN_SCORES_DTYPE")
    h_f32 = jax.jit(attn16.apply).lower(params, xw).as_text()
    monkeypatch.setenv("TMR_WIN_SCORES_DTYPE", "bf16")
    h_b16 = jax.jit(attn16.apply).lower(params, xw).as_text()
    assert h_f32 != h_b16

    # f32 model: knob inert (bit-equal to the unset run)
    attn32 = Attention(num_heads=2, rel_pos_size=(14, 14))
    xw32 = jnp.asarray(rng.standard_normal((4, 14, 14, 32)), jnp.float32)
    p32 = attn32.init(jax.random.key(0), xw32)
    with_knob = np.asarray(jax.jit(attn32.apply)(p32, xw32), np.float32)
    monkeypatch.delenv("TMR_WIN_SCORES_DTYPE")
    without = np.asarray(jax.jit(attn32.apply)(p32, xw32), np.float32)
    np.testing.assert_array_equal(with_knob, without)

    monkeypatch.setenv("TMR_WIN_SCORES_DTYPE", "int8")
    with pytest.raises(ValueError, match="TMR_WIN_SCORES_DTYPE"):
        jax.jit(attn16.apply)(params, xw)
