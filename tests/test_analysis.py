"""The static-analysis & program-audit subsystem (tmr_tpu/analysis).

Three layers of coverage:

1. **fixture proof per rule** — every AST rule and every program-tier
   predicate is proven to FIRE on a minimal bad fixture (a lint that
   can't fail can't protect anything) and to stay silent on the fixed
   version;
2. **the committed tree is clean** — the full AST tier over the real
   repo with the committed baseline yields zero unbaselined findings,
   and scripts/analyze.py emits a validated ``analysis_report/v1``
   saying so (rc 0);
3. **the program tier holds across gate states** — all 8
   TMR_DECODER_IMPL x TMR_QUANT x TMR_DECODE_TAIL combinations pass the
   jaxpr invariants on the reduced CPU geometry in tier-1 (slow-marked:
   the production sam_vit_b sweep at the 128^2 decoder grid).

Everything here runs under the conftest env (JAX_PLATFORMS=cpu, 8
forced host devices) — the transfer-guard pins are per-platform
precisely so that this works.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tmr_tpu.analysis import (
    Baseline,
    Finding,
    build_report,
    default_baseline_path,
    run_ast_passes,
)
from tmr_tpu.diagnostics import validate_analysis_report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: minimal registry/diagnostics stand-ins every mini-repo carries so the
#: hardwired-path passes (knob-parity, report-parity) have their anchors
_MINI_CONFIG = '''
ENV_KNOBS = {
    "TMR_DOCUMENTED": "a documented knob",
}
'''
_MINI_DIAG = '''
FOO_SCHEMA = "foo_report/v1"


def validate_foo_report(doc):
    return []
'''


def _mini_repo(tmp_path, files):
    """Materialize a throwaway repo layout: config/diagnostics defaults
    plus the caller's files ({relpath: source})."""
    defaults = {
        "tmr_tpu/__init__.py": "",
        "tmr_tpu/config.py": _MINI_CONFIG,
        "tmr_tpu/diagnostics.py": _MINI_DIAG,
    }
    for rel, src in {**defaults, **files}.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _findings(root, rule_id, baseline=None):
    return run_ast_passes(root=root, rules=[rule_id], baseline=baseline)


# ===================================================================== AST
def test_jit_hygiene_fires_on_each_side_effect(tmp_path):
    root = _mini_repo(tmp_path, {"tmr_tpu/bad.py": '''
        import os
        import time

        import jax
        import numpy as np

        _CACHE = {}
        _COUNT = 0


        @jax.jit
        def bad(x):
            global _COUNT
            t = time.time()
            r = np.random.default_rng(0).standard_normal(3)
            mode = os.environ.get("TMR_SOMETHING", "off")
            print("tracing", mode)
            _CACHE["last"] = t
            _COUNT = 1
            return x + r.sum()


        def clean_host_helper():
            # NOT jit-compiled: the same constructs are legal here
            print("fine", file=None) if False else None
            return os.environ.get("TMR_SOMETHING")
    '''})
    msgs = [f.message for f in _findings(root, "jit-hygiene")]
    assert any("time.time" in m for m in msgs)
    assert any("random" in m for m in msgs)
    assert any("environment" in m for m in msgs)
    assert any("print" in m for m in msgs)
    assert any("_CACHE" in m for m in msgs)
    assert any("_COUNT" in m for m in msgs)
    assert all("bad" in m for m in msgs), "host helper must not be flagged"


def test_jit_hygiene_covers_partial_alias_and_posthoc_wrap(tmp_path):
    root = _mini_repo(tmp_path, {"tmr_tpu/alias.py": '''
        import functools
        import time

        import jax

        jit = functools.partial(jax.jit, donate_argnums=(0,))


        @jit
        def aliased(x):
            return x + time.time()


        def wrapped_later(x):
            return x * time.perf_counter()


        run = jax.jit(wrapped_later)
    '''})
    found = _findings(root, "jit-hygiene")
    names = {f.message.split("'")[1] for f in found}
    assert names == {"aliased", "wrapped_later"}


def test_lock_discipline_fires_and_lock_silences(tmp_path):
    bad = '''
        import threading


        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self.counts = {}
                self._thread = threading.Thread(target=self._loop)

            def _loop(self):
                self.counts["loop"] = 1  # unlocked write, thread side

            def snapshot(self):
                return dict(self.counts)  # read, caller side
    '''
    root = _mini_repo(tmp_path, {"tmr_tpu/serve/pool.py": bad})
    found = _findings(root, "lock-discipline")
    assert len(found) == 1 and "counts" in found[0].message

    fixed = bad.replace(
        'self.counts["loop"] = 1  # unlocked write, thread side',
        'with self._lock:\n'
        '                    self.counts["loop"] = 1',
    )
    root2 = _mini_repo(tmp_path / "fixed", {"tmr_tpu/serve/pool.py": fixed})
    assert _findings(root2, "lock-discipline") == []


def test_lock_discipline_atomics_whitelist_and_module_globals(tmp_path):
    src = '''
        import threading

        _LOG = []


        def worker():
            threading.Thread(target=record).start()


        def record():
            _LOG.append(1)
    '''
    root = _mini_repo(tmp_path, {"tmr_tpu/utils/faults.py": src})
    found = _findings(root, "lock-discipline")
    assert len(found) == 1 and "_LOG" in found[0].message

    baseline = Baseline({
        "suppressions": [],
        "lock_atomics": [{"file": "tmr_tpu/utils/faults.py",
                          "attr": "_LOG",
                          "reason": "GIL-atomic append, test fixture"}],
    })
    assert _findings(root, "lock-discipline", baseline=baseline) == []


def test_knob_parity_fires_both_directions(tmp_path):
    root = _mini_repo(tmp_path, {
        "tmr_tpu/config.py": '''
            ENV_KNOBS = {
                "TMR_DOCUMENTED": "consumed below",
                "TMR_STALE": "nothing consumes this",
            }
        ''',
        "tmr_tpu/mod.py": '''
            import os


            def f():
                a = os.environ.get("TMR_DOCUMENTED")
                b = os.environ.get("TMR_UNDOCUMENTED")
                return a, b
        ''',
    })
    msgs = [f.message for f in _findings(root, "knob-parity")]
    assert any("TMR_UNDOCUMENTED" in m and "missing" in m for m in msgs)
    assert any("TMR_STALE" in m and "stale" in m.lower() or
               "no code" in m for m in msgs)


def test_knob_import_time_fires_direct_and_via_helper(tmp_path):
    root = _mini_repo(tmp_path, {"tmr_tpu/eager.py": '''
        import os


        def _env_flag(name, default=False):
            return os.environ.get(name, "") not in ("", "0")


        DIRECT = os.environ.get("TMR_DIRECT", "0")
        VIA_HELPER = _env_flag("TMR_HELPER")


        def lazy():
            return os.environ.get("TMR_LAZY")  # call-time: legal
    '''})
    found = _findings(root, "knob-import-time")
    assert len(found) == 2
    assert any("TMR_DIRECT" in f.message for f in found)
    assert any("TMR_HELPER" in f.message for f in found)


def test_report_parity_fires_on_missing_validators(tmp_path):
    root = _mini_repo(tmp_path, {
        "tmr_tpu/diagnostics.py": '''
            FOO_SCHEMA = "foo_report/v1"


            def validate_foo_report(doc):
                return []


            BARE_SCHEMA = "bare_report/v1"
        ''',
        "scripts/emit.py": '''
            from tmr_tpu.diagnostics import FOO_REPORT_SCHEMA

            print({"schema": FOO_REPORT_SCHEMA})
        ''',
    })
    found = _findings(root, "report-parity")
    assert any("bare_report" in f.message for f in found)
    assert any("validate_foo_report" in f.message
               and f.file == "scripts/emit.py" for f in found)


def test_stdout_hygiene_fires_on_bare_print_only(tmp_path):
    root = _mini_repo(tmp_path, {"tmr_tpu/noisy.py": '''
        import sys


        def f():
            print("bare")
            print("to stderr", file=sys.stderr)
    '''})
    found = _findings(root, "stdout-hygiene")
    assert len(found) == 1
    assert 'print("bare")' in (tmp_path / "tmr_tpu/noisy.py"
                               ).read_text().splitlines()[found[0].line - 1]


def test_baseline_suppression_and_validation(tmp_path):
    f = Finding("stdout-hygiene", "tmr_tpu/noisy.py", 5, "bare print() x")
    b = Baseline({"suppressions": [{
        "rule": "stdout-hygiene", "file": "tmr_tpu/noisy.py",
        "match": "bare print", "reason": "fixture",
    }]})
    assert b.allows(f)
    assert not b.allows(Finding("stdout-hygiene", "tmr_tpu/other.py", 5,
                                "bare print() x"))
    assert not b.allows(Finding("jit-hygiene", "tmr_tpu/noisy.py", 5,
                                "bare print() x"))
    # a suppression without a reason is rejected at load
    with pytest.raises(ValueError, match="reason"):
        Baseline({"suppressions": [{"rule": "r", "file": "f"}]})
    # round-trip
    path = tmp_path / "b.json"
    b.save(str(path))
    b2 = Baseline.load(str(path))
    assert b2.allows(f)


def test_report_builder_and_validator(tmp_path):
    b = Baseline()
    f = Finding("stdout-hygiene", "tmr_tpu/noisy.py", 5, "bare print()")
    doc = build_report([f], b, program_audit=None, root="/x")
    assert validate_analysis_report(doc) == []
    assert doc["checks"]["clean"] is False
    assert doc["counts_by_rule"] == {"stdout-hygiene": 1}
    # suppressed -> clean
    b2 = Baseline({"suppressions": [{
        "rule": "stdout-hygiene", "file": "tmr_tpu/noisy.py",
        "reason": "fixture",
    }]})
    doc2 = build_report([f], b2, program_audit=None, root="/x")
    assert doc2["checks"]["clean"] is True
    assert doc2["baselined_count"] == 1
    # the error record is contractually valid; garbage is not
    assert validate_analysis_report(
        {"schema": "analysis_report/v1", "error": "boom"}
    ) == []
    assert validate_analysis_report({"schema": "nope"})


# ============================================================ program tier
def test_audit_jaxpr_s2_and_transfer_predicates():
    import jax
    import jax.numpy as jnp

    from tmr_tpu.analysis.program_audit import audit_jaxpr

    x = jax.ShapeDtypeStruct((64, 64), jnp.float32)

    def dense(a):  # materializes a (64*64, 64*64)-shaped outer product
        f = a.reshape(-1)
        return (f[:, None] * f[None, :]).sum()

    S2 = (64 * 64) ** 2  # the bound a (4096,)-token attention would pin
    j = jax.make_jaxpr(dense)(x)
    rec = audit_jaxpr(j, "fixture", s2_bound=S2)
    assert not rec["ok"] and any("S^2" in p for p in rec["problems"])
    # streaming form stays under the bound
    j2 = jax.make_jaxpr(lambda a: (a * a).sum())(x)
    assert audit_jaxpr(j2, "fixture", s2_bound=S2)["ok"]

    def hops(a):
        b = jax.device_put(a)
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(a.shape, a.dtype), b
        )

    j3 = jax.make_jaxpr(hops)(x)
    rec3 = audit_jaxpr(j3, "fixture", transfer_pin=0)
    assert not rec3["ok"]
    assert any("callback" in p for p in rec3["problems"])
    assert any("device_put" in p for p in rec3["problems"])
    assert audit_jaxpr(j3, "fixture", transfer_pin=1)["problems"] == [
        p for p in audit_jaxpr(j3, "fixture", transfer_pin=1)["problems"]
        if "device_put" not in p
    ]


def test_audit_jaxpr_sees_inside_cond_branches():
    """cond/switch store their sub-jaxprs in a TUPLE param
    ('branches') — the walker must descend into it, or every invariant
    is blind inside conditionals (regression pin: a pure_callback
    hidden in a lax.cond branch must count)."""
    import jax
    import jax.numpy as jnp

    from tmr_tpu.analysis.program_audit import audit_jaxpr, jaxpr_stats

    def f(a):
        return jax.lax.cond(
            a.sum() > 0,
            lambda v: jax.pure_callback(
                lambda x: x, jax.ShapeDtypeStruct(v.shape, v.dtype), v
            ),
            lambda v: v * 2,
            a,
        )

    j = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((4,), jnp.float32))
    assert jaxpr_stats(j)["callbacks"] == 1
    rec = audit_jaxpr(j, "fixture")
    assert not rec["ok"] and any("callback" in p for p in rec["problems"])


def test_audit_jaxpr_f64_and_quant_widen_predicates():
    import jax
    import jax.numpy as jnp

    from tmr_tpu.analysis.program_audit import audit_jaxpr

    with jax.experimental.enable_x64(True):
        j = jax.make_jaxpr(
            lambda a: a.astype(jnp.float64) * 2.0
        )(jax.ShapeDtypeStruct((8,), jnp.float32))
    rec = audit_jaxpr(j, "fixture")
    assert not rec["ok"] and any("float64" in p for p in rec["problems"])
    recq = audit_jaxpr(j, "fixture", quant=True)
    assert any("quantized path" in p for p in recq["problems"])
    # f32 program: both rules silent
    j2 = jax.make_jaxpr(lambda a: a.astype(jnp.bfloat16))(
        jax.ShapeDtypeStruct((8,), jnp.float32)
    )
    assert audit_jaxpr(j2, "fixture", quant=True)["ok"]


def test_attention_impls_hold_no_s2_at_production_grid():
    from tmr_tpu.analysis.program_audit import (
        NO_S2_ATTN_IMPLS,
        audit_attention_impls,
    )

    rec = audit_attention_impls(grids=((64, 64),))
    assert rec["ok"], rec
    audited = {
        k.split(":")[1].split("@")[0]
        for k, v in rec["impls"].items() if "skipped" not in v
    }
    # every contractually-streaming impl actually traced and was audited
    assert set(NO_S2_ATTN_IMPLS) <= audited
    # densefolded is recorded but exempt (dense by design)
    dense = rec["impls"]["attn:densefolded@64x64"]
    assert dense["ok"] and dense["s2_bound"] is None
    assert dense["max_intermediate_elems"] >= 64**4


def test_program_audit_default_state_production_programs():
    """The bucketed production programs (sam_vit_b reduced CPU
    geometry) pass every invariant under the ambient env, and the
    transfer pins hold under the forced-8-device CPU conftest — which
    is also where the mesh-sharded serve variant (match_heads_dp, the
    shard_map dp program) is traceable and audited."""
    from tmr_tpu.analysis.program_audit import audit_production_programs

    rec = audit_production_programs(image_size=64, include_attention=False)
    assert rec["ok"], rec["problems"]
    names = {r["name"] for r in rec["states"][0]["programs"]}
    assert names == {"match_heads", "match_heads_dp", "backbone",
                     "heads_only", "nms_topk"}
    assert rec["platform"] == "cpu"


def test_program_audit_all_eight_gate_states_reduced_geometry():
    """TMR_DECODER_IMPL={xla,fused} x TMR_QUANT={off,int8} x
    TMR_DECODE_TAIL={host,device}: every combination's traced program
    passes the jaxpr invariants on the reduced CPU geometry (the tiny
    backbone keeps this in tier-1; the slow test runs the production
    sam_vit_b sweep)."""
    from tmr_tpu.analysis.program_audit import (
        ALL_GATE_STATES,
        audit_production_programs,
    )

    rec = audit_production_programs(
        image_size=64, emb_dim=16, backbone="resnet50_layer1",
        gate_states=ALL_GATE_STATES, include_attention=False,
        programs=("match_heads",),
        transfer_pins={"match_heads": 0},  # resnet stages no constants
    )
    assert rec["ok"], rec["problems"]
    assert len(rec["states"]) == 8
    seen = {tuple(sorted(s["gate_state"].items())) for s in rec["states"]}
    assert len(seen) == 8
    for state in rec["states"]:
        assert state["ok"], state


@pytest.mark.slow
def test_program_audit_production_geometry_full_sweep():
    """The production 128^2 decoder-grid geometry (image 1024,
    sam_vit_b, 2000 detection slots): all 8 gate states pass, plus the
    full four-program default-state audit and both attention grids."""
    from tmr_tpu.analysis.program_audit import (
        ALL_GATE_STATES,
        audit_production_programs,
    )

    rec = audit_production_programs(
        image_size=1024, max_detections=2000,
        gate_states=ALL_GATE_STATES,
        attention_grids=((64, 64), (96, 96)),
    )
    assert rec["ok"], rec["problems"]
    assert len(rec["states"]) == 8


# ================================================================== repo
def test_committed_tree_has_zero_unbaselined_findings():
    """THE acceptance pin: the full AST tier over the real tree with the
    committed baseline is clean (jit-hygiene and lock-discipline run
    here; the knob/report/stdout rules also ride their original
    test_small_utils wrappers)."""
    baseline = Baseline.load(default_baseline_path(REPO))
    findings = [
        f for f in run_ast_passes(root=REPO, baseline=baseline)
        if not baseline.allows(f)
    ]
    assert findings == [], "\n".join(str(f) for f in findings)


def test_run_analysis_library_entry():
    """The one-call library entry returns a validated clean report on
    the committed tree (AST tier; the program tier rides its own
    tests)."""
    from tmr_tpu.analysis import run_analysis

    doc = run_analysis(root=REPO, with_program_audit=False)
    assert doc["checks"]["ast_clean"] is True
    assert validate_analysis_report(doc) == []


def test_analyze_script_emits_validated_report(tmp_path):
    """scripts/analyze.py (AST tier) under the conftest CPU env: rc 0,
    ONE validated analysis_report/v1 JSON line on stdout, --out file
    matches, checks.clean true."""
    out = tmp_path / "analysis.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         "--no-program-audit", "--json", "--out", str(out)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    doc = json.loads(lines[0])
    assert validate_analysis_report(doc) == []
    assert doc["checks"]["clean"] is True
    assert doc["schema"] == "analysis_report/v1"
    assert set(doc["rules"]) >= {
        "jit-hygiene", "lock-discipline", "knob-parity",
        "knob-import-time", "report-parity", "stdout-hygiene",
    }
    assert json.loads(out.read_text())["checks"]["clean"] is True


def test_analyze_baseline_update_emits_baseline_tagged_line(tmp_path):
    """--baseline-update's stdout line is tagged analysis_baseline/v1,
    NOT analysis_report/v1 — a report-tagged line must always pass
    validate_analysis_report, and this one structurally can't."""
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"schema": "analysis_baseline/v1",
                              "suppressions": [], "lock_atomics": [],
                              "transfer_guard": {}}))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze.py"),
         "--no-program-audit", "--baseline", str(bl),
         "--baseline-update"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["schema"] == "analysis_baseline/v1"
    assert doc["baseline_updated"] == str(bl)


def test_analyze_script_nonzero_on_findings(tmp_path):
    """A dirty tree (bare print fixture) makes analyze.py exit 1 and
    carry the finding in the report — the CI gate is the exit code."""
    root = _mini_repo(tmp_path, {"tmr_tpu/noisy.py": '''
        def f():
            print("bare")
    '''})
    # the script analyzes ITS OWN repo root; drive the library instead
    # (subprocess-level rc is covered above) and pin the contract the
    # script builds on: findings -> clean False
    baseline = Baseline()
    findings = run_ast_passes(root=root, baseline=baseline)
    doc = build_report(findings, baseline, root=root)
    assert doc["checks"]["clean"] is False
    assert validate_analysis_report(doc) == []
