"""Torch oracle for the SAM decoding stack (prompt encoder / two-way
transformer / mask decoder), used to golden-test the Flax rebuild in
tmr_tpu/models/sam_decoder.py and the weight converter.

Independent compact implementation of the public SAM decoder semantics
(reference: utils/segment_anything/modeling/*), with state_dict key names
matching the SAM checkpoint layout so utils/convert.convert_sam_refiner can
consume `oracle.state_dict()` directly. Test-only; torch never enters the
framework proper.
"""

from __future__ import annotations

import math

import torch
from torch import nn
from torch.nn import functional as F


class LayerNorm2dT(nn.Module):
    def __init__(self, c, eps=1e-6):
        super().__init__()
        self.weight = nn.Parameter(torch.ones(c))
        self.bias = nn.Parameter(torch.zeros(c))
        self.eps = eps

    def forward(self, x):  # (B, C, H, W)
        u = x.mean(1, keepdim=True)
        s = ((x - u) ** 2).mean(1, keepdim=True)
        x = (x - u) / torch.sqrt(s + self.eps)
        return x * self.weight[:, None, None] + self.bias[:, None, None]


class PositionEmbeddingRandomT(nn.Module):
    def __init__(self, num_pos_feats=128):
        super().__init__()
        self.register_buffer(
            "positional_encoding_gaussian_matrix",
            torch.randn(2, num_pos_feats),
        )

    def encode(self, coords01):  # (..., 2) in [0, 1]
        c = 2 * coords01 - 1
        c = c @ self.positional_encoding_gaussian_matrix
        c = 2 * math.pi * c
        return torch.cat([torch.sin(c), torch.cos(c)], dim=-1)

    def grid(self, h, w):
        ys = (torch.arange(h).float() + 0.5) / h
        xs = (torch.arange(w).float() + 0.5) / w
        gy, gx = torch.meshgrid(ys, xs, indexing="ij")
        return self.encode(torch.stack([gx, gy], dim=-1))  # (h, w, C)


class PromptEncoderT(nn.Module):
    """Box-prompt path of the SAM prompt encoder + mask downscaling."""

    def __init__(self, embed_dim=256, mask_in_chans=16):
        super().__init__()
        self.embed_dim = embed_dim
        self.pe_layer = PositionEmbeddingRandomT(embed_dim // 2)
        self.point_embeddings = nn.ModuleList(
            [nn.Embedding(1, embed_dim) for _ in range(4)]
        )
        self.not_a_point_embed = nn.Embedding(1, embed_dim)
        self.no_mask_embed = nn.Embedding(1, embed_dim)
        self.mask_downscaling = nn.Sequential(
            nn.Conv2d(1, mask_in_chans // 4, 2, stride=2),
            LayerNorm2dT(mask_in_chans // 4),
            nn.GELU(),
            nn.Conv2d(mask_in_chans // 4, mask_in_chans, 2, stride=2),
            LayerNorm2dT(mask_in_chans),
            nn.GELU(),
            nn.Conv2d(mask_in_chans, embed_dim, 1),
        )

    def embed_boxes(self, boxes, image_size):  # (N, 4) px
        h, w = image_size
        corners = (boxes + 0.5).reshape(-1, 2, 2)
        corners = corners / torch.tensor([w, h], dtype=torch.float32)
        emb = self.pe_layer.encode(corners)
        emb[:, 0, :] += self.point_embeddings[2].weight[0]
        emb[:, 1, :] += self.point_embeddings[3].weight[0]
        return emb

    def dense_pe(self, emb_size):
        return self.pe_layer.grid(*emb_size)  # (h, w, C)

    def no_mask_dense(self, n, emb_size):
        h, w = emb_size
        return self.no_mask_embed.weight.reshape(1, 1, 1, -1).expand(
            n, h, w, self.embed_dim
        )


class AttentionT(nn.Module):
    def __init__(self, embedding_dim, num_heads, downsample_rate=1):
        super().__init__()
        self.internal_dim = embedding_dim // downsample_rate
        self.num_heads = num_heads
        self.q_proj = nn.Linear(embedding_dim, self.internal_dim)
        self.k_proj = nn.Linear(embedding_dim, self.internal_dim)
        self.v_proj = nn.Linear(embedding_dim, self.internal_dim)
        self.out_proj = nn.Linear(self.internal_dim, embedding_dim)

    def forward(self, q, k, v):
        q, k, v = self.q_proj(q), self.k_proj(k), self.v_proj(v)

        def split(x):
            b, n, c = x.shape
            return x.reshape(
                b, n, self.num_heads, c // self.num_heads
            ).transpose(1, 2)

        q, k, v = split(q), split(k), split(v)
        attn = q @ k.transpose(2, 3) / math.sqrt(q.shape[-1])
        attn = torch.softmax(attn, dim=-1)
        out = attn @ v
        b, h, n, c = out.shape
        return self.out_proj(out.transpose(1, 2).reshape(b, n, h * c))


class MLPBlockT(nn.Module):
    def __init__(self, dim, mlp_dim):
        super().__init__()
        self.lin1 = nn.Linear(dim, mlp_dim)
        self.lin2 = nn.Linear(mlp_dim, dim)

    def forward(self, x):
        return self.lin2(F.relu(self.lin1(x)))


class TwoWayAttentionBlockT(nn.Module):
    def __init__(self, dim, num_heads, mlp_dim, downsample_rate=2,
                 skip_first_layer_pe=False):
        super().__init__()
        self.self_attn = AttentionT(dim, num_heads)
        self.norm1 = nn.LayerNorm(dim)
        self.cross_attn_token_to_image = AttentionT(
            dim, num_heads, downsample_rate
        )
        self.norm2 = nn.LayerNorm(dim)
        self.mlp = MLPBlockT(dim, mlp_dim)
        self.norm3 = nn.LayerNorm(dim)
        self.norm4 = nn.LayerNorm(dim)
        self.cross_attn_image_to_token = AttentionT(
            dim, num_heads, downsample_rate
        )
        self.skip_first_layer_pe = skip_first_layer_pe

    def forward(self, queries, keys, query_pe, key_pe):
        if self.skip_first_layer_pe:
            queries = self.self_attn(queries, queries, queries)
        else:
            q = queries + query_pe
            queries = queries + self.self_attn(q, q, queries)
        queries = self.norm1(queries)

        q = queries + query_pe
        k = keys + key_pe
        queries = queries + self.cross_attn_token_to_image(q, k, keys)
        queries = self.norm2(queries)

        queries = self.norm3(queries + self.mlp(queries))

        q = queries + query_pe
        k = keys + key_pe
        keys = keys + self.cross_attn_image_to_token(k, q, queries)
        keys = self.norm4(keys)
        return queries, keys


class TwoWayTransformerT(nn.Module):
    def __init__(self, depth, dim, num_heads, mlp_dim):
        super().__init__()
        self.layers = nn.ModuleList(
            [
                TwoWayAttentionBlockT(
                    dim, num_heads, mlp_dim, skip_first_layer_pe=(i == 0)
                )
                for i in range(depth)
            ]
        )
        self.final_attn_token_to_image = AttentionT(dim, num_heads, 2)
        self.norm_final_attn = nn.LayerNorm(dim)

    def forward(self, image_embedding, image_pe, point_embedding):
        # image_embedding (B, C, h, w) NCHW like the reference
        b, c, h, w = image_embedding.shape
        keys = image_embedding.flatten(2).permute(0, 2, 1)
        key_pe = image_pe.flatten(2).permute(0, 2, 1)
        queries = point_embedding
        for layer in self.layers:
            queries, keys = layer(queries, keys, point_embedding, key_pe)
        q = queries + point_embedding
        k = keys + key_pe
        queries = queries + self.final_attn_token_to_image(q, k, keys)
        return self.norm_final_attn(queries), keys


class MLPT(nn.Module):
    def __init__(self, in_dim, hidden, out_dim, num_layers):
        super().__init__()
        dims = [in_dim] + [hidden] * (num_layers - 1)
        self.layers = nn.ModuleList(
            nn.Linear(a, b) for a, b in zip(dims, dims[1:] + [out_dim])
        )

    def forward(self, x):
        for i, layer in enumerate(self.layers):
            x = F.relu(layer(x)) if i < len(self.layers) - 1 else layer(x)
        return x


class MaskDecoderT(nn.Module):
    """SAM mask decoder with the reference's best-IoU selection patch."""

    def __init__(self, dim=256, num_multimask_outputs=3, depth=2,
                 num_heads=8, mlp_dim=2048):
        super().__init__()
        self.num_mask_tokens = num_multimask_outputs + 1
        self.iou_token = nn.Embedding(1, dim)
        self.mask_tokens = nn.Embedding(self.num_mask_tokens, dim)
        self.transformer = TwoWayTransformerT(depth, dim, num_heads, mlp_dim)
        self.output_upscaling = nn.Sequential(
            nn.ConvTranspose2d(dim, dim // 4, 2, stride=2),
            LayerNorm2dT(dim // 4),
            nn.GELU(),
            nn.ConvTranspose2d(dim // 4, dim // 8, 2, stride=2),
            nn.GELU(),
        )
        self.output_hypernetworks_mlps = nn.ModuleList(
            [MLPT(dim, dim, dim // 8, 3) for _ in range(self.num_mask_tokens)]
        )
        self.iou_prediction_head = MLPT(dim, 256, self.num_mask_tokens, 3)

    def forward(self, image_embeddings, image_pe, sparse, dense):
        # image_embeddings (1, C, h, w); image_pe (1, C, h, w);
        # sparse (N, P, C); dense (N, C, h, w)
        n = sparse.shape[0]
        output_tokens = torch.cat(
            [self.iou_token.weight, self.mask_tokens.weight], dim=0
        )
        tokens = torch.cat(
            [output_tokens.unsqueeze(0).expand(n, -1, -1), sparse], dim=1
        )
        src = image_embeddings.expand(n, -1, -1, -1) + dense
        pos = image_pe.expand(n, -1, -1, -1)
        b, c, h, w = src.shape
        hs, keys = self.transformer(src, pos, tokens)
        iou_token_out = hs[:, 0, :]
        mask_tokens_out = hs[:, 1 : 1 + self.num_mask_tokens, :]
        src = keys.transpose(1, 2).reshape(b, c, h, w)
        up = self.output_upscaling(src)
        hyper = torch.stack(
            [
                self.output_hypernetworks_mlps[i](mask_tokens_out[:, i, :])
                for i in range(self.num_mask_tokens)
            ],
            dim=1,
        )
        b, c, h, w = up.shape
        masks = (hyper @ up.reshape(b, c, h * w)).reshape(b, -1, h, w)
        iou_pred = self.iou_prediction_head(iou_token_out)
        ids = torch.argmax(iou_pred, dim=1)
        ar = torch.arange(n)
        return masks[ar, ids], iou_pred[ar, ids]
