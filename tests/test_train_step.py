"""Train step: optimizer groups, freezing, loss decrease smoke test."""

import pytest

import numpy as np

import jax
import jax.numpy as jnp

from tmr_tpu.config import Config
from tmr_tpu.models.matching_net import MatchingNet
from tmr_tpu.models.vit import SamViT
from tmr_tpu.train.state import create_train_state, make_train_step

TINY_VIT = dict(
    embed_dim=32, depth=2, num_heads=2, global_attn_indexes=(1,),
    patch_size=8, window_size=3, out_chans=16, pretrain_img_size=64,
)


def _setup(lr_backbone=0.0, **cfg_overrides):
    cfg = Config(
        backbone="sam_vit_b", emb_dim=16, fusion=True, feature_upsample=False,
        positive_threshold=0.5, negative_threshold=0.5,
        lr=1e-3, lr_backbone=lr_backbone, lr_drop=True, max_epochs=10,
        compute_dtype="float32", **cfg_overrides,
    )
    model = MatchingNet(
        backbone=SamViT(**TINY_VIT), emb_dim=cfg.emb_dim, fusion=True,
        template_capacity=9,
    )
    rng = np.random.default_rng(0)
    b, s = 2, 64
    batch = {
        "image": jnp.array(rng.standard_normal((b, s, s, 3)).astype(np.float32)),
        "exemplars": jnp.array(
            np.tile([[0.3, 0.3, 0.45, 0.5]], (b, 1)).astype(np.float32)
        )[:, None, :],
        "gt_boxes": jnp.array(
            np.tile([[[0.3, 0.3, 0.45, 0.5], [0.6, 0.6, 0.75, 0.8]]], (b, 1, 1)
                    ).astype(np.float32)
        ),
        "gt_valid": jnp.ones((b, 2), bool),
    }
    state = create_train_state(
        model, cfg, jax.random.key(0), batch["image"], batch["exemplars"],
        steps_per_epoch=10,
    )
    step = jax.jit(make_train_step(model, cfg))
    return state, step, batch


@pytest.mark.slow
def test_frozen_backbone_and_head_updates():
    state, step, batch = _setup(lr_backbone=0.0)
    p0 = jax.tree_util.tree_map(np.asarray, state.params)
    state, losses = step(state, batch)
    p1 = jax.tree_util.tree_map(np.asarray, state.params)

    # backbone untouched
    bb0 = jax.tree_util.tree_leaves(p0["backbone"])
    bb1 = jax.tree_util.tree_leaves(p1["backbone"])
    assert all(np.array_equal(a, b) for a, b in zip(bb0, bb1))
    # heads moved
    moved = any(
        not np.array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(p0["objectness_head_0"]),
            jax.tree_util.tree_leaves(p1["objectness_head_0"]),
        )
    )
    assert moved
    assert np.isfinite(float(losses["loss"]))


@pytest.mark.slow
def test_loss_decreases_over_steps():
    state, step, batch = _setup()
    first = None
    for i in range(8):
        state, losses = step(state, batch)
        if first is None:
            first = float(losses["loss"])
    last = float(losses["loss"])
    assert np.isfinite(last)
    assert last < first  # overfits the fixed batch


@pytest.mark.slow
def test_trainable_backbone_updates():
    state, step, batch = _setup(lr_backbone=1e-4)
    p0 = jax.tree_util.tree_map(np.asarray, state.params)
    state, _ = step(state, batch)
    p1 = jax.tree_util.tree_map(np.asarray, state.params)
    moved = any(
        not np.array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(p0["backbone"]),
            jax.tree_util.tree_leaves(p1["backbone"]),
        )
    )
    assert moved


@pytest.mark.slow
def test_nonfinite_loss_skips_update():
    """A batch producing a non-finite loss must leave params unchanged
    (failure containment; the reference trains through NaNs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tmr_tpu.config import Config
    from tmr_tpu.models import build_model
    from tmr_tpu.train.state import create_train_state, make_train_step

    cfg = Config(backbone="resnet50_layer1", emb_dim=8, fusion=False,
                 image_size=32, compute_dtype="float32", max_gt_boxes=4)
    model = build_model(cfg)
    img = jnp.zeros((1, 32, 32, 3), jnp.float32)
    ex = jnp.array([[[0.3, 0.3, 0.6, 0.6]]], jnp.float32)
    state = create_train_state(model, cfg, jax.random.key(0), img, ex,
                               steps_per_epoch=10)
    step = jax.jit(make_train_step(model, cfg))

    bad_batch = {
        "image": jnp.full((1, 32, 32, 3), jnp.nan),  # poisoned input
        "exemplars": ex,
        "gt_boxes": jnp.array([[[0.3, 0.3, 0.6, 0.6]]] , jnp.float32),
        "gt_valid": jnp.ones((1, 1), bool),
    }
    good_batch = dict(
        bad_batch,
        image=jnp.asarray(
            np.random.default_rng(0).standard_normal((1, 32, 32, 3)),
            jnp.float32,
        ),
    )

    # build real Adam moments first — a 'skipped' step must not move params
    # via momentum/weight-decay either (the subtle failure mode)
    state, _ = step(state, good_batch)
    state, _ = step(state, good_batch)

    new_state, losses = step(state, bad_batch)
    assert float(losses["skipped_nonfinite"]) == 1.0
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        state.params, new_state.params,
    )
    # optimizer state and step count also untouched
    assert int(new_state.step) == int(state.step)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        state.opt_state, new_state.opt_state,
    )

    new_state2, losses2 = step(new_state, good_batch)
    assert float(losses2["skipped_nonfinite"]) == 0.0
    # and a good step does change params
    leaves_eq = jax.tree_util.tree_map(
        lambda a, b: bool(np.allclose(np.asarray(a), np.asarray(b))),
        new_state.params, new_state2.params,
    )
    assert not all(jax.tree_util.tree_leaves(leaves_eq))


@pytest.mark.slow
def test_grad_accumulation_updates_every_k_steps():
    """--grad_accum_steps k (optax.MultiSteps): params stay bit-identical
    for k-1 micro-steps, then one combined update applies; the mean of the
    k accumulated gradients drives it (single-chip route to the reference's
    4-GPU effective batch)."""
    state, step, batch = _setup(grad_accum_steps=2)

    p0 = jax.tree_util.tree_leaves(state.params)
    state1, losses1 = step(state, batch)
    p1 = jax.tree_util.tree_leaves(state1.params)
    # micro-step 1 of 2: gradients accumulated, NO parameter update
    for a, b in zip(p0, p1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(float(losses1["loss"]))

    state2, losses2 = step(state1, batch)
    p2 = jax.tree_util.tree_leaves(state2.params)
    # micro-step 2 of 2: the combined update fires on the head group
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(p1, p2)
    )
    assert changed
    assert all(np.isfinite(np.asarray(l)).all() for l in p2)
