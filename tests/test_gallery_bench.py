"""scripts/gallery_bench.py: the gallery_report/v1 contract.

The smoke test runs the real script in a subprocess at tiny CPU shapes
in a CLEAN env (no forced host-device count — see test_serve.py's
caveat; the bench's bitwise pin compares across programs) with an
ISOLATED autotune cache (the bench persists its elected winners) and
asserts the acceptance checks: fused gallery arm bitwise-identical to
the N-loop of predict_multi_exemplar, backbone executions == frames
(not frames×N) via the flight recorder's program table, and the
prefilter's elected top-k at recall >= 0.99 with a >= 2x full-match
invocation cut. The validator tests pin the schema both ways."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_env(tmp_path, **extra):
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS")
    }
    env.update(
        JAX_PLATFORMS="cpu",
        TMR_BENCH_TINY="1",
        TMR_BENCH_SIZE="128",
        # the bench records elected winners; tests must not write the
        # user's real cache (nor inherit its prior state)
        TMR_AUTOTUNE_CACHE=str(tmp_path / "autotune.json"),
        TMR_AUTOTUNE_SEED=str(tmp_path / "absent_seed.json"),
        **extra,
    )
    return env


def _valid_doc():
    from tmr_tpu.diagnostics import GALLERY_REPORT_SCHEMA

    return {
        "schema": GALLERY_REPORT_SCHEMA,
        "device": "cpu",
        "config": {"image_size": 128, "patterns": 8, "frames": 4},
        "bank": {"entries": 8, "groups": [
            {"capacity": 9, "k_bucket": 1, "n_real": 8, "n_bucket": 8}
        ]},
        "throughput": {"gallery_pattern_frames_per_sec": 5.8,
                       "n_loop_pattern_frames_per_sec": 2.9,
                       "speedup": 2.0},
        "backbone": {"frames": 4, "executions": 4,
                     "pattern_frame_pairs": 32,
                     "by_program": {"gallery": 4}},
        "prefilter": {
            "rungs": [{"topk": 2, "recall": 1.0, "invocation_cut": 4.0,
                       "full_matches": 8}],
            "elected_topk": 2,
        },
        "checks": {"bitwise_exact": True, "backbone_amortized": True,
                   "prefilter_recall_ok": True, "prefilter_cut_ok": True,
                   "speedup_vs_n_loop": 2.0},
    }


def _sweep_section():
    return {
        "points": [
            {"n": 1000, "topk": 32, "linear_ms": 12.0, "index_ms": 9.0,
             "recall": 1.0, "off_exact": True, "indexed": True,
             "centroids": 32, "probes": 32, "candidates": 1000},
            {"n": 10000, "topk": 32, "linear_ms": 110.0,
             "index_ms": 31.0, "recall": 0.97, "off_exact": True,
             "indexed": True, "centroids": 100, "probes": 32,
             "candidates": 3300},
        ],
        "fit": {"linear_exponent": 0.96, "index_exponent": 0.54},
        "checks": {"index_sublinear": True, "index_recall_ok": True,
                   "index_off_exact": True},
    }


def test_validate_gallery_report_accepts_valid_and_error_docs():
    from tmr_tpu.diagnostics import (
        GALLERY_REPORT_SCHEMA,
        validate_gallery_report,
    )

    assert validate_gallery_report(_valid_doc()) == []
    assert validate_gallery_report(
        {"schema": GALLERY_REPORT_SCHEMA, "error": "watchdog: ..."}
    ) == []
    # the n_sweep section is OPTIONAL (legacy docs above stay valid)
    # but validated when present
    with_sweep = _valid_doc()
    with_sweep["n_sweep"] = _sweep_section()
    assert validate_gallery_report(with_sweep) == []


@pytest.mark.parametrize("mutate, fragment", [
    (lambda d: d.update(schema="bogus/v9"), "schema"),
    (lambda d: d["config"].update(patterns=0), "patterns"),
    (lambda d: d.pop("bank"), "bank"),
    (lambda d: d["throughput"].pop("speedup"), "speedup"),
    (lambda d: d["backbone"].update(executions=-1), "executions"),
    (lambda d: d["backbone"].pop("by_program"), "by_program"),
    (lambda d: d["prefilter"].update(rungs="nope"), "rungs"),
    (lambda d: d["prefilter"]["rungs"][0].pop("recall"), "recall"),
    (lambda d: d["prefilter"].update(elected_topk=0), "elected_topk"),
    (lambda d: d["checks"].pop("bitwise_exact"), "bitwise_exact"),
    (lambda d: d.update(error=""), "error"),
    (lambda d: d.update(n_sweep="nope"), "n_sweep"),
    (lambda d: d.update(n_sweep=dict(_sweep_section(), points=[])),
     "points"),
    (lambda d: d.update(n_sweep=_sweep_section())
     or d["n_sweep"]["points"][0].update(n=0), "n"),
    (lambda d: d.update(n_sweep=_sweep_section())
     or d["n_sweep"]["points"][1].update(recall=1.5), "recall"),
    (lambda d: d.update(n_sweep=_sweep_section())
     or d["n_sweep"]["points"][0].update(index_ms=-1), "index_ms"),
    (lambda d: d.update(n_sweep=dict(_sweep_section(), fit=None)),
     "fit"),
    (lambda d: d.update(n_sweep=_sweep_section())
     or d["n_sweep"]["checks"].pop("index_sublinear"),
     "index_sublinear"),
])
def test_validate_gallery_report_rejects_broken_docs(mutate, fragment):
    from tmr_tpu.diagnostics import validate_gallery_report

    doc = _valid_doc()
    mutate(doc)
    problems = validate_gallery_report(doc)
    assert problems, f"expected a problem for {fragment}"
    assert any(fragment in p for p in problems), problems


def test_read_gallery_report_reduces_and_fails_closed(tmp_path):
    from tmr_tpu.utils.bench_trend import read_gallery_report

    path = tmp_path / "gal.json"
    path.write_text(json.dumps(_valid_doc()) + "\n")
    out = read_gallery_report(str(path))
    assert out["checks"] == {
        "bitwise_exact": True, "backbone_amortized": True,
        "prefilter_recall_ok": True, "prefilter_cut_ok": True,
    }
    assert out["summary"]["backbone_executions"] == 4
    assert out["rungs"][0]["topk"] == 2
    # fail CLOSED: a missing check is not a pass
    doc = _valid_doc()
    del doc["checks"]["backbone_amortized"]
    path.write_text(json.dumps(doc) + "\n")
    assert read_gallery_report(str(path))["checks"][
        "backbone_amortized"
    ] is False
    # error record and unreadable file reduce to error records
    path.write_text(json.dumps({"schema": "gallery_report/v1",
                                "error": "boom"}))
    assert "error" in read_gallery_report(str(path))
    assert "error" in read_gallery_report(str(tmp_path / "absent.json"))
    # the optional n_sweep section reduces to sweep_points + the three
    # sweep checks (fail closed: a missing check is not a pass)
    doc = _valid_doc()
    doc["n_sweep"] = _sweep_section()
    del doc["n_sweep"]["checks"]["index_recall_ok"]
    path.write_text(json.dumps(doc) + "\n")
    out = read_gallery_report(str(path))
    assert out["checks"]["index_sublinear"] is True
    assert out["checks"]["index_recall_ok"] is False
    assert "fleet_probe_ok" not in out["checks"]  # only when recorded
    assert out["summary"]["index_exponent"] == 0.54
    assert [p["n"] for p in out["sweep_points"]] == [1000, 10000]


def test_bench_trend_gallery_rc_gates(tmp_path):
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_valid_doc()) + "\n")
    bad_doc = _valid_doc()
    bad_doc["checks"]["bitwise_exact"] = False
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(bad_doc) + "\n")
    script = os.path.join(REPO, "scripts", "bench_trend.py")
    ok = subprocess.run(
        [sys.executable, script, "--gallery", str(good)],
        capture_output=True, text=True, timeout=120,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert json.loads(ok.stdout)["checks"]["bitwise_exact"] is True
    fail = subprocess.run(
        [sys.executable, script, "--gallery", str(bad)],
        capture_output=True, text=True, timeout=120,
    )
    assert fail.returncode == 1
    # a failing n_sweep check gates rc even with the four legacy
    # checks green — and a passing sweep keeps rc 0
    sweep_doc = _valid_doc()
    sweep_doc["n_sweep"] = _sweep_section()
    swept = tmp_path / "swept.json"
    swept.write_text(json.dumps(sweep_doc) + "\n")
    ok2 = subprocess.run(
        [sys.executable, script, "--gallery", str(swept)],
        capture_output=True, text=True, timeout=120,
    )
    assert ok2.returncode == 0, ok2.stdout + ok2.stderr
    sweep_doc["n_sweep"]["checks"]["index_sublinear"] = False
    swept.write_text(json.dumps(sweep_doc) + "\n")
    fail2 = subprocess.run(
        [sys.executable, script, "--gallery", str(swept)],
        capture_output=True, text=True, timeout=120,
    )
    assert fail2.returncode == 1


def test_measured_gallery_winners_round_trip(tmp_path, monkeypatch):
    from tmr_tpu.utils.autotune import (
        gallery_cache_key,
        measured_gallery_nmax,
        measured_gallery_topk,
        record_gallery_winners,
    )

    monkeypatch.setenv("TMR_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    monkeypatch.setenv("TMR_AUTOTUNE_SEED", str(tmp_path / "absent.json"))
    kind = "TFRT_CPU_0"
    assert measured_gallery_nmax(128, device_kind=kind) is None
    assert measured_gallery_topk(128, device_kind=kind) is None
    record_gallery_winners(128, nmax=8, topk=2, device_kind=kind)
    assert measured_gallery_nmax(128, device_kind=kind) == 8
    assert measured_gallery_topk(128, device_kind=kind) == 2
    assert measured_gallery_nmax(999, device_kind=kind) is None
    # the key format is the writer/reader contract
    obj = json.loads((tmp_path / "autotune.json").read_text())
    assert gallery_cache_key(kind, 128) in obj


def test_gallery_bench_tiny_smoke_meets_acceptance_checks(tmp_path):
    """The acceptance proof, end to end on CPU: one JSON line, valid
    gallery_report/v1, fused arm bitwise vs the N-loop, backbone
    executions == frames for an N=8 bank, prefilter elected top-k at
    recall >= 0.99 with >= 2x invocation cut — non-hollow (detections
    exist and do not saturate)."""
    out_file = tmp_path / "gallery_report.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "gallery_bench.py"),
         "--tiny", "--out", str(out_file)],
        env=_bench_env(tmp_path), capture_output=True, text=True,
        timeout=560,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    doc = json.loads(lines[0])

    from tmr_tpu.diagnostics import validate_gallery_report

    assert validate_gallery_report(doc) == []
    assert "validator_problems" not in doc
    checks = doc["checks"]
    assert checks["bitwise_exact"] is True
    assert checks["backbone_amortized"] is True, doc["backbone"]
    assert checks["prefilter_recall_ok"] is True, doc["prefilter"]
    assert checks["prefilter_cut_ok"] is True, doc["prefilter"]
    assert checks["detections_nonzero"] and checks[
        "detections_nontrivial"
    ]
    assert doc["config"]["patterns"] >= 8  # the acceptance floor
    assert doc["backbone"]["executions"] == doc["backbone"]["frames"]
    assert doc["backbone"]["pattern_frame_pairs"] \
        == doc["config"]["patterns"] * doc["config"]["frames"]
    elected = doc["prefilter"]["elected_topk"]
    rung = next(r for r in doc["prefilter"]["rungs"]
                if r["topk"] == elected)
    assert rung["recall"] >= 0.99 and rung["invocation_cut"] >= 2.0
    # the elected winners persisted to the (isolated) autotune cache
    cache = json.loads((tmp_path / "autotune.json").read_text())
    (key,) = [k for k in cache if "|gallery|" in k]
    assert cache[key]["TMR_GALLERY_PREFILTER_TOPK"] == str(elected)
    # --out wrote the same document; progress went to stderr only
    assert json.loads(out_file.read_text())["checks"] == checks
    assert "[gallery_bench]" in out.stderr
