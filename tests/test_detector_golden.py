"""Whole-detector golden parity vs. the reference PyTorch model (VERDICT r2
missing #1): the reference's own matching_net / template_matching / TM_utils
/ criterions_TM are imported by file path and run head-to-head against
tmr_tpu on shared converted weights — forward maps, target assignment, loss
values, and decoded+NMS'd boxes must all agree.

torchvision is absent in this image, so its three ops the reference files
import (`roi_align`, `nms`, `generalized_box_iou_loss`) are stubbed with the
independently tested numpy ports from tests/oracles.py wrapped in torch —
exactly the substitution VERDICT r2 prescribed.
"""

import importlib.util
import sys
import types
from types import SimpleNamespace

import numpy as np
import pytest

import jax.numpy as jnp

from oracles import giou_loss_np, nms_np, roi_align_np
from test_vit_golden import TINY, _build_pair


pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

REF = "/root/reference"


# ------------------------------------------------------- torchvision stub
def _stub_torchvision():
    if "torchvision" in sys.modules:
        return
    import torch

    tv = types.ModuleType("torchvision")
    ops = types.ModuleType("torchvision.ops")
    boxes_mod = types.ModuleType("torchvision.ops.boxes")

    def roi_align(input, boxes, output_size, spatial_scale=1.0,
                  sampling_ratio=-1, aligned=False):
        # the reference only calls this with batch-1 input and a one-element
        # box list (template_matching.py:75)
        feats = input.detach().numpy()
        outs = []
        for b, rois in enumerate(boxes):
            out = roi_align_np(
                feats[b], rois.detach().numpy(), tuple(output_size),
                spatial_scale, sampling_ratio, aligned,
            )
            outs.append(out)
        return torch.from_numpy(
            np.concatenate(outs, axis=0).astype(np.float32)
        )

    def nms(boxes, scores, iou_threshold):
        keep = nms_np(
            boxes.detach().numpy(), scores.detach().numpy(), iou_threshold
        )
        return torch.as_tensor(list(keep), dtype=torch.int64)

    def generalized_box_iou_loss(pred, target, reduction="none", eps=1e-7):
        out = giou_loss_np(
            pred.detach().numpy().astype(np.float64),
            target.detach().numpy().astype(np.float64), eps=eps,
        )
        t = torch.from_numpy(out).to(pred.dtype)
        if reduction == "sum":
            return t.sum()
        if reduction == "mean":
            return t.mean()
        return t

    def box_area(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])

    ops.roi_align = roi_align
    ops.nms = nms
    ops.generalized_box_iou_loss = generalized_box_iou_loss
    boxes_mod.box_area = box_area
    ops.boxes = boxes_mod
    tv.ops = ops
    sys.modules["torchvision"] = tv
    sys.modules["torchvision.ops"] = ops
    sys.modules["torchvision.ops.boxes"] = boxes_mod


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_ref_detector():
    """Reference detector modules by file path (test_vit_golden pattern)."""
    if "refdet.models.matching_net" in sys.modules:
        return (
            sys.modules["refdet.models.matching_net"],
            sys.modules["refdet.TM_utils"],
            sys.modules["refdet.criterions_TM"],
        )
    _stub_torchvision()
    for pkg_name, path in (
        ("refdet", None),
        ("refdet.models", f"{REF}/models"),
        ("refdet.models.backbone", f"{REF}/models/backbone"),
        ("refdet.models.backbone.sam", f"{REF}/models/backbone/sam"),
    ):
        pkg = types.ModuleType(pkg_name)
        pkg.__path__ = [path] if path else []
        sys.modules[pkg_name] = pkg
    _load("refdet.models.backbone.sam.common",
          f"{REF}/models/backbone/sam/common.py")
    _load("refdet.models.regression_head", f"{REF}/models/regression_head.py")
    _load("refdet.models.encoders", f"{REF}/models/encoders.py")
    _load("refdet.models.template_matching",
          f"{REF}/models/template_matching.py")
    mn = _load("refdet.models.matching_net", f"{REF}/models/matching_net.py")
    tm_utils = _load("refdet.TM_utils", f"{REF}/utils/TM_utils.py")
    crit = _load("refdet.criterions_TM", f"{REF}/criterion/criterions_TM.py")
    return mn, tm_utils, crit


# ------------------------------------------------------------ model pair
ARGS = dict(
    emb_dim=8,
    fusion=True,
    ablation_no_box_regression=False,
    no_matcher=False,
    template_type="roi_align",
    squeeze=False,
    feature_upsample=True,
    decoder_num_layer=1,
    decoder_kernel_size=3,
    encoder="original",
    positive_threshold=0.5,
    negative_threshold=0.5,
    modeltype="matching_net",
)
BATCH_FLAGS = {"regression_ablation_b": False, "regression_ablation_c": False}


def _build_detector_pair(seed=0):
    """Reference matching_net (tiny ViT backbone) + our MatchingNet sharing
    converted weights."""
    import torch

    from tmr_tpu.models.matching_net import MatchingNet
    from tmr_tpu.models.vit import SamViT
    from tmr_tpu.utils.convert import convert_matching_net

    mn, _, _ = _load_ref_detector()
    ref_vit, _, _ = _build_pair(seed=seed)

    class RefBackbone(torch.nn.Module):
        """Sam_Backbone stand-in: .backbone = the encoder, num_channels
        exposed (models/backbone/sam/sam.py wraps ImageEncoderViT the same
        way, so converted key paths line up: encoder.backbone.backbone.*)."""

        def __init__(self, vit):
            super().__init__()
            self.backbone = vit
            self.num_channels = TINY["out_chans"]

        def forward(self, x):
            return self.backbone(x)

    args = SimpleNamespace(**ARGS)
    torch.manual_seed(seed + 100)
    ref_model = mn.matching_net(RefBackbone(ref_vit), args)
    # the std=0.01 head init yields near-flat maps; re-randomize the
    # detector-specific weights so the comparison exercises real structure
    with torch.no_grad():
        for name, p in ref_model.named_parameters():
            if not name.startswith("encoder.") and p.dim() > 1:
                p.normal_(std=0.3)
        ref_model.matcher.scale.fill_(1.7)
    ref_model.eval()

    mine = MatchingNet(
        backbone=SamViT(
            embed_dim=TINY["embed_dim"],
            depth=TINY["depth"],
            num_heads=TINY["num_heads"],
            global_attn_indexes=TINY["global_attn_indexes"],
            patch_size=TINY["patch_size"],
            window_size=TINY["window_size"],
            out_chans=TINY["out_chans"],
            pretrain_img_size=TINY["img_size"],
        ),
        emb_dim=ARGS["emb_dim"],
        fusion=True,
        feature_upsample=True,
        template_capacity=9,
        decoder_num_layer=1,
        decoder_kernel_size=3,
    )
    sd = {f"model.{k}": v for k, v in ref_model.state_dict().items()}
    params = convert_matching_net(sd, backbone="sam")
    return ref_model, mine, params


RNG = np.random.default_rng(42)
IMAGE = RNG.standard_normal((2, 3, 32, 32)).astype(np.float32)
EXEMPLARS = np.array(
    [[[0.30, 0.25, 0.62, 0.60]], [[0.55, 0.50, 0.80, 0.86]]], np.float32
)
GT_BOXES = [
    np.array([[0.28, 0.22, 0.64, 0.62], [0.05, 0.55, 0.35, 0.95],
              [0.60, 0.05, 0.95, 0.40]], np.float32),
    np.array([[0.52, 0.48, 0.82, 0.88], [0.10, 0.10, 0.40, 0.45]],
             np.float32),
]


def _run_pair(seed=0):
    import torch

    ref_model, mine, params = _build_detector_pair(seed=seed)
    with torch.no_grad():
        os_, bs_, f_tms, feat = ref_model(
            torch.from_numpy(IMAGE), torch.from_numpy(EXEMPLARS)
        )
    out = mine.apply(
        {"params": params},
        jnp.asarray(IMAGE.transpose(0, 2, 3, 1)),
        jnp.asarray(EXEMPLARS),
    )
    return ref_model, mine, params, (os_, bs_, f_tms, feat), out


@pytest.fixture(scope="module")
def pair():
    return _run_pair(seed=0)


def test_forward_maps_match(pair):
    """objectness / regression / f_TM / feature maps agree < 1e-4 f32."""
    _, _, _, (os_, bs_, f_tms, feat), out = pair
    np.testing.assert_allclose(
        np.asarray(out["objectness"][0]), os_[0].numpy()[:, 0],
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(out["regressions"][0]),
        bs_[0].numpy().transpose(0, 2, 3, 1), rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(out["f_tm"][0]), f_tms[0].numpy().transpose(0, 2, 3, 1),
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(out["feature"]), feat.numpy().transpose(0, 2, 3, 1),
        rtol=1e-4, atol=1e-4,
    )


def test_target_maps_match_reference_gt_map(pair):
    """assign_targets' positive/negative/ignore partition equals the real
    Get_pred_gts gt_map (1.0 / 0.0 / 0.5 coding) on the shared forward."""
    import torch

    from tmr_tpu.train.targets import assign_targets

    _, tm_utils, _ = _load_ref_detector()
    _, _, _, (os_, bs_, _, _), out = pair

    gt_t = [torch.from_numpy(b) for b in GT_BOXES]
    _, _, gt_maps = tm_utils.GT_map(SimpleNamespace(**ARGS)).Get_pred_gts(
        os_, bs_, gt_t, torch.from_numpy(EXEMPLARS), dict(BATCH_FLAGS)
    )

    M = max(len(b) for b in GT_BOXES)
    gt_boxes = np.zeros((2, M, 4), np.float32)
    gt_valid = np.zeros((2, M), bool)
    for i, b in enumerate(GT_BOXES):
        gt_boxes[i, : len(b)] = b
        gt_valid[i, : len(b)] = True

    h, w = out["objectness"][0].shape[1:3]
    tgt = assign_targets(
        jnp.asarray(gt_boxes), jnp.asarray(gt_valid),
        jnp.asarray(EXEMPLARS[:, 0]), h, w, 0.5, 0.5, is_last_level=True,
    )
    ref_map = gt_maps[0][:, 0].numpy()  # (B, H, W): 1 pos, 0 neg, 0.5 ignore
    got_map = (
        np.asarray(tgt["positive"], np.float32)
        + 0.5 * (~(np.asarray(tgt["positive"]) | np.asarray(tgt["negative"])))
    )
    np.testing.assert_array_equal(got_map, ref_map)


def test_loss_values_match_reference_criterion(pair):
    """compute_losses == real Get_pred_gts + SetCriterion_TM end to end."""
    import torch

    _, tm_utils, crit = _load_ref_detector()
    _, _, _, (os_, bs_, _, _), out = pair

    gt_t = [torch.from_numpy(b) for b in GT_BOXES]
    preds, gts, _ = tm_utils.GT_map(SimpleNamespace(**ARGS)).Get_pred_gts(
        os_, bs_, gt_t, torch.from_numpy(EXEMPLARS), dict(BATCH_FLAGS)
    )
    with torch.no_grad():
        want = crit.SetCriterion_TM(use_focal_loss=False)(preds, gts)

    from tmr_tpu.train.state import compute_losses

    M = max(len(b) for b in GT_BOXES)
    gt_boxes = np.zeros((2, M, 4), np.float32)
    gt_valid = np.zeros((2, M), bool)
    for i, b in enumerate(GT_BOXES):
        gt_boxes[i, : len(b)] = b
        gt_valid[i, : len(b)] = True
    got = compute_losses(
        out,
        {"exemplars": jnp.asarray(EXEMPLARS),
         "gt_boxes": jnp.asarray(gt_boxes),
         "gt_valid": jnp.asarray(gt_valid)},
        positive_threshold=0.5, negative_threshold=0.5,
    )
    np.testing.assert_allclose(
        float(got["loss_ce"]), float(want["loss_ce"]), rtol=1e-4
    )
    np.testing.assert_allclose(
        float(got["loss_giou"]), float(want["loss_giou"]), rtol=1e-4
    )


def test_decoded_nms_boxes_match_reference(pair):
    """Get_pred_boxes + NMS vs decode_detections + batched_nms: same
    surviving (score, box, ref) sets per image."""
    import torch

    from tmr_tpu.ops.postprocess import batched_nms, decode_detections

    _, tm_utils, _ = _load_ref_detector()
    _, _, _, (os_, bs_, _, _), out = pair

    cls_thr, iou_thr = 0.45, 0.5
    logits, boxes, refs = tm_utils.Get_pred_boxes(
        [o.detach() for o in os_], [b.detach() for b in bs_],
        torch.from_numpy(EXEMPLARS), dict(BATCH_FLAGS), cls_ths=cls_thr,
    )
    logits, boxes, refs = tm_utils.NMS(logits, boxes, refs,
                                       iou_threshold=iou_thr)

    dets = decode_detections(
        out["objectness"], out["regressions"], jnp.asarray(EXEMPLARS[:, 0]),
        cls_threshold=cls_thr, max_detections=64,
    )
    dets = batched_nms(dets, iou_thr, backend="xla")

    for b in range(2):
        want_scores = logits[b][:, 0].numpy()
        want_boxes = boxes[b].numpy()
        want_refs = refs[b].numpy()
        order = np.argsort(-want_scores, kind="mergesort")

        valid = np.asarray(dets["valid"][b])
        got_scores = np.asarray(dets["scores"][b])[valid]
        got_boxes = np.asarray(dets["boxes"][b])[valid]
        got_refs = np.asarray(dets["refs"][b])[valid]
        g_order = np.argsort(-got_scores, kind="mergesort")

        assert len(got_scores) == len(want_scores), (
            f"image {b}: {len(got_scores)} vs {len(want_scores)} detections"
        )
        assert len(want_scores) > 1  # the case must be non-trivial
        np.testing.assert_allclose(
            got_scores[g_order], want_scores[order], rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            got_boxes[g_order], want_boxes[order], rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            got_refs[g_order], want_refs[order], rtol=1e-4, atol=1e-5
        )


def test_targets_loss_randomized_vs_reference():
    """Re-oracle the target/criterion stack against the real
    Get_pred_gts/SetCriterion_TM on randomized synthetic maps (VERDICT r2:
    hand-ported oracles prove self-consistency, this proves fidelity),
    including a zero-positive image exercising the 1e-14 dummy path."""
    import torch

    from tmr_tpu.train.state import compute_losses

    _, tm_utils, crit = _load_ref_detector()
    rng = np.random.default_rng(9)
    H = W = 8
    for case in range(4):
        B = 2
        obj = rng.standard_normal((B, 1, H, W)).astype(np.float32)
        reg = (rng.standard_normal((B, 4, H, W)) * 0.3).astype(np.float32)
        ex = rng.uniform(0.2, 0.6, (B, 1, 2)).astype(np.float32)
        ex = np.concatenate([ex, ex + rng.uniform(0.15, 0.35, (B, 1, 2))],
                            axis=-1).astype(np.float32)
        gt_list = []
        for b in range(B):
            if case == 3 and b == 1:
                # far-corner tiny box -> zero positives for this image
                gt_list.append(np.array([[0.0, 0.0, 0.02, 0.02]], np.float32))
                continue
            n = int(rng.integers(1, 4))
            xy = rng.uniform(0.05, 0.55, (n, 2))
            wh = rng.uniform(0.1, 0.4, (n, 2))
            gt_list.append(
                np.concatenate([xy, np.minimum(xy + wh, 1.0)], axis=1)
                .astype(np.float32)
            )

        preds, gts, _ = tm_utils.GT_map(
            SimpleNamespace(**ARGS)
        ).Get_pred_gts(
            [torch.from_numpy(obj)], [torch.from_numpy(reg)],
            [torch.from_numpy(g) for g in gt_list], torch.from_numpy(ex),
            dict(BATCH_FLAGS),
        )
        with torch.no_grad():
            want = crit.SetCriterion_TM(False)(preds, gts)

        M = max(len(g) for g in gt_list)
        gt_boxes = np.zeros((B, M, 4), np.float32)
        gt_valid = np.zeros((B, M), bool)
        for i, g in enumerate(gt_list):
            gt_boxes[i, : len(g)] = g
            gt_valid[i, : len(g)] = True
        got = compute_losses(
            {"objectness": [jnp.asarray(obj[:, 0])],
             "regressions": [jnp.asarray(reg.transpose(0, 2, 3, 1))]},
            {"exemplars": jnp.asarray(ex), "gt_boxes": jnp.asarray(gt_boxes),
             "gt_valid": jnp.asarray(gt_valid)},
            positive_threshold=0.5, negative_threshold=0.5,
        )
        np.testing.assert_allclose(
            float(got["loss_ce"]), float(want["loss_ce"]), rtol=1e-4,
            err_msg=f"case {case}",
        )
        np.testing.assert_allclose(
            float(got["loss_giou"]), float(want["loss_giou"]), rtol=1e-4,
            err_msg=f"case {case}",
        )
