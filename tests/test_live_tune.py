"""Continuous in-production autotune (tmr_tpu/autotune_live.py): the
per-device-generation winner bank (isolation across cpu/v5e/v6e, stale
``_SWEEP_REV`` entries falling back to the offline cache, offline-cache
seeding), the LiveTuner election policy (consecutive decisive wins,
streak reset, oracle refusal, anomaly demotion with cause, decision-log
replay), the hot-swap hook (``Predictor.invalidate_compiled`` kind
scoping + ``apply_winner``), the engine/fleet wiring (attach refused
when disabled, offers from the serve pipeline, ``live_tune_pass``
counter aggregation + beat-reply election push with the worker's epoch
guard), the HealthWatch/FleetHealthWatch listener hooks, the
bench_trend carried-age audit, both new validators, and the full
scripts/live_tune_probe.py proof behind ``bench_trend --live-tune``."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tmr_tpu import autotune_live
from tmr_tpu.autotune_live import (
    DEMOTE_ANOMALIES,
    LiveTuner,
    apply_winner,
    bank_key,
    load_bank,
    make_entry,
    recorded_elections,
    replay_decisions,
    seed_bank_from_cache,
    store_bank,
)
from tmr_tpu.diagnostics import (
    LIVE_TUNE_REPORT_SCHEMA,
    WINNER_BANK_SCHEMA,
    validate_bench_trend,
    validate_live_tune_report,
    validate_winner_bank,
)

SIZE = 32
EX = np.asarray([[0.4, 0.4, 0.6, 0.6]], np.float32)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DETS = {"scores": np.ones((1, 4), np.float32)}
GENS = ("cpu", "TPU v5e", "TPU v6e")


@pytest.fixture(autouse=True)
def _live_tune_off(monkeypatch):
    """Every test opts in explicitly — the disabled byte-identity
    contract of every OTHER test file depends on the default."""
    for name in ("TMR_LIVE_TUNE", "TMR_LIVE_TUNE_SAMPLE",
                 "TMR_LIVE_TUNE_BUDGET", "TMR_LIVE_TUNE_WINS",
                 "TMR_LIVE_TUNE_BANK"):
        monkeypatch.delenv(name, raising=False)
    yield


def _tuner(tmp_path, *, arms=("fused",), incumbent="xla",
           runner=None, **kw):
    kw.setdefault("knob", "TMR_DECODER_IMPL")
    kw.setdefault("device_kind", "cpu")
    kw.setdefault("geometry", "g1")
    kw.setdefault("sample", 1.0)
    kw.setdefault("budget_s", 100.0)
    kw.setdefault("wins_needed", 3)
    knob = kw.pop("knob")
    return LiveTuner(
        knob, list(arms), incumbent,
        runner=runner or (lambda arm, payload:
                          (DETS, 0.004 if arm != incumbent else 0.010)),
        bank_file=str(tmp_path / "bank.json"), **kw,
    )


# ------------------------------------------------------------ winner bank
def test_winner_bank_device_generation_isolation(tmp_path):
    """The REQUIRED isolation pin: one bank file holding cpu/v5e/v6e
    elections never lets one generation's winner load into another."""
    path = str(tmp_path / "bank.json")
    entries = {}
    for kind in GENS:
        key = bank_key(kind, "TMR_WIN_ATTN", "g1")
        entries[key] = make_entry(kind, "TMR_WIN_ATTN", "g1", "flash",
                                  source="offline")
    assert store_bank(entries, path)
    with open(path) as f:
        raw = json.load(f)
    assert validate_winner_bank(raw) == []
    assert raw["schema"] == WINNER_BANK_SCHEMA
    # unfiltered: all three; filtered: EXACTLY the asked generation
    assert len(load_bank(path)) == 3
    for kind in GENS:
        got = load_bank(path, device_kind=kind)
        assert len(got) == 1
        (entry,) = got.values()
        assert entry["device_kind"] == kind


def test_winner_bank_stale_rev_falls_back(tmp_path, monkeypatch):
    """An entry stamped by an older harness revision is NEVER electable
    (load drops it) — the consumer falls back to the offline cache,
    whose seeding applies the same per-knob variants-stamp staleness."""
    from tmr_tpu.utils.autotune import _variants_sig

    path = str(tmp_path / "bank.json")
    fresh = make_entry("cpu", "TMR_WIN_ATTN", "g1", "flash",
                       source="live")
    stale = make_entry("cpu", "TMR_QUANT", "g1", "int8",
                       source="offline")
    stale["sweep_rev"] = "pre-history"
    store_bank({bank_key("cpu", "TMR_WIN_ATTN", "g1"): fresh,
                bank_key("cpu", "TMR_QUANT", "g1"): stale}, path)
    got = load_bank(path, device_kind="cpu")
    assert set(got) == {bank_key("cpu", "TMR_WIN_ATTN", "g1")}

    # offline-cache seeding: fresh variants stamp seeds, stale stamp and
    # fallback-annotated winners do not, other generations do not, and
    # an existing bank entry is never overwritten by its own seed
    monkeypatch.setattr(
        "tmr_tpu.utils.autotune._cache_load", lambda: {
            "cpu|96x96": {
                "TMR_DECODER_IMPL": "fused",
                "_variants_TMR_DECODER_IMPL":
                    _variants_sig("TMR_DECODER_IMPL"),
                "TMR_GLOBAL_ATTN": "blockwise",
                "_variants_TMR_GLOBAL_ATTN": "stale-stamp",
                "TMR_XCORR_IMPL_SMALL": "conv (fallback)",
                "_variants_TMR_XCORR_IMPL_SMALL":
                    _variants_sig("TMR_XCORR_IMPL_SMALL"),
            },
            "TPU v5e|96x96": {
                "TMR_DECODER_IMPL": "xla",
                "_variants_TMR_DECODER_IMPL":
                    _variants_sig("TMR_DECODER_IMPL"),
            },
        })
    bank = seed_bank_from_cache("cpu", path)
    key = bank_key("cpu", "TMR_DECODER_IMPL", "96x96")
    assert bank[key]["winner"] == "fused"
    assert bank[key]["source"] == "offline"
    assert bank_key("cpu", "TMR_GLOBAL_ATTN", "96x96") not in bank
    assert bank_key("cpu", "TMR_XCORR_IMPL_SMALL", "96x96") not in bank
    assert not any(k.startswith("TPU v5e|") for k in bank)
    # a live election for the same key outranks a later re-seed
    bank[key] = make_entry("cpu", "TMR_DECODER_IMPL", "96x96", "xla",
                           source="live", wins=3)
    store_bank(bank, path)
    reseeded = seed_bank_from_cache("cpu", path)
    assert reseeded[key]["winner"] == "xla"


def test_winner_bank_rejects_invalid(tmp_path):
    path = str(tmp_path / "bank.json")
    # foreign file: degrade to no bank, never a crash
    (tmp_path / "bank.json").write_text("not json")
    assert load_bank(path) == {}
    # fallback-annotated winner: never electable
    bad = make_entry("cpu", "TMR_WIN_ATTN", "g1", "dense (fallback)",
                     source="live")
    # key/entry mismatch: a hand-edit, dropped
    moved = make_entry("cpu", "TMR_WIN_ATTN", "g2", "flash",
                       source="live")
    store_bank({bank_key("cpu", "TMR_WIN_ATTN", "g1"): bad,
                bank_key("cpu", "TMR_WIN_ATTN", "g3"): moved}, path)
    assert load_bank(path) == {}
    # validator-level: source outside the vocabulary / boolean wins
    doc = {"schema": WINNER_BANK_SCHEMA, "sweep_rev": "r", "ts": 1.0,
           "entries": {"k": {"device_kind": "cpu", "knob": "K",
                             "geometry": "g", "winner": "w",
                             "sweep_rev": "r", "source": "guessed",
                             "wins": True, "ts": 1.0}}}
    problems = validate_winner_bank(doc)
    assert any("source" in p for p in problems)
    assert any("wins" in p for p in problems)


# ------------------------------------------------------- election policy
def test_tuner_promotes_after_consecutive_decisive_wins(tmp_path):
    applied = []
    t = _tuner(tmp_path, apply_fn=lambda k, v: applied.append((k, v)))
    for _ in range(2):
        t._shadow_one(None, None, 1)
    assert t.incumbent == "xla"  # two wins: not yet decisive
    t._shadow_one(None, None, 1)
    assert t.incumbent == "fused"
    assert applied == [("TMR_DECODER_IMPL", "fused")]
    c = t.counters()
    assert c["promotions"] == 1 and c["shadow_runs"] == 3
    events = [d["event"] for d in t.decisions]
    assert events == ["shadow", "shadow", "shadow", "promote"]
    assert t.decisions[-1]["wins"] == 3
    # the election landed in the bank as a live-source entry
    entry = load_bank(t.bank_file, device_kind="cpu")[
        bank_key("cpu", "TMR_DECODER_IMPL", "g1")]
    assert entry["winner"] == "fused" and entry["source"] == "live"
    assert entry["device_s_per_item"]["incumbent"] > 0


def test_tuner_streak_resets_on_non_win(tmp_path):
    """Decisive wins are CONSECUTIVE — a non-win resets the arm, so an
    intermittently-fast candidate never promotes."""
    seq = iter([0.004, 0.004, 0.010,   # two wins, then a tie: reset
                0.004, 0.004, 0.010])  # never three in a row

    def runner(arm, payload):
        return (DETS, 0.010) if arm == "xla" else (DETS, next(seq))

    t = _tuner(tmp_path, runner=runner)
    for _ in range(6):
        t._shadow_one(None, None, 1)
    assert t.incumbent == "xla"
    assert t.counters()["promotions"] == 0
    wins = [d["wins"] for d in t.decisions if d["event"] == "shadow"]
    assert wins == [1, 2, 0, 1, 2, 0]


def test_tuner_oracle_refusal_disqualifies(tmp_path):
    """A candidate whose RESULT disagrees with the incumbent is refused
    regardless of timing: recorded, disqualified, never promoted."""
    wrong = {"scores": np.zeros((1, 4), np.float32)}

    def runner(arm, payload):
        return (DETS, 0.010) if arm == "xla" else (wrong, 0.001)

    t = _tuner(tmp_path)
    t._runner = runner
    for _ in range(4):
        t._shadow_one(None, None, 1)
    assert t.incumbent == "xla"
    c = t.counters()
    assert c["refusals"] == 1 and c["promotions"] == 0
    assert t.report()["disqualified"] == ["fused"]
    # only ONE refusal decision: a disqualified arm leaves the pool
    assert [d["event"] for d in t.decisions] == ["refusal"]
    # a refusal of the PROMOTED arm demotes with oracle_refusal cause
    # (two arms round-robin, so "fused" shadows on runs 1/3/5)
    t2 = _tuner(tmp_path, arms=("fused", "flash"))
    for _ in range(5):
        t2._shadow_one(None, None, 1)
    assert t2.incumbent == "fused"
    t2._refuse("fused", 0.010, 0.001, 1)
    assert t2.incumbent == "xla"
    demotes = [d for d in t2.decisions if d["event"] == "demote"]
    assert demotes and demotes[-1]["cause"] == "oracle_refusal"


def test_tuner_anomaly_demotes_with_cause(tmp_path):
    applied = []
    t = _tuner(tmp_path, apply_fn=lambda k, v: applied.append(v))
    # an anomaly with NOTHING promoted must not thrash anything
    t.observe_anomalies([{"anomaly": "mfu_drop"}])
    assert t.counters()["demotions"] == 0
    for _ in range(3):
        t._shadow_one(None, None, 1)
    assert t.incumbent == "fused"
    # a non-demote anomaly kind is ignored
    t.observe_anomalies([{"anomaly": "queue_saturation"}])
    assert t.incumbent == "fused"
    assert "queue_saturation" not in DEMOTE_ANOMALIES
    t.observe_anomalies([
        {"anomaly": "fleet_mfu_drop", "evidence": {"injected": True}},
    ])
    assert t.incumbent == "xla"
    assert applied == ["fused", "xla"]  # promote swap, demote rollback
    d = t.decisions[-1]
    assert d["event"] == "demote" and d["cause"] == "fleet_mfu_drop"
    assert d["evidence"] == {"injected": True}
    # the demoted arm is disqualified: further wins cannot re-promote
    t._shadow_one(None, None, 1)
    assert t.incumbent == "xla"
    # the bank rolled back with the election
    entry = load_bank(t.bank_file, device_kind="cpu")[
        bank_key("cpu", "TMR_DECODER_IMPL", "g1")]
    assert entry["winner"] == "xla"


def test_replay_decisions_matches_recorded(tmp_path):
    t = _tuner(tmp_path)
    for _ in range(3):
        t._shadow_one(None, None, 1)
    t.observe_anomalies([{"anomaly": "latency_regression"}])
    log = t.report()["decisions"]
    recorded = recorded_elections(log)
    assert recorded == [("promote", "fused"), ("demote", "fused")]
    assert replay_decisions(log, wins_needed=3) == recorded
    # the replay is a FUNCTION of the measurements: a stricter policy
    # reaches a different election than the recorded one
    assert replay_decisions(log, wins_needed=4) == []
    # hand-written log: a refusal of the promoted arm replays as demote
    synth = [
        {"event": "shadow", "arm": "a", "base_s_per_item": 1.0,
         "cand_s_per_item": 0.5},
        {"event": "promote", "arm": "a"},
        {"event": "refusal", "arm": "a"},
    ]
    assert replay_decisions(synth, wins_needed=1) == \
        [("promote", "a"), ("demote", "a")]


# ----------------------------------------------------------- hot-swap hook
def test_invalidate_compiled_kind_scoping():
    from tmr_tpu.inference import Predictor

    p = Predictor.__new__(Predictor)
    p._compiled = {
        (64, "k1"): "single-prog", (128, "k2"): "single-prog-2",
        ("multi", 64): "m", ("multi_batched", 64): "mb",
        ("backbone", 96): "bb", ("heads", 96): "h",
        ("gallery", 1): "g", ("gallery_heads", 1): "gh",
    }
    p._storage_cache = object()
    # int-led keys ARE the single-image programs
    assert p.invalidate_compiled(("single",)) == 2
    assert not any(isinstance(k[0], int) for k in p._compiled)
    # the TMR_DECODER_IMPL scope: decode-tail programs, NOT backbone
    dropped = p.invalidate_compiled(
        autotune_live.KNOB_PROGRAM_KINDS["TMR_DECODER_IMPL"])
    assert dropped == 5
    assert set(p._compiled) == {("backbone", 96)}
    assert p._storage_cache is not None  # scoped drop keeps storage
    assert p.invalidate_compiled(None) == 1
    assert p._compiled == {} and p._storage_cache is None


def test_apply_winner_env_and_kinds(monkeypatch):
    monkeypatch.setenv("TMR_DECODER_IMPL", "auto")
    calls = []

    class _Pred:
        def invalidate_compiled(self, kinds):
            calls.append(kinds)
            return 7

    assert apply_winner(_Pred(), "TMR_DECODER_IMPL", "fused") == 7
    assert os.environ["TMR_DECODER_IMPL"] == "fused"
    assert calls == [autotune_live.KNOB_PROGRAM_KINDS["TMR_DECODER_IMPL"]]
    monkeypatch.setenv("TMR_WIN_ATTN", "dense")
    assert apply_winner(_Pred(), "TMR_WIN_ATTN", "flash") == 7
    assert calls[-1] is None  # attention knobs invalidate EVERYTHING
    # a predictor without the hook (the fleet stub): env-only, 0 drops
    assert apply_winner(object(), "TMR_WIN_ATTN", "dense") == 0


# ----------------------------------------------------------- engine wiring
def test_engine_attach_refused_when_disabled(tmp_path):
    from tmr_tpu.serve.fleet import stub_engine

    t = _tuner(tmp_path)
    with stub_engine(0.0) as eng:
        assert eng.attach_live_tuner(t) is False
        assert eng._tuner is None
        eng.submit(np.zeros((SIZE, SIZE, 3), np.float32),
                   EX).result(timeout=30)
        counters = eng.metrics_snapshot().get("counters") or {}
        assert not any(k.startswith("live_tune.") for k in counters)
    assert t.counters()["offers"] == 0


def test_engine_offers_batches_when_enabled(tmp_path, monkeypatch):
    from tmr_tpu.serve.fleet import stub_engine

    monkeypatch.setenv("TMR_LIVE_TUNE", "1")
    monkeypatch.setenv("TMR_LIVE_TUNE_BANK", str(tmp_path / "bank.json"))
    seen = []

    def runner(arm, payload):
        bucket, reqs = payload
        seen.append((arm, len(reqs)))
        assert all(r[0].shape == (SIZE, SIZE, 3) for r in reqs)
        return (DETS, 0.010 if arm == "xla" else 0.004)

    t = _tuner(tmp_path, runner=runner, metrics=None)
    eng = stub_engine(0.0)
    try:
        assert eng.attach_live_tuner(t) is True
        for i in range(4):
            eng.submit(np.full((SIZE, SIZE, 3), i, np.float32),
                       EX).result(timeout=30)
        t.drain(timeout=20.0)
        c = t.counters()
        assert c["offers"] >= 4 and c["sampled"] >= 1
        # 3 shadows promoted "fused"; later samples have no arm left
        assert c["shadow_runs"] == 3 and c["promotions"] == 1
        assert t.incumbent == "fused"
        assert seen  # the runner saw real (image, exemplars, k) tuples
    finally:
        eng.close()
    assert t._thread is None  # close() stopped the shadow thread


def test_healthwatch_listener_demotes_live_promotion(tmp_path):
    """The engine-side demotion trigger end to end: a real HealthWatch
    mfu_drop pass (not an injected record) reaches the tuner through
    add_listener and rolls the promotion back."""
    from tmr_tpu.obs.flight import HealthWatch

    t = _tuner(tmp_path)
    for _ in range(3):
        t._shadow_one(None, None, 1)
    assert t.incumbent == "fused"
    watch = HealthWatch()
    watch.add_listener(t.observe_anomalies)
    snap = {"counters": {}, "histograms": {}}
    watch.observe(snap, mfu_totals={"flops": 0.0, "device_s": 0.0})
    watch.observe(snap, mfu_totals={"flops": 1e12, "device_s": 1.0})
    fired = watch.observe(snap, mfu_totals={"flops": 1.1e12,
                                            "device_s": 2.0})
    assert [r["anomaly"] for r in fired] == ["mfu_drop"]
    assert t.incumbent == "xla"
    assert t.decisions[-1]["cause"] == "mfu_drop"


# ------------------------------------------------------------ fleet wiring
def test_fleet_live_tune_pass_and_beat_push(tmp_path, monkeypatch):
    from tmr_tpu.obs import fleetobs
    from tmr_tpu.parallel.leases import LeasePolicy
    from tmr_tpu.serve.fleet import FleetWorker, ServeFleet, stub_engine

    monkeypatch.setenv("TMR_LIVE_TUNE", "1")
    fleetobs.configure(enabled=True)
    fleet = ServeFleet([SIZE], classes=1, policy=LeasePolicy(
        lease_ttl_s=2.0, hb_interval_s=0.1, check_interval_s=0.05,
        straggler_factor=0.0, max_reassigns=1_000_000_000,
        resource_fail_workers=1_000_000_000,
    ), check_interval_s=0.05)
    fleet.start()
    try:
        knob = "TMR_DECODER_IMPL"
        # nothing elected yet: the beat reply carries no election key
        reply = fleet._op_beat({"op": "beat", "worker": "w0",
                                "held": []})
        assert "live_tune" not in reply
        assert fleet.live_tune_pass(knob) is None
        # two workers' decisive-win counters fold in over beats; their
        # SUM reaches the threshold no single worker reached
        fo = fleet.fleet_obs
        fo.metrics.fold("w1", {
            "counters": {f"live_tune.win.{knob}=fused": 2},
            "gauges": {}, "histograms": {}})
        fo.metrics.fold("w2", {
            "counters": {f"live_tune.win.{knob}=fused": 1,
                         f"live_tune.win.{knob}=other": 9,
                         f"live_tune.refusal.{knob}=other": 1},
            "gauges": {}, "histograms": {}})
        doc = fleet.live_tune_pass(knob, wins_needed=3, geometry="g1")
        # the refused arm lost despite more wins — refusals outrank
        assert doc["winner"] == "fused" and doc["wins"] == 3
        assert doc["demoted"] is False and doc["epoch"] == 1
        reply = fleet._op_beat({"op": "beat", "worker": "w0",
                                "held": []})
        assert reply["live_tune"]["winner"] == "fused"
        # a live worker applies the election ONCE (epoch guard)
        got = []
        worker = FleetWorker(fleet.address, "w1", stub_engine())
        worker.on_live_tune(got.append)
        worker.start()
        try:
            deadline = time.monotonic() + 15.0
            while not got and time.monotonic() < deadline:
                time.sleep(0.02)
            assert got and got[0]["winner"] == "fused"
            time.sleep(0.35)  # several more beats: same epoch, no re-apply
            assert len(got) == 1
            # a fleet-wide demote anomaly revokes the election and bumps
            # the epoch — the worker applies the rollback verdict
            fo.watch._recent.append({
                "schema": "anomaly/v1", "anomaly": "fleet_mfu_drop",
                "message": "injected", "evidence": {"worker": "w1"},
                "ts": time.time()})
            doc = fleet.live_tune_pass(knob, wins_needed=3)
            assert doc["demoted"] is True and doc["winner"] is None
            assert doc["cause"] == "fleet_mfu_drop"
            assert doc["demoted_arm"] == "fused" and doc["epoch"] == 2
            deadline = time.monotonic() + 15.0
            while len(got) < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert len(got) == 2 and got[1]["demoted"] is True
            # the demoted arm can never win a later pass
            fo.watch._recent.clear()
            fo.metrics.fold("w1", {
                "counters": {f"live_tune.win.{knob}=fused": 50},
                "gauges": {}, "histograms": {}})
            doc = fleet.live_tune_pass(knob, wins_needed=3)
            assert doc["winner"] is None and "fused" in doc["demoted_arms"]
        finally:
            worker.stop()
    finally:
        fleet.close()
        fleetobs.configure(enabled=False)


def test_fleet_live_tune_pass_disabled_is_none(tmp_path):
    from tmr_tpu.parallel.leases import LeasePolicy
    from tmr_tpu.serve.fleet import ServeFleet

    fleet = ServeFleet([SIZE], classes=1, policy=LeasePolicy(
        lease_ttl_s=2.0, hb_interval_s=0.1, check_interval_s=0.05))
    fleet.start()
    try:
        # TMR_LIVE_TUNE unset AND no obs plane: the pass is inert
        assert fleet.live_tune_pass("TMR_DECODER_IMPL") is None
    finally:
        fleet.close()


# ------------------------------------------------- bench_trend age audit
def _write(path, doc):
    path.write_text(json.dumps(doc))


def test_bench_trend_carried_age_audit(tmp_path):
    from tmr_tpu.utils.bench_trend import collect_bench_trend

    _write(tmp_path / "BENCH_r01.json",
           {"n": 1, "rc": 0, "parsed": {"value": 10.0, "mfu": 0.08}})
    _write(tmp_path / "BENCH_r02.json",
           {"n": 2, "rc": 1, "parsed": {
               "value": 10.0, "mfu": 0.08, "carried": True,
               "error": "watchdog", "stale_hours": 30.0}})
    _write(tmp_path / "BENCH_r03.json",
           {"n": 3, "rc": 1, "parsed": {
               "value": 10.0, "mfu": 0.08, "carried": True,
               "error": "watchdog"}})  # no age stamp at all
    # default: the exact pre-audit shape (no new keys)
    doc = collect_bench_trend(str(tmp_path))
    assert validate_bench_trend(doc) == []
    assert "stale_carried" not in doc
    assert "carried_age_ok" not in doc["checks"]
    by_label = {r["label"]: r for r in doc["rounds"]}
    assert by_label["r02"]["stale_hours"] == 30.0
    assert by_label["r03"]["stale_hours"] is None
    # armed: the 30h round exceeds 24h, the unstamped one fails closed
    doc = collect_bench_trend(str(tmp_path), max_carried_age_h=24.0)
    assert validate_bench_trend(doc) == []
    assert doc["checks"]["carried_age_ok"] is False
    assert {r["label"] for r in doc["stale_carried"]} == {"r02", "r03"}
    # a generous bound passes the stamped round, still fails unstamped
    doc = collect_bench_trend(str(tmp_path), max_carried_age_h=48.0)
    assert {r["label"] for r in doc["stale_carried"]} == {"r03"}
    # all stamped within bound: the audit passes
    fresh = tmp_path / "fresh"
    fresh.mkdir()
    _write(fresh / "BENCH_r01.json",
           {"n": 1, "rc": 0, "parsed": {"value": 10.0, "mfu": 0.08}})
    _write(fresh / "BENCH_r02.json",
           {"n": 2, "rc": 1, "parsed": {
               "value": 10.0, "mfu": 0.08, "carried": True,
               "error": "watchdog", "stale_hours": 5.0}})
    doc = collect_bench_trend(str(fresh), max_carried_age_h=24.0)
    assert doc["checks"]["carried_age_ok"] is True
    assert doc["stale_carried"] == []


def test_bench_trend_cli_carried_age_gate(tmp_path):
    _write(tmp_path / "BENCH_r01.json",
           {"n": 1, "rc": 0, "parsed": {"value": 10.0, "mfu": 0.08}})
    _write(tmp_path / "BENCH_r02.json",
           {"n": 2, "rc": 1, "parsed": {
               "value": 10.0, "mfu": 0.08, "carried": True,
               "error": "watchdog", "stale_hours": 30.0}})
    cli = [sys.executable, os.path.join(REPO, "scripts",
                                        "bench_trend.py"),
           "--repo", str(tmp_path), "--max-carried-age-h", "24"]
    # default: a WARNING on stderr, stdout stays one JSON line, rc 0
    out = subprocess.run(cli, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0
    assert "stale" in out.stderr
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1 and json.loads(lines[0])
    # --strict-carried arms the gate: same document, rc 1
    out = subprocess.run(cli + ["--strict-carried"],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert json.loads(out.stdout.strip().splitlines()[0])


# --------------------------------------------------------------- validators
def test_live_tune_report_validator():
    good = {
        "schema": LIVE_TUNE_REPORT_SCHEMA, "device_kind": "cpu",
        "tuner": {"knob": "K", "incumbent": "a",
                  "counters": {"offers": 1},
                  "decisions": [
                      {"event": "shadow", "knob": "K", "arm": "b",
                       "ts": 1.0},
                      {"event": "demote", "knob": "K", "arm": "b",
                       "ts": 2.0, "cause": "mfu_drop"},
                  ]},
        "summary": {}, "checks": {"ok": True},
    }
    assert validate_live_tune_report(good) == []
    assert validate_live_tune_report(
        {"schema": LIVE_TUNE_REPORT_SCHEMA, "error": "wedge"}) == []
    bad = json.loads(json.dumps(good))
    bad["tuner"]["decisions"][0]["event"] = "guessed"
    del bad["tuner"]["decisions"][1]["cause"]
    bad["checks"] = {}
    problems = validate_live_tune_report(bad)
    assert any("event" in p for p in problems)
    assert any("cause" in p for p in problems)
    assert any("checks" in p for p in problems)


# -------------------------------------------------------- the full probe
def test_live_tune_probe_and_gate(tmp_path):
    """The acceptance proof end to end: the probe emits ONE validated
    line with every check true, and ``bench_trend --live-tune``
    rc-gates it (fail-closed on a broken file)."""
    report = tmp_path / "live_tune.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TMR_LIVE_TUNE", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "live_tune_probe.py"),
         "--out", str(report)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1  # ONE JSON line on stdout, warnings on stderr
    doc = json.loads(lines[0])
    assert validate_live_tune_report(doc) == []
    assert all(v is True for v in doc["checks"].values())
    assert doc["summary"]["shadow_fraction"] < 0.01
    assert doc["summary"]["promotion_speedup"] > 2.0
    assert doc["summary"]["demote_cause"] == "mfu_drop"
    gate = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "bench_trend.py"),
         "--live-tune", str(report)],
        capture_output=True, text=True, timeout=120,
    )
    assert gate.returncode == 0
    reduced = json.loads(gate.stdout.strip().splitlines()[0])
    assert reduced["checks"]["promoted_decisively"] is True
    # fail-closed: a check forced false flips the gate
    doc["checks"]["replay_consistent"] = False
    broken = tmp_path / "broken.json"
    broken.write_text(json.dumps(doc))
    gate = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "bench_trend.py"),
         "--live-tune", str(broken)],
        capture_output=True, text=True, timeout=120,
    )
    assert gate.returncode == 1
