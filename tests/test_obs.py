"""The unified telemetry subsystem (tmr_tpu/obs): span tracing, metrics
registry, compile-event accounting — plus the contracts it must keep with
the serving layer (ServeEngine.stats() shape-compatible with its PR 3
form, LRUCache counters registry-backed, PhaseTimer thread-safe).

The tracer's load-bearing contract is COST: disabled (TMR_TRACE=0) span
enter/exit must stay at a few hundred ns amortized — the serve/map/train
hot paths are instrumented unconditionally, so a regression here taxes
every request in production.
"""

import json
import threading
import time

import numpy as np
import pytest

from tmr_tpu import obs


@pytest.fixture(autouse=True)
def _tracing_off_after():
    """Every test leaves tracing disabled and the rings drained — obs
    state is process-global, test order must not matter."""
    yield
    obs.configure(enabled=False, annotate=True)
    obs.clear()


@pytest.fixture(scope="module")
def pred64():
    """One tiny Predictor for the integration tests (64² keeps the jitted
    init + backbone compile to seconds on CPU)."""
    from tmr_tpu.config import preset
    from tmr_tpu.inference import Predictor

    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=64,
                 compute_dtype="float32", batch_size=1)
    pred = Predictor(cfg)
    pred.init_params(seed=0, image_size=64)
    return pred


# ---------------------------------------------------------------- metrics
def test_counter_gauge_histogram_basics():
    reg = obs.MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    assert reg.counter("c").value == 5
    reg.gauge("g").set(2.5)
    assert reg.gauge("g").value == 2.5
    h = reg.histogram("h")
    for v in (0.001, 0.002, 0.004, 0.008):
        h.observe(v)
    assert h.count == 4 and h.min == 0.001 and h.max == 0.008
    assert abs(h.sum - 0.015) < 1e-12
    assert 0.0 < h.quantile(0.5) <= h.quantile(0.99) <= 0.008


def test_registry_snapshot_is_valid_metrics_report():
    from tmr_tpu.diagnostics import (
        METRICS_REPORT_SCHEMA,
        validate_metrics_report,
    )

    reg = obs.MetricsRegistry()
    reg.counter("serve.submitted").inc(3)
    reg.gauge("pool.depth").set(2)
    reg.histogram("lat").observe(0.01)
    snap = reg.snapshot()
    assert snap["schema"] == METRICS_REPORT_SCHEMA
    assert validate_metrics_report(snap) == []
    assert snap["counters"]["serve.submitted"] == 3
    hist = snap["histograms"]["lat"]
    assert len(hist["counts"]) == len(hist["buckets_le"]) + 1
    assert {"p50", "p95", "p99"} <= set(hist)
    # snapshot round-trips JSON (the report-attachment contract)
    assert json.loads(json.dumps(snap)) == snap


def test_registry_rejects_instrument_kind_clash():
    reg = obs.MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")
    with pytest.raises(TypeError):
        reg.histogram("x")


def test_validate_metrics_report_rejects_broken_docs():
    from tmr_tpu.diagnostics import validate_metrics_report

    good = obs.MetricsRegistry().snapshot()
    assert validate_metrics_report(good) == []
    assert validate_metrics_report({"schema": "bogus"})
    bad = obs.MetricsRegistry()
    bad.histogram("h").observe(1.0)
    doc = bad.snapshot()
    doc["histograms"]["h"]["counts"] = [1]  # wrong length
    assert any("overflow" in p for p in validate_metrics_report(doc))
    doc2 = obs.MetricsRegistry().snapshot()
    doc2["counters"] = {"c": "three"}
    assert any("not a number" in p for p in validate_metrics_report(doc2))


def test_histogram_merge_and_reset():
    a = obs.Histogram()
    b = obs.Histogram()
    for v in (0.001, 0.01):
        a.observe(v)
    b.observe(0.1)
    a.merge(b)
    assert a.count == 3 and a.max == 0.1
    with pytest.raises(ValueError):
        a.merge(obs.Histogram(buckets=(1.0, 2.0)))
    a.reset()
    assert a.count == 0 and a.min is None


# ---------------------------------------------------------------- tracing
def test_disabled_span_is_noop_and_cheap():
    """TMR_TRACE=0 contract: span() returns the shared no-op (no
    allocation, nothing recorded) at a few hundred ns amortized."""
    obs.configure(enabled=False)
    obs.clear()
    s1 = obs.span("a")
    s2 = obs.span("b", key="value")
    assert s1 is s2  # the singleton: nothing allocated per call
    with obs.span("x"):
        pass
    assert obs.spans() == []

    span = obs.span
    best = float("inf")
    for _ in range(5):
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            with span("x"):
                pass
        best = min(best, (time.perf_counter() - t0) / n)
    assert best * 1e9 < 1500, f"disabled span cost {best * 1e9:.0f} ns"


def test_spans_nest_within_and_across_threads():
    obs.configure(enabled=True, annotate=False)
    obs.clear()
    with obs.span("outer", role="parent"):
        with obs.span("inner"):
            pass

    def worker():
        with obs.span("w_outer", trace_id="req-42"):
            with obs.span("w_inner"):
                pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    by = {s["name"]: s for s in obs.spans()}
    assert set(by) == {"outer", "inner", "w_outer", "w_inner"}
    # nesting: child points at parent, inherits its trace id
    assert by["inner"]["parent"] == by["outer"]["span"]
    assert by["inner"]["trace"] == by["outer"]["trace"]
    # explicit trace ids propagate to children; threads have distinct tids
    assert by["w_inner"]["trace"] == "req-42"
    assert by["w_outer"]["tid"] != by["outer"]["tid"]
    assert by["outer"]["attrs"] == {"role": "parent"}
    # thread rings don't leak nesting across threads
    assert by["w_outer"]["parent"] == 0


def test_chrome_trace_roundtrips_json():
    obs.configure(enabled=True, annotate=False)
    obs.clear()
    with obs.span("stage_a"):
        pass
    obs.add_span("stage_b", 10.0, 10.5, trace_id="tid", custom="attr")
    doc = json.loads(json.dumps(obs.chrome_trace()))
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(events) == 2
    by = {e["name"]: e for e in events}
    assert by["stage_b"]["dur"] == pytest.approx(0.5e6)  # microseconds
    assert by["stage_b"]["args"]["trace"] == "tid"
    assert by["stage_b"]["args"]["custom"] == "attr"
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert any(e["name"] == "thread_name" for e in meta)


def test_ring_buffer_bounds_memory():
    obs.configure(enabled=True, annotate=False, ring=16)
    try:
        obs.clear()
        # a fresh thread gets the new ring size
        def worker():
            for i in range(50):
                with obs.span(f"s{i}"):
                    pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        names = [s["name"] for s in obs.spans()]
        assert len(names) == 16  # oldest rolled off
        assert names[-1] == "s49" and "s0" not in names
        assert obs.dropped_spans() >= 34
    finally:
        obs.configure(ring=8192)


def test_clear_while_recording_never_raises():
    """clear() (any thread, the drain-before-measure protocol) racing a
    recording thread must never crash the recorder — a pipeline thread
    dying on telemetry would hang every pending request."""
    obs.configure(enabled=True, annotate=False, ring=16)
    try:
        obs.clear()
        errors = []

        def recorder():
            try:
                for i in range(5000):
                    obs.add_span("race", 0.0, 1.0)
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        t = threading.Thread(target=recorder)
        t.start()
        for _ in range(2000):
            obs.clear()
        t.join()
        assert errors == []
    finally:
        obs.configure(ring=8192)


def test_trace_annotation_enters_jax_region():
    """annotate=True mirrors spans into jax.profiler.TraceAnnotation —
    entering must compose with jit without error (the xprof alignment
    path; content is only observable in a real capture)."""
    import jax
    import jax.numpy as jnp

    obs.configure(enabled=True, annotate=True)
    obs.clear()
    f = jax.jit(lambda x: x * 2)
    with obs.span("jitted_region"):
        out = f(jnp.arange(4.0))
    assert out.shape == (4,)
    assert [s["name"] for s in obs.spans()] == ["jitted_region"]


# ---------------------------------------------------------------- compile
def test_track_compile_records_cold_then_key_change():
    from tmr_tpu.diagnostics import COMPILE_EVENT_CAUSES

    obs.drain_compile_events()
    kind = "test_kind_obs_unit"
    f1 = obs.track_compile(lambda x: x + 1, kind, ("k", 1),
                           bucket={"capacity": 9})
    assert f1(1) == 2 and f1(5) == 6  # second call: no second event
    f2 = obs.track_compile(lambda x: x * 2, kind, ("k", 2))
    assert f2(3) == 6
    # a SECOND instance re-compiling an already-seen (kind, key) is
    # expected warmup, not a storm: cause stays "cold"
    f3 = obs.track_compile(lambda x: x - 1, kind, ("k", 1))
    assert f3(1) == 0
    events = [e for e in obs.compile_events() if e["kind"] == kind]
    assert [e["cause"] for e in events] == ["cold", "key-change", "cold"]
    assert all(e["cause"] in COMPILE_EVENT_CAUSES for e in events)
    assert events[0]["key"] == repr(("k", 1))
    assert events[0]["bucket"] == {"capacity": 9}
    assert all(e["wall_s"] >= 0 for e in events)
    reg = obs.get_registry()
    assert reg.counter("compile.total").value >= 2
    # drain clears the log but not the cold/key-change kind memory
    assert obs.drain_compile_events()
    assert obs.compile_events() == []


def test_predictor_compile_cache_reports_events(pred64):
    """Integration: a Predictor _compiled miss + first call records one
    event; a cache hit records none (the no-recompile pin's telemetry
    side). Uses the backbone-only program — the cheapest real compile."""
    pred = pred64
    obs.drain_compile_events()
    bb = pred._get_backbone_fn()
    img = np.zeros((1, 64, 64, 3), np.float32)
    np.asarray(bb(pred.params, img))
    events = [e for e in obs.compile_events() if e["kind"] == "backbone"]
    assert len(events) == 1 and events[0]["wall_s"] > 0
    # cache hit: same wrapped fn, no new event
    assert pred._get_backbone_fn() is bb
    np.asarray(bb(pred.params, img))
    assert len([e for e in obs.compile_events()
                if e["kind"] == "backbone"]) == 1


# --------------------------------------------------------- phase timer
def test_phase_timer_is_thread_safe():
    from tmr_tpu.utils.profiling import PhaseTimer

    t = PhaseTimer()

    def worker():
        for _ in range(200):
            with t.phase("hot"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t.counts["hot"] == 800  # no lost updates


def test_phase_timer_feeds_registry():
    from tmr_tpu.utils.profiling import PhaseTimer

    reg = obs.MetricsRegistry()
    t = PhaseTimer()
    for _ in range(3):
        with t.phase("step"):
            pass
    rep = t.report(registry=reg)
    assert "PHASE" in rep and "step" in rep
    snap = reg.snapshot()
    assert snap["histograms"]["time/step"]["count"] == 3
    assert t.as_dict() == {"time/step": pytest.approx(t.totals["step"])}


def test_phase_timer_opens_spans_when_tracing():
    from tmr_tpu.utils.profiling import PhaseTimer

    obs.configure(enabled=True, annotate=False)
    obs.clear()
    t = PhaseTimer(span_prefix="train.")
    with t.phase("step"):
        pass
    assert [s["name"] for s in obs.spans()] == ["train.step"]


# ------------------------------------------------- serving-layer contracts
def test_lru_cache_counters_live_in_registry():
    from tmr_tpu.serve import LRUCache

    reg = obs.MetricsRegistry()
    c = LRUCache(2, registry=reg, name="serve.cache.result")
    c.put("a", 1)
    c.get("a")
    c.get("missing")
    snap = reg.snapshot()
    assert snap["counters"]["serve.cache.result.hits"] == 1
    assert snap["counters"]["serve.cache.result.misses"] == 1
    assert snap["counters"]["serve.cache.result.inserts"] == 1
    # the stats() shape is byte-for-byte the PR 3 one
    assert set(c.stats()) == {"capacity", "size", "hits", "misses",
                              "evictions", "inserts", "hit_rate"}


def test_serve_engine_stats_shape_is_pr3_compatible(pred64):
    """ServeEngine.stats() must keep its exact PR 3 shape (keys and value
    types) now that it reads from the metrics registry — consumers
    (serve_bench, dashboards) parse it as-is."""
    from tmr_tpu.serve import ServeEngine

    with ServeEngine(pred64, batch=2, max_wait_ms=5) as eng:
        stats = eng.stats()
        counters = eng.counters
        snap = eng.metrics_snapshot()
    assert set(stats) == {
        "submitted", "completed", "errors", "rejected", "coalesced",
        "batches", "padded_slots", "batch_fallbacks", "heads_batches",
        "feature_fills", "batch_occupancy", "pending", "result_cache",
        "feature_cache", "devices", "per_device_batches", "max_wait_ms",
        "batch_bounds", "donate",
    }
    for key in ("submitted", "completed", "errors", "rejected",
                "coalesced", "batches", "padded_slots", "batch_fallbacks",
                "heads_batches", "feature_fills"):
        assert isinstance(stats[key], int), key
    for which in ("result_cache", "feature_cache"):
        assert set(stats[which]) == {"capacity", "size", "hits", "misses",
                                     "evictions", "inserts", "hit_rate"}
    assert isinstance(stats["batch_occupancy"], dict)
    assert isinstance(stats["devices"], list)
    assert isinstance(stats["donate"], bool)
    # the counters dict attribute keeps its PR 3 keys
    assert set(counters) == {
        "submitted", "completed", "errors", "rejected", "coalesced",
        "batches", "padded_slots", "batch_fallbacks", "heads_batches",
        "feature_fills",
    }
    # and the same numbers travel in the engine's metrics_report/v1
    from tmr_tpu.diagnostics import validate_metrics_report

    assert validate_metrics_report(snap) == []
    assert snap["counters"]["serve.submitted"] == stats["submitted"]
    assert "serve.cache.result.hits" in snap["counters"]
