"""Test env: force CPU JAX with 8 virtual devices (SURVEY.md §4).

Must run before any `import jax` — pytest imports conftest first. The 8
virtual devices stand in for a TPU slice so every sharding / collective path
(the DDP + mapper/reducer replacements) is exercised in CI without hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon sitecustomize registers the TPU backend at interpreter startup and
# force-sets jax_platforms="axon,cpu"; backends initialize lazily, so pinning
# the config here (before any device access) reliably lands tests on CPU.
jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Persistent XLA compilation cache for the suite (the same
# utils/cache.enable_compilation_cache every CLI already calls;
# TMR_COMPILATION_CACHE=0 still opts out, failures degrade to a
# warning). The tier-1 run sits within seconds of its hard timeout and
# most of that wall is XLA recompiling the same tiny-geometry programs
# every run — a warm cache cuts repeat runs far below the limit.
from tmr_tpu.utils.cache import enable_compilation_cache  # noqa: E402

enable_compilation_cache()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute test (real-width compiles, depth-32 goldens, "
        "e2e training); excluded from the fast dev loop",
    )
    config.addinivalue_line(
        "markers",
        "fast: auto-applied complement of slow — `pytest -m fast` is the "
        "sub-2-minute dev loop, the full (unmarked) run is CI",
    )


def pytest_collection_modifyitems(config, items):
    """Every test not marked slow is fast: `-m fast` and `-m "not slow"`
    select the identical set, so the dev loop works with either spelling
    (VERDICT r4 #8 asks for `pytest -m fast` under 120s)."""
    import pytest

    for item in items:
        if item.get_closest_marker("slow") is None:
            item.add_marker(pytest.mark.fast)
