"""Test env: force CPU JAX with 8 virtual devices (SURVEY.md §4).

Must run before any `import jax` — pytest imports conftest first. The 8
virtual devices stand in for a TPU slice so every sharding / collective path
(the DDP + mapper/reducer replacements) is exercised in CI without hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon sitecustomize registers the TPU backend at interpreter startup and
# force-sets jax_platforms="axon,cpu"; backends initialize lazily, so pinning
# the config here (before any device access) reliably lands tests on CPU.
jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Persistent XLA compilation cache for the suite (the same
# utils/cache.enable_compilation_cache every CLI already calls;
# TMR_COMPILATION_CACHE=0 still opts out, failures degrade to a
# warning). The tier-1 run sits within seconds of its hard timeout and
# most of that wall is XLA recompiling the same tiny-geometry programs
# every run — a warm cache cuts repeat runs far below the limit.
from tmr_tpu.utils.cache import enable_compilation_cache  # noqa: E402

enable_compilation_cache()


# ---------------------------------------------------------------------
# Tier-1 runtime budget guard: the verify command runs the suite under a
# hard `timeout 870`, and the suite already consumes most of it — a new
# test that quietly adds a minute fails EVERY future session with an
# opaque timeout instead of a diagnosis. The guard records per-test
# durations and, when the session's wall clock projects past the budget,
# warns on stderr (non-fatal) naming the slowest tests so the costly
# addition is attributable.

import time as _time

#: the tier-1 hard timeout (ROADMAP.md verify command) and the fraction
#: of it that triggers the warning — at 92% a normal run variance (~5%)
#: can already push past the limit
_TIER1_BUDGET_S = 870.0
_TIER1_WARN_FRACTION = 0.92

_SESSION_T0 = _time.time()
_TEST_DURATIONS: dict = {}


def pytest_runtest_logreport(report):
    if report.duration:
        _TEST_DURATIONS[report.nodeid] = (
            _TEST_DURATIONS.get(report.nodeid, 0.0) + report.duration
        )


def pytest_sessionfinish(session, exitstatus):
    import sys

    total = _time.time() - _SESSION_T0
    if total < _TIER1_WARN_FRACTION * _TIER1_BUDGET_S:
        return
    slowest = sorted(_TEST_DURATIONS.items(), key=lambda kv: -kv[1])[:10]
    lines = [
        f"\n[tier1-budget] WARNING: suite wall {total:.0f}s is "
        f">= {_TIER1_WARN_FRACTION:.0%} of the {_TIER1_BUDGET_S:.0f}s "
        "tier-1 timeout — slow-mark or shrink the heaviest tests "
        "before the next one times the whole suite out.",
        "[tier1-budget] slowest tests this session:",
    ]
    lines += [f"[tier1-budget]   {d:7.1f}s  {nodeid}"
              for nodeid, d in slowest]
    print("\n".join(lines), file=sys.stderr, flush=True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute test (real-width compiles, depth-32 goldens, "
        "e2e training); excluded from the fast dev loop",
    )
    config.addinivalue_line(
        "markers",
        "fast: auto-applied complement of slow — `pytest -m fast` is the "
        "sub-2-minute dev loop, the full (unmarked) run is CI",
    )


def pytest_collection_modifyitems(config, items):
    """Every test not marked slow is fast: `-m fast` and `-m "not slow"`
    select the identical set, so the dev loop works with either spelling
    (VERDICT r4 #8 asks for `pytest -m fast` under 120s)."""
    import pytest

    for item in items:
        if item.get_closest_marker("slow") is None:
            item.add_marker(pytest.mark.fast)
