"""GPipe pipeline parallelism (tmr_tpu/parallel/pipeline.py): pipelined
SamViT == dense SamViT, forward and backward, on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tmr_tpu.models.vit import SamViT
from tmr_tpu.parallel.mesh import make_mesh
from tmr_tpu.parallel.pipeline import (
    pipeline_vit_apply,
    stack_stage_params,
    stage_sharding,
    stage_split,
)

TINY = dict(
    embed_dim=32,
    depth=4,
    num_heads=2,
    global_attn_indexes=(1, 3),  # 2 stages x (1 windowed + 1 global)
    patch_size=8,
    window_size=3,
    out_chans=16,
    pretrain_img_size=32,
)


def _model_and_params(seed=0):
    vit = SamViT(**TINY)
    x = jnp.asarray(
        np.random.default_rng(seed).standard_normal((4, 32, 32, 3)),
        jnp.float32,
    )
    params = vit.init(jax.random.key(0), x)["params"]
    # randomize the zero-init rel-pos/pos tables so parity is non-trivial
    leaves, treedef = jax.tree_util.tree_flatten(params)
    rng = np.random.default_rng(3)
    leaves = [
        jnp.asarray(rng.standard_normal(l.shape) * 0.05, l.dtype)
        for l in leaves
    ]
    return vit, jax.tree_util.tree_unflatten(treedef, leaves), x


def test_stage_split_validates_homogeneity():
    assert stage_split(12, (2, 5, 8, 11)) == (4, 3)
    assert stage_split(32, (7, 15, 23, 31)) == (4, 8)
    with pytest.raises(ValueError):
        stage_split(12, (1, 5, 8, 11))  # stage 0 not closed by its global
    with pytest.raises(ValueError):
        stage_split(10, (2, 5, 8))  # not divisible
    with pytest.raises(ValueError):
        stage_split(12, ())


@pytest.mark.parametrize("microbatches", [1, 2, 4])
def test_pipeline_forward_matches_dense(microbatches):
    vit, params, x = _model_and_params()
    want = vit.apply({"params": params}, x)

    mesh = make_mesh((2,), axis_names=("pipe",),
                     devices=jax.devices()[:2])
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(
            vit, p, v, mesh, microbatches=microbatches
        )
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_with_sharded_stacked_params():
    """Stage params placed on their pipe devices via stage_sharding (the
    deployment layout: each device holds ONLY its stage) still reproduce
    the dense forward."""
    vit, params, x = _model_and_params(seed=1)
    want = vit.apply({"params": params}, x)

    mesh = make_mesh((2,), axis_names=("pipe",), devices=jax.devices()[:2])
    stacked = stack_stage_params(params, vit.depth, vit.global_attn_indexes)
    stacked = jax.device_put(stacked, stage_sharding(stacked, mesh))
    pp_params = {
        k: v for k, v in params.items() if not k.startswith("blocks_")
    }
    pp_params["stages"] = stacked
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(vit, p, v, mesh, microbatches=2)
    )(pp_params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_grads_match_dense():
    """The scan-based schedule is differentiable: parameter gradients of
    the pipelined encoder equal the dense ones (the pp training path)."""
    vit, params, x = _model_and_params(seed=2)
    mesh = make_mesh((2,), axis_names=("pipe",), devices=jax.devices()[:2])

    def loss_dense(p):
        return (vit.apply({"params": p}, x) ** 2).mean()

    def loss_pipe(p):
        return (
            pipeline_vit_apply(vit, p, x, mesh, microbatches=2) ** 2
        ).mean()

    g_dense = jax.jit(jax.grad(loss_dense))(params)
    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4
        ),
        g_dense, g_pipe,
    )


def test_pipeline_four_stages():
    """A 4-stage split on the 8-device mesh (vit_b-shaped global spacing)."""
    cfg = dict(TINY, depth=8, global_attn_indexes=(1, 3, 5, 7))
    vit = SamViT(**cfg)
    x = jnp.asarray(
        np.random.default_rng(4).standard_normal((4, 32, 32, 3)), jnp.float32
    )
    params = vit.init(jax.random.key(1), x)["params"]
    want = vit.apply({"params": params}, x)
    mesh = make_mesh((4,), axis_names=("pipe",), devices=jax.devices()[:4])
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(vit, p, v, mesh, microbatches=2)
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_non_native_grid_interpolates_rel_pos():
    """Runtime grid != pretrain grid (the 1536-bucket situation): parameter
    shapes stay at the pretrain grid and get_rel_pos interpolates — the
    pipelined blocks must match dense there too (regression: the stage
    blocks once took the runtime grid and mis-shaped the tables)."""
    cfg = dict(TINY, pretrain_img_size=16)  # pretrain grid 2, runtime grid 4
    vit = SamViT(**cfg)
    x = jnp.asarray(
        np.random.default_rng(6).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    params = vit.init(jax.random.key(2), x)["params"]
    want = vit.apply({"params": params}, x)
    mesh = make_mesh((2,), axis_names=("pipe",), devices=jax.devices()[:2])
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(vit, p, v, mesh, microbatches=2)
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_composes_with_data_parallelism():
    """pp x dp in one ('pipe','data') mesh: each device pair pipelines its
    batch shard; output matches dense and keeps the data sharding."""
    vit, params, x = _model_and_params(seed=7)  # batch 4
    want = vit.apply({"params": params}, x)
    mesh = Mesh(
        np.array(jax.devices()[:4]).reshape(2, 2), ("pipe", "data")
    )
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(
            vit, p, v, mesh, microbatches=2, data_axis="data"
        )
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_refuses_seq_mesh_vit():
    # _stage_blocks rebuilds Blocks without forwarding seq_mesh/batch_axis;
    # silently dropping a ring/sequence-parallel config is worse than
    # refusing (advisor r3)
    mesh = make_mesh((2,), ("pipe",), devices=jax.devices()[:2])
    vit = SamViT(**TINY, seq_mesh=mesh)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = SamViT(**TINY).init(jax.random.key(0), x)["params"]
    with pytest.raises(ValueError, match="seq_mesh"):
        pipeline_vit_apply(vit, params, x, mesh, microbatches=2)
