"""GPipe pipeline parallelism (tmr_tpu/parallel/pipeline.py): pipelined
SamViT == dense SamViT, forward and backward, on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tmr_tpu.models.vit import SamViT
from tmr_tpu.parallel.mesh import make_mesh
from tmr_tpu.parallel.pipeline import (
    pipeline_vit_apply,
    stack_stage_params,
    stage_sharding,
    stage_split,
)

TINY = dict(
    embed_dim=32,
    depth=4,
    num_heads=2,
    global_attn_indexes=(1, 3),  # 2 stages x (1 windowed + 1 global)
    patch_size=8,
    window_size=3,
    out_chans=16,
    pretrain_img_size=32,
)



pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

def _model_and_params(seed=0):
    vit = SamViT(**TINY)
    x = jnp.asarray(
        np.random.default_rng(seed).standard_normal((4, 32, 32, 3)),
        jnp.float32,
    )
    params = vit.init(jax.random.key(0), x)["params"]
    # randomize the zero-init rel-pos/pos tables so parity is non-trivial
    leaves, treedef = jax.tree_util.tree_flatten(params)
    rng = np.random.default_rng(3)
    leaves = [
        jnp.asarray(rng.standard_normal(l.shape) * 0.05, l.dtype)
        for l in leaves
    ]
    return vit, jax.tree_util.tree_unflatten(treedef, leaves), x


def test_stage_split_validates_homogeneity():
    assert stage_split(12, (2, 5, 8, 11)) == (4, 3)
    assert stage_split(32, (7, 15, 23, 31)) == (4, 8)
    with pytest.raises(ValueError):
        stage_split(12, (1, 5, 8, 11))  # stage 0 not closed by its global
    with pytest.raises(ValueError):
        stage_split(10, (2, 5, 8))  # not divisible
    with pytest.raises(ValueError):
        stage_split(12, ())


@pytest.mark.parametrize("microbatches", [1, 2, 4])
def test_pipeline_forward_matches_dense(microbatches):
    vit, params, x = _model_and_params()
    want = vit.apply({"params": params}, x)

    mesh = make_mesh((2,), axis_names=("pipe",),
                     devices=jax.devices()[:2])
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(
            vit, p, v, mesh, microbatches=microbatches
        )
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_with_sharded_stacked_params():
    """Stage params placed on their pipe devices via stage_sharding (the
    deployment layout: each device holds ONLY its stage) still reproduce
    the dense forward."""
    vit, params, x = _model_and_params(seed=1)
    want = vit.apply({"params": params}, x)

    mesh = make_mesh((2,), axis_names=("pipe",), devices=jax.devices()[:2])
    stacked = stack_stage_params(params, vit.depth, vit.global_attn_indexes)
    stacked = jax.device_put(stacked, stage_sharding(stacked, mesh))
    pp_params = {
        k: v for k, v in params.items() if not k.startswith("blocks_")
    }
    pp_params["stages"] = stacked
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(vit, p, v, mesh, microbatches=2)
    )(pp_params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_grads_match_dense():
    """The scan-based schedule is differentiable: parameter gradients of
    the pipelined encoder equal the dense ones (the pp training path)."""
    vit, params, x = _model_and_params(seed=2)
    mesh = make_mesh((2,), axis_names=("pipe",), devices=jax.devices()[:2])

    def loss_dense(p):
        return (vit.apply({"params": p}, x) ** 2).mean()

    def loss_pipe(p):
        return (
            pipeline_vit_apply(vit, p, x, mesh, microbatches=2) ** 2
        ).mean()

    g_dense = jax.jit(jax.grad(loss_dense))(params)
    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4
        ),
        g_dense, g_pipe,
    )


def test_pipeline_four_stages():
    """A 4-stage split on the 8-device mesh (vit_b-shaped global spacing)."""
    cfg = dict(TINY, depth=8, global_attn_indexes=(1, 3, 5, 7))
    vit = SamViT(**cfg)
    x = jnp.asarray(
        np.random.default_rng(4).standard_normal((4, 32, 32, 3)), jnp.float32
    )
    params = vit.init(jax.random.key(1), x)["params"]
    want = vit.apply({"params": params}, x)
    mesh = make_mesh((4,), axis_names=("pipe",), devices=jax.devices()[:4])
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(vit, p, v, mesh, microbatches=2)
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_non_native_grid_interpolates_rel_pos():
    """Runtime grid != pretrain grid (the 1536-bucket situation): parameter
    shapes stay at the pretrain grid and get_rel_pos interpolates — the
    pipelined blocks must match dense there too (regression: the stage
    blocks once took the runtime grid and mis-shaped the tables)."""
    cfg = dict(TINY, pretrain_img_size=16)  # pretrain grid 2, runtime grid 4
    vit = SamViT(**cfg)
    x = jnp.asarray(
        np.random.default_rng(6).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    params = vit.init(jax.random.key(2), x)["params"]
    want = vit.apply({"params": params}, x)
    mesh = make_mesh((2,), axis_names=("pipe",), devices=jax.devices()[:2])
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(vit, p, v, mesh, microbatches=2)
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_composes_with_data_parallelism():
    """pp x dp in one ('pipe','data') mesh: each device pair pipelines its
    batch shard; output matches dense and keeps the data sharding."""
    vit, params, x = _model_and_params(seed=7)  # batch 4
    want = vit.apply({"params": params}, x)
    mesh = Mesh(
        np.array(jax.devices()[:4]).reshape(2, 2), ("pipe", "data")
    )
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(
            vit, p, v, mesh, microbatches=2, data_axis="data"
        )
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pipeline_refuses_seq_mesh_vit():
    # _stage_blocks rebuilds Blocks without forwarding seq_mesh/batch_axis;
    # silently dropping a ring/sequence-parallel config is worse than
    # refusing (advisor r3)
    mesh = make_mesh((2,), ("pipe",), devices=jax.devices()[:2])
    vit = SamViT(**TINY, seq_mesh=mesh)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = SamViT(**TINY).init(jax.random.key(0), x)["params"]
    with pytest.raises(ValueError, match="seq_mesh"):
        pipeline_vit_apply(vit, params, x, mesh, microbatches=2)


def test_pp_train_step_matches_dense():
    """The pipeline-parallel train step (stage-sharded params + optimizer
    moments, GPipe encoder island) must match the dense train step: same
    loss, same updated params."""
    from tmr_tpu.config import Config
    from tmr_tpu.models.matching_net import MatchingNet
    from tmr_tpu.parallel.pipeline import (
        create_pp_train_state,
        make_pp_train_step,
        pp_state_sharding,
        stack_backbone_params,
        unstack_backbone_params,
    )
    from tmr_tpu.train.state import create_train_state, make_train_step

    cfg = Config(
        backbone="resnet50", emb_dim=16, fusion=True,
        positive_threshold=0.5, negative_threshold=0.5,
        lr=1e-3, lr_backbone=1e-3, compute_dtype="float32",
    )
    vit = SamViT(**TINY)
    model = MatchingNet(backbone=vit, emb_dim=16, fusion=True,
                        template_capacity=5)
    rng = np.random.default_rng(0)
    b = 4
    batch = {
        "image": jnp.asarray(rng.standard_normal((b, 32, 32, 3)), jnp.float32),
        "exemplars": jnp.asarray(
            np.tile([[[0.3, 0.3, 0.5, 0.55]]], (b, 1, 1)), jnp.float32),
        "gt_boxes": jnp.asarray(
            np.tile([[[0.3, 0.3, 0.5, 0.55]]], (b, 1, 1)), jnp.float32),
        "gt_valid": jnp.ones((b, 1), bool),
    }

    dense_state = create_train_state(
        model, cfg, jax.random.key(0), batch["image"], batch["exemplars"],
        steps_per_epoch=10,
    )
    dense_new, dense_losses = jax.jit(make_train_step(model, cfg))(
        dense_state, batch
    )

    mesh = make_mesh((2, 2), ("data", "pipe"))
    pp_state = create_pp_train_state(
        model, cfg, jax.random.key(0), batch["image"], batch["exemplars"],
        steps_per_epoch=10,
    )
    # same init: the stacked tree must be the dense init re-laid-out
    want = stack_backbone_params(dense_state.params, vit)
    got_l, want_l = jax.tree.leaves(pp_state.params), jax.tree.leaves(want)
    for g, w in zip(got_l, want_l):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6)

    with jax.sharding.set_mesh(mesh):
        sharding = pp_state_sharding(pp_state, mesh)
        pp_state = jax.device_put(pp_state, sharding)
        step = jax.jit(
            make_pp_train_step(model, cfg, mesh, data_axis="data"),
            out_shardings=(sharding, None),
        )
        pp_new, pp_losses = step(pp_state, jax.device_put(
            batch, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("data"))))
        jax.block_until_ready(pp_new.params)

    assert np.isclose(
        float(pp_losses["loss"]), float(dense_losses["loss"]), rtol=1e-4
    )
    un = unstack_backbone_params(pp_new.params, vit)
    for path_leaf in (
        ("backbone", "blocks_0", "attn", "qkv", "kernel"),
        ("backbone", "blocks_3", "mlp", "lin2", "kernel"),
        ("input_proj_0", "kernel"),
    ):
        a = un
        d = dense_new.params
        for k in path_leaf:
            a, d = a[k], d[k]
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(d), rtol=2e-4, atol=1e-6
        )


def test_pipeline_honors_remat():
    """--remat_backbone must hold inside the island (same silent-drop class
    as seq_mesh): remat'd pipelined forward == dense forward."""
    vit, params, x = _model_and_params(seed=9)
    rvit = vit.clone(remat=True)
    want = rvit.apply({"params": params}, x)
    mesh = make_mesh((2,), axis_names=("pipe",), devices=jax.devices()[:2])
    got = jax.jit(
        lambda p, v: pipeline_vit_apply(rvit, p, v, mesh, microbatches=2)
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )
