"""The chaos gauntlets (scripts/chaos_probe.py) must pass on tier-1:

- single-process: every injected fault retried-to-success or
  quarantined with a recorded cause, tables and feature bytes identical
  to the fault-free run, crash+resume byte-identical;
- elastic (--elastic): 3 workers over 8 shards with one kill -9'd
  mid-shard and one SIGSTOPped past the heartbeat window — run
  completes, table byte-identical to the single-process run, the
  elastic_report/v1 reconciles exactly, and the SIGSTOP scenario ends
  in >= 1 fenced-commit rejection."""

import importlib.util
import os

import pytest

from tmr_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_schedule():
    faults.clear()
    yield
    faults.clear()


def _load_probe():
    spec = importlib.util.spec_from_file_location(
        "chaos_probe", os.path.join(REPO, "scripts", "chaos_probe.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_probe_passes(tmp_path):
    mod = _load_probe()
    rc = mod.main(["--work_dir", str(tmp_path / "chaos")])
    assert rc == 0


def test_elastic_chaos_gauntlet_passes(tmp_path):
    mod = _load_probe()
    rc = mod.main(["--elastic", "--work_dir", str(tmp_path / "elastic")])
    assert rc == 0
