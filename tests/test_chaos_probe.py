"""The chaos gauntlet (scripts/chaos_probe.py) must pass on tier-1: every
injected fault retried-to-success or quarantined with a recorded cause,
tables and feature bytes identical to the fault-free run, crash+resume
byte-identical."""

import importlib.util
import os

import pytest

from tmr_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_schedule():
    faults.clear()
    yield
    faults.clear()


def test_chaos_probe_passes(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "chaos_probe", os.path.join(REPO, "scripts", "chaos_probe.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--work_dir", str(tmp_path / "chaos")])
    assert rc == 0
