"""The TMR_FLEET_OBS fleet observability plane
(tmr_tpu/obs/fleetobs.py) and its wiring: disabled-mode byte-identity
pins (no ``ctx``/``obs`` wire keys, beat replies and state() exactly
the PR 18 shape), the enabled cross-process round trip (front-door
trace ids, worker serve spans coming home on beats, exact
sum-of-deltas reconciliation after a clean bye — ServeFleet AND the
elastic coordinator), wire back-compat in both directions, the
clock-offset stitcher, the fleet HealthWatch anomaly vocabulary, the
beat-attachment error counter, the ``bench_trend --fleet-obs`` rc
gate, and the full scripts/fleet_obs_probe.py proof."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from tmr_tpu.diagnostics import (
    FLEET_ANOMALY_KINDS,
    validate_anomaly,
    validate_fleet_obs_report,
    validate_metrics_report,
)
from tmr_tpu.obs import fleetobs, tracing
from tmr_tpu.obs import metrics as obsmetrics
from tmr_tpu.parallel.leases import LeasePolicy
from tmr_tpu.serve.fleet import FleetWorker, ServeFleet, stub_engine
from tmr_tpu.utils import faults
from tmr_tpu.utils.bench_trend import read_fleet_obs_report

SIZE = 32
EX = np.asarray([[0.4, 0.4, 0.6, 0.6]], np.float32)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts with the plane OFF and leaves it that way —
    the disabled byte-identity contract of every other test file
    depends on it."""
    faults.clear()
    fleetobs.configure(enabled=False)
    yield
    faults.clear()
    fleetobs.configure(enabled=False, beat_bytes=262144, max_spans=256)
    tracing.configure(enabled=False)
    tracing.clear()


def _img(seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)


def _policy():
    return LeasePolicy(
        lease_ttl_s=2.0, hb_interval_s=0.1, check_interval_s=0.05,
        straggler_factor=0.0, max_reassigns=1_000_000_000,
        resource_fail_workers=1_000_000_000,
    )


def _fleet(**kw):
    kw.setdefault("policy", _policy())
    kw.setdefault("check_interval_s", 0.05)
    fleet = ServeFleet([SIZE], classes=1, **kw)
    fleet.start()
    return fleet


def _poll(predicate, timeout_s=15.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval_s)
    return predicate()


def _await_holders(fleet, want=1):
    return _poll(lambda: sum(
        1 for rec in fleet.state()["partitions"].values()
        if rec["holder"] is not None
    ) >= want)


# --------------------------------------------- disabled: byte identity
def test_disabled_plane_is_invisible():
    """TMR_FLEET_OBS=0 pins: no plane objects, no wire keys, beat
    replies and state() exactly the pre-plane shape."""
    assert fleetobs.make_ctx() is None
    assert fleetobs.root_span("x") is None
    assert fleetobs.op_span({"ctx": {"trace_id": "t",
                                     "parent_span_id": 1}}, "x") \
        is fleetobs._NOOP_REMOTE
    fleet = _fleet()
    try:
        assert fleet.fleet_obs is None
        assert fleet.fleet_obs_pass() == []
        worker = FleetWorker(fleet.address, "w1", stub_engine()).start()
        try:
            assert worker._obs is None
            assert _await_holders(fleet)
            fleet.submit(_img(3), EX).result(timeout=30)
            # the wire-level beat reply: EXACTLY the PR 18 keys
            reply = fleet._op_beat({"op": "beat", "worker": "w1",
                                    "held": []})
            assert set(reply) == {"ok", "stale", "drained"}
            state = fleet.state()
            assert "fleet_metrics" not in state
        finally:
            worker.stop()
    finally:
        fleet.close()


def test_wire_backcompat_both_directions():
    """Old peer vs new peer, both ways: an enabled coordinator accepts
    ctx-less/obs-less ops bitwise (and counts nothing), a disabled
    coordinator ignores obs-carrying beats without a protocol error."""
    # new worker -> OLD coordinator: the obs attachment is ignored
    fleet_old = _fleet()
    try:
        reply = fleet_old._op_beat({
            "op": "beat", "worker": "w-new", "held": [],
            "obs": {"v": 1, "pid": 1, "metrics": {"counters": {"x": 1},
                                                  "gauges": {},
                                                  "histograms": {}}},
        })
        assert set(reply) == {"ok", "stale", "drained"}
        assert reply["ok"] is True
    finally:
        fleet_old.close()
    # old worker -> NEW coordinator: no ctx/obs keys, tolerated bitwise
    fleetobs.configure(enabled=True)
    fleet_new = _fleet()
    try:
        reply = fleet_new._op_beat({"op": "beat", "worker": "w-old",
                                    "held": []})
        assert reply["ok"] is True
        assert "obs_ts" in reply  # the new reply stamps its clock
        assert fleet_new.fleet_obs.metrics.errors == 0
        # beat liveness was still recorded for the old worker
        assert fleet_new.fleet_obs.worker_state()["w-old"]["beats"] == 1
        serve_reply = {}  # ctx-less op opens no span
        assert fleetobs.ctx_of(serve_reply) is None
    finally:
        fleet_new.close()


# ------------------------------------------------- enabled: round trip
def test_enabled_round_trip_chains_and_reconciliation():
    """One in-process fleet with the plane ON: the front door mints
    trace ids, worker serve spans come home on beats, the clean stop
    flushes finals, and the sum-of-deltas reconciliation is EXACT."""
    fleetobs.configure(enabled=True)
    fleet = _fleet()
    try:
        fo = fleet.fleet_obs
        assert fo is not None
        worker = FleetWorker(fleet.address, "w1", stub_engine()).start()
        try:
            assert _await_holders(fleet)
            for i in range(4):
                fleet.submit(_img(20 + i), EX).result(timeout=30)
            assert _poll(lambda: any(
                (acc.get("histograms") or {}).get(
                    "serve.request_latency_s", {}
                ).get("count", 0) >= 4
                for acc in fo.metrics.per_worker().values()
            )), "latency deltas never folded"
            state = fleet.state()
            assert "fleet_metrics" in state
            assert validate_metrics_report(
                state["fleet_metrics"]["merged"]
            ) == []
        finally:
            worker.stop()  # clean bye -> final snapshot flush
        recon = _poll(lambda: (
            lambda r: r if r["exact"] else None
        )(fo.metrics.reconcile()))
        assert recon and recon["exact"] is True
        assert recon["workers_with_finals"] == ["w1"]
        assert recon["mismatches"] == []
        # at least one complete frontdoor -> worker chain per trace id
        chains = fo.span_chains()
        complete = 0
        for recs in chains.values():
            roots = {r["span"] for r in recs if r["parent"] == 0
                     and r["proc"] == "coordinator"}
            if roots and any(r["parent"] in roots and r["proc"] == "w1"
                             for r in recs):
                complete += 1
        assert complete >= 1
        rep = fo.report()
        assert rep["trace"]["monotone"] is True
        assert rep["beat_errors"] == 0
    finally:
        fleet.close()


def test_elastic_bye_flushes_final_snapshot(tmp_path):
    """The elastic coordinator gets the same end-of-life contract: a
    clean WorkerClient.close() flushes the final totals and the lease
    grant's ctx chains the worker's shard span under the grant root."""
    from tmr_tpu.parallel import elastic

    fleetobs.configure(enabled=True)
    client = None
    coord = elastic.ElasticCoordinator(
        [], str(tmp_path / "_journal"), image_size=SIZE, batch_size=2,
        policy=elastic.ElasticPolicy(
            lease_ttl_s=2.0, hb_interval_s=0.1, check_interval_s=0.05,
            straggler_factor=0.0,
        ),
    )
    coord.start()
    try:
        assert coord.fleet_obs is not None
        client = elastic.WorkerClient(coord.address, "ew1")
        client.heartbeat(-1, -1)
        assert _poll(
            lambda: coord.fleet_obs.worker_state().get("ew1", {}).get(
                "beats", 0) >= 1
        )
        assert "fleet_metrics" in coord.state()
        client.close()
        client = None
        recon = _poll(lambda: (
            lambda r: r if r["workers_with_finals"] else None
        )(coord.fleet_obs.metrics.reconcile()))
        assert recon and recon["workers_with_finals"] == ["ew1"]
        assert recon["exact"] is True
    finally:
        if client is not None:
            client.close()
        coord.stop()


# --------------------------------------------- clock offsets, stitching
def test_estimate_offset_midpoint_and_min_rtt():
    # remote clock runs 5s AHEAD of local; rtt 10ms symmetric
    samples = [(100.0, 105.005, 100.010),  # midpoint exact: off=+5.0
               (200.0, 205.100, 200.200)]  # worse rtt: must not win
    off, err = fleetobs.estimate_offset(samples)
    assert abs(off - 5.0) <= err
    assert err == pytest.approx(0.005)
    assert fleetobs.estimate_offset([]) is None
    assert fleetobs.estimate_offset([(1.0, None, 1.1)]) is None
    sync = fleetobs.ClockSync()
    sync.add(100.0, 105.005, 100.010)
    sync.add(100.0, "bogus", 100.010)  # non-numeric stamp ignored
    est = sync.estimate()
    assert est["samples"] == 1
    assert abs(est["offset_s"] - 5.0) <= est["err_s"]


def test_stitched_timeline_offset_correction_and_pid_remap():
    """Two tracks on skewed clocks: after per-track offset correction
    the merged trace is monotone, the offset is stamped into the track
    name, and colliding pids get distinct synthetic rows."""
    span = lambda ts, name: {"name": name, "ts": ts, "dur": 0.001,
                             "tid": 1, "trace": "t1", "span": 1,
                             "parent": 0, "attrs": {}}
    tracks = [
        {"pid": 42, "label": "coordinator", "offset_s": 0.0,
         "err_s": 0.0, "spans": [span(10.0, "a"), span(10.5, "b")]},
        # worker clock 5s AHEAD: raw stamps 15.1/15.6 are really
        # 10.1/10.6 on the reference clock -> offset −5
        {"pid": 42, "label": "w1", "offset_s": -5.0, "err_s": 0.002,
         "spans": [span(15.1, "c"), span(15.6, "d")]},
    ]
    doc = fleetobs.stitch_chrome_traces(tracks)
    assert fleetobs.tracks_monotone(doc)
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len({e["pid"] for e in meta}) == 2  # collision remapped
    names = [e["args"]["name"] for e in meta]
    assert any("coordinator" in n and "+0.000" in n for n in names)
    assert any("w1" in n and "-5000.000" in n and "2.000" in n
               for n in names)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    w1_ts = [e["ts"] for e in xs if e["args"]["proc"] == "w1"]
    # corrected worker stamps land ~0.1s after the coordinator's
    assert w1_ts[0] == pytest.approx((15.1 - 5.0) * 1e6)
    # an out-of-order track is detected
    bad = fleetobs.stitch_chrome_traces([
        {"pid": 1, "label": "x", "offset_s": 0.0, "err_s": 0.0,
         "spans": [span(2.0, "a"), span(1.0, "b")]},
    ])
    assert not fleetobs.tracks_monotone(bad)


# ------------------------------------------------------ fleet HealthWatch
def _hist(counts, buckets=(0.01, 0.1, 1.0)):
    return {"buckets_le": list(buckets), "counts": list(counts),
            "count": sum(counts), "sum": 0.0, "min": 0.0, "max": 1.0}


def _lat(per_worker_counts):
    return {
        wid: {"histograms": {"serve.request_latency_s": _hist(counts)}}
        for wid, counts in per_worker_counts.items()
    }


def test_healthwatch_kinds_fire_once_and_validate():
    watch = fleetobs.FleetHealthWatch(min_window_requests=8,
                                      min_window_total=24)
    # calm: two balanced fast workers
    calm = watch.observe(_lat({"a": [12, 0, 0], "b": [12, 0, 0]}))
    assert calm == []
    # one worker's window lands in the 1.0s bucket: outlier, named
    fired = watch.observe(_lat({"a": [24, 0, 0], "b": [24, 0, 0],
                                "slow": [0, 0, 12]}))
    kinds = [a["anomaly"] for a in fired]
    assert kinds == ["worker_outlier_latency"]
    assert fired[0]["evidence"]["worker"] == "slow"
    for rec in fired:
        assert validate_anomaly(rec) == []
        assert rec["anomaly"] in FLEET_ANOMALY_KINDS
    # skew: one of three workers draws 80% of the window (fair share
    # 33%, bound min(2 x fair, 0.95) = 67%)
    watch2 = fleetobs.FleetHealthWatch(min_window_requests=8,
                                       min_window_total=24)
    watch2.observe(_lat({"a": [8, 0, 0], "b": [8, 0, 0],
                         "c": [8, 0, 0]}))
    fired = watch2.observe(_lat({"a": [88, 0, 0], "b": [18, 0, 0],
                                 "c": [18, 0, 0]}))
    assert [a["anomaly"] for a in fired] == ["partition_skew"]
    assert fired[0]["evidence"]["worker"] == "a"


def test_healthwatch_beat_gap_latches_until_fresh_beat():
    watch = fleetobs.FleetHealthWatch()
    beats = {"w1": 100.0, "w2": 100.0}
    fired = watch.observe({}, beats=beats, hb_interval_s=0.2,
                          now=101.0, live=["w1", "w2"],
                          held={"w1": ["s32c0"]})
    assert [a["anomaly"] for a in fired] == ["beat_gap", "beat_gap"]
    # latched: the same silence is ONE anomaly, not one per pass
    again = watch.observe({}, beats=beats, hb_interval_s=0.2,
                          now=102.0, live=["w1", "w2"])
    assert again == []
    # a fresh beat unlatches; renewed silence fires again
    beats["w1"] = 102.0
    assert watch.observe({}, beats=beats, hb_interval_s=0.2,
                         now=102.1, live=["w1"]) == []
    fired = watch.observe({}, beats=beats, hb_interval_s=0.2,
                          now=104.0, live=["w1"])
    assert [a["anomaly"] for a in fired] == ["beat_gap"]
    assert fired[0]["evidence"]["worker"] == "w1"
    # a cleanly-left worker (not in live) never fires
    assert watch.observe({}, beats={"w9": 0.0}, hb_interval_s=0.2,
                         now=10.0, live=[]) == []


def test_healthwatch_fleet_mfu_drop_rolling_baseline():
    watch = fleetobs.FleetHealthWatch(mfu_drop=0.5)
    mk = lambda f, d: {"w": {"flops": f, "device_s": d}}
    watch.observe({}, mfu_by_worker=mk(0.0, 0.0))
    for i in range(1, 4):  # three healthy windows: 1 TFLOP/s baseline
        assert watch.observe({}, mfu_by_worker=mk(i * 1e12, i * 1.0)) \
            == []
    # the next window achieves 0.1 TFLOP/s: an 10x drop fires
    fired = watch.observe({}, mfu_by_worker=mk(3e12 + 1e11, 4.0))
    assert [a["anomaly"] for a in fired] == ["fleet_mfu_drop"]
    assert validate_anomaly(fired[0]) == []


# ------------------------------------------- delta codec + beat errors
def test_delta_codec_roundtrip_exact():
    reg = obsmetrics.MetricsRegistry()
    reg.counter("req").inc(3)
    hist = reg.histogram("lat")
    hist.observe(0.02)
    snap1 = reg.snapshot()
    reg.counter("req").inc(2)
    reg.counter("new").inc(1)
    hist.observe(0.5)
    hist.observe(0.7)
    snap2 = reg.snapshot()
    acc = fleetobs._empty_acc()
    fleetobs._fold_delta(acc, fleetobs.snapshot_delta(None, snap1))
    fleetobs._fold_delta(acc, fleetobs.snapshot_delta(snap1, snap2))
    report = fleetobs._acc_to_report(acc)
    assert validate_metrics_report(report) == []
    assert report["counters"] == snap2["counters"]
    folded = report["histograms"]["lat"]
    assert folded["count"] == snap2["histograms"]["lat"]["count"]
    assert folded["counts"] == snap2["histograms"]["lat"]["counts"]
    assert fleetobs.snapshot_delta(snap2, snap2) is None  # quiescent


def test_truncated_and_garbage_attachments_count_not_drop():
    fleetobs.configure(enabled=True)
    fo = fleetobs.FleetObs()
    before = obsmetrics.counter("fleet.obs_beat_errors").value
    fo.note_beat("w1")
    assert fo.fold("w1", "garbage") is False
    assert fo.fold("w1", {"v": 1, "truncated": True}) is False
    assert fo.metrics.errors == 2
    assert obsmetrics.counter("fleet.obs_beat_errors").value \
        == before + 2
    # the beat's liveness half survived the bad attachments
    assert fo.worker_state()["w1"]["beats"] == 1
    assert fo.state()["beat_errors"] == 2


def test_worker_attachment_truncation_rolls_back_delta():
    """An over-budget attachment ships ``truncated`` WITHOUT advancing
    the delta watermark: the window re-ships whole on a later beat, so
    reconciliation stays exact."""
    fleetobs.configure(enabled=True, beat_bytes=4096)
    reg = obsmetrics.MetricsRegistry()
    for i in range(400):
        reg.counter(f"stress.metric_{i:04d}.total").inc(i + 1)
    wobs = fleetobs.WorkerObs(reg)
    att = wobs.attachment()
    assert att.get("truncated") is True
    assert "metrics" not in att
    fleetobs.configure(beat_bytes=262144)
    att2 = wobs.attachment(final=True)
    assert "truncated" not in att2
    # the rolled-back window shipped whole: delta == final totals
    fo = fleetobs.FleetObs()
    fo.fold("w1", att)  # counted, folds nothing
    fo.fold("w1", att2, final=True)
    recon = fo.metrics.reconcile()
    assert recon["exact"] is True, recon["mismatches"]


# -------------------------------------------------- reader + probe gate
def _good_report():
    return {
        "schema": "fleet_obs_report/v1",
        "config": {},
        "workers": {"w1": {"beats": 3, "spans": 5,
                           "clock": {"offset_s": 0.1, "err_s": 0.01}}},
        "merged": {"schema": "metrics_report/v1", "counters": {},
                   "gauges": {}, "histograms": {}},
        "reconciliation": {"exact": True, "counters_checked": 4},
        "trace": {"events": 10, "tracks": 2, "monotone": True},
        "chains": {"total": 4, "complete": 4},
        "anomalies": {"calm": []},
        "beat_errors": 0,
        "overhead": {"disabled_ns_per_check": 100.0,
                     "overhead_disabled_pct": 0.01},
        "checks": {
            "span_chain_complete": True, "metrics_reconciled": True,
            "stitched_monotone": True, "slow_worker_exact": True,
            "beat_gap_exact": True, "calm_quiet": True,
            "overhead_ok": True,
        },
    }


def test_read_fleet_obs_report_fails_closed(tmp_path):
    doc = _good_report()
    assert validate_fleet_obs_report(doc) == []
    good = tmp_path / "good.json"
    good.write_text(json.dumps(doc))
    out = read_fleet_obs_report(str(good))
    assert all(v is True for v in out["checks"].values())
    assert out["summary"]["complete_chains"] == 4
    # every degradation fails CLOSED
    doc["checks"]["metrics_reconciled"] = True
    doc["reconciliation"]["exact"] = False  # check lies, field honest
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(doc))
    assert read_fleet_obs_report(str(bad))["checks"][
        "metrics_reconciled"] is False
    del doc["checks"]["overhead_ok"]
    doc["reconciliation"]["exact"] = True
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps(doc))
    assert read_fleet_obs_report(str(partial))["checks"][
        "overhead_ok"] is False
    err = tmp_path / "err.json"
    err.write_text(json.dumps({"schema": "fleet_obs_report/v1",
                               "error": "wedged"}))
    assert "error" in read_fleet_obs_report(str(err))
    assert "error" in read_fleet_obs_report(str(tmp_path / "nope"))
    garbled = tmp_path / "garbled.jsonl"
    garbled.write_text("not json\n" + json.dumps(_good_report()) + "\n")
    assert "error" not in read_fleet_obs_report(str(garbled))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_trend_fleet_obs_rc(tmp_path, capsys):
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_good_report()))
    assert _load("bench_trend").main(["--fleet-obs", str(good)]) == 0
    capsys.readouterr()
    doc = _good_report()
    doc["checks"]["calm_quiet"] = False
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(doc))
    assert _load("bench_trend").main(["--fleet-obs", str(bad)]) == 1
    capsys.readouterr()
    assert _load("bench_trend").main(
        ["--fleet-obs", str(tmp_path / "missing.json")]
    ) == 1
    capsys.readouterr()


def test_fleet_obs_probe_passes(tmp_path, capsys):
    """The full measured proof: 3-worker mixed fleet + kill -9, one
    validated fleet_obs_report/v1, rc-gated again through
    scripts/bench_trend.py --fleet-obs."""
    out = tmp_path / "fleet_obs_report.json"
    rc = _load("fleet_obs_probe").main(["--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert validate_fleet_obs_report(doc) == []
    assert all(v is True for v in doc["checks"].values())
    assert doc["chains"]["complete"] >= 1
    assert doc["reconciliation"]["exact"] is True
    assert doc["overhead"]["overhead_disabled_pct"] < 1.0
    capsys.readouterr()
    assert _load("bench_trend").main(["--fleet-obs", str(out)]) == 0
    reader_doc = json.loads(capsys.readouterr().out.strip())
    assert all(v is True for v in reader_doc["checks"].values())
