"""Golden tests: Flax SAM decoder stack vs the torch oracle, plus the
fixed-shape refiner pipeline (tmr_tpu/refine.py)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import torch

from tests.oracles_sam import MaskDecoderT, PromptEncoderT
from tmr_tpu.models.sam_decoder import (
    MaskDecoder,
    PromptEncoder,
    masks_to_boxes,
    resize_align_corners,
)
from tmr_tpu.refine import SamRefineModule
from tmr_tpu.utils.convert import (
    convert_mask_decoder,
    convert_prompt_encoder,
    convert_sam_refiner,
)

DIM = 32  # small transformer dim for fast tests (divisible by 8 heads, /8=4)



pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

def _tiny_torch_pair(seed=0):
    """Build torch oracle modules + converted Flax params at DIM=32."""
    torch.manual_seed(seed)
    pe_t = PromptEncoderT(embed_dim=DIM, mask_in_chans=16).eval()
    md_t = MaskDecoderT(dim=DIM, depth=2, num_heads=4, mlp_dim=64).eval()
    sd = {f"prompt_encoder.{k}": v for k, v in pe_t.state_dict().items()}
    sd.update({f"mask_decoder.{k}": v for k, v in md_t.state_dict().items()})
    params = convert_sam_refiner(sd)
    pe_f = PromptEncoder(embed_dim=DIM, mask_in_chans=16)
    md_f = MaskDecoder(
        transformer_dim=DIM,
        transformer_num_heads=4,
        transformer_mlp_dim=64,
    )
    return pe_t, md_t, pe_f, md_f, params


class TestPromptEncoderGolden:
    def test_box_embedding_matches_torch(self):
        pe_t, _, pe_f, _, params = _tiny_torch_pair()
        boxes = np.array(
            [[10.0, 20.0, 110.0, 160.0], [0.0, 0.0, 64.0, 64.0]], np.float32
        )
        with torch.no_grad():
            want = pe_t.embed_boxes(torch.from_numpy(boxes), (256, 256)).numpy()
        got = pe_f.apply(
            {"params": params["prompt_encoder"]},
            jnp.asarray(boxes),
            (256, 256),
            method=PromptEncoder.embed_boxes,
        )
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    def test_dense_pe_matches_torch(self):
        pe_t, _, pe_f, _, params = _tiny_torch_pair()
        with torch.no_grad():
            want = pe_t.dense_pe((8, 8)).numpy()
        got = pe_f.apply(
            {"params": params["prompt_encoder"]},
            (8, 8),
            method=PromptEncoder.dense_pe,
        )
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


class TestMaskDecoderGolden:
    def test_masks_and_iou_match_torch(self):
        pe_t, md_t, pe_f, md_f, params = _tiny_torch_pair()
        rng = np.random.default_rng(0)
        h = w = 8
        n = 3
        feats = rng.standard_normal((1, h, w, DIM)).astype(np.float32)
        boxes = np.abs(rng.standard_normal((n, 4))).astype(np.float32) * 50
        boxes[:, 2:] += boxes[:, :2] + 10

        with torch.no_grad():
            sparse_t = pe_t.embed_boxes(torch.from_numpy(boxes), (256, 256))
            dense_t = pe_t.no_mask_dense(n, (h, w)).permute(0, 3, 1, 2)
            pe_grid_t = pe_t.dense_pe((h, w)).permute(2, 0, 1).unsqueeze(0)
            feats_t = torch.from_numpy(feats).permute(0, 3, 1, 2)
            want_masks, want_iou = md_t(feats_t, pe_grid_t, sparse_t, dense_t)

        sparse, dense = pe_f.apply(
            {"params": params["prompt_encoder"]},
            jnp.asarray(boxes),
            (256, 256),
            (h, w),
        )
        pe_grid = pe_f.apply(
            {"params": params["prompt_encoder"]},
            (h, w),
            method=PromptEncoder.dense_pe,
        )
        got_masks, got_iou = md_f.apply(
            {"params": params["mask_decoder"]},
            jnp.asarray(feats),
            pe_grid,
            sparse,
            dense,
        )
        np.testing.assert_allclose(
            np.asarray(got_iou), want_iou.numpy(), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(got_masks), want_masks.numpy(), atol=1e-3
        )


class TestResizeAlignCorners:
    @pytest.mark.parametrize("shape,out", [((2, 7, 5), (21, 15)),
                                           ((1, 8, 8), (32, 32))])
    def test_matches_torch_bilinear(self, shape, out):
        rng = np.random.default_rng(1)
        x = rng.standard_normal(shape).astype(np.float32)
        want = (
            torch.nn.functional.interpolate(
                torch.from_numpy(x)[None], out, mode="bilinear",
                align_corners=True,
            )[0]
            .numpy()
        )
        got = np.asarray(resize_align_corners(jnp.asarray(x), out))
        np.testing.assert_allclose(got, want, atol=1e-5)


class TestPointAndMaskPrompts:
    def test_point_and_mask_paths_init_and_run(self):
        ref = SamRefineModule()
        ref.prompt_encoder = PromptEncoder(embed_dim=DIM)
        ref.mask_decoder = MaskDecoder(
            transformer_dim=DIM, transformer_num_heads=4,
            transformer_mlp_dim=64,
        )
        params = ref.init_params(seed=0)["prompt_encoder"]
        pts = jnp.asarray([[[10.0, 20.0], [30.0, 40.0]]])
        labels = jnp.asarray([[1, -1]], jnp.int32)
        emb = ref.prompt_encoder.apply(
            {"params": params}, pts, labels, (64, 64),
            method=PromptEncoder.embed_points,
        )
        assert emb.shape == (1, 2, DIM)
        masks = jnp.zeros((2, 32, 32, 1))
        dense = ref.prompt_encoder.apply(
            {"params": params}, masks, method=PromptEncoder.embed_masks
        )
        assert dense.shape == (2, 8, 8, DIM)


class TestMasksToBoxes:
    def test_tight_boxes_and_empty(self):
        masks = np.zeros((3, 16, 16), bool)
        masks[0, 3:9, 4:12] = True   # box (4, 3, 11, 8)
        masks[1, 5, 5] = True        # single pixel
        # masks[2] empty
        boxes, nonempty = masks_to_boxes(jnp.asarray(masks))
        np.testing.assert_array_equal(
            np.asarray(boxes),
            [[4, 3, 11, 8], [5, 5, 5, 5], [0, 0, 0, 0]],
        )
        np.testing.assert_array_equal(
            np.asarray(nonempty), [True, True, False]
        )


class TestRefiner:
    def _dets(self, b=1, n=8):
        rng = np.random.default_rng(2)
        boxes = np.zeros((b, n, 4), np.float32)
        xy = rng.uniform(0.1, 0.6, (b, n, 2))
        boxes[..., :2] = xy
        boxes[..., 2:] = xy + rng.uniform(0.05, 0.3, (b, n, 2))
        return {
            "boxes": jnp.asarray(boxes),
            "scores": jnp.asarray(rng.uniform(0.3, 1.0, (b, n)).astype(np.float32)),
            "refs": jnp.zeros((b, n, 2), jnp.float32),
            "valid": jnp.asarray(np.array([[True] * 5 + [False] * 3] * b)),
        }

    def test_refine_shapes_and_score_semantics(self):
        ref = SamRefineModule(chunk=4)
        ref.prompt_encoder = PromptEncoder(embed_dim=DIM)
        ref.mask_decoder = MaskDecoder(
            transformer_dim=DIM, transformer_num_heads=4,
            transformer_mlp_dim=64,
        )
        _, _, _, _, params = _tiny_torch_pair()
        dets = self._dets()
        feats = jnp.asarray(
            np.random.default_rng(3)
            .standard_normal((1, 8, 8, DIM))
            .astype(np.float32)
        )
        out = jax.jit(
            lambda p, f, d: ref.refine(p, f, d, (64, 64))
        )(params, feats, dets)
        assert out["boxes"].shape == dets["boxes"].shape
        assert out["scores"].shape == dets["scores"].shape
        got = np.asarray(out["scores"])
        orig = np.asarray(dets["scores"])
        valid = np.asarray(dets["valid"])
        # invalid slots keep their original score; valid = iou * orig
        np.testing.assert_allclose(got[~valid], orig[~valid])
        # refined boxes stay normalized-ish and finite
        assert np.isfinite(np.asarray(out["boxes"])).all()
        # refs recomputed as centers
        b = np.asarray(out["boxes"])
        np.testing.assert_allclose(
            np.asarray(out["refs"]),
            np.stack([(b[..., 0] + b[..., 2]) / 2,
                      (b[..., 1] + b[..., 3]) / 2], axis=-1),
            atol=1e-6,
        )

    def test_exemplar_scaling_variant_runs(self):
        ref = SamRefineModule(chunk=4)
        ref.prompt_encoder = PromptEncoder(embed_dim=DIM)
        ref.mask_decoder = MaskDecoder(
            transformer_dim=DIM, transformer_num_heads=4,
            transformer_mlp_dim=64,
        )
        _, _, _, _, params = _tiny_torch_pair()
        dets = self._dets()
        feats = jnp.asarray(
            np.random.default_rng(4)
            .standard_normal((1, 8, 8, DIM))
            .astype(np.float32)
        )
        ex = jnp.asarray(np.array([[0.2, 0.2, 0.5, 0.5]], np.float32))
        out = ref.refine_with_exemplar_scaling(
            params, feats, dets, ex, (64, 64)
        )
        assert out["boxes"].shape == dets["boxes"].shape
        assert np.isfinite(np.asarray(out["boxes"])).all()

    def test_predictor_end_to_end_with_refine(self):
        from tmr_tpu.config import Config
        from tmr_tpu.inference import Predictor
        from tmr_tpu.models.matching_net import MatchingNet
        from tmr_tpu.models.vit import SamViT

        cfg = Config(
            backbone="sam_vit_b", emb_dim=16, fusion=True,
            image_size=64, NMS_cls_threshold=0.01, NMS_iou_threshold=0.5,
            max_detections=16, template_buckets=(9,), refine_box=True,
            compute_dtype="float32",
        )
        tiny = MatchingNet(
            backbone=SamViT(
                embed_dim=32, depth=2, num_heads=2, global_attn_indexes=(1,),
                patch_size=8, window_size=3, out_chans=DIM,
                pretrain_img_size=64,
            ),
            emb_dim=16, fusion=True, template_capacity=9,
        )
        refiner = SamRefineModule(chunk=8)
        refiner.prompt_encoder = PromptEncoder(embed_dim=DIM)
        refiner.mask_decoder = MaskDecoder(
            transformer_dim=DIM, transformer_num_heads=4,
            transformer_mlp_dim=64,
        )
        _, _, _, _, rparams = _tiny_torch_pair()
        pred = Predictor(cfg, model=tiny, refiner=refiner,
                         refiner_params=rparams)
        pred.init_params(seed=0, image_size=64)
        rng = np.random.default_rng(7)
        image = rng.standard_normal((1, 64, 64, 3)).astype(np.float32)
        ex = np.array([[[0.2, 0.2, 0.5, 0.5]]], np.float32)
        out = pred(image, ex)
        assert out["boxes"].shape == (1, cfg.max_detections, 4)
        assert np.isfinite(np.asarray(out["boxes"])).all()

    def test_decode_masks_union(self):
        ref = SamRefineModule(chunk=4)
        ref.prompt_encoder = PromptEncoder(embed_dim=DIM)
        ref.mask_decoder = MaskDecoder(
            transformer_dim=DIM, transformer_num_heads=4,
            transformer_mlp_dim=64,
        )
        _, _, _, _, params = _tiny_torch_pair()
        feats = jnp.asarray(
            np.random.default_rng(5)
            .standard_normal((1, 8, 8, DIM))
            .astype(np.float32)
        )
        boxes = jnp.asarray(
            np.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]], np.float32)
        )
        masks = ref.decode_masks(params, feats, boxes, (64, 64))
        assert masks.shape == (1, 64, 64)
        assert masks.dtype == jnp.bool_
