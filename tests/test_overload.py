"""Overload machinery (tmr_tpu/serve admission/degrade + engine wiring):
bounded admission with structured rejections, class-weighted priority
pops, deadline shedding before device work, the degrade ladder's
exactness contract, and the bounded close() drain.

Pipeline-behavior tests run against a stub predictor (instant host
"programs", no jit): the mechanics under test are queues, locks, and
accounting — the real-program path is proven end to end by
scripts/overload_probe.py (tests/test_overload_probe.py smoke).
"""

import threading
import time

import numpy as np
import pytest

SIZE = 32

SMALL_EX = np.asarray([[0.45, 0.45, 0.53, 0.55]], np.float32)
MULTI_EX = np.asarray(
    [[0.45, 0.45, 0.53, 0.55], [0.2, 0.2, 0.28, 0.3],
     [0.6, 0.55, 0.68, 0.66]], np.float32,
)


def _img(seed, size=SIZE):
    return np.random.default_rng(seed).standard_normal(
        (size, size, 3)
    ).astype(np.float32)


class _StubPredictor:
    """Predictor stand-in: host-only bucket keys and instant tiny
    'programs' — exercises the serve pipeline's threading/accounting
    without any XLA compile. ``gate`` (a threading.Event) stalls the
    single-path program until set: the wedged-device stand-in."""

    def __init__(self, gate=None, delay_s: float = 0.0):
        self.params = np.zeros((1,), np.float32)
        self.refiner_params = None
        self.gate = gate
        self.delay_s = delay_s
        self.calls = 0

    def bucket_key(self, size, ex, multi=False, k_real=None):
        ex = np.asarray(ex, np.float32).reshape(-1, 4)
        k = int(k_real) if k_real is not None else len(ex)
        if multi:
            return ("multi", int(size), 9, k)
        return ("single", int(size), 9, len(ex))

    def _dets(self, b):
        return {"boxes": np.zeros((b, 8, 4), np.float32),
                "scores": np.zeros((b, 8), np.float32),
                "refs": np.zeros((b, 8, 2), np.float32),
                "valid": np.zeros((b, 8), bool)}

    def _run(self, b):
        if self.gate is not None:
            self.gate.wait()
        if self.delay_s:
            time.sleep(self.delay_s)
        self.calls += 1
        return self._dets(b)

    def _get_fn(self, capacity, donate=False):
        return lambda p, rp, image, ex, *a: self._run(image.shape[0])

    def _get_multi_batched_fn(self, capacity, k, donate=False):
        return lambda p, rp, image, ex, k_real: self._run(image.shape[0])

    def _get_backbone_fn(self):
        return lambda p, image: np.zeros(
            (image.shape[0], 2, 2, 4), np.float32
        )

    def _get_heads_fn(self, capacity, size):
        return lambda p, rp, feats, ex: self._run(
            np.asarray(feats).shape[0]
        )

    def __call__(self, image, exemplars):
        return self._run(1)

    def predict_multi_exemplar(self, image, exemplars, k_real=None):
        return self._run(1)


def _engine(pred=None, **kw):
    from tmr_tpu.serve import ServeEngine

    kw.setdefault("batch", 1)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("feature_cache", 0)
    return ServeEngine(pred or _StubPredictor(), **kw)


# ---------------------------------------------------------- RejectedError
def test_rejected_error_fields_and_record():
    from tmr_tpu.serve import REJECTION_CAUSES, RejectedError

    e = RejectedError("queue_full", "full", priority=2,
                      retry_after_s=1.23456)
    assert e.cause == "queue_full" and e.priority == 2
    assert e.retry_after_s == 1.235  # rounded hint
    rec = e.record()
    assert rec["cause"] in REJECTION_CAUSES
    assert rec["retry_after_s"] == 1.235 and rec["message"] == "full"
    assert isinstance(e, RuntimeError)  # catchable as a plain error
    with pytest.raises(AssertionError):
        RejectedError("bogus_cause", "x")


# ----------------------------------------------------- admission controller
def test_admission_bounds_trip_and_release():
    from tmr_tpu.serve import AdmissionController

    ctl = AdmissionController(enabled=True, max_pending=2)
    assert ctl.try_admit(0) is None
    assert ctl.try_admit(0) is None
    rej = ctl.try_admit(0)
    assert rej is not None and rej.cause == "queue_full"
    assert rej.retry_after_s is not None and rej.retry_after_s > 0
    ctl.release_class(0)
    assert ctl.try_admit(0) is None  # the slot came back
    s = ctl.stats()
    assert s["in_system"] == 2 and s["rejected"]["queue_full"] == 1


def test_admission_per_class_bounds_and_idempotent_release():
    from tmr_tpu.serve import AdmissionController, Request

    # class 0 bound 1, class >= 1 bound 4 (last entry reused)
    ctl = AdmissionController(enabled=True, max_pending=8,
                              class_pending=(1, 4))
    assert ctl.try_admit(0) is None
    rej = ctl.try_admit(0)
    assert rej is not None and rej.cause == "class_limit"
    assert rej.priority == 0
    assert ctl.try_admit(1) is None  # its own class bound
    req = Request(image=None, exemplars=None, bucket=("x",), priority=1)
    req.admitted = True
    ctl.release(req)
    ctl.release(req)  # idempotent: a double terminal event is a no-op
    assert ctl.stats()["in_system"] == 1
    # disabled controller: always admits, never counts
    off = AdmissionController(enabled=False, max_pending=0)
    assert off.try_admit(0) is None
    off.release(req)


def test_admission_token_bucket_rate_limit():
    from tmr_tpu.serve import AdmissionController

    ctl = AdmissionController(enabled=True, max_pending=100,
                              rate=0.001, burst=1)
    assert ctl.try_admit(0) is None  # burst token
    rej = ctl.try_admit(0)  # bucket dry, refill is ~forever
    assert rej is not None and rej.cause == "rate_limited"
    assert rej.retry_after_s > 0


def test_class_weight_fn_parsing():
    from tmr_tpu.serve import class_weight_fn
    from tmr_tpu.serve.admission import parse_class_weights

    w = class_weight_fn("")  # default doubling ladder
    assert (w(0), w(1), w(3)) == (1.0, 2.0, 8.0)
    assert w(99) == 8.0  # beyond the list reuses the last entry
    assert parse_class_weights("1, 10") == (1.0, 10.0)
    # garbage / non-positive specs fall back to the default
    assert parse_class_weights("a,b") == (1.0, 2.0, 4.0, 8.0)
    assert parse_class_weights("0,-1") == (1.0, 2.0, 4.0, 8.0)


# ------------------------------------------------ drain-source fallback
def _seeded_controller():
    """A controller whose release window holds a usable estimate — the
    fallback the broken-source tests must land on."""
    from tmr_tpu.serve import AdmissionController

    ctl = AdmissionController(enabled=True, max_pending=8)
    for _ in range(4):
        assert ctl.try_admit(0) is None
        ctl.release_class(0)
    assert ctl.stats()["drain_per_sec"] > 0  # the window estimate
    return ctl


def test_attach_drain_source_healthy_source_wins():
    ctl = _seeded_controller()
    ctl.attach_drain_source(lambda: 123.0)
    assert ctl.stats()["drain_per_sec"] == 123.0


def test_attach_drain_source_raising_falls_back_to_window():
    """PR 12 documented the fallback; this pins it: a source that
    RAISES must never poison the retry_after hint — the release-window
    estimate answers instead."""
    ctl = _seeded_controller()
    window = ctl.stats()["drain_per_sec"]

    def broken():
        raise RuntimeError("drain source wedged")

    ctl.attach_drain_source(broken)
    assert ctl.stats()["drain_per_sec"] == pytest.approx(window, rel=0.5)
    rej = None
    for _ in range(20):  # fill to the bound, then one rejection
        rej = ctl.try_admit(0)
        if rej is not None:
            break
    assert rej is not None and rej.retry_after_s > 0


@pytest.mark.parametrize("bad_rate", [0.0, -3.0, float("nan"),
                                      float("inf")])
def test_attach_drain_source_zero_or_nonfinite_falls_back(bad_rate):
    """A source returning 0 (a STALE engine/fleet window reports
    exactly this once its completions age out), a negative number, or
    a non-finite value falls back to the window estimate."""
    ctl = _seeded_controller()
    window = ctl.stats()["drain_per_sec"]
    ctl.attach_drain_source(lambda: bad_rate)
    got = ctl.stats()["drain_per_sec"]
    assert got == pytest.approx(window, rel=0.5)
    assert got > 0


def test_engine_drain_snapshot_goes_stale():
    """The engine side of the 'goes stale' contract: a drain window
    whose newest completion is old reads 0.0 — which is exactly what
    makes the attached controller fall back."""
    import time as _time

    from collections import deque

    eng = _engine()
    try:
        now = _time.monotonic()
        with eng._drain_lock:
            eng._drain["fresh"] = deque([now - 1.0, now - 0.5])
            eng._drain["stale"] = deque([now - 300.0, now - 299.0])
        snap = eng.drain_snapshot()
        assert snap["fresh"] > 0
        assert snap["stale"] == 0.0
    finally:
        eng.close()


# ------------------------------------------------------ priority batching
def test_batcher_pops_highest_class_first_fifo_within_class():
    from tmr_tpu.serve import MicroBatcher, Request, class_weight_fn

    b = MicroBatcher(max_wait_ms=5000, bound_for=lambda bucket: 3,
                     class_weight=class_weight_fn(""))
    lo1 = Request(image=1, exemplars=None, bucket=("x",), priority=0)
    hi = Request(image=2, exemplars=None, bucket=("x",), priority=5)
    lo2 = Request(image=3, exemplars=None, bucket=("x",), priority=0)
    for r in (lo1, hi, lo2):
        b.put(r)
    bucket, reqs = b.next_batch()  # full at bound 3: all release...
    assert [r.image for r in reqs] == [1, 2, 3]
    # ...but a partial pop takes the high class first, FIFO within class
    b2 = MicroBatcher(max_wait_ms=5000, bound_for=lambda bucket: 2,
                      class_weight=class_weight_fn(""))
    for i, p in enumerate((0, 0, 5)):
        b2.put(Request(image=i, exemplars=None, bucket=("x",),
                       priority=p))
    bucket, reqs = b2.next_batch()
    assert [r.image for r in reqs] == [0, 2]  # priority 5 + oldest 0
    bucket, reqs = b2.next_batch()  # remainder drains in arrival order
    assert [r.image for r in reqs] == [1]


def test_batcher_full_bucket_selection_is_class_weighted():
    from tmr_tpu.serve import MicroBatcher, Request, class_weight_fn

    b = MicroBatcher(max_wait_ms=5000, bound_for=lambda bucket: 2,
                     class_weight=class_weight_fn(""))
    for i in range(2):  # bucket A fills first (first-use order)...
        b.put(Request(image=f"a{i}", exemplars=None, bucket=("a",)))
    for i in range(2):  # ...but bucket B holds the heavier class
        b.put(Request(image=f"b{i}", exemplars=None, bucket=("b",),
                      priority=2))
    assert b.next_batch()[0] == ("b",)
    assert b.next_batch()[0] == ("a",)


# ------------------------------------------------------- degrade controller
def test_degrade_controller_ladder_and_modes():
    from tmr_tpu.serve import DEGRADE_STEPS, DegradeController

    auto = DegradeController(mode="auto", cooldown=2, max_level=3)
    storm = [{"anomaly": "queue_saturation", "message": "x",
              "evidence": {}}]
    calm = []
    assert auto.level == 0 and auto.active_steps() == ()
    assert auto.observe(storm) == 1
    assert auto.active_steps() == DEGRADE_STEPS[:1]
    assert auto.observe(storm) == 2
    # non-overload anomalies must not shrink user results: this pass
    # counts as calm #1 of the cooldown, holding the level
    assert auto.observe([{"anomaly": "recompile_storm", "message": "x",
                          "evidence": {}}]) == 2
    assert auto.observe(calm) == 1  # calm #2 -> one step down
    assert auto.observe(calm) == 1  # calm #1 again
    assert auto.observe(calm) == 0  # calm #2 -> fully recovered

    forced = DegradeController(mode="2")
    assert forced.enabled and forced.level == 2
    assert forced.observe(storm) == 2  # pinned: never moves
    off = DegradeController(mode="off")
    assert not off.enabled and off.active_steps() == ()
    assert off.observe(storm) == 0
    with pytest.raises(ValueError):
        DegradeController(mode="sideways")


def test_downscale_image_is_strided_subsample():
    from tmr_tpu.serve.degrade import downscale_image

    img = _img(0, 8)
    half = downscale_image(img)
    assert half.shape == (4, 4, 3)
    assert np.array_equal(half, img[::2, ::2])


# ------------------------------------------------- engine: default-off pin
def test_default_knobs_keep_pr3_shapes_and_results():
    """Admission/degrade off (the default): no overload keys in stats()
    or health(), no degrade_steps on results — the PR 3 surface."""
    eng = _engine()
    try:
        r = eng.submit(_img(1), SMALL_EX).result(timeout=60)
        assert "degrade_steps" not in r
        stats = eng.stats()
        assert "overload" not in stats
        health = eng.health()
        assert "admission" not in health and "degrade" not in health
        from tmr_tpu.diagnostics import validate_health_report

        assert validate_health_report(health) == []
    finally:
        eng.close()


# --------------------------------------------- engine: admission rejection
def test_engine_admission_rejects_and_reconciles_exactly():
    from tmr_tpu.serve import AdmissionController, RejectedError

    gate = threading.Event()
    pred = _StubPredictor(gate=gate)
    eng = _engine(pred, admission=AdmissionController(enabled=True,
                                                      max_pending=2))
    try:
        futs = [eng.submit(_img(10 + i), SMALL_EX) for i in range(6)]
        rejected = [f for f in futs if f.done() and f.exception()]
        assert len(rejected) == 4  # bound 2: the rest bounced instantly
        for f in rejected:
            e = f.exception()
            assert isinstance(e, RejectedError)
            assert e.cause in ("queue_full", "class_limit")
            assert e.retry_after_s is not None
        gate.set()
        done = [f.result(timeout=60) for f in futs if f not in rejected]
        assert len(done) == 2
        c = eng.counters
        ov = eng.overload_counters()
        assert ov["admit_rejected"] == 4
        assert c["submitted"] == 2 and c["completed"] == 2
        assert c["submitted"] + ov["admit_rejected"] == 6  # exact
        stats = eng.stats()
        assert stats["overload"]["counters"]["admit_rejected"] == 4
        assert "admission" in eng.health()
    finally:
        gate.set()
        eng.close()


# --------------------------------------------------- engine: deadline shed
def test_expired_request_sheds_before_any_device_work():
    """A request expired before dispatch must never reach the program:
    zero stub calls, zero batches staged, zero compile events recorded
    and an empty devtime table (the flight instruments agree nothing
    executed)."""
    from tmr_tpu import obs
    from tmr_tpu.obs import devtime
    from tmr_tpu.serve import RejectedError

    pred = _StubPredictor()
    eng = _engine(pred, batch=4, max_wait_ms=60)
    obs.flight_configure(enabled=True)
    devtime.reset()
    seq0 = obs.compile_event_seq()
    try:
        futs = [eng.submit(_img(20 + i), SMALL_EX, deadline_ms=1.0)
                for i in range(2)]  # 2 < bound 4: released by timeout
        for f in futs:
            with pytest.raises(RejectedError) as ei:
                f.result(timeout=60)
            assert ei.value.cause == "deadline"
        assert pred.calls == 0
        stats = eng.stats()
        assert stats["batches"] == 0
        assert stats["overload"]["counters"]["shed"] == 2
        assert stats["overload"]["counters"]["shed.stage"] == 2
        events, _seq = obs.compile_events_since(seq0)
        assert events == []
        assert devtime.totals() == {"flops": 0.0, "device_s": 0.0}
    finally:
        obs.flight_configure(enabled=False)
        eng.close()


def test_coalesced_duplicates_inherit_earliest_deadline():
    """The group's single execution must satisfy every rider, so the
    EARLIEST deadline (and highest class) governs the whole group."""
    from tmr_tpu.serve import RejectedError

    pred = _StubPredictor()
    eng = _engine(pred, batch=4, max_wait_ms=60)
    img = _img(30)
    try:
        f1 = eng.submit(img, SMALL_EX, deadline_ms=60_000.0)
        f2 = eng.submit(img, SMALL_EX, deadline_ms=1.0)  # coalesces
        for f in (f1, f2):
            with pytest.raises(RejectedError) as ei:
                f.result(timeout=60)
            assert ei.value.cause == "deadline"
        assert pred.calls == 0
        assert eng.counters["coalesced"] == 1
        # both riders counted shed — no phantom backlog
        assert eng.overload_counters()["shed"] == 2
    finally:
        eng.close()


def test_deadline_met_requests_still_complete():
    pred = _StubPredictor()
    eng = _engine(pred, batch=1, max_wait_ms=5)
    try:
        r = eng.submit(_img(31), SMALL_EX,
                       deadline_ms=60_000.0).result(timeout=60)
        assert r["boxes"].shape[0] == 1
        assert eng.counters["completed"] == 1
        assert "overload" not in eng.stats()  # nothing fired
    finally:
        eng.close()


# ------------------------------------------------- engine: degrade wiring
def test_forced_degrade_records_steps_and_cache_carries_them():
    from tmr_tpu.serve import DegradeController

    pred = _StubPredictor()
    eng = _engine(pred, batch=1, max_wait_ms=5, feature_cache=4,
                  degrade=DegradeController(mode="2"))
    img = _img(40)
    try:
        r1 = eng.submit(img, SMALL_EX).result(timeout=60)
        # level 2 = truncate_k (multi only) + prefer_heads: a cold
        # single request promotes on FIRST sighting
        assert r1["degrade_steps"] == ["prefer_heads"]
        r2 = eng.submit(img, SMALL_EX).result(timeout=60)
        assert r2["degrade_steps"] == ["prefer_heads"]  # cache hit says so
        rm = eng.submit(_img(41), MULTI_EX, multi=True).result(timeout=60)
        assert "truncate_k" in rm["degrade_steps"]
        ov = eng.overload_counters()
        assert ov["degraded"] >= 2
        assert ov["degrade.prefer_heads"] >= 1
        assert ov["degrade.truncate_k"] == 1
        assert eng.stats()["overload"]["degrade"]["level"] == 2
        assert "degrade" in eng.health()
    finally:
        eng.close()


def test_forced_downscale_routes_to_half_resolution_bucket():
    from tmr_tpu.serve import DegradeController

    pred = _StubPredictor()
    eng = _engine(pred, degrade=DegradeController(mode="3", min_size=8))
    try:
        r = eng.submit(_img(50), SMALL_EX).result(timeout=60)
        assert "downscale" in r["degrade_steps"]
        # the batcher saw the HALF-resolution bucket
        occ = eng.stats()["batch_occupancy"]
        assert occ  # a batch ran
        bounds = eng.stats()["batch_bounds"]
        assert str(SIZE // 2) in bounds
    finally:
        eng.close()


def test_degrade_floor_blocks_downscale_below_min_size():
    from tmr_tpu.serve import DegradeController

    pred = _StubPredictor()
    eng = _engine(pred,
                  degrade=DegradeController(mode="3", min_size=SIZE))
    try:
        r = eng.submit(_img(51), SMALL_EX).result(timeout=60)
        # 32 // 2 < min_size 32: the step must NOT fire
        assert "downscale" not in r.get("degrade_steps", [])
    finally:
        eng.close()


# ----------------------------------------------- engine: bounded drain
def test_close_bounded_drain_rejects_leftovers_on_stalled_device():
    """Regression (satellite 1): close() under backlog used to hang on
    the drain join while callers blocked on their futures forever. Now
    the drain is bounded: past the timeout every leftover future fails
    with a structured shutdown rejection and close() returns."""
    from tmr_tpu.serve import RejectedError

    gate = threading.Event()  # never set until cleanup: a wedged device
    pred = _StubPredictor(gate=gate)
    eng = _engine(pred, batch=1, max_wait_ms=5)
    futs = [eng.submit(_img(60 + i), SMALL_EX) for i in range(3)]
    t0 = time.perf_counter()
    eng.close(timeout=0.5)
    wall = time.perf_counter() - t0
    assert wall < 5.0  # bounded, not the 300 s default join
    for f in futs:
        assert f.done()
        exc = f.exception()
        assert isinstance(exc, RejectedError) and exc.cause == "shutdown"
    stats = eng.stats()
    assert stats["overload"]["drain_timed_out"] is True
    assert stats["overload"]["counters"]["shed.shutdown"] == 3
    gate.set()  # release the stub so the daemon thread can exit


def test_close_clean_drain_unchanged():
    pred = _StubPredictor()
    eng = _engine(pred)
    f = eng.submit(_img(70), SMALL_EX)
    f.result(timeout=60)
    eng.close(timeout=30.0)  # drains normally: no rejections
    assert "overload" not in eng.stats()


# ------------------------------------------------------------- validators
def _valid_overload_doc():
    from tmr_tpu.diagnostics import OVERLOAD_REPORT_SCHEMA

    return {
        "schema": OVERLOAD_REPORT_SCHEMA,
        "device": "cpu",
        "config": {"image_size": 128, "batch": 4, "factor": 5.0},
        "capacity": {"img_per_sec": 2.0, "requests": 12},
        "overload": {
            "offered": 48, "offered_img_per_sec": 10.0,
            "completed": 20, "rejected": 28, "shed": 0, "errors": 0,
            "degraded": 0,
            "latency_ms": {"p50": 10.0, "p95": 20.0, "p99": 30.0},
            "reject_causes": {"queue_full": 28},
        },
        "close": {"wall_s": 1.0, "timeout_s": 120.0},
        "degrade": {"forced_level": 3, "steps_seen": ["downscale"]},
        "checks": {
            "p99_bounded": True, "accounting_exact": True,
            "rejected_nonzero": True, "shed_before_device": True,
            "degrade_steps_recorded": True, "degrade_auto_ladder": True,
            "close_bounded": True,
        },
    }


def test_validate_overload_report_accepts_valid_and_error_docs():
    from tmr_tpu.diagnostics import (
        OVERLOAD_REPORT_SCHEMA,
        validate_overload_report,
    )

    assert validate_overload_report(_valid_overload_doc()) == []
    assert validate_overload_report(
        {"schema": OVERLOAD_REPORT_SCHEMA, "error": "watchdog: ..."}
    ) == []


@pytest.mark.parametrize("mutate, fragment", [
    (lambda d: d.update(schema="bogus/v9"), "schema"),
    (lambda d: d.pop("capacity"), "capacity"),
    (lambda d: d["overload"].pop("rejected"), "rejected"),
    (lambda d: d["overload"].update(completed="twenty"), "completed"),
    (lambda d: d["overload"]["latency_ms"].pop("p99"), "latency_ms"),
    (lambda d: d.pop("close"), "close"),
    (lambda d: d["degrade"].update(steps_seen="downscale"), "steps_seen"),
    (lambda d: d["checks"].pop("accounting_exact"), "accounting_exact"),
    (lambda d: d.update(error=""), "error"),
])
def test_validate_overload_report_rejects_broken_docs(mutate, fragment):
    from tmr_tpu.diagnostics import validate_overload_report

    doc = _valid_overload_doc()
    mutate(doc)
    problems = validate_overload_report(doc)
    assert problems, f"expected a problem for {fragment}"
    assert any(fragment in p for p in problems), problems


def test_serve_report_validator_checks_admission_attachment():
    from tmr_tpu.diagnostics import validate_serve_report

    import tests.test_serve_bench as tsb

    doc = tsb._valid_doc()
    doc["workloads"][0]["admission"] = {
        "rejected": 3, "shed": 1, "degraded": 0, "reject_rate": 0.27,
    }
    assert validate_serve_report(doc) == []
    doc["workloads"][0]["admission"].pop("reject_rate")
    assert any("reject_rate" in p for p in validate_serve_report(doc))


def test_health_report_validator_checks_overload_sections():
    from tmr_tpu.diagnostics import validate_health_report

    eng = _engine()
    try:
        doc = eng.health()
    finally:
        eng.close()
    doc["admission"] = {"enabled": True, "max_pending": 8, "in_system": 2}
    doc["degrade"] = {"level": 1, "steps": ["truncate_k"]}
    assert validate_health_report(doc) == []
    doc["degrade"] = {"level": "one"}
    assert any("degrade" in p for p in validate_health_report(doc))
