"""The elastic serve chaos probe (scripts/elastic_serve_probe.py) must
pass on tier-1: kill -9 a serve worker mid-batch (every in-flight
future terminal, zero double-served, exact offered == completed +
rejected + shed + errors reconciliation engine-side AND probe-side),
SIGSTOP past the TTL into a FENCED late result, and a recruitment
round absorbing a 3x spike with the degrade ladder at level 0 — one
validated elastic_serve_report/v1, rc-gated again through
scripts/bench_trend.py --fleet."""

import importlib.util
import json
import os

import pytest

from tmr_tpu.diagnostics import validate_elastic_serve_report
from tmr_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_schedule():
    faults.clear()
    yield
    faults.clear()


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_elastic_serve_probe_passes(tmp_path, capsys):
    out = tmp_path / "elastic_serve_report.json"
    rc = _load("elastic_serve_probe").main(["--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert validate_elastic_serve_report(doc) == []
    checks = doc["checks"]
    assert checks["zero_double_served"] is True
    assert checks["accounting_exact_probe"] is True
    assert checks["accounting_exact_fleet"] is True
    assert checks["fenced_late_result"] is True
    assert checks["recruitment_absorbed"] is True
    assert checks["degrade_level0"] is True
    # the kill phase really exercised death rebalance
    kill = next(p for p in doc["phases"] if p["name"] == "kill")
    assert kill["worker_exit_reassigned"] is True
    assert kill["resubmitted"] >= 1
    # the trend reader rc-gates the same document
    capsys.readouterr()
    assert _load("bench_trend").main(["--fleet", str(out)]) == 0
    reader_doc = json.loads(capsys.readouterr().out.strip())
    assert reader_doc["checks"]["zero_double_served"] is True
