"""Stream sessions (tmr_tpu/serve/streams.py): temporal feature reuse
behind the block-mean delta check.

The load-bearing contracts: reuse is OFF by default (and off = a pure
passthrough); the delta election is exact at its boundaries (an
exact-equal frame always reuses, a perturbation AT the threshold still
reuses, strictly above goes full path); every reused result is labeled
``temporal_reuse`` and lives under its own result-cache namespace;
reuse never crosses stream ids; idle sessions evict; and the stamped
feature keys (PR 16's cache-key fix) keep two checkpoints from ever
sharing a feature-cache entry.

Everything runs on the numpy StubFeaturePredictor — the stub's
features carry each image's mean signature end to end, so a wrong
anchor, a crossed stream, or a stale cache row all show as score
mismatches without any XLA.
"""

import time

import numpy as np
import pytest

SIZE = 32
BOX = np.asarray([[0.2, 0.2, 0.4, 0.4]], np.float32)
FIELDS = ("boxes", "scores", "refs", "valid")


def _img(seed):
    return np.random.default_rng(seed).standard_normal(
        (SIZE, SIZE, 3)
    ).astype(np.float32)


@pytest.fixture()
def engine():
    from tmr_tpu.serve import ServeEngine
    from tmr_tpu.serve.feature_tier import StubFeaturePredictor

    eng = ServeEngine(StubFeaturePredictor(), batch=2, max_wait_ms=5.0,
                      feature_cache=0, exemplar_cache=0)
    yield eng
    eng.close()


def _same(a, b):
    return all(np.array_equal(np.asarray(a[k]), np.asarray(b[k]))
               for k in FIELDS)


# ------------------------------------------------------------ off by default
def test_reuse_off_by_default_is_pure_passthrough(engine, monkeypatch):
    """No env, no constructor flag: submit_stream is engine.submit with
    a frame counter — no sessions, no labels, no feature work."""
    monkeypatch.delenv("TMR_STREAM_REUSE", raising=False)
    from tmr_tpu.serve import StreamRouter

    r = StreamRouter(engine)
    assert r.reuse is False
    frame = _img(0)
    out = r.submit_stream("a", frame, BOX).result()
    again = r.submit_stream("a", frame, BOX).result()  # same frame twice
    assert "degrade_steps" not in out and "degrade_steps" not in again
    assert _same(out, engine.submit(frame, BOX).result())
    assert r.sessions() == {} and r.counters() == {"frames": 2}
    # TMR_STREAM_REUSE=0 is the same OFF; =1 arms it
    monkeypatch.setenv("TMR_STREAM_REUSE", "0")
    assert StreamRouter(engine).reuse is False
    monkeypatch.setenv("TMR_STREAM_REUSE", "1")
    assert StreamRouter(engine).reuse is True


# --------------------------------------------------------- delta boundaries
def test_delta_boundaries_exact_equal_at_threshold_and_above(engine):
    """The election rule at its edges, in exact float32 arithmetic
    (zeros base, power-of-two perturbations, 4x4 signature blocks):
    delta 0.0 reuses even at threshold 0.0; a single-pixel change
    landing EXACTLY on the threshold still reuses; strictly above goes
    full path and re-anchors."""
    from tmr_tpu.serve import StreamRouter

    # one pixel changed by 1.0 in a 4x4 block -> block-mean delta is
    # exactly 1/16 = 0.0625 (a power of two: exact in float32)
    r = StreamRouter(engine, reuse=True, delta=0.0625)
    base = np.zeros((SIZE, SIZE, 3), np.float32)
    first = r.submit_stream("s", base, BOX).result()
    assert "degrade_steps" not in first

    exact = r.submit_stream("s", base.copy(), BOX).result()
    assert exact.get("degrade_steps") == ["temporal_reuse"]

    at = base.copy()
    at[0, 0, 0] = 1.0  # delta == threshold: still reuses
    out_at = r.submit_stream("s", at, BOX).result()
    assert out_at.get("degrade_steps") == ["temporal_reuse"]

    above = base.copy()
    above[0, 0, 0] = 2.0  # delta 0.125 > 0.0625: full path, new anchor
    out_above = r.submit_stream("s", above, BOX).result()
    assert "degrade_steps" not in out_above
    assert _same(out_above, engine.submit(above, BOX).result())
    c = r.counters()
    assert (c["first_frames"], c["reused_frames"], c["changed_frames"]) \
        == (1, 2, 1)
    # the changed frame re-anchored: repeating it now reuses
    rep = r.submit_stream("s", above.copy(), BOX).result()
    assert rep.get("degrade_steps") == ["temporal_reuse"]
    assert np.array_equal(rep["scores"], out_above["scores"])

    # delta 0.0 still admits the bitwise-equal frame
    r0 = StreamRouter(engine, reuse=True, delta=0.0)
    r0.submit_stream("z", base, BOX).result()
    out = r0.submit_stream("z", base.copy(), BOX).result()
    assert out.get("degrade_steps") == ["temporal_reuse"]


def test_block_signature_is_deterministic_and_shape_bound():
    from tmr_tpu.serve import block_signature

    frame = _img(7)
    a, b = block_signature(frame), block_signature(frame.copy())
    assert np.array_equal(a, b)
    assert a.shape == (64, 3) and a.dtype == np.float32
    tiny = np.ones((3, 3, 3), np.float32)  # grid clamps to the frame
    assert block_signature(tiny).shape == (9, 3)


# ------------------------------------------------------ reuse data contracts
def test_reused_frames_ride_anchor_features_per_stream(engine):
    """Reused results derive from the session's OWN anchor features
    (the stub's signature rides through), and two concurrent streams
    with different content never share: structural isolation."""
    from tmr_tpu.serve import StreamRouter

    r = StreamRouter(engine, reuse=True)
    a_frame, b_frame = _img(1), _img(2)
    a0 = r.submit_stream("a", a_frame, BOX).result()
    b0 = r.submit_stream("b", b_frame, BOX).result()
    a1 = r.submit_stream("a", a_frame.copy(), BOX).result()
    b1 = r.submit_stream("b", b_frame.copy(), BOX).result()
    assert np.array_equal(a1["scores"], a0["scores"])
    assert np.array_equal(b1["scores"], b0["scores"])
    assert not np.array_equal(a1["scores"], b1["scores"])
    c = r.counters()
    assert c["reused_frames"] == 2 and c["local_fills"] == 2
    assert set(r.sessions()) == {"a", "b"}


def test_reused_result_cache_namespace_never_leaks(engine):
    """A reused answer can never be served to a frame-independent
    query: the temporal_reuse step is part of the result-cache key."""
    from tmr_tpu.serve import ServeEngine, StreamRouter
    from tmr_tpu.serve.feature_tier import StubFeaturePredictor

    eng = ServeEngine(StubFeaturePredictor(), batch=2, max_wait_ms=5.0,
                      feature_cache=0, exemplar_cache=16)
    try:
        r = StreamRouter(eng, reuse=True)
        frame = _img(3)
        r.submit_stream("a", frame, BOX).result()
        reused = r.submit_stream("a", frame.copy(), BOX).result()
        assert reused.get("degrade_steps") == ["temporal_reuse"]
        # the SAME frame, frame-independent: must not hit the reused
        # entry (the label would leak with it)
        plain = eng.submit(frame, BOX).result()
        assert "degrade_steps" not in plain
    finally:
        eng.close()


def test_features_with_multi_exemplar_is_rejected(engine):
    """Temporal reuse rides the heads-only program, which has no
    multi-exemplar formulation — the combination fails that request
    alone, synchronously at submit."""
    multi_ex = np.asarray(
        [[0.2, 0.2, 0.4, 0.4], [0.5, 0.5, 0.7, 0.7]], np.float32
    )
    feats = np.zeros((1, 2, 2, 4), np.float32)
    fut = engine.submit(_img(4), multi_ex, multi=True, k_real=2,
                        features=feats)
    with pytest.raises(ValueError, match="single-exemplar"):
        fut.result()


def test_router_prefers_feature_tier_for_anchor_fills(engine):
    """With the engine's feature client armed and holding, the anchor
    fill goes REMOTE (counted remote_fills); a client that fails drops
    to the counted local fill — the kill-mid-stream degrade path."""
    from tmr_tpu.serve import StreamRouter

    calls = []

    class FakeClient:
        def __init__(self, alive=True):
            self.alive = alive

        def holds(self, size):
            return self.alive

        def fetch(self, image, digest, size):
            calls.append(digest)
            if not self.alive:
                return None
            arr = np.asarray(image, np.float32)
            sig = arr.reshape(1, -1).mean(axis=1)
            return np.tile(sig.reshape(1, 1, 1, 1),
                           (1, 2, 2, 4)).astype(np.float32)

    engine._feature_client = FakeClient(alive=True)
    r = StreamRouter(engine, reuse=True)
    frame = _img(5)
    first = r.submit_stream("a", frame, BOX).result()
    reused = r.submit_stream("a", frame.copy(), BOX).result()
    assert np.array_equal(reused["scores"], first["scores"])
    assert calls and r.counters()["remote_fills"] == 1

    # dead worker mid-stream: the next anchor's fill falls back local
    engine._feature_client.alive = False
    frame2 = _img(6)
    r.submit_stream("b", frame2, BOX).result()
    fb = r.submit_stream("b", frame2.copy(), BOX).result()
    assert fb.get("degrade_steps") == ["temporal_reuse"]
    c = r.counters()
    assert c["local_fills"] == 1 and c["remote_fills"] == 1


# ----------------------------------------------------------------- lifecycle
def test_idle_sessions_evict_lazily(engine, monkeypatch):
    from tmr_tpu.serve import StreamRouter

    monkeypatch.setenv("TMR_STREAM_IDLE_S", "0.05")
    r = StreamRouter(engine, reuse=True)
    assert r.idle_s == 0.05
    frame = _img(8)
    r.submit_stream("a", frame, BOX).result()
    time.sleep(0.12)
    r.submit_stream("b", _img(9), BOX).result()  # sweeps "a" out
    assert set(r.sessions()) == {"b"}
    assert r.counters()["evicted_sessions"] == 1
    # the evicted stream starts over: its next frame is "first" again
    out = r.submit_stream("a", frame.copy(), BOX).result()
    assert "degrade_steps" not in out
    assert r.counters()["first_frames"] == 3


def test_explicit_evict_drops_session_and_features(engine):
    from tmr_tpu.serve import StreamRouter

    r = StreamRouter(engine, reuse=True)
    frame = _img(10)
    r.submit_stream("a", frame, BOX).result()
    r.submit_stream("a", frame.copy(), BOX).result()
    assert r.evict("a") is True and r.evict("a") is False
    assert r.sessions() == {} and r.stats()["feature_cache"]["size"] == 0


def test_stream_knob_defaults_and_stats(engine, monkeypatch):
    from tmr_tpu.serve import StreamRouter

    for knob in ("TMR_STREAM_REUSE", "TMR_STREAM_DELTA",
                 "TMR_STREAM_IDLE_S", "TMR_STREAM_CACHE_MB"):
        monkeypatch.delenv(knob, raising=False)
    r = StreamRouter(engine)
    assert (r.reuse, r.delta, r.idle_s) == (False, 0.02, 300.0)
    assert r._features.max_bytes == 64 << 20
    monkeypatch.setenv("TMR_STREAM_DELTA", "0.5")
    monkeypatch.setenv("TMR_STREAM_CACHE_MB", "1")
    r2 = StreamRouter(engine, reuse=True)
    assert r2.delta == 0.5 and r2._features.max_bytes == 1 << 20
    s = r2.stats()
    assert s["reuse"] is True and s["sessions"] == 0


# ------------------------------------------------------- stamped feature keys
def test_feature_cache_keys_carry_params_and_backbone_stamp():
    """The cache-key fix: feature keys carry (params digest, backbone
    formulation), so two engines over DIFFERENT checkpoints sharing
    one cache object can never serve each other's features — and a
    real Predictor's stamp moves when its params digest moves."""
    from tmr_tpu.serve import ServeEngine
    from tmr_tpu.serve.feature_tier import StubFeaturePredictor

    class OtherCheckpoint(StubFeaturePredictor):
        def feature_stamp(self):
            return ("other-params", "stub-backbone")

    a = ServeEngine(StubFeaturePredictor(), batch=1, max_wait_ms=5.0,
                    feature_cache=4, exemplar_cache=0)
    b = ServeEngine(OtherCheckpoint(), batch=1, max_wait_ms=5.0,
                    feature_cache=4, exemplar_cache=0)
    try:
        ka = a._feature_key("digest", SIZE)
        kb = b._feature_key("digest", SIZE)
        assert ka != kb
        assert ka == ("digest", SIZE, "stub-params", "stub-backbone")
        assert kb[2:] == ("other-params", "stub-backbone")
    finally:
        a.close()
        b.close()


def test_gallery_bank_feature_keys_carry_stamp_too():
    from tmr_tpu.serve import GalleryBank
    from tmr_tpu.serve.feature_tier import StubFeaturePredictor

    class OtherCheckpoint(StubFeaturePredictor):
        def feature_stamp(self):
            return ("other-params", "stub-backbone")

    bank_a = GalleryBank.__new__(GalleryBank)
    bank_b = GalleryBank.__new__(GalleryBank)
    for bank, pred in ((bank_a, StubFeaturePredictor()),
                       (bank_b, OtherCheckpoint())):
        fstamp = getattr(pred, "feature_stamp", None)
        bank._feat_stamp = tuple(fstamp()) if callable(fstamp) else ()
    assert bank_a._feature_key("d", SIZE) != bank_b._feature_key("d",
                                                                 SIZE)


def test_predictor_feature_stamp_tracks_params_identity():
    """The real Predictor's stamp: (params digest | identity, backbone
    formulation) — a params swap or a different backbone moves it."""
    from tmr_tpu.config import preset
    from tmr_tpu.inference import Predictor

    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=SIZE,
                 compute_dtype="float32", batch_size=1)
    pred = Predictor(cfg)
    # hold BOTH trees: the stamp is identity-keyed without storage
    # digests, and a freed tree's id could be reused
    tree_a = {"w": np.zeros((2,), np.float32)}
    tree_b = {"w": np.ones((2,), np.float32)}
    pred.params = tree_a
    s1 = pred.feature_stamp()
    assert s1[1] == "sam_vit_b"
    pred.params = tree_b
    s2 = pred.feature_stamp()
    assert s1 != s2
    del tree_a, tree_b
