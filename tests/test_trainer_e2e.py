"""End-to-end slice: fit a tiny model on a synthetic FSCD-147 fixture,
validate (AP/MAE pipeline), checkpoint best/last, resume, and test-eval."""

import os

import numpy as np

from tmr_tpu.config import Config
from tmr_tpu.inference import Predictor
from tmr_tpu.models.matching_net import MatchingNet
from tmr_tpu.models.vit import SamViT

TINY_VIT = dict(
    embed_dim=32, depth=2, num_heads=2, global_attn_indexes=(1,),
    patch_size=8, window_size=3, out_chans=16, pretrain_img_size=64,
)



import pytest

pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

def _write_fixture(root, n_train=4, n_val=2):
    """Images with 2 bright square 'objects' on dark background (the
    package's own quickstart fixture generator)."""
    from tmr_tpu.data.synthetic import write_synthetic_fscd147

    write_synthetic_fscd147(root, n_train=n_train, n_val=n_val)


def _make_trainer(root, logdir, resume=False, **overrides):
    from tmr_tpu.train.loop import Trainer

    kw = dict(
        dataset="FSCD147", datapath=root, logpath=logdir,
        backbone="sam_vit_b", emb_dim=16, fusion=True,
        feature_upsample=False, image_size=64,
        positive_threshold=0.5, negative_threshold=0.5,
        NMS_cls_threshold=0.3, NMS_iou_threshold=0.5,
        lr=2e-3, lr_backbone=0.0, max_epochs=2, AP_term=1,
        batch_size=2, num_workers=2, max_gt_boxes=8,
        compute_dtype="float32", max_detections=64,
        template_buckets=(9,), resume=resume,
    )
    kw.update(overrides)
    cfg = Config(**kw)
    trainer = Trainer(cfg)
    tiny = MatchingNet(
        backbone=SamViT(**TINY_VIT), emb_dim=cfg.emb_dim, fusion=True,
        template_capacity=9,
    )
    trainer.model = tiny
    trainer.predictor = Predictor(cfg, model=tiny)
    return trainer


def test_fit_eval_checkpoint_resume(tmp_path):
    root = str(tmp_path / "data")
    logdir = str(tmp_path / "logs")
    os.makedirs(root)
    _write_fixture(root)

    trainer = _make_trainer(root, logdir)
    trainer.fit()

    # metrics CSV written with train + val columns
    csv_path = os.path.join(logdir, "metrics.csv")
    assert os.path.exists(csv_path)
    content = open(csv_path).read()
    assert "val/AP" in content and "train/loss_ce" in content

    # checkpoints: last + at least one best version
    assert trainer.ckpt.last_path() is not None
    assert trainer.ckpt.best_path() is not None
    assert trainer.ckpt.meta["last_epoch"] == 1

    # test eval runs end to end and returns the full metric suite
    metrics = trainer.test()
    for key in ("test/AP", "test/AP50", "test/MAE", "test/RMSE",
                "test/loss_ce"):
        assert key in metrics
    assert np.isfinite(metrics["test/MAE"])

    # eval logged_datas cleaned up after epoch end (log_utils del path)
    assert not os.path.exists(os.path.join(logdir, "logged_datas", "test"))

    # resume continues from the saved epoch without error
    trainer2 = _make_trainer(root, logdir, resume=True)
    trainer2.cfg = trainer2.cfg  # same config, max_epochs already reached
    trainer2.fit()  # restores epoch 2 == max_epochs -> no further steps
    assert trainer2.ckpt.meta["last_epoch"] == 1


def test_training_converges_to_perfect_ap(tmp_path):
    """The whole stack learns: on the planted-squares fixture, 10 epochs of
    the real CLI training reach AP50 ~100 and MAE ~0 through the full
    pipeline (model -> targets -> loss -> optimizer -> decode -> NMS ->
    COCO eval). Guards against silent numerics drift anywhere in the
    chain."""
    import csv

    import main as cli

    fix = str(tmp_path / "data")
    log = str(tmp_path / "log")
    _write_fixture(fix)
    cli.main([
        "--device", "cpu", "--dataset", "FSCD147", "--datapath", fix,
        "--logpath", log, "--backbone", "resnet50_layer1", "--emb_dim", "16",
        "--image_size", "64", "--fusion", "--max_epochs", "10",
        "--AP_term", "10", "--batch_size", "2", "--compute_dtype", "float32",
        "--num_workers", "0", "--lr", "3e-3", "--NMS_cls_threshold", "0.3",
    ])
    rows = list(csv.DictReader(open(os.path.join(log, "metrics.csv"))))
    last = rows[-1]
    assert float(last["val/AP50"]) > 90.0, last
    assert float(last["val/MAE"]) < 0.5, last


def test_fresh_guard_refuses_existing_logpath(tmp_path):
    """Reference callbacks.py:12-13: a fresh (non-resume, non-eval) training
    must refuse to start into a logpath that already holds checkpoints."""
    import pytest

    root = str(tmp_path / "data")
    logdir = str(tmp_path / "logs")
    os.makedirs(root)
    _write_fixture(root)

    trainer = _make_trainer(root, logdir)
    trainer.fit()
    with pytest.raises(FileExistsError):
        _make_trainer(root, logdir)  # fresh, same logpath -> guarded
    # resume and eval both still allowed
    _make_trainer(root, logdir, resume=True)


def test_wandb_sink_degrades_gracefully(tmp_path, capsys):
    """nowandb=False without the wandb package must warn and no-op, not
    fail (reference main.py:113 defaults to WandbLogger)."""
    from tmr_tpu.utils.wandb_logger import WandbLogger

    logger = WandbLogger("proj", name="run", config={"a": 1})
    # this environment has no wandb package -> disabled but safe to use
    logger.log({"train/loss": 1.0, "epoch": 0}, step=0)
    logger.finish()
    assert not logger.enabled


def test_trainer_refine_box_end_to_end(tmp_path):
    """--refine_box wired through Trainer (VERDICT r2 #3): the Trainer builds
    the refiner, eval runs decode -> refine -> NMS (reference test-step
    order trainer.py:143-150), and refinement actually changes boxes/scores
    relative to an unrefined eval of the same params."""
    import dataclasses

    from tmr_tpu.inference import Predictor
    from tmr_tpu.models.sam_decoder import MaskDecoder, PromptEncoder
    from tmr_tpu.refine import SamRefineModule
    from tmr_tpu.train.loop import Trainer

    root = str(tmp_path / "data")
    logdir = str(tmp_path / "logs")
    os.makedirs(root)
    _write_fixture(root)

    cfg = Config(
        dataset="FSCD147", datapath=root, logpath=logdir,
        backbone="sam_vit_b", emb_dim=16, fusion=True,
        feature_upsample=False, image_size=64,
        positive_threshold=0.5, negative_threshold=0.5,
        NMS_cls_threshold=0.05, NMS_iou_threshold=0.5,
        lr=2e-3, lr_backbone=0.0, max_epochs=1, AP_term=1,
        batch_size=2, num_workers=2, max_gt_boxes=8,
        compute_dtype="float32", max_detections=16,
        template_buckets=(9,), refine_box=True,
    )
    trainer = Trainer(cfg)
    # Trainer must have built and attached a refiner on its own
    assert trainer.predictor.refiner is not None
    assert trainer.predictor.refiner_params is not None

    # swap in the tiny backbone (and a matching-width refiner) for test speed
    tiny = MatchingNet(
        backbone=SamViT(**TINY_VIT), emb_dim=cfg.emb_dim, fusion=True,
        template_capacity=9,
    )
    refiner = SamRefineModule(chunk=4)
    refiner.prompt_encoder = PromptEncoder(embed_dim=TINY_VIT["out_chans"])
    refiner.mask_decoder = MaskDecoder(
        transformer_dim=TINY_VIT["out_chans"], transformer_num_heads=4,
        transformer_mlp_dim=32,
    )
    rparams = refiner.init_params(seed=0)
    trainer.model = tiny
    trainer.predictor = Predictor(
        cfg, model=tiny, refiner=refiner, refiner_params=rparams
    )

    trainer.fit()
    metrics = trainer.test()
    assert np.isfinite(metrics["test/MAE"])

    # same params, refinement off -> different boxes/scores
    params = trainer.state.params
    cfg_off = dataclasses.replace(cfg, refine_box=False)
    plain = Predictor(cfg_off, model=tiny)
    plain.params = params
    trainer.predictor.params = params

    from PIL import Image

    img = np.asarray(
        Image.open(os.path.join(root, "images_384_VarV2", "im0.jpg")),
        np.float32,
    )[None] / 255.0
    ex = np.array([[[0.1, 0.1, 0.3, 0.3]]], np.float32)
    refined = trainer.predictor(img, ex)
    unrefined = plain(img, ex)
    rv = np.asarray(refined["valid"][0])
    uv = np.asarray(unrefined["valid"][0])
    assert rv.any() and uv.any()
    r_scores = np.sort(np.asarray(refined["scores"][0])[rv])
    u_scores = np.sort(np.asarray(unrefined["scores"][0])[uv])
    changed = (
        r_scores.shape != u_scores.shape
        or not np.allclose(r_scores, u_scores)
    )
    assert changed, "refinement had no effect on detections"


def test_trainer_multi_exemplar_eval_branch(tmp_path):
    """num_exemplars > 1 routes eval through the fused multi-exemplar
    program (per-exemplar losses summed + union NMS) end to end."""
    root = str(tmp_path / "data")
    logdir = str(tmp_path / "logs")
    os.makedirs(root)
    _write_fixture(root)

    from tmr_tpu.inference import Predictor
    from tmr_tpu.train.loop import Trainer

    cfg = Config(
        dataset="FSCD147", datapath=root, logpath=logdir,
        backbone="sam_vit_b", emb_dim=16, fusion=True,
        feature_upsample=False, image_size=64,
        positive_threshold=0.5, negative_threshold=0.5,
        NMS_cls_threshold=0.3, NMS_iou_threshold=0.5,
        lr=2e-3, lr_backbone=0.0, max_epochs=1, AP_term=1,
        batch_size=2, num_workers=2, max_gt_boxes=8,
        compute_dtype="float32", max_detections=64,
        template_buckets=(9,), num_exemplars=2,
    )
    trainer = Trainer(cfg)
    tiny = MatchingNet(
        backbone=SamViT(**TINY_VIT), emb_dim=cfg.emb_dim, fusion=True,
        template_capacity=9,
    )
    trainer.model = tiny
    trainer.predictor = Predictor(cfg, model=tiny)
    trainer.fit()
    csv_path = os.path.join(logdir, "metrics.csv")
    content = open(csv_path).read()
    assert "val/AP" in content and "val/loss_ce" in content
    assert np.isfinite(trainer.ckpt.meta["best_value"] or 0.0)


def test_eval_batch_size_matches_bs1_metrics(tmp_path):
    """--eval_batch_size > 1 (TPU throughput mode) must reproduce the bs=1
    reference protocol's AP/MAE/RMSE exactly: detections are per-image and
    the loader only groups same-size images."""
    import dataclasses

    root = str(tmp_path / "data")
    os.makedirs(root)
    _write_fixture(root, n_train=4, n_val=4)

    results = {}
    for bs in (1, 2):
        logdir = str(tmp_path / f"logs_bs{bs}")
        trainer = _make_trainer(root, logdir)
        trainer.cfg = dataclasses.replace(
            trainer.cfg, eval_batch_size=bs, max_epochs=1, logpath=logdir
        )
        trainer.fit()
        results[bs] = trainer.test()

    for key in ("test/AP", "test/AP50", "test/MAE", "test/RMSE"):
        assert np.isclose(results[1][key], results[2][key], atol=1e-6), (
            key, results[1][key], results[2][key]
        )


def test_eval_mode_restore_matches_live_metrics(tmp_path):
    """--eval (fresh process, cfg.eval=True: checkpoint restore + eval-mode
    datasets) must reproduce the live end-of-training test metrics. Guards
    the restore path end to end — a stale/corrupt best checkpoint or an
    eval-only pipeline divergence shows up as a metric gap. Objects are
    >= 25 px so the reference's small-object 1536 escalation (which
    legitimately changes eval-mode resolution) stays out of the comparison."""
    import dataclasses

    from tmr_tpu.data.synthetic import write_synthetic_fscd147

    root = str(tmp_path / "data")
    logdir = str(tmp_path / "logs")
    os.makedirs(root)
    write_synthetic_fscd147(root, n_train=4, n_val=2, square=26)

    trainer = _make_trainer(root, logdir, max_epochs=4)
    trainer.fit()
    _, _, test_loader = trainer._loaders()
    live = trainer.eval_epoch(test_loader, "test", trainer.state.params)

    ev = _make_trainer(root, logdir, eval=True)
    restored = ev.test()
    for key in ("test/AP", "test/AP50", "test/MAE", "test/RMSE"):
        assert np.isclose(live[key], restored[key], atol=1e-6), (
            key, live[key], restored[key]
        )


def test_restore_returns_host_numpy_leaves(tmp_path):
    """CheckpointManager.restore must hand back HOST numpy leaves: orbax
    can return committed device arrays whose sharding annotations pessimize
    every downstream compiled program (measured 9.2x eval slowdown on TPU
    v5 lite — ckpt_probe.json / PERF.md 2026-08-01). The production eval
    path (main --eval -> Trainer.test -> ckpt.restore) relies on this."""
    from tmr_tpu.data.synthetic import write_synthetic_fscd147

    root = str(tmp_path / "data")
    logdir = str(tmp_path / "logs")
    os.makedirs(root)
    write_synthetic_fscd147(root, n_train=2, n_val=1, square=26)

    trainer = _make_trainer(root, logdir, max_epochs=1)
    trainer.fit()

    import jax

    restored = trainer.ckpt.restore(
        trainer.ckpt.last_path(), trainer.state
    )
    leaves = jax.tree.leaves(restored)
    assert leaves
    for leaf in leaves:
        if hasattr(leaf, "shape"):
            assert isinstance(leaf, np.ndarray), type(leaf)


def test_split_per_image_unbatches_everything():
    """Ragged eval tails split into exact B=1 sub-batches (arrays sliced,
    meta list itemized) — the no-recompile path for leftover size buckets."""
    from tmr_tpu.train.loop import Trainer

    batch = {
        "image": np.arange(3 * 4).reshape(3, 2, 2, 1).astype(np.float32),
        "exemplars": np.arange(3 * 4).reshape(3, 1, 4).astype(np.float32),
        "meta": [{"img_id": i} for i in range(3)],
    }
    subs = list(Trainer._split_per_image(batch))
    assert len(subs) == 3
    for i, sub in enumerate(subs):
        assert sub["image"].shape == (1, 2, 2, 1)
        np.testing.assert_array_equal(sub["image"][0], batch["image"][i])
        assert sub["meta"] == [{"img_id": i}]


def test_eval_batch_size_forced_to_one_for_multi_exemplar(tmp_path, capsys):
    """num_exemplars > 1 forces eval loaders to bs=1 with an explicit
    warning (the multi-exemplar meta plumbing is per-image)."""
    from tmr_tpu.data.synthetic import write_synthetic_fscd147

    root = str(tmp_path / "data")
    os.makedirs(root)
    write_synthetic_fscd147(root, n_train=2, n_val=2)
    tr = _make_trainer(root, str(tmp_path / "logs"),
                       num_exemplars=2, eval_batch_size=4)
    _, val, test = tr._loaders()
    assert val.batch_size == 1 and test.batch_size == 1
    assert "forced to 1" in capsys.readouterr().err


def test_pp_trainer_fit_and_eval(tmp_path):
    """Pipeline-parallel Trainer wiring (--mesh_pipe): fit on a ('data',
    'pipe') mesh with stage-sharded params + optimizer moments, validate
    (eval consumes the dense layout via unstack), checkpoint, and test-eval
    from the restored pp state. Convergence smoke: train loss decreases."""
    import csv

    import jax
    from tmr_tpu.parallel.mesh import make_mesh
    from tmr_tpu.train.loop import Trainer

    root = str(tmp_path / "data")
    logdir = str(tmp_path / "logs")
    os.makedirs(root)
    _write_fixture(root)

    mesh = make_mesh((1, 2), ("data", "pipe"), devices=jax.devices()[:2])
    cfg = Config(
        dataset="FSCD147", datapath=root, logpath=logdir,
        backbone="sam_vit_b", emb_dim=16, fusion=True,
        feature_upsample=False, image_size=64,
        positive_threshold=0.5, negative_threshold=0.5,
        NMS_cls_threshold=0.3, NMS_iou_threshold=0.5,
        lr=2e-3, lr_backbone=1e-3, max_epochs=2, AP_term=1,
        batch_size=2, num_workers=0, max_gt_boxes=8,
        compute_dtype="float32", max_detections=64,
        template_buckets=(9,), mesh_pipe=2,
    )
    trainer = Trainer(cfg, mesh=mesh)
    # 4 blocks -> 2 homogeneous stages (1 windowed + 1 global each)
    tiny = MatchingNet(
        backbone=SamViT(**dict(TINY_VIT, depth=4, global_attn_indexes=(1, 3))),
        emb_dim=cfg.emb_dim, fusion=True, template_capacity=9,
    )
    trainer.model = tiny
    trainer.predictor = Predictor(cfg, model=tiny)
    trainer.fit()

    # stage-major layout actually trained and was checkpointed
    assert "stages" in trainer.state.params["backbone"]
    rows = list(
        csv.DictReader(open(os.path.join(logdir, "metrics.csv")))
    )
    losses = [float(r["train/loss"]) for r in rows]
    assert losses[-1] < losses[0], losses
    assert np.isfinite(float(rows[-1]["val/MAE"]))

    metrics = trainer.test()
    assert np.isfinite(metrics["test/MAE"])

    # pp --resume: the stage-major TrainState (params + AdamW moments)
    # restores from the orbax checkpoint and training continues one more
    # epoch without error
    import dataclasses

    cfg2 = dataclasses.replace(trainer.cfg, resume=True, max_epochs=3)
    trainer2 = Trainer(cfg2, mesh=mesh)
    trainer2.model = trainer.model
    trainer2.predictor = Predictor(cfg2, model=trainer.model)
    trainer2.fit()
    assert trainer2.ckpt.meta["last_epoch"] == 2
    assert "stages" in trainer2.state.params["backbone"]
    # resume-SPECIFIC evidence: only epoch 2 ran (2 prior + 1 resumed row
    # in the shared metrics.csv) — a silent restart-from-scratch would
    # append three fresh rows
    rows = list(
        csv.DictReader(open(os.path.join(logdir, "metrics.csv")))
    )
    assert len(rows) == 3, [r.get("epoch") for r in rows]
    assert rows[-1]["epoch"] == "2", rows[-1]


def test_data_sharded_eval_matches_single_device(tmp_path):
    """--eval_batch_size divisible by the 'data' axis: the fused eval
    program runs data-sharded (the reference's DDP eval spreads ranks the
    same way). Metrics must equal the unsharded run on the same params."""
    import jax
    from tmr_tpu.parallel.mesh import make_mesh

    root = str(tmp_path / "data")
    os.makedirs(root)
    _write_fixture(root)

    def build(logdir, mesh):
        from tmr_tpu.train.loop import Trainer

        cfg = Config(
            dataset="FSCD147", datapath=root, logpath=logdir,
            backbone="sam_vit_b", emb_dim=16, fusion=True,
            feature_upsample=False, image_size=64,
            positive_threshold=0.5, negative_threshold=0.5,
            NMS_cls_threshold=0.3, NMS_iou_threshold=0.5,
            lr=2e-3, lr_backbone=0.0, max_epochs=1, AP_term=1,
            batch_size=2, num_workers=0, max_gt_boxes=8,
            compute_dtype="float32", max_detections=64,
            # NOT eval=True: that flips the reference's <25px -> large-
            # bucket escalation, which the 10px fixture squares trigger
            template_buckets=(9,), eval_batch_size=2,
        )
        trainer = Trainer(cfg, mesh=mesh)
        tiny = MatchingNet(
            backbone=SamViT(**TINY_VIT), emb_dim=cfg.emb_dim, fusion=True,
            template_capacity=9,
        )
        trainer.model = tiny
        trainer.predictor = Predictor(cfg, model=tiny)
        return trainer

    t_plain = build(str(tmp_path / "log1"), None)
    params = t_plain.predictor.init_params(seed=3, image_size=64)
    want = t_plain.test(params=params)

    mesh = make_mesh((2, 1), devices=jax.devices()[:2])
    t_mesh = build(str(tmp_path / "log2"), mesh)
    got = t_mesh.test(params=params)

    for k in ("test/AP", "test/AP50", "test/MAE", "test/RMSE"):
        assert np.isclose(got[k], want[k], rtol=1e-4, atol=1e-5), (
            k, got[k], want[k]
        )
    for k in ("test/loss", "test/loss_ce"):
        assert np.isclose(got[k], want[k], rtol=1e-3, atol=1e-5), (
            k, got[k], want[k]
        )
