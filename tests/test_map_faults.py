"""Retrying shard executor (mapreduce._run_stream_impl) under injected
faults: retry-to-success, bounded-retry quarantine, hung-shard timeout,
NaN exclusion, atomic/idempotent feature writes, corrupt-image counters,
and the map_report/v1 document."""

import glob
import io
import os
import tarfile
import time

import jax.numpy as jnp
import numpy as np
import pytest

import tmr_tpu.parallel.mapreduce as mr
from tmr_tpu.diagnostics import validate_map_report
from tmr_tpu.utils import faults

SIZE = 8


@pytest.fixture(autouse=True)
def _clean_schedule():
    faults.clear()
    yield
    faults.clear()


def _make_tar(dirpath, name, n_images, seed):
    from PIL import Image

    rng = np.random.default_rng(seed)
    path = os.path.join(dirpath, name)
    with tarfile.open(path, "w") as tar:
        for i in range(n_images):
            img = Image.fromarray(
                rng.integers(0, 255, (12, 12, 3), dtype=np.uint8)
            )
            buf = io.BytesIO()
            img.save(buf, format="PNG")
            data = buf.getvalue()
            info = tarfile.TarInfo(f"img_{i}.png")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    return path


def _encode(images):
    feats = jnp.asarray(images)[:, ::2, ::2, :] - 0.5
    return feats, mr.feature_stats(feats)


def _fast_retry(**kw):
    kw.setdefault("max_attempts", 3)
    kw.setdefault("backoff_base", 0.001)
    kw.setdefault("backoff_jitter", 0.0)
    return mr.RetryPolicy(**kw)


@pytest.fixture
def shards(tmp_path):
    return [
        _make_tar(str(tmp_path), "Easy_0.tar", 3, 0),
        _make_tar(str(tmp_path), "Normal_0.tar", 2, 1),
        _make_tar(str(tmp_path), "Hard_0.tar", 2, 2),
    ]


def test_transient_fault_retried_to_identical_table(shards):
    ref = mr.run_stream(shards, _encode, batch_size=2, image_size=SIZE)

    faults.configure("tar.open:shard=0:attempts=2:raise=OSError")
    report = mr.MapReport()
    acc = mr.run_stream(
        shards, _encode, batch_size=2, image_size=SIZE,
        retry=_fast_retry(), report=report,
    )
    np.testing.assert_array_equal(acc.table, ref.table)
    doc = report.document()
    assert validate_map_report(doc) == []
    rec = doc["shards"][0]
    assert rec["status"] == "ok" and rec["attempts"] == 3
    assert [c["cause"] for c in rec["causes"]] == ["exception", "exception"]
    assert "OSError" in rec["causes"][0]["error"]
    assert doc["totals"]["retries"] == 2 and doc["quarantined"] == []


def test_permanent_fault_quarantines_without_aborting(shards):
    faults.configure("tar.open:shard=1:raise=OSError")
    report = mr.MapReport()
    acc = mr.run_stream(
        shards, _encode, batch_size=2, image_size=SIZE,
        retry=_fast_retry(max_attempts=2), report=report,
    )
    doc = report.document()
    assert doc["quarantined"] == ["Normal_0.tar"]
    rec = doc["shards"][1]
    assert rec["status"] == "quarantined" and rec["attempts"] == 2
    # the other shards still landed: Easy 3 images, Hard 2, Normal none
    assert acc.table[0, 4] == 3
    assert acc.table[1, 4] == 0
    assert acc.table[2, 4] == 2


def test_hung_shard_quarantined_within_budget(shards):
    faults.configure("tar.open:shard=0:latency=3.0")
    report = mr.MapReport()
    t0 = time.monotonic()
    acc = mr.run_stream(
        shards, _encode, batch_size=2, image_size=SIZE,
        retry=_fast_retry(max_attempts=2, shard_timeout=0.25),
        report=report,
    )
    elapsed = time.monotonic() - t0
    doc = report.document()
    rec = doc["shards"][0]
    assert rec["status"] == "quarantined"
    assert [c["cause"] for c in rec["causes"]] == ["timeout", "timeout"]
    # the run made progress instead of wedging on the hung read
    assert acc.table[1, 4] == 2 and acc.table[2, 4] == 2
    assert elapsed < 2.5, f"hung shard held the run for {elapsed:.2f}s"


def test_corrupt_tar_quarantines_on_first_attempt(tmp_path, shards):
    (tmp_path / "broken.tar").write_bytes(b"definitely not a tar")
    report = mr.MapReport()
    acc = mr.run_stream(
        shards + [str(tmp_path / "broken.tar")], _encode, batch_size=2,
        image_size=SIZE, retry=_fast_retry(), report=report,
    )
    rec = report.document()["shards"][3]
    # deterministic corruption is non-retryable: one attempt, quarantined
    assert rec["status"] == "quarantined" and rec["attempts"] == 1
    assert acc.table[:, 4].sum() == 7


def test_missing_shard_quarantines_without_backoff(tmp_path, shards):
    """A shard path that does not exist reads the same on every attempt —
    non-retryable, so a stale shard list doesn't burn the backoff budget
    (the old load_shard skipped instantly; quarantine keeps that cost)."""
    report = mr.MapReport()
    mr.run_stream(
        shards + [str(tmp_path / "no_such.tar")], _encode, batch_size=2,
        image_size=SIZE, retry=_fast_retry(backoff_base=30.0), report=report,
    )
    rec = report.document()["shards"][3]
    assert rec["status"] == "quarantined" and rec["attempts"] == 1
    assert "FileNotFoundError" in rec["causes"][0]["error"]


def test_quarantined_shard_reports_zero_images(shards):
    """A shard whose encode succeeded but whose journal commit keeps
    failing is quarantined — its images never reached the table, so the
    report must say 0, keeping totals reconcilable with the count column."""
    from tmr_tpu.parallel.journal import ShardJournal
    import tempfile

    faults.configure("journal:shard=0:raise=OSError")
    report = mr.MapReport()
    with tempfile.TemporaryDirectory() as d:
        acc = mr.run_stream(
            shards, _encode, batch_size=2, image_size=SIZE,
            retry=_fast_retry(max_attempts=2), report=report,
            journal=ShardJournal(d),
        )
    doc = report.document()
    rec = doc["shards"][0]
    assert rec["status"] == "quarantined"
    assert rec["images"] == 0 and rec["nonfinite_images"] == 0
    assert doc["totals"]["images"] == acc.table[:, 4].sum() == 4
    assert acc.table[0, 4] == 0  # Easy never folded in


def test_nan_outputs_excluded_and_counted(shards):
    ref = mr.run_stream(shards, _encode, batch_size=2, image_size=SIZE)
    faults.configure("encode:shard=0:nan=1")
    report = mr.MapReport()
    acc = mr.run_stream(
        shards, _encode, batch_size=2, image_size=SIZE,
        retry=_fast_retry(), report=report,
    )
    doc = report.document()
    assert doc["shards"][0]["nonfinite_images"] == 3
    assert doc["totals"]["nonfinite_images"] == 3
    assert np.isfinite(acc.table).all()
    assert acc.table[0, 4] == 0  # poisoned images out of the Easy sums
    np.testing.assert_array_equal(acc.table[1:], ref.table[1:])


def test_undecodable_images_counted_not_silent(shards):
    """A half-corrupt dataset must not look identical to a clean one:
    injected byte corruption at decode shows up in skipped_images and the
    report totals (satellite: iter_tar_images/preprocess_image drops are
    counted per shard)."""
    faults.configure("decode:shard=2:corrupt=1")
    report = mr.MapReport()
    acc = mr.run_stream(
        shards, _encode, batch_size=2, image_size=SIZE,
        retry=_fast_retry(), report=report,
    )
    doc = report.document()
    assert doc["shards"][2]["skipped_images"] == 2
    assert doc["totals"]["skipped_images"] == 2
    assert doc["shards"][2]["status"] == "ok"
    assert acc.table[2, 4] == 0


def test_save_fault_retries_idempotently(tmp_path, shards):
    out = tmp_path / "features"

    def save(shard, name, feat):
        d = out / shard.replace(".tar", "")
        os.makedirs(d, exist_ok=True)
        mr.atomic_save_npy(
            str(d / (os.path.splitext(name)[0] + ".npy")), feat
        )

    ref = mr.run_stream(
        shards, _encode, batch_size=2, image_size=SIZE, save_features=save,
    )
    want = {
        p: open(p, "rb").read()
        for p in sorted(glob.glob(str(out / "**" / "*.npy"), recursive=True))
    }
    assert len(want) == 7

    import shutil

    shutil.rmtree(out)
    faults.configure("save:shard=0:attempts=1:raise=OSError")
    acc = mr.run_stream(
        shards, _encode, batch_size=2, image_size=SIZE, save_features=save,
        retry=_fast_retry(),
    )
    got = {
        p: open(p, "rb").read()
        for p in sorted(glob.glob(str(out / "**" / "*.npy"), recursive=True))
    }
    assert got == want  # identical set, identical bytes — no partials/dupes
    assert not glob.glob(str(out / "**" / "*.tmp.*"), recursive=True)
    np.testing.assert_array_equal(acc.table, ref.table)


def test_slow_but_progressing_shard_is_not_quarantined(shards):
    """The timeout is a STALL budget, not total load time: a shard whose
    members keep arriving — just slowly — must never quarantine, even
    when its total load time exceeds shard_timeout."""
    faults.configure("tar.member:shard=0:latency=0.2")  # 3 members -> 0.6s
    report = mr.MapReport()
    acc = mr.run_stream(
        shards, _encode, batch_size=2, image_size=SIZE,
        retry=_fast_retry(max_attempts=2, shard_timeout=0.45),
        report=report,
    )
    rec = report.document()["shards"][0]
    assert rec["status"] == "ok" and rec["causes"] == []
    assert rec["wall_s"] > 0.45  # genuinely slower than the stall budget
    assert acc.table[0, 4] == 3


def test_quarantined_shard_partial_features_cleaned(tmp_path, shards):
    """A shard quarantined after encode+save (journal commit keeps
    failing) must not leave orphan .npy files that are in neither the
    table nor the report totals."""
    from tmr_tpu.parallel.journal import ShardJournal

    out = tmp_path / "features"

    def save(shard, name, feat):
        d = out / shard.replace(".tar", "")
        os.makedirs(d, exist_ok=True)
        mr.atomic_save_npy(
            str(d / (os.path.splitext(name)[0] + ".npy")), feat
        )

    def cleanup(shard):
        import shutil

        shutil.rmtree(out / shard.replace(".tar", ""), ignore_errors=True)

    faults.configure("journal:shard=0:raise=OSError")
    mr.run_stream(
        shards, _encode, batch_size=2, image_size=SIZE, save_features=save,
        retry=_fast_retry(max_attempts=2),
        journal=ShardJournal(str(tmp_path / "_journal")),
        cleanup_features=cleanup,
    )
    got = sorted(glob.glob(str(out / "**" / "*.npy"), recursive=True))
    # Easy_0's saves were rolled back; Normal_0 + Hard_0 remain (2+2)
    assert len(got) == 4
    assert not any("Easy_0" in p for p in got)


def test_native_path_shares_executor_semantics(shards):
    """run_stream_native goes through the same retrying executor: a
    transient tar.open fault retries to the identical table, and the
    report carries the same per-shard records as the Python path."""
    from tmr_tpu.data import native_io

    if not native_io.available():
        pytest.skip("no g++/make to build libtmr_io.so")
    ref = mr.run_stream(shards, _encode, batch_size=2, image_size=SIZE)
    faults.configure("tar.open:shard=0:attempts=1:raise=OSError")
    report = mr.MapReport()
    acc = mr.run_stream_native(
        shards, _encode, batch_size=2, image_size=SIZE,
        retry=_fast_retry(), report=report,
    )
    np.testing.assert_allclose(acc.table, ref.table, rtol=1e-6)
    doc = report.document()
    assert validate_map_report(doc) == []
    assert doc["shards"][0]["status"] == "ok"
    assert doc["shards"][0]["attempts"] == 2


def test_heartbeat_beats_for_every_scanned_member(tmp_path):
    """The stall detector's heartbeat must advance on every member the
    tar read passes — non-image and undecodable ones included — so a
    shard with a long run of skipped members is never falsely declared
    stalled."""
    path = os.path.join(str(tmp_path), "Easy_mixed.tar")
    with tarfile.open(path, "w") as tar:
        for name, payload in [
            ("notes.txt", b"x"), ("bad.jpg", b"not an image"),
            ("more.txt", b"y"),
        ]:
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    beats = []
    images = list(
        mr.iter_tar_images(path, heartbeat=lambda: beats.append(1))
    )
    assert images == []
    assert len(beats) == 3  # every member scanned beat, none decoded


def test_iter_tar_images_counts_unreadable_members(tmp_path):
    """tar members whose payload PIL rejects are tallied, not silently
    dropped (the pre-existing skip behavior keeps working)."""
    from PIL import Image

    path = os.path.join(str(tmp_path), "Easy_bad.tar")
    with tarfile.open(path, "w") as tar:
        buf = io.BytesIO()
        Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(buf, format="PNG")
        good = buf.getvalue()
        info = tarfile.TarInfo("good.png")
        info.size = len(good)
        tar.addfile(info, io.BytesIO(good))
        bad = b"not an image"
        info = tarfile.TarInfo("bad.jpg")
        info.size = len(bad)
        tar.addfile(info, io.BytesIO(bad))
    counts = {}
    images = list(mr.iter_tar_images(path, counts=counts))
    assert [n for n, _ in images] == ["good.png"]
    assert counts == {"skipped_images": 1}
