"""Promotion of user-cache autotune winners into the committed seed
(scripts/promote_cache_to_seed.py): stamped-fresh winners are promoted,
stale ones are not, and full-program pins (which outrank one-block sweep
winners) are preserved.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KEY = "TPU v5 lite|1024|128|4|512|vit_b"


def _promoter():
    spec = importlib.util.spec_from_file_location(
        "promote_cache_to_seed",
        os.path.join(REPO, "scripts", "promote_cache_to_seed.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def paths(tmp_path, monkeypatch):
    cache = tmp_path / "cache.json"
    seed = tmp_path / "seed.json"
    monkeypatch.setenv("TMR_AUTOTUNE_CACHE", str(cache))
    monkeypatch.setenv("TMR_AUTOTUNE_SEED", str(seed))
    return cache, seed


def test_fresh_winners_promote_and_stale_do_not(paths, capsys):
    from tmr_tpu.utils.autotune import _variants_sig

    cache, seed = paths
    cache.write_text(json.dumps({KEY: {
        "TMR_GLOBAL_ATTN": "pallas",
        "_variants_TMR_GLOBAL_ATTN": _variants_sig("TMR_GLOBAL_ATTN"),
        "TMR_WIN_ATTN": "flash",
        "_variants_TMR_WIN_ATTN": "stale,old,set",  # stale: must not move
        "TMR_BENCH_BATCH": "8",
    }}))
    seed.write_text(json.dumps({KEY: {
        "TMR_GLOBAL_ATTN": "blockwise",
        "_variants_TMR_GLOBAL_ATTN": "old",
        "TMR_WIN_ATTN": "dense",
        "_variants_TMR_WIN_ATTN": "old",
    }}))
    rc = _promoter().main([])
    assert rc == 0
    out = json.loads(seed.read_text())[KEY]
    assert out["TMR_GLOBAL_ATTN"] == "pallas"
    assert out["_variants_TMR_GLOBAL_ATTN"] == _variants_sig(
        "TMR_GLOBAL_ATTN"
    )
    # the stale-stamped windowed winner did NOT launder into the seed
    assert out["TMR_WIN_ATTN"] == "dense"
    assert out["_variants_TMR_WIN_ATTN"] == "old"
    # measured batch rides along
    assert out["TMR_BENCH_BATCH"] == "8"


def test_full_program_pins_outrank_sweep_winners(paths, capsys):
    from tmr_tpu.utils.autotune import _variants_sig

    cache, seed = paths
    cache.write_text(json.dumps({KEY: {
        "TMR_WIN_ATTN": "flash",
        "_variants_TMR_WIN_ATTN": _variants_sig("TMR_WIN_ATTN"),
        "TMR_XCORR_IMPL_SMALL": "vmap",
        "_variants_TMR_XCORR_IMPL_SMALL": _variants_sig(
            "TMR_XCORR_IMPL_SMALL"
        ),
    }}))
    # seed entry written by pick_full_program: dense won the WHOLE-program
    # A/B — the sweep's one-block flash pick must not overwrite it
    seed.write_text(json.dumps({KEY: {
        "TMR_WIN_ATTN": "dense",
        "_variants_TMR_WIN_ATTN": _variants_sig("TMR_WIN_ATTN"),
        "_full_program_ab": "{}",
    }}))
    rc = _promoter().main([])
    assert rc == 0
    out = json.loads(seed.read_text())[KEY]
    assert out["TMR_WIN_ATTN"] == "dense"          # preserved
    assert out["_full_program_ab"] == "{}"         # marker intact
    assert out["TMR_XCORR_IMPL_SMALL"] == "vmap"   # non-block knob promoted


def test_stale_full_program_pin_does_not_block_promotion(paths, capsys):
    """Once a sweep-revision bump stales a full-program pin's stamp, the
    runtime drops it and re-sweeps — so the fresh sweep winner MUST
    promote, or every fresh container re-sweeps over the tunnel forever
    (review finding r5)."""
    from tmr_tpu.utils.autotune import _variants_sig

    cache, seed = paths
    cache.write_text(json.dumps({KEY: {
        "TMR_WIN_ATTN": "flash",
        "_variants_TMR_WIN_ATTN": _variants_sig("TMR_WIN_ATTN"),
    }}))
    seed.write_text(json.dumps({KEY: {
        "TMR_WIN_ATTN": "dense",
        "_variants_TMR_WIN_ATTN": "pre-revision,stale",
        "_full_program_ab": "{}",
    }}))
    rc = _promoter().main([])
    assert rc == 0
    out = json.loads(seed.read_text())[KEY]
    assert out["TMR_WIN_ATTN"] == "flash"


def test_overwritten_stale_pin_loses_its_marker(paths, capsys):
    """When a stale full-program pin is replaced by a sweep winner, the
    _full_program_ab marker must go with it — otherwise the sweep pick
    inherits pin-level protection it never earned and blocks every later
    fresh sweep winner (review finding r5)."""
    from tmr_tpu.utils.autotune import _variants_sig

    cache, seed = paths
    cache.write_text(json.dumps({KEY: {
        "TMR_WIN_ATTN": "flash",
        "_variants_TMR_WIN_ATTN": _variants_sig("TMR_WIN_ATTN"),
    }}))
    seed.write_text(json.dumps({KEY: {
        "TMR_WIN_ATTN": "dense",
        "_variants_TMR_WIN_ATTN": "pre-revision,stale",
        "_full_program_ab": "{}",
    }}))
    assert _promoter().main([]) == 0
    out = json.loads(seed.read_text())[KEY]
    assert out["TMR_WIN_ATTN"] == "flash"
    assert "_full_program_ab" not in out


def test_lone_precision_impl_does_not_ride(paths, capsys):
    """_precision_impl moves only with its owner TMR_XCORR_PRECISION: a
    stale precision winner's pairing must not overwrite the seed's
    validated pairing (review finding r5)."""
    cache, seed = paths
    cache.write_text(json.dumps({KEY: {
        "TMR_XCORR_PRECISION": "bf16",
        "_variants_TMR_XCORR_PRECISION": "stale",  # owner NOT promoted
        "_precision_impl": "vmap",
        "TMR_BENCH_BATCH": "8",  # independent: rides alone
    }}))
    seed.write_text(json.dumps({KEY: {
        "TMR_XCORR_PRECISION": "default",
        "_precision_impl": "conv",
    }}))
    rc = _promoter().main([])
    assert rc == 0
    out = json.loads(seed.read_text())[KEY]
    assert out["_precision_impl"] == "conv"  # pairing untouched
    assert out["TMR_XCORR_PRECISION"] == "default"
    assert out["TMR_BENCH_BATCH"] == "8"


def test_corrupt_seed_entry_degrades_gracefully(paths, capsys):
    """A non-dict seed entry (hand-edited file) must degrade to absent,
    not crash the promote stage (review finding r5)."""
    from tmr_tpu.utils.autotune import _variants_sig

    cache, seed = paths
    cache.write_text(json.dumps({KEY: {
        "TMR_GLOBAL_ATTN": "pallas",
        "_variants_TMR_GLOBAL_ATTN": _variants_sig("TMR_GLOBAL_ATTN"),
    }}))
    seed.write_text(json.dumps({KEY: "corrupt-string-entry"}))
    rc = _promoter().main([])
    assert rc == 0
    out = json.loads(seed.read_text())[KEY]
    assert out["TMR_GLOBAL_ATTN"] == "pallas"


def test_nothing_to_promote(paths, capsys):
    cache, seed = paths
    cache.write_text(json.dumps({KEY: {
        "TMR_WIN_ATTN": "flash",
        "_variants_TMR_WIN_ATTN": "stale",
    }}))
    before = json.dumps({KEY: {"TMR_WIN_ATTN": "dense"}})
    seed.write_text(before)
    rc = _promoter().main([])
    assert rc == 3
    assert seed.read_text() == before
