"""The driver's benchmark entry (bench.py) — one JSON line, correct keys.

Runs the real script in a subprocess at tiny CPU shapes. The subprocess env
drops PALLAS_AXON_POOL_IPS so the axon sitecustomize never dials the TPU
relay (PERF.md: a wedged tunnel would hang any process that does).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))



import pytest

pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

def _bench_env(**extra):
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env.update(
        JAX_PLATFORMS="cpu",
        TMR_BENCH_SIZE="256",
        TMR_BENCH_BATCH="1",
        TMR_BENCH_CHAIN="2",
        **extra,
    )
    # per-stage tail timings and the program-tier audit are exercised by
    # their dedicated tests below; the other subprocess runs skip them
    # to stay in budget
    env.setdefault("TMR_BENCH_STAGES", "0")
    env.setdefault("TMR_BENCH_AUDIT", "0")
    return env


def test_bench_prints_one_json_line_with_required_keys():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(), capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mfu",
                "ms_per_batch", "autotuned"):
        assert key in rec, key
    assert rec["unit"] == "img/s"
    assert rec["value"] > 0
    # stage progress goes to stderr, never stdout
    assert "[bench +" in out.stderr


def _committed_live():
    """The repo's committed BENCH_LIVE.json value (None when absent or an
    outage record) — the number a failed probe must carry, not erase."""
    live_path = os.path.join(REPO, "BENCH_LIVE.json")
    if not os.path.exists(live_path):
        return None
    with open(live_path) as f:
        live = json.load(f)
    if not isinstance(live, dict) or "error" in live or not live.get("value"):
        return None
    return live


def _assert_outage_record(rec):
    """Shared contract for watchdog/fast-failure records: when the repo
    holds a live measurement the record carries it as the HEADLINE value
    (carried: true + stale_hours — a driver keying on `value` must never
    read 0.0 while a committed number exists); with no live file the
    value is an honest 0.0."""
    live = _committed_live()
    if live is not None:
        assert rec["value"] == live["value"]
        assert rec["carried"] is True
        assert rec["stale_hours"] >= 0
        assert rec["vs_baseline"] > 0
    else:
        assert rec["value"] == 0.0


def test_bench_records_validated_stage_breakdown():
    """With TMR_BENCH_STAGES on (the default), the bench record embeds a
    ``stage_breakdown`` that passes diagnostics.validate_stage_breakdown:
    seconds (or a recorded error) for the decoder_heads and decode_tail
    stages plus the formulation stamps saying what actually traced — the
    per-stage visibility the MFU push needs across rounds."""
    from tmr_tpu.diagnostics import validate_stage_breakdown

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(TMR_BENCH_STAGES="1", TMR_BENCH_AUDIT="1"),
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    sb = rec["stage_breakdown"]
    assert validate_stage_breakdown(sb) == [], sb
    # off-TPU the knobs sit at their defaults; both stages must have
    # actually measured (an error string here means the harness broke)
    assert sb["decoder_impl"] == "xla"
    assert sb["quant"] == "off"
    assert sb["decode_tail"] == "host"
    assert sb["decoder_heads_s"] > 0
    assert sb["decode_tail_s"] > 0
    # the program-tier audit verdict rides the same record: the elected
    # configuration's traced programs pass the jaxpr invariants, and a
    # failure would carry structured program_audit refusal causes
    # (diagnostics.gate_refused — the kernel-gate contract)
    audit = rec["program_audit"]
    assert audit["ok"] is True, audit
    assert audit["refusals"] == []
    assert audit["programs"]["match_heads"] is True


def test_bench_watchdog_emits_error_line(tmp_path):
    # a 1s alarm beats even a fully cache-warm run (interpreter + jax init
    # alone exceed it); a cold per-test compilation cache double-insures
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(
            TMR_BENCH_ALARM="1",
            TMR_COMPILATION_CACHE=str(tmp_path / "xla-cache"),
        ),
        capture_output=True, text=True, timeout=300,
    )
    # non-zero exit so a driver keying on status sees the wedge as a failure
    assert out.returncode == 2
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "watchdog" in rec["error"]
    _assert_outage_record(rec)


def test_bench_fast_failure_emits_error_line():
    # round 3's actual failure mode: a fast exception (jax.devices()
    # RuntimeError) long before the watchdog — must still yield the one
    # contractual JSON line, not a raw traceback (BENCH_r03.json regression)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(TMR_BENCH_SELFTEST_FAIL="1"),
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 1
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    rec = json.loads(lines[0])
    assert "selftest" in rec["error"]
    for key in ("metric", "value", "unit", "vs_baseline", "error"):
        assert key in rec, key
    # an outage record carries the last committed live measurement (with
    # provenance) AND promotes it to the headline value — a round-end
    # wedge must never erase the round's number (three consecutive rounds
    # of rc!=0/0.0 records while 21 img/s sat committed)
    _assert_outage_record(rec)
    live = _committed_live()
    if live is not None:
        # a clean checkout carries provenance; a working tree where the
        # watcher just dropped a fresh (uncommitted) measurement gets
        # the clearly-labeled uncommitted key instead
        if "last_committed_live" in rec:
            assert rec["last_committed_live"]["value"] == live["value"]
            assert rec["last_committed_live"]["committed_at"]
            # the driver must be able to see exactly how old the
            # carried number is (VERDICT r4 #6)
            assert rec["last_committed_live"]["stale_hours"] >= 0
        else:
            assert rec["last_live_uncommitted"]["value"] == live["value"]
            assert rec["last_live_uncommitted"]["stale_hours"] >= 0


def test_bench_preliminary_survives_post_measure_failure():
    """A failure AFTER the pre-sweep preliminary measurement banked must
    print the real measurement (annotated, rc 0), not a zero-value outage
    record — a wedge during the sweeps can no longer erase a completed
    headline (VERDICT r4 #6 follow-through)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(TMR_BENCH_SELFTEST_PRELIM="1"),
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    rec = json.loads(lines[0])
    assert rec["value"] > 0
    assert rec["preliminary"] is True
    assert "selftest" in rec["sweep_aborted"]


def test_bench_restores_checkpoint(tmp_path):
    # plumbing mode: --epochs 0 saves init params in the exact bench model
    # layout; bench must restore them and say so in the metric line
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "make_bench_ckpt.py"),
         "--epochs", "0", "--image_size", "64", "--compute_dtype", "float32",
         "--out", str(tmp_path / "bench_ckpt")],
        env=_bench_env(), capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    ckpt = str(tmp_path / "bench_ckpt" / "params")
    assert os.path.isdir(ckpt)

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(TMR_BENCH_CKPT=ckpt),
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "restored ckpt" in rec["metric"]
    assert rec["value"] > 0
    assert "params restored" in out.stderr


def test_gate_probe_json_contract(tmp_path):
    """scripts/gate_probe.py --json must emit ONE gate_probe/v1 document
    whose probes carry structured refusal causes (exception class/message,
    tile config, device kind) — exercised end-to-end with a FORCED refusal
    (TMR_NO_FLASH_ATTN kill-switch) so at least one cause is guaranteed
    regardless of backend, alongside the organic off-TPU backend
    refusals. --out writes the same document (the committed artifact)."""
    out_path = str(tmp_path / "gate_probe.json")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "gate_probe.py"),
         "--json", "--out", out_path],
        env=_bench_env(TMR_NO_FLASH_ATTN="1"),
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["schema"] == "gate_probe/v1"
    assert doc["backend"]["default_backend"]
    by_name = {p["probe"]: p for p in doc["probes"]}
    # the forced kill-switch refusal must surface with its structured cause
    flash = by_name["flash_global_64x64_d64"]
    assert flash["ok"] is False
    causes = flash["refusals"]
    assert causes and causes[0]["gate"] == "flash_attention_ok"
    assert causes[0]["cause"] == "kill-switch"
    assert causes[0]["device_kind"]
    assert causes[0]["config"]["gh"] == 64
    # the program-tier audit rides the probe document: the production
    # programs traced under the ambient env pass the jaxpr invariants
    # (reduced geometry off-TPU; the per-platform transfer pins make
    # this hold under the CPU backend too)
    audit = by_name["program_audit"]
    assert audit["ok"] is True, audit
    assert audit["problems"] == []
    assert "gate_state" in audit
    # every refused gate row carries at least one cause record, and the
    # flat aggregate collects them all
    refused = [p for p in doc["probes"]
               if p.get("ok") is False and "refusals" in p]
    assert refused
    for p in refused:
        assert p["refusals"], p["probe"]
        for c in p["refusals"]:
            assert c["schema"] == "gate_probe/v1"
            assert c["cause"]
    assert len(doc["refusals"]) >= len(refused)
    # the --out artifact is the same document
    with open(out_path) as f:
        on_disk = json.load(f)
    assert on_disk["schema"] == "gate_probe/v1"
    assert len(on_disk["probes"]) == len(doc["probes"])


def test_bench_extra_emits_json_on_failure_and_success(tmp_path):
    """bench_extra.py shares bench.py's contract: ONE JSON line no matter
    what (round 3 died at unguarded backend init; per-config errors were
    already inline but everything outside them wasn't)."""
    script = os.path.join(REPO, "scripts", "bench_extra.py")
    # success path at tiny shapes, single cheapest config
    out = subprocess.run(
        [sys.executable, script, "--only", "demo"],
        env=_bench_env(TMR_BENCH_TINY="1"),
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "demo" in rec and "device" in rec

    # an unknown --only name is caught by the per-config guard: still one
    # JSON line, error recorded inline, rc 0
    out = subprocess.run(
        [sys.executable, script, "--only", "nonsense"],
        env=_bench_env(), capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "error" in rec["nonsense"]

    # fast-fail OUTSIDE the per-config guards (round 3's bench.py death
    # mode): backend init fails -> one error-JSON line, rc 1
    out = subprocess.run(
        [sys.executable, script, "--only", "demo"],
        env={**_bench_env(), "JAX_PLATFORMS": "bogus"},
        capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 1
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "error" in rec

    # watchdog path
    out = subprocess.run(
        [sys.executable, script, "--only", "demo"],
        env=_bench_env(TMR_BENCH_TINY="1", TMR_BENCH_ALARM="1"),
        capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 2
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "watchdog" in rec["error"]
