"""The driver's benchmark entry (bench.py) — one JSON line, correct keys.

Runs the real script in a subprocess at tiny CPU shapes. The subprocess env
drops PALLAS_AXON_POOL_IPS so the axon sitecustomize never dials the TPU
relay (PERF.md: a wedged tunnel would hang any process that does).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))



import pytest

pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

def _bench_env(**extra):
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env.update(
        JAX_PLATFORMS="cpu",
        TMR_BENCH_SIZE="256",
        TMR_BENCH_BATCH="1",
        TMR_BENCH_CHAIN="2",
        **extra,
    )
    return env


def test_bench_prints_one_json_line_with_required_keys():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(), capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mfu",
                "ms_per_batch", "autotuned"):
        assert key in rec, key
    assert rec["unit"] == "img/s"
    assert rec["value"] > 0
    # stage progress goes to stderr, never stdout
    assert "[bench +" in out.stderr


def test_bench_watchdog_emits_error_line(tmp_path):
    # a 1s alarm beats even a fully cache-warm run (interpreter + jax init
    # alone exceed it); a cold per-test compilation cache double-insures
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(
            TMR_BENCH_ALARM="1",
            TMR_COMPILATION_CACHE=str(tmp_path / "xla-cache"),
        ),
        capture_output=True, text=True, timeout=300,
    )
    # non-zero exit so a driver keying on status sees the wedge as a failure
    assert out.returncode == 2
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] == 0.0
    assert "watchdog" in rec["error"]


def test_bench_fast_failure_emits_error_line():
    # round 3's actual failure mode: a fast exception (jax.devices()
    # RuntimeError) long before the watchdog — must still yield the one
    # contractual JSON line, not a raw traceback (BENCH_r03.json regression)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(TMR_BENCH_SELFTEST_FAIL="1"),
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 1
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    rec = json.loads(lines[0])
    assert rec["value"] == 0.0
    assert "selftest" in rec["error"]
    for key in ("metric", "value", "unit", "vs_baseline", "error"):
        assert key in rec, key
    # an outage record carries the last committed live measurement (with
    # provenance) so a round-end wedge doesn't erase the round's number —
    # asserted only when the repo actually has a real BENCH_LIVE.json
    live_path = os.path.join(REPO, "BENCH_LIVE.json")
    if os.path.exists(live_path):
        with open(live_path) as f:
            live = json.load(f)
        if "error" not in live and live.get("value"):
            # a clean checkout carries provenance; a working tree where the
            # watcher just dropped a fresh (uncommitted) measurement gets
            # the clearly-labeled uncommitted key instead
            if "last_committed_live" in rec:
                assert rec["last_committed_live"]["value"] == live["value"]
                assert rec["last_committed_live"]["committed_at"]
                # the driver must be able to see exactly how old the
                # carried number is (VERDICT r4 #6)
                assert rec["last_committed_live"]["stale_hours"] >= 0
            else:
                assert rec["last_live_uncommitted"]["value"] == live["value"]
                assert rec["last_live_uncommitted"]["stale_hours"] >= 0


def test_bench_preliminary_survives_post_measure_failure():
    """A failure AFTER the pre-sweep preliminary measurement banked must
    print the real measurement (annotated, rc 0), not a zero-value outage
    record — a wedge during the sweeps can no longer erase a completed
    headline (VERDICT r4 #6 follow-through)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(TMR_BENCH_SELFTEST_PRELIM="1"),
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    rec = json.loads(lines[0])
    assert rec["value"] > 0
    assert rec["preliminary"] is True
    assert "selftest" in rec["sweep_aborted"]


def test_bench_restores_checkpoint(tmp_path):
    # plumbing mode: --epochs 0 saves init params in the exact bench model
    # layout; bench must restore them and say so in the metric line
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "make_bench_ckpt.py"),
         "--epochs", "0", "--image_size", "64", "--compute_dtype", "float32",
         "--out", str(tmp_path / "bench_ckpt")],
        env=_bench_env(), capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    ckpt = str(tmp_path / "bench_ckpt" / "params")
    assert os.path.isdir(ckpt)

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=_bench_env(TMR_BENCH_CKPT=ckpt),
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "restored ckpt" in rec["metric"]
    assert rec["value"] > 0
    assert "params restored" in out.stderr


def test_bench_extra_emits_json_on_failure_and_success(tmp_path):
    """bench_extra.py shares bench.py's contract: ONE JSON line no matter
    what (round 3 died at unguarded backend init; per-config errors were
    already inline but everything outside them wasn't)."""
    script = os.path.join(REPO, "scripts", "bench_extra.py")
    # success path at tiny shapes, single cheapest config
    out = subprocess.run(
        [sys.executable, script, "--only", "demo"],
        env=_bench_env(TMR_BENCH_TINY="1"),
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "demo" in rec and "device" in rec

    # an unknown --only name is caught by the per-config guard: still one
    # JSON line, error recorded inline, rc 0
    out = subprocess.run(
        [sys.executable, script, "--only", "nonsense"],
        env=_bench_env(), capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "error" in rec["nonsense"]

    # fast-fail OUTSIDE the per-config guards (round 3's bench.py death
    # mode): backend init fails -> one error-JSON line, rc 1
    out = subprocess.run(
        [sys.executable, script, "--only", "demo"],
        env={**_bench_env(), "JAX_PLATFORMS": "bogus"},
        capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 1
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "error" in rec

    # watchdog path
    out = subprocess.run(
        [sys.executable, script, "--only", "demo"],
        env=_bench_env(TMR_BENCH_TINY="1", TMR_BENCH_ALARM="1"),
        capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 2
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "watchdog" in rec["error"]
