"""REAL multi-process execution of the multi-host (DCN) path.

Everything else in the suite simulates multi-chip inside ONE process; this
spawns TWO OS processes that rendezvous through
``parallel/mesh.initialize_multihost`` (jax.distributed + Gloo — the DCN
transport stand-in available on CPU) and run, across the process boundary:
the data-parallel train step on a global mesh (4 local devices each, 8
global), the MapReduce shuffle-replacement ``allreduce_stats`` psum, and the FULL
eval rendezvous — per-process per-image JSONs, barrier, process-0 COCO
merge, barrier, every process computing identical metrics from the merged
files (the reference's filesystem-as-IPC protocol, trainer.py:181-199).
The reference's multi-node story is Hadoop job submission + Lightning
DDP; this is its TPU-native equivalent actually crossing processes.
"""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mh_worker.py")



import pytest

pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

def _free_port() -> int:
    # NB: TOCTOU — the port is released before the coordinator binds it
    # (seconds later, after worker startup). Collisions are unlikely on
    # this single-test host but would surface as a rendezvous failure and
    # a clean retry of the test, not a hang (workers are killed below).
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_train_step_and_stats_psum(tmp_path):
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS")
    }
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, "2", str(pid),
             str(tmp_path / "logs")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        # one worker dying leaves the other blocked in the rendezvous —
        # never leak it (it would pin the port past the pytest session)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
    ok = [l for out in outs for l in out.splitlines() if l.startswith("MH_OK")]
    assert len(ok) == 2, outs
    # the replicated loss and the psum'd stats agree across processes
    assert ok[0] == ok[1], ok
