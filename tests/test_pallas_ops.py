"""Pallas TPU kernels vs their pure-XLA oracles (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmr_tpu.ops.nms import nms_keep_mask
from tmr_tpu.ops.pallas_nms import nms_keep_mask_pallas


def rand_boxes(n, seed, spread=1.0):
    rng = np.random.default_rng(seed)
    cx, cy = rng.uniform(0, spread, (2, n))
    w, h = rng.uniform(0.02, 0.3, (2, n))
    boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
    scores = rng.uniform(0, 1, n)
    return jnp.asarray(boxes, jnp.float32), jnp.asarray(scores, jnp.float32)


@pytest.mark.parametrize("n,seed,thr", [(64, 0, 0.5), (128, 1, 0.3),
                                        (256, 2, 0.7), (128, 3, 0.15)])
@pytest.mark.slow
def test_pallas_nms_matches_xla(n, seed, thr):
    boxes, scores = rand_boxes(n, seed, spread=0.6)  # dense -> many overlaps
    want = nms_keep_mask(boxes, scores, thr)
    got = nms_keep_mask_pallas(boxes, scores, thr, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_pallas_nms_valid_mask():
    boxes, scores = rand_boxes(96, 4, spread=0.4)
    valid = jnp.asarray(np.random.default_rng(5).uniform(0, 1, 96) > 0.3)
    want = nms_keep_mask(boxes, scores, 0.5, valid=valid)
    got = nms_keep_mask_pallas(boxes, scores, 0.5, valid=valid,
                               interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert not np.any(np.asarray(got) & ~np.asarray(valid))


def test_pallas_nms_identical_boxes():
    """Identical boxes + tied scores must keep exactly one."""
    boxes = jnp.tile(jnp.array([[0.1, 0.1, 0.3, 0.3]], jnp.float32), (8, 1))
    scores = jnp.full((8,), 0.7, jnp.float32)
    got = nms_keep_mask_pallas(boxes, scores, 0.5, interpret=True)
    assert int(np.asarray(got).sum()) == 1


def test_pallas_nms_all_invalid():
    boxes, scores = rand_boxes(32, 6)
    valid = jnp.zeros((32,), bool)
    got = nms_keep_mask_pallas(boxes, scores, 0.5, valid=valid,
                               interpret=True)
    assert int(np.asarray(got).sum()) == 0


@pytest.mark.slow
def test_batched_nms_backend_parity():
    """postprocess.batched_nms gives identical results on both backends
    (vmap over the pallas kernel included)."""
    from tmr_tpu.ops.postprocess import batched_nms

    B, N = 3, 64
    boxes = jnp.stack([rand_boxes(N, 10 + i, spread=0.5)[0] for i in range(B)])
    scores = jnp.stack([rand_boxes(N, 20 + i)[1] for i in range(B)])
    valid = scores > 0.2
    dets = {"boxes": boxes, "scores": jnp.where(valid, scores, 0.0),
            "refs": jnp.zeros((B, N, 2)), "valid": valid}
    out_x = batched_nms(dets, 0.4, backend="xla")
    out_p = batched_nms(dets, 0.4, backend="pallas")
    np.testing.assert_array_equal(np.asarray(out_p["valid"]),
                                  np.asarray(out_x["valid"]))
    np.testing.assert_allclose(np.asarray(out_p["scores"]),
                               np.asarray(out_x["scores"]))


@pytest.mark.parametrize("n", [150, 2000])
@pytest.mark.slow
def test_pallas_nms_non_lane_aligned(n):
    """N not a multiple of 128 (the eval default 2000 isn't either after
    padding semantics changed): the wrapper pads rows to a lane multiple with
    valid=0 and slices back; decisions must still match the XLA fixpoint."""
    boxes, scores = rand_boxes(n, 7, spread=0.5)
    valid = jnp.asarray(np.random.default_rng(8).uniform(0, 1, n) > 0.25)
    want = nms_keep_mask(boxes, scores, 0.5, valid=valid)
    got = nms_keep_mask_pallas(boxes, scores, 0.5, valid=valid,
                               interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pallas_compiled_selfcheck_gates_auto_backend():
    """'auto' on TPU must route through pallas_nms_compiled_ok(); off-TPU it
    must never touch the compiled path. On a real TPU this test additionally
    exercises the compiled kernel itself."""
    from tmr_tpu.ops.pallas_nms import pallas_nms_compiled_ok

    if jax.default_backend() == "tpu":
        assert pallas_nms_compiled_ok(), (
            "compiled Pallas NMS disagrees with the XLA fixpoint on TPU"
        )
    else:
        # cheap sanity: the self-check is exception-safe and returns a bool
        assert pallas_nms_compiled_ok() in (True, False)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas path needs a real TPU")
@pytest.mark.parametrize("n,seed,thr", [(256, 11, 0.5), (1100, 12, 0.3)])
def test_pallas_nms_compiled_matches_xla_on_tpu(n, seed, thr):
    boxes, scores = rand_boxes(n, seed, spread=0.5)
    want = nms_keep_mask(boxes, scores, thr)
    got = nms_keep_mask_pallas(boxes, scores, thr, interpret=False)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pallas_nms_suppression_chain():
    """A chain a>b>c where a suppresses b and b would suppress c but is
    itself suppressed -> c survives (the sequential-greedy subtlety)."""
    boxes = jnp.array(
        [
            [0.00, 0.0, 0.40, 1.0],   # a (top score)
            [0.25, 0.0, 0.65, 1.0],   # b: IoU(a,b) = .15/.65 ~ .231 -> gone
            [0.50, 0.0, 0.90, 1.0],   # c: IoU(a,c) = 0; IoU(b,c) ~ .231
        ],                            #    but b is dead -> c survives
        jnp.float32,
    )
    scores = jnp.array([0.9, 0.8, 0.7], jnp.float32)
    got = np.asarray(nms_keep_mask_pallas(boxes, scores, 0.2,
                                          interpret=True))
    want = np.asarray(nms_keep_mask(boxes, scores, 0.2))
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got, [True, False, True])


# ---- pallas depthwise correlation (ops/pallas_xcorr.py) --------------------
@pytest.mark.slow
def test_pallas_xcorr_matches_conv_path():
    """The Pallas correlation kernel (interpret mode on CPU) must equal the
    HIGHEST-precision grouped-conv lowering on identical inputs, across
    channel counts that do and don't divide the channel block."""
    from jax import lax

    from tmr_tpu.ops.pallas_xcorr import xcorr_pallas

    rng = np.random.default_rng(3)
    for B, C, H, W, T in ((2, 8, 24, 20, 5), (1, 3, 16, 16, 7)):
        f = jnp.asarray(rng.standard_normal((B, C, H, W)), jnp.float32)
        t = jnp.asarray(rng.standard_normal((B, C, T, T)), jnp.float32)
        got = np.asarray(xcorr_pallas(f, t, interpret=True))
        want = np.asarray(
            lax.conv_general_dilated(
                f.reshape(1, B * C, H, W),
                t.reshape(B * C, 1, T, T),
                window_strides=(1, 1),
                padding=[(T // 2, T // 2), (T // 2, T // 2)],
                feature_group_count=B * C,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                precision=lax.Precision.HIGHEST,
            ).reshape(B, C, H, W)
        )
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_pallas_xcorr_dispatch_falls_back_off_tpu(monkeypatch):
    """TMR_XCORR_IMPL=pallas off-TPU: the self-check refuses (no TPU), the
    dispatcher silently falls back to the conv path, results exact."""
    from tmr_tpu.ops import xcorr as xc

    rng = np.random.default_rng(4)
    B, C, H, W, cap = 2, 4, 20, 20, 9
    feat = rng.standard_normal((B, C, H, W)).astype(np.float32)
    tmpl = np.zeros((B, C, cap, cap), np.float32)
    tmpl[:, :, 2:7, 3:6] = rng.standard_normal((B, C, 5, 3))
    thw = jnp.array([[5, 3], [5, 3]], jnp.int32)

    monkeypatch.delenv("TMR_XCORR_IMPL", raising=False)
    want = np.asarray(
        xc.cross_correlation(jnp.array(feat), jnp.array(tmpl), thw)
    )
    monkeypatch.setenv("TMR_XCORR_IMPL", "pallas")
    got = np.asarray(
        xc.cross_correlation(jnp.array(feat), jnp.array(tmpl), thw)
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pallas_xcorr_ok_gates(monkeypatch):
    from tmr_tpu.ops import pallas_xcorr as px

    # capacity beyond the unroll cap always refuses, even on TPU
    assert not px.pallas_xcorr_ok(8, 64, 64, px.MAX_UNROLL_T + 2)
    # force-disable env wins regardless of backend
    monkeypatch.setenv("TMR_NO_PALLAS_XCORR", "1")
    assert not px.pallas_xcorr_ok(8, 64, 64, 17)


# ---- fused rel-pos flash attention (global ViT blocks) ---------------------
def _attn_inputs(gh, gw, D, B=1, H=2, seed=21, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    S = gh * gw
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype)
    rh = jnp.asarray(rng.standard_normal((gh, gh, D)) * 0.2, jnp.float32)
    rw = jnp.asarray(rng.standard_normal((gw, gw, D)) * 0.2, jnp.float32)
    return q, k, v, rh, rw


def test_pallas_fused_attention_matches_blockwise(monkeypatch):
    """The fused-bias kernel (row+lane-aligned tiles, bias from block
    offsets by broadcast alone — TMR_GLOBAL_ATTN=fused) vs the exact
    blockwise oracle on the Pallas interpreter: forward values and
    custom_vjp gradients, with tiles forced small enough that the online
    softmax chains across multiple k blocks."""
    from tmr_tpu.models.vit import blockwise_decomposed_attention
    from tmr_tpu.ops.pallas_attn import (
        effective_fused_tiles,
        pallas_fused_attention,
    )

    # gw=8 -> lcm(8,128)=128; S=256 with 128-tile prefs -> 2 q x 2 k blocks
    monkeypatch.setenv("TMR_PALLAS_ATTN_BQ", "128")
    monkeypatch.setenv("TMR_PALLAS_ATTN_BK", "128")
    gh, gw, D = 32, 8, 8
    assert effective_fused_tiles(gh * gw, gw) == (128, 128)
    q, k, v, rh, rw = _attn_inputs(gh, gw, D)
    scale = D**-0.5

    got = jax.jit(
        lambda *a: pallas_fused_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw)
    want = jax.jit(
        lambda *a: blockwise_decomposed_attention(*a, (gh, gw), scale)
    )(q, k, v, rh, rw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    # no-bias arity reuses the plain kernel — still blockwise-equal
    got_nb = jax.jit(
        lambda *a: pallas_fused_attention(*a, None, None, (gh, gw), scale)
    )(q, k, v)
    want_nb = jax.jit(
        lambda *a: blockwise_decomposed_attention(
            *a, None, None, (gh, gw), scale)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got_nb), np.asarray(want_nb),
                               rtol=2e-5, atol=2e-5)

    # gradients: the custom_vjp backward recomputes through blockwise —
    # this pins the plumbing (argument order, residuals)
    def loss(fn):
        return lambda a, b, c: jnp.sum(
            fn(a, b, c, rh, rw, (gh, gw), scale) ** 2)

    g_got = jax.jit(jax.grad(loss(pallas_fused_attention),
                             argnums=(0, 1, 2)))(q, k, v)
    g_want = jax.jit(jax.grad(loss(blockwise_decomposed_attention),
                              argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_xla_flash_attention_matches_blockwise(monkeypatch):
    """The pure-XLA online-softmax flash path (TMR_GLOBAL_ATTN=xlaflash)
    vs the exact blockwise oracle — multi-k-block streaming forced via the
    block-target knobs, bias on and off, non-square grid."""
    from tmr_tpu.models.vit import blockwise_decomposed_attention
    from tmr_tpu.ops.flash_attn import xla_flash_decomposed_attention

    monkeypatch.setenv("TMR_XLA_FLASH_BQ", "64")
    monkeypatch.setenv("TMR_XLA_FLASH_BK", "64")
    for gh, gw in ((16, 8), (16, 16)):
        D = 8
        q, k, v, rh, rw = _attn_inputs(gh, gw, D, B=2, H=3)
        scale = D**-0.5
        got = jax.jit(
            lambda *a, _g=(gh, gw): xla_flash_decomposed_attention(
                *a, _g, scale)
        )(q, k, v, rh, rw)
        want = jax.jit(
            lambda *a, _g=(gh, gw): blockwise_decomposed_attention(
                *a, _g, scale)
        )(q, k, v, rh, rw)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

        got_nb = jax.jit(
            lambda *a, _g=(gh, gw): xla_flash_decomposed_attention(
                *a, None, None, _g, scale)
        )(q, k, v)
        want_nb = jax.jit(
            lambda *a, _g=(gh, gw): blockwise_decomposed_attention(
                *a, None, None, _g, scale)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(got_nb), np.asarray(want_nb),
                                   rtol=2e-5, atol=2e-5)

    # the knob contract: zero / non-integer targets are rejected
    monkeypatch.setenv("TMR_XLA_FLASH_BK", "0")
    with pytest.raises(ValueError, match="TMR_XLA_FLASH_BK"):
        xla_flash_decomposed_attention(
            q, k, v, rh, rw, (16, 16), 8**-0.5)


def _max_intermediate_elems(jaxpr) -> int:
    """Largest intermediate array (in elements) anywhere in a jaxpr,
    sub-jaxprs (scan/pallas bodies) included."""
    import math as _math

    best = 0
    for eqn in jaxpr.eqns:
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is not None and getattr(aval, "shape", None) is not None:
                best = max(best, int(_math.prod(aval.shape)))
        for val in eqn.params.values():
            inner = getattr(val, "jaxpr", val)
            if hasattr(inner, "eqns"):
                best = max(best, _max_intermediate_elems(inner))
    return best


@pytest.mark.parametrize("gh,gw", [(64, 64), (96, 96)])
def test_fused_paths_never_materialize_scores(gh, gw, monkeypatch):
    """The acceptance check for both production geometries (1024 -> 64x64,
    1536 -> 96x96): the fused Pallas kernel and the XLA flash path must
    never materialize the (B, H, S, S) score tensor or the broadcast
    rel-pos bias — asserted structurally on the traced jaxpr (every
    intermediate in every sub-jaxpr stays below S*S elements). Trace-only:
    nothing executes, so the full geometries are cheap here."""
    from tmr_tpu.ops.flash_attn import xla_flash_decomposed_attention
    from tmr_tpu.ops.pallas_attn import (
        fused_supported,
        pallas_fused_attention,
    )

    monkeypatch.delenv("TMR_PALLAS_ATTN_BQ", raising=False)
    monkeypatch.delenv("TMR_PALLAS_ATTN_BK", raising=False)
    monkeypatch.delenv("TMR_XLA_FLASH_BQ", raising=False)
    monkeypatch.delenv("TMR_XLA_FLASH_BK", raising=False)
    assert fused_supported(gh * gw, gw)
    D = 64
    S = gh * gw
    q, k, v, rh, rw = _attn_inputs(gh, gw, D)
    scale = D**-0.5
    for fn in (pallas_fused_attention, xla_flash_decomposed_attention):
        jaxpr = jax.make_jaxpr(
            lambda *a, _f=fn: _f(*a, (gh, gw), scale)
        )(q, k, v, rh, rw)
        biggest = _max_intermediate_elems(jaxpr.jaxpr)
        assert biggest < S * S, (
            f"{fn.__name__} materializes a {biggest}-element intermediate "
            f"(S^2 = {S * S}) at grid ({gh}, {gw})"
        )


def test_gate_refusal_records_structured_cause(monkeypatch):
    """Every kernel-gate refusal must leave a machine-readable cause in
    the diagnostics registry: category, exception class when one was
    swallowed, the gate's tile/geometry config, and the device kind —
    exercised end-to-end here via a FORCED refusal (the kill-switch) and
    the organic off-TPU backend refusal."""
    from tmr_tpu.diagnostics import drain_gate_refusals
    from tmr_tpu.ops import flash_attn, pallas_attn

    drain_gate_refusals()

    # forced refusal: the kill-switch env, fresh cache entry
    flash_attn.flash_attention_ok.cache_clear()
    monkeypatch.setenv("TMR_NO_FLASH_ATTN", "1")
    assert flash_attn.flash_attention_ok(16, 8, 8) is False
    recs = drain_gate_refusals()
    assert len(recs) == 1
    rec = recs[0]
    assert rec["schema"] == "gate_probe/v1"
    assert rec["gate"] == "flash_attention_ok"
    assert rec["cause"] == "kill-switch"
    assert rec["config"]["gh"] == 16 and rec["config"]["head_dim"] == 8
    assert rec["device_kind"]  # resolved from the live backend
    monkeypatch.delenv("TMR_NO_FLASH_ATTN")

    # organic refusal off-TPU: the require_tpu gates record "backend",
    # with the effective tile config in the cause record
    pallas_attn.pallas_fused_ok.cache_clear()
    assert pallas_attn.pallas_fused_ok(16, 8, 8, 128, 128) is False
    recs = drain_gate_refusals()
    assert [r["cause"] for r in recs] == ["backend"]
    assert recs[0]["gate"] == "pallas_fused_ok"
    assert recs[0]["config"]["bq"] == 128
    assert recs[0]["config"]["bk"] == 128

    # the xcorr gate follows the same schema (its own config vocabulary)
    from tmr_tpu.ops import pallas_xcorr as px

    monkeypatch.setenv("TMR_NO_PALLAS_XCORR", "1")
    assert px.pallas_xcorr_ok(8, 64, 64, 17) is False
    recs = drain_gate_refusals()
    assert recs and recs[-1]["gate"] == "pallas_xcorr_ok"
    assert recs[-1]["cause"] == "kill-switch"
    assert recs[-1]["config"] == {"C": 8, "H": 64, "W": 64, "T": 17}


def test_global_bands_unroll_zero_rejected(monkeypatch):
    """TMR_GLOBAL_BANDS_UNROLL=0 must raise (the documented contract is a
    positive integer), never silently clamp to 1 — a zero pin would
    mislabel any A/B evidence recorded against it."""
    from tmr_tpu.models.vit import blockwise_decomposed_attention

    gh = gw = 8
    D = 4
    q, k, v, rh, rw = _attn_inputs(gh, gw, D)
    monkeypatch.setenv("TMR_GLOBAL_BANDS_UNROLL", "0")
    with pytest.raises(ValueError, match="TMR_GLOBAL_BANDS_UNROLL"):
        jax.jit(
            lambda *a: blockwise_decomposed_attention(*a, (gh, gw), D**-0.5)
        )(q, k, v, rh, rw)
    # a positive pin still works (and a beyond-band-count one clamps)
    monkeypatch.setenv("TMR_GLOBAL_BANDS_UNROLL", "2")
    out = jax.jit(
        lambda *a: blockwise_decomposed_attention(*a, (gh, gw), D**-0.5)
    )(q, k, v, rh, rw)
    assert out.shape == q.shape


def test_pallas_xcorr_big_bucket_falls_back_to_fft(monkeypatch):
    """TMR_XCORR_IMPL=pallas with a >threshold capacity must fall back to
    the FFT path (a direct conv at T in the 100s is the O(H^2 T^2 C)
    blowup FFT_CAPACITY_THRESHOLD exists to prevent), not the conv path."""
    from tmr_tpu.ops import xcorr as xc

    B, C, H, W, cap = 1, 2, 16, 16, 67
    assert cap > xc.FFT_CAPACITY_THRESHOLD
    feat = jnp.asarray(
        np.random.default_rng(0).standard_normal((B, C, H, W)), jnp.float32
    )
    tmpl = jnp.zeros((B, C, cap, cap), jnp.float32)
    tmpl = tmpl.at[:, :, cap // 2, cap // 2].set(1.0)
    thw = jnp.array([[1, 1]], jnp.int32)
    monkeypatch.setenv("TMR_XCORR_IMPL", "pallas")
    got = xc.cross_correlation(feat, tmpl, thw)
    # identity template through FFT: equal up to FFT rounding, and the
    # nonzero rounding proves the FFT path ran (a conv would be exact)
    np.testing.assert_allclose(np.asarray(got), np.asarray(feat), atol=1e-4)
    assert abs(np.asarray(got) - np.asarray(feat)).max() > 0


# ------------------------------------------- nms_topk padded-output tail
def _batched_rand(b, n, seed, spread=0.6):
    boxes = jnp.stack([rand_boxes(n, seed + i, spread)[0]
                       for i in range(b)])
    scores = jnp.stack([rand_boxes(n, seed + i, spread)[1]
                        for i in range(b)])
    return boxes, scores


def _topk_reference(boxes, scores, thr, valid, k):
    """Per-image numpy reference: XLA keep mask -> survivors sorted by
    (-score, slot) -> compacted into k padded slots."""
    from tmr_tpu.ops.pallas_nms import nms_topk  # noqa: F401  (under test)

    out = {"count": [], "boxes": [], "scores": [], "index": []}
    for i in range(scores.shape[0]):
        keep = np.asarray(nms_keep_mask(boxes[i], scores[i], thr,
                                        valid=valid[i]))
        idx = np.nonzero(keep)[0]
        idx = idx[np.lexsort((idx, -np.asarray(scores[i])[idx]))][:k]
        n = len(idx)
        bx = np.zeros((k, 4), np.float32)
        sc = np.zeros((k,), np.float32)
        ix = np.full((k,), -1, np.int64)
        bx[:n] = np.asarray(boxes[i])[idx]
        sc[:n] = np.asarray(scores[i])[idx]
        ix[:n] = idx
        out["count"].append(n)
        out["boxes"].append(bx)
        out["scores"].append(sc)
        out["index"].append(ix)
    return {k_: np.stack(v) for k_, v in out.items()}


@pytest.mark.parametrize("backend", ["xla", "pallas"])
@pytest.mark.parametrize("k", [4, 16, 64])
def test_nms_topk_matches_reference(backend, k):
    """Batched/padded semantics against the per-image float32 reference,
    on both backends (pallas in interpret mode — the satellite's
    interpret-parity requirement)."""
    from tmr_tpu.ops.pallas_nms import nms_topk

    boxes, scores = _batched_rand(3, 64, seed=10)
    valid = jnp.ones(scores.shape, bool)
    got = nms_topk(boxes, scores, 0.4, valid=valid, k=k,
                   backend=backend, interpret=True)
    want = _topk_reference(boxes, scores, 0.4, valid, k)
    np.testing.assert_array_equal(np.asarray(got["count"]), want["count"])
    np.testing.assert_array_equal(np.asarray(got["index"]), want["index"])
    np.testing.assert_array_equal(np.asarray(got["boxes"]), want["boxes"])
    np.testing.assert_array_equal(np.asarray(got["scores"]),
                                  want["scores"])


def test_nms_topk_degenerate_boxes():
    """Zero-area and inverted boxes must not poison the IoU math: they
    survive as their own detections (IoU 0 against everything) and the
    output stays finite."""
    from tmr_tpu.ops.pallas_nms import nms_topk

    boxes = jnp.asarray([[[0.1, 0.1, 0.1, 0.1],     # zero-area point
                          [0.5, 0.5, 0.4, 0.4],     # inverted
                          [0.2, 0.2, 0.4, 0.4]]], jnp.float32)
    scores = jnp.asarray([[0.9, 0.8, 0.7]], jnp.float32)
    out = nms_topk(boxes, scores, 0.5, backend="xla")
    assert int(out["count"][0]) == 3
    assert np.isfinite(np.asarray(out["boxes"])).all()
    np.testing.assert_array_equal(np.asarray(out["index"][0]), [0, 1, 2])


def test_nms_topk_all_suppressed_to_one():
    """N copies of one box: exactly the top scorer survives; the other
    slots are zeroed with index -1."""
    from tmr_tpu.ops.pallas_nms import nms_topk

    boxes = jnp.tile(jnp.asarray([[[0.2, 0.2, 0.6, 0.6]]], jnp.float32),
                     (1, 8, 1))
    scores = jnp.asarray([[0.1, 0.3, 0.95, 0.2, 0.5, 0.4, 0.6, 0.7]],
                         jnp.float32)
    out = nms_topk(boxes, scores, 0.5, backend="xla", k=8)
    assert int(out["count"][0]) == 1
    assert int(out["index"][0][0]) == 2
    assert float(out["scores"][0][0]) == pytest.approx(0.95)
    assert (np.asarray(out["index"][0][1:]) == -1).all()
    assert (np.asarray(out["scores"][0][1:]) == 0).all()
    assert (np.asarray(out["boxes"][0][1:]) == 0).all()


def test_nms_topk_k_beyond_valid_count_pads():
    """k larger than the input (and than the survivor count) pads: count
    reports the real survivors, slots past it are zero/-1."""
    from tmr_tpu.ops.pallas_nms import nms_topk

    boxes, scores = _batched_rand(1, 6, seed=20, spread=4.0)  # sparse
    valid = jnp.asarray([[True, True, True, False, False, False]])
    out = nms_topk(boxes, scores, 0.5, valid=valid, k=10, backend="xla")
    n = int(out["count"][0])
    assert n <= 3
    assert out["boxes"].shape == (1, 10, 4)
    assert (np.asarray(out["index"][0][n:]) == -1).all()
    assert (np.asarray(out["scores"][0][n:]) == 0).all()
    # the surviving prefix is score-descending
    sc = np.asarray(out["scores"][0][:n])
    assert (np.diff(sc) <= 0).all()


def test_nms_topk_empty_valid():
    from tmr_tpu.ops.pallas_nms import nms_topk

    boxes, scores = _batched_rand(2, 8, seed=30)
    valid = jnp.zeros(scores.shape, bool)
    out = nms_topk(boxes, scores, 0.5, valid=valid, backend="xla")
    assert (np.asarray(out["count"]) == 0).all()
    assert (np.asarray(out["index"]) == -1).all()
    assert (np.asarray(out["boxes"]) == 0).all()
