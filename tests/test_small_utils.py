"""Direct unit coverage for small leaf modules (bench_guard, COCOIndex)
plus repo-wide hygiene lints (report-schema/validator parity, stdout
discipline under tmr_tpu/)."""

import json
import os
import re

import pytest

from tmr_tpu.data.coco_index import COCOIndex
from tmr_tpu.utils.bench_guard import run_guarded, scrub_cpu_tunnel_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------ repo hygiene (thin
# wrappers: the lints themselves moved to tmr_tpu/analysis as framework
# passes — tests/test_analysis.py proves each rule fires on fixtures;
# these keep the tier-1 zero-findings coverage at its original site)
def _rule_findings(rule_id: str):
    from tmr_tpu.analysis import Baseline, default_baseline_path, \
        run_ast_passes

    baseline = Baseline.load(default_baseline_path(REPO))
    return [
        str(f) for f in run_ast_passes(root=REPO, rules=[rule_id],
                                       baseline=baseline)
        if not baseline.allows(f)
    ]


def test_every_report_schema_has_a_validator():
    """Parity pin (analysis rule ``report-parity``): every ``*_report/v1``
    schema constant declared in diagnostics.py must ship a matching
    ``validate_*`` function, and every scripts/*.py referencing a
    ``*_REPORT_SCHEMA`` constant must call its validator."""
    assert _rule_findings("report-parity") == []
    # and the declared validators are actually importable callables
    import tmr_tpu.diagnostics as diag

    src = open(os.path.join(REPO, "tmr_tpu", "diagnostics.py")).read()
    schemas = re.findall(
        r'^([A-Z][A-Z_]*)_SCHEMA\s*=\s*"(\w+_report)/v\d+"', src, re.M
    )
    assert len(schemas) >= 4  # map/serve/metrics/trace/analysis at least
    for const, tag in schemas:
        assert callable(getattr(diag, f"validate_{tag}", None)), (
            f"{const}_SCHEMA ({tag}) has no importable validate_{tag}()"
        )


def test_env_knob_registry_parity():
    """Every TMR_* env knob consumed under tmr_tpu/ must be documented
    in ``config.ENV_KNOBS`` and every registry entry consumed somewhere
    on the repo surface (analysis rule ``knob-parity``), and no knob may
    be read at import time outside config.py (``knob-import-time``)."""
    assert _rule_findings("knob-parity") == []
    assert _rule_findings("knob-import-time") == []


def test_no_bare_stdout_prints_under_tmr_tpu():
    """Stdout under tmr_tpu/ is reserved for machine-readable protocol
    output; human-readable lines go to stderr (analysis rule
    ``stdout-hygiene``)."""
    assert _rule_findings("stdout-hygiene") == []


def test_scrub_cpu_tunnel_env_strips_only_cpu_intent():
    """Tunnel-client discipline as code (the session-7 10-hour wedge): a
    JAX_PLATFORMS=cpu-intended env must lose PALLAS_AXON_POOL_IPS so the
    axon sitecustomize can never dial the single-client TPU relay; any
    other intent (tpu, mixed, unset) must leave the env untouched."""
    env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "10.0.0.1"}
    assert scrub_cpu_tunnel_env(env) is True
    assert "PALLAS_AXON_POOL_IPS" not in env

    # case/whitespace-insensitive cpu-only intent still strips
    env = {"JAX_PLATFORMS": " CPU ", "PALLAS_AXON_POOL_IPS": "10.0.0.1"}
    assert scrub_cpu_tunnel_env(env) is True
    assert "PALLAS_AXON_POOL_IPS" not in env

    # non-cpu or ambiguous intents never touch the tunnel var
    for plats in ("", "tpu", "axon,cpu", "cpu,tpu"):
        env = {"JAX_PLATFORMS": plats, "PALLAS_AXON_POOL_IPS": "10.0.0.1"}
        assert scrub_cpu_tunnel_env(env) is False
        assert env["PALLAS_AXON_POOL_IPS"] == "10.0.0.1"

    # cpu intent with no tunnel var set: no-op, not an error
    env = {"JAX_PLATFORMS": "cpu"}
    assert scrub_cpu_tunnel_env(env) is False


def test_scrub_cpu_tunnel_env_wired_into_entry_points():
    """Every scripts/ entry point that can reach a jax backend init (and
    bench.py itself) must call the scrub BEFORE importing jax — the guard
    exists as code, not prose, only if the entry points actually run it."""
    import os
    import re

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    entries = [
        os.path.join(repo, "bench.py"),
        os.path.join(repo, "scripts", "bench_extra.py"),
        os.path.join(repo, "scripts", "profile_breakdown.py"),
        os.path.join(repo, "scripts", "ckpt_probe.py"),
        os.path.join(repo, "scripts", "gate_probe.py"),
        os.path.join(repo, "scripts", "make_bench_ckpt.py"),
        os.path.join(repo, "scripts", "serve_bench.py"),
        os.path.join(repo, "scripts", "obs_probe.py"),
    ]
    for path in entries:
        src = open(path).read()
        call = src.find("scrub_cpu_tunnel_env()")
        assert call != -1, f"{path} does not call scrub_cpu_tunnel_env()"
        # the scrub must run before the first module-level jax import
        jax_import = re.search(r"^import jax", src, re.MULTILINE)
        if jax_import is not None:
            assert call < jax_import.start(), (
                f"{path}: scrub_cpu_tunnel_env() after `import jax`"
            )


def test_run_guarded_success_and_cancel(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "3300")
    seen = []

    def run(cancel):
        cancel()  # contract: callable before the success print
        seen.append("ran")
        return 0

    rc = run_guarded(run, lambda msg: seen.append(("err", msg)))
    assert rc == 0 and seen == ["ran"]


def test_run_guarded_funnels_exceptions(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "0")  # no watchdog thread
    errs = []
    rc = run_guarded(
        lambda cancel: (_ for _ in ()).throw(RuntimeError("boom")),
        errs.append,
    )
    assert rc == 1
    assert "RuntimeError: boom" in errs[0]

    # SystemExit funnels too (an in-library sys.exit must still yield the
    # contractual JSON record, not an empty stdout)
    errs = []
    rc = run_guarded(
        lambda cancel: (_ for _ in ()).throw(SystemExit(3)), errs.append
    )
    assert rc == 1 and "SystemExit" in errs[0]


def test_run_guarded_malformed_alarm_env(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "")  # int() would raise
    rc = run_guarded(lambda cancel: 0, lambda msg: None)
    assert rc == 0


def test_run_guarded_keyboardinterrupt_reraises(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "0")
    with pytest.raises(KeyboardInterrupt):
        run_guarded(
            lambda cancel: (_ for _ in ()).throw(KeyboardInterrupt()),
            lambda msg: None,
        )


def test_coco_index_read_paths(tmp_path):
    data = {
        "images": [{"id": 7, "file_name": "a.jpg"},
                   {"id": 9, "file_name": "b.jpg"}],
        "annotations": [
            {"id": 1, "image_id": 7, "bbox": [0, 0, 5, 5]},
            {"id": 2, "image_id": 7, "bbox": [1, 1, 3, 3]},
            {"id": 3, "image_id": 9, "bbox": [2, 2, 4, 4]},
        ],
    }
    p = tmp_path / "inst.json"
    p.write_text(json.dumps(data))
    idx = COCOIndex(str(p))
    assert sorted(idx.get_img_ids()) == [7, 9]
    assert idx.imgs[9]["file_name"] == "b.jpg"
    ids = idx.get_ann_ids([7])
    assert sorted(ids) == [1, 2]
    anns = idx.load_anns(ids)
    assert [a["id"] for a in anns] == sorted(ids)
    assert idx.get_ann_ids([9, 7]) and len(idx.get_ann_ids([9, 7])) == 3


def test_compilation_cache_opt_out(monkeypatch):
    """TMR_COMPILATION_CACHE=0 (and friends) must skip enabling entirely
    — no directory creation, no jax config mutation — and return None."""
    from tmr_tpu.utils import cache as cache_mod

    def boom(*a, **k):
        raise AssertionError("opt-out must not touch the filesystem")

    for val in ("0", "off", "FALSE", " no "):
        monkeypatch.setenv("TMR_COMPILATION_CACHE", val)
        monkeypatch.setattr(cache_mod.os, "makedirs", boom)
        assert cache_mod.enable_compilation_cache() is None


def test_compilation_cache_failure_degrades_to_warning(
    monkeypatch, tmp_path
):
    """An un-writable cache dir (or any enabling failure) warns and
    returns None instead of crashing the caller — the uniform script call
    sites must never turn a cache nicety into a benchmark failure."""
    from tmr_tpu.utils import cache as cache_mod

    monkeypatch.delenv("TMR_COMPILATION_CACHE", raising=False)

    def denied(*a, **k):
        raise OSError("read-only filesystem")

    monkeypatch.setattr(cache_mod.os, "makedirs", denied)
    with pytest.warns(UserWarning, match="compilation cache disabled"):
        assert cache_mod.enable_compilation_cache(
            str(tmp_path / "xla")
        ) is None


def test_compilation_cache_env_path_still_works(monkeypatch, tmp_path):
    """A directory-valued TMR_COMPILATION_CACHE keeps meaning 'relocate':
    the opt-out reading must not break the path reading."""
    from tmr_tpu.utils import cache as cache_mod

    target = tmp_path / "xla-cache"
    monkeypatch.setenv("TMR_COMPILATION_CACHE", str(target))
    calls = {}
    monkeypatch.setattr(
        cache_mod, "os",
        type("O", (), {
            "makedirs": staticmethod(
                lambda p, exist_ok=False: calls.setdefault("dir", p)
            ),
            "environ": cache_mod.os.environ,
            "path": cache_mod.os.path,
        }),
    )

    class _Cfg:
        @staticmethod
        def update(k, v):
            calls[k] = v

    import jax

    monkeypatch.setattr(jax, "config", _Cfg())
    assert cache_mod.enable_compilation_cache() == str(target)
    assert calls["dir"] == str(target)
    assert calls["jax_compilation_cache_dir"] == str(target)
