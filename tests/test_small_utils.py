"""Direct unit coverage for small leaf modules (bench_guard, COCOIndex)
plus repo-wide hygiene lints (report-schema/validator parity, stdout
discipline under tmr_tpu/)."""

import ast
import json
import os
import re

import pytest

from tmr_tpu.data.coco_index import COCOIndex
from tmr_tpu.utils.bench_guard import run_guarded, scrub_cpu_tunnel_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------ report-protocol hygiene
def test_every_report_schema_has_a_validator():
    """Parity pin: every ``*_report/v1`` schema constant declared in
    diagnostics.py must ship a matching ``validate_*`` function — a new
    report format cannot drift in unvalidated."""
    import tmr_tpu.diagnostics as diag

    src = open(os.path.join(REPO, "tmr_tpu", "diagnostics.py")).read()
    schemas = re.findall(
        r'^([A-Z][A-Z_]*)_SCHEMA\s*=\s*"(\w+_report)/v\d+"', src, re.M
    )
    assert schemas, "no *_report schema constants found in diagnostics.py"
    for const, tag in schemas:
        validator = f"validate_{tag}"
        assert callable(getattr(diag, validator, None)), (
            f"{const}_SCHEMA ({tag}) has no diagnostics.{validator}()"
        )


def test_report_emitting_scripts_call_their_validator():
    """Grep-driven pin: any scripts/*.py that references a
    ``*_REPORT_SCHEMA`` constant (i.e. emits that report) must also
    reference the matching ``validate_*_report`` — the self-check-before-
    print discipline serve_bench established."""
    import glob

    checked = 0
    for path in sorted(glob.glob(os.path.join(REPO, "scripts", "*.py"))):
        src = open(path).read()
        for const in set(re.findall(r"\b([A-Z][A-Z_]*?)_REPORT_SCHEMA\b",
                                    src)):
            validator = f"validate_{const.lower()}_report"
            assert validator in src, (
                f"{os.path.basename(path)} emits {const}_REPORT_SCHEMA "
                f"but never calls {validator}()"
            )
            checked += 1
    assert checked >= 2  # serve_bench + obs_probe at minimum


def _env_knob_reads(path: str) -> set:
    """AST scan of one file for TMR_* env-knob consumption: literal keys
    of ``os.environ`` subscripts (reads AND the autotune winner-export
    writes — same surface) and of ``environ.get/pop/setdefault`` /
    ``os.getenv`` calls."""

    def lit(node):
        return (node.value if isinstance(node, ast.Constant)
                and isinstance(node.value, str) else None)

    def is_environ(node):
        return ("environ" in ast.dump(node)) or (
            isinstance(node, ast.Attribute) and node.attr == "getenv"
        ) or (isinstance(node, ast.Name) and node.id == "getenv")

    knobs = set()
    for node in ast.walk(ast.parse(open(path).read(), filename=path)):
        key = None
        if isinstance(node, ast.Subscript) and is_environ(node.value):
            key = lit(node.slice)
        elif isinstance(node, ast.Call) and (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "pop", "setdefault", "getenv")
            and is_environ(node.func)
        ) and node.args:
            key = lit(node.args[0])
        if key and key.startswith("TMR_"):
            knobs.add(key)
    return knobs


def test_env_knob_registry_parity():
    """Every TMR_* env knob consumed under tmr_tpu/ must be documented in
    the ``config.ENV_KNOBS`` registry, and every registry entry must be
    consumed somewhere in the repo (tmr_tpu/, bench.py, scripts/) — the
    knob surface grew across 4 PRs with no single source of truth, and a
    registry that can silently go stale in either direction documents
    nothing."""
    import glob

    from tmr_tpu.config import ENV_KNOBS

    lib_files = sorted(glob.glob(os.path.join(REPO, "tmr_tpu", "**",
                                              "*.py"), recursive=True))
    consumed_lib = set().union(*(_env_knob_reads(p) for p in lib_files))
    assert consumed_lib, "AST scan found no TMR_ knob reads — scanner broke"

    undocumented = consumed_lib - set(ENV_KNOBS)
    assert not undocumented, (
        f"TMR_ knobs consumed under tmr_tpu/ but missing from "
        f"config.ENV_KNOBS: {sorted(undocumented)} — add each with a "
        "one-line description"
    )

    # reverse: a documented knob nothing consumes is a stale entry.
    # Driver knobs live in bench.py / scripts/, so the reverse scan is
    # repo-wide (string-literal match is enough for existence).
    surface = "\n".join(
        open(p).read() for p in lib_files
        + [os.path.join(REPO, "bench.py")]
        + sorted(glob.glob(os.path.join(REPO, "scripts", "*.py")))
    )
    stale = [k for k in ENV_KNOBS if f'"{k}"' not in surface
             and f"'{k}'" not in surface]
    assert not stale, (
        f"config.ENV_KNOBS entries no code consumes: {stale} — delete "
        "them or wire them up"
    )

    for knob, doc in ENV_KNOBS.items():
        assert isinstance(doc, str) and doc.strip(), (
            f"ENV_KNOBS[{knob!r}]: empty description"
        )


def test_no_bare_stdout_prints_under_tmr_tpu():
    """Stdout under tmr_tpu/ is reserved for machine-readable protocol
    output (one-JSON-line reports, the Hadoop-streaming records — written
    via sys.stdout.write); human-readable lines go to stderr through
    profiling.log_* or ``print(..., file=sys.stderr)``. A bare ``print``
    in library code corrupts whatever pipeline is parsing stdout."""
    import glob

    offenders = []
    for path in sorted(glob.glob(os.path.join(REPO, "tmr_tpu", "**",
                                              "*.py"), recursive=True)):
        tree = ast.parse(open(path).read(), filename=path)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and not any(kw.arg == "file" for kw in node.keywords)
            ):
                rel = os.path.relpath(path, REPO)
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "bare print() to stdout in library code: " + ", ".join(offenders)
    )


def test_scrub_cpu_tunnel_env_strips_only_cpu_intent():
    """Tunnel-client discipline as code (the session-7 10-hour wedge): a
    JAX_PLATFORMS=cpu-intended env must lose PALLAS_AXON_POOL_IPS so the
    axon sitecustomize can never dial the single-client TPU relay; any
    other intent (tpu, mixed, unset) must leave the env untouched."""
    env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "10.0.0.1"}
    assert scrub_cpu_tunnel_env(env) is True
    assert "PALLAS_AXON_POOL_IPS" not in env

    # case/whitespace-insensitive cpu-only intent still strips
    env = {"JAX_PLATFORMS": " CPU ", "PALLAS_AXON_POOL_IPS": "10.0.0.1"}
    assert scrub_cpu_tunnel_env(env) is True
    assert "PALLAS_AXON_POOL_IPS" not in env

    # non-cpu or ambiguous intents never touch the tunnel var
    for plats in ("", "tpu", "axon,cpu", "cpu,tpu"):
        env = {"JAX_PLATFORMS": plats, "PALLAS_AXON_POOL_IPS": "10.0.0.1"}
        assert scrub_cpu_tunnel_env(env) is False
        assert env["PALLAS_AXON_POOL_IPS"] == "10.0.0.1"

    # cpu intent with no tunnel var set: no-op, not an error
    env = {"JAX_PLATFORMS": "cpu"}
    assert scrub_cpu_tunnel_env(env) is False


def test_scrub_cpu_tunnel_env_wired_into_entry_points():
    """Every scripts/ entry point that can reach a jax backend init (and
    bench.py itself) must call the scrub BEFORE importing jax — the guard
    exists as code, not prose, only if the entry points actually run it."""
    import os
    import re

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    entries = [
        os.path.join(repo, "bench.py"),
        os.path.join(repo, "scripts", "bench_extra.py"),
        os.path.join(repo, "scripts", "profile_breakdown.py"),
        os.path.join(repo, "scripts", "ckpt_probe.py"),
        os.path.join(repo, "scripts", "gate_probe.py"),
        os.path.join(repo, "scripts", "make_bench_ckpt.py"),
        os.path.join(repo, "scripts", "serve_bench.py"),
        os.path.join(repo, "scripts", "obs_probe.py"),
    ]
    for path in entries:
        src = open(path).read()
        call = src.find("scrub_cpu_tunnel_env()")
        assert call != -1, f"{path} does not call scrub_cpu_tunnel_env()"
        # the scrub must run before the first module-level jax import
        jax_import = re.search(r"^import jax", src, re.MULTILINE)
        if jax_import is not None:
            assert call < jax_import.start(), (
                f"{path}: scrub_cpu_tunnel_env() after `import jax`"
            )


def test_run_guarded_success_and_cancel(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "3300")
    seen = []

    def run(cancel):
        cancel()  # contract: callable before the success print
        seen.append("ran")
        return 0

    rc = run_guarded(run, lambda msg: seen.append(("err", msg)))
    assert rc == 0 and seen == ["ran"]


def test_run_guarded_funnels_exceptions(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "0")  # no watchdog thread
    errs = []
    rc = run_guarded(
        lambda cancel: (_ for _ in ()).throw(RuntimeError("boom")),
        errs.append,
    )
    assert rc == 1
    assert "RuntimeError: boom" in errs[0]

    # SystemExit funnels too (an in-library sys.exit must still yield the
    # contractual JSON record, not an empty stdout)
    errs = []
    rc = run_guarded(
        lambda cancel: (_ for _ in ()).throw(SystemExit(3)), errs.append
    )
    assert rc == 1 and "SystemExit" in errs[0]


def test_run_guarded_malformed_alarm_env(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "")  # int() would raise
    rc = run_guarded(lambda cancel: 0, lambda msg: None)
    assert rc == 0


def test_run_guarded_keyboardinterrupt_reraises(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "0")
    with pytest.raises(KeyboardInterrupt):
        run_guarded(
            lambda cancel: (_ for _ in ()).throw(KeyboardInterrupt()),
            lambda msg: None,
        )


def test_coco_index_read_paths(tmp_path):
    data = {
        "images": [{"id": 7, "file_name": "a.jpg"},
                   {"id": 9, "file_name": "b.jpg"}],
        "annotations": [
            {"id": 1, "image_id": 7, "bbox": [0, 0, 5, 5]},
            {"id": 2, "image_id": 7, "bbox": [1, 1, 3, 3]},
            {"id": 3, "image_id": 9, "bbox": [2, 2, 4, 4]},
        ],
    }
    p = tmp_path / "inst.json"
    p.write_text(json.dumps(data))
    idx = COCOIndex(str(p))
    assert sorted(idx.get_img_ids()) == [7, 9]
    assert idx.imgs[9]["file_name"] == "b.jpg"
    ids = idx.get_ann_ids([7])
    assert sorted(ids) == [1, 2]
    anns = idx.load_anns(ids)
    assert [a["id"] for a in anns] == sorted(ids)
    assert idx.get_ann_ids([9, 7]) and len(idx.get_ann_ids([9, 7])) == 3


def test_compilation_cache_opt_out(monkeypatch):
    """TMR_COMPILATION_CACHE=0 (and friends) must skip enabling entirely
    — no directory creation, no jax config mutation — and return None."""
    from tmr_tpu.utils import cache as cache_mod

    def boom(*a, **k):
        raise AssertionError("opt-out must not touch the filesystem")

    for val in ("0", "off", "FALSE", " no "):
        monkeypatch.setenv("TMR_COMPILATION_CACHE", val)
        monkeypatch.setattr(cache_mod.os, "makedirs", boom)
        assert cache_mod.enable_compilation_cache() is None


def test_compilation_cache_failure_degrades_to_warning(
    monkeypatch, tmp_path
):
    """An un-writable cache dir (or any enabling failure) warns and
    returns None instead of crashing the caller — the uniform script call
    sites must never turn a cache nicety into a benchmark failure."""
    from tmr_tpu.utils import cache as cache_mod

    monkeypatch.delenv("TMR_COMPILATION_CACHE", raising=False)

    def denied(*a, **k):
        raise OSError("read-only filesystem")

    monkeypatch.setattr(cache_mod.os, "makedirs", denied)
    with pytest.warns(UserWarning, match="compilation cache disabled"):
        assert cache_mod.enable_compilation_cache(
            str(tmp_path / "xla")
        ) is None


def test_compilation_cache_env_path_still_works(monkeypatch, tmp_path):
    """A directory-valued TMR_COMPILATION_CACHE keeps meaning 'relocate':
    the opt-out reading must not break the path reading."""
    from tmr_tpu.utils import cache as cache_mod

    target = tmp_path / "xla-cache"
    monkeypatch.setenv("TMR_COMPILATION_CACHE", str(target))
    calls = {}
    monkeypatch.setattr(
        cache_mod, "os",
        type("O", (), {
            "makedirs": staticmethod(
                lambda p, exist_ok=False: calls.setdefault("dir", p)
            ),
            "environ": cache_mod.os.environ,
            "path": cache_mod.os.path,
        }),
    )

    class _Cfg:
        @staticmethod
        def update(k, v):
            calls[k] = v

    import jax

    monkeypatch.setattr(jax, "config", _Cfg())
    assert cache_mod.enable_compilation_cache() == str(target)
    assert calls["dir"] == str(target)
    assert calls["jax_compilation_cache_dir"] == str(target)
