"""Direct unit coverage for small leaf modules (bench_guard, COCOIndex)."""

import json

import pytest

from tmr_tpu.data.coco_index import COCOIndex
from tmr_tpu.utils.bench_guard import run_guarded


def test_run_guarded_success_and_cancel(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "3300")
    seen = []

    def run(cancel):
        cancel()  # contract: callable before the success print
        seen.append("ran")
        return 0

    rc = run_guarded(run, lambda msg: seen.append(("err", msg)))
    assert rc == 0 and seen == ["ran"]


def test_run_guarded_funnels_exceptions(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "0")  # no watchdog thread
    errs = []
    rc = run_guarded(
        lambda cancel: (_ for _ in ()).throw(RuntimeError("boom")),
        errs.append,
    )
    assert rc == 1
    assert "RuntimeError: boom" in errs[0]

    # SystemExit funnels too (an in-library sys.exit must still yield the
    # contractual JSON record, not an empty stdout)
    errs = []
    rc = run_guarded(
        lambda cancel: (_ for _ in ()).throw(SystemExit(3)), errs.append
    )
    assert rc == 1 and "SystemExit" in errs[0]


def test_run_guarded_malformed_alarm_env(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "")  # int() would raise
    rc = run_guarded(lambda cancel: 0, lambda msg: None)
    assert rc == 0


def test_run_guarded_keyboardinterrupt_reraises(monkeypatch):
    monkeypatch.setenv("TMR_BENCH_ALARM", "0")
    with pytest.raises(KeyboardInterrupt):
        run_guarded(
            lambda cancel: (_ for _ in ()).throw(KeyboardInterrupt()),
            lambda msg: None,
        )


def test_coco_index_read_paths(tmp_path):
    data = {
        "images": [{"id": 7, "file_name": "a.jpg"},
                   {"id": 9, "file_name": "b.jpg"}],
        "annotations": [
            {"id": 1, "image_id": 7, "bbox": [0, 0, 5, 5]},
            {"id": 2, "image_id": 7, "bbox": [1, 1, 3, 3]},
            {"id": 3, "image_id": 9, "bbox": [2, 2, 4, 4]},
        ],
    }
    p = tmp_path / "inst.json"
    p.write_text(json.dumps(data))
    idx = COCOIndex(str(p))
    assert sorted(idx.get_img_ids()) == [7, 9]
    assert idx.imgs[9]["file_name"] == "b.jpg"
    ids = idx.get_ann_ids([7])
    assert sorted(ids) == [1, 2]
    anns = idx.load_anns(ids)
    assert [a["id"] for a in anns] == sorted(ids)
    assert idx.get_ann_ids([9, 7]) and len(idx.get_ann_ids([9, 7])) == 3
