"""Deterministic fault-injection harness (tmr_tpu/utils/faults.py):
schedule grammar, shard/attempt scoping, deterministic corruption/poison,
the fired-fault log, the zero-overhead disabled path, and the retry
backoff schedule (mapreduce.backoff_delay)."""

import time

import numpy as np
import pytest

from tmr_tpu.parallel.mapreduce import RetryPolicy, backoff_delay
from tmr_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_schedule():
    faults.clear()
    yield
    faults.clear()


def test_parse_schedule_grammar():
    specs = faults.parse_schedule(
        "tar.open:shard=3:attempts=2:raise=OSError;"
        "encode:shard=7:latency=30;"
        "decode:corrupt=1;"
        "encode:nan=1"
    )
    assert [s.point for s in specs] == [
        "tar.open", "encode", "decode", "encode"
    ]
    assert specs[0].shard == 3 and specs[0].attempts == 2
    assert specs[0].raise_ == "OSError"
    assert specs[1].latency == 30.0 and specs[1].shard == 7
    assert specs[2].corrupt and specs[2].shard is None
    assert specs[3].nan


@pytest.mark.parametrize("bad", [
    "nonsense.point:raise=OSError",     # unknown point
    "encode:frobnicate=1",              # unknown key
    "encode:raise=NoSuchError",         # unknown exception class
    "encode:raise",                     # malformed field
])
def test_parse_schedule_rejects_typos(bad):
    with pytest.raises(ValueError):
        faults.parse_schedule(bad)


def test_fire_scopes_by_shard_and_attempt():
    faults.configure("tar.open:shard=3:attempts=2:raise=OSError")
    # wrong shard: no fire
    with faults.shard_scope(1, 0):
        faults.fire("tar.open")
    # right shard, attempts 0 and 1 fire; attempt 2 clean (retry succeeds)
    for attempt in (0, 1):
        with faults.shard_scope(3, attempt):
            with pytest.raises(OSError, match="injected fault at tar.open"):
                faults.fire("tar.open")
    with faults.shard_scope(3, 2):
        faults.fire("tar.open")
    assert [
        (f["shard"], f["attempt"], f["action"]) for f in faults.fired()
    ] == [(3, 0, "raise"), (3, 1, "raise")]


def test_install_from_env():
    assert not faults.install_from_env({"TMR_FAULTS": "  "})
    assert faults.install_from_env(
        {"TMR_FAULTS": "encode:nan=1", "TMR_FAULTS_SEED": "7"}
    )
    assert faults.active()


def test_corrupt_bytes_is_deterministic():
    payload = bytes(range(256)) * 4
    faults.configure("decode:shard=0:corrupt=1", seed=5)
    with faults.shard_scope(0, 0):
        a = faults.corrupt_bytes("decode", payload)
        b = faults.corrupt_bytes("decode", payload)
    assert a == b != payload
    # a different seed corrupts differently — replays are seed-exact
    faults.configure("decode:shard=0:corrupt=1", seed=6)
    with faults.shard_scope(0, 0):
        c = faults.corrupt_bytes("decode", payload)
    assert c != a
    # unmatched shard: payload passes through untouched
    with faults.shard_scope(1, 0):
        assert faults.corrupt_bytes("decode", payload) == payload


def test_poison_nans_whole_arrays():
    faults.configure("encode:nan=1")
    with faults.shard_scope(0, 0):
        f, s = faults.poison(
            "encode", np.ones((2, 3)), np.zeros((2, 4), np.float32)
        )
    assert np.isnan(f).all() and np.isnan(s).all()
    assert s.dtype == np.float32
    faults.clear()
    x = np.ones((2, 3))
    assert faults.poison("encode", x) is x  # disabled: identity, 1-arg form


def test_disabled_hooks_are_noop_cheap():
    """No schedule installed -> every hook is a falsy-dict check. 200k
    calls in well under a second pins that nothing (env parsing, regex,
    allocation) crept onto the per-image hot path."""
    assert not faults.active()
    payload = b"x" * 64
    t0 = time.perf_counter()
    for _ in range(200_000):
        faults.fire("decode")
        faults.corrupt_bytes("decode", payload)
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.0, f"disabled fault hooks cost {elapsed:.3f}s/400k"


# ------------------------------------------------------- backoff schedule
def test_backoff_doubles_and_caps_without_jitter():
    got = [backoff_delay(a, base=0.5, cap=4.0, jitter=0.0) for a in
           range(1, 7)]
    assert got == [0.5, 1.0, 2.0, 4.0, 4.0, 4.0]


def test_backoff_jitter_bounded_and_deterministic():
    for attempt in range(1, 8):
        base_d = backoff_delay(attempt, base=0.2, cap=30.0, jitter=0.0)
        d1 = backoff_delay(attempt, base=0.2, cap=30.0, jitter=0.5, key=11)
        d2 = backoff_delay(attempt, base=0.2, cap=30.0, jitter=0.5, key=11)
        assert d1 == d2  # replay-exact
        assert base_d <= d1 <= base_d * 1.5  # jitter bounded
    # schedule is monotone nondecreasing while the exponential dominates
    seq = [backoff_delay(a, base=0.2, cap=300.0, jitter=0.4, key=3)
           for a in range(1, 10)]
    assert all(b >= a for a, b in zip(seq, seq[1:]))


def test_validate_map_report_tolerates_garbage():
    """The validator gates possibly-corrupt documents — it must return
    problems, never raise, on malformed shapes."""
    from tmr_tpu.diagnostics import validate_map_report

    assert validate_map_report({}) != []
    doc = {
        "schema": "map_report/v1",
        "shards": ["Easy_0.tar", {"status": "ok", "causes": "oops"}],
        "quarantined": [], "resumed": [], "totals": {},
    }
    problems = validate_map_report(doc)
    assert any("shards[0]: not a dict" in p for p in problems)
    assert any("causes: not a list" in p for p in problems)
    problems = validate_map_report({
        "schema": "map_report/v1", "shards": [{"causes": [17]}],
        "quarantined": [], "resumed": [], "totals": {},
    })
    assert any("causes[0]: not a dict" in p for p in problems)


def test_retry_policy_delay_keys_on_shard():
    pol = RetryPolicy(backoff_base=0.1, backoff_max=10.0,
                      backoff_jitter=0.9, seed=1)
    assert pol.delay(0, 1) == pol.delay(0, 1)
    assert pol.delay(0, 1) != pol.delay(1, 1)  # shards decorrelate


def test_serve_tier_points_in_grammar_and_fire():
    """The PR-17 serve-tier points (serve.link, gallery.replica,
    gallery.beat) parse, scope, and fire like the map-tier points —
    one closed vocabulary, one grammar."""
    specs = faults.parse_schedule(
        "serve.link:shard=2:attempts=1:raise=OSError;"
        "gallery.replica:corrupt=1;"
        "gallery.beat:latency=0.01"
    )
    assert [s.point for s in specs] == [
        "serve.link", "gallery.replica", "gallery.beat"
    ]

    faults.configure("serve.link:shard=2:attempts=1:raise=OSError",
                     seed=0)
    with faults.shard_scope(1, 0):
        faults.fire("serve.link")  # wrong shard: no fire
    with faults.shard_scope(2, 1):
        faults.fire("serve.link")  # attempt past the bound: healed
    with faults.shard_scope(2, 0):
        with pytest.raises(OSError):
            faults.fire("serve.link")
    assert [r["action"] for r in faults.fired()] == ["raise"]

    faults.configure("gallery.replica:corrupt=1", seed=7)
    raw = bytes(range(256))
    with faults.shard_scope(0, 0):
        mangled = faults.corrupt_bytes("gallery.replica", raw)
    assert mangled != raw and len(mangled) == len(raw)
    assert mangled[64:] == raw[64:]  # first-64-bytes contract

    faults.configure("gallery.beat:latency=0.01", seed=0)
    t0 = time.monotonic()
    faults.fire("gallery.beat")  # no scope needed: unconditional spec
    assert time.monotonic() - t0 >= 0.01
    assert faults.fired()[-1]["action"] == "latency"
