"""Visualization subsystem (tmr_tpu/utils/visualize.py — reference
log_utils.py:311-531 + trainer.py presence dumps)."""

import json
import os

import numpy as np
import pytest

from tmr_tpu.utils.visualize import (
    per_image_ap50,
    plot_pr_curves,
    save_presence_maps,
    save_triptychs,
)


def _write_eval_jsons(log_path, stage="test"):
    imgs = [
        {"id": 1, "height": 64, "width": 96, "file_name": "a.png",
         "img_url": "/nonexistent/a.png",
         "exemplar_boxes": [[5, 5, 10, 10]]},
        {"id": 2, "height": 64, "width": 96, "file_name": "b.png",
         "img_url": "/nonexistent/b.png", "exemplar_boxes": []},
    ]
    gts = {"categories": [{"name": "fg", "id": 1}], "images": imgs,
           "annotations": [
               {"id": 1, "image_id": 1, "bbox": [10, 10, 20, 20],
                "area": 400, "iscrowd": 0, "category_id": 1},
               {"id": 2, "image_id": 1, "bbox": [50, 30, 20, 20],
                "area": 400, "iscrowd": 0, "category_id": 1},
               {"id": 3, "image_id": 2, "bbox": [4, 4, 12, 12],
                "area": 144, "iscrowd": 0, "category_id": 1},
           ]}
    preds = {"categories": [{"name": "fg", "id": 1}], "images": imgs,
             "annotations": [
                 {"id": 1, "image_id": 1, "bbox": [11, 11, 20, 20],
                  "area": 400, "category_id": 1, "score": 0.9,
                  "point": [20, 20]},
                 {"id": 2, "image_id": 1, "bbox": [80, 50, 10, 10],
                  "area": 100, "category_id": 1, "score": 0.4,
                  "point": [85, 55]},
                 {"id": 3, "image_id": 2, "bbox": [5, 5, 12, 12],
                  "area": 144, "category_id": 1, "score": 0.8,
                  "point": [10, 10]},
             ]}
    with open(os.path.join(log_path, f"instances_{stage}.json"), "w") as f:
        json.dump(gts, f)
    with open(os.path.join(log_path, f"predictions_{stage}.json"), "w") as f:
        json.dump(preds, f)


def test_per_image_ap50_perfect_and_miss():
    gt = np.array([[10, 10, 20, 20]])
    assert per_image_ap50(gt, np.array([[10, 10, 20, 20]]),
                          np.array([0.9])) == pytest.approx(100.0, abs=1.0)
    assert per_image_ap50(gt, np.array([[60, 60, 5, 5]]),
                          np.array([0.9])) == 0.0
    assert per_image_ap50(np.zeros((0, 4)), np.zeros((0, 4)),
                          np.zeros(0)) == 100.0


def test_triptychs_written_with_loader(tmp_path):
    _write_eval_jsons(str(tmp_path))
    rng = np.random.default_rng(0)

    def loader(img_info):
        return rng.integers(0, 255, (img_info["height"], img_info["width"],
                                     3), dtype=np.uint8).astype(np.uint8)

    paths = save_triptychs(str(tmp_path), "test", image_loader=loader)
    assert len(paths) == 2
    import cv2

    img = cv2.imread(paths[0])
    assert img is not None and img.shape == (64, 96 * 3, 3)  # 3 panels


def test_triptychs_missing_pixels_skipped(tmp_path):
    """img_url that can't be opened -> skipped, not raised."""
    _write_eval_jsons(str(tmp_path))
    assert save_triptychs(str(tmp_path), "test") == []


def test_pr_curves_written(tmp_path):
    _write_eval_jsons(str(tmp_path))
    path = plot_pr_curves(str(tmp_path), "test")
    assert path is not None and os.path.exists(path)


def test_presence_maps(tmp_path):
    maps = [np.random.default_rng(1).standard_normal((2, 16, 16))]
    paths = save_presence_maps(maps, str(tmp_path / "pm"), step=3)
    assert len(paths) == 1 and os.path.exists(paths[0])
    import cv2

    img = cv2.imread(paths[0], cv2.IMREAD_GRAYSCALE)
    assert img.shape == (16, 16)
