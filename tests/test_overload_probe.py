"""scripts/overload_probe.py: the overload_report/v1 contract, end to
end on CPU in a clean-env subprocess (same discipline as the serve_bench
smoke: no forced host-device count). One JSON line; every acceptance
check true: >= 5x offered load yields bounded admitted-traffic p99 and
EXACT reject/shed/complete accounting, deadline-expired requests shed
before any device work, the degrade ladder records its steps and its
auto trajectory, and close() mid-overload returns within its bound with
every future terminal. Validator both-ways coverage lives in
tests/test_overload.py — this module spends its wall budget on the one
real-program run only.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_overload_probe_tiny_smoke(tmp_path):
    out_file = tmp_path / "overload_report.json"
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS")
    }
    env.update(JAX_PLATFORMS="cpu", TMR_BENCH_TINY="1",
               TMR_BENCH_SIZE="128")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "overload_probe.py"),
         "--tiny", "--batch", "4", "--out", str(out_file)],
        env=env, capture_output=True, text=True, timeout=560,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    doc = json.loads(lines[0])

    from tmr_tpu.diagnostics import validate_overload_report

    assert validate_overload_report(doc) == []
    assert "validator_problems" not in doc
    checks = doc["checks"]
    for key in ("p99_bounded", "accounting_exact", "rejected_nonzero",
                "reject_causes_structured", "shed_before_device",
                "degrade_steps_recorded", "degrade_auto_ladder",
                "close_bounded"):
        assert checks[key] is True, (key, checks)
    over = doc["overload"]
    # the reconciliation identity, re-derived from the document itself
    assert (over["completed"] + over["rejected"] + over["shed"]
            + over["errors"]) == over["offered"]
    # rounded-field tolerance: both figures are stored at 3 decimals
    assert over["offered_img_per_sec"] >= (
        5 * doc["capacity"]["img_per_sec"] - 0.01
    )
    assert doc["shed_phase"]["shed"] == doc["shed_phase"]["offered"]
    assert doc["shed_phase"]["batches"] == 0
    assert doc["close"]["all_terminal"] is True
    assert json.loads(out_file.read_text())["checks"] == checks
    assert "[overload_probe]" in out.stderr  # progress on stderr only
