"""The serve-tier chaos gauntlet (scripts/serve_chaos_probe.py) must
pass on tier-1: replicated gallery shards survive repeated kill -9 of
primary holders with ZERO pattern loss, fan-out stays byte-identical
to the single bank when healthy, a severed serve link degrades exactly
the dead partition's patterns (and heals), a corrupted replica push is
digest-rejected and retried clean, the write-ahead journal refuses the
ack before any partial state, and a TMR_FAULTS env schedule reaches a
lease-held worker subprocess, and the streamed bulk-ingest path lands
its patterns in the same zero-loss ledger — one validated
serve_chaos_report/v1, rc-gated again (fail-closed) through
scripts/bench_trend.py --chaos."""

import importlib.util
import json
import os

import pytest

from tmr_tpu.diagnostics import (
    SERVE_CHAOS_CHECK_KEYS,
    validate_serve_chaos_report,
)
from tmr_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_schedule():
    faults.clear()
    yield
    faults.clear()


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serve_chaos_probe_passes(tmp_path, capsys):
    out = tmp_path / "serve_chaos_report.json"
    rc = _load("serve_chaos_probe").main(
        ["--tiny", "--out", str(out), "--patterns-per-shard", "2"]
    )
    assert rc == 0
    doc = json.loads(out.read_text())
    assert validate_serve_chaos_report(doc) == []
    checks = doc["checks"]
    for key in SERVE_CHAOS_CHECK_KEYS:
        assert checks[key] is True, key
    # the opt-in bulk-ingest phase streamed every pattern, replicated
    # them, and they joined the zero-loss ledger for the final sweep
    assert checks["bulk_ingest_ok"] is True
    (bulk,) = [p for p in doc["phases"] if p["name"] == "bulk_ingest"]
    assert bulk["streamed"] == bulk["patterns"] > 0
    assert bulk["parity"] is True
    # the ledger closes: every acknowledged registration survived
    assert doc["patterns"]["lost"] == []
    assert doc["patterns"]["registered"] == doc["patterns"]["survived"]
    assert doc["kills"]["rounds"] >= 1
    # every serve-tier fault point was injected, fired, and accounted
    points = {rec["point"] for rec in doc["faults"]["injected"]}
    assert points == {"serve.link", "gallery.replica", "gallery.beat",
                      "journal"}
    assert all(rec["fired"] >= 1 and rec["accounted"] >= 1
               for rec in doc["faults"]["injected"])
    # the trend reader rc-gates the same document
    capsys.readouterr()
    assert _load("bench_trend").main(["--chaos", str(out)]) == 0
    reader_doc = json.loads(capsys.readouterr().out.strip())
    assert reader_doc["checks"]["zero_patterns_lost"] is True
    assert reader_doc["checks"]["probe_checks_pass"] is True

    # --chaos is FAIL-CLOSED: a lost pattern flips the gate to rc 1
    tampered = json.loads(out.read_text())
    tampered["patterns"]["lost"] = ["pat000"]
    tampered["patterns"]["survived"] -= 1
    bad = tmp_path / "tampered.json"
    bad.write_text(json.dumps(tampered) + "\n")
    capsys.readouterr()
    assert _load("bench_trend").main(["--chaos", str(bad)]) == 1
    # ... and an error record (wedged probe) also gates rc 1
    err = tmp_path / "error.json"
    err.write_text(json.dumps(
        {"schema": "serve_chaos_report/v1", "error": "watchdog"}
    ) + "\n")
    capsys.readouterr()
    assert _load("bench_trend").main(["--chaos", str(err)]) == 1
