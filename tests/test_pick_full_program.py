"""Full-program A/B arbitration (scripts/pick_full_program.py): the
one-block autotune sweep's ranking can disagree with the production
program (round 4: flash won the sweep, lost the one-block profile), so the
battery's env-pinned whole-program benches decide — a decisive winner's
knobs are pinned into the autotune seed with fresh variant stamps.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _arbiter():
    spec = importlib.util.spec_from_file_location(
        "pick_full_program",
        os.path.join(REPO, "scripts", "pick_full_program.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rec(value, knobs=None, autotuned=None):
    return {
        "metric": "m", "value": value, "unit": "img/s", "vs_baseline": 0.1,
        "batch": 4, "knobs": knobs or {}, "autotuned": autotuned or {},
    }


@pytest.fixture
def seed_file(tmp_path, monkeypatch):
    path = tmp_path / "seed.json"
    path.write_text(json.dumps({
        "TPU v5 lite|1024|128|4|512|vit_b": {
            "TMR_GLOBAL_ATTN": "blockwise",
            "TMR_WIN_ATTN": "flash",
            "_variants_TMR_GLOBAL_ATTN": "stale",
            "_variants_TMR_WIN_ATTN": "stale",
        }
    }))
    monkeypatch.setenv("TMR_AUTOTUNE_SEED", str(path))
    return path


def test_decisive_full_program_winner_pins_seed(tmp_path, seed_file, capsys):
    """An env-pinned combo beating the autotuned headline by >3% rewrites
    the seed's formulation knobs with CURRENT variant stamps (so the entry
    loads as a cached hit, not stale) and keeps the A/B evidence."""
    arb = _arbiter()
    # headline: autotune exported its picks into the env, so knobs ==
    # autotuned (nothing externally pinned)
    (tmp_path / "bench_live.json").write_text(json.dumps(_rec(
        10.1,
        knobs={"TMR_GLOBAL_ATTN": "blockwise", "TMR_WIN_ATTN": "flash"},
        autotuned={"TMR_GLOBAL_ATTN": "blockwise", "TMR_WIN_ATTN": "flash"},
    )))
    # pinned run: TMR_GLOBAL_ATTN forced in the env (absent from autotuned)
    (tmp_path / "bench_pallas.json").write_text(json.dumps(_rec(
        27.4,
        knobs={"TMR_GLOBAL_ATTN": "pallas", "TMR_WIN_ATTN": "flash"},
        autotuned={"TMR_WIN_ATTN": "flash"},
    )))
    rc = arb.main([str(tmp_path / "bench_live.json"),
                   str(tmp_path / "bench_pallas.json")])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["updated"] is True and out["best"] == "bench_pallas.json"

    from tmr_tpu.utils.autotune import _load_validated, _variants_sig

    seed = json.loads(seed_file.read_text())
    entry = seed["TPU v5 lite|1024|128|4|512|vit_b"]
    assert entry["TMR_GLOBAL_ATTN"] == "pallas"
    # the winning run's autotuned windowed pick is full-program-endorsed
    assert entry["TMR_WIN_ATTN"] == "flash"
    assert entry["_variants_TMR_GLOBAL_ATTN"] == _variants_sig(
        "TMR_GLOBAL_ATTN"
    )
    assert "_full_program_ab" in entry
    # and the written entry survives the loader's validation
    loaded = _load_validated(str(seed_file))
    assert loaded["TPU v5 lite|1024|128|4|512|vit_b"][
        "TMR_GLOBAL_ATTN"] == "pallas"


def test_non_decisive_win_leaves_seed_alone(tmp_path, seed_file, capsys):
    arb = _arbiter()
    before = seed_file.read_text()
    (tmp_path / "bench_live.json").write_text(json.dumps(_rec(
        10.1, knobs={"TMR_GLOBAL_ATTN": "blockwise"},
        autotuned={"TMR_GLOBAL_ATTN": "blockwise"},
    )))
    (tmp_path / "bench_pallas.json").write_text(json.dumps(_rec(
        10.2, knobs={"TMR_GLOBAL_ATTN": "pallas"},
    )))
    rc = arb.main([str(tmp_path / "bench_live.json"),
                   str(tmp_path / "bench_pallas.json")])
    assert rc == 3
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["updated"] is False
    assert seed_file.read_text() == before


def test_no_baseline_refuses_to_pin(tmp_path, seed_file, capsys):
    """A pinned record with no valid autotuned headline to compare against
    must NOT be pinned — without the margin check the combo was never shown
    to beat the autotuned program (review finding r5)."""
    arb = _arbiter()
    before = seed_file.read_text()
    (tmp_path / "bench_pallas.json").write_text(json.dumps(_rec(
        27.4, knobs={"TMR_GLOBAL_ATTN": "pallas"},
    )))
    rc = arb.main([str(tmp_path / "bench_pallas.json")])
    assert rc == 3
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["updated"] is False and "baseline" in out["reason"]
    assert seed_file.read_text() == before


def test_pins_only_matching_batch_entries(tmp_path, seed_file, capsys):
    """A batch-4 A/B must not overwrite a batch-8 seed entry's winners."""
    arb = _arbiter()
    seed = json.loads(seed_file.read_text())
    seed["TPU v5 lite|1024|128|8|512|vit_b"] = {
        "TMR_GLOBAL_ATTN": "flash",
        "_variants_TMR_GLOBAL_ATTN": "whatever",
    }
    seed_file.write_text(json.dumps(seed))
    (tmp_path / "bench_live.json").write_text(json.dumps(_rec(
        10.0, knobs={"TMR_GLOBAL_ATTN": "blockwise"},
        autotuned={"TMR_GLOBAL_ATTN": "blockwise"},
    )))
    (tmp_path / "bench_pallas.json").write_text(json.dumps(_rec(
        20.0, knobs={"TMR_GLOBAL_ATTN": "pallas"},
    )))
    rc = arb.main([str(tmp_path / "bench_live.json"),
                   str(tmp_path / "bench_pallas.json")])
    assert rc == 0
    seed = json.loads(seed_file.read_text())
    assert seed["TPU v5 lite|1024|128|4|512|vit_b"][
        "TMR_GLOBAL_ATTN"] == "pallas"
    # the batch-8 entry is untouched
    assert seed["TPU v5 lite|1024|128|8|512|vit_b"][
        "TMR_GLOBAL_ATTN"] == "flash"


def test_size_match_is_positional_not_substring(tmp_path, seed_file, capsys):
    """A 512-px record must NOT update the 1024 entry: '|512|' would
    substring-match the emb field of EVERY key (kind|image|up_hw|batch|emb|
    vit) — the match must compare the image field positionally."""
    arb = _arbiter()
    base = _rec(10.0, knobs={"TMR_GLOBAL_ATTN": "blockwise"},
                autotuned={"TMR_GLOBAL_ATTN": "blockwise"})
    pin = _rec(20.0, knobs={"TMR_GLOBAL_ATTN": "pallas"})
    for r in (base, pin):
        r["image_size"] = 512
        r["device_kind"] = "TPU v5 lite"
    (tmp_path / "bench_live.json").write_text(json.dumps(base))
    (tmp_path / "bench_pallas.json").write_text(json.dumps(pin))
    rc = arb.main([str(tmp_path / "bench_live.json"),
                   str(tmp_path / "bench_pallas.json")])
    assert rc == 0
    seed = json.loads(seed_file.read_text())
    # the 1024 entry is untouched; a NEW 512 key was created instead
    assert seed["TPU v5 lite|1024|128|4|512|vit_b"][
        "TMR_GLOBAL_ATTN"] == "blockwise"
    assert seed["TPU v5 lite|512|64|4|512|vit_b"][
        "TMR_GLOBAL_ATTN"] == "pallas"


def test_error_records_and_missing_files_are_skipped(tmp_path, seed_file,
                                                     capsys):
    arb = _arbiter()
    (tmp_path / "bench_err.json").write_text(json.dumps(
        {"metric": "m", "value": 0.0, "error": "wedge"}
    ))
    rc = arb.main([str(tmp_path / "bench_err.json"),
                   str(tmp_path / "nonexistent.json")])
    assert rc == 3
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["updated"] is False


def test_pinned_tile_knobs_round_trip_the_cache(tmp_path, monkeypatch):
    """Tile/group pins written by the arbiter must survive cache validation
    and be exported to the env by autotune() as cached hits — the pallas
    kernels read them at trace time."""
    import jax

    from tmr_tpu.utils import autotune as at

    seed = tmp_path / "seed.json"
    seed.write_text(json.dumps({
        "cpu|1024|128|4|512|vit_b": {
            "TMR_GLOBAL_ATTN": "pallas",
            "_variants_TMR_GLOBAL_ATTN": at._variants_sig("TMR_GLOBAL_ATTN"),
            "TMR_PALLAS_ATTN_BQ": "256",
            "TMR_PALLAS_ATTN_BK": "1024",
            "TMR_PALLAS_WIN_GROUP": "8",
            "TMR_PALLAS_ATTN_BQ_bad": "300",  # not pow2: must be dropped
        }
    }))
    monkeypatch.setenv("TMR_AUTOTUNE_SEED", str(seed))
    monkeypatch.setenv("TMR_AUTOTUNE_CACHE", str(tmp_path / "cache.json"))
    loaded = at._load_validated(str(seed))
    entry = loaded["cpu|1024|128|4|512|vit_b"]
    assert entry["TMR_PALLAS_ATTN_BQ"] == "256"
    assert entry["TMR_PALLAS_WIN_GROUP"] == "8"
    assert "TMR_PALLAS_ATTN_BQ_bad" not in entry

    for k in ("TMR_GLOBAL_ATTN", "TMR_WIN_ATTN", "TMR_XCORR_IMPL",
              "TMR_XCORR_IMPL_SMALL", "TMR_XCORR_PRECISION",
              "TMR_PALLAS_ATTN_BQ", "TMR_PALLAS_ATTN_BK",
              "TMR_PALLAS_WIN_GROUP"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(at, "measure_rtt_floor", lambda: 0.0)
    monkeypatch.setattr(
        at, "pick_xcorr_impl", lambda *a, **k: {"conv": 0.01}
    )
    monkeypatch.setattr(
        at, "pick_win_attn_impl", lambda *a, **k: {"dense": 0.01}
    )
    monkeypatch.setattr(
        at, "pick_global_attn_impl", lambda *a, **k: {"blockwise": 0.01}
    )
    # the PR 6 decoder/quant stages are NOT what this test pins (tile
    # knobs round-tripping the cache) — unmocked they compile real
    # stage programs at the 1024 geometry and were silently charging
    # ~5 minutes of tier-1 wall to an unrelated code path
    monkeypatch.setattr(
        at, "pick_decoder_impl", lambda *a, **k: {"xla": 0.01}
    )
    monkeypatch.setattr(
        at, "pick_quant", lambda *a, **k: {"off": 0.01}
    )

    class _Dev:
        device_kind = "cpu"

    monkeypatch.setattr(jax, "devices", lambda *a: [_Dev()])
    from tmr_tpu.config import preset

    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=256,
                 batch_size=1)
    report = at.autotune(cfg, 1024, 4, tune_precision=False)
    try:
        assert report["TMR_GLOBAL_ATTN"] == {"picked": "pallas",
                                             "cached": True}
        assert os.environ["TMR_PALLAS_ATTN_BQ"] == "256"
        assert os.environ["TMR_PALLAS_ATTN_BK"] == "1024"
        assert os.environ["TMR_PALLAS_WIN_GROUP"] == "8"
    finally:
        for k in ("TMR_GLOBAL_ATTN", "TMR_WIN_ATTN", "TMR_XCORR_IMPL_SMALL",
                  "TMR_PALLAS_ATTN_BQ", "TMR_PALLAS_ATTN_BK",
                  "TMR_PALLAS_WIN_GROUP", "TMR_XCORR_PRECISION"):
            os.environ.pop(k, None)
