"""scripts/obs_probe.py: the trace_report/v1 contract.

The smoke test runs the real probe in a subprocess at tiny CPU shapes in
a CLEAN env (no forced host-device count, like the serve_bench smoke) and
asserts the acceptance checks: all seven serve pipeline stages traced
with a consistent per-request trace ID, at least one compile event with
its key, Chrome-trace JSON round-trip, and disabled-mode overhead < 1%.
The validator tests pin the schema both ways.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _probe_env(**extra):
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS", "TMR_TRACE")
    }
    env.update(
        JAX_PLATFORMS="cpu",
        TMR_BENCH_TINY="1",
        TMR_BENCH_SIZE="128",
        **extra,
    )
    return env


def _valid_doc():
    from tmr_tpu import obs
    from tmr_tpu.diagnostics import TRACE_REPORT_SCHEMA, TRACE_SERVE_STAGES

    stage = {"count": 6, "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0}
    return {
        "schema": TRACE_REPORT_SCHEMA,
        "device": "cpu",
        "config": {"image_size": 128, "batch": 2, "requests": 6},
        "serve": {"stages": {name: dict(stage)
                             for name in TRACE_SERVE_STAGES}},
        "map": {"stages": {"map.attempt": dict(stage)}},
        "compile_events": [
            {"kind": "single", "key": "(9, False)", "wall_s": 1.5,
             "cause": "cold"},
        ],
        "metrics": obs.MetricsRegistry().snapshot(),
        "overhead": {"disabled_ns_per_span": 300.0,
                     "overhead_disabled_pct": 0.001},
        "checks": {"stages_complete": True, "compile_event_recorded": True,
                   "trace_roundtrip": True, "overhead_ok": True},
    }


def test_validate_trace_report_accepts_valid_and_error_docs():
    from tmr_tpu.diagnostics import TRACE_REPORT_SCHEMA, validate_trace_report

    assert validate_trace_report(_valid_doc()) == []
    assert validate_trace_report(
        {"schema": TRACE_REPORT_SCHEMA, "error": "watchdog: ..."}
    ) == []


@pytest.mark.parametrize("mutate, fragment", [
    (lambda d: d.update(schema="bogus/v9"), "schema"),
    (lambda d: d.pop("metrics"), "metrics"),
    (lambda d: d["metrics"].update(schema="wrong"), "metrics"),
    (lambda d: d.pop("serve"), "serve"),
    (lambda d: d["serve"]["stages"]["serve.submit"].pop("p99_ms"), "p99_ms"),
    (lambda d: d["compile_events"][0].update(cause="weird"), "cause"),
    (lambda d: d.pop("overhead"), "overhead"),
    (lambda d: d["overhead"].pop("overhead_disabled_pct"),
     "overhead_disabled_pct"),
    (lambda d: d["checks"].pop("stages_complete"), "stages_complete"),
    (lambda d: d.update(error=""), "error"),
])
def test_validate_trace_report_rejects_broken_docs(mutate, fragment):
    from tmr_tpu.diagnostics import validate_trace_report

    doc = _valid_doc()
    mutate(doc)
    problems = validate_trace_report(doc)
    assert problems, f"expected a problem for {fragment}"
    assert any(fragment in p for p in problems), problems


def test_obs_probe_tiny_smoke_meets_acceptance_checks(tmp_path):
    """The acceptance proof, end to end on CPU: one JSON line, valid
    trace_report/v1, all seven serve stages traced under per-request
    trace IDs, a compile event with its key, bounded disabled overhead."""
    out_file = tmp_path / "trace_report.json"
    trace_file = tmp_path / "trace.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_probe.py"),
         "--tiny", "--out", str(out_file), "--trace-out", str(trace_file)],
        env=_probe_env(), capture_output=True, text=True, timeout=560,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    doc = json.loads(lines[0])

    from tmr_tpu.diagnostics import TRACE_SERVE_STAGES, validate_trace_report

    assert validate_trace_report(doc) == []
    assert "validator_problems" not in doc
    checks = doc["checks"]
    assert checks["stages_complete"] is True, checks
    assert checks["compile_event_recorded"] is True
    assert checks["map_retry_observed"] is True
    assert checks["trace_roundtrip"] is True
    assert checks["overhead_ok"] is True
    assert doc["overhead"]["overhead_disabled_pct"] < 1.0
    # every stage traced, count >= the workload's request count
    for name in TRACE_SERVE_STAGES:
        assert doc["serve"]["stages"][name]["count"] >= doc["serve"][
            "requests"
        ], name
    assert doc["serve"]["complete_request_traces"] >= 1
    # compile events carry their keys and a closed-vocabulary cause
    assert any(e["key"] for e in doc["compile_events"])
    # map section saw the injected retry
    assert doc["map"]["report_valid"] is True
    assert doc["map"]["stages"]["map.attempt"]["count"] >= 3
    assert "map.backoff" in doc["map"]["stages"]
    # the attached registry snapshot counts the compile events
    assert doc["metrics"]["counters"]["compile.total"] >= 1
    # --out wrote the same document; --trace-out wrote loadable JSON
    assert json.loads(out_file.read_text())["checks"] == checks
    chrome = json.loads(trace_file.read_text())
    assert any(e.get("ph") == "X" for e in chrome["traceEvents"])
    # progress goes to stderr, never stdout
    assert "[obs_probe]" in out.stderr


@pytest.mark.slow
def test_obs_probe_watchdog_emits_error_record(tmp_path):
    """A wedge yields the contractual one-line error record — still a
    valid trace_report/v1 document (the bench_guard pattern)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_probe.py"),
         "--tiny"],
        env=_probe_env(
            TMR_BENCH_ALARM="1",
            TMR_COMPILATION_CACHE=str(tmp_path / "xla-cache"),
        ),
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 2
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "watchdog" in rec["error"]

    from tmr_tpu.diagnostics import validate_trace_report

    assert validate_trace_report(rec) == []
