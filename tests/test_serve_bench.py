"""scripts/serve_bench.py: the serve_report/v1 contract.

The smoke test runs the real script in a subprocess at tiny CPU shapes in
a CLEAN env (no forced host-device count — conftest's 8 virtual devices
change XLA:CPU's thread partitioning per batch shape, see test_serve.py)
and asserts the acceptance checks: batched+cached speedup >= 1.5x over the
sequential Predictor loop, results bitwise-identical to sequential, p99
bounded, cache hits observed. The validator tests pin the schema both ways.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _serve_env(**extra):
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS")
    }
    env.update(
        JAX_PLATFORMS="cpu",
        TMR_BENCH_TINY="1",
        TMR_BENCH_SIZE="128",
        **extra,
    )
    return env


def _valid_doc():
    from tmr_tpu.diagnostics import SERVE_REPORT_SCHEMA

    cache = {"result_cache": {"hits": 1, "misses": 2, "evictions": 0,
                              "inserts": 2},
             "feature_cache": {"hits": 0, "misses": 3, "evictions": 1,
                               "inserts": 1}}
    return {
        "schema": SERVE_REPORT_SCHEMA,
        "device": "cpu",
        "config": {"image_size": 128, "batch": 4, "max_wait_ms": 10.0},
        "workloads": [{
            "name": "exact_closed", "mode": "closed", "requests": 11,
            "throughput_img_per_sec": 1.2,
            "latency_ms": {"p50": 10.0, "p95": 20.0, "p99": 30.0},
            "batch_occupancy": {"4": 2, "3": 1},
            "cache": cache,
        }],
        "checks": {"speedup_vs_sequential": 1.9, "speedup_ok": True,
                   "exact_match": True, "p99_bounded": True,
                   "cache_hit": True},
    }


def test_validate_serve_report_accepts_valid_and_error_docs():
    from tmr_tpu.diagnostics import SERVE_REPORT_SCHEMA, validate_serve_report

    assert validate_serve_report(_valid_doc()) == []
    # bench_guard's wedge record is contractually valid
    assert validate_serve_report(
        {"schema": SERVE_REPORT_SCHEMA, "error": "watchdog: ..."}
    ) == []


@pytest.mark.parametrize("mutate, fragment", [
    (lambda d: d.update(schema="bogus/v9"), "schema"),
    (lambda d: d.pop("workloads"), "workloads"),
    (lambda d: d["workloads"][0].update(mode="sideways"), "mode"),
    (lambda d: d["workloads"][0]["latency_ms"].pop("p99"), "p99"),
    (lambda d: d["workloads"][0].update(batch_occupancy={"4": "two"}),
     "batch_occupancy"),
    (lambda d: d["workloads"][0]["cache"].pop("feature_cache"),
     "feature_cache"),
    (lambda d: d.pop("checks"), "checks"),
    (lambda d: d["checks"].pop("exact_match"), "exact_match"),
    (lambda d: d.update(error=""), "error"),
])
def test_validate_serve_report_rejects_broken_docs(mutate, fragment):
    from tmr_tpu.diagnostics import validate_serve_report

    doc = _valid_doc()
    mutate(doc)
    problems = validate_serve_report(doc)
    assert problems, f"expected a problem for {fragment}"
    assert any(fragment in p for p in problems), problems


def test_serve_bench_tiny_smoke_meets_acceptance_checks(tmp_path):
    """The acceptance proof, end to end on CPU: one JSON line, valid
    serve_report/v1, speedup >= 1.5x, bitwise exactness, bounded p99,
    cache hits > 0."""
    out_file = tmp_path / "serve_report.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--tiny", "--batch", "4", "--out", str(out_file)],
        env=_serve_env(), capture_output=True, text=True, timeout=560,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    doc = json.loads(lines[0])

    from tmr_tpu.diagnostics import validate_serve_report

    assert validate_serve_report(doc) == []
    assert "validator_problems" not in doc
    checks = doc["checks"]
    assert checks["exact_match"] is True
    assert checks["speedup_ok"] is True, checks
    assert checks["speedup_vs_sequential"] >= 1.5
    assert checks["p99_bounded"] is True, checks
    assert checks["cache_hit"] is True and checks["cache_hits"] > 0
    names = [w["name"] for w in doc["workloads"]]
    assert "exact_closed" in names and "mixed_closed" in names
    assert any(n.startswith("open_rate_") for n in names)
    open_w = next(w for w in doc["workloads"]
                  if w["name"].startswith("open_rate_"))
    assert open_w["mode"] == "open" and "offered_img_per_sec" in open_w
    # --out wrote the same document
    assert json.loads(out_file.read_text())["checks"] == checks
    # progress goes to stderr, never stdout
    assert "[serve_bench]" in out.stderr


def _mesh_attachment():
    return {
        "spec": "dp2tp2",
        "shape": {"dp": 2, "tp": 2},
        "axis_names": ["dp", "tp"],
        "replica_groups": [["TFRT_CPU_0", "TFRT_CPU_1"],
                           ["TFRT_CPU_2", "TFRT_CPU_3"]],
        "tp_size_threshold": 512,
    }


def test_validate_serve_report_mesh_attachment():
    from tmr_tpu.diagnostics import validate_serve_report

    doc = _valid_doc()
    doc["mesh"] = _mesh_attachment()
    assert validate_serve_report(doc) == []
    # absent mesh = the unsharded engine, still valid (pre-mesh docs)
    assert validate_serve_report(_valid_doc()) == []
    for mutate, fragment in [
        (lambda m: m.update(spec=""), "spec"),
        (lambda m: m.update(shape={"dp": "two"}), "shape"),
        (lambda m: m.update(shape={"dp": 0}), "shape"),
        (lambda m: m.update(axis_names="dp,tp"), "axis_names"),
        (lambda m: m.update(replica_groups=[]), "replica_groups"),
        (lambda m: m.update(replica_groups=[[1, 2]]), "replica_groups"),
    ]:
        doc = _valid_doc()
        doc["mesh"] = _mesh_attachment()
        mutate(doc["mesh"])
        problems = validate_serve_report(doc)
        assert any(fragment in p for p in problems), (fragment, problems)


def test_read_serve_sweep_reduces_mesh_rounds(tmp_path):
    from tmr_tpu.utils.bench_trend import read_serve_sweep

    doc = _valid_doc()
    doc["mesh"] = _mesh_attachment()
    doc["config"]["devices"] = 4
    doc["workloads"][0]["single_device_img_per_sec"] = 0.6
    doc["checks"].update(scaling_vs_single_device=2.0, scaling_ok=True,
                         parity="bitwise", p99_ms=30.0)
    doc["aot"] = {"compile_events_after_warmup": 0}
    sweep = tmp_path / "sweep.jsonl"
    sweep.write_text(json.dumps(doc) + "\n" + json.dumps(doc) + "\n"
                     + "not json\n")
    out = read_serve_sweep(str(sweep))
    assert out["checks"]["shapes_read"] == 2
    assert out["checks"]["all_exact"] is True
    assert out["checks"]["all_scaling_ok"] is True
    assert out["checks"]["all_warm"] is True
    row = out["rows"][0]
    assert row["spec"] == "dp2tp2" and row["scaling"] == 2.0
    assert row["cold_compiles_after_warmup"] == 0
    # an empty / mesh-less file is an error record, not a crash
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps(_valid_doc()) + "\n")
    assert "error" in read_serve_sweep(str(empty))
    assert "error" in read_serve_sweep(str(tmp_path / "absent.jsonl"))


def test_serve_bench_mesh_sweep_smoke(tmp_path):
    """``--mesh dp2`` on a forced-8-device CPU subprocess: one
    serve_report/v1 line with a validated mesh attachment, bitwise
    parity vs the single-device engine, and the AOT zero-cold-compile
    pin — the tentpole's sweep contract end to end."""
    out_file = tmp_path / "mesh_sweep.jsonl"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--tiny", "--batch", "1", "--mesh", "dp2",
         "--out", str(out_file)],
        env=_serve_env(
            XLA_FLAGS="--xla_force_host_platform_device_count=8",
        ),
        capture_output=True, text=True, timeout=560,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected one line per mesh shape: {lines}"
    doc = json.loads(lines[0])

    from tmr_tpu.diagnostics import validate_serve_report

    assert validate_serve_report(doc) == []
    assert "validator_problems" not in doc
    assert doc["mesh"]["spec"] == "dp2"
    assert doc["mesh"]["shape"] == {"dp": 2, "tp": 1}
    assert len(doc["mesh"]["replica_groups"]) == 2
    checks = doc["checks"]
    assert checks["parity"] == "bitwise"
    assert checks["exact_match"] is True
    assert checks["no_cold_compiles_after_warmup"] is True
    assert checks["p99_bounded"] is True, checks
    assert checks["scaling_ok"] is True, checks
    assert doc["aot"]["warmup"]["programs"] >= 1
    assert doc["stats"]["per_group_queues"].keys() >= {"group0",
                                                       "group1", "dp"}
    # the sweep reader consumes the --out file
    from tmr_tpu.utils.bench_trend import read_serve_sweep

    reduced = read_serve_sweep(str(out_file))
    assert reduced["checks"]["shapes_read"] == 1
    assert reduced["checks"]["all_warm"] is True


@pytest.mark.slow
def test_serve_bench_watchdog_emits_error_record(tmp_path):
    """A wedge yields the contractual one-line error record — still a
    valid serve_report/v1 document (the bench_guard pattern)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--tiny"],
        env=_serve_env(
            TMR_BENCH_ALARM="1",
            TMR_COMPILATION_CACHE=str(tmp_path / "xla-cache"),
        ),
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 2
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "watchdog" in rec["error"]

    from tmr_tpu.diagnostics import validate_serve_report

    assert validate_serve_report(rec) == []
