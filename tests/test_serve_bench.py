"""scripts/serve_bench.py: the serve_report/v1 contract.

The smoke test runs the real script in a subprocess at tiny CPU shapes in
a CLEAN env (no forced host-device count — conftest's 8 virtual devices
change XLA:CPU's thread partitioning per batch shape, see test_serve.py)
and asserts the acceptance checks: batched+cached speedup >= 1.5x over the
sequential Predictor loop, results bitwise-identical to sequential, p99
bounded, cache hits observed. The validator tests pin the schema both ways.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _serve_env(**extra):
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS")
    }
    env.update(
        JAX_PLATFORMS="cpu",
        TMR_BENCH_TINY="1",
        TMR_BENCH_SIZE="128",
        **extra,
    )
    return env


def _valid_doc():
    from tmr_tpu.diagnostics import SERVE_REPORT_SCHEMA

    cache = {"result_cache": {"hits": 1, "misses": 2, "evictions": 0,
                              "inserts": 2},
             "feature_cache": {"hits": 0, "misses": 3, "evictions": 1,
                               "inserts": 1}}
    return {
        "schema": SERVE_REPORT_SCHEMA,
        "device": "cpu",
        "config": {"image_size": 128, "batch": 4, "max_wait_ms": 10.0},
        "workloads": [{
            "name": "exact_closed", "mode": "closed", "requests": 11,
            "throughput_img_per_sec": 1.2,
            "latency_ms": {"p50": 10.0, "p95": 20.0, "p99": 30.0},
            "batch_occupancy": {"4": 2, "3": 1},
            "cache": cache,
        }],
        "checks": {"speedup_vs_sequential": 1.9, "speedup_ok": True,
                   "exact_match": True, "p99_bounded": True,
                   "cache_hit": True},
    }


def test_validate_serve_report_accepts_valid_and_error_docs():
    from tmr_tpu.diagnostics import SERVE_REPORT_SCHEMA, validate_serve_report

    assert validate_serve_report(_valid_doc()) == []
    # bench_guard's wedge record is contractually valid
    assert validate_serve_report(
        {"schema": SERVE_REPORT_SCHEMA, "error": "watchdog: ..."}
    ) == []


@pytest.mark.parametrize("mutate, fragment", [
    (lambda d: d.update(schema="bogus/v9"), "schema"),
    (lambda d: d.pop("workloads"), "workloads"),
    (lambda d: d["workloads"][0].update(mode="sideways"), "mode"),
    (lambda d: d["workloads"][0]["latency_ms"].pop("p99"), "p99"),
    (lambda d: d["workloads"][0].update(batch_occupancy={"4": "two"}),
     "batch_occupancy"),
    (lambda d: d["workloads"][0]["cache"].pop("feature_cache"),
     "feature_cache"),
    (lambda d: d.pop("checks"), "checks"),
    (lambda d: d["checks"].pop("exact_match"), "exact_match"),
    (lambda d: d.update(error=""), "error"),
])
def test_validate_serve_report_rejects_broken_docs(mutate, fragment):
    from tmr_tpu.diagnostics import validate_serve_report

    doc = _valid_doc()
    mutate(doc)
    problems = validate_serve_report(doc)
    assert problems, f"expected a problem for {fragment}"
    assert any(fragment in p for p in problems), problems


def test_serve_bench_tiny_smoke_meets_acceptance_checks(tmp_path):
    """The acceptance proof, end to end on CPU: one JSON line, valid
    serve_report/v1, speedup >= 1.5x, bitwise exactness, bounded p99,
    cache hits > 0."""
    out_file = tmp_path / "serve_report.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--tiny", "--batch", "4", "--out", str(out_file)],
        env=_serve_env(), capture_output=True, text=True, timeout=560,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    doc = json.loads(lines[0])

    from tmr_tpu.diagnostics import validate_serve_report

    assert validate_serve_report(doc) == []
    assert "validator_problems" not in doc
    checks = doc["checks"]
    assert checks["exact_match"] is True
    assert checks["speedup_ok"] is True, checks
    assert checks["speedup_vs_sequential"] >= 1.5
    assert checks["p99_bounded"] is True, checks
    assert checks["cache_hit"] is True and checks["cache_hits"] > 0
    names = [w["name"] for w in doc["workloads"]]
    assert "exact_closed" in names and "mixed_closed" in names
    assert any(n.startswith("open_rate_") for n in names)
    open_w = next(w for w in doc["workloads"]
                  if w["name"].startswith("open_rate_"))
    assert open_w["mode"] == "open" and "offered_img_per_sec" in open_w
    # --out wrote the same document
    assert json.loads(out_file.read_text())["checks"] == checks
    # progress goes to stderr, never stdout
    assert "[serve_bench]" in out.stderr


@pytest.mark.slow
def test_serve_bench_watchdog_emits_error_record(tmp_path):
    """A wedge yields the contractual one-line error record — still a
    valid serve_report/v1 document (the bench_guard pattern)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--tiny"],
        env=_serve_env(
            TMR_BENCH_ALARM="1",
            TMR_COMPILATION_CACHE=str(tmp_path / "xla-cache"),
        ),
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 2
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "watchdog" in rec["error"]

    from tmr_tpu.diagnostics import validate_serve_report

    assert validate_serve_report(rec) == []
