"""CheckpointManager.restore host-roundtrip semantics.

The restore path converts leaves to host numpy to drop orbax's committed
sharding annotations (the measured 9.2x eval fix, PERF.md 2026-08-01) —
but ``np.asarray`` RAISES on arrays that are not fully addressable, which
used to abort every multi-host / pipeline-mesh resume. The guard converts
only fully-addressable leaves and passes sharded leaves through; these
tests pin both halves, including a real save/restore round-trip with
params sharded over the 8-virtual-device CPU mesh (conftest.py)."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from tmr_tpu.utils.checkpoint import CheckpointManager, _host_leaf


def test_host_leaf_converts_addressable_and_passes_sharded():
    # plain numpy / jax arrays (fully addressable) -> host numpy
    out = _host_leaf(jnp.arange(4.0))
    assert isinstance(out, np.ndarray)
    out = _host_leaf(np.arange(3))
    assert isinstance(out, np.ndarray)
    # non-array leaves (step counters, None) pass through untouched
    assert _host_leaf(7) == 7
    assert _host_leaf(None) is None

    class _ShardedStub:
        """Stands in for a multi-host jax.Array: has a shape, claims not
        to be fully addressable, and raises if anything tries to pull its
        (remote) values to host — exactly what np.asarray would do."""

        shape = (8, 2)
        is_fully_addressable = False

        def __array__(self, *a, **k):
            raise RuntimeError("tried to fetch non-addressable shards")

    stub = _ShardedStub()
    assert _host_leaf(stub) is stub  # passthrough, no __array__ call


def test_meta_survives_corruption_and_writes_atomically(tmp_path):
    """ckpt_meta.json: a truncated/garbage file (crash mid-write under the
    old non-atomic writer, or disk damage) must fall back to defaults with
    a warning instead of crashing json.load in the constructor; _save_meta
    goes through tmp + os.replace so no partial meta can exist."""
    import glob
    import json

    d = str(tmp_path / "ckpt")
    os.makedirs(d)
    meta_path = os.path.join(d, "ckpt_meta.json")
    with open(meta_path, "w") as f:
        f.write('{"best_value": 0.9, "best_ver')  # truncated mid-write

    mgr = CheckpointManager(d)  # must not raise
    assert mgr.meta == {
        "best_value": None, "best_version": -1, "last_epoch": -1
    }

    mgr.meta["last_epoch"] = 4
    mgr._save_meta()
    assert not glob.glob(meta_path + ".tmp.*")  # replace, not leftover
    with open(meta_path) as f:
        assert json.load(f)["last_epoch"] == 4
    # a valid meta still round-trips through the constructor
    assert CheckpointManager(d).meta["last_epoch"] == 4
    # non-dict JSON is also rejected to defaults, not crashed on
    with open(meta_path, "w") as f:
        json.dump([1, 2, 3], f)
    assert CheckpointManager(d).meta["best_version"] == -1


def test_restore_on_eight_device_mesh(tmp_path):
    """Save a param tree sharded over the 8-virtual-device mesh, restore
    with the sharded tree as target: must not raise, and every fully-
    addressable leaf must come back as HOST numpy with the saved values
    (the single-host fix preserved)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("data", "model"))
    params = {
        "w": jnp.asarray(
            np.random.default_rng(0).standard_normal((16, 8)), jnp.float32
        ),
        "b": jnp.zeros((8,), jnp.float32),
        "step": 3,
    }
    sharded = {
        "w": jax.device_put(
            params["w"], NamedSharding(mesh, P("data", "model"))
        ),
        "b": jax.device_put(params["b"], NamedSharding(mesh, P("model"))),
        "step": 3,
    }

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save_epoch(sharded, epoch=0, metrics={})
    mgr.wait()
    path = mgr.last_path()
    assert path and os.path.isdir(path)

    restored = mgr.restore(path, target=sharded)
    for name in ("w", "b"):
        leaf = restored[name]
        # single-process: everything is addressable -> host numpy
        assert isinstance(leaf, np.ndarray), (name, type(leaf))
        np.testing.assert_allclose(leaf, np.asarray(params[name]))
