"""Replicated gallery partitions (tmr_tpu/serve/gallery_fleet.py):
stable shard placement, the byte-exact results codec, the write-ahead
pattern journal (fencing + digest + refusal semantics), and the
in-process fleet loop — leased shards, replicated registration,
fan-out parity with the single bank, fenced stale searches, and the
counted partition_unavailable degrade when holders die.

The subprocess version of this story (kill -9, env-delivered faults)
is scripts/serve_chaos_probe.py, gated via test_serve_chaos_probe.py;
these tests pin the module's contracts without process churn."""

import json
import os
import time

import numpy as np
import pytest

from tmr_tpu.parallel.leases import LeasePolicy, oneshot
from tmr_tpu.serve.gallery_fleet import (
    GALLERY_JOURNAL_SCHEMA,
    GalleryFleet,
    GalleryFleetWorker,
    PatternJournal,
    StaleLeaseError,
    StubGalleryBank,
    pack_results,
    shard_of,
    unavailable_result,
    unpack_results,
)
from tmr_tpu.utils import faults

SIZE = 16


@pytest.fixture(autouse=True)
def _clean_schedule():
    faults.clear()
    yield
    faults.clear()


def _policy():
    return LeasePolicy(
        lease_ttl_s=1.0, hb_interval_s=0.2, check_interval_s=0.05,
        straggler_factor=0.0, max_reassigns=1_000_000_000,
        resource_fail_workers=1_000_000_000,
    )


def _poll(predicate, timeout_s=15.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval_s)
    return None


def _dets_equal(got, want):
    if set(got) != set(want):
        return False
    for key, w in want.items():
        g = got[key]
        if isinstance(w, np.ndarray):
            if not (isinstance(g, np.ndarray) and g.dtype == w.dtype
                    and g.shape == w.shape
                    and g.tobytes() == w.tobytes()):
                return False
        elif g != w:
            return False
    return True


# ------------------------------------------------------------- placement
def test_shard_of_stable_and_in_range():
    """Placement is sha256-derived, NOT hash() (process-randomized) —
    a restarted coordinator must re-derive the journal's placement."""
    for n in (1, 2, 4, 7):
        for name in ("a", "pattern-1", "ünïcode", ""):
            s = shard_of(name, n)
            assert 0 <= s < n
            assert s == shard_of(name, n)  # stable within and (by
            # construction: content hash) across processes
    assert shard_of("anything", 1) == 0


# ----------------------------------------------------------------- codec
def test_results_codec_byte_exact_and_extra_fields():
    bank = StubGalleryBank(image_size=SIZE)
    bank.register("a", np.arange(8, dtype=np.float32).reshape(2, 4))
    img = np.linspace(0, 1, SIZE * SIZE * 3, dtype=np.float32).reshape(
        SIZE, SIZE, 3
    )
    results = bank.search(img)
    results["down"] = unavailable_result()
    doc = json.loads(json.dumps(pack_results(results)))  # wire trip
    back = unpack_results(doc)
    assert set(back) == {"a", "down"}
    assert _dets_equal(back["a"], results["a"])
    assert back["down"]["degrade_steps"] == ["partition_unavailable"]
    assert back["down"]["boxes"].shape == (1, 0, 4)


# --------------------------------------------------------------- journal
def test_pattern_journal_wal_semantics(tmp_path):
    """Markers are atomic + digest-sealed; a fence raise aborts
    marker-less; the ``journal`` fault point refuses BEFORE disk; a
    tampered marker is skipped on recovery (never acknowledged)."""
    journal = PatternJournal(str(tmp_path))
    payload = {"b64": "AAAA", "dtype": "float32", "shape": [1]}
    journal.record("keep", 1, payload, 1)
    assert set(journal.load_all()) == {"keep"}
    rec = journal.load_all()["keep"]
    assert rec["schema"] == GALLERY_JOURNAL_SCHEMA
    assert rec["shard"] == 1 and rec["payload"]["b64"] == "AAAA"

    # fencing: a stale lease aborts the commit with NO marker
    def stale_fence():
        raise StaleLeaseError("epoch moved on")

    with pytest.raises(StaleLeaseError):
        journal.record("fenced", 0, payload, 1, fence=stale_fence)
    assert set(journal.load_all()) == {"keep"}

    # the journal fault point fires before anything touches disk
    faults.configure("journal:raise=OSError", seed=0)
    with pytest.raises(OSError):
        journal.record("refused", 0, payload, 1)
    faults.clear()
    assert set(journal.load_all()) == {"keep"}

    # a hand-edited marker fails its digest and is skipped
    journal.record("tampered", 0, payload, 1)
    path = journal._path("tampered")
    doc = json.load(open(path))
    doc["k_real"] = 99
    with open(path, "w") as f:
        json.dump(doc, f)
    assert set(journal.load_all()) == {"keep"}

    journal.invalidate("keep")
    journal.invalidate("keep")  # idempotent
    assert journal.load_all() == {}


# ------------------------------------------------------ in-process fleet
def test_fleet_replicates_fans_out_and_degrades(tmp_path):
    """The whole loop without process churn: two in-process workers
    lease two shards; registrations ack R=2 copies; the fan-out client
    is byte-identical to one StubGalleryBank; a stale-epoch gsearch is
    FENCED; a drained fleet degrades every pattern to the counted
    partition_unavailable label; a cold coordinator restart recovers
    the catalog from the journal."""
    reference = StubGalleryBank(image_size=SIZE)
    fleet = GalleryFleet(
        2, policy=_policy(), replicas=2,
        journal_dir=str(tmp_path / "journal"),
    )
    fleet.start()
    workers = []
    try:
        workers = [
            GalleryFleetWorker(
                fleet.address, f"w{i}",
                bank_factory=lambda shard: StubGalleryBank(SIZE),
            ).start()
            for i in range(2)
        ]
        assert _poll(lambda: all(
            fleet.holder_for(s) is not None for s in range(2)
        ))
        rng = np.random.default_rng(0)
        names = [f"pat{i}" for i in range(4)]
        for name in names:
            ex = rng.standard_normal((2, 4)).astype(np.float32)
            ack = fleet.register(name, ex)
            reference.register(name, ex)
            assert ack["ok"] and ack["journaled"]
            assert ack["copies"] == 2 and not ack["under_replicated"]

        client = fleet.client()
        img = rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)
        got = client.search(img)
        want = reference.search(img)
        assert set(got) == set(names)
        for name in names:
            assert "degrade_steps" not in got[name]
            assert _dets_equal(got[name], want[name])

        # fenced: a revoked epoch NEVER serves stale detections
        shard = 0
        wid, epoch, addr = fleet.holder_for(shard)
        from tmr_tpu.serve.fleet import pack_array

        reply = oneshot(addr, {
            "op": "gsearch", "shard": shard, "epoch": epoch + 1,
            "image": pack_array(img),
        }, timeout=10.0)
        assert reply["ok"] is False and reply["status"] == "fenced"

        # kill one worker (hard stop): its shards promote onto the
        # survivor, which already mirrors every pattern — zero loss
        victim = fleet.holder_for(0)[0]
        survivor = next(w for w in workers if w.worker_id != victim)
        next(w for w in workers if w.worker_id == victim).stop()

        def healed():
            holders = [fleet.holder_for(s) for s in range(2)]
            if not all(h and h[0] == survivor.worker_id
                       for h in holders):
                return False
            out = client.search(img)
            return all("degrade_steps" not in out[n] for n in names)

        assert _poll(healed)
        again = client.search(img)
        for name in names:
            assert _dets_equal(again[name], want[name])

        # full outage: every pattern degrades to the COUNTED label
        survivor.stop()
        assert _poll(lambda: all(
            fleet.holder_for(s) is None for s in range(2)
        ))
        dark = client.search(img)
        assert set(dark) == set(names)
        for name in names:
            assert dark[name]["degrade_steps"] == [
                "partition_unavailable"
            ]
        assert client.counters()["degraded_patterns"] >= len(names)
    finally:
        for w in workers:
            w.stop()
        fleet.close()

    # coordinator restart: the WAL is the catalog of record
    reborn = GalleryFleet(
        2, policy=_policy(), replicas=2,
        journal_dir=str(tmp_path / "journal"),
    )
    assert set(reborn.patterns()) == set(names)
    assert reborn.counters()["journal_recovered"] == len(names)
