"""Disaggregated backbone/match tiers (tmr_tpu/serve/feature_tier.py).

The load-bearing contracts:

- the generalized FeatureSinkServer accounting window resets on ANY
  successful round-trip (the PR 16 fix — the pre-fix server reset only
  on sync acks, so an online request/response link that never synced
  accumulated errors forever);
- remote features through the heads-only path match local execution
  (the StubFeaturePredictor carries each image's signature THROUGH its
  features, so equality is an end-to-end data-path check);
- a dead feature worker degrades the engine to counted LOCAL execution
  with zero dropped futures; a fenced (revoked-epoch) extract answers
  ``fenced``, never stale features; a stamp mismatch (different
  checkpoint) is refused client-side; a saturated client window fails
  fast instead of queueing.

Everything runs on loopback with numpy stubs — no XLA in the tier
tests themselves.
"""

import socket
import threading
import time

import numpy as np
import pytest

SIZE = 32
BOX = np.asarray([[0.2, 0.2, 0.4, 0.4]], np.float32)


def _img(seed):
    return np.random.default_rng(seed).standard_normal(
        (SIZE, SIZE, 3)
    ).astype(np.float32)


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def tier_worker():
    """One coordinator + one holding worker on loopback; yields
    (tier, worker, predictor)."""
    from tmr_tpu.serve.feature_tier import (
        FeatureTier,
        FeatureWorker,
        StubFeaturePredictor,
    )

    pred = StubFeaturePredictor()
    tier = FeatureTier([SIZE], host="127.0.0.1", port=0)
    tier.start()
    worker = FeatureWorker(tier.address, "w0", StubFeaturePredictor(),
                           data_host="127.0.0.1", data_port=0)
    worker.start()
    try:
        _wait(lambda: worker.held, msg="worker to acquire a partition")
        yield tier, worker, pred
    finally:
        worker.stop()
        tier.close()


# ------------------------------------------------------- sink window reset
def test_sink_window_resets_on_any_successful_roundtrip():
    """The satellite fix, wire level: an error followed by a successful
    NON-SYNC round-trip (here: evict) must not poison the next sync —
    pre-fix, only sync acks reset the window, so the stale error would
    fail a later clean attempt."""
    from tmr_tpu.parallel.leases import recv_line, send_line
    from tmr_tpu.serve.fleet import pack_array
    from tmr_tpu.serve.gallery import FeatureSinkServer

    sink = FeatureSinkServer(max_entries=8)
    host, port = sink.start()
    try:
        with socket.create_connection((host, port), timeout=5) as s:
            f = s.makefile("rb")
            send_line(s, {"op": "hello", "worker": "t"})
            assert recv_line(f)["ok"]
            send_line(s, {"op": "feature", "shard": "x", "name": "bad",
                          "array": {"b64": "!!!", "dtype": "float32",
                                    "shape": [1]}})
            send_line(s, {"op": "evict", "shard": "y"})
            assert recv_line(f)["ok"] is True  # successful round-trip
            # the window is CLEAN now: a fresh attempt on the same
            # connection syncs ok despite the historic error
            send_line(s, {"op": "feature", "shard": "x", "name": "good",
                          "array": pack_array(np.ones((2,), np.float32))})
            send_line(s, {"op": "sync", "shard": "x"})
            reply = recv_line(f)
            assert reply["ok"] is True, reply
            assert reply["errors"] == 0 and reply["features"] == 1
            send_line(s, {"op": "bye"})
    finally:
        sink.close()
    assert sink.counters()["errors"] == 1  # lifetime tally still counts


def test_sink_on_request_hook_acks_errors_and_unknown_ops():
    """The online generalization: on_request replies close the window
    like any ack, its exceptions become counted error replies, and ops
    nobody owns still get the unknown-op error."""
    from tmr_tpu.parallel.leases import recv_line, send_line
    from tmr_tpu.serve.gallery import FeatureSinkServer

    def hook(doc, state):
        if doc.get("op") == "ping":
            return {"op": "ping", "ok": True}
        if doc.get("op") == "boom":
            raise ValueError("kapow")
        return None

    sink = FeatureSinkServer(max_entries=8, on_request=hook)
    host, port = sink.start()
    try:
        with socket.create_connection((host, port), timeout=5) as s:
            f = s.makefile("rb")
            send_line(s, {"op": "feature", "shard": "x", "name": "bad",
                          "array": {"b64": "!!!", "dtype": "float32",
                                    "shape": [1]}})
            send_line(s, {"op": "ping"})
            assert recv_line(f)["ok"] is True
            send_line(s, {"op": "sync", "shard": "x"})
            assert recv_line(f)["ok"] is True  # ping reset the window
            send_line(s, {"op": "boom"})
            reply = recv_line(f)
            assert reply["ok"] is False and "kapow" in reply["error"]
            send_line(s, {"op": "nonsense"})
            reply = recv_line(f)
            assert reply["ok"] is False and "unknown op" in reply["error"]
    finally:
        sink.close()
    assert sink.counters()["errors"] == 2  # bad feature + boom


# --------------------------------------------------- disaggregated serving
def test_remote_features_match_local_execution(tier_worker):
    """End to end through the wire: an engine armed with a feature
    client routes its first sighting down the heads-only path on
    REMOTE features, and the result carries the image's signature —
    identical to a direct local call."""
    from tmr_tpu.serve import ServeEngine

    tier, worker, pred = tier_worker
    client = tier.client(predictor=pred)
    eng = ServeEngine(pred, batch=2, max_wait_ms=5.0, feature_cache=4,
                      exemplar_cache=0, feature_client=client)
    try:
        img = _img(1)
        out = eng.submit(img, BOX).result()
        local = pred(img[None], BOX[None])
        for k in ("boxes", "scores", "refs", "valid"):
            assert np.array_equal(out[k], np.asarray(local[k])), k
        oc = eng.overload_counters()
        assert oc.get("feature_tier.remote_frames", 0) == 1, oc
        assert worker.counters()["extracted"] == 1
        assert client.counters()["fetched"] == 1
        # the fetched features landed in the stamped feature cache
        assert eng.feature_cache.stats()["inserts"] == 1
    finally:
        eng.close()
        client.close()


def test_dead_worker_degrades_to_counted_local_fallback(tier_worker):
    """Kill the only feature worker mid-stream: subsequent frames must
    resolve through LOCAL execution (cold or fallback counted — never
    silent) with zero dropped futures."""
    from tmr_tpu.serve import ServeEngine

    tier, worker, pred = tier_worker
    client = tier.client(predictor=pred)
    eng = ServeEngine(pred, batch=2, max_wait_ms=5.0, feature_cache=4,
                      exemplar_cache=0, feature_client=client)
    try:
        out = eng.submit(_img(2), BOX).result()
        assert out["valid"].any()
        worker.stop()
        _wait(lambda: tier.holder_for(SIZE) is None,
              msg="holder to clear after worker exit")
        futs = [eng.submit(_img(10 + i), BOX) for i in range(3)]
        for i, fut in enumerate(futs):
            got = fut.result(timeout=30)
            local = pred(_img(10 + i)[None], BOX[None])
            assert np.array_equal(got["scores"],
                                  np.asarray(local["scores"]))
        oc = eng.overload_counters()
        counted = oc.get("feature_tier.cold_frames", 0) \
            + oc.get("feature_tier.fallback_frames", 0)
        assert counted >= 3, oc
    finally:
        eng.close()
        client.close()


def test_fenced_extract_never_serves_stale_features(tier_worker):
    """An extract carrying a revoked/unknown (partition, epoch) pair
    answers ``fenced`` — the worker's own hold is the fence, so a
    lease the coordinator moved can never produce stale features."""
    from tmr_tpu.serve.feature_tier import _ExtractLink
    from tmr_tpu.serve.fleet import pack_array

    tier, worker, pred = tier_worker
    resolved = tier.holder_for(SIZE)
    assert resolved is not None
    wid, epoch, index, addr = resolved
    link = _ExtractLink(addr, timeout_s=5.0)
    try:
        stale = link.call({"op": "extract", "partition": index,
                           "epoch": epoch + 7, "digest": "d",
                           "image": pack_array(_img(3))})
        assert stale["ok"] is False and stale["status"] == "fenced"
        assert worker.counters()["fenced"] == 1
        live = link.call({"op": "extract", "partition": index,
                          "epoch": epoch, "digest": "d",
                          "image": pack_array(_img(3))})
        assert live["ok"] is True
        assert tuple(live["stamp"]) == pred.feature_stamp()
    finally:
        link.close()


def test_client_refuses_stamp_mismatch(tier_worker):
    """A client whose engine runs a DIFFERENT checkpoint/formulation
    must refuse the worker's features (counted) — the wire-level half
    of the stamped feature-key contract."""
    from tmr_tpu.serve.feature_tier import StubFeaturePredictor

    tier, worker, pred = tier_worker

    class OtherCheckpoint(StubFeaturePredictor):
        def feature_stamp(self):
            return ("other-params", "stub-backbone")

    client = tier.client(predictor=OtherCheckpoint())
    try:
        assert client.fetch(_img(4), "d", SIZE) is None
        assert client.counters()["stamp_mismatches"] == 1
    finally:
        client.close()


def test_client_window_saturation_fails_fast(tier_worker):
    """Backpressure contract: a saturated in-flight window makes fetch
    return None immediately (counted) instead of queueing on the
    link — the engine's local fallback owns the frame."""
    tier, worker, pred = tier_worker
    client = tier.client(predictor=pred, window=1)
    try:
        assert client._window.acquire(blocking=False)  # saturate it
        t0 = time.monotonic()
        assert client.fetch(_img(5), "d", SIZE) is None
        assert time.monotonic() - t0 < 1.0  # fast, not a queue wait
        assert client.counters()["window_rejections"] == 1
        client._window.release()
        assert client.fetch(_img(5), "d", SIZE) is not None
    finally:
        client.close()


def test_client_counts_no_holder_when_tier_is_cold():
    """An empty tier (no worker ever joined) routes nothing: holds()
    is False and fetch counts no_holder."""
    from tmr_tpu.serve.feature_tier import (
        FeatureTier,
        StubFeaturePredictor,
    )

    tier = FeatureTier([SIZE], host="127.0.0.1", port=0)
    tier.start()
    client = tier.client(predictor=StubFeaturePredictor())
    try:
        assert client.holds(SIZE) is False
        assert client.fetch(_img(6), "d", SIZE) is None
        assert client.counters()["no_holder"] == 1
    finally:
        client.close()
        tier.close()


def test_worker_rebalance_after_kill_minus_nine():
    """The lease discipline under the tier: a worker that vanishes
    without bye (socket torn down, no clean handshake) loses its
    partition after TTL and a second worker inherits it at a HIGHER
    epoch — the fence the extract path checks."""
    from tmr_tpu.parallel.leases import LeasePolicy
    from tmr_tpu.serve.feature_tier import (
        FeatureTier,
        FeatureWorker,
        StubFeaturePredictor,
    )
    from tmr_tpu.serve.fleet import fleet_policy

    policy = fleet_policy(LeasePolicy.from_env(
        lease_ttl_s=0.4, hb_interval_s=0.1, check_interval_s=0.05,
    ))
    tier = FeatureTier([SIZE], host="127.0.0.1", port=0, policy=policy,
                       check_interval_s=0.05)
    tier.start()
    w1 = FeatureWorker(tier.address, "w1", StubFeaturePredictor(),
                       data_host="127.0.0.1", data_port=0)
    w1.start()
    try:
        _wait(lambda: w1.held, msg="w1 to hold")
        epoch1 = next(iter(w1.held.values()))
        # kill -9: freeze the beats and sever the control socket
        w1._stop_event.set()
        w1._sock.close()
        w2 = FeatureWorker(tier.address, "w2", StubFeaturePredictor(),
                           data_host="127.0.0.1", data_port=0)
        w2.start()
        try:
            _wait(lambda: w2.held, timeout=15.0,
                  msg="w2 to inherit the partition")
            resolved = tier.holder_for(SIZE)
            assert resolved is not None and resolved[0] == "w2"
            assert resolved[1] > epoch1  # fenced-off old epoch
        finally:
            w2.stop()
    finally:
        w1._sink.close()
        tier.close()
