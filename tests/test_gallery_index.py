"""The host-side half of the sublinear gallery prefilter:
tmr_tpu/serve/gallery_index.py's SketchIndex (deterministic seed-pinned
clustering, exact-extrema probe election, churn-triggered rebuilds,
bounded stamp journal, immediate eviction) and the coordinator's
streamed bulk-ingest path (journal-first cataloging, deferred
idempotent flush, cold-restart recovery) — all without a device or a
worker process. The device-scoring half (GalleryBank's probe/candidate
calls and the off-switch bitwise contract) lives in test_gallery.py;
the end-to-end fleet story is scripts/serve_chaos_probe.py
--patterns-per-shard."""

import numpy as np

from tmr_tpu.serve.gallery_fleet import GalleryFleet, bulk_register
from tmr_tpu.serve.gallery_index import (
    SKETCH_DIMS,
    SketchIndex,
    entry_sketch,
)
from tmr_tpu.parallel.leases import LeasePolicy


def _vec(i, n=64):
    """A deterministic sketch-like vector: three well-separated blobs
    so the clustering has real structure to find."""
    rng = np.random.default_rng(1000 + i)
    center = np.asarray([0.2, 0.2, 0.5, 0.8][i % 4] * np.ones(4))
    return np.concatenate(
        [center + rng.normal(0, 0.03, 4), rng.normal(0, 0.01, 4)]
    ).astype(np.float32)


def _fill(idx, names):
    for i, nm in enumerate(names):
        idx.add(nm, _vec(i))


def _probe_state(idx):
    # member-list ORDER is insertion order and does not affect queries
    # (candidates re-sort by registry position); the determinism
    # contract is over the sets + the elected probes
    snap = idx.snapshot()
    return (snap["medoids"], snap["probes"],
            [sorted(ms) for ms in snap["members"]])


# ------------------------------------------------------------ entry_sketch
def test_entry_sketch_uses_only_real_rows():
    ex = np.asarray([[0.1, 0.1, 0.3, 0.4],
                     [0.5, 0.5, 0.9, 0.9],
                     [0.0, 0.0, 1.0, 1.0]], np.float32)
    v2 = entry_sketch(ex, 2)
    assert v2.shape == (SKETCH_DIMS,) and v2.dtype == np.float32
    # pad rows past k_real must not move the vector — the bank hands
    # the index its PADDED exemplar array
    padded = np.concatenate([ex[:2], np.tile(ex[1:2], (5, 1))], axis=0)
    assert entry_sketch(padded, 2).tobytes() == v2.tobytes()
    assert entry_sketch(ex, 3).tobytes() != v2.tobytes()


# ------------------------------------------------------------- determinism
def test_rebuild_deterministic_across_insertion_order():
    """Same entry set in => byte-identical clustering out, regardless
    of registration order — the contract that lets a journal-rebuilt
    replica elect the same candidates as the primary it replaced."""
    names = [f"p{i:03d}" for i in range(48)]
    a, b = SketchIndex(), SketchIndex()
    _fill(a, names)
    for i in reversed(range(len(names))):  # reverse order into b
        b.add(names[i], _vec(i))
    sa, sb = a.rebuild(), b.rebuild()
    assert sa["digest"] == sb["digest"]
    assert sa["entries"] == 48 and sa["centroids"] == sb["centroids"]
    snap_a, snap_b = a.snapshot(), b.snapshot()
    assert snap_a["medoids"] == snap_b["medoids"]
    assert snap_a["probes"] == snap_b["probes"]
    assert snap_a["members"] == snap_b["members"]


def test_incremental_maintenance_is_order_independent():
    """Probes are EXACT extrema over the member set, so incremental
    add/remove after a build lands in the same state no matter the
    order — and removing + re-adding an entry is a no-op."""
    names = [f"p{i:03d}" for i in range(32)]
    a, b = SketchIndex(), SketchIndex()
    _fill(a, names)
    _fill(b, names)
    a.rebuild()
    b.rebuild()
    extra = [(f"x{i}", _vec(100 + i)) for i in range(6)]
    for nm, v in extra:
        a.add(nm, v)
    for nm, v in reversed(extra):
        b.add(nm, v)
    assert _probe_state(a) == _probe_state(b)
    # churn round trip: drop an elected probe and bring it back
    victim = a.snapshot()["probes"][0][0]
    vvec = _vec(names.index(victim))
    assert a.remove(victim)
    assert victim not in [p for pl in a.snapshot()["probes"] for p in pl]
    a.add(victim, vvec)
    assert _probe_state(a) == _probe_state(b)


def test_removed_entries_leave_snapshot_immediately():
    """No rebuild needed: eviction drops the name from the posting
    lists (and re-elects its cluster's probes) under the same lock, so
    a stale-but-built index can never hand an evicted name back."""
    names = [f"p{i:03d}" for i in range(20)]
    idx = SketchIndex()
    _fill(idx, names)
    idx.rebuild()
    for nm in names[:10]:
        assert idx.remove(nm)
    snap = idx.snapshot()
    gone = set(names[:10])
    assert not gone & {m for ms in snap["members"] for m in ms}
    assert not gone & {p for pl in snap["probes"] for p in pl}
    assert not idx.remove("p000")  # second remove: no longer indexed
    assert len(idx) == 10


# ------------------------------------------------------------------ churn
def test_needs_rebuild_tracks_churn_threshold():
    idx = SketchIndex(rebuild_frac=0.25)
    assert not idx.needs_rebuild()  # empty: nothing to build
    idx.add("a", _vec(0))
    assert idx.needs_rebuild()  # never built
    names = [f"p{i:03d}" for i in range(40)]
    _fill(idx, names)
    idx.rebuild()
    assert not idx.needs_rebuild()
    # churn accrues on add AND remove; the threshold is a strict >
    churn_allowance = int(0.25 * (len(names) + 1))
    for i in range(churn_allowance):
        idx.add(f"n{i}", _vec(200 + i))
    assert not idx.needs_rebuild()
    idx.remove("n0")
    assert idx.needs_rebuild()
    idx.rebuild(reason="test")
    assert not idx.needs_rebuild()
    assert idx.stamps()[-1]["reason"] == "test"


def test_stamps_journal_bounded_and_digest_pins_entry_set():
    idx = SketchIndex(max_stamps=4)
    _fill(idx, [f"p{i}" for i in range(9)])
    digests = set()
    for r in range(7):
        stamp = idx.rebuild(reason=f"r{r}")
        assert stamp["entries"] == 9 and stamp["centroids"] == 3
        assert stamp["wall_s"] >= 0.0
        digests.add(stamp["digest"])
    assert len(digests) == 1  # same entry set => same digest
    log = idx.stamps()
    assert len(log) == 4  # bounded, oldest dropped
    assert [s["reason"] for s in log] == ["r3", "r4", "r5", "r6"]
    assert log[-1]["rebuild"] == 7
    stats = idx.stats()
    assert stats["rebuilds"] == 7 and stats["built"] is True
    assert stats["last_rebuild"]["digest"] == log[-1]["digest"]
    # the digest moves when the entry set does
    idx.remove("p0")
    assert idx.rebuild()["digest"] not in digests


def test_probes_are_medoid_plus_anti_medoid():
    """One tight hand-built cluster: the medoid is the member nearest
    the centroid, the anti-medoid the farthest, ties by name."""
    idx = SketchIndex(min_centroids=1)
    base = np.zeros(SKETCH_DIMS, np.float32)
    idx.add("near", base + 0.01)
    idx.add("mid", base + 0.05)
    idx.add("far", base + 0.20)
    idx.rebuild()
    snap = idx.snapshot()
    assert snap["centroids"] >= 1
    flat = [p for pl in snap["probes"] for p in pl]
    assert "near" in flat and "far" in flat
    for medoid, probes in zip(snap["medoids"], snap["probes"]):
        assert probes[0] == medoid
        assert 1 <= len(probes) <= 2


# ------------------------------------------------------------- bulk ingest
def _patterns(n):
    out = []
    for i in range(n):
        rng = np.random.default_rng(i)
        out.append((f"blk{i:04d}",
                    rng.random((1 + i % 3, 4)).astype(np.float32)))
    return out


def test_bulk_register_streams_journal_first_and_flush_is_deferred(
        tmp_path):
    """The streamed path lands every pattern in the journal + catalog
    off ONE pipelined connection; with no live workers the deferred
    flush counts every pattern under-replicated (never an error), and
    a cold coordinator over the same journal recovers them all."""
    fleet = GalleryFleet(
        2, replicas=2, journal_dir=str(tmp_path / "journal"),
        policy=LeasePolicy(lease_ttl_s=1.0, hb_interval_s=0.2,
                           check_interval_s=0.05),
    )
    try:
        pats = _patterns(10)
        res = bulk_register(fleet.bulk_sink(), pats, batch="t",
                            flush=False)
        assert res["ok"] is True
        assert res["streamed"] == res["synced"] == 10
        assert res["errors"] == 0 and "flush" not in res
        assert set(fleet.patterns()) == {nm for nm, _ in pats}
        counters = fleet.counters()
        assert counters["bulk_registered"] == 10
        assert counters["journal_recovered"] == 0
        # no workers: flush distributes nothing, counts everything
        flush = fleet.flush_pending()
        assert flush == {"patterns": 10, "copies": 0,
                         "under_replicated": 10}
        # idempotent: still copy-less, so the same set is retried
        assert fleet.flush_pending()["patterns"] == 10
        assert fleet.counters()["bulk_flushes"] == 2
        # the flush op also rides the sink connection (one round trip)
        res2 = bulk_register(
            fleet.bulk_sink(),
            [("solo", np.ones((2, 4), np.float32))],
            batch="t2", flush=True,
        )
        assert res2["ok"] is True
        assert res2["flush"]["ok"] is True
        assert res2["flush"]["copies"] == 0
    finally:
        fleet.close()
    # cold restart: the WAL is the catalog of record
    reborn = GalleryFleet(2, replicas=2,
                          journal_dir=str(tmp_path / "journal"))
    try:
        assert set(reborn.patterns()) >= {nm for nm, _ in pats}
        assert reborn.counters()["journal_recovered"] == 11
        # recovered payloads round-trip byte-exact
        entry = reborn._patterns["blk0003"]
        want = dict(pats)["blk0003"]
        assert entry["k_real"] == want.shape[0]
        assert entry["digest"] == fleet._patterns["blk0003"]["digest"]
    finally:
        reborn.close()


def test_bulk_sink_reuses_one_server():
    fleet = GalleryFleet(1, journal_dir=None)
    try:
        assert fleet.bulk_sink() == fleet.bulk_sink()
        assert fleet._bulk is not None
    finally:
        fleet.close()
    assert fleet._bulk is None  # close() tore the sink down
