"""scripts/obs_watch.py: the flight_report/v1 contract.

One lean subprocess run at the obs_probe CPU smoke geometry (identical
program shapes, so the persistent XLA compile cache is shared between
the two probes and the tier-1 time budget pays the compile once):
asserts the ISSUE acceptance checks — finite per-program MFU with the
analytic-vs-cost_analysis FLOPs envelope, exactly-once anomaly firings
for the injected recompile storm and queue burst, a validating
ServeEngine.health() + heartbeat JSONL round-trip, and <1% disabled-mode
overhead. The watchdog error-record path is slow-marked (subprocess
compile time, no new coverage beyond the guard contract).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _probe_env(**extra):
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS", "TMR_FLIGHT",
                     "TMR_TRACE")
    }
    env.update(
        JAX_PLATFORMS="cpu",
        TMR_BENCH_TINY="1",
        TMR_BENCH_SIZE="128",
        **extra,
    )
    return env


def test_obs_watch_tiny_smoke_meets_acceptance_checks(tmp_path):
    """The acceptance proof, end to end on CPU: one JSON line, valid
    flight_report/v1, finite per-program MFU whose analytic FLOPs agree
    with cost_analysis() within the 1.17x envelope, exactly-once
    anomaly firings, health + heartbeat round-trip, bounded disabled
    overhead."""
    out_file = tmp_path / "flight_report.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_watch.py"),
         "--tiny", "--out", str(out_file)],
        env=_probe_env(), capture_output=True, text=True, timeout=560,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    doc = json.loads(lines[0])

    from tmr_tpu.diagnostics import validate_flight_report

    assert validate_flight_report(doc) == []
    assert "validator_problems" not in doc
    checks = doc["checks"]
    for name in ("mfu_valid", "mfu_finite", "flops_envelope_ok",
                 "health_valid", "heartbeat_roundtrip", "ring_recorded",
                 "calm_quiet", "storm_exact", "queue_exact",
                 "overhead_ok"):
        assert checks[name] is True, (name, checks)
    assert checks["flops_envelope_max_ratio"] <= 1.17
    assert doc["overhead"]["overhead_disabled_pct"] < 1.0
    # attribution: the serve workload's program appears with measured
    # (non-warmup) calls, a cost source, and a roofline verdict
    progs = doc["mfu"]["programs"]
    assert any(p["kind"] == "single" and p["calls"] >= 1 for p in progs)
    assert all(p["cost_source"] in ("xla", "analytic") for p in progs)
    # the anomaly records carry structured causes (kind + evidence)
    storm = doc["anomalies"]["recompile_storm"]
    assert [a["anomaly"] for a in storm] == ["recompile_storm"]
    assert storm[0]["evidence"]["key_change_events"] >= 3
    queue = doc["anomalies"]["queue_saturation"]
    assert [a["anomaly"] for a in queue] == ["queue_saturation"]
    # health doc: queue/cache/compile sections populated by a live engine
    health = doc["health"]
    assert health["counters"]["completed"] == doc["config"]["requests"]
    assert health["anomalies"] == []  # a healthy tiny run is quiet
    # the flight ring saw every request
    assert doc["ring"]["serve_requests"] >= doc["config"]["requests"]
    # --out wrote the same document; the heartbeat JSONL round-trips
    assert json.loads(out_file.read_text())["checks"] == checks
    hb_path = doc["heartbeat"]["path"]
    from tmr_tpu.diagnostics import validate_health_report

    hb_docs = [json.loads(l) for l in
               open(hb_path).read().splitlines() if l.strip()]
    assert len(hb_docs) >= 2
    assert all(validate_health_report(d) == [] for d in hb_docs)
    # progress goes to stderr, never stdout
    assert "[obs_watch]" in out.stderr


@pytest.mark.slow
def test_obs_watch_watchdog_emits_error_record(tmp_path):
    """A wedge yields the contractual one-line error record — still a
    valid flight_report/v1 document (the bench_guard pattern)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_watch.py"),
         "--tiny"],
        env=_probe_env(
            TMR_BENCH_ALARM="1",
            TMR_COMPILATION_CACHE=str(tmp_path / "xla-cache"),
        ),
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 2
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "watchdog" in rec["error"]

    from tmr_tpu.diagnostics import validate_flight_report

    assert validate_flight_report(rec) == []
