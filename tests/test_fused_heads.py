"""Fused decoder-head formulation (ops/fused_heads.py, TMR_DECODER_IMPL):
conv-as-matmul parity, the oracle gate's verdicts and recorded refusal
causes, and the MatchingNet trace-time dispatch — same param tree, same
outputs, knob-selected formulation."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from tmr_tpu.diagnostics import (
    FormulationFallbackWarning,
    drain_gate_refusals,
)
from tmr_tpu.ops import fused_heads as fh


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for k in ("TMR_DECODER_IMPL", "TMR_QUANT", "TMR_NO_FUSED_HEADS"):
        monkeypatch.delenv(k, raising=False)
    fh._OK_CACHE.clear()
    drain_gate_refusals()
    yield
    fh._OK_CACHE.clear()
    drain_gate_refusals()


@pytest.mark.parametrize("k", [1, 3, 5])
def test_conv_mm_matches_lax_conv(k):
    """The k^2-tap matmul formulation IS a SAME conv: parity against
    lax.conv_general_dilated at f32 (tight — identical math, different
    association only)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 9, 11, 8)), jnp.float32)
    kern = jnp.asarray(rng.standard_normal((k, k, 8, 16)) * 0.1,
                       jnp.float32)
    bias = jnp.asarray(rng.standard_normal((16,)), jnp.float32)
    got = fh.conv_mm(x, kern, bias, dtype=jnp.float32)
    want = lax.conv_general_dilated(
        x, kern, window_strides=(1, 1),
        padding=[(k // 2, k // 2)] * 2,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=lax.Precision.HIGHEST,
    ) + bias
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("dtype_name,layers", [
    ("float32", 1), ("float32", 2), ("bfloat16", 1),
])
def test_oracle_gate_admits_small_geometries(dtype_name, layers):
    """fused_heads_ok pins the fused tail against the real flax module
    stack (Decoder + ObjectnessHead + BboxesHead) at the geometry — the
    f32 tier must pass tightly, the bf16 tier inside its rounding bound."""
    assert fh.fused_heads_ok(8, 8, 16, 16, num_layers=layers,
                             kernel_size=3, dtype_name=dtype_name)
    assert drain_gate_refusals() == []


def test_oracle_verdict_cached_per_geometry():
    assert fh.fused_heads_ok(8, 8, 16, 16, dtype_name="float32")
    key_count = len(fh._OK_CACHE)
    assert fh.fused_heads_ok(8, 8, 16, 16, dtype_name="float32")
    assert len(fh._OK_CACHE) == key_count  # second call was a cache hit


def test_kill_switch_refuses_with_recorded_cause(monkeypatch):
    monkeypatch.setenv("TMR_NO_FUSED_HEADS", "1")
    assert not fh.fused_heads_ok(8, 8, 16, 16)
    causes = drain_gate_refusals()
    assert causes and causes[0]["gate"] == "fused_heads_ok"
    assert causes[0]["cause"] == "kill-switch"
    assert causes[0]["config"]["H"] == 8


def test_decoder_impl_validates_knob(monkeypatch):
    monkeypatch.setenv("TMR_DECODER_IMPL", "nope")
    with pytest.raises(ValueError, match="TMR_DECODER_IMPL"):
        fh.decoder_impl(8, 8, 16, 16, 1, 3, "float32")


def test_decoder_impl_auto_defaults_to_xla():
    assert fh.decoder_impl(8, 8, 16, 16, 1, 3, "float32") == ("xla", False)


def test_decoder_impl_fused_elects_when_gate_passes(monkeypatch):
    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    assert fh.decoder_impl(8, 8, 16, 16, 1, 3, "float32") == ("fused",
                                                              False)


def test_decoder_impl_refusal_warns_and_falls_back(monkeypatch):
    """An explicitly requested fused formulation whose gate refuses must
    fall back to xla WITH the FormulationFallbackWarning contract (so
    autotune sweeps annotate the mislabeled timing) — never silently."""
    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    monkeypatch.setattr(fh, "fused_heads_ok", lambda *a, **k: False)
    with pytest.warns(FormulationFallbackWarning) as rec:
        impl, quant = fh.decoder_impl(8, 8, 16, 16, 1, 3, "float32")
    assert (impl, quant) == ("xla", False)
    assert rec[0].message.env_var == "TMR_DECODER_IMPL"


def test_quant_rides_fused_only(monkeypatch):
    """TMR_QUANT=int8 under an xla decoder impl warns and runs exact —
    the int8 weights exist only in the fused formulation."""
    monkeypatch.setenv("TMR_QUANT", "int8")
    with pytest.warns(FormulationFallbackWarning) as rec:
        impl, quant = fh.decoder_impl(8, 8, 16, 16, 1, 3, "float32")
    assert (impl, quant) == ("xla", False)
    assert rec[0].message.env_var == "TMR_QUANT"


def test_quant_elects_under_fused_when_tiers_pass(monkeypatch):
    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    monkeypatch.setenv("TMR_QUANT", "int8")
    impl, quant = fh.decoder_impl(8, 8, 16, 16, 1, 3, "float32")
    assert impl == "fused"
    assert quant  # small synthetic geometry: both tiers pass


# --------------------------------------------------- MatchingNet dispatch
def _tiny_model(**over):
    from tmr_tpu.models.matching_net import MatchingNet
    from tmr_tpu.models.vit import SamViT

    kwargs = dict(
        backbone=SamViT(embed_dim=32, depth=2, num_heads=2,
                        global_attn_indexes=(1,), patch_size=8,
                        window_size=3, out_chans=16,
                        pretrain_img_size=64),
        emb_dim=24,
        fusion=True,
        feature_upsample=True,
        template_capacity=9,
    )
    kwargs.update(over)
    return MatchingNet(**kwargs)


def _data(b=2, s=64):
    rng = np.random.default_rng(0)
    image = rng.standard_normal((b, s, s, 3)).astype(np.float32)
    exemplars = np.tile(np.array([[0.2, 0.2, 0.4, 0.45]], np.float32),
                        (b, 1))[:, None, :]
    return jnp.array(image), jnp.array(exemplars)


@pytest.mark.slow
def test_matching_net_fused_param_tree_and_outputs_match(monkeypatch):
    """The tentpole contract: TMR_DECODER_IMPL=fused consumes the SAME
    flax param tree (checkpoints never fork) and reproduces the module
    stack's outputs at the model geometry."""
    model = _tiny_model()
    image, exemplars = _data()

    params_xla = model.init(jax.random.key(0), image, exemplars)["params"]
    out_xla = jax.jit(
        lambda p, i, e: model.apply({"params": p}, i, e)
    )(params_xla, image, exemplars)

    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    params_fused = model.init(jax.random.key(0), image, exemplars)["params"]
    # identical tree: same paths, same shapes, same initializer draws
    assert jax.tree_util.tree_structure(params_xla) == \
        jax.tree_util.tree_structure(params_fused)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params_xla, params_fused,
    )
    out_fused = jax.jit(
        lambda p, i, e: model.apply({"params": p}, i, e)
    )(params_xla, image, exemplars)

    for key in ("objectness", "regressions", "f_tm"):
        a = np.asarray(out_xla[key][0], np.float32)
        b = np.asarray(out_fused[key][0], np.float32)
        scale = max(np.abs(a).max(), 1e-6)
        assert np.abs(a - b).max() / scale < 5e-4, key


@pytest.mark.slow
def test_production_geometry_oracle_pin():
    """Acceptance criterion: the fused path is oracle-pinned at the
    production 128^2 x 1024 geometry (emb_dim 512, fusion-doubled, the
    2x-upsampled grid) — the exact shapes the bench program traces."""
    assert fh.fused_heads_ok(128, 128, 1024, 1024, num_layers=1,
                             kernel_size=3, dtype_name="bfloat16")
    assert drain_gate_refusals() == []
