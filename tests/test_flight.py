"""Flight recorder layer (tmr_tpu/obs/devtime.py + flight.py): device-
time attribution, MFU/roofline accounting, anomaly detection, health
heartbeat, and the bench-history trend reader.

The load-bearing contract mirrors PR 4's span pin: with TMR_FLIGHT=0
(the default) an instrumented program call costs one module-global bool
check. The detector tests drive every anomaly kind deterministically
with synthetic snapshots — no engine, no compiles — so the whole file
stays lean under the tier-1 time budget.
"""

import json
import time

import numpy as np
import pytest

from tmr_tpu.diagnostics import (
    ANOMALY_KINDS,
    validate_bench_trend,
    validate_flight_report,
    validate_health_report,
    validate_mfu_report,
)
from tmr_tpu.obs import devtime, flight


@pytest.fixture(scope="module")
def pred64():
    """One tiny Predictor (64² keeps the jitted init to seconds on CPU;
    the health-window test never runs an inference program)."""
    from tmr_tpu.config import preset
    from tmr_tpu.inference import Predictor

    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=64,
                 compute_dtype="float32", batch_size=1)
    pred = Predictor(cfg)
    pred.init_params(seed=0, image_size=64)
    return pred


@pytest.fixture(autouse=True)
def _flight_off_after():
    """Every test leaves the flight recorder disabled and its tables
    drained — the obs-suite hygiene protocol."""
    yield
    flight.configure(enabled=False)
    devtime.reset()
    flight.get_recorder().clear()


# ------------------------------------------------------------ validators


def _valid_mfu():
    return {
        "schema": "mfu_report/v1",
        "platform": {"backend": "cpu", "device_kind": "cpu",
                     "peak_tflops": 0.5, "peak_gbps": 50.0,
                     "peak_source": "nominal"},
        "programs": [{
            "kind": "single", "key": "(9,)", "bucket": {"capacity": 9},
            "calls": 2, "warmup_calls": 1, "dispatch_s": 0.01,
            "device_s": 1.0, "wall_s": 1.01, "cost_source": "xla",
            "mfu": 0.1, "bound": "compute",
        }],
        "totals": {"device_s": 1.0, "flops": 1e10,
                   "achieved_tflops": 0.01, "mfu": 0.02},
    }


def _valid_health():
    return {
        "schema": "health_report/v1",
        "ts": time.time(), "uptime_s": 1.0, "closed": False,
        "inflight": 0,
        "queues": {"pending": 0, "per_bucket": {}},
        "devices": ["cpu:0"], "per_device_batches": {},
        "caches": {
            "result": {"hits": 0, "misses": 0, "evictions": 0},
            "feature": {"hits": 0, "misses": 0, "evictions": 0},
        },
        "counters": {"submitted": 1},
        "compile": {"total": 0, "cold": 0, "key_change": 0},
        "anomalies": [],
    }


def test_validate_mfu_report_accepts_valid_and_rejects_broken():
    assert validate_mfu_report(_valid_mfu()) == []
    bad = _valid_mfu()
    bad["programs"][0]["bound"] = "sideways"
    assert any("bound" in p for p in validate_mfu_report(bad))
    bad = _valid_mfu()
    bad["platform"]["peak_tflops"] = 0
    assert any("peak_tflops" in p for p in validate_mfu_report(bad))
    bad = _valid_mfu()
    del bad["totals"]
    assert any("totals" in p for p in validate_mfu_report(bad))


def test_validate_health_report_accepts_valid_and_rejects_broken():
    doc = _valid_health()
    assert validate_health_report(doc) == []
    doc["anomalies"] = [{"anomaly": "recompile_storm",
                         "message": "m", "evidence": {}}]
    assert validate_health_report(doc) == []
    doc["anomalies"] = [{"anomaly": "weird", "message": "m",
                         "evidence": {}}]
    assert any("anomal" in p for p in validate_health_report(doc))
    doc = _valid_health()
    del doc["queues"]
    assert any("queues" in p for p in validate_health_report(doc))


def test_validate_flight_report_error_record_is_valid():
    assert validate_flight_report(
        {"schema": "flight_report/v1", "error": "watchdog: ..."}
    ) == []
    assert validate_flight_report({"schema": "bogus"}) != []


def test_serve_and_map_reports_validate_mfu_attachment():
    from tmr_tpu.diagnostics import validate_map_report

    doc = {
        "schema": "map_report/v1", "shards": [], "quarantined": [],
        "resumed": [],
        "totals": {k: 0 for k in (
            "shards", "ok", "quarantined", "resumed", "images",
            "skipped_images", "nonfinite_images", "retries")},
        "mfu": {"schema": "wrong"},
    }
    assert any(p.startswith("mfu:") for p in validate_map_report(doc))
    doc["mfu"] = _valid_mfu()
    assert not any(p.startswith("mfu:") for p in validate_map_report(doc))


# -------------------------------------------------------------- recorder


def test_flight_recorder_ring_bounds_and_counts_drops():
    rec = flight.FlightRecorder(capacity=16)
    for i in range(40):
        rec.record("probe", i=i)
    snap = rec.snapshot()
    assert len(snap) == 16
    assert snap[-1]["i"] == 39 and snap[0]["i"] == 24  # oldest rolled off
    assert rec.dropped() == 24
    rec.clear()
    assert rec.snapshot() == [] and rec.dropped() == 0


def test_flight_record_is_noop_when_disabled():
    flight.configure(enabled=False)
    flight.get_recorder().clear()
    assert flight.record("probe") is None
    assert flight.get_recorder().snapshot() == []
    flight.configure(enabled=True)
    assert flight.record("probe", x=1)["x"] == 1
    assert len(flight.get_recorder().snapshot()) == 1


# -------------------------------------------------------------- detector


def test_health_watch_recompile_storm_fires_exactly_at_threshold():
    watch = flight.HealthWatch(recompile_storm_threshold=3)
    below = [{"cause": "key-change", "kind": "single", "wall_s": 1.0}] * 2
    assert watch.observe({}, compile_events=below) == []
    at = [{"cause": "key-change", "kind": "single", "wall_s": 1.0}] * 3
    fired = watch.observe({}, compile_events=at)
    assert [a["anomaly"] for a in fired] == ["recompile_storm"]
    assert fired[0]["evidence"]["key_change_events"] == 3
    # cold events are warmup, never a storm
    cold = [{"cause": "cold", "kind": "single", "wall_s": 1.0}] * 10
    assert watch.observe({}, compile_events=cold) == []


def test_health_watch_queue_saturation():
    watch = flight.HealthWatch(queue_depth_threshold=8)
    assert watch.observe({}, pending=7) == []
    fired = watch.observe({}, pending=8)
    assert [a["anomaly"] for a in fired] == ["queue_saturation"]
    assert fired[0]["evidence"]["pending"] == 8


def test_health_watch_latency_regression_vs_rolling_baseline():
    from tmr_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    hist = reg.histogram("serve.request_latency_s")
    watch = flight.HealthWatch(p99_factor=3.0, min_window_requests=20)
    for _ in range(30):
        hist.observe(0.010)
    assert watch.observe(reg.snapshot()) == []  # first window: baseline
    for _ in range(30):
        hist.observe(0.010)
    assert watch.observe(reg.snapshot()) == []  # steady: no fire
    for _ in range(30):
        hist.observe(0.500)  # 50x the baseline window
    fired = watch.observe(reg.snapshot())
    assert [a["anomaly"] for a in fired] == ["latency_regression"]
    ev = fired[0]["evidence"]
    assert ev["p99_s"] > 3.0 * ev["baseline_s"]
    # a SUSTAINED regression keeps firing: the regressed window must
    # not poison its own rolling baseline and silence the detector
    for _ in range(30):
        hist.observe(0.500)
    still = watch.observe(reg.snapshot())
    assert [a["anomaly"] for a in still] == ["latency_regression"]


def test_health_watch_cache_hit_collapse():
    watch = flight.HealthWatch(hit_rate_drop=0.5, min_window_lookups=20)
    c1 = {"counters": {"serve.cache.result.hits": 90,
                       "serve.cache.result.misses": 10}}
    assert watch.observe(c1) == []  # baseline window (rate 0.9)
    c2 = {"counters": {"serve.cache.result.hits": 95,
                       "serve.cache.result.misses": 105}}
    fired = watch.observe(c2)  # window rate 5/100 = 0.05
    assert [a["anomaly"] for a in fired] == ["cache_hit_collapse"]
    assert fired[0]["evidence"]["hit_rate"] < 0.1


def test_health_watch_mfu_drop():
    watch = flight.HealthWatch(mfu_drop=0.5)
    watch.observe({}, mfu_totals={"flops": 0.0, "device_s": 0.0})
    assert watch.observe(
        {}, mfu_totals={"flops": 1e12, "device_s": 1.0}
    ) == []  # baseline window: 1 TFLOP/s
    fired = watch.observe(
        {}, mfu_totals={"flops": 1.1e12, "device_s": 2.0}
    )  # window: 0.1 TFLOP/s
    assert [a["anomaly"] for a in fired] == ["mfu_drop"]
    assert watch.recent()[-1]["anomaly"] == "mfu_drop"
    # sustained drop keeps firing (no baseline self-poisoning)
    still = watch.observe(
        {}, mfu_totals={"flops": 1.2e12, "device_s": 3.0}
    )
    assert [a["anomaly"] for a in still] == ["mfu_drop"]


def test_anomaly_records_are_gate_refused_style():
    watch = flight.HealthWatch(queue_depth_threshold=1)
    rec = watch.observe({}, pending=5)[0]
    assert rec["anomaly"] in ANOMALY_KINDS
    assert rec["message"] and isinstance(rec["evidence"], dict)
    from tmr_tpu.diagnostics import validate_anomaly

    assert validate_anomaly(rec) == []


# ------------------------------------------------------------- heartbeat


def test_heartbeat_writes_jsonl_and_final_beat(tmp_path):
    path = tmp_path / "health.jsonl"
    beats = {"n": 0}

    def emit():
        beats["n"] += 1
        return {"beat": beats["n"]}

    hb = flight.Heartbeat(emit, str(path), interval_s=30.0)
    assert hb.beats == 1  # first beat lands synchronously
    hb.stop()
    docs = [json.loads(line) for line in path.read_text().splitlines()]
    assert [d["beat"] for d in docs] == [1, 2]  # start + final beat
    assert hb.errors == 0
    hb.stop()  # idempotent


def test_heartbeat_write_failure_counts_never_raises(tmp_path):
    hb = flight.Heartbeat(lambda: {}, str(tmp_path / "no" / "dir.jsonl"),
                          interval_s=30.0)
    hb.stop()
    assert hb.errors >= 1 and hb.beats == 0


# ----------------------------------------------------- devtime wrapper


def test_track_devtime_disabled_is_passthrough_and_cheap():
    flight.configure(enabled=False)
    calls = []
    wrapped = devtime.track_devtime(lambda x: calls.append(x) or x,
                                    "probe", ("k",))
    assert wrapped(3) == 3 and calls == [3]
    assert devtime.mfu_report()["programs"] == []  # nothing recorded
    n = 20000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            wrapped(0)
        best = min(best, (time.perf_counter() - t0) / n)
    # the whole-layer disabled cost contract (the PR 4 span pin shape)
    assert best * 1e9 < 2500, f"disabled flight cost {best * 1e9:.0f} ns"


def test_track_devtime_attributes_and_reports_mfu():
    import jax
    import jax.numpy as jnp

    flight.configure(enabled=True)
    devtime.reset()
    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    wrapped = devtime.track_devtime(fn, "probe_unit", ("k", 1),
                                    bucket={"capacity": 9})
    x = jnp.ones((64, 64), jnp.float32)
    for _ in range(3):
        np.asarray(wrapped(x))
    doc = devtime.mfu_report()
    assert validate_mfu_report(doc) == []
    (prog,) = doc["programs"]
    assert prog["kind"] == "probe_unit"
    assert prog["warmup_calls"] == 1 and prog["calls"] == 2
    assert prog["cost_source"] == "xla"
    assert prog["flops_per_call"] > 0
    assert prog["mfu"] is not None and np.isfinite(prog["mfu"])
    assert prog["bound"] in ("compute", "memory", "unknown")
    assert doc["totals"]["device_s"] > 0
    devtime.reset()
    assert devtime.mfu_report()["programs"] == []


def test_devtime_totals_resolves_costs_without_mfu_report():
    """The heartbeat path calls totals() (via health()) and never
    mfu_report() — pending cost records must resolve there too, or the
    mfu_drop detector is permanently blind in production wiring."""
    import jax
    import jax.numpy as jnp

    flight.configure(enabled=True)
    devtime.reset()
    wrapped = devtime.track_devtime(jax.jit(lambda x: x + 1.0),
                                    "probe_totals", ("k",))
    x = jnp.ones((32, 32), jnp.float32)
    for _ in range(2):
        np.asarray(wrapped(x))
    totals = devtime.totals()  # no mfu_report() call before this
    assert totals["flops"] > 0 and totals["device_s"] > 0


def test_compile_events_since_cursor_survives_drain_and_trim():
    """ServeEngine.health() windows compile events by monotonic seq —
    the cursor must keep working across a drain (and by the same
    mechanism, the bounded log's head trim)."""
    from tmr_tpu import obs

    seq0 = obs.compile_event_seq()
    obs.record_compile_event("cursor_probe", ("a",), 0.0, 0.1)
    evs, seq1 = obs.compile_events_since(seq0)
    assert seq1 == seq0 + 1
    assert [e["kind"] for e in evs] == ["cursor_probe"]
    assert all(e["seq"] > seq0 for e in evs)
    obs.drain_compile_events()  # another harness drains the log...
    evs2, seq2 = obs.compile_events_since(seq1)
    assert evs2 == [] and seq2 == seq1  # ...the cursor is unaffected
    obs.record_compile_event("cursor_probe", ("b",), 0.0, 0.1)
    evs3, seq3 = obs.compile_events_since(seq1)
    assert [e["key"] for e in evs3] == [repr(("b",))]
    assert seq3 == seq1 + 1


def test_engine_health_window_starts_at_construction(pred64):
    """Key-change compile events paid BEFORE an engine existed must not
    fire a spurious recompile_storm on its first health() pass."""
    from tmr_tpu import obs
    from tmr_tpu.serve import ServeEngine

    t0 = time.perf_counter()
    for i in range(5):  # a pre-engine storm (4 key-change events)
        obs.record_compile_event("pre_engine_probe", ("k", i), t0,
                                 t0 + 0.01)
    with ServeEngine(pred64, batch=2, max_wait_ms=5,
                     exemplar_cache=0, feature_cache=0) as engine:
        doc = engine.health()
        assert doc["anomalies"] == []
        assert validate_health_report(doc) == []


def test_forward_tflops_parts_sum_and_padding_correction():
    full = devtime.forward_tflops_per_image(1024)
    bb = devtime.forward_tflops_per_image(1024, part="backbone")
    hd = devtime.forward_tflops_per_image(1024, part="heads")
    assert full == pytest.approx(bb + hd)
    # the windowed-qkv padding correction: the model must sit ABOVE the
    # old unpadded-token count (1.57 TF at 1024) and close to the
    # cost_analysis()-checked 1.60 TF (PERF.md envelope note)
    assert 1.58 < full < 1.62
    with pytest.raises(ValueError):
        devtime.forward_tflops_per_image(1024, part="sideways")


def test_map_report_attaches_mfu_only_when_flight_enabled():
    from tmr_tpu.diagnostics import validate_map_report
    from tmr_tpu.parallel.mapreduce import MapReport

    flight.configure(enabled=False)
    assert "mfu" not in MapReport().document()
    flight.configure(enabled=True)
    doc = MapReport().document()
    assert "mfu" in doc
    assert validate_map_report(doc) == []


# ----------------------------------------------------------- bench trend


def _write(path, doc):
    path.write_text(json.dumps(doc))


def test_bench_trend_reads_history_and_flags_regressions(tmp_path):
    from tmr_tpu.utils.bench_trend import collect_bench_trend

    _write(tmp_path / "BENCH_r01.json",
           {"n": 1, "rc": 0, "parsed": {"value": 10.0, "mfu": 0.08}})
    # outage round carrying the committed measurement (bench.py's
    # promoted shape: value + carried: true + error)
    _write(tmp_path / "BENCH_r02.json",
           {"n": 2, "rc": 1, "parsed": {
               "value": 10.0, "mfu": 0.08, "carried": True,
               "error": "watchdog", "stale_hours": 5.0}})
    _write(tmp_path / "BENCH_r03.json",
           {"n": 3, "rc": 0, "parsed": {"value": 8.0, "mfu": 0.05}})
    _write(tmp_path / "BENCH_r04.json", {"n": 4, "rc": 1, "parsed": None})
    _write(tmp_path / "BENCH_LIVE.json", {"value": 12.0, "mfu": 0.09})

    doc = collect_bench_trend(str(tmp_path))
    assert validate_bench_trend(doc) == []
    by_label = {r["label"]: r for r in doc["rounds"]}
    assert by_label["r01"]["source"] == "measured"
    assert by_label["r02"]["source"] == "carried"
    assert by_label["r02"]["value"] == 10.0
    assert by_label["r04"]["source"] == "error"
    assert by_label["BENCH_LIVE.json"]["source"] == "measured"
    # the r02 (carried 10.0) -> r03 (8.0) drop is 20% on value and
    # 37.5% on mfu; live recovers, so exactly one flag per field
    fields = {(r["field"], r["from_label"], r["to_label"])
              for r in doc["regressions"]}
    assert ("value", "r02", "r03") in fields
    assert ("mfu", "r02", "r03") in fields
    assert doc["checks"]["regressed"] is True
    assert doc["checks"]["measured_rounds"] == 3


def test_bench_trend_pre_promotion_outage_shape_and_empty_dir(tmp_path):
    from tmr_tpu.utils.bench_trend import collect_bench_trend

    # the r04/r05 on-disk shape: value 0.0 + last_committed_live, no
    # top-level promotion
    _write(tmp_path / "BENCH_r01.json",
           {"n": 1, "rc": 1, "parsed": {
               "value": 0.0, "error": "wedge",
               "last_committed_live": {"value": 21.065, "mfu": 0.1678}}})
    doc = collect_bench_trend(str(tmp_path))
    assert validate_bench_trend(doc) == []
    (r,) = doc["rounds"]
    assert r["source"] == "carried" and r["value"] == 21.065
    assert r["mfu"] == 0.1678

    empty = tmp_path / "empty"
    empty.mkdir()
    err = collect_bench_trend(str(empty))
    assert "error" in err
    assert validate_bench_trend(err) == []

    # a stray non-numbered BENCH_r*.json must be skipped, not crash
    _write(tmp_path / "BENCH_rerun.json", {"anything": 1})
    doc2 = collect_bench_trend(str(tmp_path))
    assert validate_bench_trend(doc2) == []
    assert all(r["label"] != "rerun" for r in doc2["rounds"])


def test_bench_trend_cli_one_line_and_rc(tmp_path):
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _write(tmp_path / "BENCH_r01.json",
           {"n": 1, "rc": 0, "parsed": {"value": 10.0, "mfu": 0.08}})
    _write(tmp_path / "BENCH_r02.json",
           {"n": 2, "rc": 0, "parsed": {"value": 5.0, "mfu": 0.04}})
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "bench_trend.py"),
         "--repo", str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 1  # regression flagged
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1
    doc = json.loads(lines[0])
    assert validate_bench_trend(doc) == []
    assert doc["checks"]["regressed"] is True
    # against the REAL repo history: must read without error and emit
    # one valid line (rc 0 or 1 depending on the committed trajectory)
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "bench_trend.py")],
        capture_output=True, text=True, timeout=120,
    )
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert validate_bench_trend(doc) == []
    assert doc["checks"]["rounds_read"] >= 5
