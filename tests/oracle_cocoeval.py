"""Independent brute-force implementation of single-category COCOeval.

pycocotools cannot be installed in this image (VERDICT r2 #9 wanted a
pycocotools cross-check), so this is the strongest substitute available: a
second, from-the-spec implementation of the COCOeval algorithm written with
deliberately different structure from tmr_tpu/utils/coco_eval.py — scalar
loops everywhere, no shared helpers, per-(threshold, area, maxdet) full
recomputation, explicit suffix-max precision envelope — so a bug in either
implementation shows up as disagreement on randomized inputs.

Semantics implemented (the published COCOeval procedure for iscrowd=0,
single category):
- per image: detections sorted by score (descending, stable), truncated to
  maxDet; GTs ordered with ignored (area outside range) last;
- per IoU threshold: greedy in detection order — each det takes the
  still-unmatched GT with the highest IoU >= threshold, never trading a
  non-ignored match for an ignored one;
- a det matched to an ignored GT is ignored; an unmatched det with area
  outside the range is ignored;
- accumulate: concatenate dets over images (image order), stable sort by
  -score, cumulate TP/FP excluding ignored, recall = TP/#(non-ignored GT),
  precision envelope made non-increasing, sampled at 101 recall points.
"""

from __future__ import annotations

import numpy as np

IOU_THRS = [0.5 + 0.05 * i for i in range(10)]
REC_THRS = [i / 100.0 for i in range(101)]
AREAS = {
    "all": (0.0, 1e10),
    "small": (0.0, 1024.0),
    "medium": (1024.0, 9216.0),
    "large": (9216.0, 1e10),
}


def _iou(d, g):
    dx1, dy1, dw, dh = d
    gx1, gy1, gw, gh = g
    ix = min(dx1 + dw, gx1 + gw) - max(dx1, gx1)
    iy = min(dy1 + dh, gy1 + gh) - max(dy1, gy1)
    if ix <= 0 or iy <= 0:
        return 0.0
    inter = ix * iy
    union = dw * dh + gw * gh - inter
    return inter / union if union > 0 else 0.0


def _match_image(gts, preds, area, max_det, iou_thr):
    """-> (scores, is_tp, is_ignored, n_gt) for one image at one setting."""
    lo, hi = AREAS[area]
    g_all = [(g["bbox"], not (lo <= g.get("area", g["bbox"][2] * g["bbox"][3]) <= hi))
             for g in gts]
    # ignored GTs last, original order otherwise
    g_sorted = [g for g in g_all if not g[1]] + [g for g in g_all if g[1]]

    order = sorted(range(len(preds)), key=lambda i: (-preds[i]["score"], i))
    order = order[:max_det]
    dets = [(preds[i]["bbox"], preds[i]["score"]) for i in order]

    gt_taken = [False] * len(g_sorted)
    scores, is_tp, is_ign = [], [], []
    for box, score in dets:
        best_iou = iou_thr
        best_g = -1
        for gi, (gbox, gig) in enumerate(g_sorted):
            if gt_taken[gi]:
                continue
            if best_g >= 0 and not g_sorted[best_g][1] and gig:
                break  # have a real match; only ignored GTs remain
            iou = _iou(box, gbox)
            if iou >= best_iou:
                best_iou = iou
                best_g = gi
        matched = best_g >= 0
        if matched:
            gt_taken[best_g] = True
        ignored = (matched and g_sorted[best_g][1]) or (
            not matched and not (lo <= box[2] * box[3] <= hi)
        )
        scores.append(score)
        is_tp.append(matched and not ignored)
        is_ign.append(ignored)
    n_gt = sum(1 for _, gig in g_sorted if not gig)
    return scores, is_tp, is_ign, n_gt


def _pr_curve(img_results):
    """Merge per-image matches -> (ap, final_recall)."""
    scores, tps, igns = [], [], []
    n_gt = 0
    for s, t, ig, n in img_results:
        scores += s
        tps += t
        igns += ig
        n_gt += n
    if n_gt == 0:
        return None, None
    order = np.argsort(-np.array(scores), kind="mergesort")
    tp = fp = 0
    rc, pr = [], []
    for i in order:
        if igns[i]:
            continue
        if tps[i]:
            tp += 1
        else:
            fp += 1
        rc.append(tp / n_gt)
        pr.append(tp / (tp + fp + np.spacing(1)))
    # envelope: precision at recall r = max precision at any recall >= r
    for i in range(len(pr) - 2, -1, -1):
        pr[i] = max(pr[i], pr[i + 1])
    q = []
    for r in REC_THRS:
        # first index with recall >= r
        pi = next((i for i, rv in enumerate(rc) if rv >= r), None)
        q.append(pr[pi] if pi is not None else 0.0)
    ap = float(np.mean(q))
    final_rc = rc[-1] if rc else 0.0
    return ap, final_rc


def evaluate(gts, preds, max_dets=(900, 1000, 1100)):
    """gts/preds: {img_id: [dict]}. Returns the 12-entry stats vector in
    COCOevalMaxDets._summarizeDets order."""
    img_ids = sorted(set(gts) | set(preds), key=str)

    def setting(area, max_det, thr_filter):
        aps, rcs = [], []
        for t in IOU_THRS:
            if thr_filter is not None and abs(t - thr_filter) > 1e-9:
                continue
            results = []
            for i in img_ids:
                g = gts.get(i, [])
                p = preds.get(i, [])
                if not g and not p:
                    continue
                results.append(_match_image(g, p, area, max_det, t))
            ap, rc = _pr_curve(results)
            if ap is not None:
                aps.append(ap)
                rcs.append(rc)
        mean = lambda xs: float(np.mean(xs)) if xs else -1.0
        return mean(aps), mean(rcs)

    md = list(max_dets)
    stats = [
        setting("all", md[2] if len(md) > 2 else md[-1], None)[0],
        setting("all", md[-1], 0.5)[0],
        setting("all", md[-1], 0.75)[0],
        setting("small", md[-1], None)[0],
        setting("medium", md[-1], None)[0],
        setting("large", md[-1], None)[0],
        setting("all", md[0], None)[1],
        setting("all", md[min(1, len(md) - 1)], None)[1],
        setting("all", md[-1], None)[1],
        setting("small", md[-1], None)[1],
        setting("medium", md[-1], None)[1],
        setting("large", md[-1], None)[1],
    ]
    return np.array(stats)
