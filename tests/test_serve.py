"""The throughput serving layer (tmr_tpu/serve): micro-batching exactness,
caches, error isolation, measured-batch defaults, multi-device dispatch.

The load-bearing contract is RAGGED-TAIL EXACTNESS: batched-serve results
for N requests must be bitwise-identical to N sequential Predictor calls,
across bucket boundaries, mixed capacities, and mixed exemplar counts —
padding and unpadding must be invisible. Everything runs at a small CPU
geometry; the programs are the production ones (same _get_fn pipeline).
"""

import os

import numpy as np
import pytest

SIZE = 128


def _predictor():
    from tmr_tpu.config import preset
    from tmr_tpu.inference import Predictor

    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=SIZE,
                 compute_dtype="float32", batch_size=1)
    pred = Predictor(cfg)
    pred.init_params(seed=0, image_size=SIZE)
    return pred


@pytest.fixture(scope="module")
def pred():
    return _predictor()


@pytest.fixture(scope="module")
def engine(pred):
    """ONE module-scoped bitwise-path engine shared by every test that
    doesn't need special caching/devices (tier-1 budget: engines are
    cheap but not free — three pipeline threads plus a stager each).
    bound 1 + caches off: every submit executes the byte-identical B=1
    program a sequential call runs, with no cross-test cache coupling."""
    from tmr_tpu.serve import ServeEngine

    eng = ServeEngine(pred, batch=1, max_wait_ms=5, feature_cache=0,
                      exemplar_cache=0)
    yield eng
    eng.close()


def _img(seed):
    return np.random.default_rng(seed).standard_normal(
        (SIZE, SIZE, 3)
    ).astype(np.float32)


SMALL_EX = np.asarray([[0.45, 0.45, 0.53, 0.55]], np.float32)  # cap 9
BIG_EX = np.asarray([[0.1, 0.1, 0.9, 0.9]], np.float32)  # cap 17
MULTI_EX = np.asarray(
    [[0.45, 0.45, 0.53, 0.55], [0.2, 0.2, 0.28, 0.3],
     [0.6, 0.55, 0.68, 0.66]], np.float32,
)

FIELDS = ("boxes", "scores", "refs", "valid")


def _np(dets):
    return {k: np.asarray(dets[k]) for k in FIELDS}


def _assert_bitwise(a, b, ctx=""):
    for k in FIELDS:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), (
            f"{ctx}: field {k!r} not bitwise-identical"
        )


# ------------------------------------------------------------- LRU cache
def test_lru_cache_counters_and_eviction():
    from tmr_tpu.serve import LRUCache

    c = LRUCache(2)
    assert c.get("a") is None  # miss
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1  # hit, refreshes recency
    c.put("c", 3)  # evicts b (LRU)
    assert c.get("b") is None
    assert c.get("c") == 3
    s = c.stats()
    assert (s["hits"], s["misses"], s["evictions"], s["inserts"]) == (
        2, 2, 1, 3
    )
    assert 0 < s["hit_rate"] < 1
    # capacity 0 = disabled: every get misses, put is a no-op
    off = LRUCache(0)
    off.put("x", 1)
    assert off.get("x") is None and len(off) == 0
    # __contains__ probes must not pollute the traffic counters
    assert "a" in c
    assert c.stats()["hits"] == 2


def test_array_digest_distinguishes_dtype_and_shape():
    from tmr_tpu.serve import array_digest

    a = np.zeros((4,), np.float32)
    assert array_digest(a) != array_digest(a.astype(np.float64))
    assert array_digest(a) != array_digest(a.reshape(2, 2))
    assert array_digest(a) == array_digest(np.zeros((4,), np.float32))


# ------------------------------------------------------------ micro-batcher
def test_batcher_releases_full_bucket_immediately():
    import time

    from tmr_tpu.serve import MicroBatcher, Request

    b = MicroBatcher(max_wait_ms=5000, bound_for=lambda bucket: 2)
    for i in range(2):
        b.put(Request(image=None, exemplars=None, bucket=("x",)))
    t0 = time.perf_counter()
    bucket, reqs = b.next_batch()
    assert time.perf_counter() - t0 < 1.0  # did not wait for the 5s bound
    assert bucket == ("x",) and len(reqs) == 2
    assert b.occupancy_snapshot() == {2: 1}


def test_batcher_flushes_lone_request_at_max_wait():
    import time

    from tmr_tpu.serve import MicroBatcher, Request

    b = MicroBatcher(max_wait_ms=150, bound_for=lambda bucket: 8)
    b.put(Request(image=None, exemplars=None, bucket=("x",)))
    t0 = time.perf_counter()
    bucket, reqs = b.next_batch()
    waited = time.perf_counter() - t0
    assert len(reqs) == 1
    assert 0.05 <= waited < 2.0  # released by the latency bound
    b.close()
    assert b.next_batch() is None


def test_batcher_expired_deadline_preempts_full_sibling():
    """Starvation guard: a request whose max_wait_ms already expired is
    released BEFORE a sibling bucket that sustained load keeps full — the
    latency bound must hold for minority buckets under overload."""
    import time

    from tmr_tpu.serve import MicroBatcher, Request

    b = MicroBatcher(max_wait_ms=100, bound_for=lambda bucket: 2)
    b.put(Request(image=None, exemplars=None, bucket=("lone",)))
    time.sleep(0.15)  # lone's deadline passes
    b.put(Request(image=None, exemplars=None, bucket=("busy",)))
    b.put(Request(image=None, exemplars=None, bucket=("busy",)))
    bucket, reqs = b.next_batch()
    assert bucket == ("lone",) and len(reqs) == 1
    bucket, reqs = b.next_batch()
    assert bucket == ("busy",) and len(reqs) == 2


def test_batcher_close_drains_partial_buckets():
    from tmr_tpu.serve import MicroBatcher, Request

    b = MicroBatcher(max_wait_ms=60000, bound_for=lambda bucket: 4)
    b.put(Request(image=None, exemplars=None, bucket=("x",)))
    b.put(Request(image=None, exemplars=None, bucket=("y",)))
    b.close()
    seen = {b.next_batch()[0], b.next_batch()[0]}
    assert seen == {("x",), ("y",)}
    assert b.next_batch() is None


def test_pad_to_power_of_two_subbuckets():
    from tmr_tpu.serve.staging import _pad_to

    assert [_pad_to(n, 8) for n in (1, 2, 3, 4, 5, 8)] == [1, 2, 4, 4, 8, 8]
    assert _pad_to(3, 4) == 4
    assert _pad_to(5, 4) == 5  # never below the request count


# ------------------------------------------------- ragged-tail exactness
# Bitwise exactness across batch shapes holds where XLA compiles
# batch-invariant programs — true on the deployment backends and on plain
# XLA:CPU (scripts/serve_bench.py --tiny pins checks.exact_match there;
# tests/test_serve_bench.py asserts it in a clean-env subprocess). THIS
# process runs under conftest's 8 forced host devices, where XLA:CPU
# thread-partitions reductions differently per batch shape (last-ULP
# drift even in the bare backbone, no serving code involved) — so
# in-process, the bitwise pin runs at bound 1 (every serve dispatch then
# executes the byte-identical program the sequential call runs) and the
# batched composition pins allclose + identical NMS keep decisions.

def _mixed_requests(n):
    reqs = []
    for i in range(n):
        img = _img(100 + i)
        if i % 3 == 2:
            reqs.append((img, MULTI_EX, True))
        else:
            reqs.append((img, BIG_EX if i % 2 else SMALL_EX, False))
    return reqs


def _sequential(pred, reqs):
    out = []
    for img, ex, multi in reqs:
        if multi:
            out.append(_np(pred.predict_multi_exemplar(img[None], ex)))
        else:
            out.append(_np(pred(img[None], ex[None])))
    return out


@pytest.mark.parametrize("n", [1, 4, 6])
def test_ragged_tail_bitwise_exactness(pred, engine, n):
    """N serve requests == N sequential Predictor calls, BITWISE, with
    mixed capacities and a multi-exemplar request in the mix — the
    unpad/re-order path must be invisible. Runs on the shared module
    engine (caches off there, so every parametrization executes)."""
    errors0 = engine.stats()["errors"]
    reqs = _mixed_requests(n)
    seq = _sequential(pred, reqs)
    futs = [engine.submit(img, ex, multi=multi) for img, ex, multi in reqs]
    results = [f.result(timeout=600) for f in futs]
    for i, (a, b) in enumerate(zip(seq, results)):
        _assert_bitwise(a, b, ctx=f"request {i} of {n}")
    assert engine.stats()["errors"] == errors0


@pytest.mark.parametrize("n", [5, 8])
def test_ragged_tail_batched_matches_sequential(pred, n):
    """Batched composition (bound 4, ragged tails across two capacity
    buckets + the multi bucket): per-request results match sequential
    calls with IDENTICAL keep decisions; floats at allclose under the
    forced-8-device caveat above (bitwise in a clean env — pinned by the
    serve_bench smoke)."""
    from tmr_tpu.serve import ServeEngine

    reqs = _mixed_requests(n)
    seq = _sequential(pred, reqs)
    with ServeEngine(pred, batch=4, max_wait_ms=40,
                     feature_cache=0) as eng:
        futs = [eng.submit(img, ex, multi=multi) for img, ex, multi in reqs]
        results = [f.result(timeout=600) for f in futs]
        stats = eng.stats()
    assert stats["errors"] == 0
    assert stats["batches"] < n  # coalescing actually batched something
    for i, (a, b) in enumerate(zip(seq, results)):
        assert np.array_equal(a["valid"], b["valid"]), f"request {i}"
        for k in ("boxes", "scores", "refs"):
            assert np.allclose(a[k], b[k], atol=1e-5), f"request {i}: {k}"


# ----------------------------------------------------------------- caches
def test_result_cache_hit_returns_identical_result(pred):
    from tmr_tpu.serve import ServeEngine

    img = _img(7)
    with ServeEngine(pred, batch=2, max_wait_ms=20,
                     feature_cache=0) as eng:
        r1 = eng.submit(img, SMALL_EX).result(timeout=600)
        r2 = eng.submit(img, SMALL_EX).result(timeout=600)
        stats = eng.stats()
    _assert_bitwise(r1, r2, ctx="result-cache hit")
    assert stats["result_cache"]["hits"] == 1
    # the hit skipped the device: only one batch was dispatched
    assert stats["batches"] == 1


def test_inflight_coalescing_resolves_all_futures(pred):
    from tmr_tpu.serve import ServeEngine

    img = _img(8)
    with ServeEngine(pred, batch=4, max_wait_ms=60,
                     feature_cache=0) as eng:
        futs = [eng.submit(img, SMALL_EX) for _ in range(3)]
        results = [f.result(timeout=600) for f in futs]
        stats = eng.stats()
    assert stats["coalesced"] == 2  # identical concurrent requests merged
    # every submitted future lands in a terminal counter (coalesced
    # duplicates included) — no phantom backlog in the accounting
    assert stats["submitted"] == 3
    assert stats["completed"] == 3 and stats["errors"] == 0
    for r in results[1:]:
        _assert_bitwise(results[0], r, ctx="coalesced")


def test_feature_cache_promotion_and_hit(pred):
    """Same image, three different exemplars: 1st = fused (cold), 2nd =
    promotion fill (encoder runs once more, features stored), 3rd =
    feature-cache hit (encoder skipped). The split-program path is
    documented as allclose-level vs the fused program, with identical
    keep decisions."""
    from tmr_tpu.serve import ServeEngine

    img = _img(9)
    ex_b = np.asarray([[0.2, 0.2, 0.28, 0.3]], np.float32)
    ex_c = np.asarray([[0.6, 0.6, 0.68, 0.7]], np.float32)
    with ServeEngine(pred, batch=2, max_wait_ms=20, feature_cache=4,
                     exemplar_cache=0) as eng:
        eng.submit(img, SMALL_EX).result(timeout=600)
        r_fill = eng.submit(img, ex_b).result(timeout=600)
        r_hit = eng.submit(img, ex_c).result(timeout=600)
        stats = eng.stats()
    assert stats["feature_fills"] >= 1
    assert stats["feature_cache"]["hits"] >= 1
    assert stats["heads_batches"] >= 2
    for r, ex in ((r_fill, ex_b), (r_hit, ex_c)):
        ref = _np(pred(img[None], ex[None]))
        assert np.array_equal(ref["valid"], r["valid"])
        for k in ("boxes", "scores", "refs"):
            assert np.allclose(ref[k], r[k], atol=1e-4), k


# -------------------------------------------------------- error isolation
def test_malformed_request_fails_alone(pred, engine):
    good_img = _img(20)
    bad_ex = np.asarray([0.2, 0.4, 0.5], np.float32)  # not (K, 4)
    rejected0 = engine.stats()["rejected"]
    f_good1 = engine.submit(good_img, SMALL_EX)
    f_bad = engine.submit(_img(21), bad_ex)
    f_shape = engine.submit(np.zeros((4, 5, 3), np.float32), SMALL_EX)
    f_good2 = engine.submit(_img(22), SMALL_EX)
    with pytest.raises(ValueError):
        f_bad.result(timeout=60)
    with pytest.raises(ValueError):
        f_shape.result(timeout=60)
    r1 = f_good1.result(timeout=600)
    r2 = f_good2.result(timeout=600)
    assert engine.stats()["rejected"] == rejected0 + 2
    _assert_bitwise(r1, _np(pred(good_img[None], SMALL_EX[None])))
    _assert_bitwise(r2, _np(pred(_img(22)[None], SMALL_EX[None])))


def test_batch_failure_falls_back_to_per_request(pred):
    """A batch-level failure must not sink the batch: the engine re-runs
    each request alone, so batch-mates of a poison batch still succeed."""
    from tmr_tpu.serve import ServeEngine

    orig_get_fn = pred._get_fn
    calls = {"boomed": False}

    def poisoned_get_fn(capacity, **kw):
        fn = orig_get_fn(capacity, **kw)

        def wrapper(params, rparams, image, exemplars, *extra):
            if image.shape[0] > 1 and not calls["boomed"]:
                calls["boomed"] = True
                raise RuntimeError("injected batch-level failure")
            return fn(params, rparams, image, exemplars, *extra)

        return wrapper

    pred._get_fn = poisoned_get_fn
    try:
        from tmr_tpu.serve import ServeEngine

        imgs = [_img(30 + i) for i in range(3)]
        with ServeEngine(pred, batch=3, max_wait_ms=30,
                         feature_cache=0) as eng:
            futs = [eng.submit(im, SMALL_EX) for im in imgs]
            results = [f.result(timeout=600) for f in futs]
            stats = eng.stats()
    finally:
        pred._get_fn = orig_get_fn
    assert calls["boomed"]
    assert stats["batch_fallbacks"] >= 1
    assert stats["errors"] == 0
    for im, r in zip(imgs, results):
        _assert_bitwise(r, _np(pred(im[None], SMALL_EX[None])),
                        ctx="fallback")


# ------------------------------------------------- recompile-free bucket keys
def test_predict_multi_exemplar_k_real_int_flavors_share_program(pred):
    """Satellite pin: Python-int vs numpy-int k_real (and numpy-derived
    capacities) must land on one compiled entry — no recompiles."""
    img = _img(40)
    pred.predict_multi_exemplar(img[None], MULTI_EX, k_real=3)
    n0 = len(pred._compiled)
    pred.predict_multi_exemplar(img[None], MULTI_EX, k_real=np.int32(3))
    pred.predict_multi_exemplar(img[None], MULTI_EX, k_real=np.int64(3))
    pred.predict_multi_exemplar(img[None], MULTI_EX)  # k from len()
    assert len(pred._compiled) == n0
    # trimming semantics: k_real=2 matches the 2-row call exactly
    a = _np(pred.predict_multi_exemplar(img[None], MULTI_EX, k_real=2))
    b = _np(pred.predict_multi_exemplar(img[None], MULTI_EX[:2]))
    _assert_bitwise(a, b, ctx="k_real trim")
    with pytest.raises(ValueError):
        pred.predict_multi_exemplar(img[None], MULTI_EX, k_real=5)


def test_bucket_key_is_python_ints(pred):
    key = pred.bucket_key(np.int64(SIZE), MULTI_EX.astype(np.float64),
                          multi=True, k_real=np.int32(3))
    assert key == ("multi", SIZE, 9, 3)
    assert all(type(x) is int for x in key[1:])
    key_s = pred.bucket_key(SIZE, BIG_EX)
    assert key_s == ("single", SIZE, 17, 1)
    assert all(type(x) is int for x in key_s[1:])


# ---------------------------------------------------- measured batch default
def test_measured_bench_batch_reads_sweep_winner(tmp_path, monkeypatch):
    import json

    from tmr_tpu.utils.autotune import (
        bench_batch_cache_key,
        measured_bench_batch,
    )

    cache = tmp_path / "autotune.json"
    key = bench_batch_cache_key("TFRT_CPU_0", 128)
    cache.write_text(json.dumps({key: {"TMR_BENCH_BATCH": "8"}}))
    monkeypatch.setenv("TMR_AUTOTUNE_CACHE", str(cache))
    monkeypatch.setenv("TMR_AUTOTUNE_SEED", str(tmp_path / "absent.json"))
    assert measured_bench_batch(128, device_kind="TFRT_CPU_0") == 8
    assert measured_bench_batch(999, device_kind="TFRT_CPU_0") is None


def test_engine_batch_bound_resolution_order(pred, tmp_path, monkeypatch):
    """Explicit arg > TMR_SERVE_BATCH > measured sweep winner > 4."""
    import json

    import jax

    from tmr_tpu.serve import ServeEngine
    from tmr_tpu.utils.autotune import bench_batch_cache_key

    cache = tmp_path / "autotune.json"
    kind = jax.devices()[0].device_kind
    cache.write_text(json.dumps(
        {bench_batch_cache_key(kind, SIZE): {"TMR_BENCH_BATCH": "16"}}
    ))
    monkeypatch.setenv("TMR_AUTOTUNE_CACHE", str(cache))
    monkeypatch.setenv("TMR_AUTOTUNE_SEED", str(tmp_path / "absent.json"))
    bucket = ("single", SIZE, 9, 1)

    eng = ServeEngine(pred, batch=2)
    assert eng._bound_for(bucket) == 2
    eng.close()
    monkeypatch.setenv("TMR_SERVE_BATCH", "3")
    eng = ServeEngine(pred)
    assert eng._bound_for(bucket) == 3
    eng.close()
    monkeypatch.delenv("TMR_SERVE_BATCH")
    eng = ServeEngine(pred)
    assert eng._bound_for(bucket) == 16  # the measured sweep winner
    eng.close()
    monkeypatch.setenv("TMR_AUTOTUNE_CACHE", str(tmp_path / "absent2.json"))
    eng = ServeEngine(pred)
    assert eng._bound_for(bucket) == 4  # the engineering default
    eng.close()


# ------------------------------------------------------------ multi-device
def test_round_robin_multi_device_dispatch_stays_exact(pred):
    """Two (virtual CPU) devices: batches round-robin, per-request results
    stay bitwise-identical to sequential — data-parallel serving needs no
    collective."""
    import jax

    from tmr_tpu.serve import ServeEngine

    devices = jax.devices()[:2]
    if len(devices) < 2:
        pytest.skip("needs >= 2 devices")
    reqs = [(_img(50 + i), SMALL_EX) for i in range(6)]
    seq = [_np(pred(im[None], ex[None])) for im, ex in reqs]
    # bound 1: every dispatch runs the B=1 program shape the sequential
    # call compiled, so the cross-device comparison stays bitwise (see the
    # forced-8-device caveat above the ragged-tail tests)
    with ServeEngine(pred, batch=1, max_wait_ms=30, devices=devices,
                     feature_cache=0) as eng:
        futs = [eng.submit(im, ex) for im, ex in reqs]
        results = [f.result(timeout=600) for f in futs]
        stats = eng.stats()
    assert len(stats["per_device_batches"]) == 2
    assert all(v > 0 for v in stats["per_device_batches"].values())
    for i, (a, b) in enumerate(zip(seq, results)):
        _assert_bitwise(a, b, ctx=f"multi-device request {i}")


def test_engine_rejects_submit_after_close(pred):
    from tmr_tpu.serve import ServeEngine

    eng = ServeEngine(pred, batch=2, max_wait_ms=10)
    eng.close()
    fut = eng.submit(_img(60), SMALL_EX)
    with pytest.raises(RuntimeError):
        fut.result(timeout=10)
