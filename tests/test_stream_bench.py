"""scripts/stream_bench.py: the stream_report/v1 contract.

The smoke test runs the real script in a subprocess at tiny CPU shapes
in a clean env with an ISOLATED autotune cache and asserts the
acceptance checks: backbone executions ≪ frames over the bursty
synthetic workload (the devtime program-table witness), frames/s
>= 1.5x the frame-independent baseline, every "changed" frame bitwise
the ordinary path, zero cross-stream hits, and every reused frame
labeled ``temporal_reuse``. The validator tests pin the schema both
ways, and the bench_trend ``--stream`` gate is pinned fail-closed.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_env(tmp_path, **extra):
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS")
    }
    env.update(
        JAX_PLATFORMS="cpu",
        TMR_BENCH_TINY="1",
        TMR_BENCH_SIZE="128",
        # isolate any autotune reads/writes from the user's real cache
        TMR_AUTOTUNE_CACHE=str(tmp_path / "autotune.json"),
        TMR_AUTOTUNE_SEED=str(tmp_path / "absent_seed.json"),
        **extra,
    )
    return env


def _valid_doc():
    from tmr_tpu.diagnostics import STREAM_REPORT_SCHEMA

    return {
        "schema": STREAM_REPORT_SCHEMA,
        "device": "cpu",
        "config": {"image_size": 128, "streams": 2,
                   "frames_per_stream": 8, "frames": 16, "delta": 0.02,
                   "seed": 0, "dtype": "float32"},
        "throughput": {"stream_frames_per_sec": 6.0,
                       "independent_frames_per_sec": 2.4,
                       "speedup": 2.5},
        "backbone": {"frames": 16, "executions": 8,
                     "baseline_by_program": {"single": 16},
                     "by_program": {"backbone": 4, "single": 4,
                                    "heads": 4}},
        "reuse": {"reused_frames": 12, "changed_frames": 2,
                  "first_frames": 2,
                  "expected": {"reused": 12, "changed": 2, "first": 2}},
        "exactness": {"changed_frames_checked": 4, "mismatches": 0,
                      "label_errors": 0},
        "isolation": {"cross_stream_hits": 0, "sessions": 2},
        "checks": {"backbone_amortized": True, "speedup_ok": True,
                   "changed_frames_exact": True,
                   "cross_stream_isolated": True, "reuse_labeled": True,
                   "verdicts_as_expected": True},
    }


def test_validate_stream_report_accepts_valid_and_error_docs():
    from tmr_tpu.diagnostics import (
        STREAM_REPORT_SCHEMA,
        validate_stream_report,
    )

    assert validate_stream_report(_valid_doc()) == []
    assert validate_stream_report(
        {"schema": STREAM_REPORT_SCHEMA, "error": "watchdog: ..."}
    ) == []


@pytest.mark.parametrize("mutate, fragment", [
    (lambda d: d.update(schema="bogus/v9"), "schema"),
    (lambda d: d["config"].update(streams=0), "streams"),
    (lambda d: d["config"].pop("delta"), "delta"),
    (lambda d: d["throughput"].pop("speedup"), "speedup"),
    (lambda d: d["backbone"].update(executions=-1), "executions"),
    (lambda d: d["backbone"].pop("by_program"), "by_program"),
    (lambda d: d.pop("reuse"), "reuse"),
    (lambda d: d["reuse"].update(reused_frames=True), "reused_frames"),
    (lambda d: d["exactness"].pop("mismatches"), "mismatches"),
    (lambda d: d.pop("isolation"), "isolation"),
    (lambda d: d["checks"].pop("reuse_labeled"), "reuse_labeled"),
    (lambda d: d.update(error=""), "error"),
])
def test_validate_stream_report_rejects_broken_docs(mutate, fragment):
    from tmr_tpu.diagnostics import validate_stream_report

    doc = _valid_doc()
    mutate(doc)
    problems = validate_stream_report(doc)
    assert problems, f"expected a problem for {fragment}"
    assert any(fragment in p for p in problems), problems


def test_read_stream_report_reduces_and_fails_closed(tmp_path):
    from tmr_tpu.utils.bench_trend import read_stream_report

    path = tmp_path / "stream.json"
    path.write_text(json.dumps(_valid_doc()) + "\n")
    out = read_stream_report(str(path))
    assert out["checks"] == {
        "backbone_amortized": True, "speedup_ok": True,
        "changed_frames_exact": True, "cross_stream_isolated": True,
        "reuse_labeled": True,
    }
    assert out["summary"]["backbone_executions"] == 8
    assert out["summary"]["frames"] == 16
    assert out["summary"]["speedup"] == 2.5
    # fail CLOSED: a missing check is not a pass
    doc = _valid_doc()
    del doc["checks"]["speedup_ok"]
    path.write_text(json.dumps(doc) + "\n")
    assert read_stream_report(str(path))["checks"]["speedup_ok"] is False
    # error record and unreadable file reduce to error records
    path.write_text(json.dumps({"schema": "stream_report/v1",
                                "error": "boom"}))
    assert "error" in read_stream_report(str(path))
    assert "error" in read_stream_report(str(tmp_path / "absent.json"))


def test_bench_trend_stream_rc_gates(tmp_path):
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_valid_doc()) + "\n")
    bad_doc = _valid_doc()
    bad_doc["checks"]["changed_frames_exact"] = False
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(bad_doc) + "\n")
    script = os.path.join(REPO, "scripts", "bench_trend.py")
    ok = subprocess.run(
        [sys.executable, script, "--stream", str(good)],
        capture_output=True, text=True, timeout=120,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert json.loads(ok.stdout)["checks"]["changed_frames_exact"] is True
    fail = subprocess.run(
        [sys.executable, script, "--stream", str(bad)],
        capture_output=True, text=True, timeout=120,
    )
    assert fail.returncode == 1


def test_stream_bench_tiny_smoke_meets_acceptance_checks(tmp_path):
    """The acceptance proof, end to end on CPU: one JSON line, valid
    stream_report/v1, backbone executions strictly below frames on the
    bursty workload, >= 1.5x frames/s over the frame-independent
    baseline, changed frames bitwise-exact, reuse labeled and never
    crossing stream ids."""
    out_file = tmp_path / "stream_report.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "stream_bench.py"),
         "--tiny", "--streams", "2", "--frames", "8",
         "--out", str(out_file)],
        env=_bench_env(tmp_path), capture_output=True, text=True,
        timeout=560,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    doc = json.loads(lines[0])

    from tmr_tpu.diagnostics import validate_stream_report

    assert validate_stream_report(doc) == []
    assert "validator_problems" not in doc
    checks = doc["checks"]
    assert checks["backbone_amortized"] is True, doc["backbone"]
    assert checks["speedup_ok"] is True, doc["throughput"]
    assert checks["changed_frames_exact"] is True, doc["exactness"]
    assert checks["cross_stream_isolated"] is True, doc["isolation"]
    assert checks["reuse_labeled"] is True, doc
    assert checks["verdicts_as_expected"] is True, doc["reuse"]
    # the witness itself, not just its boolean: the bursty workload
    # (one content swap per stream) needs far fewer backbone runs than
    # frames, and every frame is accounted to a verdict
    bb = doc["backbone"]
    assert bb["executions"] < bb["frames"], bb
    r = doc["reuse"]
    assert r["reused_frames"] + r["changed_frames"] + r["first_frames"] \
        == doc["config"]["frames"]
    assert r["reused_frames"] > 0
    assert doc["exactness"]["mismatches"] == 0
    assert doc["throughput"]["speedup"] >= 1.5
    # --out wrote the same document; progress went to stderr only
    assert json.loads(out_file.read_text())["checks"] == checks
    assert "[stream_bench]" in out.stderr
