"""Native C++ IO runtime (native/tmr_io.cc via tmr_tpu/data/native_io.py):
ustar parsing, prefetch threading, error tolerance, and stat parity with the
Python tarfile path."""

import io
import os
import tarfile

import jax.numpy as jnp
import numpy as np
import pytest

from tmr_tpu.data import native_io

pytestmark = pytest.mark.skipif(
    not native_io.available(), reason="no g++/make to build libtmr_io.so"
)


def _make_tar(dirpath, name, files):
    """files: list of (member_name, payload bytes)."""
    path = os.path.join(dirpath, name)
    with tarfile.open(path, "w") as tar:
        for member, payload in files:
            info = tarfile.TarInfo(member)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    return path


def test_stream_reads_all_members(tmp_path):
    rng = np.random.default_rng(0)
    paths = []
    want = {}
    for s in range(3):
        files = []
        for i in range(5):
            payload = rng.bytes(rng.integers(1, 5000))
            files.append((f"dir/img_{s}_{i}.png", payload))
            want[(s, f"dir/img_{s}_{i}.png")] = payload
        paths.append(_make_tar(str(tmp_path), f"shard_{s}.tar", files))

    got = {}
    with native_io.NativeTarStream(paths, threads=3, queue_cap=4) as stream:
        for shard, name, data in stream:
            got[(shard, name)] = data
        assert stream.errors == 0
    assert got == want


def test_stream_long_member_names(tmp_path):
    """ustar prefix field handling for paths > 100 chars."""
    long_name = "/".join(["deep"] * 30) + "/leaf.png"  # > 100 chars
    assert len(long_name) > 100
    path = _make_tar(str(tmp_path), "s.tar", [(long_name, b"payload")])
    with native_io.NativeTarStream([path]) as stream:
        items = list(stream)
    assert items == [(0, long_name, b"payload")]


def test_stream_skips_bad_shards(tmp_path):
    good = _make_tar(str(tmp_path), "good.tar", [("a.png", b"x" * 100)])
    bad = str(tmp_path / "bad.tar")
    with open(bad, "wb") as f:
        f.write(b"this is not a tar archive")
    missing = str(tmp_path / "missing.tar")
    with native_io.NativeTarStream([bad, good, missing]) as stream:
        items = list(stream)
        # exactly the good member arrives; both bad shards counted
        assert [(s, n) for s, n, _ in items] == [(1, "a.png")]
        assert stream.errors >= 1  # bad.tar garbage may parse as empty


def test_stream_early_close_no_hang(tmp_path):
    files = [(f"f{i}.png", b"y" * 2000) for i in range(50)]
    path = _make_tar(str(tmp_path), "big.tar", files)
    stream = native_io.NativeTarStream([path], threads=2, queue_cap=2)
    it = iter(stream)
    next(it)
    stream.close()  # workers blocked on the full queue must unblock


def test_stream_early_close_threads_exceed_cap(tmp_path):
    """n_threads > queue_cap, close without consuming anything: every worker
    can be parked in Queue::push with no consumer draining — shutdown() must
    wake them or ~Stream's join() hangs forever (ADVICE r1 finding)."""
    paths = []
    for s in range(8):
        files = [(f"s{s}_f{i}.png", b"z" * 4000) for i in range(20)]
        paths.append(_make_tar(str(tmp_path), f"shard_{s}.tar", files))
    for _ in range(3):  # a few rounds to catch the race, not just one lucky run
        stream = native_io.NativeTarStream(paths, threads=8, queue_cap=2)
        iter(stream)
        stream.close()  # must return promptly, not deadlock in join()


def test_native_run_stream_parity(tmp_path):
    """run_stream_native produces the same stat table and feature dumps as
    the Python run_stream."""
    from PIL import Image

    from tmr_tpu.parallel import mapreduce as mr

    rng = np.random.default_rng(1)
    paths = []
    for name, n in [("Easy_0.tar", 5), ("Hard_0.tar", 3)]:
        files = []
        for i in range(n):
            buf = io.BytesIO()
            Image.fromarray(
                rng.integers(0, 255, (40, 40, 3), dtype=np.uint8).astype(
                    np.uint8
                )
            ).save(buf, format="PNG")
            files.append((f"im_{i}.png", buf.getvalue()))
        files.append(("notes.txt", b"skip me"))
        paths.append(_make_tar(str(tmp_path), name, files))

    def encode(images):
        f = images * 2.0 - 0.5
        return f, mr.feature_stats(jnp.asarray(f))

    saved_a, saved_b = {}, {}
    acc_py = mr.run_stream(
        paths, encode, batch_size=4, image_size=32,
        save_features=lambda s, n, f: saved_a.__setitem__((s, n), f.sum()),
    )
    acc_nat = mr.run_stream_native(
        paths, encode, batch_size=4, image_size=32,
        save_features=lambda s, n, f: saved_b.__setitem__((s, n), f.sum()),
    )
    np.testing.assert_allclose(acc_nat.table, acc_py.table, rtol=1e-6)
    assert set(saved_a) == set(saved_b)
    for k in saved_a:
        np.testing.assert_allclose(saved_a[k], saved_b[k], rtol=1e-5)
