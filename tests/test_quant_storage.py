"""True int8 storage (TMR_QUANT_STORAGE, ops/quant.quantize_tree):
offline-quantized param trees, the bitwise stored-vs-fake equality
contract end-to-end through Predictor, the digest cache, the int8-reach
program audit, the devtime weight-bytes accounting, and the serve-layer
quant provenance stamp."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tmr_tpu.diagnostics import drain_gate_refusals
from tmr_tpu.ops import quant as q

TINY = dict(backbone="resnet50_layer1", image_size=64, emb_dim=16,
            compute_dtype="bfloat16", batch_size=1, max_detections=64)


def _tiny_cfg(**over):
    from tmr_tpu.config import preset

    return preset("TMR_FSCD147", **{**TINY, **over})


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for k in ("TMR_QUANT", "TMR_QUANT_STORAGE", "TMR_QUANT_KERNEL",
              "TMR_DECODER_IMPL", "TMR_NO_FUSED_HEADS",
              "TMR_NO_PALLAS_INT8"):
        monkeypatch.delenv(k, raising=False)
    q._OK_CACHE.clear()
    drain_gate_refusals()
    yield
    q._OK_CACHE.clear()
    drain_gate_refusals()


def _mk_tree(rng, c=8):
    z = lambda *s: jnp.zeros(s, jnp.float32)
    kern = lambda *s: jnp.asarray(rng.standard_normal(s) * 0.05,
                                  jnp.float32)
    return {
        "backbone": {"conv": {"kernel": kern(3, 3, 3, c),
                              "bias": z(c)}},
        "input_proj_0": {"kernel": kern(1, 1, c, c), "bias": z(c)},
        "decoder_o_0": {"conv_0": {"kernel": kern(3, 3, c, c),
                                   "bias": z(c)}},
        "decoder_b_0": {"conv_0": {"kernel": kern(3, 3, c, c),
                                   "bias": z(c)}},
        "objectness_head_0": {"conv": {"kernel": kern(1, 1, c, 1),
                                       "bias": z(1)}},
        "ltrbs_head_0": {"conv": {"kernel": kern(1, 1, c, 4),
                                  "bias": z(4)}},
    }


# ------------------------------------------------------- quantize_tree


def test_quantize_tree_structure_dtypes_and_scales():
    """int8 leaves exactly at the decoder/head kernel paths, per-tap
    per-output-channel scales, everything else untouched."""
    rng = np.random.default_rng(0)
    tree = _mk_tree(rng)
    qp = q.quantize_tree(tree)
    assert sorted(qp.paths) == [
        "decoder_b_0/conv_0/kernel", "decoder_o_0/conv_0/kernel",
        "ltrbs_head_0/conv/kernel", "objectness_head_0/conv/kernel",
    ]
    assert qp.tree["decoder_o_0"]["conv_0"]["kernel"].dtype == jnp.int8
    assert qp.tree["ltrbs_head_0"]["conv"]["kernel"].dtype == jnp.int8
    # untouched leaves ride through as-is (same objects)
    assert qp.tree["backbone"]["conv"]["kernel"] is \
        tree["backbone"]["conv"]["kernel"]
    assert qp.tree["input_proj_0"]["kernel"].dtype == jnp.float32
    assert qp.tree["decoder_o_0"]["conv_0"]["bias"].dtype == jnp.float32
    # per-tap per-output-channel scales: (k, k, 1, C_out)
    assert qp.scales["decoder_o_0"]["conv_0"]["kernel"].shape == \
        (3, 3, 1, 8)
    assert qp.scales["ltrbs_head_0"]["conv"]["kernel"].shape == \
        (1, 1, 1, 4)
    assert "backbone" not in qp.scales
    # int8 bytes are exactly 1/4 the f32 bytes of the same leaves
    assert qp.f32_weight_bytes == 4 * qp.weight_bytes


def test_quantize_tree_round_trip_matches_per_tap_fake_quant():
    """axis=2 offline quantization is elementwise the per-tap axis=0
    grouping the in-program fake path applies — the bitwise contract's
    foundation."""
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((3, 3, 8, 8)) * 0.05, jnp.float32)
    qw, s = q.quantize_int8(w, axis=2)
    for dy in range(3):
        for dx in range(3):
            q2, s2 = q.quantize_int8(w[dy, dx], axis=0)
            np.testing.assert_array_equal(np.asarray(qw[dy, dx]),
                                          np.asarray(q2))
            np.testing.assert_array_equal(np.asarray(s[dy, dx]),
                                          np.asarray(s2))
            np.testing.assert_array_equal(
                np.asarray(q.fake_quant(w[dy, dx], axis=0,
                                        dtype=jnp.float32)),
                np.asarray(q.dequantize(qw[dy, dx], s[dy, dx],
                                        jnp.float32)),
            )


def test_quantize_tree_digest_cache_hit_skips_requantization(monkeypatch):
    """Same weight bytes (different array objects) -> same digest -> the
    cached int8 leaves are reused, quantize_int8 never runs again."""
    rng = np.random.default_rng(2)
    tree = _mk_tree(rng)
    qp1 = q.quantize_tree(tree)
    calls = []
    real = q.quantize_int8
    monkeypatch.setattr(
        q, "quantize_int8", lambda *a, **k: calls.append(1) or real(*a, **k)
    )
    copy = jax.tree.map(lambda x: jnp.array(np.asarray(x)), tree)
    qp2 = q.quantize_tree(copy)
    assert qp2.digest == qp1.digest
    assert calls == []  # digest hit: no re-quantization
    assert qp2.tree["decoder_o_0"]["conv_0"]["kernel"] is \
        qp1.tree["decoder_o_0"]["conv_0"]["kernel"]
    # different weights -> different digest, fresh quantization
    tree3 = _mk_tree(np.random.default_rng(3))
    qp3 = q.quantize_tree(tree3)
    assert qp3.digest != qp1.digest
    assert calls  # re-quantized


def test_quantize_tree_refuses_non_matching_tree():
    with pytest.raises(ValueError, match="no storable"):
        q.quantize_tree({"backbone": {"w": jnp.zeros((2, 2))}})


# ------------------------------------------------------- gates / modes


def test_storage_and_kernel_mode_validation(monkeypatch):
    assert q.quant_storage_mode() == "off"
    assert q.quant_kernel() == "dequant"  # auto resolves to the pin
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    assert q.quant_storage_mode() == "int8"
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int4")
    with pytest.raises(ValueError, match="TMR_QUANT_STORAGE"):
        q.quant_storage_mode()
    monkeypatch.setenv("TMR_QUANT_KERNEL", "int8dot")
    assert q.quant_kernel() == "int8dot"
    monkeypatch.setenv("TMR_QUANT_KERNEL", "fp8")
    with pytest.raises(ValueError, match="TMR_QUANT_KERNEL"):
        q.quant_kernel()


def test_quant_storage_ok_equality_pin_small_geometry():
    assert q.quant_storage_ok(8, 8, 16, 16, num_layers=2, kernel_size=3)
    assert drain_gate_refusals() == []


def test_quant_storage_ok_refusal_records_storage_tier(monkeypatch):
    """Perturb the offline scales (axis=2 path only): stored != fake ->
    the equality pin refuses with tier 'storage' recorded and caches the
    verdict."""
    real = q.quantize_int8

    def skewed(w, axis=-1):
        qq, s = real(w, axis=axis)
        if axis == 2:  # the offline grouping only
            s = s * 1.5
        return qq, s

    monkeypatch.setattr(q, "quantize_int8", skewed)
    assert not q.quant_storage_ok(8, 8, 16, 16)
    causes = drain_gate_refusals()
    assert causes and causes[-1]["gate"] == "quant_storage_ok"
    assert causes[-1]["config"]["tier"] == "storage"
    assert not q.quant_storage_ok(8, 8, 16, 16)  # cached
    assert drain_gate_refusals() == []


def test_quant_int8dot_ok_small_geometry():
    assert q.quant_int8dot_ok(8, 8, 16, 16)
    assert drain_gate_refusals() == []


def test_quant_xcorr_int8dot_tier():
    assert q.quant_xcorr_ok(8, 12, 12, 5, kernel="int8dot")
    assert drain_gate_refusals() == []


def test_stored_params_for_admission_refusals(monkeypatch):
    """Every admission refusal returns None with a recorded cause AND a
    FormulationFallbackWarning naming TMR_QUANT_STORAGE."""
    from tmr_tpu.diagnostics import FormulationFallbackWarning

    rng = np.random.default_rng(4)
    tree = _mk_tree(rng)
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    # TMR_QUANT unset: storage rides the admitted fake-quant path only
    with pytest.warns(FormulationFallbackWarning):
        assert q.stored_params_for(tree, 8, 8, 16, 16, 1, 3) is None
    assert drain_gate_refusals()[-1]["gate"] == "quant_storage_ok"
    monkeypatch.setenv("TMR_QUANT", "int8")
    # explicit xla pin: int8 leaves cannot run the module stack
    monkeypatch.setenv("TMR_DECODER_IMPL", "xla")
    with pytest.warns(FormulationFallbackWarning):
        assert q.stored_params_for(tree, 8, 8, 16, 16, 1, 3) is None
    monkeypatch.delenv("TMR_DECODER_IMPL")
    # single-stack model
    with pytest.warns(FormulationFallbackWarning):
        assert q.stored_params_for(tree, 8, 8, 16, 16, 1, 3,
                                   box_reg=False) is None
    # admitted: a real QuantizedParams
    qp = q.stored_params_for(tree, 8, 8, 16, 16, 1, 3)
    assert qp is not None and len(qp.paths) == 4


# -------------------------------------------------- Predictor end-to-end


@pytest.fixture(scope="module")
def tiny_pred():
    from tmr_tpu.inference import Predictor

    cfg = _tiny_cfg()
    pred = Predictor(cfg)
    pred.init_params(seed=0, image_size=64)
    return pred


def _inputs():
    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.standard_normal((1, 64, 64, 3)), jnp.float32)
    ex = jnp.asarray([[[0.4, 0.4, 0.6, 0.6]]], jnp.float32)
    return img, ex


def test_predictor_stored_bitwise_vs_fake_reduced(tiny_pred, monkeypatch):
    """The acceptance pin at the reduced CPU geometry: the full fused
    program with a stored int8 tree is bitwise-identical to the admitted
    fake-quant program — single AND batched-multi paths."""
    from tmr_tpu.inference import Predictor

    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    monkeypatch.setenv("TMR_QUANT", "int8")
    img, ex = _inputs()
    fake = tiny_pred(img, ex)
    fake_multi = tiny_pred.predict_multi_exemplar(
        img, np.asarray([[0.4, 0.4, 0.6, 0.6], [0.3, 0.3, 0.5, 0.5]],
                        np.float32),
    )
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    pred2 = Predictor(tiny_pred.cfg, params=tiny_pred.params)
    st = pred2._storage_state()
    assert st is not None, "storage must be admitted at tiny geometry"
    assert pred2.exec_params() is st.tree
    stored = pred2(img, ex)
    for k in ("boxes", "scores", "refs", "valid"):
        np.testing.assert_array_equal(np.asarray(fake[k]),
                                      np.asarray(stored[k]), err_msg=k)
    stored_multi = pred2.predict_multi_exemplar(
        img, np.asarray([[0.4, 0.4, 0.6, 0.6], [0.3, 0.3, 0.5, 0.5]],
                        np.float32),
    )
    for k in ("boxes", "scores", "refs", "valid"):
        np.testing.assert_array_equal(
            np.asarray(fake_multi[k]), np.asarray(stored_multi[k]),
            err_msg=f"multi:{k}",
        )
    # program keys carry the checkpoint digest (stale-scale protection)
    assert any(st.digest in map(str, key) for key in pred2._compiled)
    # provenance stamp
    stamp = pred2.quant_stamp()
    assert stamp["mode"] == "int8" and stamp["storage"] == "int8"
    assert stamp["f32_weight_bytes"] == 4 * stamp["weight_bytes"]


def test_predictor_storage_off_without_quant(tiny_pred, monkeypatch):
    """TMR_QUANT_STORAGE alone (no TMR_QUANT=int8) must refuse and run
    the exact path — never silently quantize."""
    from tmr_tpu.inference import Predictor

    img, ex = _inputs()
    # fresh Predictor for the exact reference: the env knobs are read at
    # trace time, so tiny_pred's cached programs belong to other states
    exact = Predictor(tiny_pred.cfg, params=tiny_pred.params)(img, ex)
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    with pytest.warns(Warning):
        pred2 = Predictor(tiny_pred.cfg, params=tiny_pred.params)
        assert pred2._storage_state() is None
        got = pred2(img, ex)
    for k in ("boxes", "scores"):
        np.testing.assert_array_equal(np.asarray(exact[k]),
                                      np.asarray(got[k]))
    assert pred2.quant_stamp() is None


def test_second_predictor_hits_digest_cache(tiny_pred, monkeypatch):
    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    monkeypatch.setenv("TMR_QUANT", "int8")
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    from tmr_tpu.inference import Predictor

    p1 = Predictor(tiny_pred.cfg, params=tiny_pred.params)
    st1 = p1._storage_state()
    assert st1 is not None
    calls = []
    real = q.quantize_int8
    monkeypatch.setattr(
        q, "quantize_int8", lambda *a, **k: calls.append(1) or real(*a, **k)
    )
    p2 = Predictor(
        tiny_pred.cfg,
        params=jax.tree.map(lambda x: jnp.array(np.asarray(x)),
                            tiny_pred.params),
    )
    st2 = p2._storage_state()
    assert st2 is not None and st2.digest == st1.digest
    assert calls == []  # no re-quantization on the second Predictor


# ---------------------------------------------- accounting + audit


def test_mfu_report_weight_bytes_halved_and_roofline_flip():
    """The acceptance accounting pin: per-program weight bytes from the
    devtime table drop >= 2x (4x for the quantized leaves) when the
    program receives the int8 tree, cost_analysis() bytes drop with
    them, and a formerly memory-bound program's roofline verdict flips
    to compute at the same shape."""
    from tmr_tpu.obs import devtime, flight

    flight.configure(enabled=True)
    devtime.reset()
    try:
        rng = np.random.default_rng(0)
        K = N = 512
        M = 64
        w = jnp.asarray(rng.standard_normal((K, N)) * 0.05, jnp.float32)
        x = jnp.asarray(rng.standard_normal((M, K)), jnp.bfloat16)

        @jax.jit
        def f32_prog(params, x):
            return jax.lax.dot_general(
                x, params["w"].astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        qw, s = q.quantize_int8(w, axis=0)

        @jax.jit
        def int8_prog(params, x):
            op = q.dequantize(params["w"], params["s"], jnp.bfloat16)
            return jax.lax.dot_general(
                x, op, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        wf = devtime.track_devtime(f32_prog, "heads", ("f32",))
        wi = devtime.track_devtime(int8_prog, "heads", ("int8",))
        for _ in range(2):
            jax.block_until_ready(wf({"w": w}, x))
            jax.block_until_ready(wi({"w": qw, "s": s}, x))
        doc = devtime.mfu_report()
        from tmr_tpu.diagnostics import validate_mfu_report

        assert validate_mfu_report(doc) == []
        pf = next(p for p in doc["programs"] if "f32" in p["key"])
        pi = next(p for p in doc["programs"] if "int8" in p["key"])
        assert not pf["int8_weights"] and pi["int8_weights"]
        assert pf["weight_bytes"] >= 2 * pi["weight_bytes"]
        # cost_analysis bytes move with the storage, enough to flip the
        # roofline verdict of this memory-bound shape
        assert pf["cost_source"] == "xla" and pi["cost_source"] == "xla"
        assert pi["bytes_per_call"] < pf["bytes_per_call"]
        assert pf["bound"] == "memory"
        assert pi["bound"] == "compute"
    finally:
        flight.configure(enabled=False)
        devtime.reset()


def test_storage_audit_proves_int8_reach(monkeypatch):
    """The program audit's storage rule: int8 leaves arrive as program
    invars AND feed the decoder/head dot_generals."""
    monkeypatch.setenv("TMR_QUANT", "int8")
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    from tmr_tpu.analysis.program_audit import audit_storage_program

    rec = audit_storage_program(image_size=32, emb_dim=16,
                                backbone="resnet50_layer1",
                                max_detections=32)
    assert rec["ok"], rec["problems"]
    assert rec["int8_invars"] == rec["stored_leaves"] == 4
    assert rec["int8_fed_dots"] >= 10  # 3x3 taps + block-diagonal head
    assert rec["widening_converts"] == 0  # quant-widen still holds


def test_int8_reach_stats_detects_upconverted_tree():
    """A program handed an f32 tree (the silent-upconvert failure mode)
    shows zero int8 invars — the exact signal the audit keys on."""
    from tmr_tpu.analysis.program_audit import int8_reach_stats

    @jax.jit
    def prog(w, x):
        return x @ w

    w8 = jnp.ones((4, 4), jnp.int8)
    x = jnp.ones((2, 4), jnp.float32)
    good = int8_reach_stats(
        jax.make_jaxpr(lambda w, x: prog(w.astype(jnp.float32) * 0.1, x))(
            w8, x
        )
    )
    assert good["int8_invars"] == 1 and good["int8_fed_dots"] >= 1
    bad = int8_reach_stats(
        jax.make_jaxpr(prog)(jnp.ones((4, 4), jnp.float32), x)
    )
    assert bad["int8_invars"] == 0 and bad["int8_fed_dots"] == 0


# ----------------------------------------------------- serve provenance


def test_serve_engine_carries_quant_stamp(tiny_pred, monkeypatch):
    """stats()/health() carry the quant stamp under storage mode, the
    health document still validates, and the default-off engine keeps
    its byte-identical shape (no 'quant' key)."""
    from tmr_tpu.diagnostics import validate_health_report
    from tmr_tpu.serve.engine import ServeEngine

    eng = ServeEngine(tiny_pred, batch=1, exemplar_cache=0,
                      feature_cache=0)
    try:
        assert "quant" not in eng.stats()
        assert "quant" not in eng.health()
    finally:
        eng.close(timeout=5)
    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    monkeypatch.setenv("TMR_QUANT", "int8")
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    from tmr_tpu.inference import Predictor

    pred2 = Predictor(tiny_pred.cfg, params=tiny_pred.params)
    eng2 = ServeEngine(pred2, batch=1, exemplar_cache=0, feature_cache=0)
    try:
        stats = eng2.stats()
        assert stats["quant"]["storage"] == "int8"
        assert stats["quant"]["mode"] == "int8"
        health = eng2.health()
        assert health["quant"]["digest"]
        assert validate_health_report(health) == []
    finally:
        eng2.close(timeout=5)


def test_quant_attachment_validator_rejects_bad_stamp():
    from tmr_tpu.diagnostics import _validate_quant_attachment

    assert _validate_quant_attachment({}) == []
    ok = {"quant": {"mode": "int8", "storage": "int8", "digest": "ab",
                    "quantized_leaves": 4, "weight_bytes": 10,
                    "f32_weight_bytes": 40}}
    assert _validate_quant_attachment(ok) == []
    bad = {"quant": {"mode": "fp4", "storage": "int8"}}
    problems = _validate_quant_attachment(bad)
    assert any("mode" in p for p in problems)
    assert any("digest" in p for p in problems)


# ------------------------------------------------------- training scrub


def test_training_scrub_strips_storage_knobs():
    """main.py's training invariant: stored-int8 trees are
    inference-only — both quant knobs scrub before a training trace."""
    import main as main_mod

    env = {"TMR_QUANT": "int8", "TMR_QUANT_STORAGE": "int8",
           "TMR_DECODER_IMPL": "fused"}
    scrubbed = main_mod.scrub_training_env(env)
    assert sorted(scrubbed) == ["TMR_QUANT", "TMR_QUANT_STORAGE"]
    assert env["TMR_QUANT"] == "off"
    assert env["TMR_QUANT_STORAGE"] == "off"
    assert env["TMR_DECODER_IMPL"] == "fused"  # gradient-valid, kept
    assert main_mod.scrub_training_env({"TMR_QUANT": "off"}) == []


def test_training_step_params_never_int8(tiny_pred, monkeypatch):
    """Even with the storage knobs exported (pre-scrub worst case), the
    training side's param tree holds no int8 leaf — storage lives only
    inside Predictor program builds."""
    monkeypatch.setenv("TMR_QUANT", "int8")
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    from tmr_tpu.train.state import create_train_state

    state = create_train_state(
        tiny_pred.model, _tiny_cfg(), jax.random.key(0),
        jnp.zeros((1, 64, 64, 3), jnp.float32),
        jnp.array([[[0.4, 0.4, 0.6, 0.6]]], jnp.float32),
    )
    dtypes = {str(x.dtype) for x in jax.tree.leaves(state.params)}
    assert "int8" not in dtypes


# ------------------------------------------------ pallas int8 kernel


def test_pallas_int8_matmul_interpret_matches_xla():
    from tmr_tpu.ops.pallas_int8 import int8_matmul

    rng = np.random.default_rng(0)
    xq = jnp.asarray(rng.integers(-127, 128, (200, 300)), jnp.int8)
    wq = jnp.asarray(rng.integers(-127, 128, (300, 70)), jnp.int8)
    sx = jnp.asarray(rng.random((200, 1)) * 0.01 + 1e-4, jnp.float32)
    sw = jnp.asarray(rng.random((1, 70)) * 0.01 + 1e-4, jnp.float32)
    got = np.asarray(int8_matmul(xq, wq, sx, sw, interpret=True))
    want = np.asarray(
        jax.lax.dot_general(xq, wq, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.int32
                            ).astype(jnp.float32) * (sx * sw)
    )
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


def test_pallas_int8_gate_refuses_off_tpu_with_cause():
    from tmr_tpu.ops import pallas_int8 as pi8

    pi8._OK_CACHE.clear()
    assert not pi8.pallas_int8_ok()
    causes = drain_gate_refusals()
    assert causes and causes[-1]["gate"] == "pallas_int8_ok"
    assert causes[-1]["cause"] in ("backend", "exception")


def test_stored_int8dot_arm_within_tolerance(monkeypatch):
    """TMR_QUANT_KERNEL=int8dot through the jitted stage program: int8
    operands both sides, inside the output tier of the fake path."""
    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    monkeypatch.setenv("TMR_QUANT", "int8")
    from tmr_tpu.utils.stage_bench import build_decoder_tail_step

    step_f, inp = build_decoder_tail_step(1, 8, 16, 1, 3, "float32",
                                          seed=7)
    (of, bf), _ = step_f(inp[0], jnp.zeros((), jnp.float32))
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    monkeypatch.setenv("TMR_QUANT_KERNEL", "int8dot")
    step_i, inp2 = build_decoder_tail_step(1, 8, 16, 1, 3, "float32",
                                           seed=7)
    (oi, bi), _ = step_i(inp2[0], jnp.zeros((), jnp.float32))
    scale = float(jnp.max(jnp.abs(of))) + 1e-9
    rel = float(jnp.max(jnp.abs(oi - of))) / scale
    assert 0 < rel < q.OUTPUT_TIER_REL


@pytest.mark.slow
def test_quant_storage_bitwise_production_geometry(monkeypatch):
    """The production pin: the jitted decoder-tail stage at the real
    128^2 x 1024 geometry (emb 512, fusion) — stored int8 tree bitwise
    the fake-quant program."""
    monkeypatch.setenv("TMR_DECODER_IMPL", "fused")
    monkeypatch.setenv("TMR_QUANT", "int8")
    from tmr_tpu.utils.stage_bench import build_decoder_tail_step

    step_f, inp = build_decoder_tail_step(1, 128, 1024, 1, 3, "float32")
    (of, bf), _ = step_f(inp[0], jnp.zeros((), jnp.float32))
    monkeypatch.setenv("TMR_QUANT_STORAGE", "int8")
    step_s, inp2 = build_decoder_tail_step(1, 128, 1024, 1, 3, "float32")
    (os_, bs), _ = step_s(inp2[0], jnp.zeros((), jnp.float32))
    assert bool(jnp.array_equal(of, os_))
    assert bool(jnp.array_equal(bf, bs))
    # and the equality-tier gate itself admits the production geometry
    assert q.quant_storage_ok(128, 128, 1024, 1024, 1, 3)
