"""Checkpoint conversion fidelity for the full detector
(utils/convert.py:convert_matching_net — the Lightning `model.*` state_dict
layout of reference trainer.py:21 / matching_net.py)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

from tmr_tpu.models.matching_net import MatchingNet
from tmr_tpu.models.vit import SamViT
from tmr_tpu.utils.convert import convert_matching_net

EMB = 16  # tiny embed dims, reference layout
DEPTH = 2
HEADS = 2
C_OUT = 8  # backbone neck channels
PROJ = 12  # emb_dim of the detector



pytestmark = pytest.mark.slow  # multi-minute module: CI-only, excluded from the `-m fast` dev loop (VERDICT r4 #8)

def _tiny_reference_state_dict(rng):
    """A Lightning-style `model.*` state_dict with the reference's module
    paths, tiny shapes (grid 4 => pretrain 64, patch 16)."""
    t = lambda *s: torch.tensor(rng.standard_normal(s), dtype=torch.float32)
    sd = {}
    bb = "encoder.backbone.backbone."
    sd[bb + "patch_embed.proj.weight"] = t(EMB, 3, 16, 16)
    sd[bb + "patch_embed.proj.bias"] = t(EMB)
    sd[bb + "pos_embed"] = t(1, 4, 4, EMB)
    hd = EMB // HEADS
    for i in range(DEPTH):
        b = f"{bb}blocks.{i}."
        sd[b + "norm1.weight"] = t(EMB)
        sd[b + "norm1.bias"] = t(EMB)
        sd[b + "norm2.weight"] = t(EMB)
        sd[b + "norm2.bias"] = t(EMB)
        sd[b + "attn.qkv.weight"] = t(3 * EMB, EMB)
        sd[b + "attn.qkv.bias"] = t(3 * EMB)
        sd[b + "attn.proj.weight"] = t(EMB, EMB)
        sd[b + "attn.proj.bias"] = t(EMB)
        # windowed blocks use the window grid; global the native grid — the
        # converter copies whatever lengths the checkpoint has
        size = 4 if i == 1 else 2
        sd[b + "attn.rel_pos_h"] = t(2 * size - 1, hd)
        sd[b + "attn.rel_pos_w"] = t(2 * size - 1, hd)
        sd[b + "mlp.lin1.weight"] = t(4 * EMB, EMB)
        sd[b + "mlp.lin1.bias"] = t(4 * EMB)
        sd[b + "mlp.lin2.weight"] = t(EMB, 4 * EMB)
        sd[b + "mlp.lin2.bias"] = t(EMB)
    sd[bb + "neck.0.weight"] = t(C_OUT, EMB, 1, 1)
    sd[bb + "neck.1.weight"] = t(C_OUT)
    sd[bb + "neck.1.bias"] = t(C_OUT)
    sd[bb + "neck.2.weight"] = t(C_OUT, C_OUT, 3, 3)
    sd[bb + "neck.3.weight"] = t(C_OUT)
    sd[bb + "neck.3.bias"] = t(C_OUT)

    sd["input_proj.0.weight"] = t(PROJ, C_OUT, 1, 1)
    sd["input_proj.0.bias"] = t(PROJ)
    sd["matcher.scale"] = t(1)
    d = 2 * PROJ  # fusion doubles the decoder width, kept through the convs
    for dec in ("decoder_o", "decoder_b"):
        sd[f"{dec}.layer.0.weight"] = t(d, d, 3, 3)
        sd[f"{dec}.layer.0.bias"] = t(d)
    sd["objectness_head.head.0.weight"] = t(1, d, 1, 1)
    sd["objectness_head.head.0.bias"] = t(1)
    sd["ltrbs_head.head.0.weight"] = t(4, d, 1, 1)
    sd["ltrbs_head.head.0.bias"] = t(4)
    return {f"model.{k}": v for k, v in sd.items()}


def _tiny_model():
    return MatchingNet(
        backbone=SamViT(
            embed_dim=EMB, depth=DEPTH, num_heads=HEADS,
            global_attn_indexes=(1,), window_size=2, out_chans=C_OUT,
            pretrain_img_size=64,
        ),
        emb_dim=PROJ, fusion=True, template_capacity=5,
    )


def test_converted_tree_matches_init_structure():
    rng = np.random.default_rng(0)
    sd = {k: v.numpy() for k, v in _tiny_reference_state_dict(rng).items()}
    params = convert_matching_net(sd, backbone="sam")

    model = _tiny_model()
    want = model.init(
        jax.random.key(0), jnp.zeros((1, 64, 64, 3), jnp.float32),
        jnp.array([[[0.3, 0.3, 0.6, 0.6]]], jnp.float32),
    )["params"]

    flat_got = {
        "/".join(k): v.shape
        for k, v in jax.tree_util.tree_leaves_with_path(params)
        for k in [[str(p.key) for p in k]]
    }
    flat_want = {
        "/".join(k): v.shape
        for k, v in jax.tree_util.tree_leaves_with_path(want)
        for k in [[str(p.key) for p in k]]
    }
    assert flat_got == flat_want


def test_converted_params_run_and_respect_weights():
    rng = np.random.default_rng(1)
    torch_sd = _tiny_reference_state_dict(rng)
    sd = {k: v.numpy() for k, v in torch_sd.items()}
    params = convert_matching_net(sd, backbone="sam")
    model = _tiny_model()

    img = jnp.asarray(rng.standard_normal((1, 64, 64, 3)), jnp.float32)
    ex = jnp.array([[[0.3, 0.3, 0.6, 0.6]]], jnp.float32)
    out = model.apply({"params": params}, img, ex)
    assert np.all(np.isfinite(np.asarray(out["objectness"][0])))

    # spot-check weight placement: the patch embed conv kernel must be the
    # torch OIHW weight transposed to HWIO
    k = np.asarray(params["backbone"]["patch_embed"]["kernel"])
    np.testing.assert_allclose(
        k,
        torch_sd["model.encoder.backbone.backbone.patch_embed.proj.weight"]
        .numpy().transpose(2, 3, 1, 0),
    )
    np.testing.assert_allclose(
        np.asarray(params["matcher"]["scale"]),
        torch_sd["model.matcher.scale"].numpy(),
    )
    # square Linear weight: the (out, in) -> (in, out) transpose must be
    # applied (a missing transpose would be shape-invisible here)
    np.testing.assert_allclose(
        np.asarray(params["backbone"]["blocks_0"]["attn"]["proj"]["kernel"]),
        torch_sd["model.encoder.backbone.backbone.blocks.0.attn.proj.weight"]
        .numpy().T,
    )


def test_convert_cli_roundtrip(tmp_path):
    """python -m tmr_tpu.utils.convert: .ckpt in, loadable orbax out, layout
    auto-sniffed (the migration entry point for reference users)."""
    import torch

    import orbax.checkpoint as ocp

    from tmr_tpu.utils import convert as cv

    ckpt = tmp_path / "best_model.ckpt"
    torch.save(
        {"state_dict": _tiny_reference_state_dict(np.random.default_rng(0))},
        ckpt,
    )
    out = tmp_path / "orbax"
    cv.main(["--ckpt", str(ckpt), "--out", str(out)])

    restored = ocp.StandardCheckpointer().restore(str(out))
    want = cv.convert_matching_net(
        {k: v.numpy() for k, v in _tiny_reference_state_dict(
            np.random.default_rng(0)).items()}
    )
    from flax import traverse_util

    got_flat = {
        "/".join(k): v
        for k, v in traverse_util.flatten_dict(restored["params"]).items()
    }
    want_flat = {
        "/".join(k): v for k, v in traverse_util.flatten_dict(want).items()
    }
    assert set(got_flat) == set(want_flat)
    for k in want_flat:
        np.testing.assert_array_equal(got_flat[k], want_flat[k])
