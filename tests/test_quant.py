"""int8-weight quantization (ops/quant.py, TMR_QUANT): the round-trip
bound the weights tier pins, the tiered oracle gate's verdicts + recorded
causes, and the matcher-arm integration through ops/xcorr.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tmr_tpu.diagnostics import (
    FormulationFallbackWarning,
    drain_gate_refusals,
)
from tmr_tpu.ops import quant as q


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for k in ("TMR_QUANT", "TMR_DECODER_IMPL", "TMR_XCORR_IMPL",
              "TMR_XCORR_IMPL_SMALL", "TMR_XCORR_PRECISION"):
        monkeypatch.delenv(k, raising=False)
    q._OK_CACHE.clear()
    drain_gate_refusals()
    yield
    q._OK_CACHE.clear()
    drain_gate_refusals()


def test_quantize_int8_round_trip_within_grid_bound():
    """Per-channel symmetric int8: reconstruction error <= scale/2 per
    element, i.e. half of 1/127 of the channel amax — the analytic bound
    the weights tier enforces."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((3, 3, 8, 16)), jnp.float32)
    qw, scale = q.quantize_int8(w, axis=-1)
    assert qw.dtype == jnp.int8
    assert scale.shape == (3, 3, 8, 1)
    rec = np.asarray(q.dequantize(qw, scale, dtype=jnp.float32))
    err = np.abs(rec - np.asarray(w))
    bound = np.asarray(scale) / 2 + 1e-7
    assert (err <= bound).all()
    assert int(np.abs(np.asarray(qw)).max()) <= 127


def test_quantize_int8_zero_channel_is_exact():
    w = jnp.zeros((2, 4), jnp.float32)
    qw, scale = q.quantize_int8(w)
    assert np.asarray(q.dequantize(qw, scale, jnp.float32)).max() == 0.0
    assert (np.asarray(scale) == 1.0).all()  # not 0/0


def test_fake_quant_is_quantize_then_dequantize():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    qw, s = q.quantize_int8(w)
    np.testing.assert_array_equal(
        np.asarray(q.fake_quant(w, dtype=jnp.float32)),
        np.asarray(q.dequantize(qw, s, jnp.float32)),
    )


def test_quant_mode_validates_and_auto_means_off(monkeypatch):
    assert q.quant_mode() == "off"
    monkeypatch.setenv("TMR_QUANT", "auto")
    assert q.quant_mode() == "off"  # unelected auto must never quantize
    monkeypatch.setenv("TMR_QUANT", "int8")
    assert q.quant_mode() == "int8"
    monkeypatch.setenv("TMR_QUANT", "fp4")
    with pytest.raises(ValueError, match="TMR_QUANT"):
        q.quant_mode()


def test_quant_ok_passes_and_caches_small_geometry():
    assert q.quant_ok(8, 8, 16, 16, num_layers=1, kernel_size=3)
    assert drain_gate_refusals() == []
    n = len(q._OK_CACHE)
    assert q.quant_ok(8, 8, 16, 16, num_layers=1, kernel_size=3)
    assert len(q._OK_CACHE) == n


def test_quant_ok_channel_changing_first_layer_multi_depth():
    """c_in != c with num_layers > 1: only the first kernel sees c_in
    (the stacks are channel-preserving past layer 0) — the gate must
    model that instead of crashing and mis-recording a refusal."""
    assert q.quant_ok(8, 8, 8, 16, num_layers=2, kernel_size=3)
    assert drain_gate_refusals() == []


def test_quant_ok_weights_tier_refusal_is_cached(monkeypatch):
    """A weights-tier refusal must cache its verdict like every other
    outcome: the gate runs at every bucket trace, and an uncached
    negative would re-run the compiled probe and append a duplicate
    refusal record each time."""
    monkeypatch.setattr(q, "WEIGHT_TIER_REL", -1.0)
    assert not q.quant_ok(9, 9, 16, 16)
    causes = drain_gate_refusals()
    assert len(causes) == 1 and causes[0]["config"]["tier"] == "weights"
    assert not q.quant_ok(9, 9, 16, 16)  # cached: no re-probe,
    assert drain_gate_refusals() == []   # no duplicate cause


def test_quant_ok_output_tier_refusal_records_cause(monkeypatch):
    """Force the output tier to fail (zero tolerance): the refusal must
    carry the gate name, the forward-mismatch cause, and which tier."""
    monkeypatch.setattr(q, "OUTPUT_TIER_REL", 0.0)
    assert not q.quant_ok(8, 8, 16, 16)
    causes = drain_gate_refusals()
    assert causes and causes[-1]["gate"] == "quant_ok"
    assert causes[-1]["cause"] == "forward-mismatch"
    assert causes[-1]["config"]["tier"] == "output"


def test_quant_xcorr_ok_small_geometry():
    assert q.quant_xcorr_ok(8, 12, 12, 5)
    assert drain_gate_refusals() == []


def test_quantize_template_shape_and_error_bound():
    rng = np.random.default_rng(2)
    t = jnp.asarray(rng.standard_normal((2, 8, 5, 5)), jnp.float32)
    tq = q.quantize_template(t, dtype=jnp.float32)
    assert tq.shape == t.shape
    # per-(image, channel) bound: half-step of amax/127 plus fp slack
    amax = np.abs(np.asarray(t)).reshape(2, 8, 25).max(-1)
    err = np.abs(np.asarray(tq) - np.asarray(t)).reshape(2, 8, 25).max(-1)
    assert (err <= amax / 127.0 + 1e-6).all()


def test_xcorr_quant_arm_close_to_exact(monkeypatch):
    """TMR_QUANT=int8 through cross_correlation: same shape, within the
    output-tier tolerance of the exact correlation; off -> bitwise the
    exact path."""
    from tmr_tpu.ops.xcorr import cross_correlation

    rng = np.random.default_rng(3)
    f = jnp.asarray(rng.standard_normal((1, 8, 12, 12)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((1, 8, 5, 5)), jnp.float32)
    thw = jnp.full((1, 2), 5, jnp.int32)
    want = np.asarray(cross_correlation(f, t, thw), np.float32)
    monkeypatch.setenv("TMR_QUANT", "int8")
    got = np.asarray(cross_correlation(f, t, thw), np.float32)
    assert got.shape == want.shape
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < q.OUTPUT_TIER_REL


def test_xcorr_quant_refusal_warns_and_runs_exact(monkeypatch):
    """A refused quant_xcorr_ok must fall back to the exact correlation
    under the FormulationFallbackWarning contract."""
    import tmr_tpu.ops.xcorr as xc

    rng = np.random.default_rng(4)
    f = jnp.asarray(rng.standard_normal((1, 4, 10, 10)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((1, 4, 5, 5)), jnp.float32)
    thw = jnp.full((1, 2), 5, jnp.int32)
    want = np.asarray(xc.cross_correlation(f, t, thw), np.float32)
    monkeypatch.setenv("TMR_QUANT", "int8")
    monkeypatch.setattr(q, "quant_xcorr_ok", lambda *a: False)
    with pytest.warns(FormulationFallbackWarning) as rec:
        got = np.asarray(xc.cross_correlation(f, t, thw), np.float32)
    assert rec[0].message.env_var == "TMR_QUANT"
    np.testing.assert_array_equal(got, want)


def test_fused_tail_quant_within_output_tier():
    """fused_decoder_heads(quant=True) stays inside OUTPUT_TIER_REL of
    its exact-weight output — the end-to-end error inference pays is the
    error the gate pinned."""
    from tmr_tpu.ops.fused_heads import fused_decoder_heads

    rng = np.random.default_rng(5)
    c = 16
    x = jnp.asarray(rng.standard_normal((1, 8, 8, c)), jnp.float32)
    mk = lambda seed: (
        jnp.asarray(rng.standard_normal((3, 3, c, c)) * 0.05, jnp.float32),
        jnp.asarray(rng.standard_normal((c,)) * 0.01, jnp.float32),
    )
    dec_o, dec_b = [mk(0)], [mk(1)]
    ho = (jnp.asarray(rng.standard_normal((1, 1, c, 1)) * 0.05,
                      jnp.float32), jnp.zeros((1,), jnp.float32))
    hb = (jnp.asarray(rng.standard_normal((1, 1, c, 4)) * 0.05,
                      jnp.float32), jnp.zeros((4,), jnp.float32))
    o_e, r_e = fused_decoder_heads(x, dec_o, dec_b, ho, hb,
                                   dtype=jnp.float32, quant=False)
    o_q, r_q = fused_decoder_heads(x, dec_o, dec_b, ho, hb,
                                   dtype=jnp.float32, quant=True)
    scale = max(float(jnp.max(jnp.abs(o_e))), float(jnp.max(jnp.abs(r_e))),
                1e-6)
    rel = max(float(jnp.max(jnp.abs(o_q - o_e))),
              float(jnp.max(jnp.abs(r_q - r_e)))) / scale
    assert 0 < rel < q.OUTPUT_TIER_REL  # quantized (changed) but bounded
