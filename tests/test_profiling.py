"""Profiling/tracing subsystem (tmr_tpu/utils/profiling.py).

The reference has no profiler (SURVEY §5.1); these tests pin down the
subsystem we add: phase timers, trace capture producing on-disk artifacts,
annotations composing with jit, and the reducer.py-compatible stderr
protocol."""

import pytest

import os

import jax
import jax.numpy as jnp

from tmr_tpu.utils.profiling import (
    PhaseTimer,
    annotate,
    log_info,
    log_progress,
    log_warning,
    step_annotation,
    trace,
)


def test_phase_timer_accumulates():
    t = PhaseTimer()
    for _ in range(3):
        with t.phase("a"):
            pass
    with t.phase("b"):
        pass
    assert t.counts["a"] == 3 and t.counts["b"] == 1
    assert t.totals["a"] >= 0.0
    d = t.as_dict()
    assert set(d) == {"time/a", "time/b"}
    rep = t.report()
    assert "PHASE" in rep and "a" in rep and "MEAN_MS" in rep
    t.reset()
    assert not t.totals and not t.counts


def test_trace_capture_writes_artifacts(tmp_path):
    logdir = str(tmp_path / "prof")
    with trace(logdir):
        with annotate("matmul_region"):
            x = jnp.ones((64, 64))
            y = (x @ x).block_until_ready()
        with step_annotation("step", 0):
            (x + 1).block_until_ready()
    assert y is not None
    # jax.profiler.trace writes plugins/profile/<run>/*.{trace.json.gz,xplane.pb}
    found = []
    for root, _, files in os.walk(logdir):
        found.extend(files)
    assert found, "profiler trace produced no artifacts"


def test_trace_none_is_noop():
    with trace(None):
        pass
    with trace(""):
        pass


def test_annotations_compose_with_jit():
    @jax.jit
    def f(x):
        return x * 2

    with annotate("jitted"):
        out = f(jnp.arange(8.0))
    assert out.shape == (8,)


def test_stderr_protocol_format(capsys):
    log_info("hello")
    log_warning("careful")
    log_progress("3/10")
    err = capsys.readouterr().err
    assert "[INFO] hello" in err
    assert "[WARNING] careful" in err
    assert "[PROGRESS] 3/10" in err


@pytest.mark.slow
def test_xprof_top_ops_extracts_dominant_op(tmp_path):
    """scripts/xprof_top_ops.py parses a jax.profiler trace without
    TensorBoard and ranks ops by device time — on the CPU test backend the
    op events land on the host plane (the tool's documented fallback), and
    a repeated 512x512 matmul must dominate the table."""
    import json
    import subprocess
    import sys

    import numpy as np

    gen = (
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import jax.numpy as jnp, numpy as np;"
        "x = jnp.asarray(np.random.default_rng(0).standard_normal((512,512)),"
        " jnp.float32);"
        "f = jax.jit(lambda a: jnp.tanh(a @ a).sum()); f(x);"
        "import jax.profiler;"
        "ctx = jax.profiler.trace(r'%s');"
        "ctx.__enter__();"
        "[f(x).block_until_ready() for _ in range(5)];"
        "ctx.__exit__(None, None, None)" % str(tmp_path / "trace")
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ""}
    r = subprocess.run([sys.executable, "-c", gen], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "xprof_top_ops.py"),
         str(tmp_path / "trace"), "5"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["total_ms"] > 0
    assert rec["top_ops"], rec
    names = " ".join(op["name"] for op in rec["top_ops"])
    assert "dot" in names, names
    assert abs(sum(o["pct"] for o in rec["top_ops"]) ) <= 100.5
