// tmr_io: native shard-streaming runtime for the data pipeline.
//
// The reference's inference pipeline moves data with `hadoop fs -get` +
// Python tarfile + PIL inside a single-threaded mapper process
// (reference mapper.py:71-98); its training input path is torch DataLoader
// worker *processes*. This library is the TPU framework's native IO layer:
// a C++ thread pool streams tar shards from POSIX storage (NFS/FUSE/local —
// the HDFS-get replacement), parses ustar headers inline, and hands file
// payloads to Python through a bounded lock-free-ish queue via ctypes —
// overlap of storage IO + tar parsing with device compute, without Python
// threads contending on the GIL for the byte-shuffling half of the work.
//
// C ABI (consumed by tmr_tpu/data/native_io.py):
//   handle = tmr_io_open(paths, n_paths, n_threads, queue_cap)
//   rc = tmr_io_next(handle, &item)   // 1 = item, 0 = end of stream
//   tmr_io_free_item(&item)
//   tmr_io_close(handle)
//   tmr_io_error(handle)              // count of unreadable shards (skipped)
//
// Build: see native/Makefile (g++ -O2 -shared -fPIC -pthread).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Item {
  char* name;        // malloc'd, NUL-terminated member path
  uint8_t* data;     // malloc'd payload
  int64_t size;      // payload bytes
  int32_t shard;     // index into the paths array this member came from
};

struct Queue {
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  std::deque<Item> items;
  size_t cap;
  int producers_left;  // when 0 and empty -> end of stream
  bool stopped = false;  // early close: producers must not block (guarded by mu)

  explicit Queue(size_t cap_, int producers) : cap(cap_), producers_left(producers) {}

  void push(Item it) {
    std::unique_lock<std::mutex> lk(mu);
    // early-close safety: with n_threads > cap, every worker can be parked
    // in this wait with no consumer left — shutdown() must wake them, and a
    // post-shutdown push drops its item instead of enqueueing
    not_full.wait(lk, [&] { return items.size() < cap || stopped; });
    if (stopped) {
      lk.unlock();
      free(it.name);
      free(it.data);
      return;
    }
    items.push_back(it);
    not_empty.notify_one();
  }

  // 1 = got item, 0 = stream finished
  int pop(Item* out) {
    std::unique_lock<std::mutex> lk(mu);
    not_empty.wait(lk,
                   [&] { return !items.empty() || producers_left == 0 || stopped; });
    if (items.empty()) return 0;
    *out = items.front();
    items.pop_front();
    not_full.notify_one();
    return 1;
  }

  void shutdown() {  // wake all waiters for early close
    std::unique_lock<std::mutex> lk(mu);
    stopped = true;
    not_full.notify_all();
    not_empty.notify_all();
  }

  void producer_done() {
    std::unique_lock<std::mutex> lk(mu);
    if (--producers_left == 0) not_empty.notify_all();
  }

  void drain() {  // free anything unconsumed (early close)
    std::unique_lock<std::mutex> lk(mu);
    for (auto& it : items) {
      free(it.name);
      free(it.data);
    }
    items.clear();
    not_full.notify_all();
  }
};

// Parse the 12-byte octal (or base-256) tar size field.
int64_t tar_size(const unsigned char* f) {
  if (f[0] & 0x80) {  // GNU base-256 extension
    int64_t v = f[0] & 0x7f;
    for (int i = 1; i < 12; i++) v = (v << 8) | f[i];
    return v;
  }
  int64_t v = 0;
  for (int i = 0; i < 12 && f[i]; i++) {
    if (f[i] < '0' || f[i] > '7') continue;
    v = v * 8 + (f[i] - '0');
  }
  return v;
}

bool header_zero(const unsigned char* h) {
  for (int i = 0; i < 512; i++)
    if (h[i]) return false;
  return true;
}

struct Stream {
  std::vector<std::string> paths;
  std::atomic<int> next_shard{0};
  std::atomic<int> errors{0};
  std::atomic<bool> stop{false};
  Queue queue;
  std::vector<std::thread> workers;

  Stream(std::vector<std::string> p, int n_threads, size_t cap)
      : paths(std::move(p)), queue(cap, n_threads) {
    for (int t = 0; t < n_threads; t++)
      workers.emplace_back([this] { this->run(); });
  }

  void run() {
    for (;;) {
      int idx = next_shard.fetch_add(1);
      if (idx >= (int)paths.size() || stop.load()) break;
      if (!read_shard(idx)) errors.fetch_add(1);
    }
    queue.producer_done();
  }

  // Parse a PAX extended header block ("<len> <key>=<value>\n" records)
  // for a path override.
  static std::string pax_path(const uint8_t* buf, int64_t size) {
    std::string out;
    int64_t pos = 0;
    while (pos < size) {
      int64_t len = 0, p = pos;
      while (p < size && buf[p] >= '0' && buf[p] <= '9')
        len = len * 10 + (buf[p++] - '0');
      if (p >= size || buf[p] != ' ' || len <= 0 || pos + len > size) break;
      std::string rec((const char*)buf + p + 1, (size_t)(len - (p + 1 - pos)));
      if (rec.rfind("path=", 0) == 0) {
        out = rec.substr(5);
        if (!out.empty() && out.back() == '\n') out.pop_back();
      }
      pos += len;
    }
    return out;
  }

  bool read_shard(int idx) {
    FILE* f = fopen(paths[idx].c_str(), "rb");
    if (!f) return false;
    unsigned char hdr[512];
    bool ok = true;
    std::string override_name;  // from GNU 'L' or PAX 'x' records
    while (!stop.load()) {
      if (fread(hdr, 1, 512, f) != 512) break;
      if (header_zero(hdr)) break;  // end-of-archive marker
      int64_t size = tar_size(hdr + 124);
      if (size < 0 || size > (int64_t(1) << 40)) {  // corrupt size field
        ok = false;
        break;
      }
      char type = hdr[156];
      // member path: prefix (ustar) + name
      char name[257];
      size_t off = 0;
      if (memcmp(hdr + 257, "ustar", 5) == 0 && hdr[345]) {
        size_t pl = strnlen((char*)hdr + 345, 155);
        memcpy(name, hdr + 345, pl);
        name[pl] = '/';
        off = pl + 1;
      }
      size_t nl = strnlen((char*)hdr, 100);
      memcpy(name + off, hdr, nl);
      name[off + nl] = 0;

      int64_t padded = (size + 511) & ~511LL;
      if (type == 'L' || type == 'x' || type == 'g') {
        // long-name / extended-header records modify the NEXT member
        uint8_t* buf = (uint8_t*)malloc(size > 0 ? size : 1);
        if (!buf || (int64_t)fread(buf, 1, size, f) != size) {
          free(buf);
          ok = false;
          break;
        }
        if (fseek(f, padded - size, SEEK_CUR) != 0) { free(buf); ok = false; break; }
        if (type == 'L') {
          override_name.assign((char*)buf, strnlen((char*)buf, size));
        } else if (type == 'x') {
          std::string p = pax_path(buf, size);
          if (!p.empty()) override_name = p;
        }
        free(buf);
        continue;
      }
      if (type == '0' || type == 0) {  // regular file
        uint8_t* data = (uint8_t*)malloc(size > 0 ? size : 1);
        if (!data || (int64_t)fread(data, 1, size, f) != size) {
          free(data);
          ok = false;
          break;
        }
        if (fseek(f, padded - size, SEEK_CUR) != 0) { free(data); ok = false; break; }
        Item it;
        it.name = strdup(override_name.empty() ? name : override_name.c_str());
        override_name.clear();
        it.data = data;
        it.size = size;
        it.shard = idx;
        queue.push(it);
      } else {
        override_name.clear();
        if (fseek(f, padded, SEEK_CUR) != 0) { ok = false; break; }
      }
    }
    fclose(f);
    return ok;
  }

  ~Stream() {
    stop.store(true);
    queue.shutdown();  // unblock any worker parked in push()
    queue.drain();
    for (auto& w : workers)
      if (w.joinable()) w.join();
    queue.drain();
  }
};

}  // namespace

extern "C" {

typedef struct {
  char* name;
  uint8_t* data;
  int64_t size;
  int32_t shard;
} tmr_io_item;

void* tmr_io_open(const char** paths, int n_paths, int n_threads,
                  int queue_cap) {
  std::vector<std::string> p(paths, paths + n_paths);
  if (n_threads < 1) n_threads = 1;
  if (queue_cap < 2) queue_cap = 2;
  return new Stream(std::move(p), n_threads, (size_t)queue_cap);
}

int tmr_io_next(void* handle, tmr_io_item* out) {
  Item it;
  if (!((Stream*)handle)->queue.pop(&it)) return 0;
  out->name = it.name;
  out->data = it.data;
  out->size = it.size;
  out->shard = it.shard;
  return 1;
}

void tmr_io_free_item(tmr_io_item* it) {
  free(it->name);
  free(it->data);
  it->name = nullptr;
  it->data = nullptr;
}

int tmr_io_error(void* handle) { return ((Stream*)handle)->errors.load(); }

void tmr_io_close(void* handle) { delete (Stream*)handle; }

}  // extern "C"
