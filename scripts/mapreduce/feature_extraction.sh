#!/usr/bin/env bash
# Streaming feature extraction over tar shards — the Hadoop Streaming job
# (`hadoop jar streaming … -mapper mapper.py -reducer reducer.py` over
# list_tars*.txt) collapsed to one TPU-accelerated pipeline. The mapper's
# HDFS get/put becomes a posix/NFS/FUSE --data_dir; the sort/shuffle is a
# dict aggregation (or an on-device psum over a mesh, see
# tmr_tpu.parallel.mapreduce.allreduce_stats).
#
# Usage: feature_extraction.sh LIST_FILE DATA_DIR [ARTIFACT]
set -euo pipefail
LIST=${1:?list_tars*.txt}
DATA_DIR=${2:?tar shard directory}
ARTIFACT=${3:-exported/sam_vit_b_encoder.stablehlo}
[ -f "$ARTIFACT" ] || python export_encoder.py --output "$ARTIFACT"
cat "$LIST" \
  | python -m tmr_tpu.parallel.mapreduce map \
      --data_dir "$DATA_DIR" --artifact "$ARTIFACT" \
      --features_out features_output \
  | sort \
  | python -m tmr_tpu.parallel.mapreduce reduce
