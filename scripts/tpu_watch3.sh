#!/usr/bin/env bash
# Session-7 recovery battery. Prior batteries measured the headline
# (21.065 img/s, blockfolded) and killed the ckpt anomaly; what remains is
# (a) WHY every pallas/flash kernel gate-refuses on the real chip — the
# answer decides whether the next 2x (global attention is still ~55% of
# the 190 ms batch) is a kernel fix or new XLA formulation work — and
# (b) the bench_extra BASELINE configs a concurrent-client wedge consumed.
# Order: cheapest + highest-information first.
#   1. gate_probe (TMR_GATE_DEBUG): per-gate refusal reasons + direct
#      kernel calls with full tracebacks
#   2. conditional: if the direct pallas-global run WORKED, re-bench the
#      headline under TMR_GLOBAL_ATTN=pallas (its gate may be what's wrong)
#   3. bench_extra remaining stages (batch_sweep,1536,refine,train,stream)
#   4. profile_breakdown under the MEASURED winner knobs (autotune.env)
#      with the RTT-adaptive chained timer (real decode/NMS tail numbers)
# Results land as working-tree files; the session driver commits.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${TMR_WATCH_OUT:-$REPO}"
LOG="${TMR_WATCH_LOG:-/tmp/tpu_watch3.log}"

log() { echo "[$(date +%H:%M:%S)] $*" >>"$LOG"; }

probe() {
  timeout 150 python -u -c "
import jax, jax.numpy as jnp
d = jax.devices()
assert d and d[0].platform != 'cpu', d
x = jnp.ones((256, 256), jnp.bfloat16)
print(jax.device_get(jax.jit(lambda a: (a @ a).astype(jnp.float32).sum())(x)))
" >>"$LOG" 2>&1
}

log "watch3 started (pid $$)"

while true; do
  if probe; then
    log "TPU ALIVE — running session-7 recovery battery"
    cd "$REPO"
    # 1: gate refusal diagnosis (small compiles, biggest unknown)
    timeout 1800 python scripts/gate_probe.py \
      >"$OUT/gate_probe.json" 2>"$OUT/gate_probe.err"
    log "gate_probe rc=$? -> $OUT/gate_probe.json"
    # 2: if the direct pallas-global kernel ran and agreed, the gate was
    # the problem — measure the kernel headline immediately
    if grep -q '"probe": "pallas_global_direct", "ok": true' \
        "$OUT/gate_probe.json" 2>/dev/null; then
      TMR_GLOBAL_ATTN=pallas TMR_BENCH_ALARM=2700 timeout 3000 \
        python bench.py >"$OUT/bench_pallas2.json" 2>>"$LOG"
      log "bench (pallas, post-diagnosis) rc=$? -> $OUT/bench_pallas2.json"
    fi
    # 3: the BASELINE configs the wedge consumed
    timeout 5400 python scripts/bench_extra.py \
      --only batch_sweep,1536,refine,train,stream \
      >"$OUT/bench_extra_live.json" 2>>"$LOG"
    log "bench_extra (rest) rc=$? -> $OUT/bench_extra_live.json"
    if grep -q '"' "$OUT/bench_extra_live.json" 2>/dev/null \
        && ! grep -q '"error"' "$OUT/bench_extra_live.json" 2>/dev/null; then
      cp "$OUT/bench_extra_live.json" "$REPO/BENCH_EXTRA_LIVE.json" \
        2>/dev/null
    fi
    # 4: post-fix attribution under the measured winners
    tuned=""
    [ -f "$OUT/autotune.env" ] \
      && tuned=$(grep -v '^#' "$OUT/autotune.env" | xargs)
    env $tuned timeout 5400 python scripts/profile_breakdown.py \
      >"$OUT/profile_live.json" 2>>"$LOG"
    log "profile_breakdown (winner knobs) rc=$? -> $OUT/profile_live.json"
    if ! grep -q '"error"' "$OUT/profile_live.json" 2>/dev/null \
        && grep -q '"full_program"' "$OUT/profile_live.json" 2>/dev/null; then
      cp "$OUT/profile_live.json" "$REPO/PROFILE_LIVE.json" 2>/dev/null
    fi
    log "battery done"
    break
  fi
  log "probe failed; sleeping 600s"
  sleep 600
done
