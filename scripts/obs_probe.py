"""Telemetry probe: proves the obs subsystem end to end and prints ONE
``trace_report/v1`` JSON document (schema + validator in
tmr_tpu/diagnostics.py).

What it runs and what it asserts:

- **serve pipeline tracing** — a tiny ServeEngine workload with
  ``TMR_TRACE`` off (the overhead baseline) and then on: every request
  must show all seven pipeline stages as spans (submit -> queue wait ->
  batch assembly -> staging -> execute -> postprocess -> resolution)
  carrying one consistent per-request trace ID.
- **compile-event accounting** — the workload's program compiles must
  each record an event (kind, compile key, wall seconds, cold vs
  key-change) in the process registry.
- **map-phase tracing** — a 3-shard synthetic extraction with one
  injected transient fault: attempt/backoff spans, retry counters, and a
  ``map_report/v1`` document carrying the registry snapshot.
- **overhead** — the disabled-mode cost of span enter/exit, measured in
  ns and projected against the workload's per-request latency; the check
  requires < 1% (the "truly zero-cost when TMR_TRACE=0" contract).
- **export** — the Chrome trace JSON (Perfetto-loadable) must round-trip
  ``json.loads`` with every span present.

Usage:  python scripts/obs_probe.py [--tiny] [--out FILE] [--trace-out FILE]

``--tiny`` (or TMR_BENCH_TINY=1) runs the CPU smoke geometry tier-1 uses
(tests/test_obs_probe.py); real numbers use the deployment geometry.
Same one-JSON-line contract as bench.py via the shared bench_guard.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tarfile
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()


def _progress(msg: str) -> None:
    print(f"[obs_probe] {msg}", file=sys.stderr, flush=True)


def _percentiles_ms(durs_s) -> dict:
    if not durs_s:
        return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
    arr = np.asarray(durs_s) * 1000.0
    return {
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p95_ms": round(float(np.percentile(arr, 95)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
    }


def _stage_table(spans, prefix: str) -> dict:
    """{stage name: {count, p50/p95/p99 ms}} over span durations."""
    by_name: dict = {}
    for rec in spans:
        if rec["name"].startswith(prefix):
            by_name.setdefault(rec["name"], []).append(rec["dur"])
    return {
        name: {"count": len(durs), **_percentiles_ms(durs)}
        for name, durs in sorted(by_name.items())
    }


def _measure_disabled_span_ns(iters: int = 50_000) -> float:
    """Amortized enter/exit cost of a span with TMR_TRACE=0 (ns)."""
    from tmr_tpu import obs

    assert not obs.tracing_enabled()
    span = obs.span
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            with span("overhead_probe"):
                pass
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e9


def _make_tar(dirpath: str, name: str, n_images: int, seed: int) -> str:
    from PIL import Image

    rng = np.random.default_rng(seed)
    path = os.path.join(dirpath, name)
    with tarfile.open(path, "w") as tar:
        for i in range(n_images):
            img = Image.fromarray(
                rng.integers(0, 255, (24, 24, 3), dtype=np.uint8)
            )
            buf = io.BytesIO()
            img.save(buf, format="PNG")
            data = buf.getvalue()
            info = tarfile.TarInfo(f"img_{i}.png")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    return path


def _serve_closed_loop(engine, requests):
    """Submit all, await all; returns elapsed seconds."""
    t0 = time.perf_counter()
    futs = [engine.submit(img, ex) for img, ex in requests]
    for f in futs:
        f.result(timeout=600)
    return time.perf_counter() - t0


def _run_map_workload(size: int) -> dict:
    """3 synthetic shards + one injected transient fault through
    run_stream; returns the map_report/v1 document (metrics attached)."""
    import jax

    from tmr_tpu.parallel.mapreduce import (
        MapReport,
        RetryPolicy,
        feature_stats,
        run_stream,
    )
    from tmr_tpu.utils import faults

    @jax.jit
    def encode(images):  # stand-in encoder: the probe measures telemetry,
        feats = images[:, ::4, ::4, :] - 0.5  # not the model
        return feats, feature_stats(feats)

    with tempfile.TemporaryDirectory(prefix="obs_probe_") as work:
        paths = [
            _make_tar(work, name, n, seed=i)
            for i, (name, n) in enumerate(
                (("Easy_0.tar", 3), ("Normal_0.tar", 2), ("Hard_0.tar", 2))
            )
        ]
        report = MapReport()
        # one transient fault: shard 1's first load attempt dies, the
        # retry succeeds — exercising the attempt/backoff spans and the
        # map.retries counter deterministically
        faults.configure("tar.open:shard=1:attempts=1:raise=OSError")
        try:
            run_stream(
                paths, encode, batch_size=2, image_size=size,
                feeder_threads=2,
                retry=RetryPolicy(max_attempts=3, shard_timeout=5.0,
                                  backoff_base=0.01, backoff_jitter=0.0),
                report=report,
            )
        finally:
            faults.clear()
    return report.document()


def _run(cancel_watchdog, argv=None) -> int:
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke geometry (also TMR_BENCH_TINY=1)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    ap.add_argument("--trace-out", default=None,
                    help="write the Chrome trace JSON (Perfetto) here")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--requests", type=int, default=None)
    args = ap.parse_args(argv)

    tiny = args.tiny or os.environ.get("TMR_BENCH_TINY", "") not in (
        "", "0", "false"
    )
    size = int(os.environ.get("TMR_BENCH_SIZE", 128 if tiny else 1024))
    dtype = "float32" if tiny else "bfloat16"
    n_req = args.requests or (2 * args.batch + 2)

    import jax

    from tmr_tpu import obs
    from tmr_tpu.config import preset
    from tmr_tpu.diagnostics import (
        TRACE_REPORT_SCHEMA,
        TRACE_SERVE_STAGES,
        validate_map_report,
        validate_trace_report,
    )
    from tmr_tpu.inference import Predictor
    from tmr_tpu.serve import ServeEngine

    _progress(f"backend: {jax.devices()[0]} size={size} tiny={tiny}")

    # ---- disabled-mode overhead first, before anything enables tracing
    obs.configure(enabled=False)
    disabled_ns = _measure_disabled_span_ns()
    _progress(f"disabled span enter/exit: {disabled_ns:.0f} ns")

    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=size,
                 compute_dtype=dtype, batch_size=1)
    pred = Predictor(cfg)
    _progress("init_params (jitted init)")
    pred.init_params(seed=0, image_size=size)

    ex = np.asarray([[0.45, 0.45, 0.53, 0.55]], np.float32)

    def _requests(n, seed):
        r = np.random.default_rng(seed)
        return [(r.standard_normal((size, size, 3)).astype(np.float32), ex)
                for _ in range(n)]

    # ---- untraced baseline: compiles happen here (recording compile
    # events), and the per-request latency anchors the overhead check.
    # caches off: every request must ride the full pipeline.
    _progress("serve baseline (TMR_TRACE=0; warmup + timed pass)")
    with ServeEngine(pred, batch=args.batch, max_wait_ms=10,
                     exemplar_cache=0, feature_cache=0) as engine:
        _serve_closed_loop(engine, _requests(n_req, seed=1))  # warmup
        base_s = _serve_closed_loop(engine, _requests(n_req, seed=2))
    base_req_ms = base_s / n_req * 1000.0

    # ---- traced run: same workload shape, tracing on, fresh engine
    _progress("serve traced run (TMR_TRACE=1)")
    obs.configure(enabled=True)
    obs.clear()
    with ServeEngine(pred, batch=args.batch, max_wait_ms=10,
                     exemplar_cache=0, feature_cache=0) as engine:
        traced_s = _serve_closed_loop(engine, _requests(n_req, seed=3))
        serve_counters = engine.counters
        serve_metrics = engine.metrics_snapshot()
    serve_spans = obs.spans()

    # per-request completeness: every stage name present under one trace id
    by_trace: dict = {}
    for rec in serve_spans:
        if rec["name"].startswith("serve.") and rec["trace"]:
            by_trace.setdefault(rec["trace"], set()).add(rec["name"])
    complete = [t for t, names in by_trace.items()
                if set(TRACE_SERVE_STAGES) <= names]
    _progress(
        f"traced: {len(serve_spans)} spans, {len(by_trace)} request traces, "
        f"{len(complete)} with all {len(TRACE_SERVE_STAGES)} stages"
    )

    # ---- map workload (still traced)
    _progress("map workload (3 shards, 1 injected transient fault)")
    map_doc = _run_map_workload(64)
    map_spans = [r for r in obs.spans() if r["name"].startswith("map.")]
    obs.configure(enabled=False)

    # ---- export round-trip
    chrome = obs.chrome_trace()
    chrome_line = json.dumps(chrome)
    reparsed = json.loads(chrome_line)
    n_events = len([e for e in reparsed["traceEvents"] if e["ph"] == "X"])
    roundtrip_ok = n_events == len(obs.spans())
    if args.trace_out:
        with open(args.trace_out, "w") as f:
            f.write(chrome_line)

    events = obs.compile_events()
    overhead_pct = (
        disabled_ns * (len(TRACE_SERVE_STAGES) + 1)
        / (base_req_ms * 1e6) * 100.0
    )
    enabled_pct = (traced_s - base_s) / base_s * 100.0

    report = {
        "schema": TRACE_REPORT_SCHEMA,
        "device": str(jax.devices()[0]),
        "config": {
            "image_size": size,
            "batch": args.batch,
            "requests": n_req,
            "trace_ring": int(os.environ.get("TMR_TRACE_RING", "8192")
                              or 8192),
        },
        "serve": {
            "stages": _stage_table(serve_spans, "serve."),
            "requests": n_req,
            "request_traces": len(by_trace),
            "complete_request_traces": len(complete),
            "counters": serve_counters,
            "metrics": serve_metrics,
        },
        "map": {
            "stages": _stage_table(map_spans, "map."),
            "report_totals": map_doc["totals"],
            "report_valid": validate_map_report(map_doc) == [],
        },
        "compile_events": events,
        "metrics": obs.get_registry().snapshot(),
        "overhead": {
            "disabled_ns_per_span": round(disabled_ns, 1),
            "span_sites_per_request": len(TRACE_SERVE_STAGES) + 1,
            "baseline_request_ms": round(base_req_ms, 3),
            "overhead_disabled_pct": round(overhead_pct, 6),
            "enabled_overhead_pct": round(enabled_pct, 2),
        },
        "dropped_spans": obs.dropped_spans(),
    }
    report["checks"] = {
        "stages_complete": bool(len(complete) >= 1),
        "compile_event_recorded": bool(
            any(e.get("key") for e in events)
        ),
        "map_retry_observed": bool(
            report["metrics"]["counters"].get("map.retries", 0) >= 1
        ),
        "trace_roundtrip": bool(roundtrip_ok),
        "overhead_ok": bool(overhead_pct < 1.0),
    }
    problems = validate_trace_report(report)
    if problems:  # self-check: the emitted document must validate
        report["validator_problems"] = problems

    cancel_watchdog()  # before the success print: no success-then-watchdog
    line = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    return 0


def main(argv=None) -> int:
    """One trace_report/v1 JSON line on stdout, success or not: the shared
    bench_guard (same watchdog bench.py runs under) funnels wedges and
    crashes into a contractual error record."""
    from tmr_tpu.diagnostics import TRACE_REPORT_SCHEMA
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(
            json.dumps({"schema": TRACE_REPORT_SCHEMA, "error": msg}),
            flush=True,
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
