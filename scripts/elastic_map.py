"""Elastic map phase CLI — coordinator/worker shard execution over the
lease protocol (tmr_tpu/parallel/elastic.py).

Coordinator (owns the shard queue; shard names on stdin like
``mapreduce map``, emits the same Hadoop-streaming stat records on
stdout so ``| python -m tmr_tpu.parallel.mapreduce reduce`` keeps
working)::

    cat list_tars.txt | python scripts/elastic_map.py coordinator \
        --data_dir /data/tars --features_out features_output \
        --port 7077 --report_out elastic_report.json [--resume]

Workers (any number, any host that shares the filesystem; each leases
one shard at a time, heartbeats it, and commits the journal marker
under an epoch fence)::

    python scripts/elastic_map.py worker --coordinator HOST:7077 \
        --artifact exported/encoder.stablehlo

Lease knobs ride the TMR_ELASTIC_* env registry (config.ENV_KNOBS):
TTL / heartbeat cadence / liveness check interval / straggler bound /
reassignment and poison-worker limits. ``--encoder stub`` runs the
numpy stand-in encoder (tests, drills, protocol debugging — no XLA).

Fault drills: TMR_FAULTS schedules with the ``lease`` / ``heartbeat`` /
``steal`` points (utils/faults.py) inject grant failures, stalled
heartbeats (the SIGSTOP stand-in), and straggler-election faults;
scripts/chaos_probe.py --elastic is the canned gauntlet (kill -9 +
SIGSTOP, byte-identical table).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_address(text: str):
    host, _, port = text.rpartition(":")
    return (host or "127.0.0.1", int(port))


def _cli_coordinator(args) -> int:
    from tmr_tpu.parallel.elastic import ElasticCoordinator, ElasticPolicy
    from tmr_tpu.parallel.mapreduce import StatAccumulator
    from tmr_tpu.utils import faults
    from tmr_tpu.utils.profiling import log_info, log_warning

    if faults.install_from_env():
        log_warning(
            "fault injection ACTIVE (TMR_FAULTS="
            f"{os.environ.get('TMR_FAULTS', '')!r})"
        )
    names = [ln.strip() for ln in sys.stdin if ln.strip()]
    paths = [
        n if os.path.isabs(n) else os.path.join(args.data_dir, n)
        for n in names
    ]
    journal_dir = args.journal_dir
    if journal_dir is None and args.features_out:
        journal_dir = os.path.join(args.features_out, "_journal")
    if journal_dir is None:
        log_warning("coordinator: no --journal_dir/--features_out; "
                    "using ./_journal")
        journal_dir = "_journal"
    coord = ElasticCoordinator(
        paths, journal_dir,
        features_out=args.features_out, data_dir=args.data_dir,
        image_size=args.image_size, batch_size=args.batch_size,
        resume=args.resume, policy=ElasticPolicy.from_env(),
        host=args.host, port=args.port,
    )
    host, port = coord.start()
    log_info(
        f"elastic coordinator: {len(paths)} shards at {host}:{port} "
        f"(journal {journal_dir})"
    )
    settled = coord.wait(
        timeout=args.wait_timeout_s if args.wait_timeout_s > 0 else None
    )
    doc = coord.report()
    if args.report_out:
        if settled:
            doc = coord.write_report(args.report_out)  # validated
        else:
            # an unsettled run cannot produce a valid (all-settled)
            # report — dump the raw state for postmortem instead
            import json

            from tmr_tpu.utils.atomicio import atomic_write

            atomic_write(
                args.report_out,
                lambda f: json.dump(doc, f, indent=1, sort_keys=True),
            )
            log_warning(
                f"elastic: run unsettled; {args.report_out} holds the "
                "RAW (unvalidated) state"
            )
    t = doc["totals"]
    log_info(
        f"elastic: {t['committed']} committed / {t['resumed']} resumed / "
        f"{t['quarantined']} quarantined of {t['shards']} shards; "
        f"{t['reassignments']} reassignments, "
        f"{t['fenced_rejections']} fenced rejections, "
        f"{t['workers']} workers ({t['drained_workers']} drained)"
    )
    acc = StatAccumulator()
    acc.table = coord.table()
    for line in acc.emit_lines():
        sys.stdout.write(line + "\n")  # the Hadoop-streaming record form
    sys.stdout.flush()
    coord.stop()
    if not settled:
        log_warning("elastic: run did NOT settle within --wait_timeout_s")
        return 1
    return 0


def _cli_worker(args) -> int:
    from tmr_tpu.parallel.elastic import run_worker, stub_encode_stats_fn
    from tmr_tpu.parallel.mapreduce import RetryPolicy
    from tmr_tpu.utils import faults
    from tmr_tpu.utils.profiling import log_info, log_warning

    if faults.install_from_env():
        log_warning(
            "fault injection ACTIVE (TMR_FAULTS="
            f"{os.environ.get('TMR_FAULTS', '')!r})"
        )
    if args.encoder == "stub":
        fn = stub_encode_stats_fn(delay_s=args.shard_delay_s)
    elif args.artifact:
        from tmr_tpu.parallel.mapreduce import (
            make_encode_stats_fn_from_artifact,
        )

        fn = make_encode_stats_fn_from_artifact(args.artifact)
    else:
        from tmr_tpu.models import build_sam_encoder
        from tmr_tpu.parallel.mapreduce import make_encode_stats_fn

        if not args.checkpoint:
            log_warning("worker: no --artifact/--checkpoint, random "
                        "weights")
        model, params = build_sam_encoder(
            args.model_type, args.checkpoint, args.image_size or 1024
        )
        fn = make_encode_stats_fn(model, params)

    worker_id = args.worker_id or f"{os.uname().nodename}-{os.getpid()}"
    retry = RetryPolicy(
        max_attempts=max(1, args.max_attempts),
        shard_timeout=args.shard_timeout if args.shard_timeout > 0
        else None,
        backoff_base=args.backoff_base,
    )
    summary = run_worker(
        _parse_address(args.coordinator), worker_id, fn,
        retry=retry, hb_path=args.hb_path,
        batch_size=args.batch_size or None,
        image_size=args.image_size or None,
        max_idle_s=args.max_idle_s,
    )
    log_info(
        f"elastic worker {worker_id}: {summary['committed']} committed, "
        f"{summary['failed']} failed, {summary['fenced']} fenced over "
        f"{summary['leases']} leases"
        + (" (drained)" if summary["drained"] else "")
    )
    # a drained worker, or one that failed everything it touched, must
    # not look successful to the calling script (`worker ... && next`)
    if summary["drained"] or (
        summary["failed"] > 0 and summary["committed"] == 0
    ):
        return 1
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python scripts/elastic_map.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("coordinator",
                       help="serve the shard lease queue (shards on stdin)")
    c.add_argument("--data_dir", default=".",
                   help="prefix for shard names read from stdin")
    c.add_argument("--features_out", default=None,
                   help="per-image feature .npy tree (workers write it; "
                        "same layout as mapreduce map)")
    c.add_argument("--journal_dir", default=None,
                   help="done-marker + _leases directory (default "
                        "<features_out>/_journal)")
    c.add_argument("--host", default="127.0.0.1")
    c.add_argument("--port", default=0, type=int,
                   help="listen port (0 = ephemeral, printed at start)")
    c.add_argument("--image_size", default=1024, type=int)
    c.add_argument("--batch_size", default=8, type=int)
    c.add_argument("--resume", action="store_true",
                   help="fold valid journal done-markers instead of "
                        "re-leasing those shards (byte-identical table)")
    c.add_argument("--report_out", default=None,
                   help="write the validated elastic_report/v1 here")
    c.add_argument("--wait_timeout_s", default=0.0, type=float,
                   help="give up (rc 1) when the run has not settled "
                        "after this long; 0 waits forever")

    w = sub.add_parser("worker", help="lease and run shards")
    w.add_argument("--coordinator", required=True,
                   help="HOST:PORT of the coordinator")
    w.add_argument("--worker_id", default=None,
                   help="stable worker identity (default host-pid)")
    w.add_argument("--encoder", default="model",
                   choices=("model", "stub"),
                   help="'stub' = numpy stand-in encoder (tests/drills)")
    w.add_argument("--artifact", default=None,
                   help="serialized encoder from export_encoder.py")
    w.add_argument("--checkpoint", default=None)
    w.add_argument("--model_type", default="vit_b")
    w.add_argument("--batch_size", default=0, type=int,
                   help="override the coordinator's batch size")
    w.add_argument("--image_size", default=0, type=int,
                   help="override the coordinator's image size")
    w.add_argument("--max_attempts", default=3, type=int)
    w.add_argument("--shard_timeout", default=600.0, type=float)
    w.add_argument("--backoff_base", default=0.5, type=float)
    w.add_argument("--shard_delay_s", default=0.0, type=float,
                   help="stub encoder: sleep per batch (drill pacing)")
    w.add_argument("--hb_path", default=None,
                   help="heartbeat JSONL log (default under _leases/)")
    w.add_argument("--max_idle_s", default=60.0, type=float,
                   help="exit after this long with no lease available")

    args = p.parse_args(argv)
    return _cli_coordinator(args) if args.cmd == "coordinator" \
        else _cli_worker(args)


if __name__ == "__main__":
    sys.exit(main())
