"""Streaming-video benchmark: temporal feature reuse vs the
frame-independent path (tmr_tpu/serve/streams.py).

Drives a StreamRouter over a synthetic BURSTY video workload — S
streams, each a static scene that cuts to new content mid-stream —
and prints ONE ``stream_report/v1`` JSON document (schema + validator
in tmr_tpu/diagnostics.py):

- **Frame-independent baseline** — every frame through
  ``ServeEngine.submit`` the way frame-independent requests pay: one
  fused program (backbone included) per frame. Caches are OFF
  (``feature_cache=0, exemplar_cache=0``) so repeated frames recompute
  honestly and the baseline stays the bitwise-deterministic fused path.
- **Stream phase** — the same frames through
  ``StreamRouter.submit_stream`` with reuse ON: unchanged frames elect
  the heads-only program over the session anchor's cached features and
  SKIP the backbone. Checks, all mechanical:

  * ``backbone_amortized`` — backbone-bearing executions ≪ frames,
    proven from the flight recorder's per-program call table (the
    ``TMR_FLIGHT`` devtime witness, enabled in-process): at most the
    fused pass per non-reused frame plus one feature fill per anchor.
  * ``speedup_ok`` — stream frames/s >= 1.5x the frame-independent
    baseline on the same frames.
  * ``changed_frames_exact`` — every frame the delta check sent down
    the full path ("first"/"changed") is BITWISE-identical to its
    baseline result: reuse off the reuse path costs nothing.
  * ``reuse_labeled`` — every reused frame's result carries
    ``degrade_steps: ["temporal_reuse"]`` and no full-path frame does.
  * ``cross_stream_isolated`` — streams carry DISTINCT content; a
    reused result bitwise-matching another stream's results would be
    cross-stream feature leakage. Zero tolerated.

Usage:  python scripts/stream_bench.py [--tiny] [--out FILE]
        [--streams S] [--frames F] [--delta D] [--seed N]

``--tiny`` (or TMR_BENCH_TINY=1) shrinks geometry so the whole bench
smoke-runs on CPU (tier-1 runs it under JAX_PLATFORMS=cpu); real
numbers use the deployment geometry. Same one-JSON-line contract as
bench.py via the shared bench_guard; ``bench_trend.py --stream``
rc-gates the emitted report (fail closed).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()

#: detection fields compared bitwise between the stream phase's
#: full-path frames and the frame-independent baseline
_FIELDS = ("boxes", "scores", "refs", "valid")

#: the one exemplar every stream carries (streams differ by CONTENT;
#: a shared box keeps one capacity bucket → one fused + one heads
#: program for the whole bench)
_BOX = np.asarray([[0.3, 0.3, 0.5, 0.5]], np.float32)


def _progress(msg: str) -> None:
    print(f"[stream_bench] {msg}", file=sys.stderr, flush=True)


def _make_workload(size: int, n_streams: int, n_frames: int, seed: int):
    """(frames, verdicts): the bursty video shape. Each stream is a
    static random scene repeated EXACTLY (delta 0.0 → reuse) that cuts
    to fresh content at the midpoint burst (full-frame content swap —
    block-mean delta far above any sane threshold → "changed"). The
    expected verdict per (stream, frame) rides along so the report's
    label/exactness checks compare against the workload's ground
    truth, not the router's own opinion of itself."""
    frames: dict = {}
    verdicts: dict = {}
    burst_at = n_frames // 2
    for s in range(n_streams):
        rng = np.random.default_rng(1000 * (seed + 1) + s)
        anchor = rng.standard_normal((size, size, 3)).astype(np.float32)
        for f in range(n_frames):
            if f == 0:
                verdicts[(s, f)] = "first"
            elif f == burst_at:
                # the cut: entirely new content becomes the new anchor
                anchor = rng.standard_normal(
                    (size, size, 3)
                ).astype(np.float32)
                verdicts[(s, f)] = "changed"
            else:
                verdicts[(s, f)] = "reused"
            frames[(s, f)] = anchor
    return frames, verdicts


def _program_calls(kinds) -> dict:
    """Executed-call counts per devtime program kind (warmup calls
    included — an execution is an execution)."""
    from tmr_tpu import obs

    out: dict = {}
    for prog in obs.mfu_report()["programs"]:
        if prog["kind"] in kinds:
            out[prog["kind"]] = out.get(prog["kind"], 0) \
                + int(prog["calls"]) + int(prog["warmup_calls"])
    return out


def _np(result: dict) -> dict:
    return {k: np.asarray(result[k]) for k in _FIELDS if k in result}


def _same(a: dict, b: dict) -> bool:
    return all(
        np.array_equal(np.asarray(a[k]), np.asarray(b[k]))
        for k in _FIELDS
    )


def _run(cancel_watchdog, argv=None) -> int:
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke geometry (also TMR_BENCH_TINY=1)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    ap.add_argument("--streams", type=int, default=3,
                    help="concurrent stream sessions")
    ap.add_argument("--frames", type=int, default=10,
                    help="frames per stream (one mid-stream burst)")
    ap.add_argument("--delta", type=float, default=0.02,
                    help="block-mean reuse threshold (TMR_STREAM_DELTA "
                         "default)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    tiny = args.tiny or os.environ.get("TMR_BENCH_TINY", "") not in (
        "", "0", "false"
    )
    size = int(os.environ.get("TMR_BENCH_SIZE", 128 if tiny else 1024))
    dtype = "float32" if tiny else "bfloat16"

    import jax

    from tmr_tpu import obs
    from tmr_tpu.config import preset
    from tmr_tpu.diagnostics import (
        STREAM_REPORT_SCHEMA,
        validate_stream_report,
    )
    from tmr_tpu.inference import Predictor
    from tmr_tpu.serve import ServeEngine, StreamRouter

    n_streams, n_frames = int(args.streams), int(args.frames)
    total = n_streams * n_frames
    _progress(f"backend: {jax.devices()[0]} size={size} tiny={tiny} "
              f"streams={n_streams} frames/stream={n_frames}")
    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=size,
                 compute_dtype=dtype, batch_size=1)
    pred = Predictor(cfg)
    _progress("init_params (jitted init)")
    pred.init_params(seed=0, image_size=size)

    frames, verdicts = _make_workload(size, n_streams, n_frames,
                                      args.seed)
    wall0 = time.perf_counter()
    # the flight recorder is the backbone-amortization witness: every
    # program execution lands in the devtime call table
    obs.flight_configure(enabled=True)

    # ONE engine for both phases: caches off, so the baseline phase
    # leaves nothing behind for the stream phase to feed on, and both
    # run the byte-identical B=1 programs
    engine = ServeEngine(pred, batch=1, max_wait_ms=5, feature_cache=0,
                         exemplar_cache=0)
    router = StreamRouter(engine, reuse=True, delta=args.delta)

    # ---- warmup: compile the fused program (anchor frame), the local
    # backbone fill, and the heads-only program (reused frame) outside
    # every timed window, on a throwaway stream
    _progress("warmup compiles (fused + backbone fill + heads)")
    warm = np.random.default_rng(991).standard_normal(
        (size, size, 3)
    ).astype(np.float32)
    router.submit_stream("warm", warm, _BOX).result()
    router.submit_stream("warm", warm, _BOX).result()
    router.evict("warm")
    counters0 = router.counters()

    # ---- frame-independent baseline: every frame pays the fused pass
    _progress("phase frame_independent baseline")
    from tmr_tpu.obs import devtime

    devtime.reset()
    base: dict = {}
    t0 = time.perf_counter()
    for f in range(n_frames):
        for s in range(n_streams):
            base[(s, f)] = _np(
                engine.submit(frames[(s, f)], _BOX).result()
            )
    base_dt = time.perf_counter() - t0
    base_fps = total / base_dt
    base_programs = _program_calls(("single", "backbone", "heads",
                                    "multi"))
    _progress(f"baseline: {base_fps:.3f} frames/s "
              f"(by_program {base_programs})")

    # ---- stream phase: the same frames through the router, streams
    # interleaved round-robin the way live sessions arrive
    _progress("phase stream (reuse on)")
    devtime.reset()
    stream: dict = {}
    t0 = time.perf_counter()
    for f in range(n_frames):
        for s in range(n_streams):
            stream[(s, f)] = router.submit_stream(
                f"s{s}", frames[(s, f)], _BOX
            )
    results = {key: fut.result() for key, fut in stream.items()}
    stream_dt = time.perf_counter() - t0
    stream_fps = total / stream_dt
    by_program = _program_calls(("single", "backbone", "heads", "multi"))
    # backbone-bearing executions: the fused program runs the backbone
    # inline; "backbone" is the router's per-anchor feature fill
    backbone_execs = by_program.get("single", 0) \
        + by_program.get("multi", 0) + by_program.get("backbone", 0)
    counters = {
        k: v - counters0.get(k, 0) for k, v in router.counters().items()
    }
    _progress(f"stream: {stream_fps:.3f} frames/s "
              f"({stream_fps / base_fps:.2f}x baseline), backbone "
              f"executions {backbone_execs} for {total} frames "
              f"(by_program {by_program})")

    # ---- label + exactness + isolation audit against the workload's
    # ground-truth verdicts
    n_reused = sum(1 for v in verdicts.values() if v == "reused")
    n_changed = sum(1 for v in verdicts.values() if v == "changed")
    n_first = sum(1 for v in verdicts.values() if v == "first")
    mismatches = 0
    checked = 0
    label_errors = 0
    cross_hits = 0
    for key, verdict in verdicts.items():
        got = results[key]
        labeled = "temporal_reuse" in got.get("degrade_steps", ())
        if verdict == "reused":
            if not labeled:
                label_errors += 1
            # distinct per-stream content: this result matching ANY
            # other stream's baseline would be cross-stream leakage
            s = key[0]
            for (s2, f2), want in base.items():
                if s2 != s and _same(got, want):
                    cross_hits += 1
                    break
        else:
            if labeled:
                label_errors += 1
            checked += 1
            if not _same(got, base[key]):
                mismatches += 1
    _progress(f"exactness: {mismatches} mismatching full-path frames "
              f"of {checked}; {label_errors} label errors; "
              f"{cross_hits} cross-stream hits; router {counters}")

    report = {
        "schema": STREAM_REPORT_SCHEMA,
        "device": str(jax.devices()[0]),
        "config": {
            "image_size": size,
            "streams": n_streams,
            "frames_per_stream": n_frames,
            "frames": total,
            "delta": float(args.delta),
            "seed": int(args.seed),
            "dtype": dtype,
        },
        "throughput": {
            "stream_frames_per_sec": round(stream_fps, 3),
            "independent_frames_per_sec": round(base_fps, 3),
            "speedup": round(stream_fps / base_fps, 3),
        },
        "backbone": {
            "frames": total,
            "executions": int(backbone_execs),
            "baseline_by_program": base_programs,
            "by_program": by_program,
        },
        "reuse": {
            "reused_frames": int(counters.get("reused_frames", 0)),
            "changed_frames": int(counters.get("changed_frames", 0)),
            "first_frames": int(counters.get("first_frames", 0)),
            "expected": {"reused": n_reused, "changed": n_changed,
                         "first": n_first},
        },
        "exactness": {
            "changed_frames_checked": int(checked),
            "mismatches": int(mismatches),
            "label_errors": int(label_errors),
        },
        "isolation": {
            "cross_stream_hits": int(cross_hits),
            "sessions": len(router.sessions()),
        },
        "counters": router.stats(),
        "checks": {
            # ≪ frames, mechanically: at most the fused pass per
            # non-reused frame plus one feature fill per anchor
            "backbone_amortized": bool(
                backbone_execs <= 2 * (n_first + n_changed)
                and backbone_execs < total
            ),
            "speedup_ok": bool(stream_fps >= 1.5 * base_fps),
            "changed_frames_exact": bool(
                mismatches == 0 and checked == n_first + n_changed
            ),
            "cross_stream_isolated": bool(cross_hits == 0),
            "reuse_labeled": bool(label_errors == 0 and n_reused > 0),
            "verdicts_as_expected": bool(
                counters.get("reused_frames", 0) == n_reused
                and counters.get("changed_frames", 0) == n_changed
                and counters.get("first_frames", 0) == n_first
            ),
        },
    }
    report["wall_s"] = round(time.perf_counter() - wall0, 1)
    problems = validate_stream_report(report)
    if problems:  # self-check: the emitted document must validate
        report["validator_problems"] = problems
    engine.close()

    cancel_watchdog()  # before the success print: no success-then-watchdog
    line = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    return 0


def main(argv=None) -> int:
    """One stream_report/v1 JSON line on stdout, success or not: the
    shared bench_guard (same watchdog bench.py runs under) funnels
    wedges and crashes into a contractual error record."""
    from tmr_tpu.diagnostics import STREAM_REPORT_SCHEMA
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(
            json.dumps({"schema": STREAM_REPORT_SCHEMA, "error": msg}),
            flush=True,
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
