#!/usr/bin/env python
"""Promote freshly measured autotune winners from the user cache into the
committed seed (AUTOTUNE_SEED.json).

Why: a battery's sweeps store winners in ``~/.cache/tmr_tpu/autotune.json``
— which does not survive a container swap. The driver's round-end bench
runs from the committed tree, so winners must reach AUTOTUNE_SEED.json (and
be committed) to spare that bench a full re-sweep over the wedge-prone
tunnel. ``scripts/pick_full_program.py`` already writes the seed on a
DECISIVE full-program win; this script covers the other outcome — the
sweep ran, its winners stand (no pinned combo beat them), and they carry
CURRENT variant stamps that the committed seed lacks.

Policy: only knob entries whose ``_variants_<knob>`` stamp in the cache
matches the CURRENT sweep signature are promoted (a stale cached winner
must re-sweep, not get laundered into the seed); existing seed values are
overwritten only by stamped-fresh cache values. Prints one JSON summary
line; rc 0 = seed updated, 3 = nothing to promote, 1 = error.

Offline and tunnel-free. Usage: python scripts/promote_cache_to_seed.py
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    from tmr_tpu.utils.autotune import (
        _VERSIONED_KNOBS,
        _load_validated,
        _variants_sig,
        CACHE_PATH,
        seed_load,
        seed_store,
    )

    cache_path = os.environ.get("TMR_AUTOTUNE_CACHE", CACHE_PATH)
    cache = _load_validated(cache_path)
    if not cache:
        print(json.dumps({"updated": False, "reason": "empty user cache"}))
        return 3
    seed = seed_load()

    #: knobs a full-program A/B (scripts/pick_full_program.py) may have
    #: pinned — its whole-program evidence outranks the one-block sweep,
    #: so promotion must not overwrite them in an entry carrying the
    #: _full_program_ab marker WHILE the pin's own stamp is still current.
    #: Once a _SWEEP_REV bump stales the pin, runtime drops it and
    #: re-sweeps anyway, so the fresh sweep winner must promote or every
    #: fresh container re-sweeps over the tunnel forever.
    FULL_PROGRAM_KNOBS = ("TMR_WIN_ATTN", "TMR_GLOBAL_ATTN")

    promoted = {}
    for key, entry in cache.items():
        out = dict(seed.get(key, {}))
        changed = {}
        for knob in _VERSIONED_KNOBS:
            if (
                knob in FULL_PROGRAM_KNOBS
                and "_full_program_ab" in out
                and out.get(f"_variants_{knob}") == _variants_sig(knob)
            ):
                continue
            stamp = entry.get(f"_variants_{knob}")
            if knob in entry and stamp == _variants_sig(knob):
                if (out.get(knob), out.get(f"_variants_{knob}")) != (
                    entry[knob], stamp
                ):
                    out[knob] = entry[knob]
                    out[f"_variants_{knob}"] = stamp
                    changed[knob] = entry[knob]
                    if (
                        knob in FULL_PROGRAM_KNOBS
                        and "_full_program_ab" in out
                    ):
                        # the stale pin just got replaced by a SWEEP
                        # winner: drop the marker, or the sweep pick would
                        # inherit pin-level protection it never earned
                        del out["_full_program_ab"]
        # _precision_impl is the impl pairing TMR_XCORR_PRECISION's
        # decisive win was validated under — it moves ONLY with its owner
        # (a lone stale pairing would vouch for numerics on the wrong impl)
        if "TMR_XCORR_PRECISION" in changed and "_precision_impl" in entry:
            if out.get("_precision_impl") != entry["_precision_impl"]:
                out["_precision_impl"] = entry["_precision_impl"]
                changed["_precision_impl"] = entry["_precision_impl"]
        # same ownership rule for the scores-dtype <-> global formulation
        # pairing: it moves only with its owner knob
        if ("TMR_GLOBAL_SCORES_DTYPE" in changed
                and "_scores_global_impl" in entry):
            if out.get("_scores_global_impl") != entry["_scores_global_impl"]:
                out["_scores_global_impl"] = entry["_scores_global_impl"]
                changed["_scores_global_impl"] = entry["_scores_global_impl"]
        # the measured throughput-optimal batch is an independent
        # measurement: rides alone
        if (
            "TMR_BENCH_BATCH" in entry
            and out.get("TMR_BENCH_BATCH") != entry["TMR_BENCH_BATCH"]
        ):
            out["TMR_BENCH_BATCH"] = entry["TMR_BENCH_BATCH"]
            changed["TMR_BENCH_BATCH"] = entry["TMR_BENCH_BATCH"]
        if changed:
            seed[key] = out
            promoted[key] = changed

    if not promoted:
        print(json.dumps({"updated": False,
                          "reason": "no stamped-fresh winners to promote"}))
        return 3
    seed_store(seed)
    from tmr_tpu.utils.autotune import SEED_PATH

    print(json.dumps({
        "updated": True,
        "seed": os.environ.get("TMR_AUTOTUNE_SEED", SEED_PATH),
        "promoted": promoted,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
