"""Run the repo-wide static analysis + compiled-program audit and emit
ONE ``analysis_report/v1`` JSON line (tmr_tpu/analysis).

Two tiers, both riding this one entry point:

- the AST tier (jit-hygiene, lock-discipline, knob-parity,
  knob-import-time, report-parity, stdout-hygiene) walks the source
  tree — no jax, sub-second;
- the program tier traces the bucketed production programs (backbone,
  fused match+heads, heads-only, nms_topk) plus every attention
  formulation to jaxprs and asserts the structural invariants (no-S²,
  no-f64, quant-widen, transfer guard). Trace-only: no compile, no
  device execution — safe on any backend, and the CPU run audits the
  same programs the TPU serves.

Flags:
  --json               accepted for uniformity (the JSON line is the
                       default and only stdout output — bench_guard's
                       one-line contract)
  --out FILE           additionally write the document, indented
  --baseline PATH      suppression baseline (default:
                       <repo>/analysis_baseline.json)
  --baseline-update    rewrite the baseline's suppression list from the
                       CURRENT findings (each entry still needs a human
                       reason — the writer stamps a placeholder you must
                       edit before committing) and exit 0
  --no-program-audit   AST tier only (fast pre-commit loop)
  --gate-states all    sweep all 8 decoder/quant/decode-tail gate states
                       (default: the ambient env only)
  --image-size N       program-audit trace geometry (default 64 on CPU,
                       1024 on TPU — the production 128^2 decoder grid)

Exit code: 0 when ``checks.clean`` (zero unbaselined findings and a
passing program audit), 1 otherwise — CI can gate on the code alone.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import run_guarded, scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()

from tmr_tpu.diagnostics import (  # noqa: E402
    ANALYSIS_REPORT_SCHEMA,
    validate_analysis_report,
)


def _emit_error(msg: str):
    print(json.dumps({"schema": ANALYSIS_REPORT_SCHEMA, "error": msg}),
          flush=True)


def _run(cancel) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true",
                    help="emit the analysis_report/v1 JSON line (default)")
    ap.add_argument("--out", default=None,
                    help="also write the document to this path, indented")
    ap.add_argument("--baseline", default=None,
                    help="suppression baseline path")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite the baseline suppressions from current "
                         "findings and exit")
    ap.add_argument("--no-program-audit", action="store_true",
                    help="AST tier only (no jax import)")
    ap.add_argument("--gate-states", choices=("env", "all"), default="env",
                    help="program audit under the ambient env, or the "
                         "full 2x2x2 decoder/quant/decode-tail sweep")
    ap.add_argument("--image-size", type=int, default=None,
                    help="program-audit geometry (default 64 cpu / "
                         "1024 tpu)")
    args = ap.parse_args()

    from tmr_tpu.analysis import (
        Baseline,
        build_report,
        default_baseline_path,
        run_ast_passes,
    )
    from tmr_tpu.analysis.core import default_repo_root

    root = default_repo_root()
    baseline_path = args.baseline or default_baseline_path(root)
    baseline = Baseline.load(baseline_path)
    findings = run_ast_passes(root=root, baseline=baseline)

    if args.baseline_update:
        cancel()
        baseline.suppressions = [
            {"rule": f.rule, "file": f.file, "match": f.message,
             "reason": "TODO: justify this suppression before committing"}
            for f in findings if not baseline.allows(f)
        ] + baseline.suppressions
        baseline.save(baseline_path)
        from tmr_tpu.analysis.core import BASELINE_SCHEMA

        # tagged as a BASELINE document, not analysis_report/v1 — a
        # report-tagged line must always pass validate_analysis_report
        print(json.dumps({
            "schema": BASELINE_SCHEMA,
            "baseline_updated": baseline_path,
            "suppressions": len(baseline.suppressions),
        }), flush=True)
        return 0

    program = None
    if not args.no_program_audit:
        from tmr_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()  # the gate self-checks jit; reuse them
        import jax

        from tmr_tpu.analysis.program_audit import (
            ALL_GATE_STATES,
            audit_production_programs,
        )

        on_tpu = jax.default_backend() == "tpu"
        size = args.image_size or (1024 if on_tpu else 64)
        program = audit_production_programs(
            baseline=baseline,
            image_size=size,
            gate_states=(ALL_GATE_STATES if args.gate_states == "all"
                         else None),
            attention_grids=((64, 64), (96, 96)),
            record_refusals=True,
        )

    doc = build_report(findings, baseline, program_audit=program,
                       root=root)
    problems = validate_analysis_report(doc)
    if problems:  # self-check before print — the report contract
        raise AssertionError(f"invalid analysis_report/v1: {problems}")
    cancel()
    print(json.dumps(doc), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
    for f_ in doc["findings"]:  # human-readable mirror on stderr
        print(f"{f_['file']}:{f_['line']}: [{f_['rule']}] {f_['message']}",
              file=sys.stderr)
    return 0 if doc["checks"]["clean"] else 1


def main() -> int:
    return run_guarded(_run, _emit_error)


if __name__ == "__main__":
    sys.exit(main())
