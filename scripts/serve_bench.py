"""Offered-load benchmark of the serving layer (tmr_tpu/serve).

Drives ServeEngine through closed- and open-loop workloads and prints ONE
``serve_report/v1`` JSON document (schema + validator in
tmr_tpu/diagnostics.py):

- ``exact_closed`` — unique-image closed loop at the coalescing bound vs
  the sequential ``Predictor.__call__`` loop on the identical requests;
  proves batched results are BITWISE-identical to sequential and measures
  pure batching speedup (no cache involvement by construction).
- ``mixed_closed`` — the interactive mix (repeated exemplars on repeated
  images, submitted in waves so repeats can land after their first copy
  completes): result-cache and feature-cache hits happen here, and the
  headline ≥1.5x speedup check compares this workload's serve throughput
  against the same requests through the sequential loop.
- ``open_rate_*`` — open-loop arrivals at fractions of the measured
  closed-loop throughput; p50/p95/p99 latency and the batch-occupancy
  histogram per rate. The p99-bound check runs at the LOW rate, where a
  request's worst case is max_wait_ms + one padded-batch execution (the
  latency contract of the micro-batcher).

Usage:  python scripts/serve_bench.py [--tiny] [--out FILE]
        [--batch N] [--max-wait-ms MS] [--requests N] [--rates r1,r2]

``--tiny`` (or TMR_BENCH_TINY=1) shrinks geometry + counts so the whole
sweep smoke-runs on CPU in minutes (tier-1 runs it under
JAX_PLATFORMS=cpu); real numbers use the 1024^2 deployment geometry.
Same one-JSON-line contract as bench.py via the shared bench_guard.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()


def _progress(msg: str) -> None:
    print(f"[serve_bench] {msg}", file=sys.stderr, flush=True)


def _percentiles(lat_s) -> dict:
    if not lat_s:
        return {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    arr = np.asarray(lat_s) * 1000.0
    return {
        "p50": round(float(np.percentile(arr, 50)), 2),
        "p95": round(float(np.percentile(arr, 95)), 2),
        "p99": round(float(np.percentile(arr, 99)), 2),
    }


def _make_requests(size: int, batch: int, seed: int = 0):
    """The workload images/exemplars. Returns (unique, mixed):
    ``unique`` — 2*batch+3 distinct (image, exemplar) pairs spanning a
    ragged tail and two capacity buckets; ``mixed`` — the interactive
    pattern over few images: exact repeats (result-cache) and
    same-image-new-exemplar queries (feature-cache), in waves."""
    rng = np.random.default_rng(seed)
    n_unique = 2 * batch + 3
    small_ex = np.asarray([[0.45, 0.45, 0.53, 0.55]], np.float32)
    big_ex = np.asarray([[0.1, 0.1, 0.9, 0.9]], np.float32)
    unique = []
    for i in range(n_unique):
        img = rng.standard_normal((size, size, 3)).astype(np.float32)
        unique.append((img, big_ex if i % 3 == 2 else small_ex))

    n_imgs = batch  # full first-wave batches: the interactive mix should
    waves = []      # exercise batching AND caching, not padding waste
    imgs = [rng.standard_normal((size, size, 3)).astype(np.float32)
            for _ in range(n_imgs)]
    exs = [small_ex,
           np.asarray([[0.2, 0.2, 0.28, 0.3]], np.float32),
           np.asarray([[0.6, 0.55, 0.68, 0.66]], np.float32)]
    # wave 1: first sighting; waves 2..: exact repeats + fresh exemplars
    waves.append([(im, exs[0]) for im in imgs])
    waves.append([(im, exs[0]) for im in imgs])      # result-cache hits
    waves.append([(im, exs[1]) for im in imgs])      # promotion fills
    waves.append([(im, exs[2]) for im in imgs])      # feature-cache hits
    waves.append([(im, exs[1]) for im in imgs])      # result-cache hits
    return unique, waves


def _sequential_throughput(pred, requests, iters: int = 1) -> float:
    """img/s of the plain one-request-at-a-time Predictor loop (results
    fetched per request, like a naive server would)."""
    t0 = time.perf_counter()
    for _ in range(iters):
        for img, ex in requests:
            dets = pred(img[None], ex[None])
            np.asarray(dets["scores"])  # fetch = the request is done
    dt = time.perf_counter() - t0
    return len(requests) * iters / dt


def _timed_submit(engine, img, ex, lat: list, deadline_ms=None):
    """Submit with resolution-time latency capture: the done-callback
    stamps the clock WHEN the future resolves — awaiting futures in
    submission order afterwards would credit early requests with the whole
    tail of the run. Only successful resolutions enter the latency
    sample: a rejection/shed resolves in microseconds and would
    deflate the percentiles of the traffic that was actually served."""
    ts = time.perf_counter()
    f = engine.submit(img, ex, deadline_ms=deadline_ms)
    f.add_done_callback(
        lambda _f, _ts=ts: lat.append(time.perf_counter() - _ts)
        if _f.exception() is None else None
    )
    return f


def _closed_loop(engine, requests, waves: bool = False):
    """Submit everything (optionally wave-synchronized), await all.
    Returns (throughput img/s, [latency_s], [results])."""
    groups = requests if waves else [requests]
    lat, results = [], []
    t0 = time.perf_counter()
    for group in groups:
        futs = [_timed_submit(engine, img, ex, lat) for img, ex in group]
        for f in futs:
            results.append(f.result(timeout=600))
    dt = time.perf_counter() - t0
    return len(results) / dt, lat, results


def _open_loop(engine, requests, rate: float, deadline_ms=None):
    """Fixed-rate arrivals at ``rate`` img/s; returns (served_tput,
    [latency_s], served_count). Open-loop clients are NOT infinitely
    patient anymore: with admission/deadlines in play a future may
    resolve with a structured RejectedError — tallied by the engine's
    overload counters (attached to the workload record), not a crash."""
    period = 1.0 / rate
    lat: list = []
    futs = []
    t0 = time.perf_counter()
    for i, (img, ex) in enumerate(requests):
        target = t0 + i * period
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        futs.append(_timed_submit(engine, img, ex, lat,
                                  deadline_ms=deadline_ms))
    served = 0
    for f in futs:
        try:
            f.result(timeout=600)
            served += 1
        except Exception:
            pass  # rejection/shed: counted via engine.overload_counters
    dt = time.perf_counter() - t0
    return served / dt, lat, served


def _workload_record(name, mode, n, tput, lat_s, engine, occ0, cache0):
    """One workloads[] entry; occupancy/cache deltas vs the pre-workload
    snapshots so each workload reports only its own traffic."""
    stats = engine.stats()
    occ = {
        k: v - occ0.get(k, 0)
        for k, v in stats["batch_occupancy"].items()
        if v - occ0.get(k, 0) > 0
    }
    cache = {}
    for which in ("result_cache", "feature_cache"):
        now = stats[which]
        base = cache0.get(which, {})
        cache[which] = {
            k: now[k] - base.get(k, 0)
            for k in ("hits", "misses", "evictions", "inserts")
        }
    return {
        "name": name,
        "mode": mode,
        "requests": n,
        "throughput_img_per_sec": round(tput, 3),
        "latency_ms": _percentiles(lat_s),
        "batch_occupancy": occ,
        "cache": cache,
    }


def _snapshots(engine):
    s = engine.stats()
    return s["batch_occupancy"], {
        w: dict(s[w]) for w in ("result_cache", "feature_cache")
    }


def _bitwise_equal(a: dict, b: dict) -> bool:
    return all(
        np.array_equal(np.asarray(a[k]), np.asarray(b[k]))
        for k in ("boxes", "scores", "refs", "valid")
    )


def _allclose_equal(a: dict, b: dict, atol: float = 1e-4) -> bool:
    """Tensor-parallel parity: identical keep decisions, floats at
    allclose (TP collectives reorder reductions — the documented
    heads-path-style exception; dp stays bitwise)."""
    if not np.array_equal(np.asarray(a["valid"]), np.asarray(b["valid"])):
        return False
    return all(
        np.allclose(np.asarray(a[k]).astype(np.float64),
                    np.asarray(b[k]).astype(np.float64), atol=atol)
        for k in ("boxes", "scores", "refs")
    )


def _run_mesh_sweep(args, tiny: bool, size: int, dtype: str,
                    cancel_watchdog) -> int:
    """``--mesh dp4,dp2tp2,...``: one serve_report/v1 JSON line PER mesh
    shape, each with a validated ``mesh`` attachment (spec, axis shape,
    replica groups) — closed-loop throughput vs the single-device
    engine on identical requests, per-request parity (bitwise for dp
    meshes, allclose + identical keep decisions for tp), and the
    AOT-warmup zero-cold-compile pin via PR 8's compile-event cursor.

    Scaling expectations are host-aware: a forced-8-device CPU mesh on
    an N-core host can overlap at most min(devices, N) executions, so
    the ``scaling_ok`` check targets 3x only where the host can
    physically deliver it (the acceptance number for real multi-chip
    slices and multi-core CI) and degrades to a bounded-overhead check
    on single-core containers — reported, never fabricated."""
    import jax

    from tmr_tpu import obs
    from tmr_tpu.config import preset
    from tmr_tpu.diagnostics import (
        SERVE_REPORT_SCHEMA,
        validate_serve_report,
    )
    from tmr_tpu.inference import Predictor
    from tmr_tpu.serve import ServeEngine

    specs = [s.strip() for s in args.mesh.split(",") if s.strip()]
    _progress(f"mesh sweep {specs}: backend {jax.devices()[0]} "
              f"size={size} tiny={tiny}")
    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=size,
                 compute_dtype=dtype, batch_size=1)
    pred = Predictor(cfg)
    _progress("init_params (jitted init)")
    pred.init_params(seed=0, image_size=size)
    batch = args.batch or 1
    unique, _waves = _make_requests(size, batch)
    warmup_buckets = sorted(
        {pred.bucket_key(size, ex) for _img, ex in unique}
    )

    # ---- single-device baseline on the identical requests
    _progress("single-device baseline")
    # mesh="off" EXPLICITLY: the baseline must stay single-device even
    # when TMR_SERVE_MESH is set in the env (otherwise the env spec
    # either crashes against the 1-device list or silently meshes the
    # denominator every scaling number divides by)
    base = ServeEngine(pred, batch=batch, max_wait_ms=args.max_wait_ms,
                       devices=jax.devices()[:1], feature_cache=0,
                       exemplar_cache=0, warmup_buckets=warmup_buckets,
                       aot=True, mesh="off")
    base_tput, _lat, base_results = _closed_loop(base, unique)
    base.close()
    _progress(f"single-device: {base_tput:.3f} img/s")

    host_cores = os.cpu_count() or 1
    lines = []
    rc = 0
    for spec in specs:
        _progress(f"mesh {spec}: engine start (AOT warmup)")
        wall0 = time.perf_counter()
        engine = ServeEngine(pred, batch=batch,
                             max_wait_ms=args.max_wait_ms, mesh=spec,
                             feature_cache=0, exemplar_cache=0,
                             warmup_buckets=warmup_buckets, aot=True)
        stats0 = engine.stats()
        warmup = stats0.get("warmup") or {}
        # the AOT pin: every program the workload can reach compiled at
        # warmup, so steady state records ZERO new compile events
        cursor = obs.compile_event_seq()
        occ0, cache0 = _snapshots(engine)
        tput, lat, results = _closed_loop(engine, unique)
        new_events, _seq = obs.compile_events_since(cursor)
        mesh_desc = stats0.get("mesh") or {}
        tp = int((mesh_desc.get("shape") or {}).get("tp", 1))
        n_dev = sum(len(g) for g in
                    (mesh_desc.get("replica_groups") or []))
        if tp == 1:
            exact = all(_bitwise_equal(a, b)
                        for a, b in zip(base_results, results))
            parity = "bitwise"
        else:
            exact = all(_allclose_equal(a, b)
                        for a, b in zip(base_results, results))
            parity = "allclose"
        scaling = tput / base_tput if base_tput > 0 else 0.0
        expected = min(n_dev, host_cores) if \
            jax.default_backend() == "cpu" else n_dev
        scaling_target = 0.5 if expected <= 1 else min(3.0,
                                                       0.75 * expected)
        batch_global = engine._bound_for(warmup_buckets[0])
        batch_ms = batch_global / tput * 1000.0 if tput > 0 else 0.0
        slack_ms = 500.0 if jax.default_backend() == "cpu" else 50.0
        # closed-loop burst: the last request drains behind the whole
        # backlog, so the p99 envelope is the PR 9 per-batch bound times
        # the batches the burst forms (the open-loop low-rate bound
        # stays with the default serve_bench path)
        n_batches = -(-len(unique) // max(batch_global, 1))
        p99_bound_ms = (engine.max_wait_ms + n_batches * batch_ms
                        + slack_ms)
        rec = _workload_record("mesh_closed", "closed", len(unique),
                               tput, lat, engine, occ0, cache0)
        rec["single_device_img_per_sec"] = round(base_tput, 3)
        p99 = rec["latency_ms"]["p99"]
        report = {
            "schema": SERVE_REPORT_SCHEMA,
            "device": str(jax.devices()[0]),
            "config": {
                "image_size": size,
                "batch": batch,
                "batch_global": batch_global,
                "max_wait_ms": engine.max_wait_ms,
                "devices": n_dev,
                "donate": engine.donate,
                "host_cores": host_cores,
            },
            "mesh": mesh_desc,
            **({"quant": stats0["quant"]} if "quant" in stats0 else {}),
            "aot": {
                "warmup": warmup,
                "compile_events_after_warmup": len(new_events),
                "cold_after_warmup": [
                    {"kind": e["kind"], "cause": e["cause"]}
                    for e in new_events
                ],
            },
            "workloads": [rec],
            "checks": {
                "speedup_vs_sequential": round(scaling, 3),
                "speedup_ok": bool(scaling >= scaling_target),
                "scaling_vs_single_device": round(scaling, 3),
                "scaling_target": round(scaling_target, 3),
                "scaling_ok": bool(scaling >= scaling_target),
                "host_parallelism": int(expected),
                "exact_match": bool(exact),
                "parity": parity,
                "p99_ms": p99,
                "p99_bound_ms": round(p99_bound_ms, 2),
                "p99_bounded": bool(p99 <= p99_bound_ms),
                "no_cold_compiles_after_warmup": bool(
                    len(new_events) == 0
                ),
                "cache_hit": None,  # caches off: not exercised here
                "cache_exercised": False,
            },
            "stats": engine.stats(),
            "metrics": engine.metrics_snapshot(),
        }
        engine.close()
        report["wall_s"] = round(time.perf_counter() - wall0, 1)
        problems = validate_serve_report(report)
        if problems:
            report["validator_problems"] = problems
            rc = 1
        _progress(
            f"mesh {spec}: {tput:.3f} img/s ({scaling:.2f}x single-"
            f"device, target {scaling_target:.2f}x), parity={parity} "
            f"exact={exact}, cold-after-warmup={len(new_events)}"
        )
        lines.append(json.dumps(report))

    cancel_watchdog()
    out_text = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(out_text)
    sys.stdout.write(out_text)
    sys.stdout.flush()
    return rc


def _run(cancel_watchdog, argv=None) -> int:
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke geometry (also TMR_BENCH_TINY=1)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--requests", type=int, default=None,
                    help="open-loop request count per rate")
    ap.add_argument("--rates", default=None,
                    help="comma-separated open-loop offered loads (img/s); "
                         "default: 0.4x and 0.8x of measured closed-loop")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request deadline for the open-loop sweep "
                         "(finite patience; default: none, the PR 3 "
                         "behavior)")
    ap.add_argument("--mesh", default=None,
                    help="comma-separated serving-mesh specs to sweep "
                         "(e.g. dp4,dp2tp2,tp4): one serve_report/v1 "
                         "line per shape with a mesh attachment, closed-"
                         "loop scaling vs the single-device engine, and "
                         "the AOT zero-cold-compile pin")
    args = ap.parse_args(argv)

    tiny = args.tiny or os.environ.get("TMR_BENCH_TINY", "") not in (
        "", "0", "false"
    )
    size = int(os.environ.get("TMR_BENCH_SIZE", 256 if tiny else 1024))
    dtype = "float32" if tiny else "bfloat16"

    if args.mesh:
        return _run_mesh_sweep(args, tiny, size, dtype, cancel_watchdog)

    import jax

    from tmr_tpu import obs
    from tmr_tpu.config import preset
    from tmr_tpu.diagnostics import (
        SERVE_REPORT_SCHEMA,
        validate_serve_report,
    )
    from tmr_tpu.inference import Predictor
    from tmr_tpu.serve import ServeEngine

    _progress(f"backend: {jax.devices()[0]} size={size} tiny={tiny}")
    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=size,
                 compute_dtype=dtype, batch_size=1)
    pred = Predictor(cfg)
    _progress("init_params (jitted init)")
    pred.init_params(seed=0, image_size=size)

    engine = ServeEngine(pred, batch=args.batch,
                         max_wait_ms=args.max_wait_ms)
    batch = engine._bound_for(("single", size, 17, 1))
    wall0 = time.perf_counter()
    unique, waves = _make_requests(size, batch)
    report = {
        "schema": SERVE_REPORT_SCHEMA,
        "device": str(jax.devices()[0]),
        "config": {
            "image_size": size,
            "batch": batch,
            "max_wait_ms": engine.max_wait_ms,
            "devices": len(engine.devices),
            "donate": engine.donate,
            "result_cache": engine.result_cache.capacity,
            "feature_cache": engine.feature_cache.capacity,
        },
        # numerics provenance: a storage-quantized engine's report says
        # so (quant.mode/storage/digest — validator-checked)
        **({"quant": engine.stats()["quant"]}
           if "quant" in engine.stats() else {}),
        "workloads": [],
    }

    # ---- warmup: compile the sequential B=1 program, the batched fused
    # program, and the feature path (backbone fill + heads) at BOTH the
    # lone and the batch-sized shapes, outside every timed window, on
    # throwaway images
    _progress("warmup compiles (sequential + batched + feature path)")
    _sequential_throughput(pred, unique[:1])
    rng_w = np.random.default_rng(99)
    w_imgs = [rng_w.standard_normal((size, size, 3)).astype(np.float32)
              for _ in range(batch)]
    _closed_loop(engine, [(im, unique[0][1]) for im in w_imgs]
                 + unique[:1])  # fused at B=batch and B=1; marks w_imgs seen
    for ex_w in ([[0.2, 0.2, 0.3, 0.31]], [[0.6, 0.6, 0.68, 0.7]]):
        ex_w = np.asarray(ex_w, np.float32)
        # one wave of batch-sized heads traffic (promotion fills first,
        # feature hits second) plus a lone request: the backbone-fill and
        # heads programs compile at every sub-bucket shape the timed
        # workloads can produce
        _closed_loop(engine, [[(im, ex_w) for im in w_imgs]], waves=True)
        engine.submit(w_imgs[0], ex_w + 0.01).result(timeout=600)

    # ---- exact_closed: unique traffic, bitwise check vs sequential
    _progress("workload exact_closed")
    occ0, cache0 = _snapshots(engine)
    seq_results = []
    for img, ex in unique:
        d = pred(img[None], ex[None])
        seq_results.append({k: np.asarray(d[k]) for k in
                            ("boxes", "scores", "refs", "valid")})
    seq_tput_unique = _sequential_throughput(pred, unique)
    # fresh engine state for exactness: the warmup populated caches with
    # some of these images — exactness must measure the fused batch path
    engine2 = ServeEngine(pred, batch=batch,
                          max_wait_ms=engine.max_wait_ms)
    o2, c2 = _snapshots(engine2)
    tput, lat, results = _closed_loop(engine2, unique)
    exact = all(
        _bitwise_equal(a, b) for a, b in zip(seq_results, results)
    )
    report["workloads"].append(
        _workload_record("exact_closed", "closed", len(unique), tput, lat,
                         engine2, o2, c2)
    )
    report["workloads"][-1]["sequential_img_per_sec"] = round(
        seq_tput_unique, 3
    )
    batch_ms = batch / tput * 1000.0
    engine2.close()
    _progress(f"exact_closed: serve {tput:.3f} img/s vs sequential "
              f"{seq_tput_unique:.3f} img/s, exact={exact}")

    # ---- mixed_closed: the interactive repeat mix (cache traffic)
    _progress("workload mixed_closed")
    flat = [r for wave in waves for r in wave]
    seq_tput_mixed = _sequential_throughput(pred, flat)
    occ0, cache0 = _snapshots(engine)
    m_tput, m_lat, _ = _closed_loop(engine, waves, waves=True)
    rec = _workload_record("mixed_closed", "closed", len(flat), m_tput,
                           m_lat, engine, occ0, cache0)
    rec["sequential_img_per_sec"] = round(seq_tput_mixed, 3)
    report["workloads"].append(rec)
    speedup = m_tput / seq_tput_mixed
    mixed_cache = rec["cache"]
    _progress(f"mixed_closed: serve {m_tput:.3f} img/s vs sequential "
              f"{seq_tput_mixed:.3f} img/s ({speedup:.2f}x)")

    # ---- open-loop offered-load sweep
    if args.rates:
        rates = [float(r) for r in args.rates.split(",") if r.strip()]
    else:
        rates = [round(tput * 0.4, 3), round(tput * 0.8, 3)]
    n_open = args.requests or (3 * batch if tiny else 8 * batch)
    rng = np.random.default_rng(7)
    low_rate_p99 = None
    for rate in rates:
        if rate <= 0:
            continue
        _progress(f"workload open_rate_{rate}")
        reqs = []
        small_ex = np.asarray([[0.45, 0.45, 0.53, 0.55]], np.float32)
        for _ in range(n_open):
            reqs.append((
                rng.standard_normal((size, size, 3)).astype(np.float32),
                small_ex,
            ))
        occ0, cache0 = _snapshots(engine)
        ov0 = engine.overload_counters()
        o_tput, o_lat, served = _open_loop(engine, reqs, rate,
                                           deadline_ms=args.deadline_ms)
        rec = _workload_record(f"open_rate_{rate}", "open", n_open, o_tput,
                               o_lat, engine, occ0, cache0)
        rec["offered_img_per_sec"] = rate
        # admission/shed/degrade deltas for THIS round — overload rounds
        # in a trend sweep stay interpretable (zeros with default knobs)
        ov1 = engine.overload_counters()
        rejected = ov1["admit_rejected"] - ov0["admit_rejected"]
        rec["admission"] = {
            "rejected": rejected,
            "shed": ov1["shed"] - ov0["shed"],
            "degraded": ov1["degraded"] - ov0["degraded"],
            "served": served,
            "reject_rate": round(rejected / max(n_open, 1), 4),
        }
        report["workloads"].append(rec)
        if low_rate_p99 is None:
            low_rate_p99 = rec["latency_ms"]["p99"]
        _progress(f"open_rate_{rate}: {rec['latency_ms']}")

    # ---- acceptance checks
    # p99 bound: at low offered load a request waits at most max_wait_ms
    # for batch-mates plus one (padded) batch execution; host-side slack
    # covers staging/fetch scheduling jitter (CPU thread scheduling is the
    # noisy term in the tiny smoke).
    slack_ms = 500.0 if jax.default_backend() == "cpu" else 50.0
    p99_bound_ms = engine.max_wait_ms + batch_ms + slack_ms
    cache_hits = (mixed_cache["result_cache"]["hits"]
                  + mixed_cache["feature_cache"]["hits"])
    report["checks"] = {
        "speedup_vs_sequential": round(speedup, 3),
        "speedup_ok": bool(speedup >= 1.5),
        "exact_match": bool(exact),
        "batch_ms": round(batch_ms, 2),
        "p99_ms": low_rate_p99,
        "p99_bound_ms": round(p99_bound_ms, 2),
        "p99_bounded": bool(
            low_rate_p99 is not None and low_rate_p99 <= p99_bound_ms
        ),
        "cache_hits": cache_hits,
        "cache_hit": bool(cache_hits > 0),
    }
    report["stats"] = engine.stats()
    # the engine's metrics registry as one metrics_report/v1 document —
    # latency AND counter state travel in the same JSON line (validated
    # as part of validate_serve_report)
    report["metrics"] = engine.metrics_snapshot()
    if obs.flight_enabled():
        # TMR_FLIGHT=1: the per-program device-time / MFU attribution
        # for everything this bench executed rides the same line
        # (mfu_report/v1; validate_serve_report checks the attachment)
        report["mfu"] = obs.mfu_report()
    engine.close()
    report["wall_s"] = round(time.perf_counter() - wall0, 1)
    problems = validate_serve_report(report)
    if problems:  # self-check: the emitted document must validate
        report["validator_problems"] = problems

    cancel_watchdog()  # before the success print: no success-then-watchdog
    line = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    return 0


def main(argv=None) -> int:
    """One serve_report/v1 JSON line on stdout, success or not: the shared
    bench_guard (same watchdog bench.py runs under) funnels wedges and
    crashes into a contractual error record."""
    from tmr_tpu.diagnostics import SERVE_REPORT_SCHEMA
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(
            json.dumps({"schema": SERVE_REPORT_SCHEMA, "error": msg}),
            flush=True,
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
