"""Measured proof of the TMR_FLEET_OBS fleet observability plane
(tmr_tpu/obs/fleetobs.py): cross-process trace propagation, heartbeat
metrics rollup, the stitched cluster timeline, and the fleet
HealthWatch — against a REAL multi-process stub fleet. Prints ONE
``fleet_obs_report/v1`` JSON document (schema + validator in
tmr_tpu/diagnostics.py):

- **overhead** — with the plane disabled (the default), the per-site
  guard is timed (ns) and a small in-process fleet measures the
  baseline request latency; the projected per-request overhead must be
  under 1%.
- **calm / outlier** — three subprocess workers split the traffic
  partitions, one paced 12x slower than its peers. A balanced warm-up
  window passes the fleet HealthWatch QUIET; the mixed window that
  exercises the slow worker fires EXACTLY ``worker_outlier_latency``,
  naming it. Every submit mints one trace id at the front door and the
  workers' serve spans come home on heartbeats: at least one complete
  frontdoor -> worker span chain must exist under a single trace id.
- **reconciliation** — the workers are stopped CLEANLY (SIGINT ->
  ``bye`` final flush): the coordinator's sum-of-beat-deltas must match
  every worker's final counter totals EXACTLY.
- **stitched timeline** — the merged Chrome trace (one track per
  process, clock offsets estimated from beat round-trips and stamped
  into the track names) must stay monotone after offset correction.
- **beat_gap** — a fresh two-worker fleet has one worker kill -9'd:
  the next HealthWatch pass fires EXACTLY ``beat_gap`` naming it, and
  the pass after stays quiet (the gap latches).

Usage:  python scripts/fleet_obs_probe.py [--out FILE]

Fast (seconds, numpy stub engines, CPU): rides tier-1 via
tests/test_fleetobs.py. One-JSON-line contract via bench_guard.
``scripts/bench_trend.py --fleet-obs`` rc-gates on the report.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")
scrub_cpu_tunnel_env()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIZE = 32
EX = np.asarray([[0.4, 0.4, 0.6, 0.6]], np.float32)
#: disabled-plane guard sites on one request's path: submit ctx mint,
#: terminal close, the worker's serve-span check, and the beat fold
_OBS_SITES_PER_REQUEST = 4


def _progress(msg: str) -> None:
    print(f"[fleet_obs_probe] {msg}", file=sys.stderr, flush=True)


def _img(seed: int):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)


def _poll(predicate, timeout_s: float, interval_s: float = 0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval_s)
    return predicate()


def _policy(lease_ttl_s: float):
    from tmr_tpu.parallel.leases import LeasePolicy

    return LeasePolicy(
        lease_ttl_s=lease_ttl_s, hb_interval_s=0.2,
        check_interval_s=0.05, straggler_factor=0.0,
        max_reassigns=1_000_000_000,
        resource_fail_workers=1_000_000_000,
    )


def _spawn_worker(wid: str, address, delay_ms: float) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu", TMR_FLEET_OBS="1")
    env.pop("TMR_FAULTS", None)  # the gauntlet runs fault-free
    env.pop("TMR_TRACE", None)  # the plane auto-enables worker tracing
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "serve_fleet.py"),
         "worker", "--coordinator", f"{address[0]}:{address[1]}",
         "--worker_id", wid, "--engine", "stub",
         "--delay_ms", str(delay_ms), "--batch", "2"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _holder_map(fleet) -> dict:
    """partition key -> holder wid (held partitions only; the state()
    holder field is a (wid, epoch) pair)."""
    out = {}
    for key, rec in fleet.state()["partitions"].items():
        holder = rec["holder"]
        if holder is None:
            continue
        out[key] = holder[0] if isinstance(holder, (tuple, list)) \
            else holder
    return out


def _distinct_holders(fleet, want: int):
    held = _holder_map(fleet)
    return held if (len(held) >= want
                    and len(set(held.values())) >= want) else None


def _await_spread(fleet, wids, timeout_s: float = 30.0):
    """Every partition held AND every worker in ``wids`` holding at
    least one. Spawning workers one at a time against this barrier
    makes the join rebalance deterministic: each hello sees an
    all-leased fleet (so it actually revokes excess), and the lease
    fairness cap hands the freed partition to the recruit — concurrent
    joins can instead settle with an idle worker forever."""
    n_parts = len(fleet.state()["partitions"])

    def ok():
        held = _holder_map(fleet)
        if len(held) < n_parts:
            return None
        holders = set(held.values())
        return held if all(w in holders for w in wids) else None

    return _poll(ok, timeout_s)


def _stable_holders(fleet, want: int, timeout_s: float = 60.0,
                    hold_s: float = 0.6):
    """Wait for ``want`` partitions held by ``want`` DISTINCT workers,
    STABLE across ``hold_s`` — the join rebalance revokes/regrants in
    flight, so a single distinct snapshot can be mid-shuffle."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        held = _poll(lambda: _distinct_holders(fleet, want),
                     max(deadline - time.monotonic(), 0.1))
        if not held:
            return None
        time.sleep(hold_s)
        if _holder_map(fleet) == held:
            return held
    return None


def _submit_wave(fleet, classes, per_class: int, seed: int,
                 paced: bool = False) -> int:
    """Submit ``per_class`` requests to each priority class; wait for
    every future (resolution proves the latency window landed in each
    worker's histogram). ``paced`` waits each round out before the
    next — one request in flight per worker, so a CALM window's p95 is
    the bare service time with no queueing skew between equal peers."""
    pending = []
    n = 0
    for i in range(per_class):
        futs = [fleet.submit(_img(seed + 31 * i + k), EX, priority=k)
                for k in classes]
        n += len(futs)
        if paced:
            for f in futs:
                f.result(timeout=60)
        else:
            pending.extend(futs)
    for f in pending:
        f.result(timeout=60)
    return n


def _await_window(fleet, min_count: int, timeout_s: float = 20.0) -> bool:
    """Wait until the folded per-worker latency histograms cover at
    least ``min_count`` requests (beats every 0.2s carry the deltas)."""
    fo = fleet.fleet_obs

    def landed():
        total = 0
        for acc in fo.metrics.per_worker().values():
            hist = (acc.get("histograms") or {}).get(
                "serve.request_latency_s") or {}
            total += int(hist.get("count") or 0)
        return total >= min_count
    return bool(_poll(landed, timeout_s))


def _complete_chains(chains: dict) -> int:
    """Count trace ids carrying a full cross-process chain: a front-
    door root span (parent 0, coordinator process) plus at least one
    worker span parented directly under it."""
    n = 0
    for recs in chains.values():
        roots = {r["span"] for r in recs
                 if r.get("parent") == 0 and r["proc"] == "coordinator"}
        if roots and any(r.get("parent") in roots
                         and r["proc"] != "coordinator" for r in recs):
            n += 1
    return n


def _measure_disabled_check_ns(iters: int = 50_000) -> float:
    """Amortized cost of one plane-disabled guard site (the ctx mint,
    which embeds the enablement check), in ns."""
    from tmr_tpu.obs import fleetobs

    assert not fleetobs.fleet_obs_enabled()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            fleetobs.make_ctx()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e9


def _baseline_request_ms(n_req: int = 16) -> float:
    """Mean request latency of a tiny DISABLED in-process fleet — the
    denominator of the projected disabled-plane overhead."""
    from tmr_tpu.serve.fleet import FleetWorker, ServeFleet, stub_engine

    fleet = ServeFleet([SIZE], classes=1, policy=_policy(2.0),
                       check_interval_s=0.05)
    addr = fleet.start()
    assert fleet.fleet_obs is None, "plane must be off for the baseline"
    worker = FleetWorker(addr, "w-base", stub_engine()).start()
    try:
        assert _poll(lambda: _holder_map(fleet), 30.0), \
            "baseline fleet never granted its partition"
        for f in [fleet.submit(_img(7 + i), EX) for i in range(4)]:
            f.result(timeout=30)  # warm the batcher
        t0 = time.perf_counter()
        for f in [fleet.submit(_img(100 + i), EX) for i in range(n_req)]:
            f.result(timeout=30)
        return (time.perf_counter() - t0) / n_req * 1000.0
    finally:
        worker.stop()
        fleet.close()


def _run(cancel_watchdog, argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    args = ap.parse_args(argv)
    wall0 = time.perf_counter()

    # deterministic start state: plane off, no fault schedules, and no
    # user TMR_TRACE override (enablement must auto-arm tracing)
    for knob in ("TMR_FLEET_OBS", "TMR_TRACE", "TMR_FAULTS"):
        os.environ.pop(knob, None)

    from tmr_tpu.diagnostics import (
        FLEET_OBS_REPORT_SCHEMA,
        validate_fleet_obs_report,
    )
    from tmr_tpu.obs import fleetobs
    from tmr_tpu.serve.fleet import ServeFleet

    procs: list = []

    def cleanup_workers():
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    # ---- overhead: the disabled plane, measured ----------------------
    _progress("disabled-plane guard micro-benchmark")
    disabled_ns = _measure_disabled_check_ns()
    _progress(f"disabled guard: {disabled_ns:.0f} ns/site")
    base_req_ms = _baseline_request_ms()
    overhead_pct = (disabled_ns * _OBS_SITES_PER_REQUEST
                    / (base_req_ms * 1e6) * 100.0)
    _progress(f"baseline request {base_req_ms:.2f} ms -> projected "
              f"disabled overhead {overhead_pct:.5f}%")

    # ---- plane ON (also auto-arms coordinator tracing) ---------------
    fleetobs.configure(enabled=True)

    try:
        # ---- phase A: calm window, slow-worker window, clean stop ----
        _progress("phase A: 3-worker fleet, one 12x slower")
        fleet_a = ServeFleet([SIZE], classes=3, policy=_policy(2.0),
                             check_interval_s=0.05)
        addr_a = fleet_a.start()
        slow_wid = "w-slow"
        workers_a = {}
        for wid, delay in (("w-a", 10.0), ("w-b", 10.0),
                           (slow_wid, 120.0)):
            workers_a[wid] = _spawn_worker(wid, addr_a, delay_ms=delay)
            procs.append(workers_a[wid])
            if not _await_spread(fleet_a, list(workers_a)):
                raise RuntimeError(
                    f"join rebalance never gave {wid!r} a partition: "
                    f"{_holder_map(fleet_a)}"
                )
        held = _stable_holders(fleet_a, 3)
        if not held:
            raise RuntimeError(
                f"join rebalance never spread 3 partitions across 3 "
                f"workers: {_holder_map(fleet_a)}"
            )
        klass_of = {wid: int(key.rsplit("c", 1)[1])
                    for key, wid in held.items()}
        fast_classes = sorted(k for w, k in klass_of.items()
                              if w != slow_wid)
        per_class = 12

        # calm: balanced traffic on the FAST workers only — the slow
        # worker has no window yet, so a healthy pass must stay quiet
        n_calm = _submit_wave(fleet_a, fast_classes, per_class,
                              seed=10, paced=True)
        assert _await_window(fleet_a, n_calm), \
            "calm-window deltas never folded"
        calm_fired = fleet_a.fleet_obs_pass()
        _progress(f"calm pass: {[a['anomaly'] for a in calm_fired]}")

        # outlier: mixed traffic across all three — the slow worker's
        # window p95 must fire EXACTLY worker_outlier_latency
        n_mixed = _submit_wave(fleet_a, sorted(klass_of.values()),
                               per_class, seed=400)
        assert _await_window(fleet_a, n_calm + n_mixed), \
            "outlier-window deltas never folded"
        outlier_fired = fleet_a.fleet_obs_pass()
        _progress(f"outlier pass: "
                  f"{[a['anomaly'] for a in outlier_fired]}")

        # clean leave: SIGINT -> worker.stop() -> bye final flush
        for p in workers_a.values():
            p.send_signal(signal.SIGINT)
        for p in workers_a.values():
            p.wait(timeout=20)
        fo_a = fleet_a.fleet_obs
        assert _poll(
            lambda: len(
                fo_a.metrics.reconcile()["workers_with_finals"]
            ) >= 3,
            20.0,
        ), "final snapshots never arrived on bye"
        report_a = fo_a.report()
        chains = fo_a.span_chains()
        complete = _complete_chains(chains)
        _progress(
            f"chains: {complete}/{len(chains)} complete, "
            f"reconciliation exact="
            f"{report_a['reconciliation']['exact']}, "
            f"trace monotone={report_a['trace']['monotone']}"
        )
        fleet_a.close()

        # ---- phase B: kill -9 -> beat_gap, exactly once --------------
        _progress("phase B: 2-worker fleet, one kill -9")
        # long lease TTL: the killed worker must still be LIVE (not
        # reaped) when the pass runs, so beat_gap — not the lease
        # machinery — is what notices it
        fleet_b = ServeFleet([SIZE], classes=2, policy=_policy(30.0),
                             check_interval_s=0.05)
        addr_b = fleet_b.start()
        killed_wid = "w-k1"
        workers_b = {}
        for wid in ("w-k0", killed_wid):
            workers_b[wid] = _spawn_worker(wid, addr_b, delay_ms=0.0)
            procs.append(workers_b[wid])
            if not _await_spread(fleet_b, list(workers_b)):
                raise RuntimeError(
                    f"phase B join never gave {wid!r} a partition: "
                    f"{_holder_map(fleet_b)}"
                )
        assert _stable_holders(fleet_b, 2), \
            "phase B fleet never spread 2 partitions"
        fo_b = fleet_b.fleet_obs
        assert _poll(
            lambda: all(
                rec["beats"] >= 2
                for rec in fo_b.worker_state().values()
            ) and len(fo_b.worker_state()) >= 2,
            20.0,
        ), "phase B workers never beat"
        workers_b[killed_wid].kill()
        workers_b[killed_wid].wait(timeout=10)
        time.sleep(1.2)  # > beat_gap bound (4 x 0.2s beat interval)
        gap_fired = fleet_b.fleet_obs_pass()
        gap_repeat = fleet_b.fleet_obs_pass()  # latched: must be quiet
        _progress(f"beat_gap pass: {[a['anomaly'] for a in gap_fired]}"
                  f", repeat: {[a['anomaly'] for a in gap_repeat]}")
        workers_b_state = fo_b.worker_state()
        beat_errors_b = fo_b.metrics.errors
        workers_b["w-k0"].send_signal(signal.SIGINT)
        workers_b["w-k0"].wait(timeout=20)
        fleet_b.close()
    finally:
        cleanup_workers()

    report = {
        "schema": FLEET_OBS_REPORT_SCHEMA,
        "config": {
            "image_size": SIZE,
            "phase_a_workers": 3,
            "phase_b_workers": 2,
            "hb_interval_s": 0.2,
            "requests_per_class": per_class,
            "slow_delay_ms": 120.0,
            "fast_delay_ms": 10.0,
            "slow_worker": slow_wid,
            "killed_worker": killed_wid,
        },
        "workers": {**report_a["workers"], **workers_b_state},
        "merged": report_a["merged"],
        "per_worker": report_a["per_worker"],
        "reconciliation": report_a["reconciliation"],
        "trace": report_a["trace"],
        "chains": {"total": len(chains), "complete": complete},
        "anomalies": {
            "calm": calm_fired,
            "outlier": outlier_fired,
            "beat_gap": gap_fired,
            "beat_gap_repeat": gap_repeat,
        },
        "beat_errors": report_a["beat_errors"] + beat_errors_b,
        "overhead": {
            "disabled_ns_per_check": round(disabled_ns, 1),
            "check_sites_per_request": _OBS_SITES_PER_REQUEST,
            "baseline_request_ms": round(base_req_ms, 3),
            "overhead_disabled_pct": round(overhead_pct, 6),
        },
        "wall_s": round(time.perf_counter() - wall0, 1),
    }
    report["checks"] = {
        "span_chain_complete": bool(complete >= 1),
        "metrics_reconciled": report_a["reconciliation"]["exact"]
        is True,
        "stitched_monotone": bool(
            report_a["trace"]["monotone"] is True
            and report_a["trace"]["events"] > 0
            and report_a["trace"]["tracks"] >= 4
        ),
        "slow_worker_exact": bool(
            [a["anomaly"] for a in outlier_fired]
            == ["worker_outlier_latency"]
            and outlier_fired[0]["evidence"]["worker"] == slow_wid
        ),
        "beat_gap_exact": bool(
            [a["anomaly"] for a in gap_fired] == ["beat_gap"]
            and gap_fired[0]["evidence"]["worker"] == killed_wid
            and gap_repeat == []
        ),
        "calm_quiet": calm_fired == [],
        "overhead_ok": bool(overhead_pct < 1.0),
    }
    problems = validate_fleet_obs_report(report)
    if problems:  # self-check: the emitted document must validate
        report["validator_problems"] = problems

    ok = all(report["checks"].values()) and not problems
    cancel_watchdog()  # before the success print: no success-then-watchdog
    line = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    if not ok:
        failed = [k for k, v in report["checks"].items() if not v]
        _progress(f"FAILED checks: {failed} problems={problems}")
        return 1
    _progress("all checks passed")
    return 0


def main(argv=None) -> int:
    """One fleet_obs_report/v1 JSON line on stdout, success or not:
    the shared bench_guard funnels wedges and crashes into a
    contractual error record."""
    from tmr_tpu.diagnostics import FLEET_OBS_REPORT_SCHEMA
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(
            json.dumps({"schema": FLEET_OBS_REPORT_SCHEMA,
                        "error": msg}),
            flush=True,
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
