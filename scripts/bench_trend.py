"""Bench-history trend reader: the committed ``BENCH_r0*.json`` driver
records + the live bench files, reduced to ONE ``bench_trend/v1`` JSON
line with headline/MFU regressions between rounds flagged.

The BENCH trajectory had no reader — three rounds recorded rc!=0 / 0.0
headlines while a committed 21.07 img/s measurement existed, and nothing
mechanical would have flagged a real regression either. This script (and
the same document embedded per round by bench.py under
``TMR_BENCH_TREND=1``) makes the trajectory machine-checkable: per-round
value/mfu with provenance (measured / carried / error) and a
relative-threshold regression scan across consecutive usable rounds.

Usage:  python scripts/bench_trend.py [--repo DIR] [--threshold PCT]
                                      [--out FILE]

Exit code 1 when a regression is flagged (CI-gateable), else 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tmr_tpu.diagnostics import validate_bench_trend  # noqa: E402
from tmr_tpu.utils.bench_trend import (  # noqa: E402
    DEFAULT_THRESHOLD,
    collect_bench_trend,
    read_chaos_report,
    read_fleet_obs_report,
    read_fleet_report,
    read_gallery_report,
    read_live_tune_report,
    read_serve_sweep,
    read_stream_report,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repository root holding BENCH_r*.json (default: this repo)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative drop counting as a regression "
                         f"(default {DEFAULT_THRESHOLD})")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    ap.add_argument("--serve-sweep", default=None,
                    help="read a serve_bench.py --mesh sweep file "
                         "(JSONL of serve_report/v1 lines) instead of "
                         "the BENCH history: one JSON line with the "
                         "per-mesh-shape scaling table; rc 1 when any "
                         "shape fails its scaling/exactness/AOT checks")
    ap.add_argument("--fleet", default=None,
                    help="read an elastic_serve_report/v1 file "
                         "(elastic_serve_probe output) instead of the "
                         "BENCH history: one JSON line with per-phase "
                         "accounting; rc 1 unless double_served is "
                         "ZERO, the offered == completed + rejected + "
                         "shed + errors reconciliation is exact, and "
                         "every probe check passed")
    ap.add_argument("--gallery", default=None,
                    help="read a gallery_report/v1 file "
                         "(gallery_bench output) instead of the BENCH "
                         "history: one JSON line with the prefilter "
                         "rung table; rc 1 unless the fused arm is "
                         "exact, backbone executions == frames "
                         "(amortized), and the elected prefilter "
                         "top-k meets its recall + cut targets")
    ap.add_argument("--stream", default=None,
                    help="read a stream_report/v1 file (stream_bench "
                         "output) instead of the BENCH history: one "
                         "JSON line with the reuse/throughput "
                         "summary; rc 1 unless backbone executions "
                         "are amortized below the frame count, the "
                         "frames/s speedup clears 1.5x, every "
                         "'changed' frame is bitwise-exact, reuse "
                         "never crossed stream ids, and every reused "
                         "frame carried the temporal_reuse label")
    ap.add_argument("--chaos", default=None,
                    help="read a serve_chaos_report/v1 file "
                         "(serve_chaos_probe output) instead of the "
                         "BENCH history: one JSON line with the "
                         "pattern-loss/fault-ledger summary; rc 1 "
                         "unless ZERO registered patterns were lost "
                         "across the kill rounds, healthy-fleet "
                         "fan-out stayed byte-identical to the single "
                         "bank, every injected fault was observed AND "
                         "accounted for, degraded searches were "
                         "exactly labeled, and every probe check "
                         "passed")
    ap.add_argument("--fleet-obs", default=None, dest="fleet_obs",
                    help="read a fleet_obs_report/v1 file "
                         "(fleet_obs_probe output) instead of the "
                         "BENCH history: one JSON line with the "
                         "span-chain / reconciliation / timeline "
                         "summary; rc 1 unless at least one "
                         "cross-process span chain is complete, the "
                         "sum-of-deltas metrics reconciliation is "
                         "exact, the stitched timeline is monotone "
                         "after clock-offset correction, the slow "
                         "worker and killed worker each fired exactly "
                         "their anomaly, the calm pass stayed quiet, "
                         "and the disabled-mode overhead is under 1%")
    ap.add_argument("--live-tune", default=None, dest="live_tune",
                    help="read a live_tune_report/v1 file "
                         "(live_tune_probe output) instead of the "
                         "BENCH history: one JSON line with the "
                         "election summary; rc 1 unless disabled mode "
                         "is bitwise-identical, the shadow fraction is "
                         "under 1%% of steady-state device seconds, "
                         "the device-seconds budget held, the faster "
                         "candidate was promoted decisively and "
                         "serves faster with zero hot-path cold "
                         "compiles, the injected anomaly demoted with "
                         "a recorded cause, the winner banks stayed "
                         "generation-isolated, and the decision log "
                         "replays to the same elections")
    ap.add_argument("--max-carried-age-h", type=float, default=None,
                    dest="max_carried_age_h",
                    help="BENCH-history mode: flag carried rounds whose "
                         "stale_hours exceed this bound (warn to stderr "
                         "by default; rc 1 with --strict-carried)")
    ap.add_argument("--strict-carried", action="store_true",
                    dest="strict_carried",
                    help="with --max-carried-age-h: a stale carried "
                         "headline fails the run (rc 1) instead of "
                         "warning")
    args = ap.parse_args(argv)

    if args.live_tune:
        doc = read_live_tune_report(args.live_tune)
        line = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        if "error" in doc:
            return 1
        ck = doc["checks"]
        # EVERY reduced check gates fail-closed — a probe that never
        # exercised a phase reads as a failure, not a silent pass
        return 0 if all(v is True for v in ck.values()) else 1

    if args.fleet_obs:
        doc = read_fleet_obs_report(args.fleet_obs)
        line = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        if "error" in doc:
            return 1
        ck = doc["checks"]
        return 0 if (ck["span_chain_complete"]
                     and ck["metrics_reconciled"]
                     and ck["stitched_monotone"]
                     and ck["slow_worker_exact"]
                     and ck["beat_gap_exact"]
                     and ck["calm_quiet"]
                     and ck["overhead_ok"]) else 1

    if args.chaos:
        doc = read_chaos_report(args.chaos)
        line = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        if "error" in doc:
            return 1
        ck = doc["checks"]
        return 0 if (ck["zero_patterns_lost"]
                     and ck["fanout_byte_identical"]
                     and ck["all_faults_observed"]
                     and ck["all_faults_accounted"]
                     and ck["degraded_exactly_labeled"]
                     and ck["probe_checks_pass"]) else 1

    if args.stream:
        doc = read_stream_report(args.stream)
        line = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        if "error" in doc:
            return 1
        ck = doc["checks"]
        return 0 if (ck["backbone_amortized"] and ck["speedup_ok"]
                     and ck["changed_frames_exact"]
                     and ck["cross_stream_isolated"]
                     and ck["reuse_labeled"]) else 1

    if args.gallery:
        doc = read_gallery_report(args.gallery)
        line = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        if "error" in doc:
            return 1
        ck = doc["checks"]
        # EVERY check the reducer surfaced must hold — the n_sweep
        # gates (index_sublinear / index_recall_ok / index_off_exact /
        # fleet_probe_ok) activate fail-closed exactly when the report
        # carries the optional catalog-scale sweep section
        return 0 if (ck["bitwise_exact"] and ck["backbone_amortized"]
                     and ck["prefilter_recall_ok"]
                     and ck["prefilter_cut_ok"]
                     and all(v is True for v in ck.values())) else 1

    if args.fleet:
        doc = read_fleet_report(args.fleet)
        line = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        if "error" in doc:
            return 1
        ck = doc["checks"]
        return 0 if (ck["zero_double_served"]
                     and ck["reconciliation_exact"]
                     and ck["probe_checks_pass"]) else 1

    if args.serve_sweep:
        doc = read_serve_sweep(args.serve_sweep)
        line = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        if "error" in doc:
            return 1
        ck = doc["checks"]
        return 0 if (ck["all_exact"] and ck["all_scaling_ok"]
                     and ck["all_warm"]) else 1

    doc = collect_bench_trend(args.repo, threshold=args.threshold,
                              max_carried_age_h=args.max_carried_age_h)
    problems = validate_bench_trend(doc)
    if problems:  # self-check: the emitted document must validate
        doc["validator_problems"] = problems
    line = json.dumps(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    if "error" in doc:
        return 1
    stale = doc.get("stale_carried") or ()
    if stale:
        # stdout stays the one JSON line; the staleness verdict is a
        # human-facing warning unless --strict-carried arms the gate
        for rec in stale:
            print(f"[bench_trend] carried round {rec['label']!r} is "
                  f"stale: {rec['stale_hours']}h > "
                  f"{args.max_carried_age_h}h bound", file=sys.stderr)
        if args.strict_carried:
            return 1
    return 1 if doc["checks"]["regressed"] else 0


if __name__ == "__main__":
    sys.exit(main())
