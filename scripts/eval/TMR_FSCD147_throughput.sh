#!/usr/bin/env bash
# Throughput variant of the FSCD-147 eval (beyond the reference, which
# forces eval batch 1): --eval_batch_size batches size-bucketed eval
# images through the fused program, --mesh_data spreads each batch over
# the local chips (the loop shards whenever the batch divides the axis;
# ragged tails fall back per image), and --autotune picks the measured
# kernel formulations, cached per (device, shape) after the first run.
# Metrics match the batch-1 protocol (per-image JSON collection is batch-
# order agnostic; the documented caveat is the logged eval LOSS only).
python main.py \
  --project_name "Few-Shot Pattern Detection" \
  --datapath /data/fscd-147 \
  --logpath ./outputs/FSCD147 \
  --modeltype matching_net \
  --template_type roi_align \
  --dataset FSCD147 \
  --num_workers 4 \
  --batch_size 1 \
  --eval_batch_size 8 \
  --num_exemplars 1 \
  --backbone sam \
  --encoder original \
  --emb_dim 512 \
  --decoder_num_layer 1 \
  --decoder_kernel_size 3 \
  --feature_upsample \
  --positive_threshold 0.5 \
  --negative_threshold 0.5 \
  --NMS_cls_threshold 0.25 \
  --NMS_iou_threshold 0.5 \
  --fusion \
  --nowandb \
  --device tpu \
  --mesh_data -1 \
  --multi_gpu \
  --autotune \
  --eval \
  "$@"
