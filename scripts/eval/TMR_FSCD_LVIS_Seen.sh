#!/usr/bin/env bash
# Eval with the best checkpoint (reference scripts/eval/TMR_FSCD_LVIS_Seen.sh):
# batch 1, per-dataset NMS cls threshold 0.1. Append --refine_box for
# SAM box refinement (commented out in the reference too).
python main.py \
  --project_name "Few-Shot Pattern Detection" \
  --datapath /data/fscd-lvis \
  --logpath ./outputs/FSCD_LVIS_Seen \
  --modeltype matching_net \
  --template_type roi_align \
  --dataset FSCD_LVIS_Seen \
  --num_workers 1 \
  --batch_size 1 \
  --num_exemplars 1 \
  --backbone sam \
  --encoder original \
  --emb_dim 512 \
  --decoder_num_layer 1 \
  --decoder_kernel_size 3 \
  --feature_upsample \
  --positive_threshold 0.5 \
  --negative_threshold 0.5 \
  --NMS_cls_threshold 0.1 \
  --NMS_iou_threshold 0.5 \
  --fusion \
  --nowandb \
  --device tpu \
  --eval #\
#  --refine_box
