"""Chaos probe for the elastic serve fleet (tmr_tpu/serve/fleet.py).

The chaos_probe --elastic story applied to SERVING: drive a fleet of
stub-engine worker processes through the three failure modes the lease
discipline must survive, and prove the exactly-once accounting holds.
Prints ONE ``elastic_serve_report/v1`` JSON document (schema + validator
in tmr_tpu/diagnostics.py):

- **kill** — two workers split the traffic partitions; one is
  kill -9'd MID-BATCH. Its partition reassigns under epoch+1
  (``worker_exit``), the in-flight requests re-submit to the survivor,
  and every future ends terminal: ``offered == completed + rejected +
  shed + errors`` EXACTLY (probe-side future tallies AND fleet-side
  counters), zero double-served request ids, every completed result
  carrying its own image's stub signature (crossed wires would show).
- **fence** — a lone SLOW worker is SIGSTOPped past the lease TTL: the
  partition revokes (``stale_heartbeat``), and on SIGCONT the worker's
  already-running computation finishes and sends a result under the
  REVOKED epoch — the front door's commit fence rejects it (counted
  ``fenced_results``, with a lease-level ``commit`` fence record), the
  re-leased epoch serves the request exactly once.
- **recruit** — one worker at capacity is offered a 3× spike: sustained
  queue saturation RECRUITS a second worker through the spawner
  (``fleet.recruit``), a ``scale_out`` rebalance hands it real
  partitions, the spike is absorbed with zero rejections — and the
  degrade ladder (auto mode) never leaves level 0, because scale-out is
  elected BEFORE degradation sees an anomaly.

Rebalance latency (revocation → re-grant) is recorded per phase and
checked against a bound derived from the lease TTL.

Usage:  python scripts/elastic_serve_probe.py [--tiny] [--out FILE]

Fast (seconds, numpy stub engines, CPU): rides tier-1 via
tests/test_elastic_serve_probe.py. One-JSON-line contract via
bench_guard. ``scripts/bench_trend.py --fleet`` rc-gates on the
report's zero-double-served and reconciliation fields.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")
scrub_cpu_tunnel_env()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIZE = 32
EX = np.asarray([[0.4, 0.4, 0.6, 0.6]], np.float32)


def _progress(msg: str) -> None:
    print(f"[elastic_serve_probe] {msg}", file=sys.stderr, flush=True)


def _images(n: int, seed: int):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)
            for _ in range(n)]


def _spawn_worker(wid: str, address, delay_ms: float,
                  batch: int = 2) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TMR_FAULTS", None)  # the process gauntlet runs fault-free
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "serve_fleet.py"),
         "worker", "--coordinator", f"{address[0]}:{address[1]}",
         "--worker_id", wid, "--engine", "stub",
         "--delay_ms", str(delay_ms), "--batch", str(batch)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _poll(predicate, timeout_s: float, interval_s: float = 0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval_s)
    return predicate()


def _policy():
    from tmr_tpu.parallel.leases import LeasePolicy

    return LeasePolicy(
        lease_ttl_s=1.0, hb_interval_s=0.2, check_interval_s=0.05,
        straggler_factor=0.0, max_reassigns=1_000_000_000,
        resource_fail_workers=1_000_000_000,
    )


def _await_holders(fleet, want: int, timeout_s: float = 30.0) -> bool:
    """Wait until ``want`` partitions have a holder."""
    return bool(_poll(
        lambda: sum(
            1 for rec in fleet.state()["partitions"].values()
            if rec["holder"] is not None
        ) >= want,
        timeout_s,
    ))


def _collect(futs, imgs, timeout_s: float = 120.0):
    """Drain futures into probe-side outcome tallies + signature check."""
    from tmr_tpu.serve.admission import RejectedError
    from tmr_tpu.serve.fleet import stub_signature

    outcomes = {"completed": 0, "rejected": 0, "shed": 0, "errors": 0}
    signatures_ok = True
    terminal = True
    for im, fut in zip(imgs, futs):
        try:
            r = fut.result(timeout=timeout_s)
        except RejectedError as e:
            if e.cause in ("deadline", "shutdown"):
                outcomes["shed"] += 1
            else:
                outcomes["rejected"] += 1
            continue
        except Exception:
            outcomes["errors"] += 1
            continue
        outcomes["completed"] += 1
        if float(r["scores"][0, 0]) != stub_signature(im):
            signatures_ok = False
    terminal = all(f.done() for f in futs)
    return outcomes, signatures_ok, terminal


def _phase_doc(name: str, fleet, offered: int, outcomes: dict,
               extra: dict) -> dict:
    doc = {
        "name": name,
        "offered": offered,
        "outcomes": outcomes,
        "fleet": fleet.report(),
        **extra,
    }
    acc = doc["fleet"]["accounting"]
    doc["accounting_matches_probe"] = bool(
        acc["offered"] == offered
        and all(acc[k] == outcomes[k] for k in outcomes)
    )
    return doc


def _run(cancel_watchdog, argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="accepted for CLI symmetry (the probe is "
                         "already tiny: stub engines, no XLA)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    from tmr_tpu.diagnostics import (
        ELASTIC_SERVE_REPORT_SCHEMA,
        validate_elastic_serve_report,
    )
    from tmr_tpu.serve.degrade import DegradeController
    from tmr_tpu.serve.fleet import ServeFleet

    wall0 = time.perf_counter()
    policy = _policy()
    rebalance_bound_s = policy.lease_ttl_s + 4.0
    phases = []
    workers: list = []

    def cleanup_workers():
        for proc in workers:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
        workers.clear()

    # ---------------- phase 1: kill -9 a serve worker mid-batch
    _progress("phase kill: 2 workers, one kill -9'd mid-batch")
    fleet = ServeFleet([SIZE], classes=2, policy=policy,
                       check_interval_s=0.05, max_resubmits=4)
    address = fleet.start()
    workers[:] = [_spawn_worker(f"k{i}", address, delay_ms=60.0)
                  for i in range(2)]
    both_held = _await_holders(fleet, 2)
    # identify the two holders (post scale-out rebalance both workers
    # hold one partition each)
    holders = {
        rec["holder"][0] for rec in fleet.state()["partitions"].values()
        if rec["holder"]
    }
    imgs = _images(24, seed=1)
    futs = [fleet.submit(im, EX, priority=i % 2)
            for i, im in enumerate(imgs)]
    time.sleep(0.25)  # several requests now mid-batch on each worker
    victim_wid = sorted(holders)[0] if holders else "k0"
    victim = workers[int(victim_wid[1])]
    os.kill(victim.pid, signal.SIGKILL)
    _progress(f"killed worker {victim_wid} (pid {victim.pid})")
    outcomes, sigs_ok, terminal = _collect(futs, imgs)
    reassigned = _poll(
        lambda: any(r["cause"] == "worker_exit"
                    for r in fleet.state()["reassignments"]),
        10.0,
    )
    time.sleep(0.3)  # let any straggling late results commit (fenced)
    kill_doc = _phase_doc("kill", fleet, len(imgs), outcomes, {
        "both_workers_held": bool(both_held),
        "signatures_ok": bool(sigs_ok),
        "futures_terminal": bool(terminal),
        "worker_exit_reassigned": bool(reassigned),
        "resubmitted": fleet.counters()["resubmitted"],
    })
    phases.append(kill_doc)
    fleet.close()
    cleanup_workers()
    _progress(f"kill outcomes: {outcomes}")

    # -------- phase 2: SIGSTOP past the TTL, fenced late result
    _progress("phase fence: lone slow worker SIGSTOPped past the TTL")
    fleet = ServeFleet([SIZE], classes=1, policy=policy,
                       check_interval_s=0.05, max_resubmits=6)
    address = fleet.start()
    workers[:] = [_spawn_worker("f0", address, delay_ms=1500.0, batch=1)]
    _await_holders(fleet, 1)
    imgs = _images(1, seed=2)
    futs = [fleet.submit(imgs[0], EX)]
    time.sleep(0.4)  # routed; the 1.5 s stub call is now running
    os.kill(workers[0].pid, signal.SIGSTOP)
    revoked = _poll(
        lambda: any(r["cause"] == "stale_heartbeat"
                    for r in fleet.state()["reassignments"]),
        10.0,
    )
    os.kill(workers[0].pid, signal.SIGCONT)
    _progress("SIGCONT; awaiting the fenced late result + re-serve")
    outcomes, sigs_ok, terminal = _collect(futs, imgs)
    fenced = _poll(
        lambda: fleet.counters()["fenced_results"] >= 1, 10.0,
    )
    fence_doc = _phase_doc("fence", fleet, len(imgs), outcomes, {
        "stale_heartbeat_revoked": bool(revoked),
        "fenced_late_result": bool(fenced),
        "signatures_ok": bool(sigs_ok),
        "futures_terminal": bool(terminal),
    })
    phases.append(fence_doc)
    fleet.close()
    cleanup_workers()
    _progress(f"fence outcomes: {outcomes} fenced={fenced}")

    # ------------- phase 3: recruitment absorbs a 3x spike
    _progress("phase recruit: 1 worker at capacity, 3x spike")
    spawn_counter = {"n": 0}

    def spawner(i: int) -> None:
        spawn_counter["n"] += 1
        workers.append(
            _spawn_worker(f"r{i + 1}", address, delay_ms=10.0)
        )

    fleet = ServeFleet(
        [SIZE], classes=2, policy=policy, check_interval_s=0.1,
        max_resubmits=4, spawner=spawner, saturation_pending=6,
        recruit_passes=2, recruit_grace=20, max_workers=3,
        degrade=DegradeController(mode="auto"),
    )
    address = fleet.start()
    workers[:] = [_spawn_worker("r0", address, delay_ms=50.0)]
    _await_holders(fleet, 2)
    workers_before = 1
    # capacity with one worker ~ batch/delay = 2/0.05 = 40 req/s;
    # offer ~3x for ~1.5 s
    imgs = _images(90, seed=3)
    futs = []
    period = 1.0 / 120.0
    t0 = time.perf_counter()
    for i, im in enumerate(imgs):
        target = t0 + i * period
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        futs.append(fleet.submit(im, EX, priority=i % 2))
    outcomes, sigs_ok, terminal = _collect(futs, imgs)
    rec = fleet.report()
    recruit_doc = _phase_doc("recruit", fleet, len(imgs), outcomes, {
        "signatures_ok": bool(sigs_ok),
        "futures_terminal": bool(terminal),
        "workers_before": workers_before,
        "workers_after": workers_before + spawn_counter["n"],
        "recruit_rounds": rec["recruitment"]["rounds"],
        "scale_out_rebalanced": any(
            r["cause"] == "scale_out" for r in rec["reassignments"]
        ),
        "degrade_level": rec["degrade"]["level"],
        "degrade_max_seen": rec["degrade"]["max_seen"],
    })
    phases.append(recruit_doc)
    fleet.close()
    cleanup_workers()
    _progress(f"recruit outcomes: {outcomes} "
              f"rounds={rec['recruitment']['rounds']} "
              f"degrade_max={rec['degrade']['max_seen']}")

    # ------------------------------------------------- combined document
    keys = ("offered", "completed", "rejected", "shed", "errors",
            "resubmitted", "fenced_results", "late_results",
            "double_served")
    combined = {
        k: sum(p["fleet"]["accounting"][k] for p in phases)
        for k in keys
    }
    max_rebalance = max(
        p["fleet"]["rebalance"]["max_latency_s"] for p in phases
    )
    rebalance_count = sum(
        p["fleet"]["rebalance"]["count"] for p in phases
    )
    report = {
        "schema": ELASTIC_SERVE_REPORT_SCHEMA,
        "config": {
            "image_size": SIZE,
            "lease_ttl_s": policy.lease_ttl_s,
            "hb_interval_s": policy.hb_interval_s,
            "phases": [p["name"] for p in phases],
        },
        "phases": phases,
        "accounting": combined,
        "rebalance": {
            "count": rebalance_count,
            "max_latency_s": max_rebalance,
            "bound_s": rebalance_bound_s,
            "bounded": bool(max_rebalance <= rebalance_bound_s),
        },
        "recruitment": {
            "rounds": int(recruit_doc["recruit_rounds"]),
            "workers_before": int(recruit_doc["workers_before"]),
            "workers_after": int(recruit_doc["workers_after"]),
            "degrade_level": int(recruit_doc["degrade_level"]),
            "degrade_max_seen": int(recruit_doc["degrade_max_seen"]),
        },
        "checks": {
            "futures_terminal": all(
                p["futures_terminal"] for p in phases
            ),
            "zero_double_served": combined["double_served"] == 0,
            "accounting_exact_probe": all(
                p["offered"] == sum(
                    p["outcomes"][k] for k in
                    ("completed", "rejected", "shed", "errors")
                ) for p in phases
            ),
            "accounting_exact_fleet": all(
                p["accounting_matches_probe"] for p in phases
            ),
            "results_correct": all(
                p["signatures_ok"] for p in phases
            ),
            "worker_exit_reassigned": bool(
                kill_doc["worker_exit_reassigned"]
            ),
            "fenced_late_result": bool(fence_doc["fenced_late_result"]),
            "rebalance_bounded": bool(
                max_rebalance <= rebalance_bound_s
            ),
            "recruitment_absorbed": bool(
                recruit_doc["recruit_rounds"] >= 1
                and recruit_doc["workers_after"]
                > recruit_doc["workers_before"]
                and recruit_doc["outcomes"]["completed"]
                == recruit_doc["offered"]
            ),
            "degrade_level0": bool(
                recruit_doc["degrade_max_seen"] == 0
            ),
        },
        "wall_s": round(time.perf_counter() - wall0, 1),
    }
    problems = validate_elastic_serve_report(report)
    if problems:  # self-check: the emitted document must validate
        report["validator_problems"] = problems

    ok = all(report["checks"].values()) and not problems
    cancel_watchdog()  # before the success print: no success-then-watchdog
    line = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    if not ok:
        failed = [k for k, v in report["checks"].items() if not v]
        _progress(f"FAILED checks: {failed} problems={problems}")
        return 1
    _progress("all checks passed")
    return 0


def main(argv=None) -> int:
    """One elastic_serve_report/v1 JSON line on stdout, success or not:
    the shared bench_guard funnels wedges and crashes into a
    contractual error record."""
    from tmr_tpu.diagnostics import ELASTIC_SERVE_REPORT_SCHEMA
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(
            json.dumps({"schema": ELASTIC_SERVE_REPORT_SCHEMA,
                        "error": msg}),
            flush=True,
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
