"""Per-component timing breakdown of the flagship inference program.

Times each stage of the fused FSCD-147 eval program (SAM ViT-B @ 1024,
feature upsample, 512-d matcher, decoders, peak decode + NMS) in isolation
on the current default device, so perf work has a measured target instead of
guesses. Run on the real TPU:

    python scripts/profile_breakdown.py

Prints a JSON breakdown {stage: seconds_per_batch}.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from tmr_tpu.config import preset
from tmr_tpu.models import build_model
from tmr_tpu.utils.cache import enable_compilation_cache

BATCH = 4
SIZE = 1024
ITERS = 5


def timeit(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / ITERS


def main():
    enable_compilation_cache()
    cfg = preset(
        "TMR_FSCD147",
        backbone="sam_vit_b",
        image_size=SIZE,
        compute_dtype="bfloat16",
        batch_size=BATCH,
    )
    model = build_model(cfg)
    rng = np.random.default_rng(0)
    image = jnp.asarray(
        rng.standard_normal((BATCH, SIZE, SIZE, 3)), jnp.float32
    )
    exemplars = jnp.tile(
        jnp.array([[[0.45, 0.45, 0.53, 0.55]]], jnp.float32), (BATCH, 1, 1)
    )
    params = jax.jit(model.init)(jax.random.key(0), image, exemplars)["params"]

    report = {}

    # 1. full model forward
    fwd = jax.jit(lambda p, im, ex: model.apply({"params": p}, im, ex))
    report["full_forward"] = timeit(fwd, params, image, exemplars)

    # 2. backbone only
    bb = model.backbone
    bb_params = params["backbone"]
    bb_fwd = jax.jit(lambda p, im: bb.apply({"params": p}, im))
    report["backbone"] = timeit(bb_fwd, bb_params, image)
    feat = bb_fwd(bb_params, image)

    # 3. single global-attention block vs windowed block (isolated)
    from tmr_tpu.models.vit import Block

    tokens = jnp.asarray(
        rng.standard_normal((BATCH, 64, 64, 768)), jnp.bfloat16
    )
    gblk = Block(num_heads=12, window_size=0, rel_pos_size=(64, 64),
                 dtype=jnp.bfloat16)
    gp = jax.jit(gblk.init)(jax.random.key(1), tokens)["params"]
    g_fwd = jax.jit(lambda p, x: gblk.apply({"params": p}, x))
    report["one_global_block"] = timeit(g_fwd, gp, tokens)

    wblk = Block(num_heads=12, window_size=14, rel_pos_size=(64, 64),
                 dtype=jnp.bfloat16)
    wp = jax.jit(wblk.init)(jax.random.key(1), tokens)["params"]
    w_fwd = jax.jit(lambda p, x: wblk.apply({"params": p}, x))
    report["one_windowed_block"] = timeit(w_fwd, wp, tokens)

    # 4. feature upsample + input_proj + matcher (xcorr) on 128^2 @ 512
    from tmr_tpu.ops.xcorr import match_templates

    up = jax.image.resize(feat, (BATCH, 128, 128, 256), method="bilinear")
    proj = jnp.asarray(
        rng.standard_normal((BATCH, 128, 128, 512)), jnp.float32
    )
    xc = jax.jit(
        lambda f, e: match_templates(
            f.transpose(0, 3, 1, 2), e[:, 0, :], capacity=17
        )
    )
    report["xcorr_cap17"] = timeit(xc, proj, exemplars)
    xc65 = jax.jit(
        lambda f, e: match_templates(
            f.transpose(0, 3, 1, 2), e[:, 0, :], capacity=65
        )
    )
    report["xcorr_cap65"] = timeit(xc65, proj, exemplars)

    # 5. decoder convs + heads on fused input (1024ch with fusion)
    from tmr_tpu.models.heads import BboxesHead, Decoder, ObjectnessHead

    f_cat = jnp.asarray(
        rng.standard_normal((BATCH, 128, 128, 1024)), jnp.bfloat16
    )
    dec = Decoder(num_layers=1, kernel_size=3, dtype=jnp.bfloat16)
    dp = jax.jit(dec.init)(jax.random.key(2), f_cat)["params"]
    d_fwd = jax.jit(lambda p, x: dec.apply({"params": p}, x))
    report["one_decoder_stack"] = timeit(d_fwd, dp, f_cat)

    # 6. decode + NMS
    from tmr_tpu.ops.postprocess import batched_nms, decode_detections

    obj = jnp.asarray(rng.standard_normal((BATCH, 128, 128)), jnp.float32)
    regs = jnp.asarray(
        rng.standard_normal((BATCH, 128, 128, 4)), jnp.float32
    )

    def post(o, r, ex):
        dets = decode_detections(
            [o], [r], ex[:, 0, :],
            cls_threshold=cfg.NMS_cls_threshold,
            max_detections=cfg.max_detections,
            box_reg=cfg.box_reg,
            scale_imgsize=cfg.regression_scaling_imgsize,
            scale_wh_only=cfg.regression_scaling_WH_only,
        )
        return batched_nms(dets, cfg.NMS_iou_threshold)

    post_fn = jax.jit(post)
    report["decode_nms"] = timeit(post_fn, obj, regs, exemplars)

    report = {k: round(v, 5) for k, v in report.items()}
    report["batch"] = BATCH
    report["device"] = str(jax.devices()[0])
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
