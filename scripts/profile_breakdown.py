"""Per-component timing breakdown of the flagship inference program.

Times the dominant stages of the fused FSCD-147 eval program in isolation —
the full program, the SAM ViT-B backbone, one global- and one windowed-
attention block at real dims, the matcher x-corr at two capacity buckets,
the decode+NMS tail, and the two 1024-channel decoder conv stacks + heads
on the upsampled 128^2 grid (``decoder_heads`` — the post-attention budget
PERF.md lists as the never-measured remaining candidate) — with the SAME
methodology as bench.py (PERF.md Finding 1):
device-staged inputs, iterations chained through a scalar data dependency
inside each jitted program, one closing fetch, measured RTT floor
subtracted — `jax.block_until_ready` is advisory over the tunneled
transport and must not be trusted.

Run on the real TPU:   python scripts/profile_breakdown.py
Prints a JSON breakdown {stage: seconds_per_iteration}.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

BATCH = int(os.environ.get("TMR_BENCH_BATCH", 4))
SIZE = int(os.environ.get("TMR_BENCH_SIZE", 1024))
CHAIN = int(os.environ.get("TMR_BENCH_CHAIN", 10))


def _progress(msg: str) -> None:
    """Stage marker on stderr, flushed: cold-cache compiles over the tunnel
    take tens of minutes end-to-end, and without these lines a slow run is
    indistinguishable from a wedged one."""
    print(f"[profile] {msg}", file=sys.stderr, flush=True)


def _rtt() -> float:
    from tmr_tpu.utils.profiling import measure_rtt_floor

    return measure_rtt_floor()


def chained(fn, *args, rtt: float = 0.0) -> float:
    """fn(*args, fb) -> (out, fb'): chained sec/iter with the RTT removed
    (the shared utils/profiling.py harness at this script's CHAIN count)."""
    from tmr_tpu.utils.profiling import chained_seconds_per_iter

    return chained_seconds_per_iter(fn, *args, iters=CHAIN, rtt=rtt)


def attributed(fn, *args, rtt: float = 0.0) -> dict:
    """Device-attributed split of one stage step (obs/devtime.py): a few
    blocking calls separating host dispatch (``dispatch_s``) from
    post-dispatch device execution (``device_s``, RTT floor removed) —
    the chained wall numbers above deliberately conflate the two, which
    is right for throughput but wrong for 'where did the time go'."""
    from tmr_tpu.obs.devtime import attribute_call

    fb0 = jnp.zeros((), jnp.float32)
    rec = attribute_call(lambda: fn(*args, fb0), iters=3, rtt=rtt)
    return {k: (round(v, 5) if isinstance(v, float) else v)
            for k, v in rec.items()}


def main():
    from tmr_tpu.config import preset
    from tmr_tpu.inference import Predictor
    from tmr_tpu.models.vit import Block
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()
    cfg = preset(
        "TMR_FSCD147", backbone="sam_vit_b", image_size=SIZE,
        compute_dtype="bfloat16", batch_size=BATCH,
    )
    pred = Predictor(cfg)
    _progress("init_params (jitted init)")
    pred.init_params(seed=0, image_size=SIZE)
    params = pred.params
    rng = np.random.default_rng(0)
    image = jnp.asarray(
        rng.standard_normal((BATCH, SIZE, SIZE, 3)), jnp.float32
    )
    exemplars = jnp.tile(
        jnp.asarray([[[0.45, 0.45, 0.53, 0.55]]], jnp.float32), (BATCH, 1, 1)
    )
    _progress("measuring rtt floor")
    rtt = _rtt()
    report = {"rtt_floor_ms": round(rtt * 1000, 1)}

    # device-attributed seconds per stage ride alongside the chained
    # wall numbers (see `attributed`): {stage: {dispatch_s, device_s,
    # wall_s}} — stage numbers stop conflating host dispatch with
    # device execution
    report["devtime"] = {}

    # 1. full fused program (the production pipeline via its bench hook)
    _progress("stage 1: full fused program")
    fused = pred._get_fn(17, chain_feedback=True)
    step1 = lambda im, ex, fb: fused(params, None, im, ex, fb)  # noqa: E731
    report["full_program"] = chained(step1, image, exemplars, rtt=rtt)
    report["devtime"]["full_program"] = attributed(
        step1, image, exemplars, rtt=rtt
    )
    _progress(f"full_program: {report['full_program']*1000:.2f} ms")

    # 2. backbone alone (chained through the feature sum)
    bb = pred.model.backbone
    bb_params = params["backbone"]

    _progress("stage 2: backbone alone")

    @jax.jit
    def bb_step(p, im, fb):
        f = bb.apply({"params": p}, im + fb)
        return f, jnp.sum(f).astype(jnp.float32) * 0.0

    step2 = lambda im, fb: bb_step(bb_params, im, fb)  # noqa: E731
    report["backbone"] = chained(step2, image, rtt=rtt)
    report["devtime"]["backbone"] = attributed(step2, image, rtt=rtt)
    _progress(f"backbone: {report['backbone']*1000:.2f} ms")

    # 3. one global vs one windowed transformer block (768-d, real grid),
    # plus the A/B windowed variant with the bias folded into QK
    # (TMR_WIN_ATTN, read at trace time — models/vit.py)
    grid = SIZE // 16
    tokens = jnp.asarray(
        rng.standard_normal((BATCH, grid, grid, 768)), jnp.bfloat16
    )
    cases = (
        # (label, window, {knob: value}): global blocks read TMR_GLOBAL_ATTN,
        # windowed blocks TMR_WIN_ATTN (all trace-time); the pallas rows also
        # sweep the kernel's tile sizes (TMR_PALLAS_ATTN_BQ/BK)
        ("one_global_block_blockwise", 0, {"TMR_GLOBAL_ATTN": "blockwise"}),
        ("one_global_block_flash", 0, {"TMR_GLOBAL_ATTN": "flash"}),
        ("one_global_block_blockfolded", 0,
         {"TMR_GLOBAL_ATTN": "blockfolded"}),
        ("one_global_block_blockfolded_unroll2", 0,
         {"TMR_GLOBAL_ATTN": "blockfolded",
          "TMR_GLOBAL_BANDS_UNROLL": "2"}),
        ("one_global_block_blockfolded_unroll4", 0,
         {"TMR_GLOBAL_ATTN": "blockfolded",
          "TMR_GLOBAL_BANDS_UNROLL": "4"}),
        ("one_global_block_densefolded", 0,
         {"TMR_GLOBAL_ATTN": "densefolded"}),
        ("one_global_block_blockfolded_scores16", 0,
         {"TMR_GLOBAL_ATTN": "blockfolded",
          "TMR_GLOBAL_SCORES_DTYPE": "bf16"}),
        ("one_global_block_densefolded_scores16", 0,
         {"TMR_GLOBAL_ATTN": "densefolded",
          "TMR_GLOBAL_SCORES_DTYPE": "bf16"}),
        ("one_global_block_pallas", 0, {"TMR_GLOBAL_ATTN": "pallas"}),
        ("one_global_block_pallas_bq256", 0,
         {"TMR_GLOBAL_ATTN": "pallas", "TMR_PALLAS_ATTN_BQ": "256"}),
        ("one_global_block_pallas_bk1024", 0,
         {"TMR_GLOBAL_ATTN": "pallas", "TMR_PALLAS_ATTN_BK": "1024"}),
        # the fused-bias rewrite (broadcast bias tiles, no selector
        # matmuls) and its tile sweep — the verdict's "highest-information
        # measurement" rows — plus the Mosaic-independent XLA flash form
        ("one_global_block_fused", 0, {"TMR_GLOBAL_ATTN": "fused"}),
        ("one_global_block_fused_bq256", 0,
         {"TMR_GLOBAL_ATTN": "fused", "TMR_PALLAS_ATTN_BQ": "256"}),
        ("one_global_block_fused_bk1024", 0,
         {"TMR_GLOBAL_ATTN": "fused", "TMR_PALLAS_ATTN_BK": "1024"}),
        ("one_global_block_xlaflash", 0, {"TMR_GLOBAL_ATTN": "xlaflash"}),
        ("one_global_block_xlaflash_bk1024", 0,
         {"TMR_GLOBAL_ATTN": "xlaflash", "TMR_XLA_FLASH_BK": "1024"}),
        ("one_windowed_block", 14, {"TMR_WIN_ATTN": "dense"}),
        ("one_windowed_block_folded", 14, {"TMR_WIN_ATTN": "folded"}),
        ("one_windowed_block_folded_scores16", 14,
         {"TMR_WIN_ATTN": "folded", "TMR_WIN_SCORES_DTYPE": "bf16"}),
        ("one_windowed_block_flash", 14, {"TMR_WIN_ATTN": "flash"}),
        ("one_windowed_block_pallas", 14, {"TMR_WIN_ATTN": "pallas"}),
        ("one_windowed_block_pallas_g8", 14,
         {"TMR_WIN_ATTN": "pallas", "TMR_PALLAS_WIN_GROUP": "8"}),
    )
    # restore the user's knobs afterwards (autotune's _restore): the
    # full-program timing in section 1 honoured them, and later sections /
    # the rest of the process must keep seeing them
    from tmr_tpu.utils.autotune import _restore

    prev = {
        k: os.environ.get(k)
        for k in ("TMR_WIN_ATTN", "TMR_GLOBAL_ATTN", "TMR_PALLAS_ATTN_BQ",
                  "TMR_PALLAS_ATTN_BK", "TMR_PALLAS_WIN_GROUP",
                  "TMR_GLOBAL_BANDS_UNROLL", "TMR_GLOBAL_SCORES_DTYPE",
                  "TMR_WIN_SCORES_DTYPE", "TMR_XLA_FLASH_BQ",
                  "TMR_XLA_FLASH_BK")
    }
    try:
        for label, win, knobs in cases:
            if "TMR_PALLAS_WIN_GROUP" in knobs:
                # skip when the preference clamps to a different effective
                # group at this batch (same mislabeling hazard as the tile
                # rows): bh = batch * windows * heads for one block
                from tmr_tpu.ops.pallas_attn import _win_group

                n_win = ((grid + win - 1) // win) ** 2 if win else 1
                bh_blk = BATCH * n_win * 12
                want_g = int(knobs["TMR_PALLAS_WIN_GROUP"])
                os.environ["TMR_PALLAS_WIN_GROUP"] = str(want_g)
                eff_g = _win_group(bh_blk)
                os.environ.pop("TMR_PALLAS_WIN_GROUP", None)
                if eff_g != want_g:
                    _progress(f"stage 3: {label} skipped (group clamps to "
                              f"{eff_g} at bh={bh_blk})")
                    continue
            if "TMR_PALLAS_ATTN_BQ" in knobs or "TMR_PALLAS_ATTN_BK" in knobs:
                # skip tile rows whose preference clamps back to the default
                # tile at this S — they would re-measure the plain pallas
                # row under a label claiming a different tile size
                from tmr_tpu.ops.flash_attn import _block_for

                s_glob = grid * grid
                eff = (
                    _block_for(s_glob,
                               int(knobs.get("TMR_PALLAS_ATTN_BQ", 512))),
                    _block_for(s_glob,
                               int(knobs.get("TMR_PALLAS_ATTN_BK", 512))),
                )
                if eff == (_block_for(s_glob, 512), _block_for(s_glob, 512)):
                    _progress(f"stage 3: {label} skipped (tiles clamp to "
                              f"the default {eff} at S={s_glob})")
                    continue
            _progress(f"stage 3: {label}")
            for k in ("TMR_PALLAS_ATTN_BQ", "TMR_PALLAS_ATTN_BK",
                      "TMR_PALLAS_WIN_GROUP", "TMR_GLOBAL_BANDS_UNROLL",
                      "TMR_GLOBAL_SCORES_DTYPE", "TMR_WIN_SCORES_DTYPE",
                      "TMR_XLA_FLASH_BQ", "TMR_XLA_FLASH_BK"):
                os.environ.pop(k, None)  # tile/group overrides are per-case
            os.environ.update(knobs)
            blk = Block(num_heads=12, window_size=win,
                        rel_pos_size=(grid, grid), dtype=jnp.bfloat16)
            bp = jax.jit(blk.init)(jax.random.key(1), tokens)["params"]

            @jax.jit
            def blk_step(p, x, fb):
                y = blk.apply({"params": p}, x + fb.astype(x.dtype))
                return y, jnp.sum(y).astype(jnp.float32) * 0.0

            report[label] = chained(
                lambda x, fb: blk_step(bp, x, fb), tokens, rtt=rtt
            )
            _progress(f"{label}: {report[label]*1000:.2f} ms")
    finally:
        for k, v in prev.items():
            _restore(v, k)

    # 4. matcher x-corr on the upsampled grid: every formulation at the
    # production capacity (TMR_XCORR_IMPL, read at trace time — ops/xcorr.py)
    # plus the default big-template path at 127
    from tmr_tpu.ops.xcorr import match_templates

    up_hw = pred.feature_hw(SIZE)
    proj = jnp.asarray(
        rng.standard_normal((BATCH, cfg.emb_dim, up_hw, up_hw)), jnp.float32
    )
    ex0 = exemplars[:, 0, :]
    prev_xc = os.environ.get("TMR_XCORR_IMPL")
    prev_pr = os.environ.get("TMR_XCORR_PRECISION")
    try:
        for cap, impl, prec in (
            (17, "conv", "highest"), (17, "conv", "default"),
            (17, "conv", "bf16"), (17, "vmap", "highest"),
            (17, "vmap", "default"), (17, "vmap", "bf16"),
            (17, "fft", "highest"),
            (17, "pallas", "highest"), (17, "convnhwc", "highest"),
            (127, "auto", "highest"),
        ):
            _progress(f"stage 4: xcorr cap={cap} impl={impl} prec={prec}")
            os.environ["TMR_XCORR_IMPL"] = impl
            os.environ["TMR_XCORR_PRECISION"] = prec

            @jax.jit
            def xc_step(f, e, fb):
                y = match_templates(f + fb, e, capacity=cap)
                return y, jnp.sum(y) * 0.0

            label = f"xcorr_cap{cap}" + ("" if impl == "auto" else f"_{impl}")
            if prec != "highest":
                label += f"_{prec}"
            report[label] = chained(
                lambda f, e, fb: xc_step(f, e, fb), proj, ex0, rtt=rtt
            )
            _progress(f"{label}: {report[label]*1000:.2f} ms")
    finally:
        _restore(prev_xc, "TMR_XCORR_IMPL")
        _restore(prev_pr, "TMR_XCORR_PRECISION")

    # 5 + 6. the post-attention tail stages, via the SHARED stage
    # programs in utils/stage_bench — one definition feeds this
    # breakdown, bench.py's per-round ``stage_breakdown`` record, and the
    # autotune sweeps electing TMR_DECODER_IMPL / TMR_QUANT, so the three
    # surfaces can never measure different programs. Both builders read
    # the tail knobs (TMR_DECODER_IMPL, TMR_QUANT, TMR_DECODE_TAIL) at
    # trace time exactly like production: pin a knob and re-run the
    # breakdown to time that formulation — the fused-vs-xla /
    # int8-vs-exact / device-vs-host deltas the MFU push is after. The
    # decode-tail rationale (exemplar-sized synthetic boxes so the greedy
    # NMS suppression chains run production-deep) lives with the builder.
    from tmr_tpu.inference import decode_tail_mode
    from tmr_tpu.ops.fused_heads import decoder_impl
    from tmr_tpu.utils.stage_bench import (
        build_decode_tail_step,
        build_decoder_tail_step,
    )

    _progress("stage 5: decode+NMS tail")
    tail_step, tail_inputs = build_decode_tail_step(pred, BATCH, up_hw, SIZE)
    report[f"decode_nms_tail_n{cfg.max_detections}"] = chained(
        tail_step, *tail_inputs, rtt=rtt
    )
    report["devtime"]["decode_nms_tail"] = attributed(
        tail_step, *tail_inputs, rtt=rtt
    )

    c_cat = cfg.emb_dim * 2 if cfg.fusion else cfg.emb_dim
    _progress(f"stage 6: decoder_heads ({c_cat}ch @ {up_hw}^2)")
    dec_step, dec_inputs = build_decoder_tail_step(
        BATCH, up_hw, c_cat, cfg.decoder_num_layer,
        cfg.decoder_kernel_size, cfg.compute_dtype,
    )
    report["decoder_heads"] = chained(dec_step, *dec_inputs, rtt=rtt)
    report["devtime"]["decoder_heads"] = attributed(
        dec_step, *dec_inputs, rtt=rtt
    )
    _progress(f"decoder_heads: {report['decoder_heads']*1000:.2f} ms")

    # stamp which formulations the tail stages actually traced (a
    # gate-refused request falls back silently at this layer — the stamp
    # plus the gate_probe/v1 causes make the fallback attributable)
    impl, quant = decoder_impl(
        up_hw, up_hw, c_cat, c_cat, cfg.decoder_num_layer,
        cfg.decoder_kernel_size, cfg.compute_dtype,
    )
    report["decoder_impl"] = impl
    report["quant"] = "int8" if quant else "off"
    report["decode_tail_mode"] = decode_tail_mode()

    report = {
        k: (round(v, 5) if isinstance(v, float) else v)
        for k, v in report.items()
    }
    report["batch"] = BATCH
    report["device"] = str(jax.devices()[0])
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
