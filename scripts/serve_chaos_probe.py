"""Chaos gauntlet for the replicated gallery fleet
(tmr_tpu/serve/gallery_fleet.py): prove ZERO pattern loss.

The elastic_serve_probe story applied to gallery STATE: pattern shards
are leased fleet resources (primary + R-1 mirrors, write-ahead journal
on the coordinator), and this probe drives subprocess stub-bank workers
through every serve-tier fault point, checking the ledger closes. One
``serve_chaos_report/v1`` JSON line (schema + validator in
tmr_tpu/diagnostics.py):

- **fanout_parity** — three workers lease four shards; patterns
  register with ``copies == 2`` acknowledged; the fan-out client's
  merged search is BYTE-identical to one StubGalleryBank holding every
  pattern (the stub's detections depend only on (exemplars, frame), so
  crossed shards / stale payloads / codec loss all show as mismatches).
- **kill** — repeated rounds: register a FRESH pattern, then kill -9
  the primary holding its shard before the ink dries. The journal +
  replica copies re-materialize the shard on the promoted holder
  (adopt-or-push) and replication heals back to R; every pattern ever
  acknowledged searches clean and byte-identical afterwards.
- **degrade_label** — a ``serve.link`` fault severs exactly one
  shard's first fan-out: precisely that shard's patterns come back as
  counted ``degrade_steps: ["partition_unavailable"]`` results (all
  other patterns still byte-identical), and the NEXT search heals.
- **replica_corrupt** — a ``gallery.replica:corrupt=1`` schedule
  corrupts the first replica push; the worker's digest check rejects
  it (counted, never installed) and the retry lands clean: the
  registration still acks ``copies == 2``.
- **journal_wal** — a ``journal`` raise refuses the write-ahead marker
  BEFORE the catalog/ack: the pattern is nowhere (no partial state),
  and the retry after clearing registers durably.
- **beat_env** — a worker subprocess is spawned with
  ``TMR_FAULTS="gallery.beat:latency=..."`` in its env (the
  install_from_env contract): its delayed beats blow the lease TTL,
  the shard promotes onto the clean replica (``stale_heartbeat``), and
  the worker's own ``gstate`` shows the schedule active and fired —
  chaos schedules reach lease-held serve processes.
- **bulk_ingest** (``--patterns-per-shard N``, default 0 = skipped) —
  ``N * shards`` patterns stream through the coordinator's bulk-ingest
  sink (``fleet.bulk_sink()`` + ``bulk_register``: journal-first
  feature ops, one ``gflush`` distribution) and must come back from a
  fan-out search byte-identical to the single-bank oracle, fully
  replicated, and survive the final journal-recovery check like any
  register() pattern — the PR 17 gauntlet re-run at catalog scale.
- **final_sweep** — every acknowledged registration (both fleets) must
  search clean + byte-identical, and a cold coordinator restart over
  the same journal directory recovers the exact catalog.

Usage:  python scripts/serve_chaos_probe.py [--tiny] [--out FILE]
        [--patterns-per-shard N]

Fast (seconds, numpy stub banks, CPU): rides tier-1 via
tests/test_serve_chaos_probe.py. One-JSON-line contract via
bench_guard. ``scripts/bench_trend.py --chaos`` rc-gates fail-closed
on the zero-loss / all-faults-accounted invariants.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")
scrub_cpu_tunnel_env()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIZE = 16
SHARDS = 4
WORKERS = 3
REPLICAS = 2
BASE_PATTERNS = 8


def _progress(msg: str) -> None:
    print(f"[serve_chaos_probe] {msg}", file=sys.stderr, flush=True)


def _poll(predicate, timeout_s: float, interval_s: float = 0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval_s)
    return None


def _policy():
    from tmr_tpu.parallel.leases import LeasePolicy

    return LeasePolicy(
        lease_ttl_s=1.0, hb_interval_s=0.2, check_interval_s=0.05,
        straggler_factor=0.0, max_reassigns=1_000_000_000,
        resource_fail_workers=1_000_000_000,
    )


def _frame(seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)


def _exemplars(name: str) -> np.ndarray:
    """Deterministic per-name exemplars (process-stable seed)."""
    seed = int.from_bytes(
        hashlib.sha256(name.encode()).digest()[:4], "big"
    )
    rng = np.random.default_rng(seed)
    return rng.standard_normal((2, 4)).astype(np.float32)


def _pattern_names(n: int, n_shards: int, prefix: str = "pat") -> list:
    """``n`` deterministic names covering EVERY shard at least once
    (shard placement is content-hashed, so names are picked for it)."""
    from tmr_tpu.serve.gallery_fleet import shard_of

    names: list = []
    covered: set = set()
    i = 0
    while len(names) < n or len(covered) < n_shards:
        name = f"{prefix}{i:03d}"
        i += 1
        shard = shard_of(name, n_shards)
        if len(names) < n:
            names.append(name)
            covered.add(shard)
        elif shard not in covered:
            names.append(name)
            covered.add(shard)
    return names


def _spawn_gallery_worker(wid: str, address,
                          env_faults=None) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TMR_FAULTS", None)
    if env_faults:  # the install_from_env delivery path under test
        env["TMR_FAULTS"] = env_faults
        env["TMR_FAULTS_SEED"] = "0"
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "serve_fleet.py"),
         "gallery-worker", "--coordinator", f"{address[0]}:{address[1]}",
         "--worker_id", wid, "--bank", "stub",
         "--image_size", str(SIZE)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _dets_equal(got: dict, want: dict) -> bool:
    """Byte-exact detection equality (dtype + shape + buffer)."""
    if set(got) != set(want):
        return False
    for key, w in want.items():
        g = got.get(key)
        if isinstance(w, np.ndarray):
            if not (isinstance(g, np.ndarray) and g.dtype == w.dtype
                    and g.shape == w.shape
                    and g.tobytes() == w.tobytes()):
                return False
        elif g != w:
            return False
    return True


def _clean_and_exact(results: dict, reference: dict) -> bool:
    """Every reference pattern present, un-degraded, byte-identical."""
    if set(results) != set(reference):
        return False
    return all(
        "degrade_steps" not in results[name]
        and _dets_equal(results[name], reference[name])
        for name in reference
    )


def _fired_count(point: str) -> int:
    from tmr_tpu.utils import faults

    return sum(1 for rec in faults.fired() if rec["point"] == point)


def _run(cancel_watchdog, argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="fewer kill rounds / frames (tier-1 budget)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--patterns-per-shard", type=int, default=0,
                    help="bulk-ingest this many patterns per shard "
                         "through the streamed sink (0 = skip phase)")
    args = ap.parse_args(argv)

    from tmr_tpu.diagnostics import (
        SERVE_CHAOS_REPORT_SCHEMA,
        validate_serve_chaos_report,
    )
    from tmr_tpu.parallel.leases import oneshot
    from tmr_tpu.serve.gallery_fleet import (
        GalleryFleet,
        StubGalleryBank,
        bulk_register,
    )
    from tmr_tpu.utils import faults

    kill_rounds = 1 if args.tiny else 2
    parity_frames = 2 if args.tiny else 3

    phases = []
    procs = {}  # wid -> Popen
    workers_killed = 0
    reference = StubGalleryBank(image_size=SIZE)  # the single-bank oracle
    ledger = []  # every ACKNOWLEDGED main-fleet registration
    injected = []  # the fault ledger: point/schedule/fired/accounted
    observed = {}

    def cleanup():
        faults.clear()
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
        for proc in procs.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    def spawn(fleet, wid, env_faults=None):
        procs[wid] = _spawn_gallery_worker(wid, fleet.address,
                                           env_faults=env_faults)

    def kill(wid):
        nonlocal workers_killed
        proc = procs.get(wid)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            workers_killed += 1

    def register(fleet, name):
        ex = _exemplars(name)
        ack = fleet.register(name, ex)
        reference.register(name, ex)
        ledger.append(name)
        return ack

    def all_held(fleet):
        return all(fleet.holder_for(s) is not None
                   for s in range(fleet.n_shards))

    def search_clean(client) -> bool:
        return _clean_and_exact(client.search(_frame(99)),
                                reference.search(_frame(99)))

    tmp = tempfile.TemporaryDirectory(prefix="serve_chaos_")
    fleet = GalleryFleet(
        SHARDS, policy=_policy(), replicas=REPLICAS,
        journal_dir=os.path.join(tmp.name, "journal"),
    )
    fleet.start()
    mini = None
    try:
        # ---------------------------------------- phase 1: fan-out parity
        _progress(f"spawning {WORKERS} stub gallery workers")
        for i in range(WORKERS):
            spawn(fleet, f"w{i}")
        if not _poll(lambda: all_held(fleet), 30.0):
            raise RuntimeError("gallery workers never leased all shards")
        names = _pattern_names(BASE_PATTERNS, SHARDS)
        acks = [register(fleet, name) for name in names]
        replicated = all(
            a["copies"] >= REPLICAS and not a["under_replicated"]
            for a in acks
        )
        client = fleet.client()
        parity = replicated
        for f in range(parity_frames):
            img = _frame(f)
            if not _clean_and_exact(client.search(img),
                                    reference.search(img)):
                parity = False
        phases.append({
            "name": "fanout_parity", "ok": bool(parity),
            "patterns": len(names), "frames": parity_frames,
            "copies": [a["copies"] for a in acks],
        })
        _progress(f"fanout parity: ok={parity}")

        # ------------------------------- phase 2: repeated primary kills
        kills_ok = True
        for r in range(kill_rounds):
            fresh = f"fresh{r:02d}"
            ack = register(fleet, fresh)
            resolved = fleet.holder_for(ack["shard"])
            victim = resolved[0] if resolved else None
            if victim is None or victim not in procs:
                kills_ok = False
                break
            _progress(f"kill round {r}: registered {fresh!r}, "
                      f"killing primary {victim!r}")
            kill(victim)
            recruit = f"w{WORKERS + r}"
            spawn(fleet, recruit)  # keep the fleet elastic
            healed = _poll(
                lambda: recruit in fleet._svc.live_workers()  # noqa: B023
                and all_held(fleet) and search_clean(client), 30.0,
            )
            if not healed:
                kills_ok = False
                break
        phases.append({
            "name": "kill", "ok": bool(kills_ok),
            "rounds": kill_rounds, "workers_killed": workers_killed,
            "promotions": fleet.counters()["promotions"],
        })
        _progress(f"kill rounds: ok={kills_ok}")

        # ------------------------- phase 3: degrade labeling + healing
        plan = fleet.shard_map()
        target = max(plan, key=lambda s: len(plan[s]))
        schedule = f"serve.link:shard={target}:attempts=1:raise=OSError"
        faults.configure(schedule, seed=0)
        fresh_client = fleet.client()  # attempt counters start at 0
        img = _frame(7)
        want = reference.search(img)
        first = fresh_client.search(img)
        degraded = {
            name for name, dets in first.items()
            if dets.get("degrade_steps") == ["partition_unavailable"]
        }
        exact_label = (
            degraded == set(plan[target])
            and all(_dets_equal(first[n], want[n])
                    for n in want if n not in degraded)
        )
        second = fresh_client.search(img)
        heals = _clean_and_exact(second, want)
        link_fired = _fired_count("serve.link")
        link_accounted = fresh_client.counters()["link_failures"]
        observed["serve.link"] = link_fired
        injected.append({
            "point": "serve.link", "schedule": schedule,
            "fired": int(link_fired), "accounted": int(link_accounted),
        })
        faults.clear()
        phases.append({
            "name": "degrade_label",
            "ok": bool(exact_label and heals and link_fired),
            "target_shard": int(target),
            "degraded_patterns": sorted(degraded),
            "heals": bool(heals),
        })
        _progress(f"degrade labeling: exact={exact_label} heals={heals}")

        # --------------------- phase 4: corrupt replica push, rejected
        schedule = "gallery.replica:corrupt=1:attempts=1"
        faults.configure(schedule, seed=0)
        before = fleet.counters()["replica_corrupt"]
        ack = register(fleet, "healme")
        corrupt_seen = fleet.counters()["replica_corrupt"] - before
        replica_fired = _fired_count("gallery.replica")
        faults.clear()
        rejected = 0
        for wid in fleet._svc.live_workers():
            addr = fleet._addr_of(wid)
            if addr is None:
                continue
            try:
                st = oneshot(addr, {"op": "gstate"}, timeout=10.0)
                rejected += int(st["counters"]["corrupt_rejected"])
            except Exception:
                pass
        replication_recovered = bool(
            ack["copies"] >= REPLICAS and not ack["under_replicated"]
            and search_clean(client)
        )
        observed["gallery.replica"] = replica_fired
        injected.append({
            "point": "gallery.replica", "schedule": schedule,
            "fired": int(replica_fired),
            "accounted": int(min(corrupt_seen, rejected)),
        })
        phases.append({
            "name": "replica_corrupt",
            "ok": bool(replication_recovered and corrupt_seen >= 1
                       and rejected >= 1),
            "coordinator_counted": int(corrupt_seen),
            "worker_rejected": int(rejected),
            "copies": ack["copies"],
        })
        _progress(f"replica corrupt: rejected={rejected} "
                  f"healed_copies={ack['copies']}")

        # ------------------ phase 5: journal write-ahead ordering (WAL)
        schedule = "journal:raise=OSError"
        faults.configure(schedule, seed=0)
        refused = False
        try:
            fleet.register("walprobe", _exemplars("walprobe"))
        except OSError:
            refused = True
        journal_fired = _fired_count("journal")
        nowhere = "walprobe" not in fleet.patterns()
        faults.clear()
        retry = register(fleet, "walprobe")
        wal_ok = bool(refused and nowhere and journal_fired
                      and retry["copies"] >= REPLICAS)
        observed["journal"] = journal_fired
        injected.append({
            "point": "journal", "schedule": schedule,
            "fired": int(journal_fired),
            "accounted": int(refused and nowhere),
        })
        phases.append({
            "name": "journal_wal", "ok": wal_ok,
            "refused": refused, "absent_after_refusal": nowhere,
        })
        _progress(f"journal WAL ordering: ok={wal_ok}")

        # --------- phase 6: env-delivered beat fault on a mini fleet
        # (spawned worker gets TMR_FAULTS via its environment — the
        # install_from_env contract — and its delayed beats blow the
        # lease TTL: stale_heartbeat promotion, zero loss)
        schedule = "gallery.beat:latency=1.5"
        mini = GalleryFleet(
            2, policy=_policy(), replicas=REPLICAS,
            journal_dir=os.path.join(tmp.name, "mini_journal"),
        )
        mini.start()
        mini_reference = StubGalleryBank(image_size=SIZE)
        spawn(mini, "beatw", env_faults=schedule)
        beat_holds = bool(_poll(
            lambda: all(
                (mini.holder_for(s) or (None,))[0] == "beatw"
                for s in range(2)
            ),
            30.0,
        ))
        spawn(mini, "calm")
        mini_names = []
        for name in _pattern_names(2, 2, prefix="mini"):
            ex = _exemplars(name)
            mini.register(name, ex)
            mini_reference.register(name, ex)
            mini_names.append(name)

        def beat_stale():
            return any(
                r["cause"] == "stale_heartbeat"
                for r in mini.state()["reassignments"]
            )

        stale_seen = bool(_poll(beat_stale, 30.0))
        beat_fired = 0
        env_active = False
        addr = mini._addr_of("beatw")
        if addr is not None:
            try:
                st = oneshot(addr, {"op": "gstate"}, timeout=10.0)
                beat_fired = int(st["faults_fired"])
                env_active = bool(st["faults_active"])
            except Exception:
                pass
        kill("beatw")
        mini_client = mini.client()

        def mini_clean():
            if not all((mini.holder_for(s) or (None,))[0] == "calm"
                       for s in range(2)):
                return False
            img = _frame(5)
            return _clean_and_exact(mini_client.search(img),
                                    mini_reference.search(img))

        mini_healed = bool(_poll(mini_clean, 30.0))
        env_delivered = bool(env_active and beat_fired >= 1)
        stale_count = sum(
            1 for r in mini.state()["reassignments"]
            if r["cause"] == "stale_heartbeat"
        )
        observed["gallery.beat"] = beat_fired
        injected.append({
            "point": "gallery.beat", "schedule": schedule,
            "fired": int(beat_fired), "accounted": int(stale_count),
        })
        phases.append({
            "name": "beat_env",
            "ok": bool(beat_holds and stale_seen and env_delivered
                       and mini_healed),
            "stale_reassignments": int(stale_count),
            "worker_faults_fired": int(beat_fired),
            "worker_faults_active": env_active,
            "healed": mini_healed,
        })
        _progress(f"env beat fault: delivered={env_delivered} "
                  f"stale={stale_count} healed={mini_healed}")

        # ------ phase 6.5: streamed bulk ingest at catalog scale
        # (opt-in: the coordinator's feature-sink bulk path — journal
        # -first streaming, one gflush distribution — must land every
        # pattern byte-identical and fully replicated, and those
        # patterns then ride the final sweep + journal recovery like
        # any register() pattern)
        if args.patterns_per_shard > 0:
            total = SHARDS * args.patterns_per_shard
            _progress(f"bulk ingest: streaming {total} patterns")
            t0 = time.perf_counter()
            bulk_pats = [(f"blk{i:06d}", _exemplars(f"blk{i:06d}"))
                         for i in range(total)]
            res = bulk_register(fleet.bulk_sink(), bulk_pats,
                                batch="chaos")
            wall = time.perf_counter() - t0
            bulk_names = []
            for name, ex in bulk_pats:
                reference.register(name, ex)
                ledger.append(name)
                bulk_names.append(name)
            img = _frame(21)
            got = client.search(img)
            want = reference.search(img)
            bulk_parity = all(
                name in got and "degrade_steps" not in got[name]
                and _dets_equal(got[name], want[name])
                for name in bulk_names
            )
            flush = res.get("flush") or {}
            bulk_ok = bool(
                res.get("ok") and res.get("streamed") == total
                and flush.get("under_replicated") == 0 and bulk_parity
            )
            phases.append({
                "name": "bulk_ingest", "ok": bulk_ok,
                "patterns": total,
                "streamed": int(res.get("streamed") or 0),
                "copies": int(flush.get("copies") or 0),
                "parity": bool(bulk_parity),
                "wall_s": round(wall, 3),
            })
            _progress(f"bulk ingest: ok={bulk_ok} "
                      f"wall={wall:.2f}s copies={flush.get('copies')}")

        # -------------------- phase 7: final sweep + journal recovery
        img = _frame(11)
        final = client.search(img)
        want = reference.search(img)
        lost = sorted(
            name for name in ledger
            if name not in final
            or "degrade_steps" in final[name]
            or not _dets_equal(final[name], want[name])
        )
        mini_final = mini_client.search(_frame(12))
        mini_want = mini_reference.search(_frame(12))
        mini_lost = sorted(
            name for name in mini_names
            if name not in mini_final
            or "degrade_steps" in mini_final[name]
            or not _dets_equal(mini_final[name], mini_want[name])
        )
        lost += mini_lost
        # a cold coordinator over the same WAL must recover the catalog
        reborn = GalleryFleet(
            SHARDS, policy=_policy(), replicas=REPLICAS,
            journal_dir=os.path.join(tmp.name, "journal"),
        )
        recovered = set(reborn.patterns()) == set(ledger)
        registered = len(ledger) + len(mini_names)
        survived = registered - len(lost)
        phases.append({
            "name": "final_sweep",
            "ok": bool(not lost and recovered),
            "registered": registered, "survived": survived,
            "journal_recovered": reborn.counters()["journal_recovered"],
        })
        _progress(f"final sweep: {survived}/{registered} survived, "
                  f"journal recovery exact={recovered}")
    finally:
        cleanup()
        if mini is not None:
            mini.close()
        fleet.close()
        tmp.cleanup()

    by_name = {p["name"]: p for p in phases}
    checks = {
        "zero_patterns_lost": bool(not lost),
        "fanout_byte_identical": bool(by_name["fanout_parity"]["ok"]),
        "all_faults_observed": bool(
            injected and all(rec["fired"] >= 1 for rec in injected)
        ),
        "all_faults_accounted": bool(
            injected and all(rec["accounted"] >= 1 for rec in injected)
        ),
        "degraded_exactly_labeled": bool(by_name["degrade_label"]["ok"]),
        "degrade_heals": bool(by_name["degrade_label"]["heals"]),
        "replication_recovered": bool(
            by_name["replica_corrupt"]["ok"] and by_name["kill"]["ok"]
        ),
        "env_schedule_delivered": bool(by_name["beat_env"]["ok"]),
    }
    if "bulk_ingest" in by_name:  # opt-in bulk-scale phase ran
        checks["bulk_ingest_ok"] = bool(by_name["bulk_ingest"]["ok"])
    doc = {
        "schema": SERVE_CHAOS_REPORT_SCHEMA,
        "config": {
            "shards": SHARDS, "workers": WORKERS,
            "replicas": REPLICAS, "patterns": registered,
            "tiny": bool(args.tiny),
            "patterns_per_shard": int(args.patterns_per_shard),
        },
        "phases": phases,
        "patterns": {
            "registered": registered,
            "survived": survived,
            "lost": lost,
        },
        "kills": {
            "rounds": kill_rounds,
            "workers_killed": workers_killed,
        },
        "faults": {
            "injected": injected,
            "observed": {k: int(v) for k, v in observed.items()},
        },
        "checks": checks,
    }
    problems = validate_serve_chaos_report(doc)
    if problems:  # self-check: the emitted document must validate
        doc["validator_problems"] = problems
    cancel_watchdog()  # before the success print: no success-then-watchdog
    line = json.dumps(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line, flush=True)
    return 0 if (all(checks.values()) and not problems
                 and all(p["ok"] for p in phases)) else 1


def main(argv=None) -> int:
    """One serve_chaos_report/v1 JSON line on stdout, success or not:
    the shared bench_guard funnels wedges and crashes into a
    contractual error record."""
    from tmr_tpu.diagnostics import SERVE_CHAOS_REPORT_SCHEMA
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(
            json.dumps({"schema": SERVE_CHAOS_REPORT_SCHEMA,
                        "error": msg}),
            flush=True,
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
