"""Gallery-tier benchmark: patterns×frames throughput, backbone
amortization, and prefilter recall (tmr_tpu/serve/gallery.py).

Drives a GalleryBank over a synthetic streaming workload and prints ONE
``gallery_report/v1`` JSON document (schema + validator in
tmr_tpu/diagnostics.py):

- **N-loop baseline** — every (frame, pattern) pair through
  ``predict_multi_exemplar``, the way N independent requests would pay:
  the backbone runs frames×N times.
- **Gallery full match** (prefilter off) — the same pairs through
  ``GalleryBank.search``: the fused one-backbone-pass program per cold
  frame. Checks: per-pair results BITWISE-identical to the N-loop, and
  backbone executions == frames (never frames×N), proven from the
  flight recorder's per-program call table (``TMR_FLIGHT`` devtime).
- **Prefilter sweep** — top-k rungs over the coarse channel-pooled
  low-res correlation ranking: detection-level recall vs the full
  match and the full-match invocation cut per rung; the smallest rung
  meeting recall >= 0.99 AND cut >= 2x is ELECTED and persisted to the
  autotune cache (``TMR_GALLERY_PREFILTER_TOPK=auto`` consumes it —
  the prefilter itself stays off/exact by default).
- **N-ladder sweep** — full-bank search wall under ladder caps
  (chunked heads programs vs the one fused rung); the winner persists
  as the measured ``TMR_GALLERY_NMAX``.
- **Index N-sweep** (``--sweep 1000,10000,100000``) — catalog-scale
  banks of random-geometry entries, per point: the exact linear
  prefilter pass timed and kept as the selection oracle, the
  coarse-to-fine sketch index (serve/gallery_index.py) timed on the
  same frame features, SELECTION recall (index top-k ∩ linear top-k)
  against ``--index-recall-floor``, and the argpartition-vs-stable-
  sort tie contract recomputed from the raw scores. The log-log
  wall-vs-N exponents of both arms land in the report
  (``n_sweep.fit``) with the sublinearity check; ``--fleet-patterns P``
  additionally re-runs the PR 17 chaos gauntlet with ``P`` bulk
  patterns per shard and gates on its rc.

The synthetic workload is the WATCHLIST shape: of the N registered
patterns only a fixed quarter are present in the stream frames
(texture instances on a featureless background); the rest are
registered over exact-zero background, whose NCC-centered template
carries ~zero energy — the structural "this pattern is not in the
frame" that frame-relative template extraction permits. Because a
random-init objectness head fires ~uniformly at sigmoid~0.5 (a
meaningless recall denominator), the bench surgically calibrates the
pipeline into a deterministic template-response detector (identity +
mean-centering input projection, identity decoder, channel-mean head
scaled so present-entry responses sit at logit +margin and
absent-entry responses at -margin — see ``_craft_detector``).
Detections then track the template-match response, which is precisely
the signal the coarse prefilter approximates, and the prefilter's job
— rank present patterns above absent ones — is real and measured, not
assumed. Recall is over the UNION of detection locations (feature
cells, coarsened one level to absorb per-entry RoIAlign jitter): the
fraction of the full match's detected locations the prefiltered top-k
still covers. The report carries the union size and the per-side
detection counts so a zero- or saturated-detection run can never read
as a hollow recall pass.

Usage:  python scripts/gallery_bench.py [--tiny] [--out FILE]
        [--patterns N] [--frames F] [--topk K] [--seed S]
        [--sweep N1,N2,...] [--index-recall-floor R] [--nprobe P]
        [--fleet-patterns P]

``--tiny`` (or TMR_BENCH_TINY=1) shrinks geometry so the whole sweep
smoke-runs on CPU (tier-1 runs it under JAX_PLATFORMS=cpu); real
numbers use the 1024^2 deployment geometry. Same one-JSON-line
contract as bench.py via the shared bench_guard.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()

#: detection fields compared bitwise between the fused gallery arm and
#: the N-loop baseline (count rides only under TMR_DECODE_TAIL=device)
_FIELDS = ("boxes", "scores", "refs", "valid")


def _progress(msg: str) -> None:
    print(f"[gallery_bench] {msg}", file=sys.stderr, flush=True)


def _make_workload(size: int, n_patterns: int, n_frames: int, seed: int):
    """(boxes, present, frames): the watchlist shape — of N registered
    patterns, only ``present`` (a fixed quarter of the bank, min 2) are
    IN the stream frames; the rest are registered over featureless
    (zero) background. ``boxes[i]`` is entry i's (1, 4) normalized
    exemplar: present entries' boxes sit over pasted instances of a
    shared texture (patch-aligned, off the borders, so an untrained
    backbone's position sensitivity does not decide the match); absent
    entries' boxes sit over exact-zero background, whose NCC-centered
    template carries ~zero energy — the structural realization of "this
    pattern is not in the frame" that frame-relative template
    extraction permits. Frames differ by a small RELATIVE perturbation
    of the instance pixels (distinct digests per frame): perturbing the
    high-amplitude content keeps the post-LayerNorm token shift small,
    where any fresh content dropped onto the zero background would be
    LayerNorm-AMPLIFIED to unit scale and attention-mixed into every
    token of the frame (measured: a noise block anywhere shifts the
    whole feature map enough to defeat any fixed calibration)."""
    rng = np.random.default_rng(seed)
    step = 16
    # patch-aligned, border-clear, non-overlapping slots
    tops, bpix = None, None
    for cand in range(max((size // 4) // 16 * 16, 16), 0, -16):
        for gap in (step, 0):  # prefer spaced slots, tile if tight
            pos = list(range(step, size - cand - step + 1, cand + gap))
            slots = [(y, x) for y in pos for x in pos]
            if len(slots) >= n_patterns:
                tops, bpix = slots[:n_patterns], cand
                break
        if tops is not None:
            break
    if tops is None:
        raise ValueError(
            f"workload: no patch-aligned layout fits {n_patterns} "
            f"slots at size={size}"
        )
    n_present = max(2, n_patterns // 4)
    stride = max(n_patterns // n_present, 1)
    present = sorted(set(
        list(range(0, n_patterns, stride))[:n_present]
    ) | {0})
    trng = np.random.default_rng(10_000 + seed)
    texture = trng.standard_normal((bpix, bpix, 3)).astype(np.float32) \
        * 3.0
    boxes = [
        np.asarray([[x / size, y / size, (x + bpix) / size,
                     (y + bpix) / size]], np.float32)
        for (y, x) in tops
    ]
    frames = []
    for _f in range(n_frames):
        img = np.zeros((size, size, 3), np.float32)
        for e in present:
            y, x = tops[e]
            img[y:y + bpix, x:x + bpix, :] = texture + rng.standard_normal(
                (bpix, bpix, 3)
            ).astype(np.float32) * 0.05
        frames.append(img)
    return boxes, present, frames


def _craft_detector(pred, frame, boxes, present, capacity: int,
                    margin: float = 4.0) -> dict:
    """Calibrate the pipeline into a deterministic template-response
    detector (see module docstring). Three surgical edits, all on the
    ordinary param tree (no program forks):

    - ``input_proj``: identity into the first C channels with bias
      ``-mean_token`` (the probe frame's spatial-mean BACKGROUND token)
      — the matcher then correlates CENTERED raw features: the NCC
      mean-subtraction that kills the untrained backbone's huge DC
      token similarity, and what makes an absent entry's zero-region
      template carry ~zero energy;
    - objectness decoder: centered-delta identity kernels, zero bias;
    - objectness head: channel mean of the f_tm half, scaled/biased so
      the probe frame's weakest PRESENT-entry self response maps to
      logit ``+margin`` and the strongest ABSENT-entry response to
      ``-margin``.

    Returns the calibration evidence for the report."""
    import jax

    model = pred.model.clone(template_capacity=int(capacity))
    p = jax.tree.map(np.asarray, pred.params)
    bb = pred._get_backbone_fn()
    feats = np.asarray(bb(pred.params, frame[None]))[0]
    # background tokens only: patches of the probe frame that are
    # entirely zero (the workload's featureless background)
    size = int(frame.shape[0])
    ph = size // feats.shape[0]
    patch_zero = np.asarray([
        [not frame[y * ph:(y + 1) * ph, x * ph:(x + 1) * ph].any()
         for x in range(feats.shape[1])]
        for y in range(feats.shape[0])
    ])
    sel = feats[patch_zero] if patch_zero.any() else feats.reshape(
        -1, feats.shape[-1]
    )
    mean_tok = sel.reshape(-1, feats.shape[-1]).mean(axis=0)
    c_in = int(mean_tok.shape[0])
    pk = np.zeros_like(p["input_proj_0"]["kernel"])  # (1, 1, C_in, emb)
    pk[0, 0, np.arange(c_in), np.arange(c_in)] = 1.0
    p["input_proj_0"]["kernel"] = pk
    pb = np.zeros_like(p["input_proj_0"]["bias"])
    pb[:c_in] = -mean_tok
    p["input_proj_0"]["bias"] = pb
    dk = p["decoder_o_0"]["conv_0"]["kernel"]
    ident = np.zeros_like(dk)
    idx = np.arange(dk.shape[2])
    ident[dk.shape[0] // 2, dk.shape[1] // 2, idx, idx] = 1.0
    p["decoder_o_0"]["conv_0"]["kernel"] = ident
    p["decoder_o_0"]["conv_0"]["bias"] = np.zeros_like(
        p["decoder_o_0"]["conv_0"]["bias"]
    )
    pred.params = p

    # probe the crafted matcher response per entry; out["f_tm"] is the
    # relu'd matcher output — exactly what the identity decoder + mean
    # head read (up to the 0.01 leaky slope on negatives)
    probe = jax.jit(
        lambda pp, im, ex: model.apply({"params": pp}, im, ex)["f_tm"][0]
    )
    grid = pred.feature_hw(size)
    present_floor, absent_ceiling = np.inf, -np.inf
    emb = None
    for i, b in enumerate(boxes):
        m = np.asarray(probe(pred.params, frame[None], b[None]))[0]
        emb = m.shape[-1]
        resp = m.mean(axis=-1)
        if i in present:
            cx = int((b[0, 0] + b[0, 2]) / 2 * grid)
            cy = int((b[0, 1] + b[0, 3]) / 2 * grid)
            present_floor = min(
                present_floor,
                float(resp[max(cy - 1, 0):cy + 2,
                           max(cx - 1, 0):cx + 2].max()),
            )
        else:
            absent_ceiling = max(absent_ceiling, float(resp.max()))
    scale = 2.0 * margin / max(present_floor - absent_ceiling, 1e-6)
    bias = -scale * (present_floor + absent_ceiling) / 2.0
    hk = np.zeros_like(p["objectness_head_0"]["conv"]["kernel"])
    hk[0, 0, -emb:, 0] = scale / emb
    p["objectness_head_0"]["conv"]["kernel"] = hk
    p["objectness_head_0"]["conv"]["bias"] = np.asarray(
        [bias], np.float32
    )
    pred.params = p
    return {"margin": margin,
            "present_floor": round(present_floor, 6),
            "absent_ceiling": round(absent_ceiling, 6),
            "separated": bool(present_floor > absent_ceiling),
            "scale": round(scale, 4)}


def _sweep_boxes(n: int, seed: int) -> list:
    """``n`` random-geometry (1, 4) normalized exemplar boxes for
    catalog-scale banks. The patch-aligned watchlist layout tops out at
    ~hundreds of non-overlapping slots; index sweep points need
    10^3..10^5 entries whose SELECTION (not detection quality) is under
    test, so arbitrary overlapping geometry is exactly right."""
    rng = np.random.default_rng(seed)
    wh = rng.uniform(0.04, 0.25, size=(n, 2)).astype(np.float32)
    xy = rng.uniform(size=(n, 2)).astype(np.float32) * (1.0 - wh)
    boxes = np.concatenate([xy, xy + wh], axis=1)
    return [boxes[i:i + 1] for i in range(n)]


def _linear_scan(bank, feats):
    """The exact linear prefilter pass, run bench-side so the sweep
    can time it AND keep every raw per-entry score for the stable-sort
    tie reference (the bank's own scan tail-caps its scores dict at
    catalog scale)."""
    names, chunks = [], []
    for g in bank._groups_locked():
        fn = bank._pred._get_gallery_prefilter_fn(g.n_bucket, g.k_bucket)
        s = np.asarray(fn(feats, g.ex_dev, g.k_dev, g.n_dev))
        names.extend(g.names)
        chunks.append(s[:g.n_real])
    return names, np.concatenate(chunks)


def _loglog_exponent(ns, walls):
    """Least-squares slope of log(wall) vs log(N) — the measured
    scaling exponent (1.0 = linear, 0.5 = sqrt)."""
    if len(ns) < 2 or any(w <= 0 for w in walls):
        return None
    slope = np.polyfit(np.log(np.asarray(ns, np.float64)),
                       np.log(np.asarray(walls, np.float64)), 1)[0]
    return round(float(slope), 3)


def _run_fleet_probe(patterns_per_shard: int) -> dict:
    """Re-run the PR 17 serve chaos gauntlet with the streamed
    bulk-ingest phase at ``patterns_per_shard`` — the index/bulk paths
    proven under kills, corrupt replicas, and journal faults."""
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "scripts", "serve_chaos_probe.py"),
         "--tiny", "--patterns-per-shard", str(patterns_per_shard)],
        env=env, capture_output=True, text=True, timeout=1200,
    )
    doc = {}
    for ln in proc.stdout.splitlines():
        try:
            doc = json.loads(ln)
            break
        except ValueError:
            continue
    out = {
        "patterns_per_shard": int(patterns_per_shard),
        "rc": int(proc.returncode),
        "checks": doc.get("checks"),
    }
    if "error" in doc:
        out["error"] = doc["error"]
    bulk = next((p for p in doc.get("phases", ())
                 if isinstance(p, dict) and p.get("name") == "bulk_ingest"),
                None)
    if bulk is not None:
        out["bulk_ingest"] = bulk
    return out


def _run_sweep(pred, size: int, args) -> dict:
    """The index N-sweep (module docstring). Per point: one bank holds
    both arms — the same frame features flow through the exact linear
    scan (oracle + timing) and the sketch-index election (timing +
    recall + counters)."""
    import jax.numpy as jnp

    from tmr_tpu.serve import GalleryBank
    from tmr_tpu.serve.gallery import _topk_flat

    ns = sorted({int(x) for x in args.sweep.split(",") if x.strip()})
    floor = float(args.index_recall_floor)
    rng = np.random.default_rng(args.seed + 77)
    # structured query frame (low-frequency field + mild detail): the
    # regime a GEOMETRIC index serves. Real stream frames have smooth
    # feature maps, so nearby boxes score nearby; pure white noise
    # decorrelates at the patch scale and defeats any coarse routing —
    # an adversarial input the index answers with its counted linear
    # fallback, not a recall claim
    coarse = rng.standard_normal((8, 8, 3)).astype(np.float32)
    frame = np.repeat(np.repeat(coarse, size // 8, 0), size // 8, 1)
    frame = frame + rng.standard_normal(
        (size, size, 3)
    ).astype(np.float32) * 0.1
    bb = pred._get_backbone_fn()
    feats = bb(pred.exec_params(), jnp.asarray(frame[None]))
    points = []
    for n in ns:
        topk = max(1, min(32, n // 4))
        _progress(f"sweep N={n}: registering")
        boxes = _sweep_boxes(n, args.seed + n)
        t0 = time.perf_counter()
        bank = GalleryBank(pred, feature_cache=0, max_n_bucket=32,
                           index=True, index_min_n=1,
                           index_nprobe=args.nprobe or None)
        for i, b in enumerate(boxes):
            bank.register(f"sku{i:06d}", b)
        reg_s = time.perf_counter() - t0
        groups = bank._groups_locked()
        # warm pass: compiles both arms' programs; the first index
        # election also pays the k-means build (recorded via
        # index_stats, kept out of the timed query)
        t0 = time.perf_counter()
        _linear_scan(bank, feats)
        bank._prefilter_select(feats, groups, topk, jnp)
        warm_s = time.perf_counter() - t0
        c0 = {k: bank.counters[k]
              for k in ("index_queries", "index_probes",
                        "index_candidates", "index_fallbacks")}
        t0 = time.perf_counter()
        names, flat = _linear_scan(bank, feats)
        lin_idx = _topk_flat(flat, topk)
        linear_ms = (time.perf_counter() - t0) * 1e3
        linear_sel = {names[i] for i in lin_idx}
        # the argpartition/tie contract, recomputed from raw scores:
        # identical selection SET to the stable descending sort's
        # first top-k (ties in flat group order)
        ranked = sorted(range(len(names)), key=lambda i: -flat[i])
        off_exact = {names[i] for i in ranked[:topk]} == linear_sel
        t0 = time.perf_counter()
        index_sel, _ = bank._prefilter_select(feats, groups, topk, jnp)
        index_ms = (time.perf_counter() - t0) * 1e3
        delta = {k: int(bank.counters[k] - c0[k]) for k in c0}
        istats = bank.index_stats()
        recall = len(index_sel & linear_sel) / float(topk)
        points.append({
            "n": int(n), "topk": int(topk),
            "register_s": round(reg_s, 3),
            "warm_s": round(warm_s, 3),
            "linear_ms": round(linear_ms, 3),
            "index_ms": round(index_ms, 3),
            "recall": round(recall, 4),
            "off_exact": bool(off_exact),
            "indexed": bool(delta["index_queries"] >= 1
                            and delta["index_fallbacks"] == 0),
            "centroids": int(istats.get("centroids") or 0),
            "probes": delta["index_probes"],
            "candidates": delta["index_candidates"],
            "groups": len(groups),
            "rebuild_wall_s": istats.get("rebuild_wall_s"),
        })
        _progress(
            f"N={n}: linear {linear_ms:.1f}ms index {index_ms:.1f}ms "
            f"recall {recall:.3f} (probes {delta['index_probes']}, "
            f"candidates {delta['index_candidates']})"
        )
    exp_linear = _loglog_exponent([p["n"] for p in points],
                                  [p["linear_ms"] for p in points])
    exp_index = _loglog_exponent([p["n"] for p in points],
                                 [p["index_ms"] for p in points])
    if exp_index is not None:
        # sublinear in measured exponent, or decisively below the
        # linear arm's own measured scaling (fixed per-call dispatch
        # overhead can flatten BOTH curves at small N)
        sublinear = bool(exp_index <= 0.8
                         or (exp_linear is not None
                             and exp_index <= 0.8 * exp_linear))
    else:  # single-point sweep: no fit — gate on the direct wall win
        sublinear = bool(points
                         and points[-1]["index_ms"]
                         <= points[-1]["linear_ms"])
    checks = {
        "index_sublinear": sublinear and all(p["indexed"]
                                             for p in points),
        "index_recall_ok": bool(points) and all(
            p["recall"] >= floor for p in points
        ),
        "index_off_exact": bool(points) and all(
            p["off_exact"] for p in points
        ),
    }
    sweep = {
        "points": points,
        "recall_floor": floor,
        "fit": {"linear_exponent": exp_linear,
                "index_exponent": exp_index},
        "checks": checks,
    }
    if args.fleet_patterns > 0:
        _progress(f"fleet probe re-run: {args.fleet_patterns} "
                  "patterns/shard through the bulk sink")
        probe = _run_fleet_probe(args.fleet_patterns)
        sweep["fleet_probe"] = probe
        checks["fleet_probe_ok"] = bool(probe["rc"] == 0)
        _progress(f"fleet probe rc={probe['rc']}")
    return sweep


def _det_count(result: dict) -> int:
    return int(np.asarray(result["valid"]).sum())


def _det_cells(result: dict, grid: int) -> set:
    """Detected locations as COARSE feature cells (one level coarser
    than the grid, absorbing the one-cell RoIAlign jitter between
    entries' near-identical templates)."""
    valid = np.asarray(result["valid"])[0]
    refs = np.asarray(result["refs"])[0]
    out = set()
    for r in refs[valid]:
        out.add((int(r[0] * grid) // 2, int(r[1] * grid) // 2))
    return out


def _program_calls(kinds) -> dict:
    """Executed-call counts per devtime program kind (warmup calls
    included — an execution is an execution)."""
    from tmr_tpu import obs

    out: dict = {}
    for prog in obs.mfu_report()["programs"]:
        if prog["kind"] in kinds:
            out[prog["kind"]] = out.get(prog["kind"], 0) \
                + int(prog["calls"]) + int(prog["warmup_calls"])
    return out


def _run(cancel_watchdog, argv=None) -> int:
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke geometry (also TMR_BENCH_TINY=1)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    ap.add_argument("--patterns", type=int, default=8,
                    help="bank size N (acceptance floor: 8)")
    ap.add_argument("--frames", type=int, default=4,
                    help="measured stream frames")
    ap.add_argument("--topk", type=int, default=None,
                    help="pin one prefilter top-k instead of sweeping")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sweep", default="",
                    help="comma-separated catalog sizes for the index "
                         "N-sweep (e.g. 1000,10000,100000; empty = "
                         "skipped)")
    ap.add_argument("--index-recall-floor", type=float, default=0.9,
                    help="minimum index-vs-linear selection recall "
                         "per sweep point")
    ap.add_argument("--nprobe", type=int, default=0,
                    help="buckets probed per indexed sweep query "
                         "(0 = auto = ceil(sqrt(C)))")
    ap.add_argument("--fleet-patterns", type=int, default=0,
                    help="re-run the serve chaos gauntlet with this "
                         "many bulk patterns per shard (0 = skipped)")
    args = ap.parse_args(argv)

    tiny = args.tiny or os.environ.get("TMR_BENCH_TINY", "") not in (
        "", "0", "false"
    )
    size = int(os.environ.get("TMR_BENCH_SIZE", 256 if tiny else 1024))
    dtype = "float32" if tiny else "bfloat16"

    import jax

    from tmr_tpu import obs
    from tmr_tpu.config import preset
    from tmr_tpu.diagnostics import (
        GALLERY_REPORT_SCHEMA,
        validate_gallery_report,
    )
    from tmr_tpu.inference import Predictor
    from tmr_tpu.serve import GalleryBank
    from tmr_tpu.utils.autotune import record_gallery_winners

    _progress(f"backend: {jax.devices()[0]} size={size} tiny={tiny} "
              f"patterns={args.patterns} frames={args.frames}")
    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=size,
                 compute_dtype=dtype, batch_size=1)
    pred = Predictor(cfg)
    _progress("init_params (jitted init)")
    pred.init_params(seed=0, image_size=size)

    n_pat, n_frames = int(args.patterns), int(args.frames)
    boxes, present, frames = _make_workload(size, n_pat, n_frames,
                                            args.seed)
    wall0 = time.perf_counter()
    # the flight recorder is the backbone-amortization witness: every
    # program execution lands in the devtime call table
    obs.flight_configure(enabled=True)

    cap0 = pred.pick_capacity(boxes[0], size)
    calibration = _craft_detector(pred, frames[0], boxes, present, cap0)
    _progress(f"calibrated detector (present={present}): {calibration}")

    # ladder cap pinned to the bank size: the acceptance phases must
    # measure the fused single-group arm deterministically, not inherit
    # whatever a previous sweep persisted into the autotune cache
    bank = GalleryBank(pred, feature_cache=8, max_n_bucket=32)
    for i, box in enumerate(boxes):
        bank.register(f"pattern{i}", box)
    stats0 = bank.stats()
    _progress(f"bank: {stats0['entries']} entries, groups "
              f"{stats0['groups']}")

    # ---- warmup: compile the N-loop program and the fused gallery
    # program outside every timed window, on throwaway frames
    rng_w = np.random.default_rng(991)
    warm = rng_w.standard_normal((size, size, 3)).astype(np.float32)
    _progress("warmup compiles (n-loop + fused gallery)")
    pred.predict_multi_exemplar(warm[None], boxes[0], k_real=1)
    bank.search(rng_w.standard_normal((size, size, 3)).astype(np.float32))

    # ---- N-loop baseline: one predict_multi_exemplar per (frame,
    # pattern) pair — the N-independent-requests cost
    _progress("phase n_loop baseline")
    nloop: dict = {}
    t0 = time.perf_counter()
    for f, frame in enumerate(frames):
        for i, box in enumerate(boxes):
            dets = pred.predict_multi_exemplar(frame[None], box, k_real=1)
            nloop[(f, i)] = {
                k: np.asarray(dets[k]) for k in _FIELDS if k in dets
            }
    jax.block_until_ready(dets["scores"])
    nloop_dt = time.perf_counter() - t0
    nloop_tput = (n_pat * n_frames) / nloop_dt
    _progress(f"n_loop: {nloop_tput:.3f} pattern-frames/s")

    # ---- gallery full match (prefilter off), fresh devtime window
    _progress("phase gallery full match")
    from tmr_tpu.obs import devtime

    devtime.reset()
    fm0 = bank.counters["full_match_entries"]
    gallery: dict = {}
    t0 = time.perf_counter()
    for f, frame in enumerate(frames):
        results = bank.search(frame)
        for i in range(n_pat):
            gallery[(f, i)] = results[f"pattern{i}"]
    gal_dt = time.perf_counter() - t0
    gal_tput = (n_pat * n_frames) / gal_dt
    by_program = _program_calls(
        ("gallery", "gallery_heads", "backbone", "multi")
    )
    backbone_execs = by_program.get("gallery", 0) \
        + by_program.get("backbone", 0)
    full_matches_off = bank.counters["full_match_entries"] - fm0
    counters_full = dict(bank.counters)
    _progress(
        f"gallery: {gal_tput:.3f} pattern-frames/s "
        f"({gal_tput / nloop_tput:.2f}x n-loop), backbone executions "
        f"{backbone_execs} for {n_frames} frames (by_program "
        f"{by_program})"
    )

    # ---- fused-arm exactness: bitwise vs the N-loop, per pair
    mismatches = 0
    for key, want in nloop.items():
        got = gallery[key]
        if not all(
            np.array_equal(np.asarray(want[k]), np.asarray(got[k]))
            for k in _FIELDS
        ):
            mismatches += 1
    exact = mismatches == 0
    grid = pred.feature_hw(size)
    # the full match's detected locations per frame, as the UNION over
    # entries of coarse feature cells — the recall denominator (entry
    # detection sets nearly coincide on the counting workload, so the
    # union is what a stream consumer actually loses to the prefilter)
    full_union = {
        f: set().union(*(
            _det_cells(gallery[(f, i)], grid) for i in range(n_pat)
        ))
        for f in range(n_frames)
    }
    total_dets = sum(_det_count(r) for r in gallery.values())
    union_cells = sum(len(u) for u in full_union.values())
    slots = int(np.asarray(gallery[(0, 0)]["valid"]).shape[1])
    _progress(f"exactness: {mismatches} mismatching pairs of "
              f"{len(nloop)}; detections {total_dets} "
              f"({union_cells} union cells, {slots} slots/entry)")

    # ---- prefilter sweep: union recall + invocation cut per top-k rung
    if args.topk:
        rung_list = [int(args.topk)]
    else:
        rung_list = sorted({
            max(1, n_pat // 4), max(1, n_pat // 2),
            max(1, (3 * n_pat) // 4),
        })
    rungs = []
    elected = None
    for topk in rung_list:
        _progress(f"prefilter top-{topk}")
        fm0 = bank.counters["full_match_entries"]
        covered = 0
        for f, frame in enumerate(frames):
            results = bank.search(frame, prefilter_topk=topk)
            pre_union: set = set()
            for i in range(n_pat):
                pre_union |= _det_cells(results[f"pattern{i}"], grid) \
                    if "refs" in results[f"pattern{i}"] else set()
            covered += len(pre_union & full_union[f])
        full_matches = bank.counters["full_match_entries"] - fm0
        recall = (covered / union_cells) if union_cells else 0.0
        cut = (n_pat * n_frames) / max(full_matches, 1)
        rungs.append({
            "topk": topk,
            "recall": round(recall, 4),
            "full_matches": full_matches,
            "full_matches_without": n_pat * n_frames,
            "invocation_cut": round(cut, 3),
        })
        if elected is None and recall >= 0.99 and cut >= 2.0:
            elected = topk
        _progress(f"top-{topk}: recall {recall:.4f}, cut {cut:.2f}x")

    # ---- N-ladder sweep: full-bank search wall under ladder caps
    # (chunked heads programs vs the fused rung) — the measured
    # TMR_GALLERY_NMAX, elected like the batch bound
    ladder_rungs = sorted({
        r for r in (2, 4, 8, 16, 32) if r <= n_pat
    } | {n_pat if n_pat in (1, 2, 4, 8, 16, 32) else 0} - {0})
    ladder = []
    sweep_frames = frames[: min(2, len(frames))]
    for rung in ladder_rungs:
        b = GalleryBank(pred, feature_cache=0, max_n_bucket=rung)
        for i, box in enumerate(boxes):
            b.register(f"pattern{i}", box)
        for frame in sweep_frames:  # warm this rung's programs
            b.search(frame)
        t0 = time.perf_counter()
        for frame in sweep_frames:
            b.search(frame)
        ladder.append({"n_bucket": rung, "wall_s": round(
            time.perf_counter() - t0, 4
        )})
        _progress(f"ladder rung {rung}: {ladder[-1]['wall_s']}s")
    # election policy (the pick_quant decisive-win shape): the LARGEST
    # rung is the structural default — one fused single-group program,
    # bitwise arm intact — and a smaller rung must beat it by >10% to
    # win, so timing noise can never chunk production banks
    nmax_winner = None
    if ladder:
        best = max(r["n_bucket"] for r in ladder)
        best_wall = next(r["wall_s"] for r in ladder
                         if r["n_bucket"] == best)
        for r in sorted(ladder, key=lambda r: r["n_bucket"]):
            if r["wall_s"] < 0.9 * best_wall:
                best, best_wall = r["n_bucket"], r["wall_s"]
                break
        nmax_winner = best
    record_gallery_winners(size, nmax=nmax_winner, topk=elected)

    # ---- index N-sweep: sketch index vs linear scan at catalog scale
    n_sweep = _run_sweep(pred, size, args) if args.sweep else None

    # a recall pass must be NON-HOLLOW: detections exist and do not
    # saturate the slot capacity (a fire-everywhere detector makes any
    # union recall read 1.0)
    nontrivial = bool(
        union_cells > 0
        and total_dets < n_frames * n_pat * slots // 2
    )
    prefilter_recall_ok = bool(elected is not None and nontrivial)
    elected_rec = next(
        (r for r in rungs if r["topk"] == elected), None
    )
    report = {
        "schema": GALLERY_REPORT_SCHEMA,
        "device": str(jax.devices()[0]),
        "config": {
            "image_size": size,
            "patterns": n_pat,
            "frames": n_frames,
            "present": list(present),
            "seed": int(args.seed),
            "dtype": dtype,
        },
        "bank": {
            "entries": stats0["entries"],
            "groups": stats0["groups"],
            "max_n_bucket": stats0["max_n_bucket"],
        },
        "throughput": {
            "gallery_pattern_frames_per_sec": round(gal_tput, 3),
            "n_loop_pattern_frames_per_sec": round(nloop_tput, 3),
            "speedup": round(gal_tput / nloop_tput, 3),
        },
        "backbone": {
            "frames": n_frames,
            "executions": int(backbone_execs),
            "pattern_frame_pairs": n_pat * n_frames,
            "by_program": by_program,
        },
        "exact": {
            "pairs": len(nloop),
            "mismatches": mismatches,
            "total_detections": total_dets,
            "union_cells": union_cells,
            "slots_per_entry": slots,
        },
        "calibration": calibration,
        "prefilter": {
            "rungs": rungs,
            "elected_topk": elected,
            "recall_at_elected": (
                elected_rec["recall"] if elected_rec else None
            ),
            "cut_at_elected": (
                elected_rec["invocation_cut"] if elected_rec else None
            ),
        },
        "ladder": {"rungs": ladder, "elected_nmax": nmax_winner},
        **({"n_sweep": n_sweep} if n_sweep is not None else {}),
        "counters": counters_full,
        "checks": {
            "bitwise_exact": bool(exact),
            "backbone_amortized": bool(backbone_execs == n_frames),
            "full_match_entries_off": int(full_matches_off),
            "speedup_vs_n_loop": round(gal_tput / nloop_tput, 3),
            "prefilter_recall_ok": prefilter_recall_ok,
            "prefilter_cut_ok": bool(
                elected_rec is not None
                and elected_rec["invocation_cut"] >= 2.0
            ),
            "detections_nonzero": bool(total_dets > 0),
            "detections_nontrivial": nontrivial,
        },
    }
    report["wall_s"] = round(time.perf_counter() - wall0, 1)
    problems = validate_gallery_report(report)
    if problems:  # self-check: the emitted document must validate
        report["validator_problems"] = problems

    cancel_watchdog()  # before the success print: no success-then-watchdog
    line = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    return 0


def main(argv=None) -> int:
    """One gallery_report/v1 JSON line on stdout, success or not: the
    shared bench_guard (same watchdog bench.py runs under) funnels
    wedges and crashes into a contractual error record."""
    from tmr_tpu.diagnostics import GALLERY_REPORT_SCHEMA
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(
            json.dumps({"schema": GALLERY_REPORT_SCHEMA, "error": msg}),
            flush=True,
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
