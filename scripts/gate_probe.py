"""Diagnose WHY each gated custom kernel is refused on the live backend.

Round-5 session-7 finding: on the real TPU every require_tpu formulation
(flash global/windowed, pallas global/windowed, pallas xcorr) fell back,
while the one pure-XLA alternative (blockfolded) won the headline — but
the gates swallow their refusal reason, so "Mosaic can't lower through
this backend" vs "kernel miscompiles numerically" vs "backend-name
mismatch" were indistinguishable. This script runs each gate at the
production geometry and, for the pallas paths, also calls the kernel
DIRECTLY (no gate) so a lowering exception surfaces with its full
traceback.

Since the structured-diagnostics layer (tmr_tpu/diagnostics.py) landed,
every gate refusal records a machine-readable cause (category, exception
class + message, tile config, device kind); the gates are cache_clear'd
here first so a cause is recorded even for verdicts another trace already
cached.

Output modes:
  default        one JSON line per probe on stdout (legacy watcher format);
                 tracebacks/debug on stderr
  --json         ONE gate_probe/v1 JSON document on stdout:
                 {"schema", "backend", "probes": [{..., "refusals": [...]}],
                  "refusals": [...]}   (the flat list aggregates all causes)
  --out FILE     additionally write the --json document to FILE
                 (gate_probe.json schema — the committed artifact)

Single tunnel client; run only when no other bench/battery stage is live.
"""

import argparse
import json
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()
os.environ["TMR_GATE_DEBUG"] = "1"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main(argv=None) -> int:
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()  # probes jit self-checks; reuse them
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true", dest="as_doc",
                    help="emit ONE gate_probe/v1 JSON document")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    args = ap.parse_args(argv)

    from tmr_tpu.diagnostics import GATE_PROBE_SCHEMA, drain_gate_refusals

    probes = []

    def emit(**kw):
        probes.append(kw)
        if not args.as_doc:
            print(json.dumps(kw), flush=True)

    backend = dict(
        default_backend=jax.default_backend(),
        devices=[str(d) for d in jax.devices()],
        device_kind=jax.devices()[0].device_kind,
        platform=jax.devices()[0].platform,
        jax_version=jax.__version__,
    )
    emit(probe="backend", **backend)

    # 1. trivial pallas kernel, compiled mode — does Mosaic lower AT ALL?
    try:
        from jax.experimental import pallas as pl

        def add1(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1.0

        x = jnp.zeros((256, 256), jnp.float32)
        y = pl.pallas_call(
            add1, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)
        )(x)
        ok = bool(np.asarray(y)[0, 0] == 1.0)
        emit(probe="pallas_trivial", ok=ok)
    except Exception as e:
        traceback.print_exc()
        emit(probe="pallas_trivial", ok=False,
             error=f"{type(e).__name__}: {e}")

    # 2. the global-attention pallas kernels DIRECT (no gate), bench
    # geometry: grid 64x64, head_dim 64, B1 H2 (the gate's own shape)
    from tmr_tpu.models.vit import blockwise_decomposed_attention
    from tmr_tpu.ops.pallas_attn import (
        pallas_decomposed_attention,
        pallas_fused_attention,
    )

    rng = np.random.default_rng(0)
    gh = gw = 64
    D = 64
    S = gh * gw
    q = jnp.asarray(rng.standard_normal((1, 2, S, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((1, 2, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 2, S, D)), jnp.bfloat16)
    rh = jnp.asarray(rng.standard_normal((gh, gh, D)) * 0.2, jnp.float32)
    rw = jnp.asarray(rng.standard_normal((gw, gw, D)) * 0.2, jnp.float32)
    # the blockwise oracle is the same for every probed kernel: run once
    want = None
    for probe_name, attn_fn in (
        ("pallas_global_direct", pallas_decomposed_attention),
        ("pallas_fused_direct", pallas_fused_attention),
    ):
        try:
            got = jax.jit(
                lambda *a, _f=attn_fn: _f(*a, (gh, gw), D**-0.5)
            )(q, k, v, rh, rw)
            got.block_until_ready()

            if want is None:
                want = np.asarray(jax.jit(
                    lambda *a: blockwise_decomposed_attention(
                        *a, (gh, gw), D**-0.5)
                )(q, k, v, rh, rw), np.float32)
            err = float(np.abs(np.asarray(got, np.float32) - want).max())
            ref = float(np.abs(want).max())
            emit(probe=probe_name, ok=bool(err / (ref + 1e-6) < 0.05),
                 rel_err=err / (ref + 1e-6))
        except Exception as e:
            traceback.print_exc()
            emit(probe=probe_name, ok=False,
                 error=f"{type(e).__name__}: {e}")

    # 3. every production gate, cache-cleared so refusal causes record
    from tmr_tpu.ops.flash_attn import (
        blockfolded_ok,
        densefolded_ok,
        flash_attention_ok,
        flash_window_ok,
        xlaflash_ok,
    )
    from tmr_tpu.ops.pallas_attn import (
        effective_fused_tiles,
        effective_global_tiles,
        pallas_fused_ok,
        pallas_global_ok,
        pallas_window_ok,
    )
    from tmr_tpu.ops.pallas_xcorr import pallas_xcorr_ok
    from tmr_tpu.models.vit import _scores_dtype

    for gate_fn in (blockfolded_ok, densefolded_ok, flash_attention_ok,
                    flash_window_ok, xlaflash_ok, pallas_fused_ok,
                    pallas_global_ok, pallas_window_ok, pallas_xcorr_ok):
        clear = getattr(gate_fn, "cache_clear", None)  # not all are cached
        if clear is not None:
            clear()

    bq, bk = effective_global_tiles(64 * 64)
    fbq, fbk = effective_fused_tiles(64 * 64, 64)
    live_scores = _scores_dtype()
    gates = {
        "flash_global_64x64_d64": lambda: flash_attention_ok(64, 64, 64),
        f"blockfolded_64x64_d64_scores_{live_scores}":
            lambda: blockfolded_ok(64, 64, 64, live_scores),
        f"densefolded_64x64_d64_scores_{live_scores}":
            lambda: densefolded_ok(64, 64, 64, live_scores),
        "xlaflash_64x64_d64": lambda: xlaflash_ok(64, 64, 64),
        "flash_window_14x14_d64": lambda: flash_window_ok(14, 14, 64),
        "pallas_global_64x64_d64":
            lambda: pallas_global_ok(64, 64, 64, bq, bk),
        f"pallas_fused_64x64_d64_bq{fbq}_bk{fbk}":
            lambda: pallas_fused_ok(64, 64, 64, fbq, fbk),
        "pallas_window_14x14_d64_g8":
            lambda: pallas_window_ok(14, 14, 64, 8),
        "pallas_xcorr_c256_64_t17": lambda: pallas_xcorr_ok(256, 64, 64, 17),
    }
    drain_gate_refusals()  # discard causes from the direct probes above
    for name, fn in gates.items():
        try:
            ok = bool(fn())
            emit(probe=name, ok=ok, refusals=drain_gate_refusals())
        except Exception as e:
            traceback.print_exc()
            emit(probe=name, ok=False, error=f"{type(e).__name__}: {e}",
                 refusals=drain_gate_refusals())

    # the bf16-score-tile gates (the env the check traces under must match
    # the cache key being probed — set it for the duration)
    if live_scores != "bf16":
        os.environ["TMR_GLOBAL_SCORES_DTYPE"] = "bf16"
        try:
            for name, fn in {
                "blockfolded_64x64_d64_scores_bf16":
                    lambda: blockfolded_ok(64, 64, 64, "bf16"),
                "densefolded_64x64_d64_scores_bf16":
                    lambda: densefolded_ok(64, 64, 64, "bf16"),
            }.items():
                try:
                    emit(probe=name, ok=bool(fn()),
                         refusals=drain_gate_refusals())
                except Exception as e:
                    traceback.print_exc()
                    emit(probe=name, ok=False,
                         error=f"{type(e).__name__}: {e}",
                         refusals=drain_gate_refusals())
        finally:
            os.environ.pop("TMR_GLOBAL_SCORES_DTYPE", None)

    # 4. the decoder-tail gates (PR-6 surface: fused decoder heads, int8
    # quant tiers, device decode tail) at the production geometry — the
    # 2x-upsampled 128^2 grid with c_cat 1024 (emb_dim 512, fusion
    # doubles it), decoder_num_layer 1, kernel 3. These gates key their
    # own dict caches (not lru_cache), so clear those the same way for a
    # recorded cause even when another trace already cached the verdict.
    from tmr_tpu.ops import fused_heads as _fh
    from tmr_tpu.ops import pallas_int8 as _pi8
    from tmr_tpu.ops import postprocess as _pp
    from tmr_tpu.ops import quant as _q

    _fh._OK_CACHE.clear()
    _q._OK_CACHE.clear()
    _pp._TAIL_OK.clear()
    _pi8._OK_CACHE.clear()
    # production geometry on the TPU; the off-accelerator contract run
    # (tests/test_bench_cli.py) probes the same code path at a geometry a
    # CPU can turn around — the verdict is per-geometry either way
    ph, pc = (128, 1024) if jax.default_backend() == "tpu" else (32, 256)
    for name, fn in {
        f"fused_heads_{ph}x{ph}_c{pc}": lambda: _fh.fused_heads_ok(
            ph, ph, pc, pc, 1, 3, "bfloat16"),
        f"quant_int8_{ph}x{ph}_c{pc}": lambda: _q.quant_ok(
            ph, ph, pc, pc, 1, 3),
        # the TMR_QUANT_STORAGE surface: the equality-tier storage pin,
        # the both-operand-int8 tolerance tier, the Mosaic int8 MXU
        # kernel self-check, and the matcher's int8dot conv tier
        f"quant_storage_{ph}x{ph}_c{pc}": lambda: _q.quant_storage_ok(
            ph, ph, pc, pc, 1, 3),
        f"quant_int8dot_{ph}x{ph}_c{pc}": lambda: _q.quant_int8dot_ok(
            ph, ph, pc, pc, 1, 3),
        "pallas_int8_mm_256": lambda: _pi8.pallas_int8_ok(),
        "quant_xcorr_c256_64_t17": lambda: _q.quant_xcorr_ok(
            256, 64, 64, 17),
        "quant_xcorr_int8dot_c256_64_t17": lambda: _q.quant_xcorr_ok(
            256, 64, 64, 17, kernel="int8dot"),
        "device_decode_tail": lambda: _pp.device_tail_ok(),
    }.items():
        try:
            emit(probe=name, ok=bool(fn()), refusals=drain_gate_refusals())
        except Exception as e:
            traceback.print_exc()
            emit(probe=name, ok=False, error=f"{type(e).__name__}: {e}",
                 refusals=drain_gate_refusals())

    # 4b. the fused gallery program's gate (serve/gallery.py): the
    # trace-only backbone-amortization invariant — the jaxpr of the
    # one-backbone-pass multi-pattern program must consume the frame
    # through exactly one backbone entry conv. Production bank shape
    # (N=8, k=1) at the smallest capacity bucket; production image
    # geometry on TPU, reduced on CPU like the decoder-tail gates.
    # No params needed: the gate traces over eval_shape abstract params.
    try:
        from tmr_tpu.config import preset as _preset
        from tmr_tpu.inference import Predictor as _Predictor
        from tmr_tpu.serve import gallery as _gallery

        _gallery._GATE_CACHE.clear()
        gsize = 1024 if jax.default_backend() == "tpu" else 64
        gpred = _Predictor(_preset(
            "TMR_FSCD147", backbone="sam_vit_b", image_size=gsize,
            compute_dtype="float32",
        ))
        emit(probe=f"gallery_fused_{gsize}_n8_k1",
             ok=bool(_gallery.gallery_fused_ok(gpred, 9, 8, 1)),
             refusals=drain_gate_refusals())
    except Exception as e:
        traceback.print_exc()
        emit(probe="gallery_fused", ok=False,
             error=f"{type(e).__name__}: {e}",
             refusals=drain_gate_refusals())

    # 5. the program-tier audit (tmr_tpu/analysis): the bucketed
    # production programs traced to jaxprs under the CURRENT env knobs
    # and checked structurally (no-S^2 attention, no-f64, quant-widen,
    # transfer guard). Trace-only — no compile — so it is cheap even
    # over the tunnel; production geometry on TPU, reduced on CPU, same
    # split as the decoder-tail gates above. A failing audit records a
    # program_audit cause through the same gate_refused contract, so the
    # refusal travels with the probes like every kernel gate's.
    try:
        from tmr_tpu.analysis import Baseline, default_baseline_path
        from tmr_tpu.analysis.program_audit import (
            audit_production_programs,
        )

        audit = audit_production_programs(
            # committed baseline: the per-platform transfer_guard pin
            # overrides must apply here exactly as in analyze.py
            baseline=Baseline.load(default_baseline_path()),
            image_size=1024 if jax.default_backend() == "tpu" else 64,
            attention_grids=((64, 64), (96, 96)),
            record_refusals=True,
        )
        emit(probe="program_audit", ok=bool(audit["ok"]),
             problems=audit["problems"],
             gate_state=audit["states"][0]["gate_state"],
             refusals=drain_gate_refusals())
    except Exception as e:
        traceback.print_exc()
        emit(probe="program_audit", ok=False,
             error=f"{type(e).__name__}: {e}",
             refusals=drain_gate_refusals())

    doc = {
        "schema": GATE_PROBE_SCHEMA,
        "backend": backend,
        "probes": probes,
        "refusals": [
            r for p in probes for r in p.get("refusals", ())
        ],
    }
    if args.as_doc:
        print(json.dumps(doc), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
