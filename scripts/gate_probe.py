"""Diagnose WHY each gated custom kernel is refused on the live backend.

Round-5 session-7 finding: on the real TPU every require_tpu formulation
(flash global/windowed, pallas global/windowed, pallas xcorr) fell back,
while the one pure-XLA alternative (blockfolded) won the headline — but
the gates swallow their refusal reason, so "Mosaic can't lower through
this backend" vs "kernel miscompiles numerically" vs "backend-name
mismatch" were indistinguishable. This script runs each gate at the
production geometry with TMR_GATE_DEBUG=1 and, for the pallas paths, also
calls the kernel DIRECTLY (no gate) so a lowering exception surfaces with
its full traceback.

Single tunnel client; run only when no other bench/battery stage is live.
Output: one JSON line per probe on stdout; tracebacks/debug on stderr.
"""

import json
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["TMR_GATE_DEBUG"] = "1"

import jax
import jax.numpy as jnp
import numpy as np


def emit(**kw):
    print(json.dumps(kw), flush=True)


def main():
    emit(
        probe="backend",
        default_backend=jax.default_backend(),
        devices=[str(d) for d in jax.devices()],
        device_kind=jax.devices()[0].device_kind,
        platform=jax.devices()[0].platform,
        jax_version=jax.__version__,
    )

    # 1. trivial pallas kernel, compiled mode — does Mosaic lower AT ALL?
    try:
        from jax.experimental import pallas as pl

        def add1(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1.0

        x = jnp.zeros((256, 256), jnp.float32)
        y = pl.pallas_call(
            add1, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)
        )(x)
        ok = bool(np.asarray(y)[0, 0] == 1.0)
        emit(probe="pallas_trivial", ok=ok)
    except Exception as e:
        traceback.print_exc()
        emit(probe="pallas_trivial", ok=False,
             error=f"{type(e).__name__}: {e}")

    # 2. the global-attention pallas kernel DIRECT (no gate), bench
    # geometry: grid 64x64, head_dim 64, B1 H2 (the gate's own shape)
    try:
        from tmr_tpu.ops.pallas_attn import pallas_decomposed_attention

        rng = np.random.default_rng(0)
        gh = gw = 64
        D = 64
        S = gh * gw
        q = jnp.asarray(rng.standard_normal((1, 2, S, D)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((1, 2, S, D)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((1, 2, S, D)), jnp.bfloat16)
        rh = jnp.asarray(rng.standard_normal((gh, gh, D)) * 0.2, jnp.float32)
        rw = jnp.asarray(rng.standard_normal((gw, gw, D)) * 0.2, jnp.float32)
        got = jax.jit(
            lambda *a: pallas_decomposed_attention(*a, (gh, gw), D**-0.5)
        )(q, k, v, rh, rw)
        got.block_until_ready()

        from tmr_tpu.models.vit import blockwise_decomposed_attention

        want = jax.jit(
            lambda *a: blockwise_decomposed_attention(*a, (gh, gw), D**-0.5)
        )(q, k, v, rh, rw)
        err = float(
            np.abs(
                np.asarray(got, np.float32) - np.asarray(want, np.float32)
            ).max()
        )
        ref = float(np.abs(np.asarray(want, np.float32)).max())
        emit(probe="pallas_global_direct", ok=bool(err / (ref + 1e-6) < 0.05),
             rel_err=err / (ref + 1e-6))
    except Exception as e:
        traceback.print_exc()
        emit(probe="pallas_global_direct", ok=False,
             error=f"{type(e).__name__}: {e}")

    # 3. every production gate, debug on (reasons land on stderr)
    from tmr_tpu.ops.flash_attn import (
        blockfolded_ok, flash_attention_ok, flash_window_ok,
    )
    from tmr_tpu.ops.pallas_attn import (
        effective_global_tiles, pallas_global_ok, pallas_window_ok,
    )
    from tmr_tpu.ops.pallas_xcorr import pallas_xcorr_ok

    bq, bk = effective_global_tiles(64 * 64)
    from tmr_tpu.ops.flash_attn import densefolded_ok
    from tmr_tpu.models.vit import _scores_dtype

    live_scores = _scores_dtype()
    gates = {
        "flash_global_64x64_d64": lambda: flash_attention_ok(64, 64, 64),
        f"blockfolded_64x64_d64_scores_{live_scores}":
            lambda: blockfolded_ok(64, 64, 64, live_scores),
        f"densefolded_64x64_d64_scores_{live_scores}":
            lambda: densefolded_ok(64, 64, 64, live_scores),
        "flash_window_14x14_d64": lambda: flash_window_ok(14, 14, 64),
        "pallas_global_64x64_d64":
            lambda: pallas_global_ok(64, 64, 64, bq, bk),
        "pallas_window_14x14_d64_g8":
            lambda: pallas_window_ok(14, 14, 64, 8),
        "pallas_xcorr_c256_64_t17": lambda: pallas_xcorr_ok(256, 64, 64, 17),
    }
    for name, fn in gates.items():
        try:
            emit(probe=name, ok=bool(fn()))
        except Exception as e:
            traceback.print_exc()
            emit(probe=name, ok=False, error=f"{type(e).__name__}: {e}")

    # the bf16-score-tile gates (the env the check traces under must match
    # the cache key being probed — set it for the duration)
    if live_scores != "bf16":
        os.environ["TMR_GLOBAL_SCORES_DTYPE"] = "bf16"
        try:
            for name, fn in {
                "blockfolded_64x64_d64_scores_bf16":
                    lambda: blockfolded_ok(64, 64, 64, "bf16"),
                "densefolded_64x64_d64_scores_bf16":
                    lambda: densefolded_ok(64, 64, 64, "bf16"),
            }.items():
                try:
                    emit(probe=name, ok=bool(fn()))
                except Exception as e:
                    traceback.print_exc()
                    emit(probe=name, ok=False,
                         error=f"{type(e).__name__}: {e}")
        finally:
            os.environ.pop("TMR_GLOBAL_SCORES_DTYPE", None)


if __name__ == "__main__":
    main()
