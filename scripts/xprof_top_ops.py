"""Top-N op table from a jax.profiler trace (xplane.pb) — no TensorBoard.

The round-3 verdict asked for a committed "xprof top-10 op table" next to
the bench numbers. TensorBoard's own converter is unusable in this image
(tensorboard_plugin_profile's pywrap entry point is missing from the TF
build), so this parses the XSpace proto directly: every device-plane line's
events are aggregated by op name into total/self-agnostic wall duration.

Usage:
    python scripts/xprof_top_ops.py <trace_dir> [N]

Prints ONE JSON line:
    {"device_plane": ..., "total_ms": ..., "top_ops": [
        {"name": ..., "count": ..., "total_ms": ..., "pct": ...}, ...]}

Notes on semantics: durations are aggregated per metadata name over ONE
line of the chosen plane. Device planes carry both an "XLA Modules" line
(one event spanning each whole program execution) and an "XLA Ops" line
(per-op events); the module span always covers the ops plus gaps, so
neither a plane-wide sum nor a busiest-line max yields an op ranking — a
line literally named "XLA Ops" is preferred, module-named lines are
excluded from the busiest-line fallback. Percentages are of the chosen
line's summed event time, not wall clock. Good enough to rank where the
program's device time goes — the use this table serves.
"""

from __future__ import annotations

import glob
import json
import os
import sys
from collections import defaultdict

# the generated proto needs the pure-python runtime in this image (the
# upb/C++ descriptor pool rejects its older codegen)
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def load_xspaces(trace_dir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    )
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir}")
    spaces = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append(xs)
    return spaces


def top_ops(trace_dir: str, n: int = 10) -> dict:
    """Aggregate device-plane event durations by op name; rank by total."""
    spaces = load_xspaces(trace_dir)
    # prefer accelerator planes ("/device:TPU:0"); XLA:CPU runs put their op
    # events under host-thread planes ("/host:CPU"), so when no device plane
    # has events, fall back to the busiest event-bearing plane
    have_device_events = any(
        plane.name.startswith("/device:")
        and any(line.events for line in plane.lines)
        for xs in spaces
        for plane in xs.planes
    )
    # wrapper/frame events that are nesting spans, not ops — excluded in
    # the host-plane FALLBACK so the XLA client thread (real op events)
    # outranks the python main thread (PjitFunction/Execute spans cover the
    # ops plus dispatch and would win any duration ranking). Device planes
    # carry none of these.
    _WRAPPERS = ("$", "PjitFunction", "PjRtCpu", "XlaComputation")

    def _is_wrapper(name: str) -> bool:
        return name.startswith(_WRAPPERS)

    best_plane = None
    best_events = None
    best_total = -1.0
    best_is_ops_line = False
    for xs in spaces:
        for plane in xs.planes:
            if have_device_events and not plane.name.startswith("/device:"):
                continue
            meta = {k: v.name for k, v in plane.event_metadata.items()}
            for line in plane.lines:
                is_ops = line.name == "XLA Ops"
                if "module" in line.name.lower():
                    continue  # whole-program spans, not ops
                agg = defaultdict(lambda: [0, 0.0])  # name -> [count, ps]
                for ev in line.events:
                    name = meta.get(ev.metadata_id, str(ev.metadata_id))
                    if not have_device_events and _is_wrapper(name):
                        continue
                    a = agg[name]
                    a[0] += 1
                    a[1] += ev.duration_ps
                total = sum(v[1] for v in agg.values())
                better = (
                    (is_ops and not best_is_ops_line)
                    or (is_ops == best_is_ops_line and total > best_total)
                )
                if better and total > 0:
                    best_total = total
                    best_plane = f"{plane.name} [{line.name}]"
                    best_events = agg
                    best_is_ops_line = is_ops
    if best_events is None or best_total <= 0:
        raise ValueError("no event-bearing plane in trace")
    ranked = sorted(best_events.items(), key=lambda kv: -kv[1][1])[:n]
    total_ms = best_total / 1e9
    return {
        "device_plane": best_plane,
        "total_ms": round(total_ms, 3),
        "top_ops": [
            {
                "name": name[:160],
                "count": cnt,
                "total_ms": round(ps / 1e9, 3),
                "pct": round(100.0 * ps / best_total, 2) if best_total else 0,
            }
            for name, (cnt, ps) in ranked
        ],
    }


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(json.dumps({"error": "usage: xprof_top_ops.py <trace_dir> [N]"}))
        return 2
    try:
        n = int(argv[1]) if len(argv) > 1 else 10
        print(json.dumps(top_ops(argv[0], n)))
    except Exception as e:
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
