"""Probe the trained-ckpt bench anomaly (BENCH_CKPT_LIVE.json: 3628 ms vs
394 ms for an identical program).

Times the production fused program (bench shapes) under three param trees:

  init       Predictor.init_params output (the 10.1 img/s headline's args)
  restored   orbax restore with target=init params — these arrays carry
             explicit shardings (the CPU HLO diff shows per-arg
             sdy.sharding annotations, the only trace difference) and are
             the prime suspect for the 9x
  roundtrip  the restored values pulled to host and re-device_put as
             ordinary uncommitted arrays (identical numerics, no committed
             sharding)

If restored is slow and roundtrip is fast, the committed shardings
pessimized XLA's layout/compile and the fix is a host roundtrip (or
device_put-through-identity) in bench.py's restore branch. If both are
slow, the slowdown is value-dependent after all.

Prints one JSON line {variant: ms_per_batch}.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()

BATCH = int(os.environ.get("TMR_BENCH_BATCH", 4))
SIZE = int(os.environ.get("TMR_BENCH_SIZE", 1024))
CKPT = os.environ.get("TMR_BENCH_CKPT", "bench_ckpt/params")


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import orbax.checkpoint as ocp

    from tmr_tpu.config import preset
    from tmr_tpu.inference import Predictor
    from tmr_tpu.utils.cache import enable_compilation_cache
    from tmr_tpu.utils.profiling import (
        chained_seconds_per_iter,
        measure_rtt_floor,
    )

    enable_compilation_cache()
    cfg = preset(
        "TMR_FSCD147", backbone="sam_vit_b", image_size=SIZE,
        compute_dtype="bfloat16", batch_size=BATCH,
    )
    pred = Predictor(cfg)
    pred.init_params(seed=0, image_size=SIZE)
    rng = np.random.default_rng(0)
    image = jnp.asarray(
        rng.standard_normal((BATCH, SIZE, SIZE, 3)), jnp.float32
    )
    ex = jnp.tile(
        jnp.asarray([[[0.45, 0.45, 0.53, 0.55]]], jnp.float32), (BATCH, 1, 1)
    )
    fused = pred._get_fn(17, chain_feedback=True)
    rtt = measure_rtt_floor()

    restored = ocp.StandardCheckpointer().restore(
        os.path.abspath(CKPT), target=pred.params
    )
    roundtrip = jax.device_put(jax.device_get(restored))

    out = {"rtt_floor_ms": round(rtt * 1000, 1)}
    for label, params in (
        ("init", pred.params),
        ("restored", restored),
        ("roundtrip", roundtrip),
    ):
        sec = chained_seconds_per_iter(
            lambda im, fb, p=params: fused(p, None, im, ex, fb),
            image, rtt=rtt, iters=5,
        )
        out[label] = round(sec * 1000, 1)
        print(f"[ckpt_probe] {label}: {out[label]} ms/batch",
              file=sys.stderr, flush=True)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
