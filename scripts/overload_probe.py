"""Overload-robustness probe for the serving layer (tmr_tpu/serve).

The chaos_probe pattern applied to traffic instead of faults: drive
ServeEngine far past its measured capacity and prove the admission /
priority / deadline / degradation machinery holds the line. Prints ONE
``overload_report/v1`` JSON document (schema + validator in
tmr_tpu/diagnostics.py):

- **capacity** — closed-loop throughput of a plain engine on unique
  images: the denominator every overload factor is measured against.
- **overload** — a fresh engine with bounded admission
  (``max_pending = 3 x batch``) offered >= 5x capacity, open-loop.
  Checks: admitted-traffic p99 bounded by
  ``max_wait + (1 + max_pending/batch) x batch_time + slack`` (the
  whole point of bounding admission: the backlog an admitted request
  can wait behind is capped), rejections carry structured causes, and
  the probe-side future tally reconciles EXACTLY with the engine's
  counters: ``offered == rejected + completed + shed + errors``.
- **shed burst** — requests submitted with a 1 ms deadline against a
  60 ms batching window: every one must shed BEFORE staging (zero
  batches formed, zero device work — the deadline contract).
- **degrade** — a forced-level ladder records its steps on every
  result (``degrade_steps``: truncate_k / downscale here), and the
  auto controller escalates on injected queue-saturation anomalies and
  steps back down after its cooldown — deterministically, no timing.
- **close mid-overload** — close() with a backlog still queued returns
  within its drain bound and leaves every future terminal: no wedge.

Usage:  python scripts/overload_probe.py [--tiny] [--out FILE]
        [--batch N] [--requests N] [--factor F]

``--tiny`` (or TMR_BENCH_TINY=1) shrinks geometry/counts for the CPU
smoke that rides tier-1 (tests/test_overload_probe.py); real numbers
use the deployment geometry. One-JSON-line contract via bench_guard.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()


def _progress(msg: str) -> None:
    print(f"[overload_probe] {msg}", file=sys.stderr, flush=True)


def _percentiles(lat_s) -> dict:
    if not lat_s:
        return {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    arr = np.asarray(lat_s) * 1000.0
    return {
        "p50": round(float(np.percentile(arr, 50)), 2),
        "p95": round(float(np.percentile(arr, 95)), 2),
        "p99": round(float(np.percentile(arr, 99)), 2),
    }


def _images(n: int, size: int, seed: int):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((size, size, 3)).astype(np.float32)
            for _ in range(n)]


SMALL_EX = np.asarray([[0.45, 0.45, 0.53, 0.55]], np.float32)


def _run(cancel_watchdog, argv=None) -> int:
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke geometry (also TMR_BENCH_TINY=1)")
    ap.add_argument("--out", default=None)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--requests", type=int, default=None,
                    help="overload-phase offered request count")
    ap.add_argument("--factor", type=float, default=5.0,
                    help="offered load as a multiple of measured capacity")
    args = ap.parse_args(argv)

    tiny = args.tiny or os.environ.get("TMR_BENCH_TINY", "") not in (
        "", "0", "false"
    )
    size = int(os.environ.get("TMR_BENCH_SIZE", 128 if tiny else 1024))
    dtype = "float32" if tiny else "bfloat16"

    import jax
    import jax.numpy as jnp

    from tmr_tpu.config import preset
    from tmr_tpu.diagnostics import (
        OVERLOAD_REPORT_SCHEMA,
        validate_overload_report,
    )
    from tmr_tpu.inference import Predictor
    from tmr_tpu.serve import (
        AdmissionController,
        DegradeController,
        RejectedError,
        ServeEngine,
    )

    _progress(f"backend: {jax.devices()[0]} size={size} tiny={tiny}")
    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=size,
                 compute_dtype=dtype, batch_size=1)
    pred = Predictor(cfg)
    _progress("init_params (jitted init)")
    pred.init_params(seed=0, image_size=size)
    batch = max(int(args.batch), 2)
    wall0 = time.perf_counter()

    # ---- warmup: compile every program shape the timed phases can
    # produce, OUTSIDE every timed window (a cold compile inside the
    # overload round would charge seconds of XLA work to the p99)
    _progress("warmup compiles (single path B in {1,2,batch}; degraded "
              "half-size single + multi)")
    fn = pred._get_fn(9)
    ex1 = jnp.asarray(SMALL_EX[None])
    for b in sorted({1, 2, batch}):
        fn(pred.params, pred.refiner_params,
           jnp.zeros((b, size, size, 3), jnp.float32),
           jnp.tile(ex1, (b, 1, 1)))
    half = size // 2
    fn(pred.params, pred.refiner_params,
       jnp.zeros((1, half, half, 3), jnp.float32), ex1)
    mfn = pred._get_multi_batched_fn(9, 1)
    mfn(pred.params, pred.refiner_params,
        jnp.zeros((1, half, half, 3), jnp.float32),
        jnp.asarray(SMALL_EX[None]), jnp.ones((1,), jnp.int32))

    report = {
        "schema": OVERLOAD_REPORT_SCHEMA,
        "device": str(jax.devices()[0]),
        "config": {
            "image_size": size,
            "batch": batch,
            "factor": float(args.factor),
        },
    }

    # ---- phase 1: measured capacity (plain engine, unique traffic)
    _progress("phase capacity (closed loop)")
    n_cap = 3 * batch
    eng_cap = ServeEngine(pred, batch=batch, max_wait_ms=10,
                          feature_cache=0)
    imgs = _images(n_cap, size, seed=1)
    t0 = time.perf_counter()
    futs = [eng_cap.submit(im, SMALL_EX) for im in imgs]
    for f in futs:
        f.result(timeout=600)
    capacity = n_cap / (time.perf_counter() - t0)
    eng_cap.close()
    report["capacity"] = {"img_per_sec": round(capacity, 3),
                          "requests": n_cap}
    report["config"]["max_wait_ms"] = eng_cap.max_wait_ms
    _progress(f"capacity: {capacity:.3f} img/s")

    # ---- phase 2: >= 5x offered load against bounded admission
    max_pending = 3 * batch
    offered_rate = args.factor * capacity
    n_offer = args.requests or 12 * batch
    _progress(f"phase overload: {n_offer} requests at "
              f"{offered_rate:.2f} img/s (max_pending={max_pending})")
    eng = ServeEngine(
        pred, batch=batch, max_wait_ms=10, feature_cache=0,
        admission=AdmissionController(enabled=True,
                                      max_pending=max_pending),
    )
    report["config"]["max_pending"] = max_pending
    lat: list = []
    outcomes = {"completed": 0, "rejected": 0, "shed": 0, "errors": 0}
    causes: dict = {}
    period = 1.0 / offered_rate
    futs = []
    t0 = time.perf_counter()
    for i, im in enumerate(_images(n_offer, size, seed=2)):
        target = t0 + i * period
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        ts = time.perf_counter()
        f = eng.submit(im, SMALL_EX)
        f.add_done_callback(
            lambda _f, _ts=ts: lat.append(time.perf_counter() - _ts)
            if _f.exception() is None else None
        )
        futs.append(f)
    for f in futs:
        exc = None
        try:
            f.result(timeout=600)
        except Exception as e:  # noqa: BLE001 — tallied below
            exc = e
        if exc is None:
            outcomes["completed"] += 1
        elif isinstance(exc, RejectedError):
            causes[exc.cause] = causes.get(exc.cause, 0) + 1
            if exc.cause in ("deadline", "shutdown"):
                outcomes["shed"] += 1
            else:
                outcomes["rejected"] += 1
        else:
            outcomes["errors"] += 1
    counters = eng.counters
    over_counters = eng.overload_counters()
    retry_hints = [c for c in causes]  # causes observed
    batch_ms = batch / capacity * 1000.0
    slack_ms = 500.0 if jax.default_backend() == "cpu" else 50.0
    # admitted backlog is BOUNDED: a request admitted at the cap waits
    # behind at most max_pending predecessors plus its own batch window
    p99_bound_ms = (eng.max_wait_ms
                    + (1 + max_pending / batch) * batch_ms + slack_ms)
    pct = _percentiles(lat)
    report["overload"] = {
        "offered": n_offer,
        "offered_img_per_sec": round(offered_rate, 3),
        "latency_ms": pct,
        "reject_causes": causes,
        "degraded": over_counters["degraded"],
        **{k: outcomes[k] for k in
           ("completed", "rejected", "shed", "errors")},
    }
    accounting_exact = (
        sum(outcomes.values()) == n_offer
        and outcomes["rejected"] == over_counters["admit_rejected"]
        and outcomes["completed"] == counters["completed"]
        and outcomes["shed"] == over_counters["shed"]
        and counters["submitted"] ==
        n_offer - over_counters["admit_rejected"]
    )
    _progress(f"overload: {outcomes} p99={pct['p99']}ms "
              f"(bound {p99_bound_ms:.0f}ms) exact={accounting_exact}")

    # ---- phase 3: deterministic deadline shed — expired before staging
    _progress("phase shed burst (1 ms deadline vs 60 ms window)")
    eng_shed = ServeEngine(pred, batch=batch, max_wait_ms=60,
                           feature_cache=0)
    # batch-1 requests: the bucket never fills, so release waits the
    # full 60 ms window — by which point every 1 ms deadline is long
    # expired and the stage loop must shed the lot before any staging
    shed_futs = [
        eng_shed.submit(im, SMALL_EX, deadline_ms=1.0)
        for im in _images(batch - 1, size, seed=3)
    ]
    shed_hits = 0
    for f in shed_futs:
        try:
            f.result(timeout=120)
        except RejectedError as e:
            shed_hits += 1 if e.cause == "deadline" else 0
        except Exception:
            pass
    shed_stats = eng_shed.stats()
    eng_shed.close()
    # zero batches formed == zero stagings == zero device_put/execute
    shed_before_device = bool(
        shed_hits == len(shed_futs) and shed_stats["batches"] == 0
        and shed_stats["completed"] == 0
    )
    report["shed_phase"] = {
        "offered": len(shed_futs),
        "shed": shed_hits,
        "batches": shed_stats["batches"],
    }

    # ---- phase 4: degrade ladder — forced steps recorded exactly, and
    # the auto controller's escalation/cooldown trajectory
    _progress("phase degrade (forced level 3 + auto trajectory)")
    eng_deg = ServeEngine(
        pred, batch=1, max_wait_ms=5, feature_cache=0,
        degrade=DegradeController(mode="3", min_size=half),
    )
    img = _images(1, size, seed=4)[0]
    r_single = eng_deg.submit(img, SMALL_EX).result(timeout=600)
    multi_ex = np.asarray(
        [[0.45, 0.45, 0.53, 0.55], [0.2, 0.2, 0.28, 0.3],
         [0.6, 0.55, 0.68, 0.66]], np.float32,
    )
    r_multi = eng_deg.submit(img, multi_ex, multi=True).result(timeout=600)
    deg_counters = eng_deg.overload_counters()
    eng_deg.close()
    steps_single = tuple(r_single.get("degrade_steps", ()))
    steps_multi = tuple(r_multi.get("degrade_steps", ()))
    degrade_steps_recorded = bool(
        steps_single == ("downscale",)
        and steps_multi == ("downscale", "truncate_k")
        and r_single["boxes"].shape[0] == 1
        and deg_counters["degraded"] == 2
    )
    auto = DegradeController(mode="auto", cooldown=2, max_level=3)
    storm = [{"anomaly": "queue_saturation", "message": "x",
              "evidence": {}}]
    trajectory = [auto.observe(storm), auto.observe(storm),
                  auto.observe([]), auto.observe([]),
                  auto.observe([]), auto.observe([])]
    degrade_auto_ladder = trajectory == [1, 2, 2, 1, 1, 0]
    report["degrade"] = {
        "forced_level": 3,
        "steps_seen": sorted(set(steps_single) | set(steps_multi)),
        "counters": deg_counters,
        "auto_trajectory": trajectory,
    }

    # ---- phase 5: close() mid-overload — bounded, no wedge
    _progress("phase close mid-overload")
    burst = [eng.submit(im, SMALL_EX)
             for im in _images(6 * batch, size, seed=5)]
    close_timeout = 120.0
    t0 = time.perf_counter()
    eng.close(timeout=close_timeout)
    close_wall = time.perf_counter() - t0
    all_terminal = all(f.done() for f in burst)
    leftover = eng.overload_counters().get("shed.shutdown", 0)
    report["close"] = {
        "wall_s": round(close_wall, 3),
        "timeout_s": close_timeout,
        "leftover_rejected": int(leftover),
        "all_terminal": bool(all_terminal),
    }
    _progress(f"close: {close_wall:.2f}s, all_terminal={all_terminal}, "
              f"leftover={leftover}")

    report["checks"] = {
        "p99_ms": pct["p99"],
        "p99_bound_ms": round(p99_bound_ms, 2),
        "p99_bounded": bool(outcomes["completed"] > 0
                            and pct["p99"] <= p99_bound_ms),
        "accounting_exact": bool(accounting_exact),
        "rejected_nonzero": bool(outcomes["rejected"] > 0),
        "reject_causes_structured": bool(
            retry_hints and all(c in ("queue_full", "class_limit",
                                      "rate_limited", "deadline",
                                      "shutdown") for c in retry_hints)
        ),
        "shed_before_device": shed_before_device,
        "degrade_steps_recorded": degrade_steps_recorded,
        "degrade_auto_ladder": bool(degrade_auto_ladder),
        "close_bounded": bool(close_wall <= close_timeout
                              and all_terminal),
    }
    report["counters"] = {**counters, **over_counters}
    report["wall_s"] = round(time.perf_counter() - wall0, 1)
    problems = validate_overload_report(report)
    if problems:  # self-check: the emitted document must validate
        report["validator_problems"] = problems

    cancel_watchdog()  # before the success print: no success-then-watchdog
    line = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    return 0


def main(argv=None) -> int:
    """One overload_report/v1 JSON line on stdout, success or not: the
    shared bench_guard funnels wedges and crashes into a contractual
    error record."""
    from tmr_tpu.diagnostics import OVERLOAD_REPORT_SCHEMA
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(
            json.dumps({"schema": OVERLOAD_REPORT_SCHEMA, "error": msg}),
            flush=True,
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
