"""Chaos gauntlet for the fault-tolerant map phase (CI tier-1).

Runs the synthetic-shard extraction under canned deterministic fault
schedules (tmr_tpu/utils/faults.py) covering every injection point — I/O
error, hung shard, corrupt member, NaN encoder output, failed save/journal
commits, crash + resume — and exits nonzero unless:

- every injected fault was observed (faults.fired()) and is accounted for
  in the map_report/v1 document;
- transient faults are retried to success: the reducer table and the
  per-image feature files come out byte-identical to the fault-free run;
- permanent faults quarantine with a recorded cause (or, for data damage,
  show up exactly in the skipped/non-finite counters), and the table
  equals the journal-predicted contribution of the unaffected shards;
- a crash mid-run + `--resume` yields a byte-identical table, re-encoding
  only unjournaled shards, with no partial `.npy` anywhere.

`--elastic` runs the ELASTIC gauntlet instead (coordinator/worker lease
execution, tmr_tpu/parallel/elastic.py): 3 worker processes over 8
shards with one worker kill -9'd mid-shard and another SIGSTOPped past
the heartbeat window (then SIGCONTed so its fenced commit is actually
attempted and rejected), plus an in-process lease/heartbeat
fault-injection round — and exits nonzero unless the run completes, the
final stats table is byte-identical to the single-process run, the
validated elastic_report/v1 reconciles exactly (every reassignment
carries a closed-vocab cause; >= 1 fenced-commit rejection in the
SIGSTOP scenario), and the feature tree matches byte-for-byte.

Fast (seconds, tiny tensors, CPU): rides tier-1 via
tests/test_chaos_probe.py.
"""

import argparse
import glob
import hashlib
import io
import os
import shutil
import sys
import tarfile
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")
scrub_cpu_tunnel_env()

import numpy as np  # noqa: E402

SIZE = 16  # decode size — tiny keeps the whole gauntlet in seconds
SHARDS = (  # (name, n_images) — index order is the fault 'shard=' key
    ("Easy_0.tar", 4),
    ("Easy_1.tar", 3),
    ("Normal_0.tar", 4),
    ("Normal_1.tar", 2),
    ("Hard_0.tar", 3),
    ("misc.tar", 2),
)


def _make_tar(dirpath, name, n_images, seed):
    from PIL import Image

    rng = np.random.default_rng(seed)
    path = os.path.join(dirpath, name)
    with tarfile.open(path, "w") as tar:
        for i in range(n_images):
            img = Image.fromarray(
                rng.integers(0, 255, (24, 24, 3), dtype=np.uint8)
            )
            buf = io.BytesIO()
            img.save(buf, format="PNG")
            data = buf.getvalue()
            info = tarfile.TarInfo(f"img_{i}.png")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    return path


def _encode_fn():
    import jax

    from tmr_tpu.parallel.mapreduce import feature_stats

    @jax.jit
    def encode(images):
        feats = images[:, ::4, ::4, :] - 0.5  # stand-in encoder features
        return feats, feature_stats(feats)

    return encode


def _manifest(features_dir):
    """{relpath: sha256} over every .npy under features_dir."""
    out = {}
    for path in sorted(
        glob.glob(os.path.join(features_dir, "**", "*.npy"), recursive=True)
    ):
        with open(path, "rb") as f:
            out[os.path.relpath(path, features_dir)] = hashlib.sha256(
                f.read()
            ).hexdigest()
    return out


def _tmp_leftovers(root):
    return glob.glob(os.path.join(root, "**", "*.tmp.*"), recursive=True)


def _run(paths, encode, out_dir, *, resume=False, retry=None, expect_crash=False):
    from tmr_tpu.parallel.journal import ShardJournal
    from tmr_tpu.parallel.mapreduce import (
        CATEGORIES,
        MapReport,
        RetryPolicy,
        atomic_save_npy,
        category_of,
        reducer_table,
        run_stream,
    )

    features = os.path.join(out_dir, "features")

    def save(shard, name, feat):
        d = os.path.join(features, CATEGORIES[category_of(shard)],
                         shard.replace(".tar", ""))
        os.makedirs(d, exist_ok=True)
        atomic_save_npy(
            os.path.join(d, os.path.splitext(name)[0] + ".npy"), feat
        )

    journal = ShardJournal(os.path.join(out_dir, "features", "_journal"))
    report = MapReport()
    retry = retry or RetryPolicy(
        max_attempts=3, shard_timeout=2.0, backoff_base=0.01,
        backoff_jitter=0.0,
    )
    crashed = False
    acc = None
    try:
        acc = run_stream(
            paths, encode, batch_size=2, image_size=SIZE,
            save_features=save, feeder_threads=2, retry=retry,
            journal=journal, resume=resume, report=report,
        )
    except KeyboardInterrupt:
        crashed = True
        if not expect_crash:
            raise
    table = reducer_table(acc.table) if acc is not None else None
    return {
        "table": table,
        "manifest": _manifest(features),
        "report": report.document() if not crashed else None,
        "journal": journal,
        "crashed": crashed,
        "features_dir": features,
    }


# ------------------------------------------------------- elastic gauntlet
ELASTIC_SHARDS = (  # 8 shards — index order is the fault 'shard=' key.
    # Every shard has >=3 images so at batch 2 each worker spends >=2
    # stub-delayed batches per shard — kills/stops land mid-shard.
    ("Easy_0.tar", 4), ("Easy_1.tar", 3), ("Easy_2.tar", 3),
    ("Normal_0.tar", 4), ("Normal_1.tar", 3), ("Normal_2.tar", 3),
    ("Hard_0.tar", 3), ("Hard_1.tar", 3),
)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _poll(predicate, timeout_s, interval_s=0.02):
    """Poll until predicate() is truthy; returns its value (falsy on
    timeout)."""
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval_s)
    return predicate()


def _spawn_stub_worker(wid, address, extra=()):
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TMR_FAULTS", None)  # process gauntlet runs fault-free
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "elastic_map.py"),
         "worker", "--coordinator", f"{address[0]}:{address[1]}",
         "--worker_id", wid, "--encoder", "stub",
         "--shard_delay_s", "0.45", "--max_attempts", "2",
         "--max_idle_s", "30", *extra],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _held_leases(coord):
    """{worker_id: (index, epoch, hb)} for every currently held lease."""
    state = coord.state()
    out = {}
    for index, leases in state["leases"].items():
        for lease in leases:
            out[lease["worker"]] = (int(index), lease["epoch"],
                                    lease["hb"])
    return out


def _elastic_main(args) -> int:
    """The elastic chaos gauntlet (see module docstring)."""
    import signal
    import threading
    import time

    from tmr_tpu.diagnostics import (
        ELASTIC_REASSIGN_CAUSES,
        validate_elastic_report,
    )
    from tmr_tpu.parallel.elastic import (
        ElasticCoordinator,
        ElasticPolicy,
        run_worker,
        stub_encode_stats_fn,
    )
    from tmr_tpu.parallel.mapreduce import (
        RetryPolicy,
        reducer_table,
        run_stream,
    )
    from tmr_tpu.utils import faults

    work = args.work_dir or tempfile.mkdtemp(prefix="chaos_elastic_")
    os.makedirs(work, exist_ok=True)
    problems = []

    def check(ok, msg):
        print(f"[{'ok' if ok else 'FAIL'}] {msg}", file=sys.stderr)
        if not ok:
            problems.append(msg)

    data = os.path.join(work, "shards")
    os.makedirs(data, exist_ok=True)
    paths = [
        _make_tar(data, name, n, seed=i)
        for i, (name, n) in enumerate(ELASTIC_SHARDS)
    ]

    # ------------------------------------------- baseline: single process
    faults.clear()
    base_feats = os.path.join(work, "base_features")

    def _save_into(features_dir):
        from tmr_tpu.parallel.elastic import make_feature_sinks

        return make_feature_sinks(features_dir)

    save, cleanup, sync = _save_into(base_feats)
    base_acc = run_stream(
        paths, stub_encode_stats_fn(), batch_size=2, image_size=SIZE,
        save_features=save, cleanup_features=cleanup, sync_features=sync,
    )
    base_table = reducer_table(base_acc.table)
    base_manifest = _manifest(base_feats)
    check(base_manifest, "elastic baseline: single-process run completed")

    # ---------------- process gauntlet: 3 workers, kill -9 + SIGSTOP/CONT
    feats = os.path.join(work, "features")
    policy = ElasticPolicy(
        lease_ttl_s=1.0, hb_interval_s=0.2, check_interval_s=0.05,
        straggler_factor=0.0,
    )
    coord = ElasticCoordinator(
        paths, os.path.join(feats, "_journal"), features_out=feats,
        image_size=SIZE, batch_size=2, policy=policy,
    )
    address = coord.start()
    workers = {
        f"w{i}": _spawn_stub_worker(f"w{i}", address) for i in range(3)
    }

    # victims: two distinct workers holding FRESH leases (few heartbeats
    # in), so the signals land mid-shard rather than racing the commit
    held = _poll(
        lambda: (lambda h: h if len(
            [w for w, (_, _, hb) in h.items() if hb <= 2]
        ) >= 2 else None)(_held_leases(coord)),
        timeout_s=60.0,
    )
    check(bool(held), "elastic: >=2 workers leased shards concurrently")
    victims = sorted(
        w for w, (_, _, hb) in (held or {}).items() if hb <= 2
    )[:2]
    kill_wid = victims[0] if victims else None
    stop_wid = victims[1] if len(victims) > 1 else None
    kill_shard = held[kill_wid][0] if kill_wid else None
    stop_shard = held[stop_wid][0] if stop_wid else None
    if kill_wid:
        os.kill(workers[kill_wid].pid, signal.SIGKILL)  # mid-shard
    if stop_wid:
        os.kill(workers[stop_wid].pid, signal.SIGSTOP)  # past hb window

    def _cause_for(index, cause):
        return lambda: any(
            r["index"] == index and r["cause"] == cause
            for r in coord.state()["reassignments"]
        )

    check(
        bool(_poll(_cause_for(kill_shard, "worker_exit"), 20.0)),
        "elastic: kill -9 worker reassigned with cause worker_exit",
    )
    check(
        bool(_poll(_cause_for(stop_shard, "stale_heartbeat"), 20.0)),
        "elastic: SIGSTOPped worker's lease revoked as stale_heartbeat",
    )
    if stop_wid:
        os.kill(workers[stop_wid].pid, signal.SIGCONT)
    check(
        bool(_poll(
            lambda: coord.state()["fenced_rejections"], 30.0
        )),
        "elastic: resumed (paused) worker's commit attempt was fenced",
    )
    check(coord.wait(timeout=90.0), "elastic: run settled")
    for wid, proc in workers.items():
        if wid == kill_wid:
            proc.wait(timeout=10)
            continue
        try:
            proc.wait(timeout=30)
        except Exception:
            proc.kill()
            check(False, f"elastic: worker {wid} had to be killed")
    doc = coord.report()
    table = reducer_table(coord.table())
    coord.stop()

    check(validate_elastic_report(doc) == [],
          "elastic: elastic_report/v1 valid (totals reconcile exactly)")
    check(table == base_table,
          "elastic: stats table byte-identical to single-process run")
    manifest = _manifest(feats)
    check(manifest == base_manifest,
          "elastic: feature files byte-identical to single-process run")
    # the fenced loser must not have unlinked the winner's done-marker:
    # a coordinator crash right now must be resumable from the journal
    from tmr_tpu.parallel.journal import ShardJournal

    journal = ShardJournal(os.path.join(feats, "_journal"))
    missing = [
        r["shard"] for r in doc["shards"]
        if r["status"] == "committed"
        and journal.done(r["shard"]) is None
    ]
    check(not missing,
          f"elastic: every committed shard keeps a valid journal "
          f"marker for crash-resume (missing: {missing})")
    totals = doc["totals"]
    check(
        totals["committed"] + totals["resumed"] + totals["quarantined"]
        == totals["shards"] == len(ELASTIC_SHARDS)
        and totals["quarantined"] == 0,
        "elastic: every shard settled exactly once (committed)",
    )
    check(
        doc["reassignments"] and all(
            r["cause"] in ELASTIC_REASSIGN_CAUSES
            for r in doc["reassignments"]
        ),
        "elastic: every reassignment carries a closed-vocab cause",
    )
    check(totals["fenced_rejections"] >= 1,
          "elastic: >=1 fenced-commit rejection in the SIGSTOP scenario")
    killed_shard_rec = doc["shards"][kill_shard] if kill_shard is not None \
        else None
    check(
        killed_shard_rec is not None
        and killed_shard_rec["status"] == "committed"
        and killed_shard_rec["worker"] != kill_wid,
        "elastic: the killed worker's shard was committed by another "
        "worker",
    )
    # kill -9 can orphan *.tmp.<pid> files mid-atomic-write; they must
    # all belong to the two victim processes, never a healthy writer
    victim_pids = {str(workers[w].pid) for w in victims if w}
    stray = [
        p for p in _tmp_leftovers(feats)
        if p.rsplit(".", 1)[-1] not in victim_pids
    ]
    check(not stray, f"elastic: no orphan .tmp files from healthy "
                     f"workers ({stray})")

    # --------------- in-process round: lease + heartbeat fault injection
    faults.configure(
        # grant of shard 1 fails once (epoch 1), succeeds on re-grant
        "lease:shard=1:attempts=2:raise=OSError;"
        # shard 2's first holder stalls its heartbeats past the TTL
        # (epoch 1 only) — the in-process SIGSTOP stand-in
        "heartbeat:shard=2:attempts=2:latency=1.6"
    )
    feats2 = os.path.join(work, "features_faults")
    coord2 = ElasticCoordinator(
        paths, os.path.join(feats2, "_journal"), features_out=feats2,
        image_size=SIZE, batch_size=2,
        policy=ElasticPolicy(
            lease_ttl_s=0.6, hb_interval_s=0.15, check_interval_s=0.05,
            straggler_factor=0.0,
        ),
    )
    address2 = coord2.start()
    retry = RetryPolicy(max_attempts=2, backoff_base=0.01,
                        backoff_jitter=0.0)
    threads = [
        threading.Thread(
            target=run_worker,
            args=(address2, f"t{i}", stub_encode_stats_fn()),
            kwargs={"retry": retry, "max_idle_s": 20.0},
            daemon=True,
        )
        for i in range(2)
    ]
    for t in threads:
        t.start()
    check(coord2.wait(timeout=60.0), "faults: injected run settled")
    for t in threads:
        t.join(timeout=20)
    doc2 = coord2.report()
    table2 = reducer_table(coord2.table())
    coord2.stop()
    fired = {(f["point"], f["action"]) for f in faults.fired()}
    check(("lease", "raise") in fired, "faults: lease grant fault fired")
    check(("heartbeat", "latency") in fired,
          "faults: heartbeat stall fault fired")
    check(validate_elastic_report(doc2) == [],
          "faults: elastic_report/v1 valid")
    check(table2 == base_table,
          "faults: stats table byte-identical under injected faults")
    check(
        any(r["index"] == 2 and r["cause"] == "stale_heartbeat"
            for r in doc2["reassignments"]),
        "faults: stalled-heartbeat lease revoked and reassigned",
    )
    faults.clear()

    if problems:
        print(f"chaos_probe --elastic: {len(problems)} FAILED check(s):",
              file=sys.stderr)
        for msg in problems:
            print(f"  - {msg}", file=sys.stderr)
        return 1
    print("chaos_probe --elastic: all checks passed", file=sys.stderr)
    if not args.keep and args.work_dir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 0


def main(argv=None) -> int:
    from tmr_tpu.diagnostics import validate_map_report
    from tmr_tpu.utils import faults
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()  # the gauntlet re-encodes shards repeatedly
    from tmr_tpu.parallel.mapreduce import (
        CATEGORIES,
        RetryPolicy,
        reducer_table,
    )

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--work_dir", default=None,
                    help="scratch dir (default: a fresh tempdir, removed "
                         "on success)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    ap.add_argument("--elastic", action="store_true",
                    help="run the elastic coordinator/worker gauntlet "
                         "(kill -9 / SIGSTOP / lease+heartbeat faults) "
                         "instead of the single-process one")
    args = ap.parse_args(argv)
    if args.elastic:
        return _elastic_main(args)

    work = args.work_dir or tempfile.mkdtemp(prefix="chaos_probe_")
    os.makedirs(work, exist_ok=True)
    problems = []

    def check(ok, msg):
        print(f"[{'ok' if ok else 'FAIL'}] {msg}", file=sys.stderr)
        if not ok:
            problems.append(msg)

    data = os.path.join(work, "shards")
    os.makedirs(data, exist_ok=True)
    paths = [
        _make_tar(data, name, n, seed=i)
        for i, (name, n) in enumerate(SHARDS)
    ]
    encode = _encode_fn()

    # ---------------------------------------------------- 0: fault-free
    faults.clear()
    base = _run(paths, encode, os.path.join(work, "baseline"))
    base_entries = base["journal"].load_all()
    check(base["table"] is not None, "baseline: completed")
    check(len(base_entries) == len(SHARDS), "baseline: every shard journaled")

    # --------------------------- 1: transient faults -> retried to success
    faults.configure(
        "tar.open:shard=0:attempts=2:raise=OSError;"   # I/O error x2
        "tar.open:shard=1:attempts=1:latency=1.5;"     # hung shard (timeout)
        "save:shard=2:attempts=1:raise=OSError;"       # save dies mid-shard
        "journal:shard=3:attempts=1:raise=OSError;"    # journal commit fails
        "encode:shard=4:attempts=1:raise=RuntimeError"  # encoder fault
    )
    t = _run(
        paths, encode, os.path.join(work, "transient"),
        retry=RetryPolicy(max_attempts=3, shard_timeout=0.3,
                          backoff_base=0.01, backoff_jitter=0.0),
    )
    fired_points = {f["point"] for f in faults.fired()}
    for point in ("tar.open", "save", "journal", "encode"):
        check(point in fired_points, f"transient: fault at {point} fired")
    doc = t["report"]
    check(validate_map_report(doc) == [], "transient: map_report/v1 valid")
    from tmr_tpu.diagnostics import validate_metrics_report

    # the report document carries the registry snapshot (metrics key,
    # schema-versioned) — counter state rides the same document
    check(
        validate_metrics_report(doc.get("metrics", {})) == []
        and doc["metrics"]["counters"].get("map.retries", 0) >= 5,
        "transient: metrics snapshot attached and counting retries",
    )
    check(t["table"] == base["table"],
          "transient: reducer table identical to fault-free run")
    check(t["manifest"] == base["manifest"],
          "transient: feature files byte-identical to fault-free run")
    check(not _tmp_leftovers(os.path.join(work, "transient")),
          "transient: no partial .tmp files on disk")
    check(all(r["status"] == "ok" for r in doc["shards"]),
          "transient: every faulted shard retried to success")
    causes = {r["shard"]: [c["cause"] for c in r["causes"]]
              for r in doc["shards"]}
    check(causes.get("Easy_1.tar") == ["timeout"],
          "transient: hung shard recorded a timeout cause within budget")
    check(doc["totals"]["retries"] >= 5,
          "transient: every injected failure cost a recorded retry")

    # ------------- 2: permanent damage -> quarantine / exact accounting
    faults.configure(
        "decode:shard=0:corrupt=1;"      # every Easy_0 image undecodable
        "encode:shard=1:nan=1;"          # every Easy_1 stat non-finite
        "tar.open:shard=2:raise=OSError"  # Normal_0 permanently unreadable
    )
    p = _run(paths, encode, os.path.join(work, "permanent"))
    doc = p["report"]
    by_shard = {r["shard"]: r for r in doc["shards"]}
    fired_actions = {(f["point"], f["action"]) for f in faults.fired()}
    check(("decode", "corrupt") in fired_actions,
          "permanent: corrupt-member fault fired")
    check(("encode", "nan") in fired_actions,
          "permanent: NaN-poison fault fired")
    check(validate_map_report(doc) == [], "permanent: map_report/v1 valid")
    check(
        by_shard["Easy_0.tar"]["skipped_images"]
        == base_entries["Easy_0.tar"]["images"],
        "permanent: corrupt members all counted as skipped",
    )
    check(
        by_shard["Easy_1.tar"]["nonfinite_images"]
        == base_entries["Easy_1.tar"]["images"],
        "permanent: NaN outputs all counted as non-finite",
    )
    check(
        by_shard["Normal_0.tar"]["status"] == "quarantined"
        and [c["cause"] for c in by_shard["Normal_0.tar"]["causes"]]
        == ["exception"] * 3,
        "permanent: unreadable shard quarantined with recorded causes",
    )
    check(doc["quarantined"] == ["Normal_0.tar"],
          "permanent: quarantine list exact")
    # the table must equal the journal-predicted sum of unaffected shards
    unaffected = [n for n, _ in SHARDS
                  if n not in ("Easy_0.tar", "Easy_1.tar", "Normal_0.tar")]
    want = np.zeros((len(CATEGORIES), 5), np.float64)
    for name in unaffected:
        e = base_entries[name]
        want[e["category"]] += np.asarray(e["sums"], np.float64)
    check(p["table"] == reducer_table(want),
          "permanent: table equals journal-predicted unaffected shards")

    # --------------------------------------------- 3: crash, then resume
    faults.configure("tar.open:shard=3:raise=KeyboardInterrupt")
    crash_dir = os.path.join(work, "crash")
    c = _run(paths, encode, crash_dir, expect_crash=True)
    check(c["crashed"], "crash: injected crash killed the run")
    done_before = set(c["journal"].load_all())
    check(
        done_before and "Normal_1.tar" not in done_before,
        f"crash: journal holds only pre-crash shards ({sorted(done_before)})",
    )
    faults.clear()
    r = _run(paths, encode, crash_dir, resume=True)
    doc = r["report"]
    resumed = set(doc["resumed"])
    check(resumed == done_before, "resume: exactly the journaled shards skipped")
    check(r["table"] == base["table"],
          "resume: reducer table byte-identical to fault-free run")
    check(r["manifest"] == base["manifest"],
          "resume: feature files byte-identical, no duplicates/partials")
    check(not _tmp_leftovers(crash_dir), "resume: no partial .tmp files")

    faults.clear()
    if problems:
        print(f"chaos_probe: {len(problems)} FAILED check(s):",
              file=sys.stderr)
        for msg in problems:
            print(f"  - {msg}", file=sys.stderr)
        return 1
    print("chaos_probe: all checks passed", file=sys.stderr)
    if not args.keep and args.work_dir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
