"""Elastic serve fleet CLI — front door + worker processes over the
partition-lease protocol (tmr_tpu/serve/fleet.py).

Front door (owns the partition leases, cluster-wide admission, and the
recruitment election; serves a demo workload when asked)::

    python scripts/serve_fleet.py frontdoor --sizes 1024 --classes 2 \
        --port 7078 [--requests N --report_out fleet_report.json]

Workers (any number; each wraps a full ServeEngine — mesh-aware via
TMR_SERVE_MESH — joins the fleet, leases traffic partitions, and
heartbeats them with its measured drain rate)::

    python scripts/serve_fleet.py worker --coordinator HOST:7078 \
        [--engine stub --delay_ms 40]      # numpy drill engine
    python scripts/serve_fleet.py worker --coordinator HOST:7078 \
        --engine model --checkpoint ckpt   # the real predictor

Gallery workers (replicated pattern shards on the gallery-fleet
coordinator, tmr_tpu/serve/gallery_fleet.py; ``--bank stub`` is the
wire-exact numpy drill)::

    python scripts/serve_fleet.py gallery-worker \
        --coordinator HOST:7079 [--bank stub]

Gallery front door (the gallery-fleet coordinator + its streamed
bulk-ingest sink; workers join with ``gallery-worker``)::

    python scripts/serve_fleet.py gallery-frontdoor --shards 4 \
        --journal_dir /tmp/gj [--port 7079]

Bulk registration (stream a pattern catalog into a running gallery
front door's bulk sink — one pipelined connection + one distributing
flush, NOT N register round-trips; ``--npz`` loads named arrays, else
``--count`` synthesizes a seeded catalog)::

    python scripts/serve_fleet.py bulk-register --sink HOST:PORT \
        [--npz patterns.npz | --count 100000] [--prefix sku]

Lease liveness rides the shared TMR_ELASTIC_* knobs; fleet behavior
(saturation threshold, recruitment bounds, resubmission bound) rides
TMR_FLEET_* (config.ENV_KNOBS). Every entrypoint here installs
``TMR_FAULTS`` schedules (faults.install_from_env) so chaos probes
reach lease-held serve processes the same way map workers install
them. ``scripts/elastic_serve_probe.py`` is the canned chaos proof
(kill -9 / SIGSTOP / recruitment) for the traffic fleet and
``scripts/serve_chaos_probe.py`` for the gallery fleet, riding tier-1.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_address(text: str):
    host, _, port = text.rpartition(":")
    return (host or "127.0.0.1", int(port))


def _cli_frontdoor(args) -> int:
    import numpy as np

    from tmr_tpu.serve.fleet import ServeFleet, stub_signature
    from tmr_tpu.utils import faults
    from tmr_tpu.utils.profiling import log_info, log_warning

    if faults.install_from_env():
        log_warning(
            "fault injection ACTIVE (TMR_FAULTS="
            f"{os.environ.get('TMR_FAULTS', '')!r})"
        )
    fleet = ServeFleet(
        [int(s) for s in args.sizes.split(",") if s.strip()],
        classes=args.classes, host=args.host, port=args.port,
    )
    host, port = fleet.start()
    log_info(
        f"fleet front door: {len(fleet.sizes)} size bucket(s) x "
        f"{fleet.classes} class(es) at {host}:{port}"
    )
    rc = 0
    try:
        if args.requests > 0:
            deadline = time.monotonic() + args.worker_wait_s
            while time.monotonic() < deadline:
                if any(v["holder"] for v in
                       fleet.state()["partitions"].values()):
                    break
                time.sleep(0.1)
            rng = np.random.default_rng(args.seed)
            size = fleet.sizes[0]
            ex = np.asarray([[0.4, 0.4, 0.6, 0.6]], np.float32)
            imgs = [
                rng.standard_normal((size, size, 3)).astype(np.float32)
                for _ in range(args.requests)
            ]
            futs = [fleet.submit(im, ex) for im in imgs]
            done = errors = 0
            exact = True
            for im, f in zip(imgs, futs):
                try:
                    r = f.result(timeout=args.request_timeout_s)
                    done += 1
                    if args.check_stub and \
                            float(r["scores"][0, 0]) != stub_signature(im):
                        exact = False
                except Exception:
                    errors += 1
            log_info(
                f"fleet workload: {done}/{args.requests} completed, "
                f"{errors} failed"
                + ("" if not args.check_stub
                   else f", stub signatures exact={exact}")
            )
            if errors or (args.check_stub and not exact):
                rc = 1
        else:
            log_info("fleet front door serving until interrupted "
                     "(--requests 0)")
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        doc = fleet.report()
        if args.report_out:
            with open(args.report_out, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
        acc = doc["accounting"]
        log_info(
            f"fleet: offered {acc['offered']} = "
            f"{acc['completed']} completed + {acc['rejected']} rejected "
            f"+ {acc['shed']} shed + {acc['errors']} errors; "
            f"{acc['double_served']} double-served, "
            f"{acc['fenced_results']} fenced results, "
            f"{len(doc['reassignments'])} reassignments"
        )
        fleet.close()
    return rc


def _cli_worker(args) -> int:
    from tmr_tpu.serve.fleet import FleetWorker, stub_engine
    from tmr_tpu.utils import faults
    from tmr_tpu.utils.profiling import log_info, log_warning

    if faults.install_from_env():
        log_warning(
            "fault injection ACTIVE (TMR_FAULTS="
            f"{os.environ.get('TMR_FAULTS', '')!r})"
        )
    if args.engine == "stub":
        engine = stub_engine(delay_s=args.delay_ms / 1000.0,
                             batch=args.batch, max_wait_ms=args.wait_ms)
    else:
        from tmr_tpu.config import preset
        from tmr_tpu.inference import Predictor
        from tmr_tpu.serve.engine import ServeEngine

        cfg = preset("TMR_FSCD147", backbone="sam_vit_b",
                     image_size=args.image_size)
        pred = Predictor(cfg)
        if args.checkpoint:
            pred.load_params(args.checkpoint)
        else:
            log_warning("worker: no --checkpoint, random weights")
            pred.init_params(seed=0, image_size=args.image_size)
        engine = ServeEngine(pred)

    worker_id = args.worker_id or f"{os.uname().nodename}-{os.getpid()}"
    worker = FleetWorker(
        _parse_address(args.coordinator), worker_id, engine,
        data_host=args.data_host, data_port=args.data_port,
    )
    worker.start()
    log_info(
        f"fleet worker {worker_id}: engine={args.engine}, data plane at "
        f"{worker._data_server.server_address[:2]}"
    )
    try:
        while not (worker.drained or worker.coordinator_lost):
            time.sleep(0.25)
        log_info(
            f"fleet worker {worker_id}: "
            + ("drained" if worker.drained else "coordinator lost")
            + "; exiting"
        )
    except KeyboardInterrupt:
        pass
    finally:
        worker.stop()
    return 1 if worker.drained or worker.coordinator_lost else 0


def _cli_gallery_worker(args) -> int:
    from tmr_tpu.serve.gallery_fleet import (
        GalleryFleetWorker,
        StubGalleryBank,
    )
    from tmr_tpu.utils import faults
    from tmr_tpu.utils.profiling import log_info, log_warning

    # chaos schedules reach lease-held gallery workers through the
    # SAME env contract the map/elastic workers honor — a probe sets
    # TMR_FAULTS in the subprocess env and the beats/pushes here fire
    if faults.install_from_env():
        log_warning(
            "fault injection ACTIVE (TMR_FAULTS="
            f"{os.environ.get('TMR_FAULTS', '')!r})"
        )
    if args.bank == "stub":
        def bank_factory(shard):
            return StubGalleryBank(image_size=args.image_size)
    else:
        from tmr_tpu.config import preset
        from tmr_tpu.inference import Predictor
        from tmr_tpu.serve.gallery import GalleryBank

        cfg = preset("TMR_FSCD147", backbone="sam_vit_b",
                     image_size=args.image_size)
        pred = Predictor(cfg)
        if args.checkpoint:
            pred.load_params(args.checkpoint)
        else:
            log_warning("gallery worker: no --checkpoint, random weights")
            pred.init_params(seed=0, image_size=args.image_size)

        def bank_factory(shard):
            return GalleryBank(pred, image_size=args.image_size)

    worker_id = args.worker_id or f"{os.uname().nodename}-{os.getpid()}"
    worker = GalleryFleetWorker(
        _parse_address(args.coordinator), worker_id,
        bank_factory=bank_factory,
        data_host=args.data_host, data_port=args.data_port,
    ).start()
    log_info(
        f"gallery worker {worker_id}: bank={args.bank}, data plane at "
        f"{worker.data_address[:2]}"
    )
    try:
        while not (worker.drained or worker.coordinator_lost):
            time.sleep(0.25)
        log_info(
            f"gallery worker {worker_id}: "
            + ("drained" if worker.drained else "coordinator lost")
            + "; exiting"
        )
    except KeyboardInterrupt:
        pass
    finally:
        worker.stop()
    return 1 if worker.drained or worker.coordinator_lost else 0


def _cli_gallery_frontdoor(args) -> int:
    from tmr_tpu.serve.gallery_fleet import GalleryFleet
    from tmr_tpu.utils import faults
    from tmr_tpu.utils.profiling import log_info, log_warning

    if faults.install_from_env():
        log_warning(
            "fault injection ACTIVE (TMR_FAULTS="
            f"{os.environ.get('TMR_FAULTS', '')!r})"
        )
    fleet = GalleryFleet(
        args.shards, replicas=args.replicas or None,
        journal_dir=args.journal_dir, host=args.host, port=args.port,
    )
    host, port = fleet.start()
    bhost, bport = fleet.bulk_sink()
    log_info(
        f"gallery front door: {fleet.n_shards} shard(s) x "
        f"{fleet.replicas} replica(s) at {host}:{port}, bulk-ingest "
        f"sink at {bhost}:{bport}"
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        counters = fleet.counters()
        log_info(
            "gallery front door: "
            + ", ".join(f"{k}={v}" for k, v in sorted(counters.items())
                        if v)
        )
        fleet.close()
    return 0


def _cli_bulk_register(args) -> int:
    import numpy as np

    from tmr_tpu.serve.gallery_fleet import bulk_register
    from tmr_tpu.utils.profiling import log_info

    if args.npz:
        data = np.load(args.npz)
        patterns = ((name, data[name]) for name in data.files)
        total = len(data.files)
    else:
        rng = np.random.default_rng(args.seed)

        def synthetic():
            for i in range(args.count):
                # k in 1..3 rows of normalized xyxy boxes — the synth
                # catalog shape gallery_bench's N-sweep uses
                k = int(rng.integers(1, 4))
                x0 = rng.uniform(0.0, 0.8, size=(k, 1))
                y0 = rng.uniform(0.0, 0.8, size=(k, 1))
                w = rng.uniform(0.05, 0.2, size=(k, 1))
                h = rng.uniform(0.05, 0.2, size=(k, 1))
                box = np.concatenate(
                    [x0, y0, np.minimum(x0 + w, 1.0),
                     np.minimum(y0 + h, 1.0)], axis=1
                ).astype(np.float32)
                yield f"{args.prefix}{i:06d}", box

        patterns = synthetic()
        total = args.count
    t0 = time.monotonic()
    res = bulk_register(
        _parse_address(args.sink), patterns, batch=args.batch,
        flush=not args.no_flush,
    )
    dt = time.monotonic() - t0
    rate = res["streamed"] / dt if dt > 0 else 0.0
    log_info(
        f"bulk-register: {res['streamed']}/{total} streamed "
        f"({rate:.0f}/s), sync ok={res['ok']}, "
        f"flush={res.get('flush')}"
    )
    return 0 if res["ok"] else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python scripts/serve_fleet.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    f = sub.add_parser("frontdoor",
                       help="serve the partition leases + route submits")
    f.add_argument("--sizes", default="1024",
                   help="comma-separated image-size buckets")
    f.add_argument("--classes", default=1, type=int,
                   help="priority classes (partitions = sizes x classes)")
    f.add_argument("--host", default="127.0.0.1")
    f.add_argument("--port", default=0, type=int,
                   help="control port (0 = ephemeral, printed at start)")
    f.add_argument("--requests", default=0, type=int,
                   help="demo workload size (0 = serve forever)")
    f.add_argument("--seed", default=0, type=int)
    f.add_argument("--worker_wait_s", default=30.0, type=float,
                   help="wait this long for a first worker before the "
                        "demo workload")
    f.add_argument("--request_timeout_s", default=120.0, type=float)
    f.add_argument("--check_stub", action="store_true",
                   help="verify stub-engine signatures on the demo "
                        "workload")
    f.add_argument("--report_out", default=None,
                   help="write the fleet report section here at exit")

    w = sub.add_parser("worker", help="lease and serve traffic partitions")
    w.add_argument("--coordinator", required=True,
                   help="HOST:PORT of the fleet front door")
    w.add_argument("--worker_id", default=None,
                   help="stable worker identity (default host-pid)")
    w.add_argument("--engine", default="stub",
                   choices=("stub", "model"),
                   help="'stub' = numpy drill engine (no XLA)")
    w.add_argument("--delay_ms", default=0.0, type=float,
                   help="stub engine: per-program-call delay (capacity "
                        "control for drills)")
    w.add_argument("--batch", default=2, type=int,
                   help="stub engine: micro-batch bound")
    w.add_argument("--wait_ms", default=5.0, type=float,
                   help="stub engine: micro-batch wait bound")
    w.add_argument("--image_size", default=1024, type=int)
    w.add_argument("--checkpoint", default=None)
    w.add_argument("--data_host", default="127.0.0.1")
    w.add_argument("--data_port", default=0, type=int)

    g = sub.add_parser("gallery-worker",
                       help="lease and serve replicated pattern shards")
    g.add_argument("--coordinator", required=True,
                   help="HOST:PORT of the gallery-fleet coordinator")
    g.add_argument("--worker_id", default=None,
                   help="stable worker identity (default host-pid)")
    g.add_argument("--bank", default="stub", choices=("stub", "model"),
                   help="'stub' = numpy drill bank (no XLA)")
    g.add_argument("--image_size", default=32, type=int)
    g.add_argument("--checkpoint", default=None)
    g.add_argument("--data_host", default="127.0.0.1")
    g.add_argument("--data_port", default=0, type=int)

    gf = sub.add_parser(
        "gallery-frontdoor",
        help="gallery-fleet coordinator + streamed bulk-ingest sink",
    )
    gf.add_argument("--shards", default=4, type=int)
    gf.add_argument("--replicas", default=0, type=int,
                    help="copies per pattern (0 = the "
                         "TMR_GALLERY_REPLICAS knob)")
    gf.add_argument("--journal_dir", default=None,
                    help="write-ahead pattern journal directory "
                         "(unset = registrations are not durable)")
    gf.add_argument("--host", default="127.0.0.1")
    gf.add_argument("--port", default=0, type=int,
                    help="control port (0 = ephemeral, printed at start)")

    b = sub.add_parser(
        "bulk-register",
        help="stream a pattern catalog into a gallery bulk-ingest sink",
    )
    b.add_argument("--sink", required=True,
                   help="HOST:PORT of the front door's bulk-ingest sink")
    b.add_argument("--npz", default=None,
                   help="load named exemplar arrays from this .npz")
    b.add_argument("--count", default=1000, type=int,
                   help="synthetic catalog size when --npz is unset")
    b.add_argument("--prefix", default="sku",
                   help="synthetic pattern name prefix")
    b.add_argument("--seed", default=0, type=int)
    b.add_argument("--batch", default="bulk",
                   help="batch label the sink accounts this stream under")
    b.add_argument("--no_flush", action="store_true",
                   help="stream + sync only; distribute later with one "
                        "flush over all batches")

    args = p.parse_args(argv)
    if args.cmd == "frontdoor":
        return _cli_frontdoor(args)
    if args.cmd == "gallery-worker":
        return _cli_gallery_worker(args)
    if args.cmd == "gallery-frontdoor":
        return _cli_gallery_frontdoor(args)
    if args.cmd == "bulk-register":
        return _cli_bulk_register(args)
    return _cli_worker(args)


if __name__ == "__main__":
    sys.exit(main())
