"""Continuous-autotune probe: proves the live shadow-election loop
end to end on the numpy fleet stub (zero XLA, CPU-deterministic) and
emits ONE validated ``live_tune_report/v1`` JSON line.

Phases:

1. **Disabled pin** — with ``TMR_LIVE_TUNE`` off (the default), an
   engine that had ``attach_live_tuner`` called on it (refused) serves
   BITWISE-identical results to one that never heard of live tuning,
   and its metrics registry carries no ``live_tune.*`` keys.
2. **Promotion** — a slow incumbent formulation (stub program paced at
   ``SLOW_S``) vs a decisively faster candidate (``FAST_S``): sampled
   serve batches are shadow-measured off the critical path, the
   candidate passes the oracle and wins consecutively, promotion
   hot-swaps the serving predictor — the SAME engine then serves
   measurably faster with ZERO cold compiles on the hot path, the
   winner bank records the election, all under the device-seconds
   budget.
3. **Shadow-fraction pin** — at the DEFAULT sample rate the shadow
   work (incumbent + candidate per sample) stays under 1% of the
   steady-state serve device seconds.
4. **Demotion** — an injected ``mfu_drop`` anomaly (the HealthWatch
   record shape, delivered through the tuner's listener hook) rolls
   the promotion back to the incumbent with the cause recorded, and
   the bank follows.
5. **Replay + bank isolation** — the decision log replays to exactly
   the recorded elections, and winner banks never leak across device
   generations (cpu / TPU v5e / TPU v6e) or across sweep revisions.

``bench_trend.py --live-tune <file>`` rc-gates the emitted line
fail-closed. Usage: python scripts/live_tune_probe.py [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from tmr_tpu import autotune_live  # noqa: E402
from tmr_tpu.diagnostics import (  # noqa: E402
    LIVE_TUNE_REPORT_SCHEMA,
    validate_live_tune_report,
)

SLOW_S = 0.03   # incumbent stub program pacing (per call)
FAST_S = 0.003  # candidate pacing: a ~10x win, decisive by any margin
KNOB = "TMR_DECODER_IMPL"


def _warn(msg: str) -> None:
    print(f"[live_tune_probe] {msg}", file=sys.stderr)


def _images(n: int, seed: int) -> list:
    rng = np.random.default_rng(seed)
    return [rng.random((16, 16, 3), np.float32).astype(np.float32)
            for _ in range(n)]


def _serve(engine, images) -> list:
    """Sequential submit+wait (one batch per request) returning the
    full result dicts — the bitwise-comparison payload."""
    ex = np.zeros((1, 4), np.float32)
    out = []
    for img in images:
        out.append(engine.submit(img, ex).result(timeout=60))
    return out


def _results_equal(a: list, b: list) -> bool:
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if sorted(ra) != sorted(rb):
            return False
        if not all(np.array_equal(np.asarray(ra[k]), np.asarray(rb[k]))
                   for k in ra):
            return False
    return True


def _phase_disabled() -> dict:
    """TMR_LIVE_TUNE off: attach refuses, serving is bitwise-identical,
    no live_tune metrics keys exist."""
    from tmr_tpu.serve.fleet import stub_engine

    os.environ.pop("TMR_LIVE_TUNE", None)
    images = _images(8, seed=7)
    with stub_engine(0.0) as plain:
        baseline = _serve(plain, images)
    with stub_engine(0.0) as eng:
        tuner = autotune_live.LiveTuner(
            KNOB, ["fused"], "xla",
            runner=lambda arm, payload: (None, 0.0),
        )
        attached = eng.attach_live_tuner(tuner)
        attempted = _serve(eng, images)
        counters = (eng.metrics_snapshot().get("counters") or {})
    live_keys = [k for k in counters if k.startswith("live_tune.")]
    return {
        "attach_refused": attached is False,
        "bitwise_identical": _results_equal(baseline, attempted),
        "live_tune_metrics_keys": live_keys,
    }


def _phase_election(bank_file: str) -> dict:
    """Promotion -> demotion on one live engine under TMR_LIVE_TUNE=1."""
    from tmr_tpu.obs import compile_event_seq, compile_events_since
    from tmr_tpu.serve.fleet import StubFleetPredictor, stub_engine

    os.environ["TMR_LIVE_TUNE"] = "1"
    engine = stub_engine(SLOW_S)
    serving_pred = engine._pred
    # per-arm shadow predictors: same numerics (the oracle must pass),
    # different pacing (the candidate's decisive win)
    shadow = {"xla": StubFleetPredictor(delay_s=SLOW_S),
              "fused": StubFleetPredictor(delay_s=FAST_S)}

    def runner(arm, payload):
        _bucket, reqs = payload
        images = np.stack([r[0] for r in reqs])
        t0 = time.perf_counter()
        out = shadow[arm]._run(images)
        return out, time.perf_counter() - t0

    applied = []

    def apply_fn(knob, value):
        # the production hot-swap (env export + compiled-program
        # invalidation; the stub has no _compiled, so 0 drops) plus the
        # stub's analogue of "the program got faster": pacing swap
        applied.append((knob, value,
                        autotune_live.apply_winner(serving_pred, knob,
                                                   value)))
        serving_pred.delay_s = FAST_S if value == "fused" else SLOW_S

    tuner = autotune_live.LiveTuner(
        KNOB, ["fused"], "xla", runner=runner,
        device_kind="cpu", geometry="stub16",
        sample=0.5, budget_s=5.0, wins_needed=3,
        bank_file=bank_file, apply_fn=apply_fn, metrics=engine.metrics,
    )
    out: dict = {}
    try:
        if not engine.attach_live_tuner(tuner):
            out["error"] = "attach_live_tuner refused under " \
                           "TMR_LIVE_TUNE=1"
            return out
        # --- pre-promotion serving (shadow sampling live underneath)
        pre_images = _images(6, seed=11)
        t0 = time.perf_counter()
        _serve(engine, pre_images)
        pre_wall = time.perf_counter() - t0
        tuner.drain(timeout=30.0)
        rep = tuner.report()
        out["promoted_arm"] = rep["incumbent"]
        out["promotions"] = rep["counters"]["promotions"]
        out["pre_s_per_req"] = pre_wall / len(pre_images)
        # --- post-promotion serving: faster, zero hot-path compiles
        seq = compile_event_seq()
        post_images = _images(10, seed=13)
        t0 = time.perf_counter()
        _serve(engine, post_images)
        post_wall = time.perf_counter() - t0
        events, _ = compile_events_since(seq)
        out["post_s_per_req"] = post_wall / len(post_images)
        out["hot_path_compiles"] = len(events)
        out["speedup"] = (out["pre_s_per_req"] / out["post_s_per_req"]
                          if out["post_s_per_req"] > 0 else None)
        bank = autotune_live.load_bank(bank_file, device_kind="cpu")
        key = autotune_live.bank_key("cpu", KNOB, "stub16")
        out["bank_after_promote"] = (bank.get(key) or {}).get("winner")
        # --- injected anomaly -> demotion with recorded cause
        tuner.observe_anomalies([{
            "schema": "anomaly/v1", "anomaly": "mfu_drop",
            "message": "injected: post-promotion MFU collapse",
            "evidence": {"injected": True}, "ts": time.time(),
        }])
        rep = tuner.report()
        out["restored_arm"] = rep["incumbent"]
        out["demotions"] = rep["counters"]["demotions"]
        demotes = [d for d in rep["decisions"] if d["event"] == "demote"]
        out["demote_cause"] = demotes[-1]["cause"] if demotes else None
        out["serving_delay_s"] = serving_pred.delay_s
        bank = autotune_live.load_bank(bank_file, device_kind="cpu")
        out["bank_after_demote"] = (bank.get(key) or {}).get("winner")
        out["applied"] = applied
        out["tuner"] = tuner.report()
    finally:
        engine.close()
    return out


def _phase_fraction() -> dict:
    """Default-sample-rate shadow cost against simulated steady-state
    traffic: synthesized per-arm timings (no sleeping — the fraction is
    a structural property of sample rate x (1 + cand/base))."""
    dets = {"scores": np.zeros((1, 4), np.float32)}

    def runner(arm, payload):
        return dets, 0.010 if arm == "xla" else 0.004

    tuner = autotune_live.LiveTuner(
        "TMR_WIN_ATTN", ["flash"], "dense", runner=runner,
        device_kind="cpu", geometry="frac",
        sample=None,            # the DEFAULT rate — the pin under test
        budget_s=5.0, wins_needed=10 ** 6,  # never promote here
    )
    # dense/flash arms reuse the runner's xla/other split
    tuner._runner = lambda arm, payload: runner(
        "xla" if arm == "dense" else "flash", payload
    )
    tuner.start()
    offers = 3000
    for _ in range(offers):
        tuner.offer(None, None, items=1)
        if not tuner._q.empty():
            tuner.drain(timeout=10.0)  # keep the bounded queue drained
    tuner.drain(timeout=30.0)
    tuner.stop()
    counters = tuner.counters()
    return {
        "offers": offers,
        "sample": tuner.sample,
        "shadow_runs": counters["shadow_runs"],
        "shadow_device_s": counters["shadow_device_s"],
        "budget_s": tuner.budget_s,
        "shadow_fraction": tuner.shadow_fraction(),
    }


def _phase_bank_isolation(path: str) -> dict:
    """Per-generation isolation + stale-revision fallback on one file."""
    entries = {}
    for kind in ("cpu", "TPU v5e", "TPU v6e"):
        key = autotune_live.bank_key(kind, "TMR_WIN_ATTN", "g1")
        entries[key] = autotune_live.make_entry(
            kind, "TMR_WIN_ATTN", "g1", "flash", source="offline")
    stale_key = autotune_live.bank_key("cpu", "TMR_QUANT", "g1")
    stale = autotune_live.make_entry("cpu", "TMR_QUANT", "g1", "int8",
                                     source="offline")
    stale["sweep_rev"] = "pre-history"  # a harness revision ago
    entries[stale_key] = stale
    autotune_live.store_bank(entries, path)
    loads = {
        kind: autotune_live.load_bank(path, device_kind=kind)
        for kind in ("cpu", "TPU v5e", "TPU v6e")
    }
    return {
        "per_kind_counts": {k: len(v) for k, v in loads.items()},
        "isolated": all(
            set(e["device_kind"] for e in loads[k].values()) <= {k}
            and len(loads[k]) == 1  # own entry only; stale one dropped
            for k in loads
        ),
        "stale_dropped": stale_key not in loads["cpu"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON line to this path")
    args = ap.parse_args(argv)

    tmpdir = tempfile.mkdtemp(prefix="live_tune_probe_")
    bank_file = os.path.join(tmpdir, "winner_bank.json")
    iso_file = os.path.join(tmpdir, "winner_bank_iso.json")
    os.environ["TMR_LIVE_TUNE_BANK"] = bank_file
    prior_live = os.environ.get("TMR_LIVE_TUNE")
    try:
        disabled = _phase_disabled()
        election = _phase_election(bank_file)
        fraction = _phase_fraction()
        isolation = _phase_bank_isolation(iso_file)
    finally:
        if prior_live is None:
            os.environ.pop("TMR_LIVE_TUNE", None)
        else:
            os.environ["TMR_LIVE_TUNE"] = prior_live

    if "error" in election:
        doc = {"schema": LIVE_TUNE_REPORT_SCHEMA,
               "error": election["error"]}
        print(json.dumps(doc))
        return 1

    tuner_rep = election.pop("tuner")
    decisions = tuner_rep["decisions"]
    replay = autotune_live.replay_decisions(
        decisions, wins_needed=tuner_rep["wins_needed"],
        win_ratio=tuner_rep["win_ratio"],
    )
    recorded = autotune_live.recorded_elections(decisions)
    shadow_wins = [d for d in decisions
                   if d["event"] == "shadow" and d["win"]]
    counters = tuner_rep["counters"]

    checks = {
        "disabled_identical": bool(
            disabled["attach_refused"]
            and disabled["bitwise_identical"]
            and not disabled["live_tune_metrics_keys"]
        ),
        "shadow_fraction_ok": bool(
            isinstance(fraction["shadow_fraction"], float)
            and fraction["shadow_fraction"] < 0.01
        ),
        "budget_respected": bool(
            counters["shadow_device_s"] <= tuner_rep["budget_s"]
            and fraction["shadow_device_s"] <= fraction["budget_s"]
        ),
        "promoted_decisively": bool(
            election["promotions"] == 1
            and election["promoted_arm"] == "fused"
            and len(shadow_wins) >= tuner_rep["wins_needed"]
            and all(d["cand_s_per_item"]
                    < tuner_rep["win_ratio"] * d["base_s_per_item"]
                    for d in shadow_wins)
            and election["bank_after_promote"] == "fused"
        ),
        "promotion_faster": bool(
            isinstance(election["speedup"], float)
            and election["speedup"] > 2.0
        ),
        "no_hot_path_compiles": election["hot_path_compiles"] == 0,
        "anomaly_demotes": bool(
            election["demotions"] == 1
            and election["restored_arm"] == "xla"
            and election["demote_cause"] == "mfu_drop"
            and election["serving_delay_s"] == SLOW_S
            and election["bank_after_demote"] == "xla"
        ),
        "replay_consistent": bool(recorded and replay == recorded),
        "bank_isolated": bool(
            isolation["isolated"] and isolation["stale_dropped"]
        ),
    }

    doc = {
        "schema": LIVE_TUNE_REPORT_SCHEMA,
        "ts": time.time(),
        "device_kind": "cpu",
        "config": {
            "knob": KNOB, "slow_s": SLOW_S, "fast_s": FAST_S,
            "bank_file": bank_file,
        },
        "tuner": tuner_rep,
        "disabled": disabled,
        "election": election,
        "fraction": fraction,
        "bank_isolation": isolation,
        "replay": {"recorded": recorded, "replayed": replay},
        "summary": {
            "shadow_fraction": fraction["shadow_fraction"],
            "demote_cause": election["demote_cause"],
            "promotion_speedup": election["speedup"],
            "pre_s_per_req": election["pre_s_per_req"],
            "post_s_per_req": election["post_s_per_req"],
            "bank_final_winner": election["bank_after_demote"],
        },
        "checks": checks,
    }
    problems = validate_live_tune_report(doc)
    if problems:  # self-check: the emitted line must validate
        for p in problems:
            _warn(f"validator: {p}")
        doc["validator_problems"] = problems
    line = json.dumps(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    for name, ok in checks.items():
        if not ok:
            _warn(f"check failed: {name}")
    return 0 if not problems and all(checks.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
