"""Extended benchmarks: the BASELINE.md configs beyond bench.py's headline.

bench.py stays the driver's single-JSON-line headline (FSCD-147 eval,
ViT-B @ 1024, batch 4). This script measures the remaining tracked configs
(BASELINE.md "Benchmark configs to track") and prints ONE JSON dict:

  1. demo-style single-image 3-shot inference (per-exemplar passes + merged
     NMS, batch 1) — config #1;
  2. RPINE-style eval with vit_h + --refine_box (batch 1) — config #3;
  4. streaming map/reduce inference over synthetic tar shards, native C++ IO
     vs pure-python IO, reducer table emitted — config #4 (reference anchor:
     ~25 s/img for the ONNX-CPU mapper, logs/mapper_debug_*.txt);
  5. one training step, ViT-B @ 1024 batch 4 — config #5's inner loop;
  plus the 1536 small-object bucket (eval protocol, batch 1).

  6. serving layer vs sequential Predictor loop (tmr_tpu/serve closed-loop
     interactive mix; scripts/serve_bench.py holds the full sweep).

Usage:  python scripts/bench_extra.py
        [--only demo,batch_sweep,refine,stream,train,1536,serve]
Results are committed as BENCH_EXTRA.json next to BENCH_r{N}.json.

Same measurement rules as bench.py: device-staged inputs, chained execution
via a scalar data dependency, single closing fetch.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tarfile
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()

# TMR_BENCH_TINY=1: shrink every config so the whole script smoke-runs on
# CPU in minutes (validating the code paths); real numbers use defaults.
TINY = os.environ.get("TMR_BENCH_TINY", "") not in ("", "0", "false")
SIZE = 256 if TINY else 1024
SIZE_HI = 384 if TINY else 1536
BACKBONE_B = "sam_vit_b"
BACKBONE_H = "sam_vit_b" if TINY else "sam_vit_h"
DTYPE = "float32" if TINY else "bfloat16"
N_ITER = 2 if TINY else 5
N_ITER_LONG = 2 if TINY else 8  # 1536/train keep the longer average


def _chain_time(step, n, *args):
    """Chained timing: step(*args, fb) -> (out, fb'); returns sec/iter.
    The shared utils/profiling.py harness (warm/zero the feedback before
    the timed window, one closing scalar fetch, RTT floor subtracted)."""
    from tmr_tpu.utils.profiling import (
        chained_seconds_per_iter,
        measure_rtt_floor,
    )

    return chained_seconds_per_iter(
        step, *args, iters=n, rtt=measure_rtt_floor()
    )


def bench_demo() -> dict:
    """Config #1: single image, 3 exemplars, per-exemplar passes + one NMS.

    The demo path is inherently a host-driven multi-call pipeline (one
    forward per exemplar, merged NMS — trainer.py:75-121), so unlike the
    single fused program it cannot be chained through one scalar; the image
    is staged on device once, dispatches queue asynchronously, and a single
    closing fetch ends the timing (dispatch latency is part of this path).
    """
    import jax
    import jax.numpy as jnp

    from tmr_tpu.config import preset
    from tmr_tpu.inference import Predictor

    cfg = preset("TMR_FSCD147", backbone=BACKBONE_B, image_size=SIZE,
                 compute_dtype=DTYPE, batch_size=1)
    pred = Predictor(cfg)
    pred.init_params(seed=0, image_size=SIZE)
    rng = np.random.default_rng(0)
    image = jnp.asarray(
        rng.standard_normal((1, SIZE, SIZE, 3)), jnp.float32
    )  # staged on device once
    exemplars = np.array(
        [[0.45, 0.45, 0.53, 0.55], [0.2, 0.2, 0.27, 0.28],
         [0.7, 0.6, 0.78, 0.69]], np.float32,
    )
    out = pred.predict_multi_exemplar(image, exemplars)  # compile
    _ = jax.device_get(out["scores"])
    n = N_ITER
    t0 = time.perf_counter()
    for _ in range(n):
        out = pred.predict_multi_exemplar(image, exemplars)
    _ = jax.device_get(out["scores"])
    dt = (time.perf_counter() - t0) / n
    return {"img_per_sec": round(1.0 / dt, 3), "sec_per_image": round(dt, 4),
            "exemplars": 3}


def _fused_eval_step(cfg, capacity, image_size, refiner=None,
                     refiner_params=None):
    """The PRODUCTION fused program via Predictor's chain_feedback hook —
    the benchmark measures the exact pipeline eval compiles, no copy."""
    import jax.numpy as jnp

    from tmr_tpu.inference import Predictor

    pred = Predictor(cfg, refiner=refiner, refiner_params=refiner_params)
    pred.init_params(seed=0, image_size=image_size)
    rng = np.random.default_rng(0)
    image = jnp.asarray(
        rng.standard_normal((cfg.batch_size, image_size, image_size, 3)),
        jnp.float32,
    )
    ex = jnp.tile(jnp.asarray([[[0.45, 0.45, 0.53, 0.55]]], jnp.float32),
                  (cfg.batch_size, 1, 1))
    fused = pred._get_fn(capacity, chain_feedback=True)

    def step(p, im, e, fb):
        return fused(p, pred.refiner_params, im, e, fb)

    return step, pred.params, image, ex


def bench_batch_sweep() -> dict:
    """Throughput vs batch size for the headline config (ViT-B @ 1024,
    fused eval). bench.py's headline batch (4) was an engineering guess;
    this measures img/s at 1, 2, 4, 8 and 16 so the throughput-optimal
    batch is a recorded number, not a default. Skips a batch on OOM/compile
    failure rather than dying (16 at 1024^2 can exceed a v5e's 16 GB).

    On TPU the winner is persisted into the autotune winner cache as
    TMR_BENCH_BATCH keyed by (device kind, image size): the next bench.py
    on this machine defaults its headline batch to the measured optimum —
    the same "measured winners become the defaults" mechanism as the
    formulation knobs (explicit TMR_BENCH_BATCH always wins)."""
    import jax

    from tmr_tpu.config import preset
    from tmr_tpu.utils.autotune import _cache_store, bench_batch_cache_key

    out = {}
    best = (None, -1.0)
    for batch in ((1, 2) if TINY else (1, 2, 4, 8, 16)):
        cfg = preset("TMR_FSCD147", backbone=BACKBONE_B, image_size=SIZE,
                     compute_dtype=DTYPE, batch_size=batch)
        try:
            step, params, image, ex = _fused_eval_step(cfg, 17, SIZE)
            dt = _chain_time(step, N_ITER, params, image, ex)
            ips = batch / dt
            out[f"batch{batch}"] = {
                "img_per_sec": round(ips, 3),
                "ms_per_batch": round(dt * 1000, 2),
            }
            if ips > best[1]:
                best = (batch, ips)
        except Exception as e:
            out[f"batch{batch}"] = {"error": f"{type(e).__name__}: {e}"}
    if best[0] is not None and jax.default_backend() == "tpu":
        key = bench_batch_cache_key(jax.devices()[0].device_kind, SIZE)
        _cache_store(key, {"TMR_BENCH_BATCH": {"picked": str(best[0])}})
        out["cached_default"] = best[0]
    return out


def bench_1536() -> dict:
    """The small-object escalation bucket (eval protocol: batch 1)."""
    from tmr_tpu.config import preset

    cfg = preset("TMR_FSCD147", backbone=BACKBONE_B, image_size=SIZE_HI,
                 compute_dtype=DTYPE, batch_size=1)
    step, params, image, ex = _fused_eval_step(cfg, 17, SIZE_HI)
    dt = _chain_time(step, N_ITER_LONG,
                     params, image, ex)
    return {"img_per_sec": round(1.0 / dt, 3), "sec_per_image": round(dt, 4)}


def bench_refine() -> dict:
    """Config #3: RPINE protocol — vit_h, batch 1, SAM-decoder refinement."""
    from tmr_tpu.config import preset
    from tmr_tpu.refine import build_refiner

    cfg = preset("TMR_RPINE", backbone=BACKBONE_H, image_size=SIZE,
                 compute_dtype=DTYPE, batch_size=1, refine_box=True,
                 max_detections=64 if TINY else 1100)
    refiner, rparams = build_refiner(cfg, seed=0)
    step, params, image, ex = _fused_eval_step(
        cfg, 33, SIZE, refiner=refiner, refiner_params=rparams
    )
    dt = _chain_time(step, N_ITER,
                     params, image, ex)
    return {"img_per_sec": round(1.0 / dt, 3), "sec_per_image": round(dt, 4)}


def bench_train() -> dict:
    """Config #5's inner loop: one training step, ViT-B @ 1024, batch 4.

    TMR_XCORR_PRECISION is pinned to the parity default for this config:
    autotune's relaxed-precision winners are inference-only policy
    (utils/autotune.py tune_precision), so the training benchmark must
    measure the same f32 matcher gradients production training runs."""
    prev_prec = os.environ.get("TMR_XCORR_PRECISION")
    os.environ["TMR_XCORR_PRECISION"] = "highest"
    try:
        return _bench_train_inner()
    finally:
        if prev_prec is None:
            os.environ.pop("TMR_XCORR_PRECISION", None)
        else:
            os.environ["TMR_XCORR_PRECISION"] = prev_prec


def _bench_train_inner() -> dict:
    import jax
    import jax.numpy as jnp

    from tmr_tpu.config import preset
    from tmr_tpu.train.state import create_train_state, make_train_step

    cfg = preset("TMR_FSCD_LVIS_Unseen", backbone=BACKBONE_B,
                 image_size=SIZE, compute_dtype=DTYPE,
                 batch_size=2 if TINY else 4)
    from tmr_tpu.models import build_model

    model = build_model(cfg).clone(template_capacity=17)
    b = cfg.batch_size
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(
            rng.standard_normal((b, SIZE, SIZE, 3)), jnp.float32
        ),
        "exemplars": jnp.tile(
            jnp.asarray([[[0.45, 0.45, 0.53, 0.55]]], jnp.float32), (b, 1, 1)
        ),
        "gt_boxes": jnp.tile(
            jnp.asarray([[[0.45, 0.45, 0.53, 0.55]]], jnp.float32), (b, 8, 1)
        ),
        "gt_valid": jnp.ones((b, 8), bool),
    }
    state = create_train_state(
        model, cfg, jax.random.key(0), batch["image"], batch["exemplars"],
        steps_per_epoch=100,
    )
    step = jax.jit(make_train_step(model, cfg))

    state, losses = step(state, batch)  # compile
    _ = jax.device_get(losses["loss"])
    n = N_ITER_LONG
    t0 = time.perf_counter()
    for _ in range(n):
        state, losses = step(state, batch)
    _ = jax.device_get(losses["loss"])
    dt = (time.perf_counter() - t0) / n
    return {"img_per_sec": round(b / dt, 3), "sec_per_step": round(dt, 4),
            "batch": b}


def _write_synthetic_shards(root: str, n_shards=4, imgs_per_shard=8,
                            size=512) -> list:  # size: source JPEG side
    """Easy_/Normal_/Hard_ tar shards of random JPEGs (mapper.py layout)."""
    from PIL import Image

    rng = np.random.default_rng(0)
    cats = ["Easy", "Normal", "Hard"]
    paths = []
    for s in range(n_shards):
        name = f"{cats[s % 3]}_shard_{s:03d}.tar"
        path = os.path.join(root, name)
        with tarfile.open(path, "w") as tar:
            for i in range(imgs_per_shard):
                arr = rng.integers(0, 255, (size, size, 3), np.uint8)
                buf = io.BytesIO()
                Image.fromarray(arr).save(buf, format="JPEG")
                data = buf.getvalue()
                info = tarfile.TarInfo(f"img_{s:03d}_{i:02d}.jpg")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
        paths.append(path)
    return paths


def bench_stream() -> dict:
    """Config #4: streaming map/reduce feature extraction over tar shards.

    Reference anchor: the Hadoop mapper ran ~25 s/img on ONNX CPU
    (logs/mapper_debug_20251228_162952.txt). Reports native C++ IO vs pure
    python IO and emits the reducer table like reducer.py:25-27.
    """
    from tmr_tpu.models import build_sam_encoder
    from tmr_tpu.parallel.mapreduce import (
        make_encode_stats_fn,
        reduce_lines,
        format_stats_table,
        run_stream,
        run_stream_native,
    )

    if TINY:
        from tmr_tpu.models.vit import SamViT

        import jax as _jax
        import jax.numpy as _jnp

        encoder = SamViT(
            embed_dim=32, depth=2, num_heads=2, global_attn_indexes=(1,),
            patch_size=8, window_size=3, out_chans=16,
            pretrain_img_size=SIZE,
        )
        params = _jax.jit(encoder.init)(
            _jax.random.key(0), _jnp.zeros((1, SIZE, SIZE, 3))
        )["params"]
    else:
        encoder, params = build_sam_encoder("vit_b", image_size=SIZE)
    fn = make_encode_stats_fn(encoder, params)
    out = {}
    with tempfile.TemporaryDirectory() as root:
        paths = _write_synthetic_shards(root, size=SIZE // 2)
        n_imgs = 4 * 8
        # warmup/compile on one shard
        run_stream(paths[:1], fn, batch_size=8, image_size=SIZE)
        for label, runner in (("native", run_stream_native),
                              ("python", run_stream)):
            try:
                t0 = time.perf_counter()
                acc = runner(paths, fn, batch_size=8, image_size=SIZE)
                dt = time.perf_counter() - t0
                out[label] = {
                    "img_per_sec": round(n_imgs / dt, 3),
                    "sec_per_image": round(dt / n_imgs, 4),
                    "vs_mapper_25s_per_img": round((n_imgs / dt) / 0.04, 1),
                }
                if label == "native":
                    table = format_stats_table(
                        reduce_lines(acc.emit_lines())
                    )
                    out["reducer_table"] = table.splitlines()
            except Exception as e:  # native lib may be unbuilt
                out[label] = {"error": str(e)}
    return out


def bench_serve() -> dict:
    """The serving layer (tmr_tpu/serve) vs the sequential Predictor loop
    at the headline geometry: closed-loop batched+cached throughput over an
    interactive mix (unique images, exact repeats, same-image-new-exemplar
    queries). scripts/serve_bench.py is the full offered-load sweep with
    latency percentiles; this stage is the battery's one-number summary."""
    import time

    from tmr_tpu.config import preset
    from tmr_tpu.inference import Predictor
    from tmr_tpu.serve import ServeEngine

    cfg = preset("TMR_FSCD147", backbone=BACKBONE_B, image_size=SIZE,
                 compute_dtype=DTYPE, batch_size=1)
    pred = Predictor(cfg)
    pred.init_params(seed=0, image_size=SIZE)
    rng = np.random.default_rng(0)
    ex = np.asarray([[0.45, 0.45, 0.53, 0.55]], np.float32)
    ex2 = np.asarray([[0.2, 0.2, 0.28, 0.3]], np.float32)
    ex3 = np.asarray([[0.6, 0.55, 0.68, 0.66]], np.float32)
    n_imgs = 2 if TINY else 4
    imgs = [rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)
            for _ in range(n_imgs)]
    # the interactive mix: cold wave, exact repeats (result cache),
    # same-image-new-exemplar (promotion fills, then feature-cache hits)
    waves = [[(im, ex) for im in imgs], [(im, ex) for im in imgs],
             [(im, ex2) for im in imgs], [(im, ex3) for im in imgs],
             [(im, ex2) for im in imgs]]
    flat = [r for w in waves for r in w]

    def run_waves(engine, wave_list):
        for wave in wave_list:
            futs = [engine.submit(img, e) for img, e in wave]
            for f in futs:
                f.result(timeout=600)

    # warmup on THROWAWAY images: compiles every program the timed waves
    # hit (fused + backbone + heads at the wave batch shape) without
    # seeding the measured workload's caches
    _ = np.asarray(pred(imgs[0][None], ex[None])["scores"])
    w_imgs = [rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)
              for _ in range(n_imgs)]
    with ServeEngine(pred) as warm:
        run_waves(warm, [[(im, ex) for im in w_imgs],
                         [(im, ex2) for im in w_imgs],
                         [(im, ex3) for im in w_imgs]])

    t0 = time.perf_counter()
    for img, e in flat:
        np.asarray(pred(img[None], e[None])["scores"])
    seq = len(flat) / (time.perf_counter() - t0)

    with ServeEngine(pred) as eng:
        t0 = time.perf_counter()
        run_waves(eng, waves)
        serve = len(flat) / (time.perf_counter() - t0)
        stats = eng.stats()
    return {
        "sequential_img_per_sec": round(seq, 3),
        "serve_img_per_sec": round(serve, 3),
        "speedup": round(serve / seq, 2),
        "batch": stats["batch_bounds"],
        "batch_occupancy": stats["batch_occupancy"],
        "result_cache_hits": stats["result_cache"]["hits"],
        "feature_cache_hits": stats["feature_cache"]["hits"],
    }


ALL = {
    "demo": bench_demo,
    "batch_sweep": bench_batch_sweep,
    "1536": bench_1536,
    "refine": bench_refine,
    "train": bench_train,
    "stream": bench_stream,
    "serve": bench_serve,
}


def _run(cancel_watchdog, argv=None) -> int:
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of " + ",".join(ALL))
    args = ap.parse_args(argv)
    names = list(ALL) if not args.only else args.only.split(",")
    import jax

    # Measure every stage under the headline's tuned formulations, not the
    # library defaults (a blockwise-default batch sweep would understate
    # the framework ~2x): export cached fresh winners without measuring,
    # then fall back to stale-stamped previous winners (valid values whose
    # variant set grew — bench.py's bank uses the same policy). Explicit
    # env pins always win (setdefault). Non-headline geometries (1536,
    # vit_h) re-gate each formulation per geometry at trace time.
    if jax.default_backend() == "tpu":
        from tmr_tpu.config import preset
        from tmr_tpu.utils.autotune import autotune, stale_winners

        cfg0 = preset("TMR_FSCD147", backbone=BACKBONE_B, image_size=SIZE,
                      compute_dtype=DTYPE, batch_size=4)
        autotune(cfg0, SIZE, 4, sweep=False,
                 log=lambda m: print(f"[bench_extra] {m}", file=sys.stderr,
                                     flush=True))
        for k, v in stale_winners(cfg0, SIZE, 4).items():
            os.environ.setdefault(k, v)
            print(f"[bench_extra] pinned stale-stamped winner {k}={v}",
                  file=sys.stderr, flush=True)

    results = {"device": str(jax.devices()[0])}
    for name in names:
        t0 = time.perf_counter()
        try:
            results[name] = ALL[name]()
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {e}"}
        results[name]["wall_s"] = round(time.perf_counter() - t0, 1)
        print(f"[bench_extra] {name}: {results[name]}", file=sys.stderr,
              flush=True)
    cancel_watchdog()  # before the success print: no success-then-watchdog
    print(json.dumps(results))
    return 0


def main(argv=None) -> int:
    """Per-config failures are recorded inline by _run; the SHARED guard
    (tmr_tpu/utils/bench_guard.py, same one bench.py runs under) covers
    everything OUTSIDE those try blocks — backend init (round 3's bench.py
    died exactly there), argparse, cache setup — plus the tunnel-wedge
    watchdog: the output is ALWAYS one JSON line."""
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(json.dumps({"error": msg}), flush=True),
    )


if __name__ == "__main__":
    sys.exit(main())
