#!/usr/bin/env bash
# Session-7 follow-up, run ONCE after scripts/tpu_watch3.sh's battery
# completes (single tunnel client discipline): the definitive headline
# bench under the GROWN variant set (densefolded + bf16 score tiles in
# the running), then seed promotion so the driver's round-end bench
# cache-hits the winners instead of re-sweeping.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG="${TMR_WATCH_LOG:-/tmp/post_battery.log}"

log() { echo "[$(date +%H:%M:%S)] $*" >>"$LOG"; }

cd "$REPO"
log "post_battery started"
rm -f "$REPO/autotune.env"
TMR_AUTOTUNE_EXPORT="$REPO/autotune.env" TMR_BENCH_ALARM=2700 \
  timeout 3000 python bench.py >"$REPO/bench_live.json" 2>>"$LOG"
log "final headline rc=$? -> bench_live.json"
if grep -q '"value"' "$REPO/bench_live.json" 2>/dev/null \
    && ! grep -q '"error"' "$REPO/bench_live.json" 2>/dev/null; then
  cp "$REPO/bench_live.json" "$REPO/BENCH_LIVE.json"
fi
timeout 120 python scripts/promote_cache_to_seed.py \
  >"$REPO/promote_seed.json" 2>>"$LOG"
log "promote rc=$? -> promote_seed.json"
log "post_battery done"
