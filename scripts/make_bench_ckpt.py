"""Produce the benchmark checkpoint: quickstart-train the flagship config.

Trains the bench's exact model (SAM ViT-B backbone, 512-d matcher, fusion —
bench.py's preset) on the synthetic quickstart fixture (data/synthetic.py)
and saves a PARAMS-ONLY orbax checkpoint; point bench.py at it explicitly
via ``TMR_BENCH_CKPT=<out>/params`` (there is deliberately NO default-path
auto-detect — the random-weights headline must stay a random-weights
measurement). This
closes the "random weights" asterisk on the bench metric: the measured
program then runs checkpoint-restored, post-training activations.

Params are resolution-independent (pos-embed/rel-pos interpolate), so
training at a smaller --image_size than the benched 1024 is valid and much
cheaper; the backbone is frozen (lr_backbone 0, the reference recipe), so
training shapes the detector head on real gradient signal.

``--epochs 0`` skips training and saves the initializer output — a fast
plumbing mode for tests.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--image_size", default=256, type=int)
    p.add_argument("--epochs", default=2, type=int)
    p.add_argument("--batch_size", default=2, type=int)
    p.add_argument("--n_train", default=8, type=int)
    p.add_argument("--out", default=os.path.join(REPO, "bench_ckpt"))
    p.add_argument("--compute_dtype", default="bfloat16")
    args = p.parse_args(argv)

    import jax
    import orbax.checkpoint as ocp

    from tmr_tpu.config import preset
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()

    out = os.path.abspath(os.path.join(args.out, "params"))
    with tempfile.TemporaryDirectory() as tmp:
        fixture = os.path.join(tmp, "data")
        cfg = preset(
            "TMR_FSCD147",
            backbone="sam_vit_b",
            image_size=args.image_size,
            compute_dtype=args.compute_dtype,
            batch_size=args.batch_size,
            datapath=fixture,
            logpath=os.path.join(tmp, "log"),
            max_epochs=args.epochs,
            AP_term=max(args.epochs, 1),  # one val pass at the cadence end
            num_workers=0,
            nowandb=True,
        )
        if args.epochs <= 0:
            from tmr_tpu.inference import Predictor

            predictor = Predictor(cfg)
            predictor.init_params(seed=0, image_size=args.image_size)
            params = predictor.params
        else:
            from tmr_tpu.data.synthetic import write_synthetic_fscd147
            from tmr_tpu.train.loop import Trainer

            write_synthetic_fscd147(
                fixture, n_train=args.n_train, n_val=2
            )
            trainer = Trainer(cfg)
            trainer.fit()
            params = trainer.state.params

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(out, params, force=True)
        ckptr.wait_until_finished()
    print(f"bench checkpoint saved: {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
