"""Flight-recorder probe: proves the performance-accounting layer end to
end and prints ONE ``flight_report/v1`` JSON document (schema + validator
in tmr_tpu/diagnostics.py).

What it runs and what it asserts:

- **device-time attribution + MFU** — a tiny ServeEngine workload with
  ``TMR_FLIGHT`` off (the overhead baseline) and then on: every executed
  program must appear in ``mfu_report/v1`` with finite per-program MFU,
  a roofline classification, and analytic FLOPs agreeing with the
  compiled program's own ``cost_analysis()`` within the
  PERF.md-documented 1.17x envelope.
- **health introspection** — ``ServeEngine.health()`` must validate as
  ``health_report/v1``, and the heartbeat writer's JSONL file must
  round-trip (every appended line re-validates).
- **anomaly detection** — an injected recompile storm (key-change
  compile events over threshold) and a queue-saturation burst must each
  fire EXACTLY their one anomaly, with structured gate_refused-style
  causes; a calm pass must fire none.
- **overhead** — the disabled-mode cost of the flight layer's per-site
  bool check, projected against the workload's per-request latency; the
  check requires < 1% (the TMR_FLIGHT=0 zero-cost contract, same shape
  as PR 4's span pin).

Usage:  python scripts/obs_watch.py [--tiny] [--out FILE]

``--tiny`` (or TMR_BENCH_TINY=1) runs the CPU smoke geometry tier-1
uses (tests/test_obs_watch.py); real numbers use the deployment
geometry. Same one-JSON-line contract as bench.py via the shared
bench_guard.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-intended invocations must never dial the TPU relay — strip the
# tunnel env BEFORE any jax import (single-client tunnel; session-7 wedge)
from tmr_tpu.utils.bench_guard import scrub_cpu_tunnel_env  # noqa: E402

scrub_cpu_tunnel_env()

#: flight-layer touch points on one request's path: the devtime wrapper
#: at program execution, the engine's _finish record guard, and the
#: mapreduce-style per-summary guard — the sites the disabled bool
#: check is paid at
_FLIGHT_SITES_PER_REQUEST = 3


def _progress(msg: str) -> None:
    print(f"[obs_watch] {msg}", file=sys.stderr, flush=True)


def _measure_disabled_check_ns(iters: int = 50_000) -> float:
    """Amortized cost of one flight-disabled instrumented call (the
    track_devtime wrapper around a trivial callable), in ns."""
    from tmr_tpu.obs import devtime, flight

    assert not flight.flight_enabled()
    wrapped = devtime.track_devtime(lambda: 0, "probe", ("overhead",))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            wrapped()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e9


def _serve_closed_loop(engine, requests):
    t0 = time.perf_counter()
    futs = [engine.submit(img, ex) for img, ex in requests]
    for f in futs:
        f.result(timeout=600)
    return time.perf_counter() - t0


def _run(cancel_watchdog, argv=None) -> int:
    from tmr_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke geometry (also TMR_BENCH_TINY=1)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--requests", type=int, default=None)
    args = ap.parse_args(argv)

    tiny = args.tiny or os.environ.get("TMR_BENCH_TINY", "") not in (
        "", "0", "false"
    )
    size = int(os.environ.get("TMR_BENCH_SIZE", 128 if tiny else 1024))
    dtype = "float32" if tiny else "bfloat16"
    n_req = args.requests or (2 * args.batch + 2)

    import jax

    from tmr_tpu import obs
    from tmr_tpu.config import preset
    from tmr_tpu.diagnostics import (
        FLIGHT_REPORT_SCHEMA,
        validate_flight_report,
        validate_health_report,
        validate_mfu_report,
    )
    from tmr_tpu.inference import Predictor
    from tmr_tpu.obs import devtime, flight
    from tmr_tpu.serve import ServeEngine

    _progress(f"backend: {jax.devices()[0]} size={size} tiny={tiny}")

    # ---- disabled-mode overhead first, before anything enables flight
    flight.configure(enabled=False)
    disabled_ns = _measure_disabled_check_ns()
    _progress(f"disabled flight check: {disabled_ns:.0f} ns")

    cfg = preset("TMR_FSCD147", backbone="sam_vit_b", image_size=size,
                 compute_dtype=dtype, batch_size=1)
    pred = Predictor(cfg)
    _progress("init_params (jitted init)")
    pred.init_params(seed=0, image_size=size)

    ex = np.asarray([[0.45, 0.45, 0.53, 0.55]], np.float32)

    def _requests(n, seed):
        r = np.random.default_rng(seed)
        return [(r.standard_normal((size, size, 3)).astype(np.float32), ex)
                for _ in range(n)]

    # ---- baseline: flight OFF, per-request latency anchors the
    # overhead check; compiles happen here. caches off: every request
    # must ride the full pipeline.
    _progress("serve baseline (TMR_FLIGHT=0; warmup + timed pass)")
    with ServeEngine(pred, batch=args.batch, max_wait_ms=10,
                     exemplar_cache=0, feature_cache=0) as engine:
        _serve_closed_loop(engine, _requests(n_req, seed=1))  # warmup
        base_s = _serve_closed_loop(engine, _requests(n_req, seed=2))
    base_req_ms = base_s / n_req * 1000.0
    overhead_pct = (
        disabled_ns * _FLIGHT_SITES_PER_REQUEST
        / (base_req_ms * 1e6) * 100.0
    )

    # ---- flight ON: attribution + health + heartbeat on a fresh engine
    _progress("flight run (TMR_FLIGHT=1)")
    flight.configure(enabled=True)
    devtime.reset()
    flight.get_recorder().clear()
    hb_path = (args.out or "obs_watch") + ".heartbeat.jsonl"
    try:
        os.remove(hb_path)
    except OSError:
        pass
    with ServeEngine(pred, batch=args.batch, max_wait_ms=10,
                     exemplar_cache=0, feature_cache=0) as engine:
        engine.start_heartbeat(hb_path, interval_s=30.0)
        flight_s = _serve_closed_loop(engine, _requests(n_req, seed=3))
        health = engine.health()
    # engine.close() stopped the heartbeat and appended its final beat
    health_problems = validate_health_report(health)
    hb_lines = []
    with open(hb_path) as f:
        for line in f:
            if line.strip():
                hb_lines.append(json.loads(line))
    hb_ok = len(hb_lines) >= 2 and all(
        validate_health_report(doc) == [] for doc in hb_lines
    )
    if not args.out:
        os.remove(hb_path)
    ring = flight.get_recorder().snapshot()
    req_records = [r for r in ring if r["kind"] == "serve.request"]

    _progress("mfu_report (cost_analysis per program)")
    mfu = devtime.mfu_report()
    mfu_problems = validate_mfu_report(mfu)
    measured = [p for p in mfu["programs"]
                if p["calls"] > 0 or p["warmup_only"]]
    mfu_finite = bool(measured) and all(
        p["mfu"] is not None and np.isfinite(p["mfu"]) and p["mfu"] > 0
        for p in measured
    )
    # analytic vs cost_analysis envelope over the fused single programs
    # (the modeled family; PERF.md documents the 1.17x envelope)
    ratios = [
        max(p["flops_per_call"], p["analytic_flops_per_call"])
        / min(p["flops_per_call"], p["analytic_flops_per_call"])
        for p in mfu["programs"]
        if p["kind"] == "single" and p["cost_source"] == "xla"
        and p["analytic_flops_per_call"]
    ]
    envelope_max = max(ratios) if ratios else None
    envelope_ok = bool(ratios) and envelope_max <= 1.17
    flight.configure(enabled=False)

    # ---- anomaly detection: a calm pass, then an injected recompile
    # storm and a queue-saturation burst against tight thresholds —
    # each must fire EXACTLY its one structured anomaly
    _progress("anomaly injection (storm + queue burst)")
    watch = obs.HealthWatch(recompile_storm_threshold=3,
                            queue_depth_threshold=8)
    reg = obs.MetricsRegistry()
    calm = watch.observe(reg.snapshot(), compile_events=(), pending=0)
    t0 = time.perf_counter()
    storm_events = [
        obs.record_compile_event("storm_probe", ("key", i), t0,
                                 t0 + 0.05)
        for i in range(4)
    ]  # first is cold, the 3 after are key-change: exactly threshold
    storm = watch.observe(reg.snapshot(), compile_events=storm_events,
                          pending=0)
    queue = watch.observe(reg.snapshot(), compile_events=(), pending=32)
    storm_exact = [a["anomaly"] for a in storm] == ["recompile_storm"]
    queue_exact = [a["anomaly"] for a in queue] == ["queue_saturation"]

    report = {
        "schema": FLIGHT_REPORT_SCHEMA,
        "device": str(jax.devices()[0]),
        "config": {
            "image_size": size,
            "batch": args.batch,
            "requests": n_req,
            "flight_ring": flight.get_recorder().capacity,
        },
        "mfu": mfu,
        "health": health,
        "heartbeat": {
            "path": hb_path if args.out else None,
            "beats": len(hb_lines),
            "interval_s": 30.0,
        },
        "ring": {
            "records": len(ring),
            "serve_requests": len(req_records),
            "dropped": flight.get_recorder().dropped(),
        },
        "anomalies": {
            "calm": calm,
            "recompile_storm": storm,
            "queue_saturation": queue,
        },
        "overhead": {
            "disabled_ns_per_check": round(disabled_ns, 1),
            "check_sites_per_request": _FLIGHT_SITES_PER_REQUEST,
            "baseline_request_ms": round(base_req_ms, 3),
            "overhead_disabled_pct": round(overhead_pct, 6),
            "enabled_wall_s": round(flight_s, 3),
            "baseline_wall_s": round(base_s, 3),
        },
    }
    report["checks"] = {
        "mfu_valid": mfu_problems == [],
        "mfu_finite": mfu_finite,
        "flops_envelope_ok": envelope_ok,
        "flops_envelope_max_ratio": (
            round(envelope_max, 4) if envelope_max else None
        ),
        "health_valid": health_problems == [],
        "heartbeat_roundtrip": bool(hb_ok),
        "ring_recorded": bool(len(req_records) >= n_req),
        "calm_quiet": calm == [],
        "storm_exact": bool(storm_exact),
        "queue_exact": bool(queue_exact),
        "overhead_ok": bool(overhead_pct < 1.0),
    }
    problems = validate_flight_report(report)
    if problems:  # self-check: the emitted document must validate
        report["validator_problems"] = problems

    cancel_watchdog()  # before the success print: no success-then-watchdog
    line = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    return 0


def main(argv=None) -> int:
    """One flight_report/v1 JSON line on stdout, success or not: the
    shared bench_guard (same watchdog bench.py runs under) funnels
    wedges and crashes into a contractual error record."""
    from tmr_tpu.diagnostics import FLIGHT_REPORT_SCHEMA
    from tmr_tpu.utils.bench_guard import run_guarded

    return run_guarded(
        lambda cancel: _run(cancel, argv),
        lambda msg: print(
            json.dumps({"schema": FLIGHT_REPORT_SCHEMA, "error": msg}),
            flush=True,
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
